"""Headline benchmark: PSO on Rastrigin-30D at 1M particles, one chip.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

Baseline: the reference has no published numbers (BASELINE.md); its
measured aggregate throughput is ~40,000 agent-steps/sec at 64 agents on a
2.70 GHz Xeon core (SURVEY.md §6) — that is the denominator for
``vs_baseline``.
"""

import json
import time

import jax
import jax.numpy as jnp

from distributed_swarm_algorithm_tpu.ops.objectives import rastrigin
from distributed_swarm_algorithm_tpu.ops.pso import pso_init, pso_run

N = 1_048_576           # 1M particles (BASELINE.json north star)
DIM = 30                # Rastrigin-30D
HALF_WIDTH = 5.12
WARMUP_STEPS = 20
BENCH_STEPS = 200
REFERENCE_AGENT_STEPS_PER_SEC = 40_000.0  # SURVEY.md §6, measured


def main():
    state = pso_init(rastrigin, n=N, dim=DIM, half_width=HALF_WIDTH, seed=0)
    jax.block_until_ready(state.pos)

    # Warmup: trigger compilation of the scan'd kernel.
    state = pso_run(state, rastrigin, WARMUP_STEPS, half_width=HALF_WIDTH)
    jax.block_until_ready(state.gbest_fit)

    start = time.perf_counter()
    state = pso_run(state, rastrigin, BENCH_STEPS, half_width=HALF_WIDTH)
    jax.block_until_ready(state.gbest_fit)
    elapsed = time.perf_counter() - start

    steps_per_sec = BENCH_STEPS / elapsed
    agent_steps_per_sec = steps_per_sec * N
    print(
        json.dumps(
            {
                "metric": (
                    "agent-steps/sec, PSO Rastrigin-30D, 1,048,576 "
                    "particles, 1 chip"
                ),
                "value": round(agent_steps_per_sec, 1),
                "unit": "agent-steps/sec",
                "vs_baseline": round(
                    agent_steps_per_sec / REFERENCE_AGENT_STEPS_PER_SEC, 2
                ),
            }
        )
    )


if __name__ == "__main__":
    main()
