"""Headline benchmark: PSO on Rastrigin-30D at 1M particles, one chip.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

Baseline: the reference has no published numbers (BASELINE.md); its
measured aggregate throughput is ~40,000 agent-steps/sec at 64 agents on a
2.70 GHz Xeon core (SURVEY.md §6) — that is the denominator for
``vs_baseline``.

Uses the fused Pallas TPU kernel (ops/pallas/pso_fused.py) when a TPU is
attached, else the portable jit path.  Methodology notes:
  - warmup executes the SAME (static n_steps) program that is timed, so
    compilation is excluded;
  - sync is a scalar device_get (``float(...)``) — under the axon TPU
    tunnel, ``block_until_ready`` can return before remote execution
    completes, which silently times dispatch instead of compute;
  - 2560 steps per timed call with 64 in-VMEM steps per kernel block:
    sustained-throughput regime (real optimization runs are thousands of
    steps); the one-time [N,D]→[D,N] transposes amortize out and HBM
    sees pos/vel/pbest once per 64 steps, leaving the VPU as the limit.
"""

import json
import os
import time

N = 1_048_576           # 1M particles (BASELINE.json north star)
DIM = 30                # Rastrigin-30D
BENCH_STEPS = 2560
REPS = 3
REFERENCE_AGENT_STEPS_PER_SEC = 40_000.0  # SURVEY.md §6, measured

# Backend-init retry (r8, VERDICT r5 #1): the r5 capture lost its
# whole round to ONE transient tunnel hiccup — bench.py died on a
# traceback before printing any JSON, and the round recorded null.
# Backend/device acquisition is the only phase that can fail
# transiently (the math after it is deterministic), so it gets a
# bounded retry with backoff, and the FINAL failure prints one
# structured JSON line (value null) instead of an unparseable stack.
INIT_ATTEMPTS = int(os.environ.get("DSA_BENCH_INIT_ATTEMPTS", "3"))
INIT_BACKOFF_S = float(os.environ.get("DSA_BENCH_INIT_BACKOFF", "5"))

HEADLINE_METRIC = (
    "agent-steps/sec, PSO Rastrigin-30D, 1,048,576 particles, 1 chip"
)


def _append_to_run_dir(record: dict) -> None:
    """With DSA_RUN_DIR set, deposit the headline line there too —
    stdout remains the contract; the run dir is the durable copy the
    inspector reads.  Under run_all (DSA_RUN_ALL sentinel) this is a
    no-op: the suite collector already captures every stdout JSON
    line into metrics.jsonl, and a second direct write would double
    the row (harmless for metrics, but a value-null failure line
    would show as two failures in `swarmscope summary`)."""
    run_dir = os.environ.get("DSA_RUN_DIR")
    if not run_dir or os.environ.get("DSA_RUN_ALL"):
        return
    try:
        from distributed_swarm_algorithm_tpu.utils import rundir

        rundir.append_metrics(run_dir, [record])
    except Exception:
        pass  # the run dir is best-effort; the stdout line already shipped


def _retry_backend_init(fn, attempts=INIT_ATTEMPTS,
                        backoff_s=INIT_BACKOFF_S, sleep=time.sleep,
                        label="backend-init"):
    """Run ``fn`` with bounded retry + linear backoff.  Raises
    ``SystemExit(3)`` after printing ONE structured failure line when
    every attempt fails — a tunnel hiccup degrades the round's capture
    to an explicit null record instead of nulling it silently."""
    last = None
    for attempt in range(1, attempts + 1):
        try:
            return fn()
        except Exception as e:  # noqa: BLE001 — any init failure retries
            last = e
            if attempt < attempts:
                sleep(backoff_s * attempt)
    failure = {
        "metric": HEADLINE_METRIC + " (FAILED)",
        "value": None,
        "unit": "agent-steps/sec",
        "vs_baseline": None,
        "error": label,
        "attempts": attempts,
        "detail": f"{type(last).__name__}: {last}",
    }
    print(json.dumps(failure))
    _append_to_run_dir(failure)
    raise SystemExit(3)


def _parity_gate():
    """On-TPU numerical parity for the headline kernel (VERDICT r1 #1):
    the fused Pallas program is validated against interpret-mode math on
    the host plus an on-chip PRNG statistics check BEFORE any headline
    is printed.  Returns None when no TPU is attached (nothing to
    certify — the portable path's math is the tests' oracle)."""
    import importlib.util
    import os

    # Load by file path rather than sys.path.insert(0, benchmarks/): a
    # permanent path prepend would let any module-name collision in
    # that dir shadow stdlib/site-packages for the rest of the process.
    vod_path = os.path.join(
        os.path.dirname(os.path.abspath(__file__)),
        "benchmarks",
        "verify_on_device.py",
    )
    spec = importlib.util.spec_from_file_location(
        "verify_on_device", vod_path
    )
    vod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(vod)
    run_gates = vod.run_gates

    report = run_gates(quick=True)
    if report["parity_ok"] is False:
        print(
            json.dumps({
                "metric": "PARITY FAILURE — headline withheld",
                "value": 0.0,
                "unit": "agent-steps/sec",
                "vs_baseline": 0.0,
                "parity_ok": False,
                "gates": report["gates"],
            })
        )
        raise SystemExit(2)
    return report["parity_ok"]


def main():
    # Touch the backend FIRST, inside the retry envelope: jax.devices()
    # is where a broken tunnel/driver surfaces, and it is also what the
    # infra-failure drill (tests/test_infra_failure_drill.py)
    # monkeypatches to exercise this path without a real outage.
    def _probe():
        import jax

        return jax.devices()

    # Distinct labels per phase: only the devices probe is a pure
    # "backend-init" signal; a gate or construction failure after N
    # retries is recorded under its own phase name, so a
    # deterministic bug cannot masquerade as a tunnel hiccup in the
    # round artifact (the retry still helps when the hiccup surfaces
    # late, e.g. the first real compile).
    _retry_backend_init(_probe)
    parity_ok = _retry_backend_init(_parity_gate, label="parity-gate")

    from distributed_swarm_algorithm_tpu.models.pso import PSO

    def _construct():
        opt = PSO("rastrigin", n=N, dim=DIM, seed=0, steps_per_kernel=64)
        float(opt.state.gbest_fit)
        return opt

    opt = _retry_backend_init(_construct, label="pso-construct")

    # Warmup: compile + first execution of the exact timed program.
    opt.run(BENCH_STEPS)
    float(opt.state.gbest_fit)

    best = 0.0
    for _ in range(REPS):
        start = time.perf_counter()
        opt.run(BENCH_STEPS)
        float(opt.state.gbest_fit)          # force real device sync
        elapsed = time.perf_counter() - start
        best = max(best, BENCH_STEPS / elapsed)

    agent_steps_per_sec = best * N
    path = "pallas-fused" if opt.use_pallas else "xla-jit"
    record = {
        "metric": (
            "agent-steps/sec, PSO Rastrigin-30D, 1,048,576 "
            f"particles, 1 chip ({path})"
        ),
        "value": round(agent_steps_per_sec, 1),
        "unit": "agent-steps/sec",
        "vs_baseline": round(
            agent_steps_per_sec / REFERENCE_AGENT_STEPS_PER_SEC, 2
        ),
        # True = fused kernel numerically certified on this chip
        # this run; None = no TPU attached (portable path).
        "parity_ok": parity_ok,
    }
    print(json.dumps(record))
    _append_to_run_dir(record)


if __name__ == "__main__":
    main()
