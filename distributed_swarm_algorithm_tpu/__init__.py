"""distributed_swarm_algorithm_tpu — a TPU-native swarm framework.

A ground-up re-design of the capabilities of the reference
``distributed-swarm-algorithm`` (decentralized leader election, heartbeat
failure detection, distributed task allocation, formation control, APF
motion planning) as synchronous vectorized JAX dataflow: the swarm is one
struct-of-arrays pytree, the per-agent 10 Hz loop body is one jitted
whole-swarm kernel, and every message-based protocol is a masked reduction
that shards over a TPU mesh via ``shard_map`` (see ``parallel/``).

Layers (mirrors SURVEY.md §1, rebuilt TPU-first):
  models/    VectorSwarm (capability parity), PSO (perf flagship),
             DE / CMAES (optimizer families), Boids (flocking),
             SwarmAgent (per-agent CPU-compatible API + real transport)
  ops/       pure kernels: physics, coordination, allocation, PSO/DE/
             CMA-ES/boids, objectives, neighbor search
  parallel/  mesh/sharding/island-model multi-chip layer
  serve/     multi-tenant rollout service (r13): scenario-batched
             rollouts, bucketed compiled shapes, submit/collect
  utils/     config, checkpoint, metrics, profiling, telemetry
             (the in-scan flight recorder, docs/OBSERVABILITY.md)
"""

from .state import (
    ELECTION_WAIT,
    FOLLOWER,
    LEADER,
    NO_CAP,
    NO_LEADER,
    NO_WINNER,
    TASK_ASSIGNED,
    TASK_LOCKED,
    TASK_OPEN,
    TASK_TENTATIVE,
    SwarmState,
    make_swarm,
    with_tasks,
)
from .utils.config import (
    DEFAULT_CONFIG,
    TELEMETRY_OFF,
    TELEMETRY_ON,
    SwarmConfig,
    TelemetryConfig,
)
from .utils.telemetry import (
    TelemetrySummary,
    TickTelemetry,
    summarize_telemetry,
    telemetry_events,
    write_events_jsonl,
)
from .models.swarm import VectorSwarm, swarm_rollout, swarm_tick
from .models.pso import PSO
from .models.memetic import MemeticPSO
from .models.de import DE
from .models.cmaes import CMAES
from .models.boids import Boids
from .models.aco import ACO
from .models.abc_bees import ABC
from .models.gwo import GWO
from .ops import objectives
from .ops.boids import BoidsParams, BoidsState, boids_init, boids_run, boids_step
from .ops.cmaes import CMAESState, cmaes_init, cmaes_params, cmaes_run, cmaes_step
from .ops.de import DEState, de_init, de_run, de_step
from .ops.allocation import (
    allocation_step,
    arbitrate,
    task_status_view,
    utility_matrix,
)
from .ops.coordination import (
    coordination_step,
    current_leader,
    instant_election,
    kill,
    revive,
)
from .ops.abc import ABCState, abc_init, abc_run, abc_step
from .ops.aco import (
    ACOState,
    aco_init,
    aco_run,
    aco_step,
    coords_to_dist,
    tour_lengths,
)
from .ops.gwo import GWOState, gwo_init, gwo_run, gwo_step
from .ops.hashgrid_plan import HashgridPlan, build_hashgrid_plan
from .ops.memetic import gd_refine, memetic_run, refine_pbest
from .ops.pallas import fused_pso_run
from .ops.physics import apf_forces, formation_targets, physics_step
from .ops.pso import PSOState, pso_init, pso_run, pso_step
from .ops.topology import neighbor_best, ring_best, von_neumann_best

__version__ = "0.1.0"

__all__ = [
    "SwarmConfig", "DEFAULT_CONFIG", "SwarmState", "make_swarm", "with_tasks",
    "TelemetryConfig", "TELEMETRY_ON", "TELEMETRY_OFF",
    "TickTelemetry", "TelemetrySummary", "summarize_telemetry",
    "telemetry_events", "write_events_jsonl",
    "VectorSwarm", "swarm_tick", "swarm_rollout", "PSO",
    "PSOState", "pso_init", "pso_step", "pso_run", "fused_pso_run",
    "MemeticPSO", "memetic_run", "refine_pbest", "gd_refine",
    "neighbor_best", "ring_best", "von_neumann_best",
    "DE", "DEState", "de_init", "de_step", "de_run",
    "CMAES", "CMAESState", "cmaes_params", "cmaes_init", "cmaes_step",
    "cmaes_run",
    "Boids", "BoidsParams", "BoidsState", "boids_init", "boids_step",
    "boids_run",
    "ACO", "ACOState", "aco_init", "aco_step", "aco_run",
    "coords_to_dist", "tour_lengths",
    "ABC", "ABCState", "abc_init", "abc_step", "abc_run",
    "GWO", "GWOState", "gwo_init", "gwo_step", "gwo_run",
    "objectives",
    "coordination_step", "instant_election", "current_leader", "kill",
    "revive",
    "allocation_step", "arbitrate", "utility_matrix", "task_status_view",
    "physics_step", "apf_forces", "formation_targets",
    "HashgridPlan", "build_hashgrid_plan",
    "FOLLOWER", "ELECTION_WAIT", "LEADER",
    "TASK_OPEN", "TASK_TENTATIVE", "TASK_ASSIGNED", "TASK_LOCKED",
    "NO_LEADER", "NO_CAP", "NO_WINNER",
]
