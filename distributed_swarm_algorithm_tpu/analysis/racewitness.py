"""Runtime lock-witness — the dynamic half of racelint (r21).

The static model (``rules_concurrency``) claims: every statically
guarded site in a with-lock region actually holds that lock when it
executes.  This module checks the claim on a LIVE program — the race
drill runs a short ``StreamingService`` segment while rival threads
hammer ``/metrics``, ``snapshot()`` and trace export, and the witness
observes every executed line inside a statically-derived lock region,
asserting the mapped lock is held by the executing thread.

Two pieces:

- :class:`WitnessLock` — a delegating wrapper installed over a real
  ``threading.Lock``/``RLock`` **by attribute replacement** (e.g.
  ``registry._lock = WitnessLock(registry._lock)``), which tracks
  per-thread hold depth so ``held()`` answers "does the CURRENT
  thread hold this lock?" — the question a runtime race check needs
  and the stdlib locks cannot answer.

- :class:`RuntimeLockWitness` — line-granular execution monitor over
  the static model's ``lock_regions`` output.  On 3.12+ it rides
  ``sys.monitoring`` (PEP 669: near-zero cost outside watched code
  via ``DISABLE`` returns); on older interpreters it falls back to
  ``sys.settrace`` + ``threading.settrace``, returning a local trace
  function only for watched code objects so unwatched frames run
  untraced.  Install the witness BEFORE spawning rival threads:
  already-running threads keep their current (un)traced state.

Pure stdlib, jax-free — importable anywhere the analysis package is.
"""

from __future__ import annotations

import sys
import threading
from typing import Dict, Iterable, List, Optional, Tuple


class WitnessLock:
    """Delegating lock wrapper with per-thread hold-depth tracking.

    Re-entrant bookkeeping works for both Lock and RLock underneath
    (a plain Lock simply never reaches depth 2)."""

    def __init__(self, inner):
        self._inner = inner
        self._holders: Dict[int, int] = {}

    def acquire(self, *a, **k) -> bool:
        got = self._inner.acquire(*a, **k)
        if got:
            tid = threading.get_ident()
            self._holders[tid] = self._holders.get(tid, 0) + 1
        return got

    def release(self) -> None:
        tid = threading.get_ident()
        # Decrement BEFORE the real release: after releasing, another
        # thread may acquire and read _holders concurrently.
        depth = self._holders.get(tid, 0)
        if depth <= 1:
            self._holders.pop(tid, None)
        else:
            self._holders[tid] = depth - 1
        self._inner.release()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False

    def held(self) -> bool:
        """Does the CURRENT thread hold this lock?"""
        return self._holders.get(threading.get_ident(), 0) > 0


class RuntimeLockWitness:
    """Checks statically-guarded lines against live lock holds.

    Parameters
    ----------
    regions:
        ``lock_regions()`` output — ``(relpath, func, lo, hi,
        lock_name)`` tuples.  ``relpath`` is matched as a suffix of
        ``co_filename`` so repo-relative static paths find absolute
        runtime paths.
    locks:
        ``lock_name -> WitnessLock`` for every lock the drill wrapped.
        Regions whose lock is not in the map still count hits (the
        static and dynamic models agree the line is watched) but
        cannot witness a violation.
    """

    def __init__(
        self,
        regions: Iterable[Tuple[str, str, int, int, str]],
        locks: Dict[str, WitnessLock],
    ):
        self.locks = dict(locks)
        #: func name -> [(relpath, lo, hi, lock_name)] — first-level
        #: filter by co_name, then the relpath suffix check.
        self._by_func: Dict[str, List[tuple]] = {}
        for relpath, fname, lo, hi, lock in regions:
            self._by_func.setdefault(fname, []).append(
                (relpath, int(lo), int(hi), lock)
            )
        self.hits = 0
        self.violations: List[tuple] = []
        self._lock = threading.Lock()
        self._installed: Optional[str] = None
        self._prev_trace = None

    # -- shared region check ----------------------------------------------
    def _regions_of(self, code) -> Optional[List[tuple]]:
        cands = self._by_func.get(code.co_name)
        if not cands:
            return None
        fname = code.co_filename
        out = [r for r in cands if fname.endswith(r[0])]
        return out or None

    def _check_line(self, regions, line) -> None:
        for relpath, lo, hi, lock_name in regions:
            if lo <= line <= hi:
                wl = self.locks.get(lock_name)
                ok = wl is None or wl.held()
                with self._lock:
                    self.hits += 1
                    if not ok:
                        self.violations.append(
                            (relpath, line, lock_name,
                             threading.current_thread().name)
                        )

    # -- sys.settrace backend (<=3.11) ------------------------------------
    def _global_trace(self, frame, event, arg):
        if event != "call":
            return None
        if self._regions_of(frame.f_code) is None:
            return None
        return self._local_trace

    def _local_trace(self, frame, event, arg):
        if event == "line":
            regions = self._regions_of(frame.f_code)
            if regions:
                self._check_line(regions, frame.f_lineno)
        return self._local_trace

    # -- sys.monitoring backend (3.12+) -----------------------------------
    def _install_monitoring(self) -> bool:
        mon = getattr(sys, "monitoring", None)
        if mon is None:
            return False
        try:
            tool = mon.PROFILER_ID
            mon.use_tool_id(tool, "racelint-witness")

            def on_line(code, line):
                regions = self._regions_of(code)
                if regions is None:
                    return mon.DISABLE
                self._check_line(regions, line)
                return None

            mon.register_callback(
                tool, mon.events.LINE, on_line
            )
            mon.set_events(tool, mon.events.LINE)
        except Exception:
            return False
        self._mon_tool = tool
        return True

    # -- lifecycle ---------------------------------------------------------
    def install(self) -> "RuntimeLockWitness":
        """Start observing.  Prefer ``sys.monitoring``; fall back to
        settrace.  Call before spawning the rival threads."""
        if self._installed is not None:
            return self
        if self._install_monitoring():
            self._installed = "monitoring"
            return self
        self._prev_trace = sys.gettrace()
        threading.settrace(self._global_trace)
        sys.settrace(self._global_trace)
        self._installed = "settrace"
        return self

    def uninstall(self) -> None:
        if self._installed == "monitoring":
            mon = sys.monitoring
            mon.set_events(self._mon_tool, 0)
            mon.register_callback(
                self._mon_tool, mon.events.LINE, None
            )
            mon.free_tool_id(self._mon_tool)
        elif self._installed == "settrace":
            threading.settrace(None)  # type: ignore[arg-type]
            sys.settrace(self._prev_trace)
        self._installed = None

    def __enter__(self):
        return self.install()

    def __exit__(self, *exc):
        self.uninstall()
        return False
