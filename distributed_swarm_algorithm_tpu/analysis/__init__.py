"""swarmlint — AST-based hazard analyzer for this repo's JAX/Pallas code.

Run it::

    python -m distributed_swarm_algorithm_tpu.analysis            # text
    python -m distributed_swarm_algorithm_tpu.analysis --json     # machine

It parses (never imports) every .py file under the default scan set
(the package, benchmarks/, examples/, bench.py) and checks the hazard
classes that have actually bitten this repo on TPU: PRNG key reuse,
host syncs and Python branches inside traced code, per-call re-jit,
per-iteration spatial-index rebuilds, ungated flight-recorder
collection in scan bodies, host branches on traced done flags in env
rollouts, collectives under non-uniform cond predicates in shard_map
bodies, dtype drift in ops/ hot paths, the fused-kernel dispatch
contract, and bench metric-name hygiene.  As of r21 the four
cross-module rules ride a project-wide call-graph engine
(``callgraph.py``) and a fifth hazard family — **racelint**
(``rules_concurrency.py``) — audits host-thread lock discipline over
the serve plane's shared mutable state.  See
docs/STATIC_ANALYSIS.md for the rule catalog, the suppression
policy, and how to add a rule.

The package's second analyzer, **jaxlint** (``jaxlint.py``, r15 — run
as ``python -m distributed_swarm_algorithm_tpu.cli jaxlint``), audits
the LOWERED program instead of the source text: per-entry collective
census with declared budgets (jaxlint-budgets.json), donation
aliasing, and dtype-widening contracts over every compile-observatory
registry entry.  It is deliberately not imported here: this package
import stays jax-free so the AST gate runs anywhere.

Importing this package registers the built-in rules (import order is
display order).
"""

from __future__ import annotations

from . import baseline  # noqa: F401
from .core import (  # noqa: F401
    BAD_SUPPRESS,
    Finding,
    ModuleInfo,
    REGISTRY,
    Rule,
    Suppression,
    analyze_module,
    analyze_paths,
    iter_py_files,
    parse_suppressions,
    register,
)

# Importing the rule modules populates REGISTRY.
from . import rules_prng    # noqa: E402,F401
from . import rules_trace   # noqa: E402,F401
from . import rules_dtype   # noqa: E402,F401
from . import rules_contract  # noqa: E402,F401
from . import rules_concurrency  # noqa: E402,F401  (racelint, r21)

from . import callgraph  # noqa: E402,F401  (cross-module engine, r21)
from .rules_concurrency import racelint_rules  # noqa: E402,F401

#: What `python -m distributed_swarm_algorithm_tpu.analysis` scans
#: when given no paths (repo-relative).
DEFAULT_PATHS = (
    "distributed_swarm_algorithm_tpu",
    "benchmarks",
    "examples",
    "bench.py",
)

__all__ = [
    "BAD_SUPPRESS",
    "DEFAULT_PATHS",
    "Finding",
    "ModuleInfo",
    "REGISTRY",
    "Rule",
    "Suppression",
    "analyze_module",
    "analyze_paths",
    "baseline",
    "callgraph",
    "iter_py_files",
    "parse_suppressions",
    "racelint_rules",
    "register",
]
