"""Repo-contract rules: Pallas dispatch gates, bench metric hygiene,
and the serve hot-loop host-sync contract.

- ``pallas-gate``: every ``ops/pallas/*_fused.py`` kernel family must
  expose a ``*_supported()`` capability gate at module scope and pass
  an explicit ``interpret=`` through each ``pl.pallas_call`` — the
  hashgrid_supported pattern (r6) made mandatory, so dispatch sites
  can ask *before* tracing and CPU tests can drive the same body.
  r23 extends the rule to ``ops/pallas/candidate_sweep.py`` (the
  plan-native candidate sweep) and adds a call-site half: any call to
  ``candidate_sweep_pallas`` / ``candidate_sweep_forces`` outside the
  defining module whose enclosing function never consults the fit
  model (``candidate_sweep_supported`` / ``candidate_backend_choice``
  / ``tick_uses_hashgrid_kernel``) is flagged — an ungated dispatch
  is exactly the hashgrid R=2 VMEM-overrun shape.
- ``metric-fstring``: metric names handed to the benchmark
  ``report()`` contract must be string literals.  A run-varying name
  (the r5 bench_recovery f-string) silently drops the metric from the
  cross-round union gate — the regression tracker matches on the
  exact string.
- ``metric-label``: metric NAMES or label-schema elements handed to
  the r19 metrics-registry registration calls
  (``counter()``/``gauge()``/``histogram()``) must be string
  literals.  A formatted name (f-string, ``.format``, ``+``
  concatenation, ``%``) registers one metric FAMILY per distinct
  runtime value — unbounded cardinality on a process-lifetime
  registry, and every family lands outside the declared taxonomy
  (docs/OBSERVABILITY.md).  The registry's MAX_SERIES bound catches
  the runtime half; this rule catches it at the source.
- ``serve-host-sync``: a host sync (``jax.block_until_ready`` /
  ``jax.device_get`` / ``.item()`` / ``np.asarray``-family) reachable
  from a ``serve/`` HOT-LOOP method — any function whose name carries
  an admit/launch/rotate/pump/advance stem, followed transitively
  through same-module calls.  The streaming loop's whole design is
  that admission and segment rotation never wait on the device (the
  r16 double-buffer rotation); one stray sync silently serializes
  the pipeline — every dispatch then costs a full rollout of
  latency, which no test fails and no bench catches until the soak's
  p99 row moves.  Collection paths (collect/harvest-after-enqueue)
  that must block carry a justified inline suppression.
"""

from __future__ import annotations

import ast
from typing import Optional

from .core import ModuleInfo, Rule, register

_PALLAS_CALL = frozenset(
    {"jax.experimental.pallas.pallas_call", "pallas.pallas_call"}
)

#: The candidate-sweep kernel entries (r23) and the fit-model names a
#: dispatch site must consult before calling one.  Matched on the
#: final segment of the resolved dotted chain — the entries are
#: repo-unique names, and suffix matching survives every import style
#: (relative, absolute, aliased module attribute).
_CANDIDATE_ENTRIES = frozenset(
    {"candidate_sweep_pallas", "candidate_sweep_forces"}
)
_CANDIDATE_GUARDS = frozenset(
    {
        "candidate_sweep_supported",
        "candidate_backend_choice",
        "tick_uses_hashgrid_kernel",
    }
)


def _module_level_names(tree: ast.Module):
    """Names bound at module scope: defs, assignments, imports."""
    for st in tree.body:
        if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef,
                           ast.ClassDef)):
            yield st.name
        elif isinstance(st, ast.Assign):
            for t in st.targets:
                for node in ast.walk(t):
                    if isinstance(node, ast.Name):
                        yield node.id
        elif isinstance(st, ast.AnnAssign) and isinstance(
            st.target, ast.Name
        ):
            yield st.target.id
        elif isinstance(st, (ast.Import, ast.ImportFrom)):
            for a in st.names:
                yield a.asname or a.name.split(".")[0]


@register
class PallasGateRule(Rule):
    id = "pallas-gate"
    summary = "fused Pallas module missing *_supported() gate or interpret="
    details = (
        "ops/pallas/*_fused.py (and the r23 plan-native "
        "candidate_sweep.py) must bind a module-level *_supported "
        "capability gate (dispatchers ask before tracing; the "
        "hashgrid R=2 VMEM overrun was exactly an ungated dispatch) "
        "and every pallas_call must plumb an explicit interpret= so "
        "the identical kernel body runs under CPU tests.  Call-site "
        "half: candidate_sweep_pallas/candidate_sweep_forces callers "
        "outside the defining module must consult the fit model "
        "(candidate_sweep_supported / candidate_backend_choice / "
        "tick_uses_hashgrid_kernel) in the enclosing function."
    )

    def applies(self, mod: ModuleInfo) -> bool:
        return "ops/pallas/" in mod.relpath and (
            mod.relpath.endswith("_fused.py")
            or mod.relpath.endswith("candidate_sweep.py")
        )

    def check(self, mod: ModuleInfo):
        if self.applies(mod):
            if not any(
                n.endswith("_supported")
                for n in _module_level_names(mod.tree)
            ):
                yield mod.finding(
                    self.id,
                    mod.tree.body[0] if mod.tree.body else mod.tree,
                    "fused kernel module exposes no *_supported() "
                    "capability gate — dispatchers cannot check the "
                    "envelope before tracing",
                )
            for node in ast.walk(mod.tree):
                if not isinstance(node, ast.Call):
                    continue
                if mod.resolve(node.func) not in _PALLAS_CALL:
                    continue
                if not any(
                    kw.arg == "interpret" for kw in node.keywords
                ):
                    yield mod.finding(
                        self.id, node,
                        "pallas_call without an explicit interpret= — "
                        "the kernel body cannot run under CPU tests",
                    )
        yield from self._unguarded_candidate_calls(mod)

    def _unguarded_candidate_calls(self, mod: ModuleInfo):
        """Flag candidate-sweep kernel calls whose enclosing function
        never consults the fit model.  The defining module is exempt
        (its internal forwarding IS the guarded implementation);
        references are matched as real Name/Attribute nodes, so a
        docstring mention cannot satisfy the gate."""
        if mod.relpath.endswith("ops/pallas/candidate_sweep.py"):
            return
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            resolved = mod.resolve(node.func)
            if resolved.rpartition(".")[2] not in _CANDIDATE_ENTRIES:
                continue
            scope = self._enclosing_function(mod, node)
            if not self._references_guard(scope or mod.tree):
                yield mod.finding(
                    self.id, node,
                    "candidate_sweep kernel called without consulting "
                    "its fit model (candidate_sweep_supported / "
                    "candidate_backend_choice / "
                    "tick_uses_hashgrid_kernel) — an ungated dispatch "
                    "can overrun the VMEM envelope",
                )

    @staticmethod
    def _enclosing_function(mod: ModuleInfo, node):
        cur = mod.parent(node)
        while cur is not None:
            if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef)):
                return cur
            cur = mod.parent(cur)
        return None

    @staticmethod
    def _references_guard(tree) -> bool:
        for node in ast.walk(tree):
            if isinstance(node, ast.Name) and node.id in _CANDIDATE_GUARDS:
                return True
            if (
                isinstance(node, ast.Attribute)
                and node.attr in _CANDIDATE_GUARDS
            ):
                return True
        return False


@register
class MetricStringRule(Rule):
    id = "metric-fstring"
    summary = "non-literal metric name passed to benchmark report()"
    details = (
        "The union perf gate matches metrics by exact string across "
        "rounds; an f-string or computed name that varies per run "
        "lands every round in the non-gating 'new'/'dropped' buckets "
        "(the r5 bench_recovery bug).  Pass a string literal."
    )

    def applies(self, mod: ModuleInfo) -> bool:
        return (
            mod.relpath.startswith("benchmarks/")
            or mod.relpath == "bench.py"
        )

    def check(self, mod: ModuleInfo):
        if not self.applies(mod):
            return
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            func = node.func
            is_report = (
                isinstance(func, ast.Name) and func.id == "report"
            ) or (
                isinstance(func, ast.Attribute) and func.attr == "report"
            )
            if not is_report:
                continue
            metric = node.args[0] if node.args else None
            if metric is None:
                for kw in node.keywords:
                    if kw.arg == "metric":
                        metric = kw.value
            if metric is None:
                continue
            if isinstance(metric, ast.Constant) and isinstance(
                metric.value, str
            ):
                continue
            kind = (
                "f-string" if isinstance(metric, ast.JoinedStr)
                else "computed expression"
            )
            yield mod.finding(
                self.id, metric,
                f"metric name is a {kind} — the union gate matches "
                "exact strings; use a literal",
            )


# ---------------------------------------------------------------------------
# metric-label (r19)

#: The registry's registration methods (utils/metrics.py).  Only
#: ATTRIBUTE calls count (``reg.counter(...)``): a bare-name
#: ``histogram(...)`` is some other library's function, and
#: ``jnp.histogram``/``np.histogram`` pass data positionally — their
#: first arg is a Name, which this rule deliberately never flags (a
#: Name cannot be PROVEN a formatted string; only explicit
#: string-formatting expressions are).
_REGISTRY_METHODS = frozenset({"counter", "gauge", "histogram"})


def _is_formatted_string(node: ast.expr) -> Optional[str]:
    """The kind of runtime string formatting ``node`` performs, or
    None when it is not provably a formatted string.  Literal-safe by
    construction: plain Names, attribute reads, and literal constants
    all return None."""
    if isinstance(node, ast.JoinedStr):
        return "f-string"
    if isinstance(node, ast.Call) and isinstance(
        node.func, ast.Attribute
    ) and node.func.attr == "format":
        return ".format() call"
    if isinstance(node, ast.BinOp):
        if isinstance(node.op, ast.Mod) and isinstance(
            node.left, ast.Constant
        ) and isinstance(node.left.value, str):
            return "%-formatting"
        if isinstance(node.op, ast.Add):
            for side in (node.left, node.right):
                if (
                    isinstance(side, ast.Constant)
                    and isinstance(side.value, str)
                ) or isinstance(side, ast.JoinedStr):
                    return "string concatenation"
    return None


@register
class MetricLabelRule(Rule):
    id = "metric-label"
    summary = "formatted metric name/label in a registry registration"
    details = (
        "utils/metrics.py registration calls (.counter/.gauge/"
        ".histogram) fix a metric's name and label SCHEMA for the "
        "process lifetime; an f-string/format/concatenated/%-"
        "formatted name (or label-tuple element) mints one metric "
        "family per runtime value — unbounded registry cardinality, "
        "and every minted family falls outside the declared taxonomy "
        "the live dashboard and the exposition render.  Pass string "
        "literals; runtime variation belongs in label VALUES at the "
        "observation site, drawn from a design-bounded set."
    )

    def check(self, mod: ModuleInfo):
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            if not isinstance(node.func, ast.Attribute):
                continue
            if node.func.attr not in _REGISTRY_METHODS:
                continue
            name = node.args[0] if node.args else None
            for kw in node.keywords:
                if kw.arg == "name":
                    name = kw.value
            if name is not None:
                kind = _is_formatted_string(name)
                if kind is not None:
                    yield mod.finding(
                        self.id, name,
                        f"metric name is built by {kind} — one "
                        "registered family per runtime value; the "
                        "registry taxonomy is fixed strings",
                    )
            # The label schema may arrive as labels= OR positionally
            # (3rd arg to counter/gauge, 4th to histogram after
            # buckets) — check every candidate tuple/list the same
            # way; float bucket literals can never read as formatted
            # strings, so histogram's buckets arg is inert here.
            label_args = [
                kw.value for kw in node.keywords
                if kw.arg == "labels"
            ] + list(node.args[2:])
            for labels in label_args:
                if not isinstance(labels, (ast.Tuple, ast.List)):
                    continue
                for el in labels.elts:
                    kind = _is_formatted_string(el)
                    if kind is not None:
                        yield mod.finding(
                            self.id, el,
                            f"label name is built by {kind} — the "
                            "label SCHEMA is fixed at registration; "
                            "runtime variation belongs in label "
                            "values",
                        )


# ---------------------------------------------------------------------------
# serve-host-sync (r16)

#: Function-name stems that mark a serve/ hot-loop method.  The
#: streaming loop's admission (admit), dispatch (launch), segment
#: rotation (rotate/advance), and the pump that drives them must stay
#: sync-free; collection paths use other names and MAY block.
_HOT_STEMS = ("admit", "launch", "rotate", "pump", "advance")

#: Resolved dotted names that force a host<->device sync.
_SYNC_CALLS = frozenset(
    {
        "jax.block_until_ready",
        "jax.device_get",
        "numpy.asarray",
        "numpy.array",
        "numpy.ascontiguousarray",
        "numpy.asfortranarray",
    }
)


def _is_hot_name(name: str) -> bool:
    low = name.lower()
    return any(stem in low for stem in _HOT_STEMS)


@register
class ServeHostSyncRule(Rule):
    id = "serve-host-sync"
    summary = "host sync reachable from a serve/ hot-loop method"
    details = (
        "serve/ hot-loop methods (names carrying an admit/launch/"
        "rotate/pump/advance stem) and everything they call in their "
        "module must not force a device sync (jax.block_until_ready, "
        "jax.device_get, .item(), np.asarray/np.array of a device "
        "array): one stray sync serializes the streaming pipeline — "
        "every dispatch then pays a full rollout of latency on the "
        "host loop's critical path.  Blocking collection sites carry "
        "a justified suppression (they read only work whose "
        "successor dispatch is already enqueued)."
    )

    def applies(self, mod: ModuleInfo) -> bool:
        return "/serve/" in f"/{mod.relpath}"

    def _hot_reach(self, project):
        """Project-wide closure from every serve/ hot-stem method,
        computed once per project (callgraph re-hosting, r21): bare
        names and attribute calls resolve by terminal name within a
        module exactly as the legacy walker did, and additionally
        follow import aliases, module-global instances, and
        ``self.attr`` methods into other modules — a sync hidden in a
        utils helper the pump calls still serializes the stream.

        The walk stops at TRACED callees (jit/shard_map bodies): code
        under a trace runs on device — a host sync there is a
        trace-time error, not a per-tick serialization, and numpy on
        trace-time constants is free."""
        reach = project.cache.get(self.id)
        if reach is None:
            roots = []
            for m in project.modules:
                if not self.applies(m):
                    continue
                for name, fns in project.funcs_by_name(m).items():
                    if _is_hot_name(name):
                        roots.extend(
                            project.func_ref(m, fn) for fn in fns
                        )
            reach = project.closure(
                roots, follow_attr=True,
                skip=lambda fr: fr.node in fr.mod.traced_functions(),
            )
            project.cache[self.id] = reach
        return reach

    def check(self, mod: ModuleInfo):
        project = mod.project
        if project is None:
            from . import callgraph

            project = callgraph.Project([mod])
        # Roots live in serve/ modules; sites are reported while
        # checking the module THEY live in, so suppressions and
        # fingerprints stay local to the file they annotate.
        local = [
            fr for fr in self._hot_reach(project).values()
            if fr.mod is mod
        ]
        seen: set = set()
        for fr in sorted(local, key=lambda fr: fr.node.lineno):
            for node in ast.walk(fr.node):
                f = self._sync_site(mod, node, fr.name)
                if f is None:
                    continue
                site = (f.line, f.snippet)
                if site not in seen:
                    seen.add(site)
                    yield f

    def _sync_site(self, mod, node, root: str):
        if not isinstance(node, ast.Call):
            return None
        if (
            isinstance(node.func, ast.Attribute)
            and node.func.attr == "item"
            and not node.args
        ):
            return mod.finding(
                self.id, node,
                "`.item()` reachable from serve hot-loop method "
                f"`{root}` forces a device sync on the serving path",
            )
        name = mod.resolve(node.func)
        if name in _SYNC_CALLS:
            short = name.replace("numpy", "np")
            return mod.finding(
                self.id, node,
                f"`{short}` reachable from serve hot-loop method "
                f"`{root}` blocks the host loop on device work — the "
                "pipeline serializes",
            )
        # A sync passed AS AN ARGUMENT — tree_map(np.asarray, carry),
        # this codebase's dominant whole-pytree transfer idiom — is
        # the same serialization with the call site one level up.
        for arg in list(node.args) + [kw.value for kw in node.keywords]:
            if not isinstance(arg, (ast.Name, ast.Attribute)):
                continue
            aname = mod.resolve(arg)
            if aname in _SYNC_CALLS:
                short = aname.replace("numpy", "np")
                return mod.finding(
                    self.id, node,
                    f"`{short}` passed as a mapped function from "
                    f"serve hot-loop method `{root}` blocks the host "
                    "loop on device work — the pipeline serializes",
                )
        return None


# ---------------------------------------------------------------------------
# nondonated-carry (r20)

_LOOP_CALLS = frozenset(
    {"jax.lax.scan", "jax.lax.fori_loop", "jax.lax.while_loop"}
)

#: Identifier components (underscore-split) that mark a loop carry as
#: an optimizer-or-params pytree — the buffers a training loop cycles
#: every update, where a missing donation doubles live memory (the
#: whole state exists twice per step: the consumed input and the
#: fresh output).  Deliberately narrow: generic rollout carries
#: ("state", "carry", "plan") update in place too, but their
#: lifetime is one call — the hazard this rule exists for is the
#: long-LIVED learner state (train/ppo.py's TrainState discipline).
_OPT_COMPONENTS = frozenset(
    {"opt", "optimizer", "param", "params", "theta", "weights",
     "train"}
)

_JIT_NAMES = frozenset({"jax.jit", "jax.pmap"})
_DONATE_KWARGS = frozenset({"donate_argnums", "donate_argnames"})

#: The loop call's carry-init operand: positional index / keyword.
_CARRY_SLOT = {
    "jax.lax.scan": (1, "init"),
    "jax.lax.fori_loop": (3, "init_val"),
    "jax.lax.while_loop": (2, "init_val"),
}


def _optish(name: str) -> bool:
    return bool(
        _OPT_COMPONENTS.intersection(name.lower().split("_"))
    )


@register
class NondonatedCarryRule(Rule):
    id = "nondonated-carry"
    summary = (
        "watched jitted entry scans an optimizer/params carry "
        "without donation"
    )
    details = (
        "A `@watched(...)` jitted entry whose lax.scan/fori_loop/"
        "while_loop threads an optimizer-or-params pytree (carry "
        "names carrying an opt/params/theta/weights/train component) "
        "without `donate_argnums`/`donate_argnames` on its jit keeps "
        "BOTH copies of the learner state live across every update — "
        "the classic training-loop memory doubling (train/ppo.py "
        "donates its whole TrainState; the jaxlint min-aliased floor "
        "proves the aliasing landed).  Donate the carry argument, or "
        "mark sharded donors with jax.buffer_donor."
    )

    def check(self, mod: ModuleInfo):
        for fn in ast.walk(mod.tree):
            if not isinstance(
                fn, (ast.FunctionDef, ast.AsyncFunctionDef)
            ):
                continue
            if not self._is_watched(mod, fn):
                continue
            if self._is_donated(mod, fn):
                continue
            assigns = self._assignments(fn)
            seen: set = set()
            for node in ast.walk(fn):
                if not isinstance(node, ast.Call):
                    continue
                loop = mod.resolve(node.func)
                if loop not in _LOOP_CALLS:
                    continue
                init = self._carry_init(node, loop)
                if init is None:
                    continue
                hits = sorted(self._optish_names(init, assigns))
                if not hits:
                    continue
                site = (node.lineno, node.col_offset)
                if site in seen:
                    continue
                seen.add(site)
                yield mod.finding(
                    self.id, node,
                    f"loop carry threads {hits} through watched "
                    f"jitted entry `{fn.name}` with no donation — "
                    "both copies of the learner state stay live "
                    "every update; add donate_argnums (or "
                    "jax.buffer_donor for sharded carries)",
                )

    @staticmethod
    def _is_watched(mod: ModuleInfo, fn) -> bool:
        """A decorator of the form ``@watched("entry")`` /
        ``@WATCH.watched("entry")`` — the compile-observatory
        registration that marks a function as a long-lived entry
        point (the scope this rule gates)."""
        for dec in fn.decorator_list:
            if not isinstance(dec, ast.Call):
                continue
            name = mod.resolve(dec.func)
            if name.rsplit(".", 1)[-1] == "watched":
                return True
        return False

    @staticmethod
    def _is_donated(mod: ModuleInfo, fn) -> bool:
        """True when any jit/pmap decorator (direct, called, or via
        functools.partial) carries a donate kwarg — or the body
        mentions ``jax.buffer_donor`` (the shard_map donation
        spelling, r18)."""
        for dec in fn.decorator_list:
            if not isinstance(dec, ast.Call):
                continue
            name = mod.resolve(dec.func)
            kws = {k.arg for k in dec.keywords if k.arg}
            if name in _JIT_NAMES and kws & _DONATE_KWARGS:
                return True
            if (
                name == "functools.partial"
                and dec.args
                and mod.resolve(dec.args[0]) in _JIT_NAMES
                and kws & _DONATE_KWARGS
            ):
                return True
        for node in ast.walk(fn):
            if (
                isinstance(node, ast.Constant)
                and isinstance(node.value, str)
                and "buffer_donor" in node.value
            ):
                return True
        return False

    @staticmethod
    def _carry_init(node: ast.Call, loop: str):
        pos, kw = _CARRY_SLOT[loop]
        for k in node.keywords:
            if k.arg == kw:
                return k.value
        if len(node.args) > pos:
            return node.args[pos]
        return None

    @staticmethod
    def _assignments(fn):
        """name -> last assigned value node, CONTAINER expressions
        only (one-level indirection: ``carry0 = (params, m, v)`` then
        ``scan(body, carry0)``).  Call RHSes deliberately don't
        expand — ``plan = build_plan(pos, params)`` names params as a
        builder INPUT, not as a carried pytree."""
        out: dict = {}
        for node in ast.walk(fn):
            if isinstance(node, ast.Assign) and isinstance(
                node.value, (ast.Tuple, ast.List, ast.Name)
            ):
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        out[t.id] = node.value
        return out

    @classmethod
    def _optish_names(cls, init, assigns, _depth: int = 0):
        hits: set = set()
        for node in ast.walk(init):
            if isinstance(node, ast.Name):
                if _optish(node.id):
                    hits.add(node.id)
                elif _depth < 1 and node.id in assigns:
                    hits |= cls._optish_names(
                        assigns[node.id], assigns, _depth + 1
                    )
            elif isinstance(node, ast.Attribute) and _optish(
                node.attr
            ):
                hits.add(node.attr)
        return hits
