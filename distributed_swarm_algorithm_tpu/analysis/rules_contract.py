"""Repo-contract rules: Pallas dispatch gates and bench metric hygiene.

- ``pallas-gate``: every ``ops/pallas/*_fused.py`` kernel family must
  expose a ``*_supported()`` capability gate at module scope and pass
  an explicit ``interpret=`` through each ``pl.pallas_call`` — the
  hashgrid_supported pattern (r6) made mandatory, so dispatch sites
  can ask *before* tracing and CPU tests can drive the same body.
- ``metric-fstring``: metric names handed to the benchmark
  ``report()`` contract must be string literals.  A run-varying name
  (the r5 bench_recovery f-string) silently drops the metric from the
  cross-round union gate — the regression tracker matches on the
  exact string.
"""

from __future__ import annotations

import ast

from .core import ModuleInfo, Rule, register

_PALLAS_CALL = frozenset(
    {"jax.experimental.pallas.pallas_call", "pallas.pallas_call"}
)


def _module_level_names(tree: ast.Module):
    """Names bound at module scope: defs, assignments, imports."""
    for st in tree.body:
        if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef,
                           ast.ClassDef)):
            yield st.name
        elif isinstance(st, ast.Assign):
            for t in st.targets:
                for node in ast.walk(t):
                    if isinstance(node, ast.Name):
                        yield node.id
        elif isinstance(st, ast.AnnAssign) and isinstance(
            st.target, ast.Name
        ):
            yield st.target.id
        elif isinstance(st, (ast.Import, ast.ImportFrom)):
            for a in st.names:
                yield a.asname or a.name.split(".")[0]


@register
class PallasGateRule(Rule):
    id = "pallas-gate"
    summary = "fused Pallas module missing *_supported() gate or interpret="
    details = (
        "ops/pallas/*_fused.py must bind a module-level *_supported "
        "capability gate (dispatchers ask before tracing; the "
        "hashgrid R=2 VMEM overrun was exactly an ungated dispatch) "
        "and every pallas_call must plumb an explicit interpret= so "
        "the identical kernel body runs under CPU tests."
    )

    def applies(self, mod: ModuleInfo) -> bool:
        return (
            "ops/pallas/" in mod.relpath
            and mod.relpath.endswith("_fused.py")
        )

    def check(self, mod: ModuleInfo):
        if not self.applies(mod):
            return
        if not any(
            n.endswith("_supported") for n in _module_level_names(mod.tree)
        ):
            yield mod.finding(
                self.id, mod.tree.body[0] if mod.tree.body else mod.tree,
                "fused kernel module exposes no *_supported() "
                "capability gate — dispatchers cannot check the "
                "envelope before tracing",
            )
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            if mod.resolve(node.func) not in _PALLAS_CALL:
                continue
            if not any(kw.arg == "interpret" for kw in node.keywords):
                yield mod.finding(
                    self.id, node,
                    "pallas_call without an explicit interpret= — the "
                    "kernel body cannot run under CPU tests",
                )


@register
class MetricStringRule(Rule):
    id = "metric-fstring"
    summary = "non-literal metric name passed to benchmark report()"
    details = (
        "The union perf gate matches metrics by exact string across "
        "rounds; an f-string or computed name that varies per run "
        "lands every round in the non-gating 'new'/'dropped' buckets "
        "(the r5 bench_recovery bug).  Pass a string literal."
    )

    def applies(self, mod: ModuleInfo) -> bool:
        return (
            mod.relpath.startswith("benchmarks/")
            or mod.relpath == "bench.py"
        )

    def check(self, mod: ModuleInfo):
        if not self.applies(mod):
            return
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            func = node.func
            is_report = (
                isinstance(func, ast.Name) and func.id == "report"
            ) or (
                isinstance(func, ast.Attribute) and func.attr == "report"
            )
            if not is_report:
                continue
            metric = node.args[0] if node.args else None
            if metric is None:
                for kw in node.keywords:
                    if kw.arg == "metric":
                        metric = kw.value
            if metric is None:
                continue
            if isinstance(metric, ast.Constant) and isinstance(
                metric.value, str
            ):
                continue
            kind = (
                "f-string" if isinstance(metric, ast.JoinedStr)
                else "computed expression"
            )
            yield mod.finding(
                self.id, metric,
                f"metric name is a {kind} — the union gate matches "
                "exact strings; use a literal",
            )
