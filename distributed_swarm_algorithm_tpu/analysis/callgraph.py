"""Project-wide call-graph + symbol-resolution engine (r21).

Before this module, every rule that needed to follow calls carried its
own private closure walker over a bare-name ``dict`` — four copies of
the same BFS (serve-host-sync, halo-width, cond-collective, span-leak),
each blind past its module boundary.  ``Project`` centralizes that walk
and extends it across modules:

* **module globals** — ``TRACER.dump()`` resolves through a top-level
  ``TRACER = SpanTracer(...)`` binding to ``SpanTracer.dump``, in the
  same module or through an import alias
  (``metricslib.METRICS.counter``);
* **class/method tables** — ``self.f()`` resolves to the enclosing
  class's method (walking base classes declared in the project);
* **instance-attribute methods** — ``self.tracer.span()`` resolves via
  ``self.tracer = TRACER if tracer is None else tracer`` (constructor
  calls, if/or alternatives, and parameter annotations all contribute
  candidate classes);
* **functools.partial / decorator unwrapping** —
  ``partial(f, x)(...)`` and ``@partial(shard_map, ...)`` both reach
  ``f``.

Resolution is deliberately *under*-approximate: an expression that
cannot be resolved contributes no edge.  Rules built on top therefore
keep swarmlint's precision bias — silence is never proof of absence,
but a reported path is a real lexical path.

Like everything in ``analysis/``, this works on source text alone and
never imports the code it reads.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Optional, Tuple

_FUNC_DEFS = (ast.FunctionDef, ast.AsyncFunctionDef)

#: Constructors whose results are mutable containers (used by
#: rules_concurrency's shared-state footprint; kept here because the
#: tables that recognize them are built here).
MUTABLE_CONSTRUCTORS = frozenset(
    {"dict", "list", "set", "collections.deque", "deque",
     "collections.defaultdict", "defaultdict",
     "collections.OrderedDict", "OrderedDict",
     "collections.Counter", "Counter"}
)

#: Context-manager protocol methods pulled into the closure when a
#: class constructor is a call target: ``with Foo(...):`` executes all
#: three, and a body that stashes the instance reaches them later.
_CTOR_PROTOCOL = ("__init__", "__enter__", "__exit__")


def module_dotted(relpath: str) -> str:
    """Dotted module name of a repo-relative path:
    ``pkg/serve/loop.py`` -> ``pkg.serve.loop`` (``__init__`` maps to
    its package)."""
    dotted = relpath[:-3] if relpath.endswith(".py") else relpath
    dotted = dotted.replace("/", ".")
    if dotted.endswith(".__init__"):
        dotted = dotted[: -len(".__init__")]
    return dotted


class FuncRef:
    """A function definition located in the project: AST node + the
    module it lives in + (for directly-defined methods) its class."""

    __slots__ = ("mod", "node", "cls")

    def __init__(self, mod, node, cls=None):
        self.mod = mod
        self.node = node
        self.cls = cls

    @property
    def name(self) -> str:
        return getattr(self.node, "name", "<lambda>")

    def key(self) -> int:
        return id(self.node)

    def __repr__(self) -> str:  # pragma: no cover - debug aid
        return f"FuncRef({self.mod.relpath}:{self.name})"


class ClassInfo:
    """A class definition: direct method table + lazily-inferred
    instance-attribute class candidates."""

    __slots__ = ("mod", "node", "name", "methods")

    def __init__(self, mod, node):
        self.mod = mod
        self.node = node
        self.name = node.name
        self.methods: Dict[str, ast.AST] = {}
        for st in node.body:
            if isinstance(st, _FUNC_DEFS):
                self.methods.setdefault(st.name, st)

    def key(self) -> Tuple[str, str]:
        return (self.mod.relpath, self.name)


def _param_annotation(fn, name: str):
    """Annotation expr of parameter ``name`` of ``fn`` (or None)."""
    if isinstance(fn, ast.Lambda) or fn is None:
        return None
    args = fn.args
    for a in (
        list(args.posonlyargs) + list(args.args)
        + list(args.kwonlyargs)
        + ([args.vararg] if args.vararg else [])
        + ([args.kwarg] if args.kwarg else [])
    ):
        if a is not None and a.arg == name:
            return a.annotation
    return None


class Project:
    """Symbol tables + call resolution over a set of ``ModuleInfo``s.

    One instance is built per analysis run (``analyze_paths`` spans
    every scanned file; ``analyze_module`` wraps the single module) and
    attached to each module as ``mod.project``.  ``cache`` is scratch
    space for rules that compute a project-global model once
    (racelint's thread-root reach, serve-host-sync's hot closure).
    """

    def __init__(self, modules: Iterable):
        self.modules = list(modules)
        self.by_relpath = {m.relpath: m for m in self.modules}
        self._by_dotted = {
            module_dotted(m.relpath): m for m in self.modules
        }
        self._tables: Dict[int, dict] = {}
        self._attr_cache: Dict[Tuple[int, str], list] = {}
        self.cache: Dict[str, object] = {}
        for m in self.modules:
            m.project = self

    # -- per-module tables -------------------------------------------------

    def tables(self, mod) -> dict:
        t = self._tables.get(id(mod))
        if t is None:
            t = self._build_tables(mod)
            self._tables[id(mod)] = t
        return t

    def _build_tables(self, mod) -> dict:
        by_name: Dict[str, list] = {}
        classes: Dict[str, ClassInfo] = {}
        owner: Dict[int, ClassInfo] = {}
        for node in ast.walk(mod.tree):
            if isinstance(node, _FUNC_DEFS):
                by_name.setdefault(node.name, []).append(node)
            elif isinstance(node, ast.ClassDef):
                ci = ClassInfo(mod, node)
                classes.setdefault(node.name, ci)
                for meth in ci.methods.values():
                    owner.setdefault(id(meth), ci)
        top: Dict[str, ast.AST] = {}
        instances: Dict[str, ast.AST] = {}
        for st in mod.tree.body:
            if isinstance(st, _FUNC_DEFS):
                top.setdefault(st.name, st)
            elif isinstance(st, ast.Assign) and len(st.targets) == 1:
                tgt = st.targets[0]
                if isinstance(tgt, ast.Name) and isinstance(
                    st.value, ast.Call
                ):
                    instances.setdefault(tgt.id, st.value.func)
            elif isinstance(st, ast.AnnAssign) and isinstance(
                st.target, ast.Name
            ) and isinstance(st.value, ast.Call):
                instances.setdefault(st.target.id, st.value.func)
        return {
            "by_name": by_name,
            "classes": classes,
            "owner": owner,
            "top": top,
            "instances": instances,
        }

    def funcs_by_name(self, mod) -> Dict[str, list]:
        """All function/method defs in ``mod`` keyed by bare name —
        the table the four legacy closure walkers each rebuilt."""
        return self.tables(mod)["by_name"]

    def owner_class(self, mod, fn) -> Optional[ClassInfo]:
        """ClassInfo whose body directly defines ``fn`` (or None)."""
        return self.tables(mod)["owner"].get(id(fn))

    def func_ref(self, mod, fn) -> FuncRef:
        return FuncRef(mod, fn, self.owner_class(mod, fn))

    # -- dotted-name navigation -------------------------------------------

    def _find_module(self, dotted: str):
        """Module whose dotted name is ``dotted`` or uniquely ends with
        it — relative imports surface as suffix paths
        (``from ..utils import trace`` resolves through
        ``utils.trace``)."""
        m = self._by_dotted.get(dotted)
        if m is not None:
            return m
        tail = "." + dotted
        hits = [
            mm for k, mm in self._by_dotted.items() if k.endswith(tail)
        ]
        return hits[0] if len(hits) == 1 else None

    def lookup_dotted(self, mod, dotted: str):
        """Resolve an alias-expanded dotted chain to a project symbol.

        Returns ``("func", FuncRef)``, ``("class", ClassInfo)``,
        ``("instance", ClassInfo)`` (a module-global built by a
        constructor call — the ClassInfo is the instance's class), or
        ``None``.
        """
        parts = dotted.split(".")
        hit = self._navigate(mod, parts)
        if hit is not None:
            return hit
        for i in range(len(parts) - 1, 0, -1):
            m2 = self._find_module(".".join(parts[:i]))
            if m2 is not None and m2 is not mod:
                return self._navigate(m2, parts[i:])
        return None

    def _navigate(self, mod, parts: list):
        if not parts:
            return None
        t = self.tables(mod)
        head, rest = parts[0], parts[1:]
        if not rest:
            fn = t["top"].get(head)
            if fn is not None:
                return ("func", FuncRef(mod, fn, None))
            ci = t["classes"].get(head)
            if ci is not None:
                return ("class", ci)
            inst = self.instance_class(mod, head)
            if inst is not None:
                return ("instance", inst)
            return None
        if len(rest) == 1:
            ci = t["classes"].get(head) or self.instance_class(
                mod, head
            )
            if ci is not None:
                m = self.method_of(ci, rest[0])
                if m is not None:
                    return ("func", m)
        return None

    def instance_class(self, mod, name: str) -> Optional[ClassInfo]:
        """Class of a module-global ``NAME = ClassName(...)``."""
        ctor = self.tables(mod)["instances"].get(name)
        if ctor is None:
            return None
        return self.resolve_class(mod, ctor)

    def resolve_class(self, mod, expr) -> Optional[ClassInfo]:
        """ClassInfo named by a Name/Attribute expr (same module or
        through an import alias)."""
        if isinstance(expr, ast.Name):
            ci = self.tables(mod)["classes"].get(expr.id)
            if ci is not None:
                return ci
        dotted = mod.resolve(expr)
        if dotted:
            hit = self.lookup_dotted(mod, dotted)
            if hit is not None and hit[0] == "class":
                return hit[1]
        return None

    def method_of(
        self, ci: ClassInfo, name: str, _depth: int = 0
    ) -> Optional[FuncRef]:
        """Method ``name`` of ``ci`` or a project-resolved base."""
        meth = ci.methods.get(name)
        if meth is not None:
            return FuncRef(ci.mod, meth, ci)
        if _depth >= 4:
            return None
        for base in ci.node.bases:
            bi = self.resolve_class(ci.mod, base)
            if bi is not None and bi is not ci:
                hit = self.method_of(bi, name, _depth + 1)
                if hit is not None:
                    return hit
        return None

    # -- instance-attribute class inference --------------------------------

    def attr_classes(self, ci: ClassInfo, attr: str) -> list:
        """Candidate classes of ``self.<attr>`` on ``ci``, inferred
        from every ``self.<attr> = ...`` in the class body (constructor
        calls, ``a if c else b`` / ``a or b`` alternatives, annotated
        parameters, module-global instances)."""
        key = (id(ci.node), attr)
        out = self._attr_cache.get(key)
        if out is not None:
            return out
        out = []
        seen = set()
        for meth in ci.methods.values():
            for node in ast.walk(meth):
                value = None
                if isinstance(node, ast.Assign):
                    tgts, value = node.targets, node.value
                elif isinstance(node, ast.AnnAssign) and node.value:
                    tgts, value = [node.target], node.value
                else:
                    continue
                for tgt in tgts:
                    if (
                        isinstance(tgt, ast.Attribute)
                        and tgt.attr == attr
                        and isinstance(tgt.value, ast.Name)
                        and tgt.value.id == "self"
                    ):
                        for cand in self._classes_of_value(
                            ci.mod, value, meth
                        ):
                            if cand.key() not in seen:
                                seen.add(cand.key())
                                out.append(cand)
        self._attr_cache[key] = out
        return out

    def _classes_of_value(self, mod, expr, fn) -> list:
        if isinstance(expr, ast.Call):
            ci = self.resolve_class(mod, expr.func)
            return [ci] if ci is not None else []
        if isinstance(expr, ast.IfExp):
            return self._classes_of_value(
                mod, expr.body, fn
            ) + self._classes_of_value(mod, expr.orelse, fn)
        if isinstance(expr, ast.BoolOp):
            out = []
            for v in expr.values:
                out.extend(self._classes_of_value(mod, v, fn))
            return out
        if isinstance(expr, ast.Name):
            inst = self.instance_class(mod, expr.id)
            if inst is None:
                dotted = mod.resolve(expr)
                if dotted and dotted != expr.id:
                    hit = self.lookup_dotted(mod, dotted)
                    if hit is not None and hit[0] == "instance":
                        inst = hit[1]
            if inst is not None:
                return [inst]
            ann = _param_annotation(fn, expr.id)
            ci = self.class_from_annotation(mod, ann)
            return [ci] if ci is not None else []
        if isinstance(expr, ast.Attribute):
            dotted = mod.resolve(expr)
            if dotted:
                hit = self.lookup_dotted(mod, dotted)
                if hit is not None and hit[0] == "instance":
                    return [hit[1]]
        return []

    def class_from_annotation(self, mod, ann) -> Optional[ClassInfo]:
        """Class named by an annotation, unwrapping ``Optional[...]``/
        ``Union[...]`` subscripts and string forward references."""
        if ann is None:
            return None
        if isinstance(ann, ast.Constant) and isinstance(ann.value, str):
            name = ann.value.strip().rsplit(".", 1)[-1]
            return self.tables(mod)["classes"].get(name)
        if isinstance(ann, ast.Subscript):
            sl = ann.slice
            elts = sl.elts if isinstance(sl, ast.Tuple) else [sl]
            for e in elts:
                ci = self.class_from_annotation(mod, e)
                if ci is not None:
                    return ci
            return None
        if isinstance(ann, (ast.Name, ast.Attribute)):
            return self.resolve_class(mod, ann)
        return None

    # -- call resolution ----------------------------------------------------

    def _ctor_refs(self, ci: ClassInfo) -> list:
        return [
            FuncRef(ci.mod, ci.methods[m], ci)
            for m in _CTOR_PROTOCOL
            if m in ci.methods
        ]

    def _hit_to_funcs(self, hit) -> list:
        if hit is None:
            return []
        kind, obj = hit
        if kind == "func":
            return [obj]
        if kind == "class":
            return self._ctor_refs(obj)
        return []

    def resolve_callable(
        self, mod, expr, cls=None, follow_attr=False
    ) -> list:
        """FuncRefs an expression in call position can reach.

        ``cls`` is the enclosing ClassInfo (enables ``self.*``
        resolution); ``follow_attr`` enables the legacy terminal-name
        fallback for unresolvable attribute calls (``obj.f()`` matches
        any same-module def named ``f`` — serve-host-sync semantics).
        """
        if isinstance(expr, ast.Call):
            # functools.partial(f, ...) used in call/target position.
            if mod.resolve(expr.func) in (
                "functools.partial", "partial"
            ) and expr.args:
                return self.resolve_callable(
                    mod, expr.args[0], cls=cls, follow_attr=follow_attr
                )
            return []
        if isinstance(expr, ast.Name):
            t = self.tables(mod)
            hits = t["by_name"].get(expr.id)
            if hits:
                return [
                    FuncRef(mod, h, t["owner"].get(id(h)))
                    for h in hits
                ]
            ci = t["classes"].get(expr.id)
            if ci is not None:
                return self._ctor_refs(ci)
            dotted = mod.resolve(expr)
            if dotted and dotted != expr.id:
                return self._hit_to_funcs(
                    self.lookup_dotted(mod, dotted)
                )
            return []
        if isinstance(expr, ast.Attribute):
            base = expr.value
            if (
                cls is not None
                and isinstance(base, ast.Name)
                and base.id == "self"
            ):
                m = self.method_of(cls, expr.attr)
                if m is not None:
                    return [m]
            if (
                cls is not None
                and isinstance(base, ast.Attribute)
                and isinstance(base.value, ast.Name)
                and base.value.id == "self"
            ):
                out = []
                for ci in self.attr_classes(cls, base.attr):
                    m = self.method_of(ci, expr.attr)
                    if m is not None:
                        out.append(m)
                if out:
                    return out
            dotted = mod.resolve(expr)
            if dotted:
                fs = self._hit_to_funcs(self.lookup_dotted(mod, dotted))
                if fs:
                    return fs
            if follow_attr:
                t = self.tables(mod)
                return [
                    FuncRef(mod, h, t["owner"].get(id(h)))
                    for h in t["by_name"].get(expr.attr, [])
                ]
            return []
        return []

    def callees(self, mod, call, cls=None, follow_attr=False) -> list:
        """FuncRefs a Call node can invoke."""
        return self.resolve_callable(
            mod, call.func, cls=cls, follow_attr=follow_attr
        )

    def closure(
        self, roots: Iterable[FuncRef], follow_attr=False, skip=None
    ):
        """Transitive call closure: ``{id(node): FuncRef}`` for every
        function reachable from ``roots`` (roots included).

        ``skip`` is an optional predicate over callee FuncRefs: a
        callee it accepts is neither entered nor expanded (roots are
        always entered) — rules use it to stop at semantic boundaries
        such as traced functions.
        """
        seen: Dict[int, FuncRef] = {}
        frontier = list(roots)
        while frontier:
            fr = frontier.pop()
            if fr.key() in seen:
                continue
            seen[fr.key()] = fr
            for node in ast.walk(fr.node):
                if isinstance(node, ast.Call):
                    for cal in self.callees(
                        fr.mod, node,
                        cls=fr.cls, follow_attr=follow_attr,
                    ):
                        if skip is not None and skip(cal):
                            continue
                        frontier.append(cal)
        return seen
