"""racelint — host-concurrency lock-discipline audit (r21).

The serve plane is a genuinely multi-threaded host program: the pump
loop, the `serve_metrics_endpoint` daemon thread, the r19
`jax.debug.callback` probe thread, and the atexit trace exporter all
touch shared registries.  Both concurrency bugs found so far (the r19
MetricsRegistry scrape-vs-pump race, the unlocked probe-token dicts)
were caught by review, not by a gate.  This module is that gate — the
thread-safety twin of swarmlint's source hazards and jaxlint's
lowered-program contracts, built on the callgraph engine:

1. **Thread-root inference** — functions that run concurrently with
   the main program: ``threading.Thread``/``Timer`` targets,
   ``ThreadingHTTPServer`` handler ``do_*`` methods,
   ``jax.debug.callback`` callees (the jax runtime thread),
   ``atexit`` hooks (run while daemon threads are still live), the
   serve pump-loop entry methods, and each spawn site's enclosing
   function (the main-thread side of the pair).

2. **Shared-mutable-state footprint** — module-level containers and
   ``self.``-attributes accessed from two distinct roots with at
   least one write, where at least one root is truly asynchronous
   (thread/handler/callback/atexit — two main-thread functions are
   sequential, not a race).  Two happens-before refinements keep the
   footprint honest: accesses inside ``__init__`` precede publication,
   and accesses in a spawner's own body BEFORE its first spawn site
   precede the thread's existence.

3. **Lock-witness checking** — every shared site must be reached
   under ``with <lock>`` of the SAME lock on every path (lexical
   ``with`` blocks plus interprocedural must-hold propagation along
   the call graph).  Distinct findings:

   * ``race-unguarded-write``  — no access takes any lock;
   * ``race-guard-split``      — some sites locked, this one is not;
   * ``race-lock-mismatch``    — all sites locked, no common lock;
   * ``race-lock-order``       — two locks nested in opposite orders
     on different paths (deadlock under contention).

Like all of swarmlint this is pure AST — precision-biased
(unresolvable expressions contribute no edge) and suppressible with
justified inline comments or the baseline ledger.  The with-lock
regions the model collects are exported (``lock_regions``) to the
dynamic race drill, whose runtime witness checks that every
statically-guarded line actually holds its lock mid-flight.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Optional, Set, Tuple

from .core import ModuleInfo, Rule, register

_LOCK_CTORS = frozenset({"threading.Lock", "threading.RLock"})

#: Root kinds that run asynchronously with the main thread.  "pump"
#: and "spawner" are the main-thread side of a pair — two of those are
#: sequential, not concurrent.
ASYNC_KINDS = frozenset({"thread", "handler", "callback", "atexit"})

#: Method calls that mutate their receiver container.
_MUT_METHODS = frozenset(
    {"append", "extend", "insert", "add", "update", "setdefault",
     "pop", "popitem", "remove", "discard", "clear", "appendleft",
     "popleft", "rotate", "__setitem__"}
)

_MUTABLE_LITERALS = (
    ast.Dict, ast.List, ast.Set, ast.ListComp, ast.DictComp,
    ast.SetComp,
)

_FUNC_DEFS = (ast.FunctionDef, ast.AsyncFunctionDef)


# ---------------------------------------------------------------------------
# lock / state tables


def _module_locks(project, mod) -> Dict[str, tuple]:
    """Module-global ``NAME = threading.Lock()/RLock()`` bindings."""
    key = ("racelint-mlocks", id(mod))
    out = project.cache.get(key)
    if out is None:
        out = {}
        for st in mod.tree.body:
            if (
                isinstance(st, ast.Assign)
                and len(st.targets) == 1
                and isinstance(st.targets[0], ast.Name)
                and isinstance(st.value, ast.Call)
                and mod.resolve(st.value.func) in _LOCK_CTORS
            ):
                name = st.targets[0].id
                out[name] = ("G", mod.relpath, name)
        project.cache[key] = out
    return out


def _class_locks(project, ci) -> Dict[str, tuple]:
    """``self.X = threading.Lock()/RLock()`` attributes of a class."""
    key = ("racelint-clocks", id(ci.node))
    out = project.cache.get(key)
    if out is None:
        out = {}
        for meth in ci.methods.values():
            for node in ast.walk(meth):
                if not (
                    isinstance(node, ast.Assign)
                    and isinstance(node.value, ast.Call)
                    and ci.mod.resolve(node.value.func) in _LOCK_CTORS
                ):
                    continue
                for tgt in node.targets:
                    if (
                        isinstance(tgt, ast.Attribute)
                        and isinstance(tgt.value, ast.Name)
                        and tgt.value.id == "self"
                    ):
                        out[tgt.attr] = ("A", ci.key(), tgt.attr)
        project.cache[key] = out
    return out


def _module_state(project, mod) -> Set[str]:
    """Module-global mutable containers (the shared-state footprint's
    module-level half)."""
    key = ("racelint-mstate", id(mod))
    out = project.cache.get(key)
    if out is None:
        out = set()
        for st in mod.tree.body:
            tgt = None
            if isinstance(st, ast.Assign) and len(st.targets) == 1:
                tgt, value = st.targets[0], st.value
            elif isinstance(st, ast.AnnAssign) and st.value is not None:
                tgt, value = st.target, st.value
            else:
                continue
            if not isinstance(tgt, ast.Name):
                continue
            if isinstance(value, _MUTABLE_LITERALS):
                out.add(tgt.id)
            elif isinstance(value, ast.Call):
                from .callgraph import MUTABLE_CONSTRUCTORS

                if mod.resolve(value.func) in MUTABLE_CONSTRUCTORS:
                    out.add(tgt.id)
        project.cache[key] = out
    return out


def lock_name(lock_key: tuple) -> str:
    """Human/witness rendering of a lock key."""
    if lock_key[0] == "G":
        return f"{lock_key[1]}::{lock_key[2]}"
    (relpath, cls), attr = lock_key[1], lock_key[2]
    return f"{relpath}::{cls}.{attr}"


def _lock_of(project, fr, expr) -> Optional[tuple]:
    """Lock key of a ``with`` context expression, or None when the
    expression is not a recognizable lock object."""
    if isinstance(expr, ast.Name):
        lk = _module_locks(project, fr.mod).get(expr.id)
        if lk is not None:
            return lk
        dotted = fr.mod.resolve(expr)
        if dotted and "." in dotted:
            head, name = dotted.rsplit(".", 1)
            m2 = project._find_module(head)
            if m2 is not None:
                return _module_locks(project, m2).get(name)
        return None
    if isinstance(expr, ast.Attribute):
        base = expr.value
        if (
            fr.cls is not None
            and isinstance(base, ast.Name)
            and base.id == "self"
        ):
            return _class_locks(project, fr.cls).get(expr.attr)
        if (
            fr.cls is not None
            and isinstance(base, ast.Attribute)
            and isinstance(base.value, ast.Name)
            and base.value.id == "self"
        ):
            for ci in project.attr_classes(fr.cls, base.attr):
                lk = _class_locks(project, ci).get(expr.attr)
                if lk is not None:
                    return lk
        dotted = fr.mod.resolve(expr)
        if dotted and "." in dotted:
            head, name = dotted.rsplit(".", 1)
            m2 = project._find_module(head)
            if m2 is not None:
                return _module_locks(project, m2).get(name)
    return None


def _binding_names(tgt) -> Iterable[str]:
    """Names a target BINDS: bare names and destructuring elements.
    ``x[k] = v`` / ``x.a = v`` mutate ``x``'s referent — they bind
    nothing, so they must not shadow a module global."""
    if isinstance(tgt, ast.Name):
        yield tgt.id
    elif isinstance(tgt, (ast.Tuple, ast.List)):
        for el in tgt.elts:
            yield from _binding_names(el)
    elif isinstance(tgt, ast.Starred):
        yield from _binding_names(tgt.value)


def _local_names(fn) -> Set[str]:
    """Names bound locally in ``fn`` (these shadow module globals);
    ``global``-declared names are excluded."""
    out: Set[str] = set()
    hard_globals: Set[str] = set()
    args = fn.args
    for a in (
        list(getattr(args, "posonlyargs", [])) + list(args.args)
        + list(args.kwonlyargs)
        + ([args.vararg] if args.vararg else [])
        + ([args.kwarg] if args.kwarg else [])
    ):
        out.add(a.arg)
    for node in ast.walk(fn):
        if isinstance(node, ast.Global):
            hard_globals.update(node.names)
        elif isinstance(node, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
            tgts = (
                node.targets if isinstance(node, ast.Assign)
                else [node.target]
            )
            for tgt in tgts:
                out.update(_binding_names(tgt))
        elif isinstance(node, ast.For):
            out.update(_binding_names(node.target))
        elif isinstance(node, (ast.With, ast.AsyncWith)):
            for item in node.items:
                if item.optional_vars is not None:
                    out.update(_binding_names(item.optional_vars))
        elif isinstance(node, ast.comprehension):
            out.update(_binding_names(node.target))
    return out - hard_globals


# ---------------------------------------------------------------------------
# thread-root inference


class _Root:
    __slots__ = ("fr", "kinds", "spawn_line")

    def __init__(self, fr):
        self.fr = fr
        self.kinds: Set[str] = set()
        #: For spawner roots: line of the first spawn call in the
        #: function's own body — accesses before it happen before the
        #: spawned thread exists.
        self.spawn_line: Optional[int] = None

    @property
    def is_async(self) -> bool:
        return bool(self.kinds & ASYNC_KINDS)

    def desc(self) -> str:
        return (
            f"`{self.fr.mod.relpath}:{self.fr.name}` "
            f"[{'/'.join(sorted(self.kinds))}]"
        )


def _enclosing(mod, project, node):
    """(FuncRef, first enclosing function) of a call site; the FuncRef
    is None at module top level."""
    for anc in mod.ancestors(node):
        if isinstance(anc, _FUNC_DEFS):
            return project.func_ref(mod, anc)
    return None


def _add_root(roots, fr, kind):
    if fr is None:
        return None
    r = roots.get(fr.key())
    if r is None:
        r = roots[fr.key()] = _Root(fr)
    r.kinds.add(kind)
    return r


def _thread_roots(project) -> Dict[int, "_Root"]:
    roots: Dict[int, _Root] = {}
    for mod in project.modules:
        if "/serve/" in f"/{mod.relpath}":
            for name, fns in project.funcs_by_name(mod).items():
                if "pump" in name.lower():
                    for fn in fns:
                        _add_root(
                            roots, project.func_ref(mod, fn), "pump"
                        )
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            resolved = mod.resolve(node.func) or ""
            leaf = resolved.rsplit(".", 1)[-1]
            spawner = _enclosing(mod, project, node)
            cls = spawner.cls if spawner is not None else None
            targets: list = []
            kind = None
            if resolved == "threading.Thread":
                kind = "thread"
                for kw in node.keywords:
                    if kw.arg == "target":
                        targets.append(kw.value)
            elif resolved == "threading.Timer":
                kind = "thread"
                for kw in node.keywords:
                    if kw.arg == "function":
                        targets.append(kw.value)
                if not targets and len(node.args) > 1:
                    targets.append(node.args[1])
            elif resolved == "atexit.register" and node.args:
                kind = "atexit"
                targets.append(node.args[0])
            elif node.args and (
                resolved.endswith("debug.callback")
                or leaf in ("pure_callback", "io_callback")
            ):
                # All three jax host-callback spellings take the
                # host function as their first positional argument.
                kind = "callback"
                targets.append(node.args[0])
            elif leaf in (
                "ThreadingHTTPServer", "HTTPServer", "TCPServer",
                "ThreadingTCPServer",
            ) and len(node.args) > 1:
                kind = "handler"
                handler_ci = project.resolve_class(mod, node.args[1])
                if handler_ci is not None:
                    for mname, meth in handler_ci.methods.items():
                        if mname.startswith("do_"):
                            _add_root(
                                roots,
                                project.func_ref(
                                    handler_ci.mod, meth
                                ),
                                "handler",
                            )
            if kind is None:
                continue
            for tgt in targets:
                # A target wrapped as functools.partial(f, ...)
                # still roots at f — the heartbeat registry binds
                # its callback this way.
                if isinstance(tgt, ast.Call):
                    inner = mod.resolve(tgt.func) or ""
                    if (
                        inner.rsplit(".", 1)[-1] == "partial"
                        and tgt.args
                    ):
                        tgt = tgt.args[0]
                for fr in project.resolve_callable(
                    mod, tgt, cls=cls
                ):
                    _add_root(roots, fr, kind)
            sp = _add_root(roots, spawner, "spawner")
            if sp is not None:
                line = node.lineno
                if sp.spawn_line is None or line < sp.spawn_line:
                    sp.spawn_line = line
    return roots


# ---------------------------------------------------------------------------
# per-root reach with held-lock propagation


class _Access:
    __slots__ = ("rw", "root", "fr", "node", "locks")

    def __init__(self, rw, root, fr, node, locks):
        self.rw = rw            # "r" | "w"
        self.root = root        # _Root
        self.fr = fr
        self.node = node
        self.locks = locks      # frozenset of lock keys held


class RaceModel:
    """Project-global result of the racelint analysis."""

    def __init__(self):
        #: state key -> [_Access]; keys are ("G", relpath, name) for
        #: module globals and ("A", (relpath, Class), attr) for
        #: instance attributes.
        self.accesses: Dict[tuple, List[_Access]] = {}
        #: (outer lock, inner lock) -> (fr, node) first nesting site.
        self.order: Dict[tuple, tuple] = {}
        #: (relpath, func name, lo, hi, lock key) with-block regions
        #: reached from a root — the dynamic witness's watch list.
        self.regions: List[tuple] = []
        self._region_seen: Set[tuple] = set()
        self.findings: List = []

    def add_region(self, relpath, fname, lo, hi, lock_key):
        item = (relpath, fname, lo, hi, lock_key)
        if item not in self._region_seen:
            self._region_seen.add(item)
            self.regions.append(item)


def state_name(key: tuple) -> str:
    if key[0] == "G":
        return f"module global `{key[2]}`"
    return f"`{key[1][1]}.{key[2]}`"


def _scan_root(project, root, model: RaceModel) -> None:
    held_map: Dict[int, frozenset] = {root.fr.key(): frozenset()}
    fr_map = {root.fr.key(): root.fr}
    queue = [root.fr.key()]
    while queue:
        fkey = queue.pop()
        fr = fr_map[fkey]
        held = held_map[fkey]
        cutoff = (
            root.spawn_line
            if fkey == root.fr.key() and root.spawn_line is not None
            and not (root.kinds - {"spawner"})
            else None
        )
        _scan_fn(
            project, root, fr, held, cutoff, model,
            held_map, fr_map, queue,
        )


def _scan_fn(
    project, root, fr, held, cutoff, model, held_map, fr_map, queue
):
    mod = fr.mod
    local = (
        _local_names(fr.node)
        if isinstance(fr.node, _FUNC_DEFS + (ast.Lambda,)) else set()
    )
    mstate = _module_state(project, mod)
    clocks = (
        _class_locks(project, fr.cls) if fr.cls is not None else {}
    )
    in_init = fr.name == "__init__"

    def record(key, rw, node, locks):
        if in_init:
            return
        if cutoff is not None and node.lineno <= cutoff:
            return
        model.accesses.setdefault(key, []).append(
            _Access(rw, root, fr, node, frozenset(held | set(locks)))
        )

    def global_key(name) -> Optional[tuple]:
        if name in mstate and name not in local:
            return ("G", mod.relpath, name)
        return None

    def attr_key(node) -> Optional[tuple]:
        # self.X on a method, excluding the lock attributes themselves
        if (
            fr.cls is not None
            and isinstance(node, ast.Attribute)
            and isinstance(node.value, ast.Name)
            and node.value.id == "self"
            and node.attr not in clocks
        ):
            return ("A", fr.cls.key(), node.attr)
        return None

    def state_of(expr) -> Optional[tuple]:
        if isinstance(expr, ast.Name):
            return global_key(expr.id)
        if isinstance(expr, ast.Attribute):
            return attr_key(expr)
        return None

    def visit(node, locks):
        if isinstance(
            node, _FUNC_DEFS + (ast.Lambda, ast.ClassDef)
        ):
            return  # runs only when called — reached via call edges
        if isinstance(node, (ast.With, ast.AsyncWith)):
            acquired = []
            for item in node.items:
                visit(item.context_expr, locks)
                lk = _lock_of(project, fr, item.context_expr)
                if lk is None:
                    continue
                prior = held | set(locks) | {a for a, _ in acquired}
                for outer in prior:
                    if outer != lk:
                        pair = (outer, lk)
                        if pair not in model.order:
                            model.order[pair] = (
                                fr, item.context_expr
                            )
                acquired.append((lk, item.context_expr))
            if acquired and node.body:
                lo = node.body[0].lineno
                hi = max(
                    getattr(st, "end_lineno", st.lineno)
                    for st in node.body
                )
                for lk, _ in acquired:
                    model.add_region(
                        mod.relpath, fr.name, lo, hi, lk
                    )
            inner = locks + tuple(lk for lk, _ in acquired)
            for st in node.body:
                visit(st, inner)
            return
        # -- accesses ---------------------------------------------------
        if isinstance(node, ast.Assign):
            for tgt in node.targets:
                _target_access(tgt, locks)
        elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
            _target_access(node.target, locks)
        elif isinstance(node, ast.Delete):
            for tgt in node.targets:
                _target_access(tgt, locks)
        elif isinstance(node, ast.Call):
            if (
                isinstance(node.func, ast.Attribute)
                and node.func.attr in _MUT_METHODS
            ):
                key = state_of(node.func.value)
                if key is not None:
                    record(key, "w", node, locks)
            # propagate held locks along call edges
            new_held = frozenset(held | set(locks))
            for cal in project.callees(mod, node, cls=fr.cls):
                ck = cal.key()
                prev = held_map.get(ck)
                if prev is None:
                    held_map[ck] = new_held
                    fr_map[ck] = cal
                    queue.append(ck)
                elif not prev.issubset(new_held):
                    # must-hold = intersection over all call paths
                    held_map[ck] = prev & new_held
                    fr_map[ck] = cal
                    queue.append(ck)
        elif isinstance(node, ast.Name) and isinstance(
            node.ctx, ast.Load
        ):
            key = global_key(node.id)
            if key is not None:
                record(key, "r", node, locks)
        elif isinstance(node, ast.Attribute) and isinstance(
            node.ctx, ast.Load
        ):
            key = attr_key(node)
            if key is not None:
                record(key, "r", node, locks)
        for child in ast.iter_child_nodes(node):
            visit(child, locks)

    def _target_access(tgt, locks):
        if isinstance(tgt, ast.Subscript):
            key = state_of(tgt.value)
            if key is not None:
                record(key, "w", tgt, locks)
        else:
            key = state_of(tgt)
            if key is not None:
                record(key, "w", tgt, locks)

    body = (
        fr.node.body if isinstance(fr.node.body, list)
        else [fr.node.body]
    )
    for st in body:
        visit(st, ())


# ---------------------------------------------------------------------------
# model -> findings


def race_model(project) -> RaceModel:
    model = project.cache.get("racelint")
    if model is not None:
        return model
    model = RaceModel()
    roots = _thread_roots(project)
    for root in roots.values():
        _scan_root(project, root, model)
    _derive_findings(model)
    project.cache["racelint"] = model
    return model


def _site(acc: _Access) -> tuple:
    return (acc.fr.mod.relpath, acc.node.lineno)


def _derive_findings(model: RaceModel) -> None:
    for key in sorted(
        model.accesses, key=lambda k: (k[0], str(k[1]), str(k[2]))
    ):
        accs = sorted(model.accesses[key], key=_site)
        root_keys = {a.root.fr.key() for a in accs}
        if len(root_keys) < 2:
            continue
        if not any(a.root.is_async for a in accs):
            continue
        writes = [a for a in accs if a.rw == "w"]
        if not writes:
            continue
        roots_desc = " and ".join(
            sorted({a.root.desc() for a in accs})[:3]
        )
        locked = [a for a in accs if a.locks]
        unlocked = [a for a in accs if not a.locks]
        if not locked:
            a = writes[0]
            model.findings.append(a.fr.mod.finding(
                "race-unguarded-write", a.node,
                f"{state_name(key)} is written here and accessed "
                f"from {roots_desc} with NO lock discipline on any "
                "path — wrap every access in `with <lock>` of one "
                "shared threading.RLock (the MetricsRegistry._lock "
                "pattern)",
            ))
            continue
        common = frozenset.intersection(*(a.locks for a in accs))
        if common:
            continue  # every path holds the same lock — clean
        if unlocked:
            a = unlocked[0]
            other = locked[0]
            model.findings.append(a.fr.mod.finding(
                "race-guard-split", a.node,
                f"{state_name(key)} is accessed here with no lock "
                f"held, but is guarded under "
                f"`{lock_name(sorted(other.locks)[0])}` at "
                f"{_site(other)[0]}:{_site(other)[1]} — a guarded "
                "write does not protect an unguarded read; every "
                "path (from " + roots_desc + ") must hold the lock",
            ))
            continue
        a, b = accs[0], next(
            x for x in accs if not (x.locks & accs[0].locks)
        )
        model.findings.append(b.fr.mod.finding(
            "race-lock-mismatch", b.node,
            f"{state_name(key)} is guarded by "
            f"`{lock_name(sorted(b.locks)[0])}` here but by "
            f"`{lock_name(sorted(a.locks)[0])}` at "
            f"{_site(a)[0]}:{_site(a)[1]} — two locks serialize "
            "nothing; pick ONE lock for every access",
        ))
    seen_pairs: Set[frozenset] = set()
    for (outer, inner), (fr, node) in sorted(
        model.order.items(),
        key=lambda kv: (kv[1][0].mod.relpath, kv[1][1].lineno),
    ):
        rev = model.order.get((inner, outer))
        if rev is None:
            continue
        pk = frozenset((outer, inner))
        if pk in seen_pairs:
            continue
        seen_pairs.add(pk)
        rfr, rnode = rev
        model.findings.append(fr.mod.finding(
            "race-lock-order", node,
            f"`{lock_name(inner)}` is acquired while holding "
            f"`{lock_name(outer)}` here, but the OPPOSITE order is "
            f"taken at {rfr.mod.relpath}:{rnode.lineno} — "
            "inconsistent nesting deadlocks under contention; fix "
            "one canonical order",
        ))


def lock_regions(root: str, paths: Iterable[str]):
    """Statically-guarded with-lock regions over ``paths`` — the
    dynamic race drill's witness list.  Returns
    ``[(relpath, func, lo, hi, lock_name_str), ...]``."""
    from . import callgraph
    from .core import ModuleInfo, iter_py_files

    mods = []
    for rel in iter_py_files(root, list(paths)):
        try:
            mods.append(ModuleInfo(root, rel))
        except (SyntaxError, UnicodeDecodeError, OSError):
            continue
    project = callgraph.Project(mods)
    model = race_model(project)
    return [
        (relpath, fname, lo, hi, lock_name(lk))
        for relpath, fname, lo, hi, lk in model.regions
    ]


def racelint_rules() -> dict:
    """The racelint slice of the registry (for scoped runs: the
    `racelint-findings` bench row and graft dryrun axis 35)."""
    from .core import REGISTRY

    return {
        rid: rule for rid, rule in REGISTRY.items()
        if rid.startswith("race-")
    }


# ---------------------------------------------------------------------------
# rules


class _RaceRule(Rule):
    def check(self, mod: ModuleInfo):
        project = mod.project
        if project is None:
            from . import callgraph

            project = callgraph.Project([mod])
        for f in race_model(project).findings:
            if f.rule == self.id and f.path == mod.relpath:
                yield f


@register
class UnguardedWriteRule(_RaceRule):
    id = "race-unguarded-write"
    summary = "shared mutable state written with no lock on any path"
    details = (
        "A module-level container or instance attribute is written "
        "from one thread root and read or written from another, and "
        "NO access takes a lock: concurrent scrape/pump/callback "
        "interleavings tear the structure (the r19 MetricsRegistry "
        "race).  Guard every access with one shared threading.RLock "
        "— the MetricsRegistry._lock pattern."
    )


@register
class GuardSplitRule(_RaceRule):
    id = "race-guard-split"
    summary = "shared state guarded on some paths, bare on others"
    details = (
        "Some accesses to a shared structure hold a lock and at "
        "least one does not: a guarded write does not protect an "
        "unguarded read — the reader can still observe a torn "
        "update.  Every path from every thread root must hold the "
        "same lock."
    )


@register
class LockMismatchRule(_RaceRule):
    id = "race-lock-mismatch"
    summary = "shared state guarded by different locks on different paths"
    details = (
        "Every access is locked but there is no lock COMMON to all "
        "of them: two locks serialize nothing between each other's "
        "holders.  Pick one canonical lock for the structure."
    )


@register
class LockOrderRule(_RaceRule):
    id = "race-lock-order"
    summary = "two locks nested in opposite orders on different paths"
    details = (
        "Path A acquires lock L1 then L2; path B acquires L2 then "
        "L1.  Under contention each holds what the other wants — "
        "classic deadlock.  Fix one canonical acquisition order "
        "(document it next to the lock definitions)."
    )
