"""jaxlint — trace/HLO-level program auditor (r15).

swarmlint (rules_*.py) catches hazards visible in source text, but the
contracts the sharded/serving layers live or die by are properties of
the LOWERED program: "collective-permute present, all-gather absent"
(the r12 spatial tick), "one pmax + one psum per tick, not 37
all-reduces" (the r11 packed-telemetry finding, 34% overhead), donated
buffers actually aliased (the r13 double-buffer loop).  Until r15 each
of those was asserted ad hoc as an HLO-text grep inside one test;
jaxlint promotes them into a first-class analysis pass with per-entry
budgets, a ledger, and tier-1 gating — the HLO twin of swarmlint.

How it works — no backend execution, no backend compile:

1. Every ``compile_watch.watched()`` registry entry has a **lint
   spec** here: a builder producing the entry's canonical small
   example invocation ``(fn, args, kwargs)``.
2. The entry is lowered once through the observatory's memoized
   ``CompileWatch.lower_cached()`` path (``jit(...).lower(...)`` —
   trace + StableHLO emission only), and the module text is parsed
   into a per-function op table with call edges and
   ``stablehlo.while`` loop regions.
3. Four audits run over that table (the **census**, one flat
   ``{key: count}`` dict per entry):

   - **collective census** — all-gather / all-reduce /
     collective-permute / reduce-scatter / all-to-all counts over the
     whole module.  Note this sees what ``lower()`` sees: explicit
     collectives (``shard_map`` bodies, ``lax.p*``) — GSPMD-inserted
     collectives materialize later, inside XLA's SPMD partitioner,
     and would need a backend compile to observe.
   - **scan-body census** (``scan-*`` keys) — the same collectives
     plus ``dynamic_slice`` counted INSIDE ``while`` loop regions
     (scan/fori/while all lower to ``stablehlo.while``), following
     ``func.call`` edges out of the region: a per-tick collective
     costs T× a one-shot one, so the loop-body count is the one that
     gates ("collectives-per-tick").
   - **donation audit** — ``donated-not-aliased`` counts the buffers
     jit WARNED it could not alias ("Some donated buffers were not
     usable"), the exact signal of the r13 donated double-buffer loop
     regressing to copies; ``aliased-outputs`` (informational, plus
     the ``min-aliased-outputs`` floor budget) counts the
     ``tf.aliasing_output`` parameter attributes that prove aliasing.
   - **dtype/widening audit** — ``f64`` type occurrences,
     ``f32-to-f64`` converts (an x64-creep guard: every kernel
     contract here is f32/i32), and ``i64-to-f32`` converts (ids
     widened past i32 then packed into f32 break the 2^24-exact
     packing contract the r11/r12 packed collectives rely on).
   - **bytes census** (r17, the memory observatory) — per-entry
     ``compiled.memory_analysis()`` buckets (:data:`MEMORY_KEYS`),
     memoized via ``CompileWatch.memory_cached``.  The one audit
     that backend-COMPILES (still no execution): peak temp bytes
     are a property of the buffer assignment, not the StableHLO.
     Backends without memory analysis produce a structured
     ``memory_skipped`` reason; ``--no-memory`` skips the pass.

4. Counts are checked against the entry's **declared budgets** in
   ``jaxlint-budgets.json`` (repo root — the same fingerprint-ledger
   pattern as ``swarmlint-baseline.json``): every gated key is a
   CEILING defaulting to 0, so a refactor that silently reintroduces
   an all-gather into the spatial tick, or unpacks the r11 packed
   telemetry reduction back into per-gauge all-reduces, fails tier-1.
   Each ledger entry pins the example invocation's signature hash:
   when the entry's example program changes shape, the entry goes
   **signature-stale** and must be re-measured (``--write-budgets``)
   — budgets must never silently gate a different program.  Ledger
   entries for entries no longer registered are **stale** and fail,
   so the file shrinks when entries die (the swarmlint baseline
   discipline).

Run it::

    python -m distributed_swarm_algorithm_tpu.cli jaxlint            # text
    python -m distributed_swarm_algorithm_tpu.cli jaxlint --json     # machine
    python -m distributed_swarm_algorithm_tpu.cli jaxlint --census   # table
    python -m distributed_swarm_algorithm_tpu.cli jaxlint --write-budgets

Gated in tier-1 by ``tests/test_jaxlint.py`` (full registry lints
clean) and in ``run_all`` as the fixed-name ``jaxlint-findings``
metric plus per-entry ``jaxlint-collectives-per-tick`` rows (unit
"collectives", lower-is-better in compare.py/rundir.py).
"""

from __future__ import annotations

import hashlib
import json
import os
import re
from collections import Counter
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional

# ---------------------------------------------------------------------------
# Census keys

#: Whole-module collective counts (census key -> StableHLO mnemonic).
COLLECTIVE_OPS = {
    "all-gather": "all_gather",
    "all-reduce": "all_reduce",
    "collective-permute": "collective_permute",
    "reduce-scatter": "reduce_scatter",
    "all-to-all": "all_to_all",
}

#: Ops additionally censused inside while-loop regions (per-tick cost).
SCAN_EXTRA_OPS = {"scan-dynamic-slice": "dynamic_slice"}

#: Keys that are reported but never ceiling-gated: they are floors or
#: structure facts, not hazards ("aliased-outputs" regressing DOWN is
#: the hazard — the ``min-aliased-outputs`` budget covers that).
#: "donor-args" (r18): ``jax.buffer_donor`` parameter attrs — how a
#: donated SHARDED arg shows up in the lowering (shard_map defers the
#: input/output pairing to the compiler, so no ``tf.aliasing_output``
#: appears; the bytes census' alias-bytes then proves the aliasing
#: actually landed).
INFO_KEYS = ("aliased-outputs", "donor-args", "while-loops")

#: Budget key declaring a FLOOR on the donation evidence —
#: ``aliased-outputs + donor-args`` (the donation audit's positive
#: half: the r13 serve entry must keep actually aliasing its donated
#: carry, not merely avoid the warning; the r18 sharded entry's
#: donation is donor-attr-shaped, see INFO_KEYS).
MIN_ALIASED = "min-aliased-outputs"

#: The bytes census (r17, the memory observatory): per-entry
#: ``compiled.memory_analysis()`` buckets, each a CEILING budget in
#: bytes (unit "bytes" is already lower-is-better in
#: compare.py/rundir.py).  Unlike the op census these need a backend
#: COMPILE (no execution) — peak temp bytes are a property of the
#: buffer assignment, not the StableHLO — so they ride
#: ``CompileWatch.memory_cached`` (memoized per entry+signature, like
#: the r15 lowering cache).  ``alias-bytes`` is how the r13 donated
#: double-buffer shows up positively: donated carries alias instead
#: of growing temp.
MEMORY_KEYS = (
    "temp-bytes",
    "argument-bytes",
    "output-bytes",
    "alias-bytes",
    "generated-code-bytes",
)

DEFAULT_BUDGETS_BASENAME = "jaxlint-budgets.json"

#: jit's lowering-time donation complaint (utils/compile_watch caches
#: the warning strings alongside the memoized Lowered).
_DONATION_WARNING = "donated buffers were not usable"


def census_keys() -> List[str]:
    """Every census key, in table order."""
    keys = list(COLLECTIVE_OPS)
    keys += [f"scan-{k}" for k in COLLECTIVE_OPS]
    keys += list(SCAN_EXTRA_OPS)
    keys += ["f64", "f32-to-f64", "i64-to-f32", "donated-not-aliased"]
    keys += list(INFO_KEYS)
    return keys


# ---------------------------------------------------------------------------
# StableHLO module text parsing
#
# jax pretty-prints one op per line, so the parser is line-based:
# function bodies and while-op regions are tracked by per-line brace
# deltas (with quoted strings stripped first — sharding attributes
# like ``mhlo.sharding = "{replicated}"`` carry braces inside quotes).

_FUNC_RE = re.compile(
    r"func\.func\s+(?:public\s+|private\s+)?@([\w$.\-]+)"
)
_OP_RE = re.compile(r"\"?(?:stablehlo|mhlo)\.([a-z_0-9]+)")
_CALL_RE = re.compile(r"(?:func\.call|=\s*call)\s+@([\w$.\-]+)")
_QUOTED = re.compile(r'"[^"]*"')
_WHILE_RE = re.compile(r"\"?(?:stablehlo|mhlo)\.while\b")
#: No leading word boundary: the common spelling is ``tensor<4xf64>``
#: and ``xf64`` has no \b between the ``x`` and the ``f``.
_F64 = re.compile(r"(?<!b)f64\b")
_CONVERT_F32_F64 = re.compile(r"convert.*f32.*->.*f64")
_CONVERT_I64_F32 = re.compile(r"convert.*i64.*->.*f32")
_ALIASED = re.compile(r"tf\.aliasing_output")
_DONOR = re.compile(r"jax\.buffer_donor")


def _brace_delta(line: str) -> int:
    bare = _QUOTED.sub('""', line)
    return bare.count("{") - bare.count("}")


@dataclass
class HloFunction:
    """One ``func.func`` of the lowered module."""

    name: str
    lines: List[str]
    ops: Counter = field(default_factory=Counter)
    calls: List[str] = field(default_factory=list)
    #: One entry per top-level ``while`` op: the region's lines
    #: (cond + body — both run per iteration).
    while_regions: List[List[str]] = field(default_factory=list)


def split_functions(text: str) -> Dict[str, HloFunction]:
    """Carve the module into functions (brace-balanced, line-based)."""
    funcs: Dict[str, HloFunction] = {}
    cur: Optional[HloFunction] = None
    depth = 0
    for line in text.splitlines():
        if cur is None:
            m = _FUNC_RE.search(line)
            if not m:
                continue
            cur = HloFunction(name=m.group(1), lines=[line])
            depth = _brace_delta(line)
            if depth <= 0:      # declaration-only (no body)
                funcs[cur.name] = cur
                cur = None
            continue
        cur.lines.append(line)
        depth += _brace_delta(line)
        if depth <= 0:
            funcs[cur.name] = cur
            cur = None
    for fn in funcs.values():
        _index_function(fn)
    return funcs


def _index_function(fn: HloFunction) -> None:
    body = fn.lines
    for line in body:
        fn.ops.update(_OP_RE.findall(line))
        fn.calls.extend(_CALL_RE.findall(line))
    # While regions: from each top-level while op line, consume until
    # the brace depth returns to its pre-op level (the op's two
    # regions, ``cond { ... } do { ... }``, balance out).  Nested
    # whiles are consumed inside the outer region — they stay part of
    # the outer loop's per-iteration cost and are not double-scanned.
    i = 0
    while i < len(body):
        if not _WHILE_RE.search(body[i]):
            i += 1
            continue
        depth = _brace_delta(body[i])
        region: List[str] = []
        opened = depth > 0
        j = i + 1
        while j < len(body):
            d = _brace_delta(body[j])
            depth += d
            region.append(body[j])
            if depth > 0:
                opened = True
            if opened and depth <= 0:
                break
            j += 1
        fn.while_regions.append(region)
        i = j + 1


def _closure_ops(
    funcs: Dict[str, HloFunction], name: str, memo: Dict[str, Counter],
    active: set,
) -> Counter:
    """Op counts of function ``name`` plus everything it transitively
    calls (cycle-safe).  Callees count once per CALL SITE: a body
    calling a collective-bearing helper twice pays its collectives
    twice, and the census must say so."""
    if name in memo:
        return memo[name]
    if name in active or name not in funcs:
        return Counter()
    active.add(name)
    total = Counter(funcs[name].ops)
    for callee, n_sites in Counter(funcs[name].calls).items():
        sub = _closure_ops(funcs, callee, memo, active)
        for op, c in sub.items():
            total[op] += c * n_sites
    active.discard(name)
    memo[name] = total
    return total


def census_of_text(
    text: str, lowering_warnings: Optional[List[str]] = None
) -> Dict[str, int]:
    """The full census of one lowered module's text."""
    funcs = split_functions(text)
    counts: Dict[str, int] = {k: 0 for k in census_keys()}

    module_ops: Counter = Counter()
    for fn in funcs.values():
        module_ops.update(fn.ops)
    for key, op in COLLECTIVE_OPS.items():
        counts[key] = module_ops.get(op, 0)
    counts["while-loops"] = module_ops.get("while", 0)

    # Scan-body census: direct ops inside every while region, plus the
    # transitive closure of functions called from inside a region
    # (scan bodies routinely lower to ``func.call @...``).
    memo: Dict[str, Counter] = {}
    loop_ops: Counter = Counter()
    for fn in funcs.values():
        for region in fn.while_regions:
            callees: List[str] = []
            for line in region:
                loop_ops.update(_OP_RE.findall(line))
                callees.extend(_CALL_RE.findall(line))
            # Once per call SITE: two calls of one helper per
            # iteration cost its collectives twice per tick.
            for callee, n_sites in Counter(callees).items():
                sub = _closure_ops(funcs, callee, memo, set())
                for op, c in sub.items():
                    loop_ops[op] += c * n_sites
    for key, op in COLLECTIVE_OPS.items():
        counts[f"scan-{key}"] = loop_ops.get(op, 0)
    for key, op in SCAN_EXTRA_OPS.items():
        counts[key] = loop_ops.get(op, 0)

    counts["f64"] = len(_F64.findall(text))
    counts["f32-to-f64"] = sum(
        1 for ln in text.splitlines() if _CONVERT_F32_F64.search(ln)
    )
    counts["i64-to-f32"] = sum(
        1 for ln in text.splitlines() if _CONVERT_I64_F32.search(ln)
    )
    counts["aliased-outputs"] = len(_ALIASED.findall(text))
    counts["donor-args"] = len(_DONOR.findall(text))
    counts["donated-not-aliased"] = sum(
        w.count("ShapedArray")
        for w in (lowering_warnings or [])
        if _DONATION_WARNING in w
    )
    return counts


def collectives_per_tick(counts: Dict[str, int]) -> int:
    """The headline per-entry number: collectives inside loop bodies
    (each fires once per tick of the scanned rollout)."""
    return sum(counts[f"scan-{k}"] for k in COLLECTIVE_OPS)


# ---------------------------------------------------------------------------
# Lint-entry registry: entry name -> canonical small example invocation

@dataclass(frozen=True)
class LintSpec:
    """One watched entry's lint registration."""

    entry: str
    build: Callable[[], tuple]   # -> (fn, args, kwargs)
    min_devices: int = 1
    note: str = ""


LINT_REGISTRY: Dict[str, LintSpec] = {}


def lint_entry(
    entry: str, min_devices: int = 1, note: str = ""
) -> Callable:
    """Decorator registering a builder of ``entry``'s canonical
    example invocation.  Builders import lazily and must be cheap on
    host (eager constructors only — ``jax.eval_shape`` /
    ``ShapeDtypeStruct`` where a concrete arg would need device
    execution to produce)."""

    def register(build: Callable[[], tuple]) -> Callable[[], tuple]:
        if entry in LINT_REGISTRY:
            raise ValueError(f"duplicate lint entry {entry!r}")
        LINT_REGISTRY[entry] = LintSpec(
            entry=entry, build=build, min_devices=min_devices,
            note=note,
        )
        return build

    return register


def _rastrigin():
    from ..ops.objectives import get_objective

    return get_objective("rastrigin")[0]


def _swarm_cfg():
    """The r12 flagship hashgrid config — shared by the rollout, tick
    and spatial specs so their censuses are comparable."""
    import distributed_swarm_algorithm_tpu as dsa

    return dsa.SwarmConfig().replace(
        separation_mode="hashgrid", world_hw=64.0,
        formation_shape="none", hashgrid_backend="portable",
        grid_max_per_cell=24, max_speed=1.0, hashgrid_skin=1.0,
    )


def _station(n: int, seed: int = 0):
    import jax.numpy as jnp

    import distributed_swarm_algorithm_tpu as dsa

    s = dsa.make_swarm(n, seed=seed, spread=64.0 * 0.9)
    return s.replace(
        target=jnp.asarray(s.pos),
        has_target=jnp.ones_like(s.has_target),
    )


@lint_entry("swarm-tick")
def _spec_swarm_tick():
    from ..models.swarm import _swarm_tick_impl

    return _swarm_tick_impl, (_station(64), None, _swarm_cfg()), {}


@lint_entry("swarm-rollout")
def _spec_swarm_rollout():
    from ..models.swarm import _swarm_rollout_impl

    # r22: census the locality-aware refresh path (per-cell partial
    # repair) — the flagship amortized rollout configuration.
    cfg = _swarm_cfg().replace(hashgrid_partial_refresh=True)
    return (
        _swarm_rollout_impl, (_station(64), None, cfg, 4), {},
    )


@lint_entry("candidate-sweep")
def _spec_candidate_sweep():
    from ..ops.pallas.candidate_sweep import candidate_sweep_forces
    from ..ops.physics import build_tick_plan

    # r23: the plan-native Pallas candidate sweep's standalone
    # watched entry — censused in interpret mode (the Mosaic lowering
    # is TPU-only) on the flagship station with the candidates-flavor
    # plan (lane-tiled cand + recv operands).
    cfg = _swarm_cfg().replace(hashgrid_kernel="candidates")
    state = _station(64)
    plan = build_tick_plan(state, cfg)
    return (
        candidate_sweep_forces,
        (state.pos, plan),
        {
            "k_sep": float(cfg.k_sep),
            "personal_space": float(cfg.personal_space),
            "eps": float(cfg.dist_eps),
            "interpret": True,
        },
    )


@lint_entry(
    "swarm-rollout-spatial", min_devices=8,
    note="needs the 8-virtual-device rig (conftest XLA flag)",
)
def _spec_swarm_rollout_spatial():
    import jax

    from ..models.swarm import _swarm_rollout_spatial_impl
    from ..parallel.mesh import make_mesh
    from ..parallel.spatial import SPATIAL_AXIS, spatial_shard_swarm

    # r22: census the per-tile trigger + re-homing tick — the fully
    # locality-aware sharded configuration (the global-OR baseline
    # stays covered by the bitwise parity pins in
    # tests/test_spatial_shard.py).
    cfg = _swarm_cfg().replace(
        spatial_per_tile_rebuild=True, spatial_rehome=True,
    )
    mesh = make_mesh((SPATIAL_AXIS,), devices=jax.devices()[:8])
    tiled, spec = spatial_shard_swarm(_station(512), mesh, cfg)
    return (
        _swarm_rollout_spatial_impl,
        (tiled, None, cfg, 6, mesh, spec), {},
    )


@lint_entry("boids-run")
def _spec_boids_run():
    from ..ops.boids import BoidsParams, boids_init, boids_run

    params = BoidsParams()
    return boids_run, (boids_init(64, params=params), params, 4), {}


@lint_entry("island-run")
def _spec_island_run():
    from ..parallel.islands import island_init, island_run

    fn = _rastrigin()
    st = island_init(fn, 4, 16, 4, 5.12, seed=0)
    return (
        island_run, (st, fn, 4),
        {"migrate_every": 2, "migrate_k": 2},
    )


@lint_entry("pso-dimshard", min_devices=8)
def _spec_pso_dimshard():
    import jax

    from ..ops.pso import pso_init
    from ..parallel.dimshard import pso_run_dimshard, shard_pso_dim
    from ..parallel.mesh import make_mesh

    mesh = make_mesh(("dim",), devices=jax.devices()[:8])
    st = shard_pso_dim(
        pso_init(_rastrigin(), n=32, dim=16, half_width=5.12, seed=0),
        mesh,
    )
    return pso_run_dimshard, (st, "rastrigin", mesh, 3), {}


@lint_entry("es-dimshard", min_devices=8)
def _spec_es_dimshard():
    import jax

    from ..ops.es import es_init
    from ..parallel.dimshard import es_run_dimshard, shard_es_dim
    from ..parallel.mesh import make_mesh

    mesh = make_mesh(("dim",), devices=jax.devices()[:8])
    st = shard_es_dim(
        es_init(_rastrigin(), dim=16, half_width=5.12, seed=0), mesh
    )
    return es_run_dimshard, (st, "rastrigin", mesh, 3), {"n": 16}


@lint_entry("pso-shmap", min_devices=8)
def _spec_pso_shmap():
    import jax

    from ..ops.pso import pso_init
    from ..parallel.mesh import make_mesh
    from ..parallel.sharding import pso_run_shmap, shard_pso

    fn = _rastrigin()
    mesh = make_mesh(("agents",), devices=jax.devices()[:8])
    st = shard_pso(pso_init(fn, 64, 4, 5.12, seed=0), mesh)
    return pso_run_shmap, (st, fn, mesh, 3), {"axis": "agents"}


@lint_entry("pso-run")
def _spec_pso_run():
    from ..ops.pso import pso_init, pso_run

    fn = _rastrigin()
    return pso_run, (pso_init(fn, 32, 4, 5.12, seed=0), fn, 3), {}


@lint_entry("de-run")
def _spec_de_run():
    from ..ops.de import de_init, de_run

    fn = _rastrigin()
    return de_run, (de_init(fn, 16, 4, 5.12, seed=0), fn, 3), {}


@lint_entry("es-run")
def _spec_es_run():
    from ..ops.es import es_init, es_run

    fn = _rastrigin()
    return (
        es_run, (es_init(fn, dim=4, half_width=5.12, seed=0), fn, 3),
        {"n": 16},
    )


@lint_entry("gwo-run")
def _spec_gwo_run():
    from ..ops.gwo import gwo_init, gwo_run

    fn = _rastrigin()
    return gwo_run, (gwo_init(fn, 32, 4, 5.12, seed=0), fn, 3), {}


def _serve_cfg():
    import distributed_swarm_algorithm_tpu as dsa

    return dsa.SwarmConfig().replace(
        formation_shape="none", utility_threshold=2.0,
        election_timeout_ticks=10, heartbeat_period_ticks=5,
    )


@lint_entry("serve-materialize")
def _spec_serve_materialize():
    import jax.numpy as jnp

    from ..serve.batched import _materialize_batch_impl

    S, cap = 2, 8
    return (
        _materialize_batch_impl,
        (
            jnp.zeros((S,), jnp.int32),
            jnp.full((S,), 8.0, jnp.float32),
            jnp.ones((S, cap), bool),
            jnp.zeros((S,), bool),
            jnp.zeros((S, 2), jnp.float32),
            jnp.zeros((S, 0, 2), jnp.float32),
            cap,
            0,
        ),
        {},
    )


@lint_entry("serve-batched-rollout")
def _spec_serve_batched_rollout():
    import jax
    import jax.numpy as jnp

    from ..serve.batched import (
        _materialize_batch_impl,
        scenario_params,
        stack_params,
    )

    cfg = _serve_cfg()
    S, cap = 2, 8
    # The donated states arg rides as ShapeDtypeStructs (lower()
    # accepts avals) — materializing for real would EXECUTE the
    # materializer, and jaxlint never executes.  Statics are bound
    # via partial: eval_shape abstracts every positional arg.
    import functools

    states = jax.eval_shape(
        functools.partial(
            _materialize_batch_impl, capacity=cap, n_tasks=0
        ),
        jnp.zeros((S,), jnp.int32),
        jnp.full((S,), 8.0, jnp.float32),
        jnp.ones((S, cap), bool),
        jnp.zeros((S,), bool),
        jnp.zeros((S, 2), jnp.float32),
        jnp.zeros((S, 0, 2), jnp.float32),
    )
    params = stack_params([scenario_params(cfg), scenario_params(cfg)])
    from ..serve.batched import _batched_rollout_impl

    return _batched_rollout_impl, (states, params, cfg, 6), {}


@lint_entry(
    "serve-batched-rollout-sharded", min_devices=8,
    note="needs the 8-virtual-device rig (conftest XLA flag)",
)
def _spec_serve_batched_rollout_sharded():
    import functools

    import jax
    import jax.numpy as jnp

    from ..parallel.mesh import SCENARIO_AXIS, make_serve_mesh
    from ..serve.batched import (
        _batched_rollout_sharded_impl,
        _materialize_batch_impl,
        scenario_params,
        stack_params,
    )

    cfg = _serve_cfg()
    S, cap = 8, 8
    # Same ShapeDtypeStruct discipline as the unsharded serve spec:
    # the donated states ride as avals, nothing executes.  The mesh
    # is the genuine 2D (scenarios, tiles) serve mesh — the census
    # must prove zero collectives on the REAL axis layout, tiles
    # replication included.
    states = jax.eval_shape(
        functools.partial(
            _materialize_batch_impl, capacity=cap, n_tasks=0
        ),
        jnp.zeros((S,), jnp.int32),
        jnp.full((S,), 8.0, jnp.float32),
        jnp.ones((S, cap), bool),
        jnp.zeros((S,), bool),
        jnp.zeros((S, 2), jnp.float32),
        jnp.zeros((S, 0, 2), jnp.float32),
    )
    params = stack_params([scenario_params(cfg)] * S)
    mesh = make_serve_mesh(
        scenarios=4, tiles=2, devices=jax.devices()[:8]
    )
    return (
        _batched_rollout_sharded_impl,
        (states, params, cfg, 6, mesh, SCENARIO_AXIS), {},
    )


@lint_entry("env-rollout")
def _spec_env_rollout():
    import jax

    from .. import envs

    cfg = _serve_cfg()
    env = envs.SwarmMARLEnv(
        cfg=cfg, capacity=24, n_tasks=2, n_obstacles=2, k_neighbors=4,
        obs_max_per_cell=24,
    )
    from ..envs.core import _env_rollout_impl

    p = envs.stack_env_params(
        [envs.station_keeping(env, n_agents=20)]
    )
    keys = jax.random.PRNGKey(7)[None]
    return _env_rollout_impl, (keys, p, env, 8), {}


def _train_env():
    """The training specs' shared small env + scenario batch — the
    heterogeneous pursuit shape (2 capability classes, the obs plan
    on the r20 Verlet carry) so the lint census covers the full
    machinery, at lint-friendly scale."""
    from .. import envs
    from ..train.caps import pursuit_caps

    env = envs.SwarmMARLEnv(
        cfg=_serve_cfg(), capacity=12, k_neighbors=2,
        obs_max_per_cell=12, n_cap_classes=2, obs_skin=2.0,
    )
    p = envs.stack_env_params([
        envs.pursuit_evasion(
            env, n_agents=8, caps=pursuit_caps(env, n_agents=8),
            max_steps=100,
        )
    ])
    return env, p


@lint_entry("train-step")
def _spec_train_step():
    import functools

    import jax

    from ..train.ppo import (
        TrainConfig,
        _train_step_impl,
        init_train_state,
    )

    env, p = _train_env()
    tcfg = TrainConfig(rollout_steps=4, n_epochs=2, hidden=(16,))
    # The donated TrainState rides as ShapeDtypeStructs (lower()
    # accepts avals) — materializing it would EXECUTE the vmapped env
    # reset + network init, and jaxlint never executes.
    ts = jax.eval_shape(
        functools.partial(init_train_state, env=env, tcfg=tcfg),
        jax.random.PRNGKey(0), p,
    )
    return _train_step_impl, (ts, env, tcfg), {}


@lint_entry("policy-rollout")
def _spec_policy_rollout():
    import functools

    import jax

    from ..train.ppo import (
        TrainConfig,
        _policy_rollout_impl,
        init_policy_params,
    )

    env, p = _train_env()
    tcfg = TrainConfig(rollout_steps=4, n_epochs=2, hidden=(16,))
    net = jax.eval_shape(
        functools.partial(
            init_policy_params, obs_dim=env.obs_dim, act_dim=2,
            tcfg=tcfg,
        ),
        jax.random.PRNGKey(0),
    )
    keys = jax.random.PRNGKey(3)[None]
    return _policy_rollout_impl, (keys, p, net, env, tcfg, 6), {}


# ---------------------------------------------------------------------------
# Auditing

@dataclass
class EntryAudit:
    """One registry entry's measured census (or skip reason)."""

    entry: str
    signature: str = ""          # short fingerprint of the example args
    counts: Dict[str, int] = field(default_factory=dict)
    skipped: str = ""            # non-empty: why the entry did not lower
    #: Bytes census (r17): MEMORY_KEYS -> measured bytes; empty when
    #: the memory audit was off or structurally skipped.
    memory: Dict[str, int] = field(default_factory=dict)
    #: Non-empty: why the bytes census could not be measured here
    #: (backend keeps no memory analysis) — structured, never silent.
    memory_skipped: str = ""

    def to_dict(self) -> dict:
        return {
            "entry": self.entry,
            "signature": self.signature,
            "counts": dict(self.counts),
            "skipped": self.skipped,
            "memory": dict(self.memory),
            "memory_skipped": self.memory_skipped,
            "collectives_per_tick": (
                collectives_per_tick(self.counts) if self.counts else None
            ),
        }


def _sig_hash(args: tuple, kwargs: dict) -> str:
    from ..utils.compile_watch import arg_signature

    return hashlib.sha256(
        arg_signature(args, kwargs).encode()
    ).hexdigest()[:12]


def census_of(fn: Callable, *args, **kwargs) -> Dict[str, int]:
    """Census an arbitrary jitted callable + example args (the API the
    migrated HLO-grep tests and seeded fixtures use).  Lowering is
    memoized through the global compile observatory."""
    from ..utils.compile_watch import WATCH

    lowered, warns = WATCH.lower_cached(fn, *args, **kwargs)
    return census_of_text(lowered.as_text(), warns)


def audit_entry(name: str, memory: bool = True) -> EntryAudit:
    """Lower + census one registered entry (memoized per process via
    the observatory's lowering cache).  ``memory=True`` additionally
    backend-compiles the example (still no execution) for the bytes
    census — memoized the same way, so the full registry pays each
    compile once per process."""
    import jax

    spec = LINT_REGISTRY[name]
    if len(jax.devices()) < spec.min_devices:
        return EntryAudit(
            entry=name,
            skipped=(
                f"needs {spec.min_devices} devices, have "
                f"{len(jax.devices())}"
                + (f" ({spec.note})" if spec.note else "")
            ),
        )
    fn, args, kwargs = spec.build()
    counts = census_of(fn, *args, **kwargs)
    mem: Dict[str, int] = {}
    mem_skip = ""
    if memory:
        from ..utils.compile_watch import WATCH

        got = WATCH.memory_cached(
            fn, *args,
            has_aliasing=(
                counts.get("aliased-outputs", 0) > 0
                or counts.get("donor-args", 0) > 0
            ),
            **kwargs,
        )
        if "skipped" in got:
            mem_skip = got["skipped"]
        else:
            mem = dict(got)
    return EntryAudit(
        entry=name, signature=_sig_hash(args, kwargs), counts=counts,
        memory=mem, memory_skipped=mem_skip,
    )


def entry_census(name: str) -> Dict[str, int]:
    """The census dict of one registered entry (raises on skip — a
    caller asserting a collective contract must not pass vacuously)."""
    audit = audit_entry(name)
    if audit.skipped:
        raise RuntimeError(
            f"jaxlint entry {name!r} not lintable here: {audit.skipped}"
        )
    return audit.counts


# ---------------------------------------------------------------------------
# Budget ledger (jaxlint-budgets.json)

#: Repo root = three levels up (package/analysis/jaxlint.py).
REPO_ROOT = os.path.dirname(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)


@dataclass(frozen=True)
class BudgetEntry:
    entry: str
    signature: str
    budgets: Dict[str, int]
    justification: str

    def to_dict(self) -> dict:
        return {
            "entry": self.entry,
            "signature": self.signature,
            "budgets": dict(self.budgets),
            "justification": self.justification,
        }


class BudgetError(ValueError):
    """Malformed budgets file."""


def load_budgets(path: str) -> Dict[str, BudgetEntry]:
    if not os.path.exists(path):
        return {}
    with open(path, encoding="utf-8") as f:
        try:
            data = json.load(f)
        except json.JSONDecodeError as e:
            raise BudgetError(f"{path}: not valid JSON: {e}") from e
    out: Dict[str, BudgetEntry] = {}
    for i, raw in enumerate(data.get("entries", [])):
        missing = [
            k for k in ("entry", "signature", "budgets", "justification")
            if k not in raw
        ]
        if missing:
            raise BudgetError(f"{path}: entry {i} missing {missing}")
        if not str(raw["justification"]).strip():
            raise BudgetError(
                f"{path}: entry {i} ({raw['entry']}) has an empty "
                "justification — declared budgets must say why the "
                "counts are the contract"
            )
        bad = [
            k for k in raw["budgets"]
            if k != MIN_ALIASED and k not in census_keys()
            and k not in MEMORY_KEYS
        ]
        if bad:
            raise BudgetError(
                f"{path}: entry {i} ({raw['entry']}) budgets unknown "
                f"census key(s) {bad}"
            )
        out[raw["entry"]] = BudgetEntry(
            entry=raw["entry"],
            signature=str(raw["signature"]),
            budgets={k: int(v) for k, v in raw["budgets"].items()},
            justification=str(raw["justification"]),
        )
    return out


def save_budgets(path: str, entries: Dict[str, BudgetEntry]) -> None:
    with open(path, "w", encoding="utf-8") as f:
        json.dump(
            {
                "entries": [
                    entries[k].to_dict() for k in sorted(entries)
                ]
            },
            f, indent=1, sort_keys=True,
        )
        f.write("\n")


def budget_from_audit(
    audit: EntryAudit, justification: str,
    previous: Optional[BudgetEntry] = None,
) -> BudgetEntry:
    """A ledger entry pinning the audit's measured counts (nonzero
    gated keys only — zero is the default ceiling)."""
    budgets = {
        k: v for k, v in audit.counts.items()
        if v and k not in INFO_KEYS
    }
    evidence = (
        audit.counts.get("aliased-outputs", 0)
        + audit.counts.get("donor-args", 0)
    )
    if evidence:
        budgets[MIN_ALIASED] = evidence
    # Bytes census (r17): nonzero measured bytes become ceilings too
    # (zero stays the default, so a footprint APPEARING where none
    # was declared fails until re-measured).  An audit that carried
    # NO memory census (--no-memory, or a structural backend skip)
    # preserves the previously declared byte ceilings instead of
    # silently erasing them from the ledger.
    if audit.memory:
        budgets.update(
            {k: v for k, v in audit.memory.items() if v}
        )
    elif previous is not None:
        budgets.update({
            k: v for k, v in previous.budgets.items()
            if k in MEMORY_KEYS
        })
    return BudgetEntry(
        entry=audit.entry, signature=audit.signature,
        budgets=budgets, justification=justification,
    )


@dataclass(frozen=True)
class LintFinding:
    """One budget/contract violation at one entry."""

    entry: str
    check: str                   # census key, or a lifecycle check id
    message: str
    measured: Optional[int] = None
    budget: Optional[int] = None

    def to_dict(self) -> dict:
        return {
            "entry": self.entry,
            "check": self.check,
            "message": self.message,
            "measured": self.measured,
            "budget": self.budget,
        }

    def render(self) -> str:
        return f"{self.entry}: [{self.check}] {self.message}"


def check_against_budget(
    audit: EntryAudit, entry: Optional[BudgetEntry]
) -> List[LintFinding]:
    """Findings for one audited entry vs its declared budgets."""
    findings: List[LintFinding] = []
    if entry is None:
        findings.append(
            LintFinding(
                entry=audit.entry, check="undeclared",
                message=(
                    "no declared budget — every registered entry "
                    "must declare its census contract (run "
                    "`cli jaxlint --write-budgets`, then edit the "
                    "justification)"
                ),
            )
        )
        return findings
    if entry.signature != audit.signature:
        findings.append(
            LintFinding(
                entry=audit.entry, check="signature-stale",
                message=(
                    f"example-program signature {audit.signature} != "
                    f"declared {entry.signature} — the entry's "
                    "canonical invocation changed shape; re-measure "
                    "and re-declare (`--write-budgets`), budgets must "
                    "never gate a different program"
                ),
            )
        )
        # Signature drift does NOT short-circuit the count checks:
        # a refactor that both reshapes the example AND regresses a
        # collective must surface both facts.
    for key, measured in audit.counts.items():
        if key in INFO_KEYS:
            continue
        budget = entry.budgets.get(key, 0)
        if measured > budget:
            findings.append(
                LintFinding(
                    entry=audit.entry, check=key,
                    measured=measured, budget=budget,
                    message=(
                        f"{key} count {measured} exceeds the declared "
                        f"budget {budget}"
                        + (
                            " — a collective crept into the lowered "
                            "program"
                            if key in COLLECTIVE_OPS
                            or key.startswith("scan-")
                            else ""
                        )
                    ),
                )
            )
    # Bytes-census ceilings (r17): same default-0 discipline as the
    # op census — any measured footprint past its declared budget
    # (or appearing undeclared) gates; a structural memory skip
    # (audit.memory empty) checks nothing here, and the skip reason
    # rides the audit's to_dict so it is never silent.
    for key, measured in audit.memory.items():
        budget = entry.budgets.get(key, 0)
        if measured > budget:
            findings.append(
                LintFinding(
                    entry=audit.entry, check=key,
                    measured=measured, budget=budget,
                    message=(
                        f"{key} {measured} exceeds the declared "
                        f"budget {budget} — the compiled footprint "
                        "grew; re-measure (`--write-budgets`) only "
                        "if the growth is justified"
                    ),
                )
            )
    floor = entry.budgets.get(MIN_ALIASED)
    if floor is not None:
        got = (
            audit.counts.get("aliased-outputs", 0)
            + audit.counts.get("donor-args", 0)
        )
        if got < floor:
            findings.append(
                LintFinding(
                    entry=audit.entry, check=MIN_ALIASED,
                    measured=got, budget=floor,
                    message=(
                        f"only {got} aliased/donor-marked buffers, "
                        f"floor {floor} — donation regressed to "
                        "copies (the r13 double-buffer contract)"
                    ),
                )
            )
    return findings


@dataclass
class AuditResult:
    audits: List[EntryAudit]
    findings: List[LintFinding]
    stale: List[str]             # ledger entries with no registry entry
    skipped: List[EntryAudit]

    def to_dict(self) -> dict:
        return {
            "tool": "jaxlint",
            "counts": {
                "entries": len(self.audits),
                "findings": len(self.findings),
                "stale_budget": len(self.stale),
                "skipped": len(self.skipped),
            },
            "findings": [f.to_dict() for f in self.findings],
            "stale_budget": list(self.stale),
            "entries": [a.to_dict() for a in self.audits],
            "skipped": [a.to_dict() for a in self.skipped],
        }


def run_audit(
    entries: Optional[List[str]] = None,
    budgets_path: Optional[str] = None,
    memory: bool = True,
) -> AuditResult:
    """Audit ``entries`` (default: the whole registry) against the
    declared budgets.  Stale ledger entries only prove stale on a
    full-registry run (the swarmlint scoped-scan rule).
    ``memory=False`` skips the bytes census (lower-only audit — no
    backend compiles)."""
    names = list(entries) if entries else sorted(LINT_REGISTRY)
    unknown = [n for n in names if n not in LINT_REGISTRY]
    if unknown:
        raise KeyError(
            f"unknown lint entr{'y' if len(unknown) == 1 else 'ies'} "
            f"{unknown}; registered: {sorted(LINT_REGISTRY)}"
        )
    path = budgets_path or os.path.join(
        REPO_ROOT, DEFAULT_BUDGETS_BASENAME
    )
    declared = load_budgets(path)
    audits: List[EntryAudit] = []
    skipped: List[EntryAudit] = []
    findings: List[LintFinding] = []
    for name in names:
        audit = audit_entry(name, memory=memory)
        if audit.skipped:
            skipped.append(audit)
            continue
        audits.append(audit)
        findings.extend(
            check_against_budget(audit, declared.get(name))
        )
    stale: List[str] = []
    if not entries:   # full run: absence from the REGISTRY proves
        # staleness (skipped entries are still registered — a budget
        # for an entry this host cannot lower is not stale debt)
        stale = sorted(e for e in declared if e not in LINT_REGISTRY)
        for e in stale:
            findings.append(
                LintFinding(
                    entry=e, check="stale-budget",
                    message=(
                        "budget declared for an entry that is no "
                        "longer registered — remove it from "
                        f"{DEFAULT_BUDGETS_BASENAME}"
                    ),
                )
            )
    return AuditResult(
        audits=audits, findings=findings, stale=stale, skipped=skipped,
    )


# ---------------------------------------------------------------------------
# CLI (dispatched from cli.py's ``jaxlint`` subcommand)


def main_cli(args) -> int:
    """Exit 0 clean, 1 findings/stale budgets, 2 usage error."""
    budgets_path = args.budgets or os.path.join(
        REPO_ROOT, DEFAULT_BUDGETS_BASENAME
    )
    if args.list_entries:
        for name in sorted(LINT_REGISTRY):
            spec = LINT_REGISTRY[name]
            extra = (
                f"  (min {spec.min_devices} devices)"
                if spec.min_devices > 1 else ""
            )
            print(f"{name:24}{extra}")
        return 0
    import sys

    try:
        result = run_audit(
            entries=args.entries or None, budgets_path=budgets_path,
            memory=not getattr(args, "no_memory", False),
        )
    except (KeyError, BudgetError) as e:
        # KeyError str() is the quoted repr of its arg — unwrap it.
        msg = e.args[0] if isinstance(e, KeyError) and e.args else e
        print(f"jaxlint: {msg}", file=sys.stderr)
        return 2 if isinstance(e, KeyError) else 1

    if args.write_budgets:
        declared = load_budgets(budgets_path)
        for audit in result.audits:
            prev = declared.get(audit.entry)
            just = (
                prev.justification
                if prev is not None
                and not prev.justification.startswith("TODO(")
                else "TODO(jaxlint): justify the measured counts"
            )
            declared[audit.entry] = budget_from_audit(
                audit, just, previous=prev
            )
        for name in result.stale:
            declared.pop(name, None)
        save_budgets(budgets_path, declared)
        print(
            f"jaxlint: wrote {len(declared)} entries to "
            f"{budgets_path} (edit the TODO justifications)"
        )
        return 0

    if args.as_json:
        print(json.dumps(result.to_dict(), indent=1))
    else:
        if args.census:
            keys = [
                k for k in census_keys() if any(
                    a.counts.get(k) for a in result.audits
                )
            ]
            for audit in result.audits:
                row = ", ".join(
                    f"{k}={audit.counts[k]}" for k in keys
                    if audit.counts.get(k)
                ) or "no collectives / clean"
                if audit.memory:
                    row += (
                        f"  bytes[temp={audit.memory['temp-bytes']}"
                        f", alias={audit.memory['alias-bytes']}]"
                    )
                elif audit.memory_skipped:
                    row += "  bytes[skipped]"
                print(
                    f"{audit.entry:24} per-tick="
                    f"{collectives_per_tick(audit.counts):<3} {row}"
                )
        for f in result.findings:
            print(f.render())
        for a in result.skipped:
            print(f"# skipped: {a.entry} ({a.skipped})")
        print(
            f"# jaxlint: {len(result.findings)} finding(s), "
            f"{len(result.audits)} entr"
            f"{'y' if len(result.audits) == 1 else 'ies'} audited, "
            f"{len(result.skipped)} skipped, "
            f"{len(result.stale)} stale budget entr"
            f"{'y' if len(result.stale) == 1 else 'ies'}"
        )
    return 1 if result.findings else 0
