"""swarmlint CLI.

Exit codes: 0 clean (every finding fixed, suppressed with a
justification, or baselined — and no stale baseline entries), 1 new
findings / stale entries / malformed baseline, 2 usage error (e.g. a
nonexistent scan path).  ``--json`` prints one machine-readable
summary object — the shape ``benchmarks/run_all.py`` turns into the
fixed-name ``swarmlint-findings`` metric.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

from . import (
    DEFAULT_PATHS,
    REGISTRY,
    analyze_paths,
    baseline,
    iter_py_files,
)

#: Repo root = three levels up from this file (package/analysis/__main__).
REPO_ROOT = os.path.dirname(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)


def build_parser() -> argparse.ArgumentParser:
    ap = argparse.ArgumentParser(
        prog="python -m distributed_swarm_algorithm_tpu.analysis",
        description=__doc__,
    )
    ap.add_argument(
        "paths", nargs="*",
        help=f"files/dirs to scan (default: {', '.join(DEFAULT_PATHS)})",
    )
    ap.add_argument("--root", default=REPO_ROOT,
                    help="repo root paths are relative to")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="machine-readable summary on stdout")
    ap.add_argument(
        "--baseline",
        default=None,
        help="baseline file (default <root>/"
             f"{baseline.DEFAULT_BASENAME})",
    )
    ap.add_argument("--no-baseline", action="store_true",
                    help="ignore the baseline: report every finding")
    ap.add_argument(
        "--write-baseline", action="store_true",
        help="write all current new findings to the baseline file "
             "with TODO justifications (then edit them in)",
    )
    ap.add_argument("--list-rules", action="store_true")
    return ap


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    if args.list_rules:
        for rule in REGISTRY.values():
            print(f"{rule.id:16} {rule.summary}")
        return 0

    root = os.path.abspath(args.root)
    paths = list(args.paths) or [
        p for p in DEFAULT_PATHS
        if os.path.exists(os.path.join(root, p))
    ]
    baseline_path = args.baseline or os.path.join(
        root, baseline.DEFAULT_BASENAME
    )

    try:
        findings, suppressed, errors = analyze_paths(root, paths)
        scanned = set(iter_py_files(root, paths))
    except FileNotFoundError as e:
        print(str(e), file=sys.stderr)
        return 2

    entries = []
    if not args.no_baseline:
        try:
            entries = baseline.load(baseline_path)
        except baseline.BaselineError as e:
            print(f"swarmlint: {e}", file=sys.stderr)
            return 1
    new, baselined, stale = baseline.partition(findings, entries)
    # On a scoped run (explicit paths), an entry for an unscanned file
    # is unknown, not stale — only the full default scan can prove
    # staleness.
    stale = [e for e in stale if e.path in scanned]

    if args.write_baseline:
        # A rewrite must not reset hand-written justifications to
        # TODO (the r17 `budget_from_audit(previous=)` discipline):
        # when an edited line re-fingerprints an old finding, its now-
        # stale entry still holds the human's reasoning — carry it
        # over, matching tight (rule, path, context) first, then
        # (rule, path).
        def _carried(f) -> str:
            for match in (
                lambda e: (e.rule, e.path, e.context)
                == (f.rule, f.path, f.context),
                lambda e: (e.rule, e.path) == (f.rule, f.path),
            ):
                for e in stale:
                    if match(e) and not e.justification.startswith(
                        "TODO"
                    ):
                        return e.justification
            return "TODO(swarmlint): justify or fix"

        merged = [e for e in entries if e not in stale] + [
            baseline.from_finding(f, _carried(f)) for f in new
        ]
        baseline.save(baseline_path, merged)
        n_todo = sum(
            1 for e in merged
            if e.justification.startswith("TODO(swarmlint)")
        )
        print(
            f"swarmlint: wrote {len(merged)} entries to "
            f"{baseline_path} ({len(new)} new, {n_todo} TODO "
            "justifications to edit)"
        )
        return 0

    summary = {
        "tool": "swarmlint",
        "counts": {
            "new": len(new),
            "baselined": len(baselined),
            "suppressed": len(suppressed),
            "stale_baseline": len(stale),
            "total": len(new) + len(baselined),
            "parse_errors": len(errors),
            # The racelint slice (new + baselined): the fixed-name
            # `racelint-findings` bench row and graft dryrun axis 35
            # read this without re-partitioning the findings list.
            "racelint": sum(
                1 for f in new + baselined
                if f.rule.startswith("race-")
            ),
        },
        "findings": [
            dict(f.to_dict(), status="new") for f in new
        ] + [
            dict(f.to_dict(), status="baselined") for f in baselined
        ],
        "stale_baseline": [e.to_dict() for e in stale],
        "parse_errors": [
            {"path": p, "error": m} for p, m in errors
        ],
    }

    if args.as_json:
        print(json.dumps(summary, indent=1))
    else:
        for f in new:
            print(f.render())
        for p, m in errors:
            print(f"{p}:0: [parse-error] {m}")
        for e in stale:
            print(
                f"# stale baseline entry: [{e.rule}] {e.path} "
                f"({e.context}) — fixed? remove it from the baseline"
            )
        c = summary["counts"]
        print(
            f"# swarmlint: {c['new']} new, {c['baselined']} "
            f"baselined, {c['suppressed']} suppressed, "
            f"{c['stale_baseline']} stale baseline entr"
            f"{'y' if c['stale_baseline'] == 1 else 'ies'} "
            f"({len(REGISTRY)} rules)"
        )
    # Stale entries fail too (matching tier-1's baseline-is-tight
    # test): the ledger must shrink the moment its debt is paid.
    return 1 if (new or errors or stale) else 0


if __name__ == "__main__":
    raise SystemExit(main())
