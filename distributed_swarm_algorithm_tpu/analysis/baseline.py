"""Baseline file: grandfathered findings, each with a justification.

The baseline is the escape hatch that lets the analyzer gate in
tier-1 from day one without demanding every legacy finding be fixed
in the same commit — but it is a *ledger*, not a dumping ground:
every entry carries a one-line justification, and entries that no
longer match anything are reported as stale so the file shrinks as
debt is paid.

Format (``swarmlint-baseline.json`` at the repo root)::

    {
      "entries": [
        {"rule": "metric-fstring",
         "path": "benchmarks/decompose_gridmean.py",
         "context": "main",
         "snippet": "report(f\"cic-deposit, {tag}\", ...)",
         "justification": "tag is a fixed config label, ..."}
      ]
    }

Matching is by ``Finding.fingerprint()`` — (rule, path, context,
stripped source line) — so baselines survive unrelated edits that
shift line numbers, and die (go stale) when the flagged line itself
changes.
"""

from __future__ import annotations

import json
import os
from dataclasses import dataclass

DEFAULT_BASENAME = "swarmlint-baseline.json"


@dataclass(frozen=True)
class Entry:
    rule: str
    path: str
    context: str
    snippet: str
    justification: str

    def fingerprint(self) -> tuple:
        return (self.rule, self.path, self.context, self.snippet)

    def to_dict(self) -> dict:
        return {
            "rule": self.rule,
            "path": self.path,
            "context": self.context,
            "snippet": self.snippet,
            "justification": self.justification,
        }


class BaselineError(ValueError):
    """Malformed baseline file (bad JSON, missing keys, or an entry
    with no justification)."""


def load(path: str) -> list:
    """Parse and validate a baseline file; [] if it does not exist."""
    if not os.path.exists(path):
        return []
    with open(path, encoding="utf-8") as f:
        try:
            data = json.load(f)
        except json.JSONDecodeError as e:
            raise BaselineError(f"{path}: not valid JSON: {e}") from e
    entries = []
    for i, raw in enumerate(data.get("entries", [])):
        missing = [
            k
            for k in ("rule", "path", "context", "snippet",
                      "justification")
            if k not in raw
        ]
        if missing:
            raise BaselineError(
                f"{path}: entry {i} missing {missing}"
            )
        if not str(raw["justification"]).strip():
            raise BaselineError(
                f"{path}: entry {i} ({raw['rule']} at {raw['path']}) "
                "has an empty justification — baselined findings must "
                "say why they are exempt"
            )
        entries.append(
            Entry(
                rule=raw["rule"],
                path=raw["path"],
                context=raw["context"],
                snippet=raw["snippet"],
                justification=str(raw["justification"]),
            )
        )
    return entries


def save(path: str, entries) -> None:
    with open(path, "w", encoding="utf-8") as f:
        json.dump(
            {"entries": [e.to_dict() for e in entries]},
            f, indent=1, sort_keys=True,
        )
        f.write("\n")


def from_finding(finding, justification: str) -> Entry:
    return Entry(
        rule=finding.rule,
        path=finding.path,
        context=finding.context,
        snippet=finding.snippet,
        justification=justification,
    )


def partition(findings, entries):
    """Split ``findings`` into (new, baselined) and return the stale
    entries.  One entry silences every finding sharing its
    fingerprint (two identical lines in one function are one hazard
    class, one justification)."""
    known = {e.fingerprint(): e for e in entries}
    new, baselined = [], []
    hit: set = set()
    for f in findings:
        fp = f.fingerprint()
        if fp in known:
            baselined.append(f)
            hit.add(fp)
        else:
            new.append(f)
    stale = [e for e in entries if e.fingerprint() not in hit]
    return new, baselined, stale
