"""Rules: PRNG key reuse (`key-reuse`) and vmapped-axis key
broadcast (`key-broadcast`).

The single most common silent-correctness bug in jax code: the same
key consumed by two ``jax.random.*`` calls yields *identical or
correlated* draws — e.g. initializing positions and velocities from
one key makes them bitwise-coupled.  The safe idiom threads keys
explicitly::

    key, sub = jax.random.split(key)
    x = jax.random.normal(sub, shape)

Detection is a branch-aware sequential scan of each function scope
(and the module scope): a bare name consumed by two key-consuming
``jax.random.*`` calls with no intervening re-assignment is a
finding on the second call.  ``fold_in(key, i)`` is treated as
*non*-consuming — deriving independent streams from one key with
distinct fold constants is this repo's documented domain-separation
idiom (pso_fused's ``0x6E0`` host key, etc.).  Only bare-``Name``
key arguments are tracked; ``state.key`` attribute flows are the
checkpoint/pytree discipline's job.

``key-broadcast`` (r13, the scenario-batching twin): a PRNG key
passed through ``jax.vmap``'s ``in_axes=None`` slot is the SAME key
in every batch member — every vmapped scenario draws identical
"random" numbers (correlated election jitter across tenants is
silent and wrong; each tenant must get its own split key, e.g. the
key inside its stacked state pytree, mapped with axis 0).  Detection
is the immediate-call shape ``jax.vmap(f, in_axes=...)(args...)``:
a bare-``Name`` call argument whose name mentions ``key`` aligned
with a ``None`` axis (or a whole-tree ``in_axes=None``) is a
finding.
"""

from __future__ import annotations

import ast

from .core import Finding, ModuleInfo, Rule, register

#: jax.random members whose FIRST argument consumes key entropy.
#: ``fold_in`` derives (domain separation), ``PRNGKey``/``key``/
#: ``wrap_key_data`` construct — none of those consume.
_NON_CONSUMERS = frozenset(
    {"PRNGKey", "key", "wrap_key_data", "key_data", "clone"}
)


def _is_consumer(mod: ModuleInfo, call: ast.Call) -> bool:
    name = mod.resolve(call.func)
    if not name.startswith("jax.random."):
        return False
    member = name.rsplit(".", 1)[1]
    return member not in _NON_CONSUMERS and member != "fold_in"


def _key_arg(call: ast.Call):
    """The bare-Name key operand of a consumer call, if any."""
    if call.args and isinstance(call.args[0], ast.Name):
        return call.args[0]
    for kw in call.keywords:
        if kw.arg == "key" and isinstance(kw.value, ast.Name):
            return kw.value
    return None


def _bound_names(target) -> list:
    """Names (re)bound by an assignment target / loop target."""
    out = []
    for node in ast.walk(target):
        if isinstance(node, ast.Name):
            out.append(node.id)
    return out


@register
class KeyReuseRule(Rule):
    id = "key-reuse"
    summary = "PRNG key consumed by two jax.random calls"
    details = (
        "A key passed to two key-consuming jax.random.* calls without "
        "an intervening re-assignment (split/fold_in producing a new "
        "binding) yields correlated draws.  Thread keys: "
        "`key, sub = jax.random.split(key)`."
    )

    def check(self, mod: ModuleInfo):
        findings: dict = {}
        scopes = [self._module_body(mod.tree)]
        for node in ast.walk(mod.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                scopes.append(node.body)
        for body in scopes:
            self._scan_stmts(mod, body, {}, findings)
        for f in sorted(findings.values(), key=lambda f: f.line):
            yield f

    @staticmethod
    def _module_body(tree: ast.Module) -> list:
        # Module scope minus function bodies (scanned separately).
        return [
            st
            for st in tree.body
            if not isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef,
                                   ast.ClassDef))
        ]

    # -- statement walk ---------------------------------------------------

    def _scan_stmts(self, mod, stmts, counts, findings) -> None:
        for st in stmts:
            self._scan_stmt(mod, st, counts, findings)

    def _scan_stmt(self, mod, st, counts, findings) -> None:
        if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef,
                           ast.ClassDef)):
            return  # separate scope
        if isinstance(st, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
            if st.value is not None:
                self._scan_expr(mod, st.value, counts, findings)
            targets = (
                st.targets if isinstance(st, ast.Assign) else [st.target]
            )
            for t in targets:
                for name in _bound_names(t):
                    counts[name] = 0
            return
        if isinstance(st, ast.If):
            self._scan_expr(mod, st.test, counts, findings)
            self._scan_branches(mod, [st.body, st.orelse], counts,
                                findings)
            return
        if isinstance(st, (ast.For, ast.AsyncFor)):
            self._scan_expr(mod, st.iter, counts, findings)
            for name in _bound_names(st.target):
                counts[name] = 0
            # Two passes expose loop-carried reuse (a key consumed
            # once per iteration without re-binding IS reuse);
            # findings dedupe on site so the second pass adds nothing
            # for straight-line single uses.
            for _ in range(2):
                body_counts = dict(counts)
                self._scan_stmts(mod, st.body, body_counts, findings)
                counts.update(body_counts)
            self._scan_stmts(mod, st.orelse, counts, findings)
            return
        if isinstance(st, ast.While):
            for _ in range(2):
                self._scan_expr(mod, st.test, counts, findings)
                body_counts = dict(counts)
                self._scan_stmts(mod, st.body, body_counts, findings)
                counts.update(body_counts)
            self._scan_stmts(mod, st.orelse, counts, findings)
            return
        if isinstance(st, ast.Try):
            branches = [st.body]
            for h in st.handlers:
                branches.append(h.body)
            branches.append(st.orelse)
            self._scan_branches(mod, branches, counts, findings)
            self._scan_stmts(mod, st.finalbody, counts, findings)
            return
        if isinstance(st, (ast.With, ast.AsyncWith)):
            for item in st.items:
                self._scan_expr(mod, item.context_expr, counts, findings)
                if item.optional_vars is not None:
                    for name in _bound_names(item.optional_vars):
                        counts[name] = 0
            self._scan_stmts(mod, st.body, counts, findings)
            return
        # Return / Expr / Assert / Raise / Delete / ...
        for child in ast.iter_child_nodes(st):
            if isinstance(child, ast.expr):
                self._scan_expr(mod, child, counts, findings)

    @staticmethod
    def _terminates(body) -> bool:
        """True if the branch body never falls through to the code
        after it (ends the scope or the loop iteration)."""
        return any(
            isinstance(st, (ast.Return, ast.Raise, ast.Break,
                            ast.Continue))
            for st in body
        )

    def _scan_branches(self, mod, branch_bodies, counts, findings):
        """Mutually exclusive branches: each starts from the incoming
        state; the merged state is the per-name max over the branches
        that can fall through (a branch ending in return/raise never
        reaches the code after the if, so its consumptions must not
        count against later uses — the early-return key pattern)."""
        merged = dict(counts)
        for body in branch_bodies:
            c = dict(counts)
            self._scan_stmts(mod, body, c, findings)
            if self._terminates(body):
                continue
            for name, n in c.items():
                merged[name] = max(merged.get(name, 0), n)
        counts.clear()
        counts.update(merged)

    # -- expression walk --------------------------------------------------

    def _scan_expr(self, mod, expr, counts, findings) -> None:
        for node in ast.walk(expr):
            if not isinstance(node, ast.Call):
                continue
            if not _is_consumer(mod, node):
                continue
            key = _key_arg(node)
            if key is None:
                continue
            counts[key.id] = counts.get(key.id, 0) + 1
            if counts[key.id] >= 2:
                site = (mod.relpath, node.lineno, node.col_offset)
                if site not in findings:
                    findings[site] = mod.finding(
                        self.id,
                        node,
                        f"PRNG key `{key.id}` consumed again without "
                        "an intervening split/re-assignment — "
                        "correlated draws",
                    )


def _in_axes_value(call: ast.Call):
    """The ``in_axes`` operand of a ``jax.vmap`` call: second
    positional argument or keyword.  Returns (node, True) when
    present, (None, False) when defaulted (axis 0 everywhere — the
    safe default)."""
    if len(call.args) >= 2:
        return call.args[1], True
    for kw in call.keywords:
        if kw.arg == "in_axes":
            return kw.value, True
    return None, False


def _is_none_axis(node) -> bool:
    return isinstance(node, ast.Constant) and node.value is None


def _looks_like_key(node) -> bool:
    return isinstance(node, ast.Name) and "key" in node.id.lower()


@register
class KeyBroadcastRule(Rule):
    id = "key-broadcast"
    summary = "PRNG key broadcast across a vmapped axis (in_axes=None)"
    details = (
        "jax.vmap(f, in_axes=..., ...)(..., key, ...) with the key's "
        "axis None hands EVERY batch member the same key — identical "
        "draws per member (correlated election jitter, identical "
        "init noise).  Split per member instead: map a [S]-leaved "
        "key array with axis 0 (jax.random.split(key, S))."
    )

    def check(self, mod: ModuleInfo):
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            # The immediate-call shape: jax.vmap(fn, ...)(args...).
            vmap = node.func
            if not isinstance(vmap, ast.Call):
                continue
            if mod.resolve(vmap.func) != "jax.vmap":
                continue
            axes, explicit = _in_axes_value(vmap)
            if not explicit:
                continue  # default in_axes=0: every arg mapped
            if _is_none_axis(axes):
                # Whole-tree broadcast: every key-looking arg is the
                # same key in every member.
                for arg in node.args:
                    if _looks_like_key(arg):
                        yield mod.finding(
                            self.id, arg,
                            f"PRNG key `{arg.id}` broadcast across "
                            "the vmapped axis (in_axes=None) — every "
                            "batch member draws the same stream; "
                            "split one key per member and map it "
                            "with axis 0",
                        )
                continue
            if isinstance(axes, (ast.Tuple, ast.List)):
                for axis, arg in zip(axes.elts, node.args):
                    if _is_none_axis(axis) and _looks_like_key(arg):
                        yield mod.finding(
                            self.id, arg,
                            f"PRNG key `{arg.id}` rides a None slot "
                            "of in_axes — the same key reaches every "
                            "member of the vmapped axis; split one "
                            "key per member (axis 0) instead",
                        )
