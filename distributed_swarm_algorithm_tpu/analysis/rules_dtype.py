"""Rule: dtype drift in ops/ hot paths (`dtype-drift`).

Every Pallas kernel and every fused driver in this repo is written
against an explicit float32 (or int32) contract — dtype-less array
constructors in ``ops/`` inherit whatever flows in, and an upstream
float64 (x64 mode) or weak-typed literal silently changes the traced
program: at best a recompile per distinct dtype, at worst a kernel
that rejects the operand on-chip only.  Scope is deliberately the hot
paths (``**/ops/**``): model/benchmark code may stage host-side in
float64 on purpose (e.g. grid_moments' QxQ block-algebra constants).

Flagged:
- ``jnp.zeros/ones/empty/full/array/asarray`` with no dtype (neither
  the positional dtype slot nor ``dtype=``);
- any explicit float64 dtype in a ``jnp.*`` call (``jnp.float64``,
  ``np.float64``, ``"float64"``).

``jnp.arange`` is exempt: dtype-less ``arange(n)`` is the universal
index-vector idiom and lands on int32 under the repo's x64-off config.
"""

from __future__ import annotations

import ast

from .core import ModuleInfo, Rule, register

#: function -> index of its positional dtype slot
_CREATORS = {
    "jax.numpy.zeros": 1,
    "jax.numpy.ones": 1,
    "jax.numpy.empty": 1,
    "jax.numpy.full": 2,
    "jax.numpy.array": 1,
    "jax.numpy.asarray": 1,
}

_F64 = frozenset({"jax.numpy.float64", "numpy.float64"})


def _has_dtype(call: ast.Call, pos_index: int) -> bool:
    if len(call.args) > pos_index:
        return True
    return any(kw.arg == "dtype" for kw in call.keywords)


def _is_f64(mod: ModuleInfo, node: ast.expr) -> bool:
    if isinstance(node, ast.Constant) and node.value == "float64":
        return True
    return mod.resolve(node) in _F64


@register
class DtypeDriftRule(Rule):
    id = "dtype-drift"
    summary = "dtype-less or float64 array constructor in ops/"
    details = (
        "Hot-path (ops/, ops/pallas/) jnp constructors must pin their "
        "dtype: dtype-less jnp.zeros/ones/full/array/asarray inherit "
        "upstream drift and retrace per dtype; explicit float64 "
        "either downcasts silently (x64 off) or breaks the f32 kernel "
        "contract (x64 on)."
    )

    def applies(self, mod: ModuleInfo) -> bool:
        return "/ops/" in f"/{mod.relpath}"

    def check(self, mod: ModuleInfo):
        if not self.applies(mod):
            return
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            name = mod.resolve(node.func)
            if name in _CREATORS:
                if not _has_dtype(node, _CREATORS[name]):
                    short = name.replace("jax.numpy", "jnp")
                    yield mod.finding(
                        self.id, node,
                        f"`{short}` without an explicit dtype in an "
                        "ops/ hot path — pin it (f32/i32 kernel "
                        "contract)",
                    )
            if name.startswith("jax.numpy."):
                f64_args = [
                    a
                    for a in list(node.args)
                    + [k.value for k in node.keywords]
                    if _is_f64(mod, a)
                ]
                for a in f64_args:
                    yield mod.finding(
                        self.id, a,
                        "float64 dtype in a jnp call in an ops/ hot "
                        "path — the kernel contract is float32",
                    )
