"""Rules over traced (jit/scan/vmap/pallas) function bodies.

- ``host-sync``: host round-trips inside traced code (``.item()``,
  ``np.asarray``, builtin casts of computed values) force a device
  sync per call — the silent throughput killer on TPU.
- ``tracer-branch``: Python ``if``/``while`` on a traced argument
  raises ``TracerBoolConversionError`` at trace time on-chip but can
  pass CPU tests that never hit the jitted path; use ``lax.cond`` /
  ``jnp.where``.
- ``retrace``: ``jax.jit`` constructed where it re-runs per call
  (inside loops, or constructed-and-immediately-called) recompiles
  every time; unhashable static-arg defaults fail at first call.
- ``plan-staleness``: ``build_hashgrid_plan`` called inside a
  ``lax.scan``/``fori_loop``/``while_loop`` body that never routes
  through ``refresh_plan`` pays the full bin+sort every iteration —
  the r8 structural floor the r9 Verlet carry exists to amortize;
  rollout bodies must carry a plan and ``refresh_plan`` it.
- ``telemetry-gate``: flight-recorder collection
  (``*tick_telemetry``) inside a scan body without the static
  ``TelemetryConfig`` gate bloats EVERY rollout's graph with
  collection ops and stacked ys, whether or not anyone reads them —
  the r10 contract is that the disabled trace compiles to the
  identical telemetry-free HLO, which only a trace-time Python ``if``
  on the static gate can guarantee.
- ``scope-fstring``: a dynamic (f-string / ``.format`` /
  concatenated) name passed to ``jax.named_scope`` — each distinct
  name string is a fresh trace annotation, so a run-varying scope
  name is a retrace hazard (and shreds XProf trace aggregation, which
  groups by exact scope string) exactly like a run-varying metric
  name shreds the bench union gate.
- ``halo-width``: a ``shard_map`` body that builds or consumes a
  per-shard ``HashgridPlan`` with NO halo exchange reachable in its
  scope silently drops every pair that straddles a shard boundary —
  the plan's 3x3 stencil only covers agents the shard actually
  holds, so without boundary agents shipped in (``lax.ppermute``
  payloads sized for ``personal_space + skin``,
  parallel/spatial.py) the "exact" sharded tick is quietly wrong at
  every tile seam.
- ``done-branch``: a host ``if``/``while`` on a traced done/
  terminated flag inside an env-rollout scan body — the classic
  auto-reset hazard (ConcretizationError on-chip, or a per-boolean
  retrace); the sanctioned pattern is the ``jnp.where``-select
  auto-reset (envs/core.py).
- ``cond-collective``: a collective (``ppermute``/``psum``/``pmax``)
  reachable inside a ``lax.cond`` branch under shard_map without a
  mesh-uniform predicate nearby — collectives rendezvous across the
  mesh, so devices disagreeing on the branch DEADLOCK (the r12
  rebuild hazard); the sanctioned pattern OR-reduces the trigger
  first (``lax.pmax(flag, axis) > 0``, parallel/spatial.py).
- ``span-leak``: a tracer span begun with the explicit
  ``begin_span``/``end_span`` pair inside ``serve/`` or a
  loop-transform body, or ``jax.profiler.start_trace`` with no
  reachable ``stop_trace`` — any exception or early return between
  begin and end leaks an open span across pump cycles (and an
  unclosed profiler capture corrupts the trace file); use the
  ``with tracer.span(...)`` form or :meth:`SpanTracer.emit`
  (utils/trace.py).
"""

from __future__ import annotations

import ast

from .core import ModuleInfo, Rule, register

# ---------------------------------------------------------------------------
# host-sync

_NP_SYNC = frozenset(
    {
        "numpy.asarray",
        "numpy.array",
        "numpy.ascontiguousarray",
        "numpy.asfortranarray",
    }
)
_CASTS = frozenset({"float", "int", "bool"})


@register
class HostSyncRule(Rule):
    id = "host-sync"
    summary = "host round-trip inside traced code"
    details = (
        "`.item()`, `np.asarray`/`np.array`, and `float()`/`int()`/"
        "`bool()` of a computed value inside a jit/scan/vmap body "
        "block on the device (or fail to trace).  Keep values on "
        "device; cast outside the traced region."
    )

    def check(self, mod: ModuleInfo):
        traced = mod.traced_functions()
        seen: set = set()
        for fn in traced:
            body = fn.body if isinstance(fn.body, list) else [fn.body]
            for st in body:
                for node in ast.walk(st):
                    # Nested traced defs walk their own bodies; dedupe
                    # the overlap by site.
                    site = (
                        getattr(node, "lineno", 0),
                        getattr(node, "col_offset", 0),
                    )
                    if site in seen:
                        continue
                    f = self._check_call(mod, node)
                    if f is not None:
                        seen.add(site)
                        yield f

    def _check_call(self, mod, node):
        if not isinstance(node, ast.Call):
            return None
        if (
            isinstance(node.func, ast.Attribute)
            and node.func.attr == "item"
            and not node.args
        ):
            return mod.finding(
                self.id, node,
                "`.item()` inside traced code forces a device sync",
            )
        name = mod.resolve(node.func)
        if name in _NP_SYNC:
            return mod.finding(
                self.id, node,
                f"`{name.replace('numpy', 'np')}` inside traced code "
                "pulls the value to host",
            )
        if name in _CASTS and node.args:
            # Only computed values: a Call argument is (almost) always
            # a traced intermediate; bare names / attributes are
            # usually static config and stay un-flagged.
            if isinstance(node.args[0], ast.Call):
                return mod.finding(
                    self.id, node,
                    f"`{name}()` of a computed value inside traced "
                    "code concretizes a tracer",
                )
        return None


# ---------------------------------------------------------------------------
# tracer-branch


def _static_param_names(mod: ModuleInfo, fn) -> set:
    """Parameter names marked static via static_argnames/static_argnums
    in a jit decorator (direct or functools.partial)."""
    static: set = set()
    if isinstance(fn, ast.Lambda):
        return static
    params = [a.arg for a in (
        fn.args.posonlyargs + fn.args.args + fn.args.kwonlyargs
    )]
    for dec in fn.decorator_list:
        if not isinstance(dec, ast.Call):
            continue
        for kw in dec.keywords:
            if kw.arg == "static_argnames":
                for node in ast.walk(kw.value):
                    if isinstance(node, ast.Constant) and isinstance(
                        node.value, str
                    ):
                        static.add(node.value)
            elif kw.arg == "static_argnums":
                for node in ast.walk(kw.value):
                    if isinstance(node, ast.Constant) and isinstance(
                        node.value, int
                    ):
                        if 0 <= node.value < len(params):
                            static.add(params[node.value])
    return static


def _hazard_names(test: ast.expr) -> set:
    """Bare Names in a test expression that would concretize a tracer:
    excludes `x is (not) None` operands, attribute bases (`x.shape`),
    and call callees (`f(...)`)."""
    exempt: set = set()
    for node in ast.walk(test):
        if isinstance(node, ast.Compare) and all(
            isinstance(op, (ast.Is, ast.IsNot)) for op in node.ops
        ):
            for operand in [node.left] + node.comparators:
                for n in ast.walk(operand):
                    if isinstance(n, ast.Name):
                        exempt.add(n.id)
        # `any(x is None for x in (a, b))` — a presence check over
        # operands, not a value branch: exempt the whole comprehension
        # when its element is purely an is/is-not comparison.
        if isinstance(node, (ast.GeneratorExp, ast.ListComp,
                             ast.SetComp)) and isinstance(
            node.elt, ast.Compare
        ) and all(
            isinstance(op, (ast.Is, ast.IsNot)) for op in node.elt.ops
        ):
            for n in ast.walk(node):
                if isinstance(n, ast.Name):
                    exempt.add(n.id)
        if isinstance(node, ast.Attribute) and isinstance(
            node.value, ast.Name
        ):
            exempt.add(node.value.id)
        if isinstance(node, ast.Call):
            for n in ast.walk(node.func):
                if isinstance(n, ast.Name):
                    exempt.add(n.id)
            if isinstance(node.func, ast.Name) and node.func.id in (
                "isinstance", "len", "hasattr", "callable",
            ):
                for arg in node.args:
                    for n in ast.walk(arg):
                        if isinstance(n, ast.Name):
                            exempt.add(n.id)
    return {
        node.id
        for node in ast.walk(test)
        if isinstance(node, ast.Name) and node.id not in exempt
    }


@register
class TracerBranchRule(Rule):
    id = "tracer-branch"
    summary = "Python if/while on a traced argument"
    details = (
        "Branching on a non-static parameter inside a traced function "
        "raises TracerBoolConversionError at trace time; use "
        "jax.lax.cond / jnp.where, or mark the argument static."
    )

    def check(self, mod: ModuleInfo):
        for fn in mod.traced_functions():
            if isinstance(fn, ast.Lambda):
                continue  # lambdas cannot contain statements
            params = {
                a.arg
                for a in (
                    fn.args.posonlyargs + fn.args.args
                    + fn.args.kwonlyargs
                )
            }
            params -= _static_param_names(mod, fn)
            params.discard("self")
            for st in fn.body:
                for node in ast.walk(st):
                    if not isinstance(node, (ast.If, ast.While)):
                        continue
                    # Don't cross into nested defs — they are traced
                    # functions in their own right and get their own
                    # parameter set.
                    if any(
                        isinstance(a, (ast.FunctionDef,
                                       ast.AsyncFunctionDef, ast.Lambda))
                        and a is not fn
                        for a in mod.ancestors(node)
                    ):
                        continue
                    hot = _hazard_names(node.test) & params
                    if hot:
                        kind = (
                            "if" if isinstance(node, ast.If) else "while"
                        )
                        yield mod.finding(
                            self.id, node,
                            f"Python `{kind}` on traced argument(s) "
                            f"{sorted(hot)} — use lax.cond/jnp.where "
                            "or mark static",
                        )


# ---------------------------------------------------------------------------
# plan-staleness

#: Loop-carrying transforms whose bodies re-execute per iteration —
#: the scopes where an un-refreshed spatial-index build is a per-tick
#: cost.  lax.cond is deliberately absent: refresh_plan's own rebuild
#: branch lives under cond, and a conditional build is the amortized
#: pattern, not the hazard.
_LOOP_CALLS = frozenset(
    {
        "jax.lax.scan",
        "jax.lax.fori_loop",
        "jax.lax.while_loop",
        "jax.lax.map",
    }
)


@register
class PlanStalenessRule(Rule):
    id = "plan-staleness"
    summary = "HashgridPlan built per-iteration inside a scan body"
    details = (
        "`build_hashgrid_plan` inside a lax.scan/fori_loop/while_loop "
        "body pays the full bin+sort every iteration — the r8 "
        "structural floor.  Rollout bodies should carry the plan and "
        "route it through `refresh_plan` or `refresh_plan_partial` "
        "(ops/hashgrid_plan.py), which rebuild under lax.cond/switch "
        "only when the Verlet skin guarantee has expired."
    )

    def check(self, mod: ModuleInfo):
        by_name: dict = {}
        for node in ast.walk(mod.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                by_name.setdefault(node.name, []).append(node)
        bodies: set = set()
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            if mod.resolve(node.func) not in _LOOP_CALLS:
                continue
            for arg in list(node.args) + [k.value for k in node.keywords]:
                if isinstance(arg, ast.Lambda):
                    bodies.add(arg)
                elif isinstance(arg, ast.Name):
                    bodies.update(by_name.get(arg.id, []))
        seen: set = set()
        for fn in bodies:
            stmts = fn.body if isinstance(fn.body, list) else [fn.body]
            builds: list = []
            has_refresh = False
            for st in stmts:
                for node in ast.walk(st):
                    if not isinstance(node, ast.Call):
                        continue
                    name = mod.resolve(node.func)
                    leaf = name.rsplit(".", 1)[-1] if name else ""
                    if leaf == "build_hashgrid_plan":
                        builds.append(node)
                    elif leaf in (
                        "refresh_plan", "refresh_plan_partial"
                    ):
                        has_refresh = True
            if has_refresh:
                continue
            for b in builds:
                site = (b.lineno, b.col_offset)
                if site in seen:
                    continue
                seen.add(site)
                yield mod.finding(
                    self.id, b,
                    "`build_hashgrid_plan` inside a loop-transform "
                    "body rebuilds the spatial index every iteration "
                    "— carry the plan and use `refresh_plan` / "
                    "`refresh_plan_partial` (Verlet skin reuse)",
                )


# ---------------------------------------------------------------------------
# telemetry-gate

#: Flight-recorder collector leaf names (utils/telemetry.py): the
#: generic entry point plus its per-model conveniences.  Any
#: ``*_tick_telemetry`` leaf matches too (r11 added island/optimizer/
#: driver-private collectors; new ones must not dodge the gate rule
#: by name).
_TELEMETRY_COLLECTORS = frozenset(
    {"tick_telemetry", "swarm_tick_telemetry", "boids_tick_telemetry"}
)


def _is_telemetry_collector(leaf: str) -> bool:
    return leaf in _TELEMETRY_COLLECTORS or leaf.endswith(
        "_tick_telemetry"
    )


def _gated_by_telemetry_flag(mod: ModuleInfo, node, fn) -> bool:
    """True when ``node`` sits under a Python ``if`` (within ``fn``)
    whose test mentions the telemetry gate — a Name or Attribute
    component literally named ``telemetry`` (``if telemetry:``,
    ``if cfg.telemetry.enabled:``, ...).  A trace-time static branch
    is the ONLY gate shape that keeps the disabled HLO identical,
    which is why the rule looks for exactly this."""
    for anc in mod.ancestors(node):
        if anc is fn or isinstance(
            anc, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
        ):
            # Stop at the body function boundary: a gate OUTSIDE the
            # scan body runs once at trace setup and cannot gate the
            # per-iteration collection.
            return False
        if not isinstance(anc, ast.If):
            continue
        for sub in ast.walk(anc.test):
            if isinstance(sub, ast.Name) and sub.id == "telemetry":
                return True
            if isinstance(sub, ast.Attribute) and sub.attr == "telemetry":
                return True
    return False


@register
class TelemetryGateRule(Rule):
    id = "telemetry-gate"
    summary = "ungated telemetry collection inside a scan body"
    details = (
        "`tick_telemetry` (or a `*_tick_telemetry` convenience) "
        "called inside a lax.scan/fori_loop/while_loop body without a "
        "static TelemetryConfig gate adds collection ops and stacked "
        "ys to EVERY rollout, enabled or not.  Guard the call with a "
        "trace-time Python `if` on the static gate (`if telemetry:` "
        "/ `if cfg.telemetry.enabled:`) so the disabled trace "
        "compiles to the identical telemetry-free HLO "
        "(utils/telemetry.py, docs/OBSERVABILITY.md)."
    )

    def check(self, mod: ModuleInfo):
        by_name: dict = {}
        for node in ast.walk(mod.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                by_name.setdefault(node.name, []).append(node)
        bodies: set = set()
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            if mod.resolve(node.func) not in _LOOP_CALLS:
                continue
            for arg in list(node.args) + [k.value for k in node.keywords]:
                if isinstance(arg, ast.Lambda):
                    bodies.add(arg)
                elif isinstance(arg, ast.Name):
                    bodies.update(by_name.get(arg.id, []))
        seen: set = set()
        for fn in bodies:
            stmts = fn.body if isinstance(fn.body, list) else [fn.body]
            for st in stmts:
                for node in ast.walk(st):
                    if not isinstance(node, ast.Call):
                        continue
                    name = mod.resolve(node.func)
                    leaf = name.rsplit(".", 1)[-1] if name else ""
                    if not _is_telemetry_collector(leaf):
                        continue
                    if _gated_by_telemetry_flag(mod, node, fn):
                        continue
                    site = (node.lineno, node.col_offset)
                    if site in seen:
                        continue
                    seen.add(site)
                    yield mod.finding(
                        self.id, node,
                        f"`{leaf}` inside a loop-transform body "
                        "without the static TelemetryConfig gate — "
                        "wrap it in `if telemetry:` / `if "
                        "cfg.telemetry.enabled:` so the disabled "
                        "rollout keeps its telemetry-free HLO",
                    )


# ---------------------------------------------------------------------------
# done-branch

#: Names that read as episode-termination flags.  Exact matches plus
#: the common suffix forms (``ep_done``, ``all_dones``); chosen
#: narrow — a generic "flag word" list would flag host drivers.
_DONE_EXACT = frozenset(
    {"done", "dones", "terminated", "terminateds", "truncated",
     "truncateds", "terminal", "terminals"}
)
_DONE_SUFFIXES = (
    "_done", "_dones", "_terminated", "_truncated", "_terminal",
)


def _is_done_name(name: str) -> bool:
    low = name.lower()
    return low in _DONE_EXACT or low.endswith(_DONE_SUFFIXES)


@register
class DoneBranchRule(Rule):
    id = "done-branch"
    summary = "host if/while on a traced done flag inside a rollout body"
    details = (
        "A Python `if`/`while` on a done/terminated flag inside a "
        "lax.scan/fori_loop/while_loop body is the classic auto-reset "
        "hazard: the flag is a tracer there, so the branch either "
        "raises ConcretizationError at trace time or — when the body "
        "is traced per call — silently retraces per boolean value.  "
        "Auto-reset must be the `jnp.where`-select pattern "
        "(envs/core.py: compute the reset state unconditionally and "
        "select it in), which keeps the whole rollout ONE compiled "
        "program (docs/ENVIRONMENTS.md)."
    )

    def check(self, mod: ModuleInfo):
        by_name: dict = {}
        for node in ast.walk(mod.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                by_name.setdefault(node.name, []).append(node)
        bodies: set = set()
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            if mod.resolve(node.func) not in _LOOP_CALLS:
                continue
            for arg in list(node.args) + [k.value for k in node.keywords]:
                if isinstance(arg, ast.Lambda):
                    bodies.add(arg)
                elif isinstance(arg, ast.Name):
                    bodies.update(by_name.get(arg.id, []))
        seen: set = set()
        for fn in bodies:
            stmts = fn.body if isinstance(fn.body, list) else [fn.body]
            for st in stmts:
                for node in ast.walk(st):
                    if not isinstance(node, (ast.If, ast.While)):
                        continue
                    # The branch must belong to the loop body ITSELF:
                    # the nearest enclosing function of the If/While
                    # (ancestors yield nearest-first) must be `fn` —
                    # nested defs are their own scope.
                    nested = False
                    for a in mod.ancestors(node):
                        if a is fn:
                            break
                        if isinstance(
                            a, (ast.FunctionDef, ast.AsyncFunctionDef,
                                ast.Lambda)
                        ):
                            nested = True
                            break
                    if nested:
                        continue
                    hot = {
                        n for n in _hazard_names(node.test)
                        if _is_done_name(n)
                    }
                    if not hot:
                        continue
                    site = (node.lineno, node.col_offset)
                    if site in seen:
                        continue
                    seen.add(site)
                    kind = "if" if isinstance(node, ast.If) else "while"
                    yield mod.finding(
                        self.id, node,
                        f"Python `{kind}` on traced done flag(s) "
                        f"{sorted(hot)} inside a loop-transform body "
                        "— auto-reset must be a `jnp.where` select "
                        "(compute the reset branch unconditionally, "
                        "select on the traced flag)",
                    )


# ---------------------------------------------------------------------------
# scope-fstring


@register
class ScopeStringRule(Rule):
    id = "scope-fstring"
    summary = "dynamic name passed to jax.named_scope"
    details = (
        "`jax.named_scope` names become trace annotations keyed by "
        "exact string: an f-string / `.format` / concatenated name "
        "mints a fresh annotation per distinct value — a retrace "
        "hazard inside jitted code (the traced program embeds the "
        "name) and an aggregation-shredder in XProf (the scope map in "
        "docs/OBSERVABILITY.md relies on stable names).  Use a "
        "string literal (or a module-level constant)."
    )

    def check(self, mod: ModuleInfo):
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            name = mod.resolve(node.func)
            leaf = name.rsplit(".", 1)[-1] if name else ""
            if leaf != "named_scope":
                continue
            if not node.args:
                continue
            arg = node.args[0]
            if isinstance(arg, ast.JoinedStr):
                kind = "f-string"
            elif isinstance(arg, ast.Call) and isinstance(
                arg.func, ast.Attribute
            ) and arg.func.attr == "format":
                kind = "str.format"
            elif isinstance(arg, ast.BinOp) and isinstance(
                arg.op, (ast.Add, ast.Mod)
            ):
                kind = "concatenated/interpolated string"
            else:
                # Literals and bare names (module constants) are
                # stable; only syntactically-dynamic names flag.
                continue
            yield mod.finding(
                self.id, node,
                f"`named_scope` name is a {kind} — each distinct "
                "value is a fresh trace annotation (retrace hazard); "
                "use a literal",
            )


# ---------------------------------------------------------------------------
# halo-width

#: Plan producers/consumers whose presence in a shard_map body means
#: the body runs a PER-SHARD spatial index.
_PLAN_CALLS = frozenset(
    {
        "build_hashgrid_plan",
        "refresh_plan",
        "refresh_plan_partial",
        "separation_grid_plan",
    }
)

#: Call leaves that count as a halo exchange being in scope: the ring
#: collectives themselves, or a helper named for the job.
_EXCHANGE_LEAVES = frozenset({"ppermute", "pshuffle"})


def _project_of(mod: ModuleInfo):
    """The module's cross-module view; a single-module project when the
    module is analyzed standalone (callgraph re-hosting, r21)."""
    if mod.project is None:
        from . import callgraph

        callgraph.Project([mod])
    return mod.project


def _body_stmts(node):
    return node.body if isinstance(node.body, list) else [node.body]


def _shard_map_bodies(mod: ModuleInfo):
    """FunctionDef/Lambda nodes that run as shard_map bodies: direct
    ``shard_map(f, ...)`` calls, and defs decorated with
    ``@partial(shard_map, ...)`` (the repo idiom)."""
    by_name: dict = {}
    for node in ast.walk(mod.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            by_name.setdefault(node.name, []).append(node)
    bodies: set = set()

    def is_shard_map(expr) -> bool:
        name = mod.resolve(expr)
        return bool(name) and name.rsplit(".", 1)[-1] == "shard_map"

    for node in ast.walk(mod.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for dec in node.decorator_list:
                if (
                    isinstance(dec, ast.Call)
                    and mod.resolve(dec.func) in (
                        "functools.partial", "partial"
                    )
                    and dec.args
                    and is_shard_map(dec.args[0])
                ):
                    bodies.add(node)
        if isinstance(node, ast.Call) and is_shard_map(node.func):
            for arg in node.args[:1]:
                if isinstance(arg, ast.Lambda):
                    bodies.add(arg)
                elif isinstance(arg, ast.Name):
                    bodies.update(by_name.get(arg.id, []))
    return bodies, by_name


@register
class HaloWidthRule(Rule):
    id = "halo-width"
    summary = "per-shard HashgridPlan without a halo exchange in scope"
    details = (
        "A shard_map body building or sweeping a HashgridPlan sees "
        "only its own shard's agents: without a halo exchange "
        "(lax.ppermute of boundary agents, band depth "
        "personal_space + skin — see parallel/spatial.py) every "
        "pair straddling a shard boundary is silently dropped, so "
        "the sharded tick is quietly wrong at every tile seam.  Ship "
        "boundary agents before consuming the plan, or run the plan "
        "on the full (unsharded) swarm."
    )

    def check(self, mod: ModuleInfo):
        project = _project_of(mod)
        bodies, _ = _shard_map_bodies(mod)
        for fn in bodies:
            # Reachable call closure (project-wide since r21): the
            # exchange (and the plan call) routinely live in helpers
            # the body calls — including helpers in other modules.
            reach = project.closure([project.func_ref(mod, fn)])
            plan_calls: list = []
            has_exchange = False
            for fr in reach.values():
                for st in _body_stmts(fr.node):
                    for node in ast.walk(st):
                        if not isinstance(node, ast.Call):
                            continue
                        name = fr.mod.resolve(node.func) or ""
                        leaf = name.rsplit(".", 1)[-1]
                        if leaf in _PLAN_CALLS:
                            plan_calls.append((fr, node))
                        if leaf in _EXCHANGE_LEAVES or (
                            "collective_permute" in name
                        ):
                            has_exchange = True
            if has_exchange:
                continue
            seen_sites: set = set()
            remote: list = []
            for fr, call in plan_calls:
                site = (fr.mod.relpath, call.lineno, call.col_offset)
                if site in seen_sites:
                    continue
                seen_sites.add(site)
                name = fr.mod.resolve(call.func) or ""
                leaf = name.rsplit(".", 1)[-1]
                if fr.mod is not mod:
                    # Cross-module reach (r21): anchor at the
                    # shard_map BODY, where the sharding decision (and
                    # the fix — exchange or axis choice) lives; the
                    # shared ops/ helper is correct for its other,
                    # exchanged or unsharded, callers.
                    remote.append(
                        f"{leaf} ({fr.mod.relpath}:{call.lineno})"
                    )
                    continue
                yield mod.finding(
                    self.id, call,
                    f"`{leaf}` in a shard_map body with no halo "
                    "exchange in scope — cross-shard neighbor pairs "
                    "are silently dropped; ppermute boundary agents "
                    "(band depth personal_space + skin) before "
                    "consuming a per-shard plan",
                )
            if remote:
                yield mod.finding(
                    self.id, fn,
                    "shard_map body reaches per-shard plan "
                    f"build(s) [{', '.join(sorted(remote))}] with no "
                    "halo exchange in scope — cross-shard neighbor "
                    "pairs are silently dropped; ppermute boundary "
                    "agents before consuming the plan, or shard a "
                    "batch axis the plan never straddles",
                )


# ---------------------------------------------------------------------------
# cond-collective

#: Collective leaves whose presence inside a cond branch means the
#: branch RENDEZVOUSES: every device must take the same branch or the
#: program deadlocks.
_COND_COLLECTIVES = frozenset(
    {"ppermute", "pshuffle", "psum", "pmax", "pmin", "pmean",
     "all_gather", "psum_scatter", "all_to_all"}
)

#: Reduction leaves that make a predicate mesh-uniform: every device
#: computes the same value because the value IS a mesh reduction.
_MESH_REDUCE = frozenset(
    {"psum", "pmax", "pmin", "pmean", "all_gather", "psum_scatter"}
)


def _collect_collectives(project, root_ref):
    """Collective call leaves reachable from ``root_ref`` through the
    project call closure (cross-module since r21)."""
    found: list = []
    for fr in project.closure([root_ref]).values():
        for st in _body_stmts(fr.node):
            for node in ast.walk(st):
                if not isinstance(node, ast.Call):
                    continue
                leaf = (
                    fr.mod.resolve(node.func) or ""
                ).rsplit(".", 1)[-1]
                if leaf in _COND_COLLECTIVES:
                    found.append(leaf)
    return found


def _expr_has_mesh_reduce(mod, expr) -> bool:
    for node in ast.walk(expr):
        if isinstance(node, ast.Call):
            leaf = (mod.resolve(node.func) or "").rsplit(".", 1)[-1]
            if leaf in _MESH_REDUCE:
                return True
    return False


def _predicate_is_uniform(mod, cond_call) -> bool:
    """True when the cond's predicate is visibly mesh-uniform: the
    predicate expression contains a mesh reduction, or a Name in it
    was LAST assigned (lexically, before the cond) from one in the
    cond's enclosing function — the ``stale_any = lax.pmax(...) > 0``
    idiom (parallel/spatial.py).  Only the latest assignment counts:
    an earlier pmax re-assigned to a per-shard value before the cond
    is exactly the deadlock this rule exists to flag."""
    pred = cond_call.args[0] if cond_call.args else None
    if pred is None:
        return False
    if _expr_has_mesh_reduce(mod, pred):
        return True
    names = {
        n.id for n in ast.walk(pred) if isinstance(n, ast.Name)
    }
    if not names:
        return False
    enclosing = None
    for anc in mod.ancestors(cond_call):
        if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef)):
            enclosing = anc
            break
    if enclosing is None:
        return False
    # name -> (lineno of latest assignment before the cond, uniform?)
    latest: dict = {}
    for node in ast.walk(enclosing):
        targets: list = []
        if isinstance(node, ast.Assign):
            targets, value = node.targets, node.value
        elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
            targets, value = [node.target], node.value
        else:
            continue
        if value is None or node.lineno >= cond_call.lineno:
            continue
        uniform = _expr_has_mesh_reduce(mod, value)
        for tgt in targets:
            for n in ast.walk(tgt):
                if isinstance(n, ast.Name) and n.id in names:
                    prev = latest.get(n.id)
                    if prev is None or node.lineno >= prev[0]:
                        latest[n.id] = (node.lineno, uniform)
    return any(uniform for _, uniform in latest.values())


@register
class CondCollectiveRule(Rule):
    id = "cond-collective"
    summary = "collective inside a lax.cond branch without a uniform predicate"
    details = (
        "Inside shard_map, every device must agree on which lax.cond "
        "branch runs when the branch holds a collective (ppermute/"
        "psum/pmax rendezvous across the mesh): a per-shard predicate "
        "sends devices down different branches and the collective "
        "DEADLOCKS — the r12 rebuild hazard.  OR/AND-reduce the "
        "trigger across the mesh first (`lax.pmax(flag, axis) > 0`, "
        "parallel/spatial.py) so the predicate is mesh-uniform by "
        "construction."
    )

    def check(self, mod: ModuleInfo):
        project = _project_of(mod)
        bodies, _ = _shard_map_bodies(mod)
        seen_sites: set = set()
        for body in bodies:
            # Every function reachable from the shard_map body runs
            # per shard — a cond anywhere in that closure (cross-module
            # since r21) is a per-shard branch decision.
            reach = project.closure([project.func_ref(mod, body)])
            for fr in reach.values():
                for st in _body_stmts(fr.node):
                    for node in ast.walk(st):
                        if not isinstance(node, ast.Call):
                            continue
                        name = fr.mod.resolve(node.func) or ""
                        if name.rsplit(".", 1)[-1] != "cond":
                            continue
                        branch_fns: list = []
                        for arg in node.args[1:3]:
                            if isinstance(arg, ast.Lambda):
                                branch_fns.append(
                                    project.func_ref(fr.mod, arg)
                                )
                            elif isinstance(
                                arg, (ast.Name, ast.Attribute)
                            ):
                                branch_fns.extend(
                                    project.resolve_callable(
                                        fr.mod, arg, cls=fr.cls
                                    )
                                )
                        hot: list = []
                        for bf in branch_fns:
                            hot.extend(
                                _collect_collectives(project, bf)
                            )
                        if not hot:
                            continue
                        if _predicate_is_uniform(fr.mod, node):
                            continue
                        site = (
                            fr.mod.relpath, node.lineno,
                            node.col_offset,
                        )
                        if site in seen_sites:
                            continue
                        seen_sites.add(site)
                        yield fr.mod.finding(
                            self.id, node,
                            f"lax.cond branch holds collective(s) "
                            f"{sorted(set(hot))} under shard_map but "
                            "the predicate is not visibly "
                            "mesh-uniform — reduce the trigger "
                            "across the mesh first (`lax.pmax(flag, "
                            "axis) > 0`) or the rendezvous deadlocks",
                        )


# ---------------------------------------------------------------------------
# retrace


@register
class RetraceRule(Rule):
    id = "retrace"
    summary = "jax.jit constructed where it recompiles per call"
    details = (
        "`jax.jit(f)` inside a loop, or `jax.jit(f)(x)` constructed "
        "and called in one expression, builds a fresh cache entry "
        "every execution — hoist the jitted callable to module scope "
        "or cache it.  Mutable defaults on static args fail hashing "
        "at the first call."
    )

    def check(self, mod: ModuleInfo):
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.Call):
                yield from self._check_call(mod, node)
            elif isinstance(node, (ast.FunctionDef,
                                   ast.AsyncFunctionDef)):
                yield from self._check_static_defaults(mod, node)

    def _check_call(self, mod, node):
        name = mod.resolve(node.func)
        if name == "jax.jit":
            in_loop = False
            for anc in mod.ancestors(node):
                if isinstance(anc, (ast.FunctionDef,
                                    ast.AsyncFunctionDef, ast.Lambda)):
                    # A def inside a loop still re-jits per iteration
                    # when the loop re-executes it, so keep climbing
                    # only if the def itself is not decorator scope.
                    break
                if isinstance(anc, (ast.For, ast.AsyncFor, ast.While)):
                    in_loop = True
                    break
            if in_loop:
                yield mod.finding(
                    self.id, node,
                    "`jax.jit` constructed inside a loop — each "
                    "iteration builds (and retraces) a new callable",
                )
        # jax.jit(f, ...)(x): the jitted wrapper is rebuilt per call.
        if isinstance(node.func, ast.Call):
            if mod.resolve(node.func.func) == "jax.jit":
                yield mod.finding(
                    self.id, node,
                    "`jax.jit(f)(...)` constructed and called in one "
                    "expression retraces on every execution — bind "
                    "the jitted callable once",
                )

    def _check_static_defaults(self, mod, fn):
        static = _static_param_names(mod, fn)
        if not static:
            return
        args = fn.args
        pos = args.posonlyargs + args.args
        defaults = args.defaults
        for param, default in zip(pos[len(pos) - len(defaults):],
                                  defaults):
            if param.arg in static and isinstance(
                default, (ast.List, ast.Dict, ast.Set)
            ):
                yield mod.finding(
                    self.id, default,
                    f"static arg `{param.arg}` has an unhashable "
                    "mutable default — jit static args must hash",
                )
        for param, default in zip(args.kwonlyargs, args.kw_defaults):
            if default is not None and param.arg in static and isinstance(
                default, (ast.List, ast.Dict, ast.Set)
            ):
                yield mod.finding(
                    self.id, default,
                    f"static arg `{param.arg}` has an unhashable "
                    "mutable default — jit static args must hash",
                )


# ---------------------------------------------------------------------------
# span-leak (r17)


def _call_leaf(mod: ModuleInfo, node: ast.Call) -> str:
    """Terminal name of a call target: resolved dotted leaf when the
    chain resolves, the bare Attribute attr otherwise (method calls
    on locals — ``tracer.begin_span`` — resolve to "")."""
    name = mod.resolve(node.func)
    if name:
        return name.rsplit(".", 1)[-1]
    if isinstance(node.func, ast.Attribute):
        return node.func.attr
    return ""


@register
class SpanLeakRule(Rule):
    id = "span-leak"
    summary = "tracer span begun without context-manager form"
    details = (
        "`SpanTracer.begin_span` inside serve/ or a loop-transform "
        "body leaks an open span across pump cycles the moment an "
        "exception or early return skips the matching `end_span` — "
        "use the `with tracer.span(...)` form, or `emit(...)` for "
        "endpoints other bookkeeping already stamped "
        "(utils/trace.py; the explicit pair is for host drivers "
        "OUTSIDE the serve hot loop).  `jax.profiler.start_trace` "
        "with no reachable `stop_trace` is the same leak one level "
        "down: the capture never finalizes and the trace file is "
        "corrupt (utils/profiling.trace is the sanctioned wrapper)."
    )

    def check(self, mod: ModuleInfo):
        yield from self._check_begin_span(mod)
        yield from self._check_profiler_trace(mod)

    def _check_begin_span(self, mod: ModuleInfo):
        in_serve = "/serve/" in f"/{mod.relpath}"
        by_name: dict = {}
        for node in ast.walk(mod.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                by_name.setdefault(node.name, []).append(node)
        bodies: set = set()
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            if mod.resolve(node.func) not in _LOOP_CALLS:
                continue
            for arg in list(node.args) + [k.value for k in node.keywords]:
                if isinstance(arg, ast.Lambda):
                    bodies.add(arg)
                elif isinstance(arg, ast.Name):
                    bodies.update(by_name.get(arg.id, []))
        seen: set = set()
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            if _call_leaf(mod, node) != "begin_span":
                continue
            where = None
            if in_serve:
                where = "serve/ (the streaming hot loop)"
            else:
                for anc in mod.ancestors(node):
                    if anc in bodies:
                        where = "a loop-transform body"
                        break
            if where is None:
                continue
            site = (node.lineno, node.col_offset)
            if site in seen:
                continue
            seen.add(site)
            yield mod.finding(
                self.id, node,
                f"`begin_span` inside {where} — an exception or "
                "early return before `end_span` leaks the open span; "
                "use `with tracer.span(...)` or "
                "`emit(name, t0, t1, ...)`",
            )

    def _check_profiler_trace(self, mod: ModuleInfo):
        project = _project_of(mod)
        seen: set = set()
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            name = mod.resolve(node.func) or ""
            if not name.endswith("profiler.start_trace"):
                continue
            # stop_trace must be reachable from the start's enclosing
            # scope through the project call closure (cross-module
            # since r21) — a try/finally wrapper in the same function
            # counts, the utils/profiling.trace pattern.
            scope = None
            for anc in mod.ancestors(node):
                if isinstance(
                    anc, (ast.FunctionDef, ast.AsyncFunctionDef,
                          ast.Lambda)
                ):
                    scope = anc
                    break
            from .callgraph import FuncRef

            root = (
                project.func_ref(mod, scope)
                if scope is not None else FuncRef(mod, mod.tree)
            )
            has_stop = False
            for fr in project.closure([root]).values():
                for st in _body_stmts(fr.node):
                    for n in ast.walk(st):
                        if not isinstance(n, ast.Call):
                            continue
                        nm = fr.mod.resolve(n.func) or ""
                        if nm.rsplit(".", 1)[-1] == "stop_trace":
                            has_stop = True
                            break
                    if has_stop:
                        break
                if has_stop:
                    break
            if has_stop:
                continue
            site = (node.lineno, node.col_offset)
            if site in seen:
                continue
            seen.add(site)
            yield mod.finding(
                self.id, node,
                "`jax.profiler.start_trace` with no reachable "
                "`stop_trace` — the capture never finalizes and the "
                "trace file is corrupt; use utils/profiling.trace "
                "(start/stop under try/finally)",
            )
