"""swarmlint core: finding model, rule registry, module context, runner.

The analyzer is pure-AST — no file under analysis is ever imported or
executed, so it is safe to run over broken or TPU-only modules and it
costs milliseconds at pytest time instead of minutes at TPU time.

Three layers:

- ``ModuleInfo``: one parsed file + the derived tables every rule
  shares (import-alias resolution, parent links, enclosing-scope
  qualnames, traced-function detection, suppression comments).
- ``Rule`` subclasses (rules_*.py) register themselves in ``REGISTRY``
  and yield ``Finding``s from ``check(mod)``.
- ``analyze_paths``: walk the tree, run every rule, apply inline
  suppressions, and report invalid (justification-free) suppressions
  as findings of the built-in ``bad-suppress`` meta-rule.
"""

from __future__ import annotations

import ast
import io
import os
import re
import tokenize
from dataclasses import dataclass
from typing import Iterable, Iterator

# ---------------------------------------------------------------------------
# Findings

#: Inline suppression syntax (the justification after ``--`` is
#: mandatory — see ``Suppression``):
#:   # swarmlint: disable=rule-a,rule-b -- why this is safe here
SUPPRESS_RE = re.compile(
    r"#\s*swarmlint:\s*disable=([A-Za-z0-9_,-]+)\s*(?:--\s*(\S.*))?"
)

#: Meta-rule id for a disable comment with no justification.
BAD_SUPPRESS = "bad-suppress"


@dataclass(frozen=True)
class Finding:
    """One hazard at one site.

    ``fingerprint`` deliberately excludes the line number: baselines
    must survive unrelated edits above the finding, so identity is
    (rule, file, enclosing scope, stripped source line).
    """

    rule: str
    path: str          # repo-relative, posix separators
    line: int          # 1-based
    context: str       # enclosing def/class qualname, or "<module>"
    message: str
    snippet: str       # the stripped source line

    def fingerprint(self) -> tuple:
        return (self.rule, self.path, self.context, self.snippet)

    def to_dict(self) -> dict:
        return {
            "rule": self.rule,
            "path": self.path,
            "line": self.line,
            "context": self.context,
            "message": self.message,
            "snippet": self.snippet,
        }

    def render(self) -> str:
        return f"{self.path}:{self.line}: [{self.rule}] {self.message}"


@dataclass(frozen=True)
class Suppression:
    """One parsed ``# swarmlint: disable=...`` comment.

    A suppression is only honored when ``justification`` is non-empty
    — the policy the analyzer exists to enforce is "every silenced
    hazard carries its reason next to it".  ``applies_to`` is the code
    line being excused: the comment's own line for a trailing comment,
    the next line for a standalone comment.
    """

    line: int
    rules: tuple
    justification: str
    applies_to: int

    @property
    def valid(self) -> bool:
        return bool(self.justification.strip())


def parse_suppressions(source: str) -> list:
    """Extract every swarmlint disable comment from ``source``.

    Tokenize-based: only real COMMENT tokens count, so suppression
    syntax quoted inside docstrings/string literals (e.g. this
    repo's own docs and tests) is neither honored nor flagged."""
    out = []
    lines = source.splitlines()
    try:
        tokens = list(
            tokenize.generate_tokens(io.StringIO(source).readline)
        )
    except (tokenize.TokenError, IndentationError, SyntaxError):
        return out  # unparseable files are reported elsewhere
    for tok in tokens:
        if tok.type != tokenize.COMMENT:
            continue
        m = SUPPRESS_RE.search(tok.string)
        if not m:
            continue
        i, col = tok.start
        text = lines[i - 1] if 1 <= i <= len(lines) else ""
        standalone = not text[:col].strip()
        out.append(
            Suppression(
                line=i,
                rules=tuple(
                    r.strip() for r in m.group(1).split(",") if r.strip()
                ),
                justification=(m.group(2) or "").strip(),
                applies_to=i + 1 if standalone else i,
            )
        )
    return out


# ---------------------------------------------------------------------------
# Module context

#: Transforms whose function-valued arguments run under trace.  Keys
#: are fully-resolved dotted names (after import-alias resolution).
TRACING_CALLS = frozenset(
    {
        "jax.jit",
        "jax.pmap",
        "jax.vmap",
        "jax.grad",
        "jax.value_and_grad",
        "jax.checkpoint",
        "jax.remat",
        "jax.lax.scan",
        "jax.lax.fori_loop",
        "jax.lax.while_loop",
        "jax.lax.cond",
        "jax.lax.switch",
        "jax.lax.map",
        "jax.lax.associative_scan",
        "jax.experimental.pallas.pallas_call",
        "jax.experimental.shard_map.shard_map",
        "jax.shard_map",
    }
)

#: Decorators that make the decorated function's body traced.
TRACING_DECORATORS = frozenset(
    {"jax.jit", "jax.pmap", "jax.vmap", "jax.checkpoint", "jax.remat"}
)

_FUNC_NODES = (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)


class ModuleInfo:
    """One parsed source file plus the shared per-module tables."""

    def __init__(self, root: str, relpath: str):
        self.root = root
        self.relpath = relpath.replace(os.sep, "/")
        with open(os.path.join(root, relpath), encoding="utf-8") as f:
            self.source = f.read()
        self.lines = self.source.splitlines()
        self.tree = ast.parse(self.source, filename=self.relpath)
        self.suppressions = parse_suppressions(self.source)
        self._parents: dict = {}
        self._qualnames: dict = {}
        self._aliases: dict = {}
        self._build_tables()
        self._traced: set | None = None
        #: Cross-module view, attached by callgraph.Project when this
        #: module is analyzed as part of a project (analyze_paths spans
        #: every scanned file; analyze_module wraps the single module).
        self.project = None

    # -- construction -----------------------------------------------------

    def _build_tables(self) -> None:
        for node in ast.walk(self.tree):
            for child in ast.iter_child_nodes(node):
                self._parents[child] = node
        for node in ast.walk(self.tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    self._aliases[a.asname or a.name.split(".")[0]] = (
                        a.name if a.asname else a.name.split(".")[0]
                    )
            elif isinstance(node, ast.ImportFrom) and node.module:
                for a in node.names:
                    if a.name == "*":
                        continue
                    self._aliases[a.asname or a.name] = (
                        f"{node.module}.{a.name}"
                    )

    # -- shared helpers ---------------------------------------------------

    def parent(self, node):
        return self._parents.get(node)

    def ancestors(self, node) -> Iterator[ast.AST]:
        node = self._parents.get(node)
        while node is not None:
            yield node
            node = self._parents.get(node)

    def qualname(self, node) -> str:
        """Dotted name of the scope enclosing ``node`` ("<module>" at
        top level) — the ``context`` component of fingerprints."""
        parts = []
        for anc in self.ancestors(node):
            if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef,
                                ast.ClassDef)):
                parts.append(anc.name)
            elif isinstance(anc, ast.Lambda):
                parts.append("<lambda>")
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            parts.insert(0, node.name)
        return ".".join(reversed(parts)) or "<module>"

    def resolve(self, node) -> str:
        """Dotted name of a Name/Attribute chain with import aliases
        expanded: ``jr.normal`` -> ``jax.random.normal``.  Returns ""
        for anything that is not a plain dotted chain."""
        parts = []
        while isinstance(node, ast.Attribute):
            parts.append(node.attr)
            node = node.value
        if not isinstance(node, ast.Name):
            return ""
        parts.append(self._aliases.get(node.id, node.id))
        return ".".join(reversed(parts))

    def snippet(self, line: int) -> str:
        if 1 <= line <= len(self.lines):
            return self.lines[line - 1].strip()
        return ""

    def finding(self, rule: str, node, message: str) -> Finding:
        line = getattr(node, "lineno", 1)
        return Finding(
            rule=rule,
            path=self.relpath,
            line=line,
            context=self.qualname(node),
            message=message,
            snippet=self.snippet(line),
        )

    # -- traced-function detection ---------------------------------------

    def decorator_resolves(self, fn, targets: frozenset) -> bool:
        """True if any decorator of ``fn`` is one of ``targets``,
        directly, called (``@jax.jit(...)``), or via
        ``functools.partial(jax.jit, ...)``."""
        if isinstance(fn, ast.Lambda):
            return False
        for dec in fn.decorator_list:
            if self.resolve(dec) in targets:
                return True
            if isinstance(dec, ast.Call):
                name = self.resolve(dec.func)
                if name in targets:
                    return True
                if name == "functools.partial" and dec.args:
                    if self.resolve(dec.args[0]) in targets:
                        return True
        return False

    def traced_functions(self) -> set:
        """Function/lambda nodes whose bodies execute under a jax
        trace: jit/pmap/vmap-decorated, passed to a TRACING_CALLS
        transform (by name within this module, or as an inline
        lambda), or nested inside either."""
        if self._traced is not None:
            return self._traced
        by_name: dict = {}
        for node in ast.walk(self.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                by_name.setdefault(node.name, []).append(node)
        traced: set = set()
        for node in ast.walk(self.tree):
            if isinstance(node, _FUNC_NODES) and self.decorator_resolves(
                node, TRACING_DECORATORS
            ):
                traced.add(node)
            if not isinstance(node, ast.Call):
                continue
            if self.resolve(node.func) not in TRACING_CALLS:
                continue
            for arg in list(node.args) + [k.value for k in node.keywords]:
                if isinstance(arg, ast.Lambda):
                    traced.add(arg)
                elif isinstance(arg, ast.Name):
                    traced.update(by_name.get(arg.id, []))
        # Nested defs inside a traced function trace too.
        for node in ast.walk(self.tree):
            if isinstance(node, _FUNC_NODES) and any(
                a in traced for a in self.ancestors(node)
            ):
                traced.add(node)
        self._traced = traced
        return traced


# ---------------------------------------------------------------------------
# Rule registry

REGISTRY: dict = {}


class Rule:
    """Base class; subclasses set ``id``/``summary``/``details`` and
    implement ``check``."""

    id: str = ""
    summary: str = ""
    details: str = ""

    def check(self, mod: ModuleInfo) -> Iterator[Finding]:
        raise NotImplementedError
        yield  # pragma: no cover


def register(cls):
    """Class decorator: instantiate and add to REGISTRY (import order
    is presentation order in --list-rules and docs)."""
    inst = cls()
    if not inst.id:
        raise ValueError(f"rule {cls.__name__} has no id")
    if inst.id in REGISTRY:
        raise ValueError(f"duplicate rule id {inst.id!r}")
    REGISTRY[inst.id] = inst
    return cls


# ---------------------------------------------------------------------------
# Runner

_SKIP_DIRS = {"__pycache__", ".git", ".pytest_cache", ".hypothesis"}


def iter_py_files(root: str, paths: Iterable[str]) -> Iterator[str]:
    """Yield repo-relative .py paths under each of ``paths`` (which may
    themselves be files), sorted, skipping cache/VCS directories.

    A nonexistent path raises — a typo'd scan path must not report a
    vacuously clean run (callers that want existence-filtering, like
    the DEFAULT_PATHS fallback, filter before calling)."""
    seen = set()
    for p in paths:
        full = os.path.join(root, p)
        if not os.path.exists(full):
            raise FileNotFoundError(
                f"swarmlint: no such scan path: {p!r} (under {root})"
            )
        if os.path.isfile(full) and p.endswith(".py"):
            if p not in seen:
                seen.add(p)
                yield p.replace(os.sep, "/")
            continue
        for dirpath, dirnames, filenames in os.walk(full):
            dirnames[:] = sorted(
                d for d in dirnames if d not in _SKIP_DIRS
            )
            for fn in sorted(filenames):
                if not fn.endswith(".py"):
                    continue
                rel = os.path.relpath(os.path.join(dirpath, fn), root)
                rel = rel.replace(os.sep, "/")
                if rel not in seen:
                    seen.add(rel)
                    yield rel


def _apply_suppressions(raw, mods_by_path):
    """Split ``raw`` into ``(kept, suppressed)`` using each finding's
    OWN module's inline suppressions — with cross-module rules a
    finding may live in a different file than the module whose
    ``check()`` produced it, and only a comment in the finding's file
    may silence it."""
    kept: list = []
    suppressed: list = []
    for f in raw:
        owner = mods_by_path.get(f.path)
        valid = (
            [s for s in owner.suppressions if s.valid]
            if owner is not None else []
        )
        if any(
            s.applies_to == f.line and f.rule in s.rules for s in valid
        ):
            suppressed.append(f)
        else:
            kept.append(f)
    return kept, suppressed


def _bad_suppress_findings(mod: ModuleInfo) -> list:
    out: list = []
    for s in mod.suppressions:
        if not s.valid:
            out.append(
                Finding(
                    rule=BAD_SUPPRESS,
                    path=mod.relpath,
                    line=s.line,
                    context="<module>",
                    message=(
                        "swarmlint disable comment without a "
                        "justification (use `# swarmlint: "
                        "disable=RULE -- why`)"
                    ),
                    snippet=mod.snippet(s.line),
                )
            )
    return out


def _check_modules(mods, rules):
    """Run ``rules`` over ``mods``, deduping identical findings — a
    cross-module rule rooted in two different modules can report the
    same site twice."""
    raw: list = []
    seen: set = set()
    for mod in mods:
        for rule in rules:
            for f in rule.check(mod):
                key = (f.rule, f.path, f.line, f.context, f.snippet)
                if key in seen:
                    continue
                seen.add(key)
                raw.append(f)
    return raw


def analyze_module(mod: ModuleInfo, rules=None):
    """Run rules over one module; apply inline suppressions.

    Returns ``(kept, suppressed)`` — invalid suppressions become
    ``bad-suppress`` findings in ``kept`` and do NOT silence anything.
    The module gets a single-module ``callgraph.Project`` if it is not
    already part of one, so cross-module rules degrade to their
    same-module reach.
    """
    from . import callgraph

    rules = list((rules or REGISTRY).values())
    if mod.project is None:
        callgraph.Project([mod])
    raw = _check_modules([mod], rules)
    kept, suppressed = _apply_suppressions(raw, {mod.relpath: mod})
    kept.extend(_bad_suppress_findings(mod))
    return kept, suppressed


def analyze_paths(root: str, paths: Iterable[str], rules=None):
    """Run the registry over every .py file under ``paths``.

    All parseable files are loaded first and share one
    ``callgraph.Project``, so rules see cross-module call paths across
    the whole scan set.  Returns ``(findings, suppressed, errors)``;
    ``errors`` are (path, message) pairs for unparseable files
    (reported, not fatal — a syntax error is pytest's job to flag, not
    the linter's to crash on)."""
    from . import callgraph

    rules = list((rules or REGISTRY).values())
    mods: list = []
    errors: list = []
    for rel in iter_py_files(root, paths):
        try:
            mods.append(ModuleInfo(root, rel))
        except (SyntaxError, UnicodeDecodeError, OSError) as e:
            errors.append((rel, f"{type(e).__name__}: {e}"))
            continue
    callgraph.Project(mods)
    raw = _check_modules(mods, rules)
    findings, suppressed = _apply_suppressions(
        raw, {m.relpath: m for m in mods}
    )
    for mod in mods:
        findings.extend(_bad_suppress_findings(mod))
    findings.sort(key=lambda f: (f.path, f.line, f.rule))
    suppressed.sort(key=lambda f: (f.path, f.line, f.rule))
    return findings, suppressed, errors
