"""Swarm state as a struct-of-arrays pytree.

The reference scatters all mutable state across instance attributes of one
``SwarmAgent`` object per OS process (/root/reference/agent.py:25-54).  The
TPU-native model holds the *entire swarm* in one immutable pytree of arrays,
so the per-tick update is a pure function ``SwarmState -> SwarmState`` that
jits into a handful of fused XLA kernels and shards over a device mesh along
the agent axis.

Mapping from reference attributes to fields here:
  - state / leader_id / leader_pos      (agent.py:31-33)  -> fsm, leader_id,
    leader_pos, has_leader_pos — kept PER AGENT ([N]-shaped) so the
    decentralized protocol semantics (divergent views during elections)
    are preserved, not collapsed into one global scalar.
  - last_heartbeat_time (agent.py:34)   -> last_hb_tick [N] (tick-based; the
    synchronous model has no wall clock inside jit).
  - tick (agent.py:35)                  -> tick (scalar, shared: synchronous).
  - election_wait_start/delay (38-39)   -> wait_until [N] (absolute tick).
  - tasks / task_claims dicts (41-44)   -> task_pos/task_cap/task_winner/
    task_util arrays + task_claimed [N,T] bitmap.  String statuses
    'OPEN'|'TENTATIVE'|'ASSIGNED'|'LOCKED' become derived views
    (see ops/allocation.py:task_status_view).
  - position/velocity/target (47-51)    -> pos, vel, target, has_target.
  - capabilities: list[str] (52)        -> caps [N,C] one-hot bool (string
    sets don't vectorize; SURVEY.md §7 "scale limits to remove").
  - sensors (50)                        -> obstacles are an *input* to the
    step (like update_sensors, agent.py:59-65); neighbors are implicit
    (every alive agent, or a spatial-hash subset at large N).

Agent ids are int32, removing the reference's u8 wire-format ceiling of 255
agents (agent.py:186; SURVEY.md §5a bug 2).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from flax import struct

# FSM states — same values as the reference enum (agent.py:19-22).
FOLLOWER = 1
ELECTION_WAIT = 2
LEADER = 3

# Task status codes for derived views (reference string statuses, agent.py:41).
TASK_OPEN = 0
TASK_TENTATIVE = 1
TASK_ASSIGNED = 2
TASK_LOCKED = 3

# Sentinel for "no leader known" (reference uses None, agent.py:32).
NO_LEADER = -1
# Sentinel for "no capability required" on a task (agent.py:344).
NO_CAP = -1
# Sentinel for "task unclaimed" (reference: absent key in task_claims dict).
NO_WINNER = -1


@struct.dataclass
class SwarmState:
    """Struct-of-arrays swarm state. N agents, D spatial dims, T tasks, C caps."""

    # --- global ---
    tick: jax.Array            # i32 scalar
    key: jax.Array             # PRNG key (election jitter, agent.py:229)

    # --- agents ---
    agent_id: jax.Array        # [N] i32
    alive: jax.Array           # [N] bool — failure injection = clearing bits
    pos: jax.Array             # [N,D] f32
    vel: jax.Array             # [N,D] f32
    caps: jax.Array            # [N,C] bool one-hot capabilities
    target: jax.Array          # [N,D] f32 nav target (agent.py:56-57)
    has_target: jax.Array      # [N] bool (reference: target is None, agent.py:51)

    # --- per-agent coordination view (decentralized semantics) ---
    fsm: jax.Array             # [N] i32 FOLLOWER/ELECTION_WAIT/LEADER
    leader_id: jax.Array       # [N] i32, NO_LEADER when unknown
    leader_pos: jax.Array      # [N,D] f32 last heard leader position
    has_leader_pos: jax.Array  # [N] bool
    last_hb_tick: jax.Array    # [N] i32 tick of last heard heartbeat
    wait_until: jax.Array      # [N] i32 acclaim-after tick (ELECTION_WAIT)

    # --- event-maintained caches (see recount_alive_below) ---
    # alive_below[i] = number of alive agents with id < agent_id[i].
    # Invariant during a rollout (``alive`` only changes through kill/
    # revive, which recount); carrying it replaces a per-tick
    # scatter+cumsum+gather in the formation ordinal-rank path that
    # measured ~12 ms/tick at 1M agents on v5e (r3).
    alive_below: jax.Array     # [N] i32
    # leader_live[i] = "agent i's believed leader is currently alive".
    # True at every in-protocol adoption (heartbeats/acclaims only come
    # from live agents); cleared by kill() for believers, restored by
    # revive() — exactly the instantaneous alive-lookup it replaces.
    leader_live: jax.Array     # [N] bool

    # --- tasks (global table = the leader's arbitration ledger) ---
    task_pos: jax.Array        # [T,D] f32
    task_cap: jax.Array        # [T] i32 required capability, NO_CAP if none
    task_winner: jax.Array     # [T] i32 awarded agent id, NO_WINNER if open
    task_util: jax.Array       # [T] f32 winning utility (hysteresis incumbent)
    task_claimed: jax.Array    # [N,T] bool — per-agent "I have claimed /
    #                            have seen this task resolved" view; drives
    #                            TENTATIVE/LOCKED statuses and claim gating.

    @property
    def n_agents(self) -> int:
        return self.agent_id.shape[0]

    @property
    def dim(self) -> int:
        return self.pos.shape[-1]

    @property
    def n_tasks(self) -> int:
        return self.task_pos.shape[0]


def make_swarm(
    n_agents: int,
    dim: int = 2,
    n_tasks: int = 0,
    n_caps: int = 1,
    seed: int = 0,
    pos: Optional[jax.Array] = None,
    spread: float = 0.0,
    dtype=jnp.float32,
) -> SwarmState:
    """Build an initial SwarmState.

    The reference spawns every agent at the origin (agent.py:47), which its
    physics cannot survive (ZeroDivisionError, SURVEY.md §5a bug 1).  We
    default to the same origin spawn — safe here because every norm is
    epsilon-clamped — but ``spread`` scatters agents uniformly in
    [-spread, spread]^D, and ``pos`` overrides entirely.
    """
    key = jax.random.PRNGKey(seed)
    if pos is None:
        if spread > 0.0:
            key, sub = jax.random.split(key)
            pos = jax.random.uniform(
                sub, (n_agents, dim), dtype, minval=-spread, maxval=spread
            )
        else:
            pos = jnp.zeros((n_agents, dim), dtype)
    else:
        pos = jnp.asarray(pos, dtype)

    return SwarmState(
        tick=jnp.asarray(0, jnp.int32),
        key=key,
        agent_id=jnp.arange(n_agents, dtype=jnp.int32),
        alive=jnp.ones((n_agents,), bool),
        pos=pos,
        vel=jnp.zeros((n_agents, dim), dtype),
        caps=jnp.zeros((n_agents, max(n_caps, 1)), bool),
        target=jnp.zeros((n_agents, dim), dtype),
        has_target=jnp.zeros((n_agents,), bool),
        fsm=jnp.full((n_agents,), FOLLOWER, jnp.int32),
        leader_id=jnp.full((n_agents,), NO_LEADER, jnp.int32),
        leader_pos=jnp.zeros((n_agents, dim), dtype),
        has_leader_pos=jnp.zeros((n_agents,), bool),
        last_hb_tick=jnp.zeros((n_agents,), jnp.int32),
        wait_until=jnp.zeros((n_agents,), jnp.int32),
        alive_below=jnp.arange(n_agents, dtype=jnp.int32),
        leader_live=jnp.ones((n_agents,), bool),
        task_pos=jnp.zeros((n_tasks, dim), dtype),
        task_cap=jnp.full((n_tasks,), NO_CAP, jnp.int32),
        task_winner=jnp.full((n_tasks,), NO_WINNER, jnp.int32),
        task_util=jnp.zeros((n_tasks,), dtype),
        task_claimed=jnp.zeros((n_agents, n_tasks), bool),
    )


# Agent-axis fields (dim 0 == N) — the fields a swarm-wide permutation
# must move together.  Listed explicitly rather than inferred from shapes:
# with n_tasks == n_agents a shape test would silently permute the task
# table too.
AGENT_AXIS_FIELDS = (
    "agent_id", "alive", "pos", "vel", "caps", "target", "has_target",
    "fsm", "leader_id", "leader_pos", "has_leader_pos", "last_hb_tick",
    "wait_until", "alive_below", "leader_live", "task_claimed",
)


def recount_alive_below(state: SwarmState) -> SwarmState:
    """Recompute the ``alive_below`` cache from ``alive`` and ``agent_id``.

    One scatter + cumsum + gather in id space — O(N), slot-order
    invariant.  Called at ``alive``-mutation time (make_swarm, kill,
    revive) so the formation ordinal-rank path (ops/physics.py) never
    pays for it inside the tick loop: a dynamic gather of a loop-carried
    array in the scan body defeats XLA's loop-invariant hoisting and
    measured ~12 ms/tick at 1M on v5e (r3).  Any code that writes
    ``alive`` directly (instead of kill/revive) must call this.
    """
    n = state.n_agents
    alive_by_id = (
        jnp.zeros((n,), jnp.int32)
        .at[state.agent_id]
        .set(state.alive.astype(jnp.int32))
    )
    cum = jnp.cumsum(alive_by_id) - alive_by_id     # alive ids < id k
    return state.replace(alive_below=cum[state.agent_id])


def permute_agents(state: SwarmState, order: jax.Array) -> SwarmState:
    """Reorder the swarm's agent axis by ``order`` ([N] indices).

    Semantically transparent: every protocol op is a reduction or an
    elementwise update over the agent axis, and identity lives in
    ``agent_id`` (which moves with its agent) — only the *array slot* of
    each agent changes.  Used by ``separation_mode="window"`` with
    ``sort_every > 1`` to keep the swarm approximately Morton-sorted so
    the separation pass needs no per-tick gather/scatter.

    For the hot sorted-reorder path prefer :func:`sort_agents_by_key`:
    this gather form costs ~13 ms PER FIELD COLUMN at 1M on v5e (TPU
    gathers are latency-bound), ~20x a variadic sort carrying the same
    payload.
    """
    return state.replace(
        **{f: getattr(state, f)[order] for f in AGENT_AXIS_FIELDS}
    )


def sort_agents_by_key(state: SwarmState, keys: jax.Array) -> SwarmState:
    """Reorder the swarm's agent axis into ascending ``keys`` order —
    same semantics as ``permute_agents(state, argsort(keys))``, but the
    whole agent-axis payload rides through ONE variadic ``lax.sort``
    (a comparison network: vectorized compare/selects, zero gathers).
    Measured at 1M on v5e: a single [N] gather costs ~13 ms while a
    1-key + 8-payload variadic sort costs ~6 ms TOTAL — the r3 fix for
    the window mode's re-sort cadence dominating the protocol tick.

    Multi-column fields ([N, 2] pos, [N, C] caps, ...) split into
    per-column operands (lax.sort requires same-shape operands) and
    reassemble after.
    """
    fields = [(f, getattr(state, f)) for f in AGENT_AXIS_FIELDS]
    cols: list[jax.Array] = []
    # (field, ncols) — ncols None marks a 1-D field; a 2-D field with
    # ZERO columns (e.g. task_claimed [N, 0] before any tasks) is a
    # valid layout that consumes no sort operands.
    layout: list[tuple[str, int | None]] = []
    for f, arr in fields:
        if arr.ndim == 1:
            layout.append((f, None))
            cols.append(arr)
        else:
            layout.append((f, arr.shape[1]))
            cols.extend(arr[:, j] for j in range(arr.shape[1]))
    sorted_ops = jax.lax.sort(
        (keys, *cols), num_keys=1, is_stable=True
    )[1:]
    out = {}
    i = 0
    for f, ncols in layout:
        if ncols is None:
            out[f] = sorted_ops[i]
            i += 1
        elif ncols == 0:
            out[f] = getattr(state, f)           # [N, 0]: nothing moves
        else:
            out[f] = jnp.stack(sorted_ops[i:i + ncols], axis=1)
            i += ncols
    return state.replace(**out)


def with_tasks(state: SwarmState, task_pos, task_cap=None) -> SwarmState:
    """Install a task table (the reference's de-facto input API is writing
    the ``tasks`` dict directly, agent.py:41-42 / test_allocation.py)."""
    task_pos = jnp.asarray(task_pos, state.task_pos.dtype)
    t = task_pos.shape[0]
    if task_cap is None:
        task_cap = jnp.full((t,), NO_CAP, jnp.int32)
    else:
        task_cap = jnp.asarray(task_cap, jnp.int32)
    return state.replace(
        task_pos=task_pos,
        task_cap=task_cap,
        task_winner=jnp.full((t,), NO_WINNER, jnp.int32),
        task_util=jnp.zeros((t,), state.task_util.dtype),
        task_claimed=jnp.zeros((state.n_agents, t), bool),
    )
