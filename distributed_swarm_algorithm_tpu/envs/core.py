"""SwarmMARLEnv — the protocol swarm as a JaxMARL-style MARL environment.

The tick (election + allocation + formation + APF physics) is pure
fixed-shape dataflow, which is exactly the contract a JAX-native
multi-agent RL environment needs (JaxMARL, arxiv 2311.10090): a pure
``reset(key, params) -> (obs, state)`` / ``step(key, state, actions)
-> (obs, state, rewards, dones, info)`` pair that composes with
``jit``/``vmap``/``lax.scan`` end to end.  This module wraps
``models/swarm.swarm_tick_dyn`` (the r13 scenario-batching substrate)
in that API:

- **Actions** are a bounded per-agent steering force ``[N, 2]``
  injected between the APF term and ``integrate``
  (``_physics_step_core(extra_force=...)``).  The injection is a
  sign-of-zero-safe select, so an all-zero action reproduces the pure
  protocol trajectory BITWISE — the env's ground truth is the swarm
  everyone else ships, pinned in tests/test_envs.py against
  ``swarm_rollout``.
- **Observations** are fixed-shape per-agent rows: own pose/velocity/
  liveness, the leader-relative block (leader offset + formation slot
  error via ``formation_targets``), a K-nearest-neighbor block read
  off the existing :class:`~..ops.hashgrid_plan.HashgridPlan`
  (candidate rows from the stencil-union table, true-distance
  ``top_k``), and a task-board slice (per-task offset + open/mine
  flags).  Collection is read-only — it cannot perturb the
  trajectory.
- **Auto-reset** is the standard ``jnp.where`` select (never a host
  branch on the traced ``done`` — swarmlint's ``done-branch`` rule
  exists because that is the classic retrace/ConcretizationError
  hazard): when an episode hits ``params.max_steps`` the freshly
  materialized state is selected in, so a full rollout is ONE
  compiled ``lax.scan``.
- **Scenarios are data** (envs/scenarios.py): a scenario is an
  :class:`EnvParams` pytree — :class:`~..serve.batched.ScenarioParams`
  gains + a reward id + spawn/team/task/obstacle tables — never a
  fork of the tick, so heterogeneous scenarios vmap into one compiled
  program and ride the serve layer's bucket lattice
  (``serve/batched.env_rollouts``).

The compiled entry is registered with the compile observatory as
``"env-rollout"``; per-tick :class:`~..utils.telemetry.TickTelemetry`
threads through ``step`` behind the same static gate as every other
rollout (disabled lowering is byte-identical — pinned).
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from flax import struct

from ..models.swarm import swarm_tick_dyn
from ..ops.hashgrid_plan import build_hashgrid_plan, refresh_plan
from ..ops.physics import formation_targets
from ..serve.batched import (
    ScenarioParams,
    scenario_params,
    validate_serve_config,
)
from ..state import (
    FOLLOWER,
    NO_CAP,
    NO_LEADER,
    NO_WINNER,
    SwarmState,
    recount_alive_below,
)
from ..utils.compile_watch import watched
from ..utils.config import TELEMETRY_ON, SwarmConfig

#: Compile-observatory registry name of the env rollout entry.
ENV_ROLLOUT_ENTRY = "env-rollout"

#: Where inactive obstacle rows are parked: far enough that the
#: repulsion term is exactly zero for any in-arena agent (surface
#: distance >> rho0), so a scenario with fewer obstacles than the
#: env's static table costs nothing but padding.
_REMOTE = 1.0e6


@struct.dataclass
class EnvParams:
    """One scenario as TRACED data — every leaf stacks along a leading
    scenario axis, so heterogeneous scenarios run in one compiled
    program (the r13 discipline, extended to the RL surface).

    ``scenario`` carries the protocol gains
    (:class:`~..serve.batched.ScenarioParams`); ``reward_id`` selects
    the reward function from envs/scenarios.py via ``lax.switch``;
    ``alive0``/``team`` are the population register (pad slots dead;
    team 1 = evaders in the pursuit scenario, killed via the alive
    mask when tagged); ``max_steps`` is the auto-reset episode
    boundary; ``tag_radius <= 0`` disables tagging entirely (the
    non-pursuit scenarios select the untouched state bitwise).

    Capability classes (r20, train/caps.py — the ABMax-style
    heterogeneous-agents axis, arxiv 2508.16508): ``cap_class``
    assigns each agent one of the env's ``n_cap_classes`` classes,
    and the three per-class tables scale that agent's action bound
    (``cap_act``), speed clamp (``cap_speed``) and reward weight
    (``cap_reward``) — all TRACED data, so one compiled program
    serves every class layout.  The default table (every agent class
    0, every scale 1.0) is arithmetically a multiply-by-one, so the
    r14 zero-action == protocol BITWISE pin extends to it unchanged
    (pinned in tests/test_train.py)."""

    scenario: ScenarioParams   # protocol gains, each an f32 scalar
    reward_id: jax.Array       # i32 — envs/scenarios.py registry index
    spread: jax.Array          # f32 — spawn arena half-width
    use_point: jax.Array       # bool — shared nav goal vs station-keep
    point: jax.Array           # [2] f32 — the shared goal (if use_point)
    alive0: jax.Array          # [capacity] bool — initial population
    team: jax.Array            # [capacity] i32 — 0 pursuer/default, 1 evader
    task_pos: jax.Array        # [n_tasks, 2] f32 — task board
    obstacles: jax.Array       # [n_obstacles, 3] f32 (cx, cy, radius)
    max_steps: jax.Array       # i32 — episode length (auto-reset)
    tag_radius: jax.Array      # f32 — pursuit tag distance (<= 0: off)
    cap_class: jax.Array       # [capacity] i32 — capability class id
    cap_act: jax.Array         # [n_cap_classes] f32 — act_limit scale
    cap_speed: jax.Array       # [n_cap_classes] f32 — max_speed scale
    cap_reward: jax.Array      # [n_cap_classes] f32 — reward weight


@struct.dataclass
class EnvState:
    """The env's scan carry: the live protocol state, the episode
    clock, and the scenario's own params (carried so ``step`` needs no
    params argument and ``vmap`` over states covers the scenario axis
    in one in_axes).

    ``obs_plan`` (r20, ROADMAP item 4's named scatter floor): with
    ``env.obs_skin > 0`` the carry additionally holds the KNN
    observation's skin-inflated
    :class:`~..ops.hashgrid_plan.HashgridPlan`, refreshed under the
    r9 Verlet triggers (``refresh_plan``) instead of rebuilt per step
    — the per-step bin+sort becomes a per-rebuild cost while the
    KNN block stays exact within its coverage radius (candidates are
    distance-ranked against CURRENT positions every step).  ``None``
    (the default, ``obs_skin == 0``) keeps the pre-r20 per-step
    build bitwise."""

    swarm: SwarmState
    t: jax.Array               # i32 — steps into the current episode
    params: EnvParams
    obs_plan: Optional[object] = None


def stack_env_params(params: Sequence[EnvParams]) -> EnvParams:
    """Stack single scenarios into the ``[S]``-leaved batch pytree."""
    params = list(params)
    if not params:
        raise ValueError("stack_env_params needs at least one scenario")
    return jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *params)


def env_params_row(params: EnvParams, i: int) -> EnvParams:
    """Scenario ``i`` out of a stacked batch."""
    return jax.tree_util.tree_map(lambda x: x[i], params)


@dataclasses.dataclass(frozen=True)
class SwarmMARLEnv:
    """The swarm as a multi-agent RL environment — STATIC structure
    only (frozen + hashable, so the env rides as a jit-static
    argument; everything per-scenario lives in :class:`EnvParams`).

    ``cfg`` must sit inside the scenario-batching envelope
    (``separation_mode`` in ``{dense, off}`` — the serve contract;
    the obs spatial index is the env's own and does not constrain the
    tick).  ``capacity``/``n_tasks``/``n_obstacles`` are the shape
    axes every scenario of this env shares (a scenario with fewer
    agents rides the alive mask, fewer obstacles the remote-row
    padding).  The obs KNN block reads a per-step
    :class:`~..ops.hashgrid_plan.HashgridPlan` over the
    ``[-obs_hw, obs_hw)^2`` box: neighbors are exact within one obs
    cell (``2 * obs_hw / g``); agents outside the box clip into edge
    cells and degrade gracefully (candidates distance-ranked, never
    wrong, possibly missing).  ``act_limit`` bounds the steering
    force per agent (L2).

    ``n_cap_classes`` (r20) is the capability-class table's shape
    axis (train/caps.py): per-class act/speed/reward scales ride
    :class:`EnvParams` as traced data; ``> 1`` additionally appends a
    class one-hot block to the observation so a shared policy can
    condition on its own class.  ``obs_skin``/``obs_rebuild_every``
    (r20) opt the observation KNN plan into the r9 Verlet carry: the
    plan lives in :class:`EnvState` and rebuilds only under the
    displacement/alive/ceiling triggers (0 = the per-step build)."""

    cfg: SwarmConfig
    capacity: int
    n_tasks: int = 0
    n_obstacles: int = 0
    k_neighbors: int = 4
    obs_hw: float = 16.0
    obs_cell: float = 4.0
    obs_max_per_cell: int = 8
    obs_neighbor_cap: int = 32
    act_limit: float = 1.0
    enable_tagging: bool = True
    n_cap_classes: int = 1
    obs_skin: float = 0.0
    obs_rebuild_every: int = 0

    def __post_init__(self):
        validate_serve_config(self.cfg)
        if self.cfg.dtype != "float32":
            raise ValueError(
                f"SwarmMARLEnv materializes float32 swarms; got "
                f"cfg.dtype={self.cfg.dtype!r}"
            )
        if self.capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {self.capacity}")
        if not self.obs_hw > 0 or not self.obs_cell > 0:
            raise ValueError(
                "obs_hw and obs_cell must be > 0 (the obs KNN grid "
                f"tiles [-obs_hw, obs_hw)^2); got {self.obs_hw}, "
                f"{self.obs_cell}"
            )
        if not 1 <= self.k_neighbors <= self.obs_neighbor_cap:
            raise ValueError(
                f"k_neighbors {self.k_neighbors} outside [1, "
                f"obs_neighbor_cap={self.obs_neighbor_cap}] — the KNN "
                "block ranks candidates from the plan's stencil-union "
                "rows, so K cannot exceed the row width"
            )
        if not self.act_limit > 0:
            raise ValueError(
                f"act_limit must be > 0, got {self.act_limit} (the "
                "steering bound; actions are norm-clamped to it)"
            )
        if self.n_cap_classes < 1:
            raise ValueError(
                f"n_cap_classes must be >= 1, got "
                f"{self.n_cap_classes} (the capability table's shape "
                "axis; 1 = the homogeneous default)"
            )
        if self.obs_skin < 0:
            raise ValueError(
                f"obs_skin must be >= 0, got {self.obs_skin} (the "
                "obs plan's Verlet reuse window; 0 = per-step build)"
            )
        if self.obs_rebuild_every < 0:
            raise ValueError(
                f"obs_rebuild_every must be >= 0, got "
                f"{self.obs_rebuild_every}"
            )
        if self.obs_rebuild_every and not self.obs_skin > 0:
            raise ValueError(
                "obs_rebuild_every only applies to the carried obs "
                "plan — set obs_skin > 0 (with skin 0 the plan is "
                "rebuilt every step anyway)"
            )

    # -- observation layout -------------------------------------------------
    def obs_layout(self):
        """[(block, width), ...] — the documented per-agent row
        layout, in order (docs/ENVIRONMENTS.md).  The capability
        block only exists for heterogeneous envs (``n_cap_classes >
        1``) — the homogeneous default keeps the r14 layout
        byte-for-byte."""
        layout = [
            ("own: pos, vel, alive", 5),
            ("leader: offset, has_leader, slot_err", 5),
            ("neighbors: K x (rel_pos, rel_vel, valid)",
             5 * self.k_neighbors),
            ("tasks: T x (rel_pos, open, mine)", 4 * self.n_tasks),
        ]
        if self.n_cap_classes > 1:
            layout.append(
                ("caps: class one-hot", self.n_cap_classes)
            )
        return layout

    @property
    def obs_dim(self) -> int:
        return sum(w for _, w in self.obs_layout())

    @property
    def action_dim(self) -> int:
        return 2

    # -- constructors -------------------------------------------------------
    def materialize(self, key: jax.Array, p: EnvParams) -> SwarmState:
        """The scenario's initial :class:`SwarmState` from traced data
        — the same construction as the serve layer's vmapped
        materializer (``serve/batched._materialize_batch_impl``), so
        ``reset(jax.random.PRNGKey(seed), params)`` reproduces
        ``serve.materialize_scenario`` of the matching request
        bitwise, and the auto-reset branch can re-materialize inside
        the compiled rollout."""
        capacity = self.capacity
        key, sub = jax.random.split(key)
        pos = jax.random.uniform(
            sub, (capacity, 2), jnp.float32,
            minval=-p.spread, maxval=p.spread,
        )
        aint = p.alive0.astype(jnp.int32)
        alive_below = jnp.cumsum(aint) - aint
        target = jnp.where(
            p.use_point, jnp.broadcast_to(p.point, pos.shape), pos
        )
        return SwarmState(
            tick=jnp.asarray(0, jnp.int32),
            key=key,
            agent_id=jnp.arange(capacity, dtype=jnp.int32),
            alive=p.alive0,
            pos=pos,
            vel=jnp.zeros((capacity, 2), jnp.float32),
            caps=jnp.zeros((capacity, 1), bool),
            target=target,
            has_target=jnp.ones((capacity,), bool),
            fsm=jnp.full((capacity,), FOLLOWER, jnp.int32),
            leader_id=jnp.full((capacity,), NO_LEADER, jnp.int32),
            leader_pos=jnp.zeros((capacity, 2), jnp.float32),
            has_leader_pos=jnp.zeros((capacity,), bool),
            last_hb_tick=jnp.zeros((capacity,), jnp.int32),
            wait_until=jnp.zeros((capacity,), jnp.int32),
            alive_below=alive_below,
            leader_live=jnp.ones((capacity,), bool),
            task_pos=p.task_pos,
            task_cap=jnp.full((self.n_tasks,), NO_CAP, jnp.int32),
            task_winner=jnp.full((self.n_tasks,), NO_WINNER, jnp.int32),
            task_util=jnp.zeros((self.n_tasks,), jnp.float32),
            task_claimed=jnp.zeros((capacity, self.n_tasks), bool),
        )

    # -- observation --------------------------------------------------------
    def build_obs_plan(self, state: SwarmState):
        """The observation KNN's spatial index for ``state`` — THE one
        builder both the per-step path and the r20 Verlet carry go
        through, so their geometry cannot drift.  With ``obs_skin >
        0`` the binning cell is inflated by the skin (the r9 reuse
        window); coverage after drift stays >= one obs cell either
        way (ops/hashgrid_plan.py module doc)."""
        return build_hashgrid_plan(
            state.pos, state.alive, float(self.obs_hw),
            float(self.obs_cell), self.obs_max_per_cell,
            need_csr=True, neighbor_cap=self.obs_neighbor_cap,
            skin=float(self.obs_skin),
        )

    def obs(self, state: SwarmState, derived=None, plan=None,
            cap_class=None) -> jax.Array:
        """[capacity, obs_dim] per-agent observation rows (dead agents
        read all-zero).  Read-only off the current state — collection
        cannot perturb the trajectory.

        ``derived`` (r18): the tick's already-computed formation
        ``(target, has_target)`` columns — ``step`` passes them when
        it can prove they match what a re-derivation here would
        produce (``formation_targets`` is position-independent, so
        only the tag sweep's liveness flips can invalidate them);
        ``None`` derives from ``state`` as before.

        ``plan`` (r20): a carried — possibly Verlet-stale —
        observation :class:`~..ops.hashgrid_plan.HashgridPlan`
        (:class:`EnvState` holds it when ``obs_skin > 0``); ``None``
        builds per call.  Candidate rows are read through the plan
        but distances/velocities come from the CURRENT state, so a
        within-skin-stale plan yields the same top-K block a fresh
        same-geometry build would (pinned in tests/test_train.py).

        ``cap_class`` (r20): the scenario's per-agent class ids —
        required (and appended as a one-hot block) only when the env
        is heterogeneous (``n_cap_classes > 1``)."""
        with jax.named_scope("env_obs"):
            return self._obs_impl(state, derived, plan, cap_class)

    def _obs_impl(self, state: SwarmState, derived=None, plan=None,
                  cap_class=None) -> jax.Array:
        n = self.capacity
        pos, vel, alive = state.pos, state.vel, state.alive
        falive = alive.astype(jnp.float32)

        own = jnp.concatenate([pos, vel, falive[:, None]], axis=-1)

        # Leader block: offset to the last-heard leader pose and the
        # formation slot error (the derived target the APF attraction
        # actually steers toward this tick).
        if derived is None:
            d = formation_targets(state, self.cfg)
            derived = (d.target, d.has_target)
        d_target, d_has = derived
        has_lead = state.has_leader_pos & alive
        lead_rel = jnp.where(
            has_lead[:, None], state.leader_pos - pos, 0.0
        )
        slot_err = jnp.where(
            (d_has & alive)[:, None],
            d_target - pos, 0.0,
        )
        leader = jnp.concatenate(
            [lead_rel, has_lead.astype(jnp.float32)[:, None], slot_err],
            axis=-1,
        )

        # KNN block off the shared spatial index: one plan build (or
        # the r20 carried plan), one [N, W] candidate gather (the r9
        # stencil-union table), exact top-K by true distance within
        # one obs cell of coverage.  A carried plan's key/cand tables
        # are build-time snapshots, but the scores below are CURRENT
        # distances — the Verlet contract every plan consumer keeps.
        if plan is None:
            plan = self.build_obs_plan(state)
        g2 = plan.g * plan.g
        cell = jnp.minimum(plan.key, g2 - 1)   # dead agents clip; masked out
        cand = plan.cand[cell]                                # [N, W]
        idx = jnp.minimum(cand, n - 1)
        iota = jnp.arange(n, dtype=jnp.int32)
        valid = (
            (cand < n)
            & (idx != iota[:, None])
            & alive[idx]
            & alive[:, None]
        )
        rel = pos[idx] - pos[:, None, :]                      # [N, W, 2]
        d2 = jnp.sum(rel * rel, axis=-1)
        score = jnp.where(valid, -d2, -jnp.inf)
        _, top = jax.lax.top_k(score, self.k_neighbors)       # [N, K]
        sel = jnp.take_along_axis(idx, top, axis=1)
        sel_ok = jnp.take_along_axis(valid, top, axis=1)
        nrel = jnp.where(
            sel_ok[..., None],
            jnp.take_along_axis(rel, top[..., None], axis=1), 0.0,
        )
        nrelv = jnp.where(
            sel_ok[..., None], vel[sel] - vel[:, None, :], 0.0
        )
        nbr = jnp.concatenate(
            [nrel, nrelv, sel_ok.astype(jnp.float32)[..., None]],
            axis=-1,
        ).reshape(n, 5 * self.k_neighbors)

        blocks = [own, leader, nbr]

        if self.n_tasks:
            trel = state.task_pos[None, :, :] - pos[:, None, :]
            open_ = (state.task_winner == NO_WINNER).astype(jnp.float32)
            mine = (
                (state.task_winner[None, :] == state.agent_id[:, None])
                & (state.task_winner != NO_WINNER)[None, :]
            ).astype(jnp.float32)
            tb = jnp.concatenate(
                [
                    trel,
                    jnp.broadcast_to(
                        open_[None, :], mine.shape
                    )[..., None],
                    mine[..., None],
                ],
                axis=-1,
            ).reshape(n, 4 * self.n_tasks)
            blocks.append(tb)

        if self.n_cap_classes > 1:
            # Heterogeneous env: a shared policy must be able to
            # condition on its own capability class (the ABMax
            # asymmetric-game point) — one-hot, dead rows zeroed by
            # the trailing select like every other block.
            if cap_class is None:
                raise ValueError(
                    "obs() on a heterogeneous env (n_cap_classes > 1) "
                    "needs the scenario's cap_class column — pass "
                    "params.cap_class (reset/step thread it "
                    "automatically)"
                )
            cls = jnp.clip(cap_class, 0, self.n_cap_classes - 1)
            blocks.append(
                jax.nn.one_hot(cls, self.n_cap_classes,
                               dtype=jnp.float32)
            )

        out = jnp.concatenate(blocks, axis=-1)
        return jnp.where(alive[:, None], out, 0.0)

    # -- the env API --------------------------------------------------------
    def reset(
        self, key: jax.Array, params: EnvParams
    ) -> Tuple[jax.Array, EnvState]:
        """(obs, state): materialize the scenario and observe it."""
        swarm = self.materialize(key, params)
        plan = (
            self.build_obs_plan(swarm) if self.obs_skin > 0 else None
        )
        state = EnvState(
            swarm=swarm, t=jnp.asarray(0, jnp.int32), params=params,
            obs_plan=plan,
        )
        return (
            self.obs(swarm, plan=plan, cap_class=params.cap_class),
            state,
        )

    def step(
        self,
        key: jax.Array,
        state: EnvState,
        actions: jax.Array,
        auto_reset: bool = True,
    ):
        """(obs, state, rewards, dones, info): one protocol tick under
        the per-agent steering ``actions`` ([capacity, 2], L2-clamped
        to ``act_limit``), then reward, termination, and the
        ``where``-select auto-reset.

        ``rewards``/``dones`` are per-agent ``[capacity]`` (dead and
        pad slots reward 0 and read done); ``info["done"]`` is the
        episode-boundary scalar, and ``info["telemetry"]`` the tick's
        flight-recorder record when the static gate is on.  With
        ``auto_reset=False`` (static) the episode boundary only
        reports — the state keeps stepping (the bench's overhead
        twin)."""
        p = state.params
        prev = state.swarm

        # Capability classes (r20): per-agent act/speed scales gathered
        # from the traced class tables.  The default table is all-ones,
        # and x * 1.0 is bitwise x in f32 — which is how the r14
        # zero-action == protocol pin survives the heterogeneous
        # machinery being always-on (tests/test_train.py).
        cap_cls = jnp.clip(p.cap_class, 0, self.n_cap_classes - 1)

        a = jnp.asarray(actions, jnp.float32)
        norm = jnp.linalg.norm(a, axis=-1, keepdims=True)
        lim = (
            jnp.asarray(self.act_limit, jnp.float32)
            * p.cap_act[cap_cls][:, None]
        )
        a = a * jnp.minimum(1.0, lim / jnp.maximum(norm, 1e-9))

        # Per-agent speed clamp: the scenario's scalar max_speed times
        # the class scale, shaped [capacity, 1] so ops/physics.
        # integrate's keepdims-speed comparison broadcasts row-wise.
        sp = p.scenario.replace(
            max_speed=(
                p.scenario.max_speed * p.cap_speed[cap_cls]
            )[:, None]
        )

        obstacles = p.obstacles if self.n_obstacles else None
        # r18 (ROADMAP item 4 speed note): without the tag sweep the
        # tick's formation derivation is provably the one the obs
        # pass would redo — formation_targets reads only leader/rank/
        # liveness fields, which physics never writes — so the tick
        # hands its ephemeral derived columns over and obs skips the
        # second derivation.  The tag sweep (static enable_tagging)
        # CAN flip liveness (killed evaders shift every higher-id
        # agent's formation rank), so tagging envs keep the post-tag
        # re-derivation — bitwise the pre-r18 path either way
        # (pinned in tests/test_envs.py).
        reuse_derived = not self.enable_tagging
        if reuse_derived:
            swarm, telem, derived = swarm_tick_dyn(
                prev, obstacles, self.cfg, params=sp,
                extra_force=a, return_derived=True,
            )
        else:
            swarm, telem = swarm_tick_dyn(
                prev, obstacles, self.cfg, params=sp,
                extra_force=a,
            )
            derived = None
            swarm = _pursuit_tag(swarm, p)

        from .scenarios import reward_switch

        # Class-conditional reward weight: r * 1.0 is bitwise r, so
        # the default table leaves every reward pin untouched.
        rewards = (
            reward_switch(prev, swarm, p, self.cfg)
            * p.cap_reward[cap_cls]
        )

        t_next = state.t + 1
        done = t_next >= p.max_steps
        dones = done | ~swarm.alive
        if auto_reset:
            key, rkey = jax.random.split(key)
            fresh = self.materialize(rkey, p)
            swarm = jax.tree_util.tree_map(
                lambda r, s: jnp.where(done, r, s), fresh, swarm
            )
            t_next = jnp.where(done, 0, t_next)
            if derived is not None:
                # A fresh state has no leader, so its derivation is
                # the identity on (target, has_target) — the reset
                # branch's derived columns come for free.
                derived = (
                    jnp.where(done, fresh.target, derived[0]),
                    jnp.where(done, fresh.has_target, derived[1]),
                )

        # r20: refresh the carried obs plan against the state obs will
        # read — AFTER the auto-reset select, so one refresh serves
        # both cases: an episode boundary's respawn jump / liveness
        # change fires the displacement/alive triggers like any other
        # motion (a second, unconditional fresh build per step for the
        # reset branch would cost exactly the bin+sort the carry
        # exists to amortize), and the Verlet exactness argument is
        # purely geometric — any state within skin/2 of the snapshot
        # reuses the plan legally, however it got there.
        plan = state.obs_plan
        if plan is not None:
            plan = refresh_plan(
                swarm.pos, swarm.alive, plan,
                rebuild_every=self.obs_rebuild_every,
            )
        new_state = EnvState(
            swarm=swarm, t=t_next, params=p, obs_plan=plan
        )
        info = {"done": done}
        if self.cfg.telemetry.enabled:
            info["telemetry"] = telem
        return (
            self.obs(swarm, derived, plan, p.cap_class),
            new_state, rewards, dones, info,
        )

    def replace(self, **kw) -> "SwarmMARLEnv":
        return dataclasses.replace(self, **kw)


def _pursuit_tag(swarm: SwarmState, p: EnvParams) -> SwarmState:
    """Post-tick tagging for the two-population scenarios: an alive
    evader (team 1) within ``tag_radius`` of any alive pursuer
    (team 0) is killed — the team id rides the alive mask, so the
    protocol's recovery machinery (dead-winner eviction, re-election
    around a tagged leader) reacts with no tick fork.  Mirrors
    ``ops/coordination.kill`` semantics (believers see the liveness
    flip; the ``alive_below`` cache is recounted).

    Data-gated on ``tag_radius > 0``: non-pursuit scenarios select
    the untouched masks bitwise, so the zero-action parity contract
    survives the shared heterogeneous program."""
    tag_on = p.tag_radius > 0.0
    pos, alive = swarm.pos, swarm.alive
    n = pos.shape[0]
    pursuer = alive & (p.team == 0)
    evader = alive & (p.team == 1)
    delta = pos[:, None, :] - pos[None, :, :]
    d2 = jnp.sum(delta * delta, axis=-1)
    close = d2 <= p.tag_radius * p.tag_radius
    tagged = evader & jnp.any(close & pursuer[None, :], axis=1)
    kill_mask = jnp.where(tag_on, tagged, False)

    # Believers in a tagged leader see the liveness flip immediately
    # (the kill() cache contract, by id value).
    dead_by_id = (
        jnp.zeros((n,), bool).at[swarm.agent_id].set(kill_mask)
    )
    lid_valid = (swarm.leader_id >= 0) & (swarm.leader_id < n)
    believed = lid_valid & dead_by_id[jnp.clip(swarm.leader_id, 0, n - 1)]
    return recount_alive_below(
        swarm.replace(
            alive=alive & ~kill_mask,
            leader_live=swarm.leader_live & ~believed,
        )
    )


def make_env_params(
    env: SwarmMARLEnv,
    reward_id: int,
    n_agents: Optional[int] = None,
    spread: float = 6.0,
    target: Optional[Tuple[float, float]] = None,
    task_pos: Sequence[Tuple[float, float]] = (),
    obstacles: Sequence[Tuple[float, float, float]] = (),
    team: Optional[Sequence[int]] = None,
    kill_ids: Sequence[int] = (),
    max_steps: int = 10_000,
    tag_radius: float = 0.0,
    cap_class: Optional[Sequence[int]] = None,
    cap_act: Optional[Sequence[float]] = None,
    cap_speed: Optional[Sequence[float]] = None,
    cap_reward: Optional[Sequence[float]] = None,
    **overrides,
) -> EnvParams:
    """One scenario's :class:`EnvParams` against ``env``'s static
    shapes — the host-side constructor every zoo entry goes through.

    ``n_agents`` (default: full capacity) rides the ``alive0`` mask;
    ``kill_ids`` injects initial faults (the recovery hook);
    ``task_pos`` must match ``env.n_tasks`` exactly (a shape);
    ``obstacles`` rows ``(cx, cy, r)`` up to ``env.n_obstacles``
    (missing rows park at the remote pad where their force is exactly
    zero); ``**overrides`` are
    :class:`~..serve.batched.ScenarioParams` fields (``k_att``,
    ``auction_eps``, ...).  ``n_agents=0`` is the dead FILLER
    scenario the bucket padding uses.

    ``cap_class``/``cap_act``/``cap_speed``/``cap_reward`` (r20) are
    the heterogeneous capability tables — per-agent class ids
    (``[capacity]``) and per-class act/speed/reward scales
    (``[env.n_cap_classes]`` each); ``None`` defaults to the
    homogeneous table (class 0 everywhere, every scale 1.0 — the
    bitwise-neutral default).  ``train/caps.py`` holds the builders."""
    cap = env.capacity
    n = cap if n_agents is None else int(n_agents)
    if not 0 <= n <= cap:
        raise ValueError(
            f"n_agents {n} outside [0, capacity={cap}]"
        )
    if not spread > 0:
        raise ValueError(f"spread must be > 0, got {spread}")
    if len(task_pos) != env.n_tasks:
        raise ValueError(
            f"task_pos has {len(task_pos)} rows; this env's task "
            f"board is n_tasks={env.n_tasks} (a shape — pad or "
            "rebuild the env)"
        )
    if len(obstacles) > env.n_obstacles:
        raise ValueError(
            f"{len(obstacles)} obstacles exceed the env's static "
            f"table n_obstacles={env.n_obstacles}"
        )
    bad = [k for k in kill_ids if not 0 <= k < max(n, 1)]
    if bad:
        raise ValueError(
            f"kill_ids {bad} outside [0, n_agents={n}) — fault "
            "injection must name real agents"
        )
    if max_steps < 1:
        raise ValueError(f"max_steps must be >= 1, got {max_steps}")
    if tag_radius > 0 and not env.enable_tagging:
        raise ValueError(
            f"tag_radius {tag_radius} > 0 but the env was built with "
            "enable_tagging=False — the tag sweep was statically "
            "compiled out, so the scenario would silently never tag; "
            "build the env with enable_tagging=True for pursuit "
            "scenarios"
        )

    n_cls = env.n_cap_classes
    cls_arr = np.zeros((cap,), np.int32)
    if cap_class is not None:
        cls_arr = np.asarray(cap_class, np.int32)
        if cls_arr.shape != (cap,):
            raise ValueError(
                f"cap_class must be [capacity]={cap} ints, got "
                f"{cls_arr.shape}"
            )
        if cls_arr.min(initial=0) < 0 or cls_arr.max(initial=0) >= n_cls:
            raise ValueError(
                f"cap_class ids outside [0, n_cap_classes={n_cls}) — "
                "class tables are shapes; build the env with enough "
                "classes"
            )

    def _cap_table(vals, name, positive):
        if vals is None:
            return np.ones((n_cls,), np.float32)
        arr = np.asarray(vals, np.float32)
        if arr.shape != (n_cls,):
            raise ValueError(
                f"{name} must be [n_cap_classes]={n_cls} floats, got "
                f"{arr.shape}"
            )
        if positive and not (arr > 0).all():
            raise ValueError(
                f"{name} scales must be > 0 (a zero scale would park "
                "a class with no way to express it in the reward)"
            )
        return arr

    act_tab = _cap_table(cap_act, "cap_act", positive=True)
    speed_tab = _cap_table(cap_speed, "cap_speed", positive=True)
    reward_tab = _cap_table(cap_reward, "cap_reward", positive=False)

    alive0 = np.zeros((cap,), bool)
    alive0[:n] = True
    if kill_ids:
        alive0[list(kill_ids)] = False
    team_arr = np.zeros((cap,), np.int32)
    if team is not None:
        team = np.asarray(team, np.int32)
        if team.shape != (cap,):
            raise ValueError(
                f"team must be [capacity]={cap} ints, got "
                f"{team.shape}"
            )
        team_arr = team
    obs_arr = np.full((env.n_obstacles, 3), 0.0, np.float32)
    obs_arr[:, 0] = _REMOTE
    obs_arr[:, 1] = _REMOTE
    for i, row in enumerate(obstacles):
        obs_arr[i] = np.asarray(row, np.float32)
    tpos = (
        np.asarray(task_pos, np.float32).reshape(env.n_tasks, 2)
        if env.n_tasks
        else np.zeros((0, 2), np.float32)
    )
    return EnvParams(
        scenario=scenario_params(env.cfg, **overrides),
        reward_id=jnp.asarray(reward_id, jnp.int32),
        spread=jnp.asarray(spread, jnp.float32),
        use_point=jnp.asarray(target is not None),
        point=jnp.asarray(
            target if target is not None else (0.0, 0.0), jnp.float32
        ),
        alive0=jnp.asarray(alive0),
        team=jnp.asarray(team_arr),
        task_pos=jnp.asarray(tpos),
        obstacles=jnp.asarray(obs_arr),
        max_steps=jnp.asarray(max_steps, jnp.int32),
        tag_radius=jnp.asarray(tag_radius, jnp.float32),
        cap_class=jnp.asarray(cls_arr),
        cap_act=jnp.asarray(act_tab),
        cap_speed=jnp.asarray(speed_tab),
        cap_reward=jnp.asarray(reward_tab),
    )


@watched(ENV_ROLLOUT_ENTRY)
@partial(
    jax.jit,
    static_argnames=(
        "env", "n_steps", "random_policy", "telemetry", "auto_reset",
    ),
)
def _env_rollout_impl(
    keys: jax.Array,
    params: EnvParams,
    env: SwarmMARLEnv,
    n_steps: int,
    random_policy: bool = False,
    telemetry: bool = False,
    auto_reset: bool = True,
):
    """``n_steps`` vmapped env steps under one ``lax.scan`` — the
    compiled MARL rollout.  ``keys`` is ``[S, 2]`` (one PRNG stream
    per scenario — never broadcast, the key-broadcast rule) and
    ``params`` ``[S]``-leaved; S heterogeneous scenarios step in one
    program (``reward_id`` dispatches via ``lax.switch``).

    ``random_policy=True`` draws uniform actions in
    ``[-act_limit, act_limit]^2`` per agent per step (the bench /
    smoke policy); False steps the zero action — BITWISE the pure
    protocol rollout.  Returns ``(states, rewards [T, S, capacity],
    dones [T, S, capacity])`` with the stacked ``[T, S]`` telemetry
    record appended when the static gate is on (disabled lowering
    byte-identical — the r10 contract, pinned in
    tests/test_envs.py)."""
    telem_on = telemetry or env.cfg.telemetry.enabled
    if telem_on and not env.cfg.telemetry.enabled:
        env = env.replace(cfg=env.cfg.replace(telemetry=TELEMETRY_ON))

    split = jax.vmap(lambda k: jax.random.split(k, 2))(keys)
    obs, states = jax.vmap(env.reset)(split[:, 0], params)

    def body(carry, _):
        lkeys, _obs, states = carry
        parts = jax.vmap(lambda k: jax.random.split(k, 3))(lkeys)
        lkeys, akeys, skeys = parts[:, 0], parts[:, 1], parts[:, 2]
        if random_policy:
            acts = jax.vmap(
                lambda ak: jax.random.uniform(
                    ak, (env.capacity, 2), jnp.float32,
                    minval=-env.act_limit, maxval=env.act_limit,
                )
            )(akeys)
        else:
            acts = jnp.zeros(
                _obs.shape[:2] + (2,), jnp.float32
            )
        obs, states, rew, dones, info = jax.vmap(
            lambda k, s, a: env.step(k, s, a, auto_reset=auto_reset)
        )(skeys, states, acts)
        telem = None
        if telem_on:
            telem = info["telemetry"]
        return (lkeys, obs, states), (rew, dones, telem)

    (_, obs, states), (rewards, dones, telem) = jax.lax.scan(
        body, (split[:, 1], obs, states), None, length=n_steps
    )
    out = (states, rewards, dones)
    if telem_on:
        if not n_steps:
            telem = None
        out = out + (telem,)
    return out


def env_rollout(
    keys: jax.Array,
    env: SwarmMARLEnv,
    params: EnvParams,
    n_steps: int,
    random_policy: bool = False,
    telemetry: bool = False,
    auto_reset: bool = True,
):
    """Public entry for the compiled env rollout (see
    :func:`_env_rollout_impl`).  ``keys`` must carry a leading
    scenario axis matching ``params`` (``[S, 2]``; build a batch of
    one with ``stack_env_params([p])`` and ``key[None]``)."""
    keys = jnp.asarray(keys)
    if keys.ndim != 2:
        raise ValueError(
            "env_rollout wants batched keys [S, 2] — one PRNG stream "
            f"per scenario; got shape {keys.shape} (wrap a single "
            "key with key[None] and stack_env_params([params]))"
        )
    return _env_rollout_impl(
        keys, params, env, n_steps, random_policy, telemetry,
        auto_reset,
    )
