"""Swarm-as-environment (r14): a JaxMARL-compatible RL facade over the
protocol tick.  See envs/core.py (``SwarmMARLEnv`` — pure
``reset``/``step``, fixed-shape per-agent obs, bounded steering
actions, ``where``-select auto-reset, the watched ``env-rollout``
compiled entry) and envs/scenarios.py (the scenario zoo: each scenario
is a params pytree + a reward id, never a fork of the tick)."""

from .core import (
    ENV_ROLLOUT_ENTRY,
    EnvParams,
    EnvState,
    SwarmMARLEnv,
    env_params_row,
    env_rollout,
    make_env_params,
    stack_env_params,
)
from .scenarios import (
    COVERAGE,
    OBSTACLE,
    PURSUIT,
    REWARD_NAMES,
    STATION,
    ZOO,
    coverage_foraging,
    filler_params,
    obstacle_field,
    pursuit_evasion,
    reward_switch,
    station_keeping,
    zoo_batch,
)

__all__ = [
    "COVERAGE",
    "ENV_ROLLOUT_ENTRY",
    "EnvParams",
    "EnvState",
    "OBSTACLE",
    "PURSUIT",
    "REWARD_NAMES",
    "STATION",
    "SwarmMARLEnv",
    "ZOO",
    "coverage_foraging",
    "env_params_row",
    "env_rollout",
    "filler_params",
    "make_env_params",
    "obstacle_field",
    "pursuit_evasion",
    "reward_switch",
    "stack_env_params",
    "station_keeping",
    "zoo_batch",
]
