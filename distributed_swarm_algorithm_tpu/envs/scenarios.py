"""The scenario zoo: each scenario is a params pytree + a reward id.

No scenario forks the tick.  A zoo entry is an
:class:`~.core.EnvParams` builder (spawn/team/task/obstacle tables +
:class:`~..serve.batched.ScenarioParams` gain overrides) plus one of
the reward functions below, selected at trace time by ``reward_id``
through ``lax.switch`` — so FOUR different scenarios vmap into ONE
compiled rollout, and a scenario is exactly the kind of data the
serve layer's bucket lattice already batches
(``serve/batched.env_rollouts``).

Reward functions share one signature ``(prev, cur, params, cfg) ->
[capacity] f32`` (per-agent, 0 on dead/pad slots; ``prev`` is the
pre-tick swarm so transition events — an evader tagged this step —
are observable).  They are read-only: reward computation can never
perturb the trajectory, which is what keeps the zero-action rollout
bitwise equal to the pure protocol.

The zoo (see docs/ENVIRONMENTS.md for the matrix):

- **station-keeping** (``STATION``): hold the spawn formation; reward
  is the negative distance to the (formation-derived) nav target —
  the protocol's own objective, so the pure protocol is already a
  strong baseline policy.
- **obstacle-field** (``OBSTACLE``): reach a shared goal through an
  obstacle line; the APF repulsion already exists, the reward adds a
  proximity penalty inside ``rho0`` on top of the goal distance.
- **pursuit-evasion** (``PURSUIT``): two populations via the per-agent
  team id riding the alive mask — pursuers close on the nearest
  evader, evaders open distance; a tagged evader is KILLED (alive bit
  cleared), so the election/allocation recovery machinery is
  stress-tested by adversarial motion, not a quiet arena.
- **coverage-foraging** (``COVERAGE``): reward rides the task
  -allocation auction — an agent scores for holding a task award
  (``ops/allocation.agent_task_view``) and for actually standing near
  the task it won, so the learned policy must cooperate with (not
  fight) the protocol's assignment mechanism.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from ..ops.allocation import agent_task_view
from ..state import SwarmState
from ..utils.config import SwarmConfig
from .core import EnvParams, SwarmMARLEnv, make_env_params

#: Reward registry indices — ``EnvParams.reward_id`` values.
STATION = 0
OBSTACLE = 1
PURSUIT = 2
COVERAGE = 3

REWARD_NAMES = (
    "station-keeping",
    "obstacle-field",
    "pursuit-evasion",
    "coverage-foraging",
)

#: Distance-shaping cap for the pursuit rewards: beyond this range the
#: gradient is noise, and an unbounded evader reward would reward
#: leaving the arena.
_PURSUIT_RANGE = 20.0
_FAR = 1.0e9


def _station_reward(prev: SwarmState, cur: SwarmState,
                    p: EnvParams, cfg: SwarmConfig) -> jax.Array:
    err = jnp.linalg.norm(cur.target - cur.pos, axis=-1)
    return jnp.where(cur.alive, -err, 0.0)


def _obstacle_reward(prev: SwarmState, cur: SwarmState,
                     p: EnvParams, cfg: SwarmConfig) -> jax.Array:
    base = _station_reward(prev, cur, p, cfg)
    if p.obstacles.shape[0] == 0:
        return base
    centers = p.obstacles[:, :2]
    radii = p.obstacles[:, 2]
    d = (
        jnp.linalg.norm(cur.pos[:, None, :] - centers[None, :, :],
                        axis=-1)
        - radii[None, :]
    )
    # Penalty ramps linearly inside the APF influence radius rho0 —
    # the same length scale the repulsion term acts on, so the reward
    # and the physics agree about what "too close" means.
    pen = jnp.sum(jnp.clip(1.0 - d / cfg.rho0, 0.0, 1.0), axis=1)
    return jnp.where(cur.alive, base - 2.0 * pen, 0.0)


def _pursuit_reward(prev: SwarmState, cur: SwarmState,
                    p: EnvParams, cfg: SwarmConfig) -> jax.Array:
    pos = cur.pos
    d = jnp.linalg.norm(pos[:, None, :] - pos[None, :, :], axis=-1)
    evader = cur.alive & (p.team == 1)
    pursuer = cur.alive & (p.team == 0)
    d_to_evader = jnp.min(
        jnp.where(evader[None, :], d, _FAR), axis=1
    )
    d_to_pursuer = jnp.min(
        jnp.where(pursuer[None, :], d, _FAR), axis=1
    )
    r_pursue = -jnp.minimum(d_to_evader, _PURSUIT_RANGE)
    r_evade = jnp.minimum(d_to_pursuer, _PURSUIT_RANGE)
    r = jnp.where(p.team == 0, r_pursue, r_evade)
    # A tagged evader's terminal penalty lands on the transition tick
    # (prev alive, now dead); afterwards the slot rewards 0.
    tagged_now = prev.alive & ~cur.alive & (p.team == 1)
    return jnp.where(
        cur.alive, r, jnp.where(tagged_now, -_PURSUIT_RANGE, 0.0)
    )


def _coverage_reward(prev: SwarmState, cur: SwarmState,
                     p: EnvParams, cfg: SwarmConfig) -> jax.Array:
    zero = jnp.zeros((cur.n_agents,), jnp.float32)
    if cur.n_tasks == 0:
        return zero
    my_task = agent_task_view(cur)                        # [N] i32
    won = my_task >= 0
    tpos = cur.task_pos[jnp.maximum(my_task, 0)]
    d = jnp.linalg.norm(tpos - cur.pos, axis=-1)
    # Holding an award is worth 1; standing on the task doubles it —
    # the auction decides WHO serves, the policy must actually GO.
    r = jnp.where(won, 1.0 + 1.0 / (1.0 + d), 0.0)
    return jnp.where(cur.alive, r, 0.0)


#: reward_id -> function, in registry order (REWARD_NAMES aligns).
REWARD_FNS = (
    _station_reward, _obstacle_reward, _pursuit_reward,
    _coverage_reward,
)


def reward_switch(prev: SwarmState, cur: SwarmState, p: EnvParams,
                  cfg: SwarmConfig) -> jax.Array:
    """Per-agent reward dispatched on the TRACED ``reward_id`` — under
    ``vmap`` the switch lowers to a select, so heterogeneous scenarios
    cost every branch but stay one compiled program (the same
    cond->select economics as the r13 vmapped auction)."""
    idx = jnp.clip(p.reward_id, 0, len(REWARD_FNS) - 1)
    return jax.lax.switch(
        idx,
        [lambda a, b, c, f=f: f(a, b, c, cfg) for f in REWARD_FNS],
        prev, cur, p,
    )


# ---------------------------------------------------------------------------
# Zoo builders — every entry goes through make_env_params, so the
# shapes are the env's statics and the gains are ScenarioParams data.


def station_keeping(env: SwarmMARLEnv, n_agents: Optional[int] = None,
                    spread: float = 6.0, max_steps: int = 10_000,
                    kill_ids=(), caps=None, **overrides) -> EnvParams:
    """Hold the spawn formation (the r12 quiet arena, as an env)."""
    return make_env_params(
        env, STATION, n_agents=n_agents, spread=spread,
        task_pos=[(0.0, 0.0)] * env.n_tasks,
        max_steps=max_steps, kill_ids=kill_ids, **(caps or {}),
        **overrides,
    )


def obstacle_field(env: SwarmMARLEnv, n_agents: Optional[int] = None,
                   spread: float = 4.0, max_steps: int = 10_000,
                   caps=None, **overrides) -> EnvParams:
    """Cross an obstacle line to a shared goal — APF repulsion is
    already in the tick; the reward adds the proximity penalty."""
    rows = [
        (6.0, -3.0, 1.0), (6.5, 0.0, 1.2), (6.0, 3.0, 1.0),
    ][: env.n_obstacles]
    return make_env_params(
        env, OBSTACLE, n_agents=n_agents, spread=spread,
        target=(12.0, 0.0), obstacles=rows,
        task_pos=[(0.0, 0.0)] * env.n_tasks,
        max_steps=max_steps, **(caps or {}), **overrides,
    )


def pursuit_evasion(env: SwarmMARLEnv, n_agents: Optional[int] = None,
                    spread: float = 8.0, tag_radius: float = 1.0,
                    max_steps: int = 10_000, caps=None,
                    **overrides) -> EnvParams:
    """Two populations: the lower half of the id range pursues, the
    upper half evades; a tagged evader dies through the alive mask
    (the recovery machinery's adversarial workout).

    ``caps`` (r20): a capability-table kwargs dict
    (``train/caps.py:pursuit_caps`` builds the canonical asymmetric
    one — per-class act/speed/reward scales aligned with the team
    split) merged into :func:`~.core.make_env_params`; ``None`` keeps
    the homogeneous bitwise-neutral default."""
    cap = env.capacity
    n = cap if n_agents is None else int(n_agents)
    team = [0] * cap
    for i in range(n // 2, n):
        team[i] = 1
    return make_env_params(
        env, PURSUIT, n_agents=n_agents, spread=spread, team=team,
        tag_radius=tag_radius,
        task_pos=[(0.0, 0.0)] * env.n_tasks,
        max_steps=max_steps, **(caps or {}), **overrides,
    )


def coverage_foraging(env: SwarmMARLEnv,
                      n_agents: Optional[int] = None,
                      spread: float = 6.0, max_steps: int = 10_000,
                      caps=None, **overrides) -> EnvParams:
    """Serve the task board: the auction (or greedy arbiter) awards,
    the reward pays for holding an award and standing on it."""
    if env.n_tasks == 0:
        raise ValueError(
            "coverage-foraging needs a task board: build the env "
            "with n_tasks >= 1 (the reward rides the allocation "
            "award)"
        )
    import math

    ring = []
    for i in range(env.n_tasks):
        ang = 2.0 * math.pi * i / env.n_tasks
        ring.append((8.0 * math.cos(ang), 8.0 * math.sin(ang)))
    overrides.setdefault("utility_threshold", 2.0)
    return make_env_params(
        env, COVERAGE, n_agents=n_agents, spread=spread,
        task_pos=ring, max_steps=max_steps, **(caps or {}),
        **overrides,
    )


def filler_params(env: SwarmMARLEnv) -> EnvParams:
    """The dead FILLER scenario bucket padding dispatches: every slot
    dead, station reward — it ticks along at full shape and its rows
    are discarded (the serve/buckets.py padding contract)."""
    return make_env_params(
        env, STATION, n_agents=0,
        task_pos=[(0.0, 0.0)] * env.n_tasks,
    )


#: name -> builder, the zoo surface examples/benches iterate.
ZOO = {
    "station-keeping": station_keeping,
    "obstacle-field": obstacle_field,
    "pursuit-evasion": pursuit_evasion,
    "coverage-foraging": coverage_foraging,
}


def zoo_batch(env: SwarmMARLEnv, **common) -> EnvParams:
    """The whole zoo as one stacked ``[4]``-leaved batch — the
    heterogeneous ONE-compiled-program workload (requires
    ``env.n_tasks >= 1`` for the coverage entry and
    ``env.n_obstacles >= 1`` for the obstacle entry to be
    distinguishable)."""
    from .core import stack_env_params

    return stack_env_params(
        [ZOO[name](env, **common) for name in REWARD_NAMES]
    )
