"""Multi-device execution: GSPMD sharding + explicit shard_map collectives.

Two complementary paths, per the scaling-book recipe ("pick a mesh,
annotate shardings, let XLA insert collectives"):

1. **GSPMD (default)** — ``shard_swarm`` / ``shard_pso`` place the state
   pytree on a mesh with the agent/particle axis sharded; the *same* jitted
   kernels (``swarm_tick``, ``pso_run``) then run partitioned, and XLA
   lowers every global reduction (election max-id, allocation argmax, gbest
   argmin) to ICI collectives automatically.

2. **Explicit shard_map** — ``pso_step_shmap`` and ``elect_shmap`` spell
   the collectives out (``lax.pmin``/``lax.pmax``/``lax.psum``) for the
   protocol-level reductions.  This is the TPU-native replacement for the
   reference's never-implemented UDP/TCP transport (agent.py:188-195) and
   its wire protocol (agent.py:184-214): the "message" is a reduction over
   the mesh axis, and delivery is the ICI fabric.
"""

from __future__ import annotations

from functools import partial
from typing import Callable

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..ops import pso as _pso
from ..state import NO_LEADER, SwarmState
from ..utils.compat import shard_map
from ..utils.compile_watch import watched
from .mesh import AGENT_AXIS

_BIG_I32 = jnp.iinfo(jnp.int32).max


def _exchange_best(loc_fit, loc_pos, best_fit, best_pos, dev, axis):
    """Cross-device global-best exchange used by every shmap driver:
    ``pmin`` the per-shard best value, break ties to the lowest device
    index, ``psum``-broadcast the winner's position, and merge into the
    carried incumbent.  Returns ``(best_fit, best_pos)``."""
    gmin = lax.pmin(loc_fit, axis)
    mine = loc_fit == gmin
    win = lax.pmin(jnp.where(mine, dev, _BIG_I32), axis)
    gcand = lax.psum(jnp.where(dev == win, loc_pos, 0.0), axis)
    better = gmin < best_fit
    return (
        jnp.where(better, gmin, best_fit),
        jnp.where(better, gcand, best_pos),
    )


def _tree_shard_dim0(tree, mesh: Mesh, axis: str, n: int):
    """Shard every leaf whose dim 0 == n over ``axis``; replicate the rest."""
    sharded = NamedSharding(mesh, P(axis))
    repl = NamedSharding(mesh, P())

    def place(leaf):
        if getattr(leaf, "ndim", 0) >= 1 and leaf.shape[0] == n:
            return jax.device_put(leaf, sharded)
        return jax.device_put(leaf, repl)

    return jax.tree_util.tree_map(place, tree)


def shard_swarm(state: SwarmState, mesh: Mesh, axis: str = AGENT_AXIS):
    """Place a SwarmState with the agent axis sharded over the mesh.

    After this, calling the ordinary jitted ``swarm_tick`` runs SPMD: XLA
    partitions the per-agent updates and inserts all-reduces for the
    election/heartbeat/allocation reductions.  Requires n_agents % devices
    == 0 (pad the swarm with dead agents otherwise — alive-masking makes
    padding free).

    ``separation_mode='hashgrid'`` on a mesh runs the PORTABLE path
    (the fused kernel is a single-device program — the driver guard in
    models/swarm.py re-dispatches 'auto' and rejects forced 'pallas').
    Since r8 that path consumes the ONE shared spatial build
    (ops/hashgrid_plan.py) per tick: the same collective classes as
    the pre-plan tick — the cell sort is XLA's gather-sort-reslice
    exactly like the cadenced window re-sort, and the CSR occupancy
    scatter targets the bounded, replicated ``[g*g]`` key space — but
    built once instead of once per force term, so the per-tick
    all-gather count does not grow with the number of plan consumers
    (separation + moments field + rescue).
    """
    return _tree_shard_dim0(state, mesh, axis, state.n_agents)


def shard_pso(state: _pso.PSOState, mesh: Mesh, axis: str = AGENT_AXIS):
    """Place a PSOState with the particle axis sharded over the mesh."""
    return _tree_shard_dim0(state, mesh, axis, state.pos.shape[0])


def pad_to_devices(n: int, n_devices: int) -> int:
    """Smallest multiple of n_devices ≥ n."""
    return -(-n // n_devices) * n_devices


# ---------------------------------------------------------------------------
# Explicit-collective path (shard_map)
# ---------------------------------------------------------------------------


def pso_step_shmap(
    state: _pso.PSOState,
    objective: Callable,
    mesh: Mesh,
    axis: str = AGENT_AXIS,
    w: float = _pso.W,
    c1: float = _pso.C1,
    c2: float = _pso.C2,
    half_width: float = 5.12,
    vmax_frac: float = 0.5,
    telemetry: bool = False,
):
    """One PSO step with the cross-device gbest reduction written as
    explicit collectives: local argmin → ``lax.pmin`` for the value →
    min-device-index tie-break → ``lax.psum`` to broadcast the winning
    position.  Semantically identical to the GSPMD path.

    ``telemetry=True`` (r11, static gate): returns ``(state, telem)``
    — one ``utils/telemetry.TickTelemetry`` reduced over the mesh
    axis with the same collective classes as the step itself
    (``psum`` counts, ``pmax`` gauges); ``leader_id`` is the device
    index holding the incumbent global best, the residency pair the
    per-shard particle counts.  Collection only READS step outputs,
    so the carried state is bitwise-identical either way."""

    shard = P(axis)
    spec = _pso.PSOState(
        pos=shard, vel=shard, pbest_pos=shard, pbest_fit=shard,
        gbest_pos=P(), gbest_fit=P(), key=P(), iteration=P(),
    )

    @partial(
        shard_map, mesh=mesh, in_specs=(spec,),
        out_specs=(spec, P()) if telemetry else spec,
        check_vma=False,
    )
    def step(s: _pso.PSOState):
        # Per-device keys: fold in the device index so shards draw
        # independent randomness from one replicated key.
        dev = lax.axis_index(axis)
        key = jax.random.fold_in(s.key, dev)
        key, k1, k2 = jax.random.split(key, 3)
        shape = s.pos.shape
        r1 = jax.random.uniform(k1, shape, s.pos.dtype)
        r2 = jax.random.uniform(k2, shape, s.pos.dtype)

        vel = (
            w * s.vel
            + c1 * r1 * (s.pbest_pos - s.pos)
            + c2 * r2 * (s.gbest_pos[None, :] - s.pos)
        )
        vmax = half_width * vmax_frac
        vel = jnp.clip(vel, -vmax, vmax)
        pos = jnp.clip(s.pos + vel, -half_width, half_width)

        fit = objective(pos)
        improved = fit < s.pbest_fit
        pbest_fit = jnp.where(improved, fit, s.pbest_fit)
        pbest_pos = jnp.where(improved[:, None], pos, s.pbest_pos)

        # Local best …
        loc = jnp.argmin(pbest_fit)
        loc_fit = pbest_fit[loc]
        loc_pos = pbest_pos[loc]
        # … global best via ICI collectives.
        gbest_fit, gbest_pos = _exchange_best(
            loc_fit, loc_pos, s.gbest_fit, s.gbest_pos, dev, axis
        )

        # Keep the carried key replicated (every shard advances the same
        # base key; shards re-diversify via fold_in above).
        base_key, _ = jax.random.split(s.key)
        out = _pso.PSOState(
            pos=pos, vel=vel, pbest_pos=pbest_pos, pbest_fit=pbest_fit,
            gbest_pos=gbest_pos, gbest_fit=gbest_fit, key=base_key,
            iteration=s.iteration + 1,
        )
        if telemetry:  # static TelemetryConfig-style gate
            from ..utils.telemetry import (
                mesh_reduce_telemetry,
                optimizer_tick_telemetry,
            )

            n_loc = jnp.asarray(pos.shape[0], jnp.int32)
            speed = jnp.linalg.norm(vel, axis=-1)
            finite = (
                jnp.all(jnp.isfinite(pos))
                & jnp.all(jnp.isfinite(vel))
                & jnp.all(jnp.isfinite(fit))
            )
            holder = lax.pmin(
                jnp.where(loc_fit == gbest_fit, dev, _BIG_I32), axis
            )
            local = optimizer_tick_telemetry(
                out.iteration,
                n_loc,
                speed_max=jnp.max(speed),
                speed_mean=jnp.mean(speed),
                nonfinite=~finite,
                best_shard=jnp.where(
                    holder == _BIG_I32, NO_LEADER, holder
                ),
                shard_max=n_loc,
            )
            # The reducer's pmin/pmax over per-shard counts fills the
            # residency pair; best_shard/nonfinite are replicated.
            return out, mesh_reduce_telemetry(local, axis)
        return out

    return step(state)


@watched("pso-shmap")
@partial(
    jax.jit,
    static_argnames=(
        "objective", "mesh", "n_steps", "axis", "w", "c1", "c2",
        "half_width", "vmax_frac", "telemetry",
    ),
)
def pso_run_shmap(
    state: _pso.PSOState,
    objective: Callable,
    mesh: Mesh,
    n_steps: int,
    axis: str = AGENT_AXIS,
    w: float = _pso.W,
    c1: float = _pso.C1,
    c2: float = _pso.C2,
    half_width: float = 5.12,
    vmax_frac: float = 0.5,
    telemetry: bool = False,
):
    """``n_steps`` explicit-collective PSO steps under one ``lax.scan`` —
    one dispatch for the whole rollout (important on oversubscribed hosts:
    CPU-backend collective rendezvous is time-limited, so per-step Python
    dispatch of 8-way collectives is avoidable flake surface).

    ``telemetry=True`` (r11, static gate): the per-step mesh-reduced
    records ride the scan as stacked ys and the return becomes
    ``(state, telem)`` — see ``pso_step_shmap``."""

    def body(s, _):
        out = pso_step_shmap(
            s, objective, mesh, axis, w, c1, c2, half_width, vmax_frac,
            telemetry=telemetry,
        )
        if telemetry:
            return out
        return out, None

    state, telem = jax.lax.scan(body, state, None, length=n_steps)
    if telemetry:
        return state, telem
    return state


@partial(
    jax.jit,
    static_argnames=(
        "objective_name", "mesh", "n_steps", "axis", "w", "c1", "c2",
        "half_width", "vmax_frac", "steps_per_kernel", "tile_n", "rng",
        "interpret",
    ),
)
def fused_pso_run_shmap(
    state: _pso.PSOState,
    objective_name: str,
    mesh: Mesh,
    n_steps: int,
    axis: str = AGENT_AXIS,
    w: float = _pso.W,
    c1: float = _pso.C1,
    c2: float = _pso.C2,
    half_width: float = 5.12,
    vmax_frac: float = 0.5,
    steps_per_kernel: int = 8,
    tile_n: int | None = None,
    rng: str = "tpu",
    interpret: bool = False,
) -> _pso.PSOState:
    """Multi-chip fused-Pallas PSO: each device runs ``steps_per_kernel``
    in-VMEM iterations of the fused kernel (ops/pallas/pso_fused.py) on its
    particle shard, then the shards exchange the global best over ICI
    (``pmin`` value + ``psum`` position broadcast) — the per-block gbest
    staleness of the single-chip kernel and the cross-device reduction
    cadence coincide, so multi-chip costs no extra semantic delay.

    N is padded (cyclic particle duplication, optimum-preserving) to
    devices × lane-tile.  On CPU meshes pass ``rng="host",
    interpret=True`` (tests do).  All padding/seed/loop/reassembly
    invariants are shared with the single-chip driver via the helpers in
    ops/pallas/pso_fused.py; only the gbest merge differs (collectives
    here, local compare there).
    """
    from ..ops.pallas.common import ceil_to
    from ..ops.pallas.pso_fused import (
        _auto_tile,
        best_of_block,
        fused_pso_step_t,
        host_uniforms,
        prep_padded_t,
        rebuild_state,
        run_blocks,
        seed_base,
    )

    n, d = state.pos.shape
    n_dev = mesh.shape[axis]
    if rng == "host":
        steps_per_kernel = 1
    if tile_n is None:
        tile_n = _auto_tile(ceil_to(max(d, 8), 8))
    tile_n = min(tile_n, ceil_to(-(-n // n_dev), 128))
    n_pad = ceil_to(n, n_dev * tile_n)
    n_tiles_local = (n_pad // n_dev) // tile_n

    pos_t, vel_t, bpos_t, bfit_t = prep_padded_t(state, n_pad)
    seed0 = seed_base(state.key)
    host_key = jax.random.fold_in(state.key, 0x5EED)

    col = P(None, axis)   # transposed layout: particles on the last axis

    @partial(
        shard_map,
        mesh=mesh,
        in_specs=(col, col, col, col, P(), P()),
        out_specs=(col, col, col, col, P(), P()),
        check_vma=False,
    )
    def run(pos_t, vel_t, bpos_t, bfit_t, gpos, gfit):
        dev = lax.axis_index(axis)

        def block(carry, call_i, k):
            pos_t, vel_t, bpos_t, bfit_t, gpos, gfit = carry
            seed = seed0 + (call_i * n_dev + dev) * n_tiles_local
            r1 = r2 = None
            if rng == "host":
                r1, r2 = host_uniforms(
                    host_key, call_i, pos_t.shape, fold=dev
                )
            pos_t, vel_t, bpos_t, bfit_t = fused_pso_step_t(
                seed, gpos[:, None], pos_t, vel_t, bpos_t, bfit_t, r1, r2,
                objective_name=objective_name, w=w, c1=c1, c2=c2,
                half_width=half_width, vmax_frac=vmax_frac, tile_n=tile_n,
                rng=rng, interpret=interpret, k_steps=k, track_best=False,
            )
            # Per-shard best, then cross-device gbest exchange.
            loc_fit, loc_pos = best_of_block(bfit_t, bpos_t)
            gfit, gpos = _exchange_best(
                loc_fit, loc_pos, gfit, gpos, dev, axis
            )
            return (pos_t, vel_t, bpos_t, bfit_t, gpos, gfit)

        return run_blocks(
            block,
            (pos_t, vel_t, bpos_t, bfit_t, gpos, gfit),
            n_steps, steps_per_kernel,
        )

    carry = run(
        pos_t, vel_t, bpos_t, bfit_t,
        state.gbest_pos.astype(jnp.float32),
        state.gbest_fit.astype(jnp.float32),
    )
    return rebuild_state(state, *carry, n_steps)


@partial(
    jax.jit,
    static_argnames=(
        "objective_name", "mesh", "n_steps", "axis", "half_width",
        "f_min", "f_max", "alpha", "gamma", "r0", "sigma_local",
        "steps_per_kernel", "tile_n", "rng", "interpret",
    ),
)
def fused_bat_run_shmap(
    state,
    objective_name: str,
    mesh: Mesh,
    n_steps: int,
    axis: str = AGENT_AXIS,
    half_width: float = 5.12,
    f_min: float | None = None,
    f_max: float | None = None,
    alpha: float | None = None,
    gamma: float | None = None,
    r0: float | None = None,
    sigma_local: float | None = None,
    steps_per_kernel: int = 8,
    tile_n: int | None = None,
    rng: str = "tpu",
    interpret: bool = False,
):
    """Multi-chip fused-Pallas bat colony (ops/pallas/bat_fused.py):
    each device runs ``steps_per_kernel`` in-VMEM generations on its bat
    shard, then the shards exchange the two global quantities over ICI —
    the incumbent best (``pmin`` value + ``psum`` position broadcast,
    exactly like the PSO driver) and the mean loudness (``pmean`` of the
    per-shard means; shards are equal-sized so that IS the colony mean).
    The per-block staleness of the single-chip kernel and the
    cross-device cadence coincide, so multi-chip costs no extra
    semantic delay.  On CPU meshes pass ``rng="host", interpret=True``.
    """
    from ..ops.bat import ALPHA, F_MAX, F_MIN, GAMMA, R0, SIGMA_LOCAL
    from ..ops.pallas.bat_fused import (
        bat_host_uniforms,
        fused_bat_step_t,
        rebuild_bat_state,
    )
    from ..ops.pallas.common import ceil_to, cyclic_pad_rows
    from ..ops.pallas.pso_fused import (
        _auto_tile,
        best_of_block,
        run_blocks,
        seed_base,
    )

    f_min = F_MIN if f_min is None else f_min
    f_max = F_MAX if f_max is None else f_max
    alpha = ALPHA if alpha is None else alpha
    gamma = GAMMA if gamma is None else gamma
    r0 = R0 if r0 is None else r0
    sigma_local = SIGMA_LOCAL if sigma_local is None else sigma_local

    n, d = state.pos.shape
    n_dev = mesh.shape[axis]
    if rng == "host":
        steps_per_kernel = 1
    if tile_n is None:
        tile_n = _auto_tile(ceil_to(max(d, 8), 8))
    tile_n = min(tile_n, ceil_to(-(-n // n_dev), 128))
    n_pad = ceil_to(n, n_dev * tile_n)
    n_tiles_local = (n_pad // n_dev) // tile_n

    pos_t = cyclic_pad_rows(state.pos, n_pad).T
    vel_t = cyclic_pad_rows(state.vel, n_pad).T
    fit_t = cyclic_pad_rows(state.fit, n_pad)[None, :]
    loud_t = cyclic_pad_rows(state.loudness, n_pad)[None, :]
    pulse_t = cyclic_pad_rows(state.pulse, n_pad)[None, :]
    seed0 = seed_base(state.key)
    host_key = jax.random.fold_in(state.key, 0xBA7)

    col = P(None, axis)

    @partial(
        shard_map,
        mesh=mesh,
        in_specs=(col, col, col, col, col, P(), P()),
        out_specs=(col, col, col, col, col, P(), P()),
        check_vma=False,
    )
    def run(pos_t, vel_t, fit_t, loud_t, pulse_t, bpos, bfit):
        dev = lax.axis_index(axis)

        def block(carry, call_i, k):
            pos_t, vel_t, fit_t, loud_t, pulse_t, bpos, bfit, it = carry
            scalars = jnp.stack(
                [seed0 + (call_i * n_dev + dev) * n_tiles_local, it]
            )
            rb = rw = re = ra = None
            if rng == "host":
                rb, rw, re, ra = bat_host_uniforms(
                    host_key, call_i, fit_t.shape, pos_t.shape, fold=dev
                )
            # Colony mean loudness: pmean of per-shard means (equal
            # shard sizes).  Padding duplicates are legal bats, so the
            # padded mean deviates only by duplicate weighting.
            mean_a = lax.pmean(jnp.mean(loud_t), axis)
            pos_t, vel_t, fit_t, loud_t, pulse_t = fused_bat_step_t(
                scalars, bpos[:, None], mean_a,
                pos_t, vel_t, fit_t, loud_t, pulse_t, rb, rw, re, ra,
                objective_name=objective_name, half_width=half_width,
                f_min=f_min, f_max=f_max, alpha=alpha, gamma=gamma,
                r0=r0, sigma_local=sigma_local, tile_n=tile_n, rng=rng,
                interpret=interpret, k_steps=k,
            )
            loc_fit, loc_pos = best_of_block(fit_t, pos_t)
            bfit, bpos = _exchange_best(
                loc_fit, loc_pos, bfit, bpos, dev, axis
            )
            return (
                pos_t, vel_t, fit_t, loud_t, pulse_t, bpos, bfit, it + k
            )

        carry = run_blocks(
            block,
            (pos_t, vel_t, fit_t, loud_t, pulse_t, bpos, bfit,
             state.iteration),
            n_steps, steps_per_kernel,
        )
        return carry[:7]

    carry = run(
        pos_t, vel_t, fit_t, loud_t, pulse_t,
        state.best_pos.astype(jnp.float32),
        state.best_fit.astype(jnp.float32),
    )
    return rebuild_bat_state(state, *carry, n_steps)


@partial(
    jax.jit,
    static_argnames=(
        "objective", "mesh", "n_steps", "n", "axis", "half_width",
        "sigma", "lr", "momentum",
    ),
)
def es_run_shmap(
    state,
    objective,
    mesh: Mesh,
    n_steps: int,
    n: int = 256,
    axis: str = AGENT_AXIS,
    half_width: float = 5.12,
    sigma: float | None = None,
    lr: float | None = None,
    momentum: float | None = None,
):
    """Multi-chip OpenAI-ES — the canonical distributed-ES design
    (Salimans et al. 2017) on ICI: every device draws its own antithetic
    perturbation shard from a device-folded key and evaluates it
    locally; the only cross-device traffic per generation is the
    ``psum`` of the partial gradient estimate ``shaped^T @ eps`` plus
    the best-sample exchange — O(D) bytes, independent of population
    size.  Rank shaping needs the global fitness vector, so fitnesses
    are ``all_gather``ed ([n] scalars — also tiny).

    ``n`` is the GLOBAL population (must divide by mesh size, halves
    antithetic per device).  Results match the single-chip ``es_run``
    semantics (different RNG stream).
    """
    from ..ops.es import ESState, LR, MOMENTUM, SIGMA, centered_ranks

    sigma = SIGMA if sigma is None else sigma
    lr = LR if lr is None else lr
    momentum = MOMENTUM if momentum is None else momentum
    n_dev = mesh.shape[axis]
    if n % (2 * n_dev):
        raise ValueError(
            f"global population n ({n}) must be a multiple of "
            f"2 * devices ({2 * n_dev})"
        )
    n_loc = n // n_dev
    d = state.mean.shape[0]
    s = sigma * half_width

    @partial(
        shard_map,
        mesh=mesh,
        in_specs=(P(), P(), P(), P(), P()),
        out_specs=(P(), P(), P(), P(), P()),
        check_vma=False,
    )
    def run(mean, mom, best_pos, best_fit, key):
        dev = lax.axis_index(axis)

        def step(carry, _):
            mean, mom, best_pos, best_fit, key = carry
            key, kd = jax.random.split(key)
            kd = jax.random.fold_in(kd, dev)
            eps_half = jax.random.normal(
                kd, (n_loc // 2, d), mean.dtype
            )
            eps = jnp.concatenate([eps_half, -eps_half], axis=0)
            pop = jnp.clip(mean + s * eps, -half_width, half_width)
            fit = objective(pop)                        # [n_loc]

            # Global centered ranks need every fitness; the gathered
            # vector is n scalars — negligible next to the [n, D] work
            # that stayed device-local.
            all_fit = lax.all_gather(fit, axis)         # [n_dev, n_loc]
            shaped_all = centered_ranks(all_fit.reshape(-1))
            shaped = lax.dynamic_slice(
                shaped_all, (dev * n_loc,), (n_loc,)
            )
            grad = lax.psum((shaped @ eps) / (n * s), axis)
            mom = momentum * mom - lr * half_width * grad
            mean = jnp.clip(mean + mom, -half_width, half_width)

            b = jnp.argmin(fit)
            best_fit, best_pos = _exchange_best(
                fit[b], pop[b], best_fit, best_pos, dev, axis
            )
            mean_fit = objective(mean[None, :])[0]
            better_mean = mean_fit < best_fit
            best_fit = jnp.where(better_mean, mean_fit, best_fit)
            best_pos = jnp.where(better_mean, mean, best_pos)
            return (mean, mom, best_pos, best_fit, key), None

        carry, _ = jax.lax.scan(
            step, (mean, mom, best_pos, best_fit, key), None,
            length=n_steps,
        )
        return carry

    mean, mom, best_pos, best_fit, key = run(
        state.mean, state.mom, state.best_pos, state.best_fit, state.key
    )
    return ESState(
        mean=mean,
        mom=mom,
        best_pos=best_pos,
        best_fit=best_fit,
        key=key,
        iteration=state.iteration + n_steps,
    )


@partial(
    jax.jit,
    static_argnames=(
        "objective_name", "mesh", "n_steps", "axis", "half_width",
        "t_max", "steps_per_kernel", "tile_n", "rng", "interpret",
    ),
)
def fused_gwo_run_shmap(
    state,
    objective_name: str,
    mesh: Mesh,
    n_steps: int,
    axis: str = AGENT_AXIS,
    half_width: float = 5.12,
    t_max: int = 500,
    steps_per_kernel: int = 8,
    tile_n: int | None = None,
    rng: str = "tpu",
    interpret: bool = False,
):
    """Multi-chip fused-Pallas GWO: each device runs ``steps_per_kernel``
    in-VMEM generations on its wolf shard; between blocks the three
    leaders are re-elected globally — each shard contributes its local
    top-3 (vs the incumbents) via ``all_gather`` ([n_dev, 3] candidates,
    O(D) bytes) and every shard deterministically re-ranks the same
    pool.  Leader staleness equals the single-chip kernel's per-block
    delay, so multi-chip costs no extra semantic lag."""
    from ..ops.gwo import GWOState
    from ..ops.pallas.common import ceil_to, cyclic_pad_rows
    from ..ops.pallas.gwo_fused import fused_gwo_step_t
    from ..ops.pallas.pso_fused import (
        _auto_tile,
        host_uniforms,
        run_blocks,
        seed_base,
    )

    n, d = state.pos.shape
    n_dev = mesh.shape[axis]
    if rng == "host":
        steps_per_kernel = 1
    if tile_n is None:
        tile_n = _auto_tile(ceil_to(max(8 * d, 8), 8))
    tile_n = min(tile_n, ceil_to(-(-n // n_dev), 128))
    n_pad = ceil_to(n, n_dev * tile_n)
    n_tiles_local = (n_pad // n_dev) // tile_n

    pos_t = cyclic_pad_rows(state.pos, n_pad).T
    fit_t = cyclic_pad_rows(state.fit, n_pad)[None, :]
    seed0 = seed_base(state.key)
    host_key = jax.random.fold_in(state.key, 0x6E0)

    col = P(None, axis)

    @partial(
        shard_map,
        mesh=mesh,
        in_specs=(col, col, P(), P()),
        out_specs=(col, col, P(), P()),
        check_vma=False,
    )
    def run(pos_t, fit_t, leaders, leader_fit):
        dev = lax.axis_index(axis)

        def block(carry, call_i, k):
            pos_t, fit_t, leaders, leader_fit, it = carry
            scalars = jnp.stack(
                [seed0 + (call_i * n_dev + dev) * n_tiles_local, it]
            )
            ra = rc = None
            if rng == "host":
                ra, rc = host_uniforms(
                    host_key, call_i, (3 * d,) + pos_t.shape[1:],
                    fold=dev,
                )
            pos_t, fit_t = fused_gwo_step_t(
                scalars, leaders, pos_t, ra, rc,
                objective_name=objective_name, half_width=half_width,
                t_max=t_max, tile_n=tile_n, rng=rng,
                interpret=interpret, k_steps=k,
            )
            # Each shard contributes its PACK-local top-3 only; the
            # replicated incumbents join the pool exactly once in the
            # global re-rank (gathering incumbents from every shard
            # would flood the pool with n_dev duplicates and collapse
            # alpha/beta/delta into copies of one wolf).
            _, loc3 = jax.lax.top_k(-fit_t[0], 3)
            cand_fit = jnp.concatenate([
                leader_fit,
                lax.all_gather(fit_t[0, loc3], axis).reshape(-1),
            ])                                    # [3 + n_dev * 3]
            cand_pos = jnp.concatenate([
                leaders,
                lax.all_gather(pos_t.T[loc3], axis).reshape(-1, d),
            ], axis=0)
            _, top3 = jax.lax.top_k(-cand_fit, 3)
            return (
                pos_t, fit_t, cand_pos[top3], cand_fit[top3], it + k
            )

        carry = run_blocks(
            block,
            (pos_t, fit_t, leaders, leader_fit, state.iteration),
            n_steps, steps_per_kernel,
        )
        return carry[:4]

    pos_t, fit_t, leaders, leader_fit = run(
        pos_t, fit_t,
        state.leaders.astype(jnp.float32),
        state.leader_fit.astype(jnp.float32),
    )
    dt = state.pos.dtype
    return GWOState(
        pos=pos_t.T[:n].astype(dt),
        fit=fit_t[0, :n].astype(state.fit.dtype),
        leaders=leaders.astype(state.leaders.dtype),
        leader_fit=leader_fit.astype(state.leader_fit.dtype),
        key=jax.random.fold_in(state.key, n_steps),
        iteration=state.iteration + n_steps,
    )


@partial(
    jax.jit,
    static_argnames=(
        "objective_name", "mesh", "n_steps", "axis", "f", "cr",
        "half_width", "steps_per_kernel", "tile_n", "rng", "interpret",
    ),
)
def fused_de_run_shmap(
    state,
    objective_name: str,
    mesh: Mesh,
    n_steps: int,
    axis: str = AGENT_AXIS,
    f: float | None = None,
    cr: float | None = None,
    half_width: float = 5.12,
    steps_per_kernel: int = 8,
    tile_n: int | None = None,
    rng: str = "tpu",
    interpret: bool = False,
):
    """Multi-chip fused-Pallas DE: each device runs rotational-donor DE
    blocks (ops/pallas/de_fused.py) on its population shard; the global
    best is exchanged over ICI per block (``pmin`` + ``psum``
    broadcast).  Donor pools are SHARD-LOCAL between exchanges — the
    mesh behaves like an island model whose islands share their best
    every ``steps_per_kernel`` generations, the same semantic lag class
    as every other fused shmap driver here.  Each shard needs >= 4 lane
    tiles for distinct donor shifts (n >= devices * 512)."""
    from ..ops.de import DEState, CR as _CR, F as _F
    from ..ops.pallas.common import ceil_to, cyclic_pad_rows
    from ..ops.pallas.de_fused import (
        _auto_tile,
        _distinct_tile_shifts,
        best_of_block,
        fused_de_step_t,
        host_uniforms,
        run_blocks,
        seed_base,
        shrink_tile_for_donors,
    )

    f = _F if f is None else f
    cr = _CR if cr is None else cr
    n, d = state.pos.shape
    n_dev = mesh.shape[axis]
    if rng == "host":
        steps_per_kernel = 1
    steps_per_kernel = min(steps_per_kernel, 32)   # VMEM (see de_fused)
    if tile_n is None:
        tile_n = _auto_tile(ceil_to(max(d, 8), 8))
    tile_n = min(tile_n, ceil_to(-(-n // n_dev), 128))
    tile_n, n_pad, n_tiles_local = shrink_tile_for_donors(
        n, tile_n, per_shard=n_dev
    )

    pos_t = cyclic_pad_rows(state.pos, n_pad).T
    fit_t = cyclic_pad_rows(state.fit, n_pad)[None, :]
    seed0 = seed_base(state.key)
    host_key = jax.random.fold_in(state.key, 0xDE)
    shift_key = jax.random.fold_in(state.key, 0x5F1F7)

    col = P(None, axis)

    @partial(
        shard_map,
        mesh=mesh,
        in_specs=(col, col, P(), P()),
        out_specs=(col, col, P(), P()),
        check_vma=False,
    )
    def run(pos_t, fit_t, best_pos, best_fit):
        dev = lax.axis_index(axis)

        def block(carry, call_i, k):
            pos_t, fit_t, best_pos, best_fit = carry
            kk = jax.random.fold_in(
                jax.random.fold_in(shift_key, call_i), dev
            )
            sa, sb, sc = _distinct_tile_shifts(kk, n_tiles_local)
            lanes = jax.random.randint(
                jax.random.fold_in(kk, 1), (3,), 0, tile_n
            )
            scalars = jnp.concatenate([
                jnp.stack([
                    seed0 + (call_i * n_dev + dev) * n_tiles_local,
                    sa, sb, sc,
                ]),
                lanes,
            ]).astype(jnp.int32)
            r = None
            if rng == "host":
                (r, _) = host_uniforms(
                    host_key, call_i, pos_t.shape, fold=dev
                )
            pos_t, fit_t = fused_de_step_t(
                scalars, pos_t, fit_t, r,
                objective_name=objective_name, f=f, cr=cr,
                half_width=half_width, tile_n=tile_n, rng=rng,
                interpret=interpret, k_steps=k,
            )
            loc_fit, loc_pos = best_of_block(fit_t, pos_t)
            best_fit, best_pos = _exchange_best(
                loc_fit, loc_pos, best_fit, best_pos, dev, axis
            )
            return (pos_t, fit_t, best_pos, best_fit)

        return run_blocks(
            block, (pos_t, fit_t, best_pos, best_fit),
            n_steps, steps_per_kernel,
        )

    pos_t, fit_t, best_pos, best_fit = run(
        pos_t, fit_t,
        state.best_pos.astype(jnp.float32),
        state.best_fit.astype(jnp.float32),
    )
    dt = state.pos.dtype
    return DEState(
        pos=pos_t.T[:n].astype(dt),
        fit=fit_t[0, :n].astype(state.fit.dtype),
        best_pos=best_pos.astype(state.best_pos.dtype),
        best_fit=best_fit.astype(state.best_fit.dtype),
        key=jax.random.fold_in(state.key, n_steps),
        iteration=state.iteration + n_steps,
    )


@partial(
    jax.jit,
    static_argnames=(
        "objective_name", "mesh", "n_steps", "axis", "half_width",
        "t_max", "spiral_b", "steps_per_kernel", "tile_n", "rng",
        "interpret",
    ),
)
def fused_woa_run_shmap(
    state,
    objective_name: str,
    mesh: Mesh,
    n_steps: int,
    axis: str = AGENT_AXIS,
    half_width: float = 5.12,
    t_max: int = 500,
    spiral_b: float | None = None,
    steps_per_kernel: int = 8,
    tile_n: int | None = None,
    rng: str = "tpu",
    interpret: bool = False,
):
    """Multi-chip fused-Pallas WOA: each device runs rotational-peer
    blocks (ops/pallas/woa_fused.py) on its pod shard; the incumbent
    best is exchanged over ICI per block (``pmin`` + ``psum``
    broadcast) — per-block best staleness and the cross-device cadence
    coincide, like every fused shmap driver here.  Random peers are
    shard-local between exchanges."""
    from ..ops.pallas.common import ceil_to, cyclic_pad_rows
    from ..ops.pallas.woa_fused import (
        _auto_tile,
        best_of_block,
        fused_woa_step_t,
        host_uniforms,
        run_blocks,
        seed_base,
    )
    from ..ops.woa import SPIRAL_B, WOAState

    spiral_b = float(SPIRAL_B if spiral_b is None else spiral_b)
    n, d = state.pos.shape
    n_dev = mesh.shape[axis]
    if rng == "host":
        steps_per_kernel = 1
    steps_per_kernel = min(steps_per_kernel, 32)   # VMEM (see woa_fused)
    if tile_n is None:
        tile_n = _auto_tile(ceil_to(max(d, 8), 8))
    tile_n = min(tile_n, ceil_to(-(-n // n_dev), 128))
    n_pad = ceil_to(n, n_dev * tile_n)
    n_tiles_local = (n_pad // n_dev) // tile_n

    pos_t = cyclic_pad_rows(state.pos, n_pad).T
    fit_t = cyclic_pad_rows(state.fit, n_pad)[None, :]
    seed0 = seed_base(state.key)
    host_key = jax.random.fold_in(state.key, 0x30A)
    shift_key = jax.random.fold_in(state.key, 0x0A1)

    col = P(None, axis)

    @partial(
        shard_map,
        mesh=mesh,
        in_specs=(col, col, P(), P()),
        out_specs=(col, col, P(), P()),
        check_vma=False,
    )
    def run(pos_t, fit_t, best_pos, best_fit):
        dev = lax.axis_index(axis)

        def block(carry, call_i, k):
            pos_t, fit_t, best_pos, best_fit, it = carry
            kk = jax.random.fold_in(
                jax.random.fold_in(shift_key, call_i), dev
            )
            tshift = jax.random.randint(kk, (), 0, n_tiles_local)
            lshift = jax.random.randint(
                jax.random.fold_in(kk, 1), (), 0, tile_n
            )
            scalars = jnp.stack([
                seed0 + (call_i * n_dev + dev) * n_tiles_local,
                tshift, it, lshift,
            ]).astype(jnp.int32)
            r_a = r_c = r_p = r_l = None
            if rng == "host":
                r_a, r_c = host_uniforms(
                    host_key, call_i, pos_t.shape, fold=dev
                )
                r_p, r_l = host_uniforms(
                    host_key, call_i, fit_t.shape, fold=1000 + dev
                )
            pos_t, fit_t = fused_woa_step_t(
                scalars, best_pos[:, None], pos_t, r_a, r_c, r_p, r_l,
                objective_name=objective_name, half_width=half_width,
                t_max=t_max, spiral_b=spiral_b, tile_n=tile_n, rng=rng,
                interpret=interpret, k_steps=k,
            )
            loc_fit, loc_pos = best_of_block(fit_t, pos_t)
            best_fit, best_pos = _exchange_best(
                loc_fit, loc_pos, best_fit, best_pos, dev, axis
            )
            return (pos_t, fit_t, best_pos, best_fit, it + k)

        carry = run_blocks(
            block,
            (pos_t, fit_t, best_pos, best_fit, state.iteration),
            n_steps, steps_per_kernel,
        )
        return carry[:4]

    pos_t, fit_t, best_pos, best_fit = run(
        pos_t, fit_t,
        state.best_pos.astype(jnp.float32),
        state.best_fit.astype(jnp.float32),
    )
    dt = state.pos.dtype
    return WOAState(
        pos=pos_t.T[:n].astype(dt),
        fit=fit_t[0, :n].astype(state.fit.dtype),
        best_pos=best_pos.astype(state.best_pos.dtype),
        best_fit=best_fit.astype(state.best_fit.dtype),
        key=jax.random.fold_in(state.key, n_steps),
        iteration=state.iteration + n_steps,
    )


def elect_shmap(
    alive: jax.Array,
    agent_id: jax.Array,
    mesh: Mesh,
    axis: str = AGENT_AXIS,
    telemetry: bool = False,
):
    """Bully-election fixed point as an explicit cross-device reduction:
    leader = max alive id (agent.py:244-251 collapsed to one ``lax.pmax``).
    Returns the replicated winning id (NO_LEADER if none alive).

    ``telemetry=True`` (r11, static gate): returns ``(leader_id,
    telem)`` where ``telem`` is one mesh-reduced
    ``utils/telemetry.TickTelemetry`` — global alive count (``psum``),
    the elected leader, and the per-device residency pair
    (``pmax``/``pmin`` of per-shard alive counts): the live-agent
    imbalance counter for an agent-sharded swarm."""

    @partial(
        shard_map, mesh=mesh, in_specs=(P(axis), P(axis)),
        out_specs=(P(), P()) if telemetry else P(),
        check_vma=False,
    )
    def elect(alive_l, id_l):
        local = jnp.max(jnp.where(alive_l, id_l, NO_LEADER))
        leader = lax.pmax(local, axis)[None]
        if not telemetry:  # static TelemetryConfig-style gate
            return leader
        from ..utils.telemetry import (
            mesh_reduce_telemetry,
            tick_telemetry,
        )

        # Position/velocity are not the election's business: a zero
        # [n_loc, 1] placeholder keeps the gauges neutral while the
        # alive mask drives the counts the reducer turns into the
        # global total and the per-shard residency pair.
        zeros = jnp.zeros((alive_l.shape[0], 1), jnp.float32)
        local_rec = tick_telemetry(
            zeros, zeros, alive_l, 0, leader_id=leader[0]
        )
        return leader, mesh_reduce_telemetry(local_rec, axis)

    out = elect(alive, agent_id)
    if telemetry:
        leader, rec = out
        return leader[0], rec
    return out[0]


def swarm_telemetry_shmap(
    state: SwarmState,
    mesh: Mesh,
    axis: str = AGENT_AXIS,
):
    """One mesh-reduced ``utils/telemetry.TickTelemetry`` from an
    agent-sharded ``SwarmState`` — the sharded flight recorder's
    one-shot form (r11).

    The in-rollout recorder already runs under GSPMD (the partitioned
    ``jnp`` reductions in ``tick_telemetry`` lower to collectives when
    the state is sharded), but GSPMD cannot express PER-DEVICE
    quantities — a partitioned ``sum`` is the global sum by
    construction.  This collector drops to ``shard_map``, collects the
    same record per shard, and reduces with named-axis collectives
    (``mesh_reduce_telemetry``), which is exactly what fills
    ``shard_max_alive``/``shard_imbalance``: the live-agent residency
    spread an imbalanced kill pattern creates across devices.  Pure
    read-only — safe to call on any sharded state at any cadence."""
    shard = P(axis)

    @partial(
        shard_map, mesh=mesh,
        in_specs=(shard, shard, shard, shard, shard, P()),
        out_specs=P(),
        check_vma=False,
    )
    def collect(pos, vel, alive, fsm, agent_id, tick):
        from ..state import LEADER as _LEADER
        from ..state import ELECTION_WAIT as _EW
        from ..utils.telemetry import (
            mesh_reduce_telemetry,
            tick_telemetry,
        )

        mask = alive & (fsm == _LEADER)
        lid = jnp.max(jnp.where(mask, agent_id, NO_LEADER))
        electing = jnp.sum(alive & (fsm == _EW))
        local = tick_telemetry(
            pos, vel, alive, tick,
            leader_id=lax.pmax(lid, axis), electing=electing,
        )
        return mesh_reduce_telemetry(local, axis)

    return collect(
        state.pos, state.vel, state.alive, state.fsm, state.agent_id,
        state.tick,
    )


# --------------------------------------------------------------------------
# r3 shmap drivers: the rest of the fused zoo (VERDICT r2 §weak-2).
# All follow fused_de_run_shmap's shape: per-shard fused kernel blocks,
# cross-device best exchange per block over ICI (_exchange_best), donor/
# peer pools SHARD-LOCAL between exchanges (island-model lag class).
# --------------------------------------------------------------------------


def _shard_real_count(n, n_dev, shard_w, dev):
    """Real (unpadded) lane count of shard ``dev`` after global cyclic
    padding to ``n_dev * shard_w``: clip(n - dev*w, 0, w)."""
    return jnp.clip(n - dev * shard_w, 0, shard_w)


@partial(
    jax.jit,
    static_argnames=(
        "objective_name", "mesh", "n_steps", "axis", "half_width", "pa",
        "step_scale", "levy_beta", "steps_per_kernel", "tile_n", "rng",
        "interpret",
    ),
)
def fused_cuckoo_run_shmap(
    state,
    objective_name: str,
    mesh: Mesh,
    n_steps: int,
    axis: str = AGENT_AXIS,
    half_width: float = 5.12,
    pa: float | None = None,
    step_scale: float | None = None,
    levy_beta: float | None = None,
    steps_per_kernel: int = 8,
    tile_n: int | None = None,
    rng: str = "tpu",
    interpret: bool = False,
):
    """Multi-chip fused cuckoo: rotational egg-drop/peer blocks per
    shard (ops/pallas/cuckoo_fused.py); the shared best is exchanged
    per block over ICI."""
    from ..ops.cuckoo import (
        LEVY_BETA as _LB,
        PA as _PA,
        STEP_SCALE as _SS,
        CuckooState,
    )
    from ..ops.pallas.common import ceil_to, cyclic_pad_rows
    from ..ops.pallas.cuckoo_fused import (
        fused_cuckoo_step_t,
        host_draws as _cuckoo_host_draws,
    )
    from ..ops.pallas.de_fused import shrink_tile_for_donors
    from ..ops.pallas.pso_fused import (
        _auto_tile,
        best_of_block,
        run_blocks,
        seed_base,
    )

    pa = _PA if pa is None else pa
    step_scale = _SS if step_scale is None else step_scale
    levy_beta = _LB if levy_beta is None else levy_beta
    n, d = state.pos.shape
    n_dev = mesh.shape[axis]
    if rng == "host":
        steps_per_kernel = 1
    steps_per_kernel = min(steps_per_kernel, 8)    # VMEM (cuckoo_fused)
    if tile_n is None:
        tile_n = _auto_tile(ceil_to(max(d, 8), 8))
    tile_n = min(tile_n, ceil_to(-(-n // n_dev), 128))
    tile_n, n_pad, n_tiles_local = shrink_tile_for_donors(
        n, tile_n, per_shard=n_dev
    )

    pos_t = cyclic_pad_rows(state.pos, n_pad).T
    fit_t = cyclic_pad_rows(state.fit, n_pad)[None, :]
    seed0 = seed_base(state.key)
    host_key = jax.random.fold_in(state.key, 0xC0C)
    shift_key = jax.random.fold_in(state.key, 0xC1C)
    col = P(None, axis)

    @partial(
        shard_map, mesh=mesh,
        in_specs=(col, col, P(), P()),
        out_specs=(col, col, P(), P()),
        check_vma=False,
    )
    def run(pos_t, fit_t, best_pos, best_fit):
        dev = lax.axis_index(axis)

        def block(carry, call_i, k):
            pos_t, fit_t, best_pos, best_fit = carry
            kk = jax.random.fold_in(
                jax.random.fold_in(shift_key, call_i), dev
            )
            tshifts = jax.random.randint(
                kk, (2,), 1, max(n_tiles_local, 2)
            )
            lanes = jax.random.randint(
                jax.random.fold_in(kk, 1), (3,), 0, tile_n
            )
            scalars = jnp.concatenate([
                jnp.stack(
                    [seed0 + (call_i * n_dev + dev) * n_tiles_local]
                ),
                tshifts, lanes,
            ]).astype(jnp.int32)
            r1 = r2 = rab = rwk = None
            if rng == "host":
                r1, r2, rab, rwk = _cuckoo_host_draws(
                    host_key, call_i, pos_t.shape, fit_t.shape,
                    fold=dev,
                )
            pos_t, fit_t = fused_cuckoo_step_t(
                scalars, best_pos[:, None], pos_t, fit_t, r1, r2, rab,
                rwk,
                objective_name=objective_name, half_width=half_width,
                pa=pa, step_scale=step_scale, levy_beta=levy_beta,
                tile_n=tile_n, rng=rng, interpret=interpret, k_steps=k,
            )
            loc_fit, loc_pos = best_of_block(fit_t, pos_t)
            best_fit, best_pos = _exchange_best(
                loc_fit, loc_pos, best_fit, best_pos, dev, axis
            )
            return (pos_t, fit_t, best_pos, best_fit)

        return run_blocks(
            block, (pos_t, fit_t, best_pos, best_fit),
            n_steps, steps_per_kernel,
        )

    pos_t, fit_t, best_pos, best_fit = run(
        pos_t, fit_t,
        state.best_pos.astype(jnp.float32),
        state.best_fit.astype(jnp.float32),
    )
    dt = state.pos.dtype
    return CuckooState(
        pos=pos_t.T[:n].astype(dt),
        fit=fit_t[0, :n].astype(state.fit.dtype),
        best_pos=best_pos.astype(state.best_pos.dtype),
        best_fit=best_fit.astype(state.best_fit.dtype),
        key=jax.random.fold_in(state.key, n_steps),
        iteration=state.iteration + n_steps,
    )


@partial(
    jax.jit,
    static_argnames=(
        "objective_name", "mesh", "n_steps", "axis", "half_width",
        "t_max", "levy_beta", "steps_per_kernel", "tile_n", "rng",
        "interpret",
    ),
)
def fused_hho_run_shmap(
    state,
    objective_name: str,
    mesh: Mesh,
    n_steps: int,
    axis: str = AGENT_AXIS,
    half_width: float = 5.12,
    t_max: int | None = None,
    levy_beta: float | None = None,
    steps_per_kernel: int = 8,
    tile_n: int | None = None,
    rng: str = "tpu",
    interpret: bool = False,
):
    """Multi-chip fused HHO: rotational-peer blocks per shard
    (ops/pallas/hho_fused.py); the rabbit (best) AND the global swarm
    mean are exchanged per block over ICI (``psum`` of per-shard
    real-lane sums — exact, pad lanes excluded)."""
    from ..ops.hho import LEVY_BETA as _LB, T_MAX as _TM, HHOState
    from ..ops.pallas.common import ceil_to, cyclic_pad_rows
    from ..ops.pallas.de_fused import shrink_tile_for_donors
    from ..ops.pallas.hho_fused import (
        fused_hho_step_t,
        host_draws as _hho_host_draws,
    )
    from ..ops.pallas.pso_fused import (
        _auto_tile,
        best_of_block,
        run_blocks,
        seed_base,
    )

    t_max = _TM if t_max is None else t_max
    levy_beta = _LB if levy_beta is None else levy_beta
    n, d = state.pos.shape
    n_dev = mesh.shape[axis]
    if rng == "host":
        steps_per_kernel = 1
    steps_per_kernel = min(steps_per_kernel, 8)    # VMEM (hho_fused)
    if tile_n is None:
        tile_n = _auto_tile(ceil_to(max(d, 8), 8))
    tile_n = min(tile_n, ceil_to(-(-n // n_dev), 128))
    tile_n, n_pad, n_tiles_local = shrink_tile_for_donors(
        n, tile_n, per_shard=n_dev
    )
    shard_w = n_pad // n_dev

    pos_t = cyclic_pad_rows(state.pos, n_pad).T
    fit_t = cyclic_pad_rows(state.fit, n_pad)[None, :]
    seed0 = seed_base(state.key)
    host_key = jax.random.fold_in(state.key, 0x440)
    shift_key = jax.random.fold_in(state.key, 0x441)
    col = P(None, axis)

    @partial(
        shard_map, mesh=mesh,
        in_specs=(col, col, P(), P()),
        out_specs=(col, col, P(), P()),
        check_vma=False,
    )
    def run(pos_t, fit_t, best_pos, best_fit):
        dev = lax.axis_index(axis)
        n_real_local = _shard_real_count(n, n_dev, shard_w, dev)

        def block(carry, call_i, k):
            pos_t, fit_t, best_pos, best_fit, it = carry
            kk = jax.random.fold_in(
                jax.random.fold_in(shift_key, call_i), dev
            )
            tshift = jax.random.randint(
                kk, (), 1, max(n_tiles_local, 2)
            )
            lshift = jax.random.randint(
                jax.random.fold_in(kk, 1), (), 0, tile_n
            )
            scalars = jnp.stack([
                seed0 + (call_i * n_dev + dev) * n_tiles_local,
                tshift, it, lshift,
            ]).astype(jnp.int32)
            # Global mean over REAL lanes: per-shard masked sum + psum.
            lane = jnp.arange(shard_w)
            real = (lane < n_real_local)[None, :]
            loc_sum = jnp.sum(
                jnp.where(real, pos_t, 0.0), axis=1, keepdims=True
            )
            mean = lax.psum(loc_sum, axis) / n
            draws = None
            if rng == "host":
                draws = _hho_host_draws(
                    host_key, call_i, pos_t.shape, fit_t.shape,
                    fold=dev,
                )
            pos_t, fit_t = fused_hho_step_t(
                scalars, best_pos[:, None], mean, pos_t, fit_t,
                draws,
                objective_name=objective_name, half_width=half_width,
                t_max=t_max, levy_beta=levy_beta, tile_n=tile_n,
                rng=rng, interpret=interpret, k_steps=k,
            )
            loc_fit, loc_pos = best_of_block(fit_t, pos_t)
            best_fit, best_pos = _exchange_best(
                loc_fit, loc_pos, best_fit, best_pos, dev, axis
            )
            return (pos_t, fit_t, best_pos, best_fit, it + k)

        carry = run_blocks(
            block, (pos_t, fit_t, best_pos, best_fit, state.iteration),
            n_steps, steps_per_kernel,
        )
        return carry[:4]

    pos_t, fit_t, best_pos, best_fit = run(
        pos_t, fit_t,
        state.best_pos.astype(jnp.float32),
        state.best_fit.astype(jnp.float32),
    )
    dt = state.pos.dtype
    return HHOState(
        pos=pos_t.T[:n].astype(dt),
        fit=fit_t[0, :n].astype(state.fit.dtype),
        best_pos=best_pos.astype(state.best_pos.dtype),
        best_fit=best_fit.astype(state.best_fit.dtype),
        key=jax.random.fold_in(state.key, n_steps),
        iteration=state.iteration + n_steps,
    )


@partial(
    jax.jit,
    static_argnames=(
        "objective_name", "mesh", "n_steps", "axis", "half_width",
        "t_max", "b", "steps_per_kernel", "tile_n", "rng", "interpret",
        "sort_blocks",
    ),
)
def fused_mfo_run_shmap(
    state,
    objective_name: str,
    mesh: Mesh,
    n_steps: int,
    axis: str = AGENT_AXIS,
    half_width: float = 5.12,
    t_max: int | None = None,
    b: float | None = None,
    steps_per_kernel: int = 8,
    tile_n: int | None = None,
    rng: str = "tpu",
    interpret: bool = False,
    sort_blocks: int = 8,
):
    """Multi-chip fused MFO: positional-flame blocks per shard
    (ops/pallas/mfo_fused.py) with a SHARD-LOCAL flame memory — flame
    slots update per step in-kernel (positional elitism, r3 split)
    and each shard re-sorts its own N-local flames by fitness every
    ``sort_blocks`` blocks, the island-model trade (global rank order
    would need a cross-device sort; the shards couple through nothing
    else, exactly like the portable island model over MFO).  The
    flame-count schedule runs on the shard width."""
    from ..ops.mfo import SPIRAL_B as _SB, T_MAX as _TM, MFOState
    from ..ops.pallas.common import ceil_to, cyclic_pad_rows
    from ..ops.pallas.mfo_fused import (
        fused_mfo_step_t,
        resort_flames as _mfo_resort,
    )
    from ..ops.pallas.pso_fused import (
        _auto_tile,
        run_blocks,
        seed_base,
    )

    t_max = _TM if t_max is None else t_max
    b = _SB if b is None else b
    n, d = state.pos.shape
    n_dev = mesh.shape[axis]
    if rng == "host":
        steps_per_kernel = 1
    steps_per_kernel = min(steps_per_kernel, 32)
    if tile_n is None:
        tile_n = _auto_tile(ceil_to(max(d, 8), 8))
    tile_n = min(tile_n, ceil_to(-(-n // n_dev), 128))
    n_pad = ceil_to(n, n_dev * tile_n)
    shard_w = n_pad // n_dev
    n_tiles_local = shard_w // tile_n

    pos_t = cyclic_pad_rows(state.pos, n_pad).T
    fit_t = cyclic_pad_rows(state.fit, n_pad)[None, :]
    flame_pos_t = jnp.concatenate(
        [
            state.flame_pos.T.astype(jnp.float32),
            jnp.broadcast_to(
                state.flame_pos[-1][:, None].astype(jnp.float32),
                (d, n_pad - n),
            ),
        ],
        axis=1,
    )
    flame_fit = jnp.concatenate([
        state.flame_fit.astype(jnp.float32),
        jnp.full((n_pad - n,), jnp.inf, jnp.float32),
    ])[None, :]
    seed0 = seed_base(state.key)
    host_key = jax.random.fold_in(state.key, 0x3F0)
    col = P(None, axis)

    @partial(
        shard_map, mesh=mesh,
        in_specs=(col, col, col, col),
        out_specs=(col, col, col, col),
        check_vma=False,
    )
    def run(pos_t, fit_t, flame_pos_t, flame_fit_row):
        dev = lax.axis_index(axis)

        def block(carry, call_i, k):
            pos_t, fit_t, flame_pos_t, flame_fit, it = carry
            t = (it + 1).astype(jnp.float32)
            frac = jnp.clip(t / t_max, 0.0, 1.0)
            n_flames = jnp.round(
                shard_w - frac * (shard_w - 1)
            ).astype(jnp.int32)
            r_lo = -1.0 - frac
            last = jax.lax.dynamic_slice(
                flame_pos_t, (0, jnp.maximum(n_flames - 1, 0)), (d, 1)
            )
            scalars = jnp.stack([
                seed0 + (call_i * n_dev + dev) * n_tiles_local,
                n_flames,
                jnp.round(r_lo * 65536.0).astype(jnp.int32),
            ]).astype(jnp.int32)
            r_l = None
            if rng == "host":
                r_l = jax.random.uniform(
                    jax.random.fold_in(
                        jax.random.fold_in(host_key, call_i), dev
                    ),
                    pos_t.shape, jnp.float32,
                )
            pos_t, fit_t, flame_pos_t, ffit_row = fused_mfo_step_t(
                scalars, last, pos_t, flame_pos_t, flame_fit[None, :],
                r_l,
                objective_name=objective_name,
                half_width=half_width, b=b, tile_n=tile_n, rng=rng,
                interpret=interpret, k_steps=k,
            )
            flame_fit = ffit_row[0]
            # shard-local rank re-sort on the shared sort_blocks
            # cadence (per-step positional elitism happens in-kernel;
            # see mfo_fused's r3 docstring)
            flame_pos_t, flame_fit = jax.lax.cond(
                (call_i + 1) % sort_blocks == 0,
                lambda a: _mfo_resort(*a), lambda a: a,
                (flame_pos_t, flame_fit),
            )
            return (pos_t, fit_t, flame_pos_t, flame_fit, it + k)

        carry = run_blocks(
            block,
            (pos_t, fit_t, flame_pos_t, flame_fit_row[0],
             state.iteration),
            n_steps, steps_per_kernel,
        )
        pos_t, fit_t, flame_pos_t, flame_fit, _ = carry
        flame_pos_t, flame_fit = _mfo_resort(flame_pos_t, flame_fit)
        return pos_t, fit_t, flame_pos_t, flame_fit[None, :]

    pos_t, fit_t, flame_pos_t, flame_fit = run(
        pos_t, fit_t, flame_pos_t, flame_fit
    )
    dt = state.pos.dtype
    return MFOState(
        pos=pos_t.T[:n].astype(dt),
        fit=fit_t[0, :n].astype(state.fit.dtype),
        flame_pos=flame_pos_t.T[:n].astype(state.flame_pos.dtype),
        flame_fit=flame_fit[0, :n].astype(state.flame_fit.dtype),
        key=jax.random.fold_in(state.key, n_steps),
        iteration=state.iteration + n_steps,
    )


@partial(
    jax.jit,
    static_argnames=(
        "objective_name", "mesh", "n_steps", "axis", "half_width",
        "t_max", "steps_per_kernel", "tile_n", "rng", "interpret",
    ),
)
def fused_salp_run_shmap(
    state,
    objective_name: str,
    mesh: Mesh,
    n_steps: int,
    axis: str = AGENT_AXIS,
    half_width: float = 5.12,
    t_max: int | None = None,
    steps_per_kernel: int = 16,
    tile_n: int | None = None,
    rng: str = "tpu",
    interpret: bool = False,
):
    """Multi-chip fused salp: each shard runs its own sub-chain with
    its own leader (the kernel's tile-0 leader rule fires per shard),
    all leaders following the GLOBAL food source exchanged per block
    over ICI — the multi-leader salp-chain variant; per-step in-kernel
    best recording feeds the exchange."""
    from ..ops.pallas.common import ceil_to, cyclic_pad_rows
    from ..ops.pallas.pso_fused import (
        _auto_tile,
        host_uniforms,
        run_blocks,
        seed_base,
    )
    from ..ops.pallas.salp_fused import fused_salp_step_t
    from ..ops.salp import T_MAX as _TM, SalpState

    t_max = _TM if t_max is None else t_max
    n, d = state.pos.shape
    n_dev = mesh.shape[axis]
    if rng == "host":
        steps_per_kernel = 1
    steps_per_kernel = min(steps_per_kernel, 16)
    if tile_n is None:
        tile_n = _auto_tile(ceil_to(max(d, 8), 8))
    tile_n = min(tile_n, ceil_to(-(-n // n_dev), 128))
    n_pad = ceil_to(n, n_dev * tile_n)
    shard_w = n_pad // n_dev
    n_tiles_local = shard_w // tile_n

    pos_t = cyclic_pad_rows(state.pos, n_pad).T
    fit_t = cyclic_pad_rows(state.fit, n_pad)[None, :]
    seed0 = seed_base(state.key)
    host_key = jax.random.fold_in(state.key, 0x5A1)
    col = P(None, axis)

    @partial(
        shard_map, mesh=mesh,
        in_specs=(col, col, P(), P()),
        out_specs=(col, col, P(), P()),
        check_vma=False,
    )
    def run(pos_t, fit_t, best_pos, best_fit):
        dev = lax.axis_index(axis)

        def block(carry, call_i, k):
            pos_t, fit_t, best_pos, best_fit, it = carry
            scalars = jnp.stack([
                seed0 + (call_i * n_dev + dev) * n_tiles_local, it,
            ]).astype(jnp.int32)
            r2 = r3 = None
            if rng == "host":
                r2, r3 = host_uniforms(
                    host_key, call_i, pos_t.shape, fold=dev
                )
            pos_t, fit_t, blk_fit, blk_pos = fused_salp_step_t(
                scalars, best_pos[:, None], pos_t, fit_t, r2, r3,
                objective_name=objective_name, half_width=half_width,
                t_max=t_max, tile_n=tile_n, rng=rng,
                interpret=interpret, k_steps=k,
            )
            best_fit, best_pos = _exchange_best(
                blk_fit[0, 0], blk_pos[:, 0], best_fit, best_pos,
                dev, axis,
            )
            return (pos_t, fit_t, best_pos, best_fit, it + k)

        carry = run_blocks(
            block, (pos_t, fit_t, best_pos, best_fit, state.iteration),
            n_steps, steps_per_kernel,
        )
        return carry[:4]

    pos_t, fit_t, best_pos, best_fit = run(
        pos_t, fit_t,
        state.best_pos.astype(jnp.float32),
        state.best_fit.astype(jnp.float32),
    )
    dt = state.pos.dtype
    return SalpState(
        pos=pos_t.T[:n].astype(dt),
        fit=fit_t[0, :n].astype(state.fit.dtype),
        best_pos=best_pos.astype(state.best_pos.dtype),
        best_fit=best_fit.astype(state.best_fit.dtype),
        key=jax.random.fold_in(state.key, n_steps),
        iteration=state.iteration + n_steps,
    )


@partial(
    jax.jit,
    static_argnames=(
        "objective_name", "mesh", "n_steps", "axis", "half_width",
        "eta_c", "eta_m", "p_cross", "p_mut", "steps_per_kernel",
        "tile_n", "rng", "interpret",
    ),
)
def fused_ga_run_shmap(
    state,
    objective_name: str,
    mesh: Mesh,
    n_steps: int,
    axis: str = AGENT_AXIS,
    half_width: float = 5.12,
    eta_c: float | None = None,
    eta_m: float | None = None,
    p_cross: float | None = None,
    p_mut: float | None = None,
    steps_per_kernel: int = 8,
    tile_n: int | None = None,
    rng: str = "tpu",
    interpret: bool = False,
):
    """Multi-chip fused GA: rotational-tournament blocks per shard
    (ops/pallas/ga_fused.py); tournament snapshot pools are SHARD-LOCAL
    between exchanges and the best is exchanged per block over ICI."""
    from ..ops.ga import GAState
    from ..ops.nsga2 import ETA_C as _EC, ETA_M as _EM, P_CROSS as _PC
    from ..ops.pallas.common import ceil_to, cyclic_pad_rows
    from ..ops.pallas.de_fused import shrink_tile_for_donors
    from ..ops.pallas.ga_fused import (
        fused_ga_step_t,
        host_draws as _ga_host_draws,
    )
    from ..ops.pallas.pso_fused import (
        _auto_tile,
        best_of_block,
        run_blocks,
        seed_base,
    )

    eta_c = _EC if eta_c is None else eta_c
    eta_m = _EM if eta_m is None else eta_m
    p_cross = _PC if p_cross is None else p_cross
    n, d = state.pos.shape
    if p_mut is None:
        p_mut = 1.0 / d
    n_dev = mesh.shape[axis]
    if rng == "host":
        steps_per_kernel = 1
    steps_per_kernel = min(steps_per_kernel, 8)    # VMEM (ga_fused)
    if tile_n is None:
        tile_n = _auto_tile(ceil_to(max(d, 8), 8))
    tile_n = min(tile_n, ceil_to(-(-n // n_dev), 128))
    tile_n, n_pad, n_tiles_local = shrink_tile_for_donors(
        n, tile_n, per_shard=n_dev
    )

    pos_t = cyclic_pad_rows(state.pos, n_pad).T
    fit_t = cyclic_pad_rows(state.fit, n_pad)[None, :]
    seed0 = seed_base(state.key)
    host_key = jax.random.fold_in(state.key, 0x6A)
    shift_key = jax.random.fold_in(state.key, 0x6A5F)
    col = P(None, axis)

    @partial(
        shard_map, mesh=mesh,
        in_specs=(col, col, P(), P()),
        out_specs=(col, col, P(), P()),
        check_vma=False,
    )
    def run(pos_t, fit_t, best_pos, best_fit):
        dev = lax.axis_index(axis)

        def block(carry, call_i, k):
            pos_t, fit_t, best_pos, best_fit = carry
            kk = jax.random.fold_in(
                jax.random.fold_in(shift_key, call_i), dev
            )
            tshifts = jax.random.randint(
                kk, (2,), 1, max(n_tiles_local, 2)
            )
            lanes = jax.random.randint(
                jax.random.fold_in(kk, 1), (3,), 0, tile_n
            )
            scalars = jnp.concatenate([
                jnp.stack(
                    [seed0 + (call_i * n_dev + dev) * n_tiles_local]
                ),
                tshifts, lanes,
            ]).astype(jnp.int32)
            rs = rg = rm = rd = None
            if rng == "host":
                rs, rg, rm, rd = _ga_host_draws(
                    host_key, call_i, pos_t.shape, fit_t.shape,
                    fold=dev,
                )
            pos_t, fit_t = fused_ga_step_t(
                scalars, pos_t, fit_t, rs, rg, rm, rd,
                objective_name=objective_name, half_width=half_width,
                eta_c=eta_c, eta_m=eta_m, p_cross=p_cross, p_mut=p_mut,
                tile_n=tile_n, rng=rng, interpret=interpret, k_steps=k,
            )
            loc_fit, loc_pos = best_of_block(fit_t, pos_t)
            best_fit, best_pos = _exchange_best(
                loc_fit, loc_pos, best_fit, best_pos, dev, axis
            )
            return (pos_t, fit_t, best_pos, best_fit)

        return run_blocks(
            block, (pos_t, fit_t, best_pos, best_fit),
            n_steps, steps_per_kernel,
        )

    pos_t, fit_t, best_pos, best_fit = run(
        pos_t, fit_t,
        state.best_pos.astype(jnp.float32),
        state.best_fit.astype(jnp.float32),
    )
    dt = state.pos.dtype
    return GAState(
        pos=pos_t.T[:n].astype(dt),
        fit=fit_t[0, :n].astype(state.fit.dtype),
        best_pos=best_pos.astype(state.best_pos.dtype),
        best_fit=best_fit.astype(state.best_fit.dtype),
        key=jax.random.fold_in(state.key, n_steps),
        iteration=state.iteration + n_steps,
    )


@partial(
    jax.jit,
    static_argnames=(
        "objective_name", "mesh", "n_steps", "axis", "half_width",
        "limit", "steps_per_kernel", "tile_n", "rng", "interpret",
    ),
)
def fused_abc_run_shmap(
    state,
    objective_name: str,
    mesh: Mesh,
    n_steps: int,
    axis: str = AGENT_AXIS,
    half_width: float = 5.12,
    limit: int = 20,
    steps_per_kernel: int = 8,
    tile_n: int | None = None,
    rng: str = "tpu",
    interpret: bool = False,
):
    """Multi-chip fused ABC: Bernoulli-recruitment blocks per shard
    (ops/pallas/abc_fused.py); the onlooker's cross-tile snapshot
    partner pool is SHARD-LOCAL between exchanges; trial counters ride
    sharded; the best is exchanged per block over ICI."""
    from ..ops.abc import ABCState
    from ..ops.pallas.abc_fused import (
        fused_abc_step_t,
        host_draws as _abc_host_draws,
    )
    from ..ops.pallas.common import ceil_to, cyclic_pad_rows
    from ..ops.pallas.de_fused import shrink_tile_for_donors
    from ..ops.pallas.pso_fused import (
        _auto_tile,
        best_of_block,
        run_blocks,
        seed_base,
    )

    n, d = state.pos.shape
    n_dev = mesh.shape[axis]
    if rng == "host":
        steps_per_kernel = 1
    steps_per_kernel = min(steps_per_kernel, 8)    # VMEM (abc_fused)
    if tile_n is None:
        tile_n = _auto_tile(ceil_to(max(d, 8), 8))
    tile_n = min(tile_n, ceil_to(-(-n // n_dev), 128))
    tile_n, n_pad, n_tiles_local = shrink_tile_for_donors(
        n, tile_n, per_shard=n_dev
    )

    pos_t = cyclic_pad_rows(state.pos, n_pad).T
    fit_t = cyclic_pad_rows(state.fit, n_pad)[None, :]
    tri_t = cyclic_pad_rows(state.trials, n_pad)[None, :].astype(
        jnp.int32
    )
    seed0 = seed_base(state.key)
    host_key = jax.random.fold_in(state.key, 0xABC)
    shift_key = jax.random.fold_in(state.key, 0xAB5)
    col = P(None, axis)

    @partial(
        shard_map, mesh=mesh,
        in_specs=(col, col, col, P(), P()),
        out_specs=(col, col, col, P(), P()),
        check_vma=False,
    )
    def run(pos_t, fit_t, tri_t, best_pos, best_fit):
        dev = lax.axis_index(axis)

        def block(carry, call_i, k):
            pos_t, fit_t, tri_t, best_pos, best_fit = carry
            kk = jax.random.fold_in(
                jax.random.fold_in(shift_key, call_i), dev
            )
            tshift = jax.random.randint(
                kk, (1,), 1, max(n_tiles_local, 2)
            )
            lanes = jax.random.randint(
                jax.random.fold_in(kk, 1), (2,), 0, tile_n
            )
            scalars = jnp.concatenate([
                jnp.stack(
                    [seed0 + (call_i * n_dev + dev) * n_tiles_local]
                ),
                tshift, lanes,
            ]).astype(jnp.int32)
            r_host = None
            if rng == "host":
                r_host = _abc_host_draws(
                    host_key, call_i, pos_t.shape, fit_t.shape,
                    fold=dev,
                )
            pos_t, fit_t, tri_t = fused_abc_step_t(
                scalars, pos_t, fit_t, tri_t, r_host,
                objective_name=objective_name, half_width=half_width,
                limit=limit, tile_n=tile_n, rng=rng,
                interpret=interpret, k_steps=k,
            )
            loc_fit, loc_pos = best_of_block(fit_t, pos_t)
            best_fit, best_pos = _exchange_best(
                loc_fit, loc_pos, best_fit, best_pos, dev, axis
            )
            return (pos_t, fit_t, tri_t, best_pos, best_fit)

        return run_blocks(
            block, (pos_t, fit_t, tri_t, best_pos, best_fit),
            n_steps, steps_per_kernel,
        )

    pos_t, fit_t, tri_t, best_pos, best_fit = run(
        pos_t, fit_t, tri_t,
        state.best_pos.astype(jnp.float32),
        state.best_fit.astype(jnp.float32),
    )
    dt = state.pos.dtype
    return ABCState(
        pos=pos_t.T[:n].astype(dt),
        fit=fit_t[0, :n].astype(state.fit.dtype),
        trials=tri_t[0, :n].astype(state.trials.dtype),
        best_pos=best_pos.astype(state.best_pos.dtype),
        best_fit=best_fit.astype(state.best_fit.dtype),
        key=jax.random.fold_in(state.key, n_steps),
        iteration=state.iteration + n_steps,
    )


@partial(
    jax.jit,
    static_argnames=(
        "objective_name", "mesh", "n_steps", "axis", "half_width",
        "sigma0", "swap_every", "steps_per_kernel", "tile_n", "rng",
        "interpret",
    ),
)
def fused_pt_run_shmap(
    state,
    objective_name: str,
    mesh: Mesh,
    n_steps: int,
    axis: str = AGENT_AXIS,
    half_width: float = 5.12,
    sigma0: float | None = None,
    swap_every: int | None = None,
    steps_per_kernel: int = 16,
    tile_n: int | None = None,
    rng: str = "tpu",
    interpret: bool = False,
):
    """Multi-chip fused parallel tempering: the geometric ladder is
    laid out contiguously along lanes and SHARDED over the mesh — each
    shard holds a contiguous temperature sub-range, exchange stays
    adjacent-lane within shards (the kernel's tile-local pairing;
    shard boundaries idle exactly like tile boundaries at odd parity),
    and the best visited state is exchanged per block over ICI.
    Phantom pad chains (last shard only) are masked from exchange via
    the kernel's traced real-lane bound."""
    from ..ops.pallas.common import ceil_to, cyclic_pad_rows
    from ..ops.pallas.pso_fused import (
        _auto_tile,
        run_blocks,
        seed_base,
    )
    from ..ops.pallas.tempering_fused import (
        fused_pt_step_t,
        host_draws as _pt_host_draws,
    )
    from ..ops.tempering import (
        SIGMA0 as _S0,
        SWAP_EVERY as _SE,
        PTState,
    )

    sigma0 = _S0 if sigma0 is None else sigma0
    swap_every = _SE if swap_every is None else swap_every
    n, d = state.pos.shape
    n_dev = mesh.shape[axis]
    if rng == "host":
        steps_per_kernel = 1
    steps_per_kernel = min(steps_per_kernel, 16)
    if tile_n is None:
        tile_n = _auto_tile(ceil_to(max(d, 8), 8))
    tile_n = min(tile_n, ceil_to(-(-n // n_dev), 128))
    n_pad = ceil_to(n, n_dev * tile_n)
    shard_w = n_pad // n_dev
    n_tiles_local = shard_w // tile_n

    pos_t = cyclic_pad_rows(state.pos, n_pad).T
    fit_t = cyclic_pad_rows(state.fit, n_pad)[None, :]
    temps_t = cyclic_pad_rows(state.temps, n_pad)[None, :]
    sigma_t = sigma0 * half_width * jnp.sqrt(temps_t)
    beta_t = 1.0 / temps_t
    seed0 = seed_base(state.key)
    host_key = jax.random.fold_in(state.key, 0x9E)
    col = P(None, axis)

    @partial(
        shard_map, mesh=mesh,
        in_specs=(col, col, col, col, P(), P()),
        out_specs=(col, col, P(), P()),
        check_vma=False,
    )
    def run(pos_t, fit_t, sigma_t, beta_t, best_pos, best_fit):
        dev = lax.axis_index(axis)
        n_real_local = _shard_real_count(n, n_dev, shard_w, dev)

        def block(carry, call_i, k):
            pos_t, fit_t, best_pos, best_fit, it = carry
            scalars = jnp.stack([
                seed0 + (call_i * n_dev + dev) * n_tiles_local,
                it,
                n_real_local,
            ]).astype(jnp.int32)
            rn = ra = rs = None
            if rng == "host":
                rn, ra, rs = _pt_host_draws(
                    host_key, call_i, pos_t.shape, fit_t.shape,
                    fold=dev,
                )
            pos_t, fit_t, blk_fit, blk_pos = fused_pt_step_t(
                scalars, pos_t, fit_t, sigma_t, beta_t, rn, ra, rs,
                objective_name=objective_name, half_width=half_width,
                swap_every=swap_every, tile_n=tile_n, rng=rng,
                interpret=interpret, k_steps=k,
            )
            best_fit, best_pos = _exchange_best(
                blk_fit[0, 0], blk_pos[:, 0], best_fit, best_pos,
                dev, axis,
            )
            return (pos_t, fit_t, best_pos, best_fit, it + k)

        carry = run_blocks(
            block, (pos_t, fit_t, best_pos, best_fit, state.iteration),
            n_steps, steps_per_kernel,
        )
        return carry[:4]

    pos_t, fit_t, best_pos, best_fit = run(
        pos_t, fit_t, sigma_t, beta_t,
        state.best_pos.astype(jnp.float32),
        state.best_fit.astype(jnp.float32),
    )
    dt = state.pos.dtype
    return PTState(
        pos=pos_t.T[:n].astype(dt),
        fit=fit_t[0, :n].astype(state.fit.dtype),
        temps=state.temps,
        best_pos=best_pos.astype(state.best_pos.dtype),
        best_fit=best_fit.astype(state.best_fit.dtype),
        key=jax.random.fold_in(state.key, n_steps),
        iteration=state.iteration + n_steps,
    )


@partial(
    jax.jit,
    static_argnames=(
        "objective_name", "mesh", "n_steps", "axis", "half_width",
        "tile_n", "rng", "interpret", "archive_window_frac",
    ),
)
def fused_shade_run_shmap(
    state,
    objective_name: str,
    mesh: Mesh,
    n_steps: int,
    axis: str = AGENT_AXIS,
    half_width: float = 5.12,
    tile_n: int | None = None,
    rng: str = "tpu",
    interpret: bool = False,
    archive_window_frac: int = 8,
):
    """Multi-chip fused SHADE-R: per-shard rotational-donor kernels with
    the success-history adaptation kept GLOBAL and EXACT — the per-
    generation weighted success sums are ``psum``'d across shards, so
    every device updates the same replicated F/CR memory the portable
    path would.  Donor pools, the tile-champion elite pool, and the
    archive window stay SHARD-LOCAL between the per-generation best
    exchanges (island-model lag class, like every fused shmap driver)."""
    from ..ops.pallas.common import ceil_to, cyclic_pad_rows
    from ..ops.pallas.de_fused import shrink_tile_for_donors
    from ..ops.pallas.pso_fused import _auto_tile, seed_base
    from ..ops.pallas.shade_fused import (
        _ELITE,
        _FRAC_FX,
        _tile_champion_elite,
        fused_shade_step_t,
    )
    from ..ops.shade import CR_SCALE, F_SCALE, H, SHADEState

    n, d = state.pos.shape
    dt = state.pos.dtype
    n_dev = mesh.shape[axis]
    if tile_n is None:
        tile_n = _auto_tile(ceil_to(max(d, 8), 8))
    tile_n = min(tile_n, ceil_to(-(-n // n_dev), 128))
    tile_n, n_pad, n_tiles_local = shrink_tile_for_donors(
        n, tile_n, per_shard=n_dev
    )
    shard_w = n_pad // n_dev
    win = max(tile_n, shard_w // archive_window_frac)
    win = min(ceil_to(win, 128), shard_w)

    pos_t = cyclic_pad_rows(state.pos, n_pad).T
    fit_t = cyclic_pad_rows(state.fit, n_pad)[None, :]
    row = jnp.arange(n)[:, None]
    arch_src = jnp.where(row < state.archive_n, state.archive, state.pos)
    arch_t = cyclic_pad_rows(arch_src, n_pad).T
    seed0 = seed_base(state.key)
    base_key = jax.random.fold_in(state.key, 0x5AADE)
    col = P(None, axis)

    @partial(
        shard_map, mesh=mesh,
        in_specs=(col, col, col, P(), P(), P(), P(), P()),
        out_specs=(col, col, col, P(), P(), P(), P(), P()),
        check_vma=False,
    )
    def run(pos_t, fit_t, arch_t, m_f, m_cr, mem_k, best_pos, best_fit):
        dev = lax.axis_index(axis)
        n_real_local = _shard_real_count(n, n_dev, shard_w, dev)

        def gen(carry, step_i):
            (pos_t, fit_t, arch_t, m_f, m_cr, mem_k, best_pos,
             best_fit) = carry
            kk = jax.random.fold_in(
                jax.random.fold_in(base_key, step_i), dev
            )
            (k_slot, k_f, k_cr, k_sh, k_ln, k_win, k_hc, k_hs) = (
                jax.random.split(kk, 8)
            )

            slot = jax.random.randint(k_slot, (shard_w,), 0, H)
            mf = m_f[slot]
            mcr = m_cr[slot]
            f_i = jnp.clip(
                mf + F_SCALE * jax.random.cauchy(
                    k_f, (shard_w,), jnp.float32
                ),
                0.01, 1.0,
            )
            cr_i = jnp.clip(
                mcr + CR_SCALE * jax.random.normal(
                    k_cr, (shard_w,), jnp.float32
                ),
                0.0, 1.0,
            )

            sh = jax.random.randint(
                k_sh, (3,), 1, max(n_tiles_local, 2)
            )
            lanes = jax.random.randint(k_ln, (4,), 0, tile_n)
            lanes = lanes.at[3].set(
                jax.random.randint(k_hs, (), 0, _ELITE)
            )
            frac = jnp.asarray(0.5 * _FRAC_FX, jnp.int32)
            scalars = jnp.concatenate([
                jnp.stack([
                    seed0 + (step_i * n_dev + dev) * n_tiles_local,
                    sh[0], sh[1], sh[2],
                ]),
                lanes, frac[None],
            ]).astype(jnp.int32)

            elite = _tile_champion_elite(
                pos_t, fit_t[0], n_tiles_local, tile_n
            )

            r_cross = r_src = None
            if rng == "host":
                kc1, kc2 = jax.random.split(k_hc)
                r_cross = jax.random.uniform(
                    kc1, pos_t.shape, jnp.float32
                )
                r_src = jax.random.uniform(
                    kc2, fit_t.shape, jnp.float32
                )

            new_pos_t, new_fit_t = fused_shade_step_t(
                scalars, pos_t, fit_t, f_i[None, :], cr_i[None, :],
                arch_t, elite, r_cross, r_src,
                objective_name=objective_name, half_width=half_width,
                tile_n=tile_n, rng=rng, interpret=interpret,
            )

            # --- success memory: psum'd, globally exact ---------------
            valid = jnp.arange(shard_w) < n_real_local
            better = (new_fit_t[0] < fit_t[0]) & valid
            w = jnp.where(better, fit_t[0] - new_fit_t[0], 0.0)
            w_sum = lax.psum(jnp.sum(w), axis)
            wf2 = lax.psum(jnp.sum(w * f_i * f_i), axis)
            wf = lax.psum(jnp.sum(w * f_i), axis)
            wcr = lax.psum(jnp.sum(w * cr_i), axis)
            any_success = w_sum > 0.0
            safe = jnp.where(any_success, w_sum, 1.0)
            new_mf = wf2 / jnp.maximum(wf, 1e-12)
            new_mcr = wcr / safe
            m_f = jnp.where(
                any_success, m_f.at[mem_k].set(new_mf), m_f
            )
            m_cr = jnp.where(
                any_success, m_cr.at[mem_k].set(new_mcr), m_cr
            )
            mem_k = jnp.where(
                any_success, (mem_k + 1) % H, mem_k
            ).astype(jnp.int32)

            # --- archive: defeated parents, shard-local window --------
            off = jax.random.randint(k_win, (), 0, shard_w // 128) * 128
            off = jnp.minimum(off, shard_w - win)
            par = jax.lax.dynamic_slice(pos_t, (0, off), (d, win))
            old = jax.lax.dynamic_slice(arch_t, (0, off), (d, win))
            bet = jax.lax.dynamic_slice(
                better[None, :], (0, off), (1, win)
            )
            arch_t = jax.lax.dynamic_update_slice(
                arch_t, jnp.where(bet, par, old), (0, off)
            )

            # --- best exchange ----------------------------------------
            b = jnp.argmin(new_fit_t[0])
            best_fit, best_pos = _exchange_best(
                new_fit_t[0, b], new_pos_t[:, b], best_fit, best_pos,
                dev, axis,
            )

            return (
                new_pos_t, new_fit_t, arch_t, m_f, m_cr, mem_k,
                best_pos, best_fit,
            ), None

        carry, _ = jax.lax.scan(
            gen,
            (pos_t, fit_t, arch_t, m_f, m_cr, mem_k, best_pos,
             best_fit),
            jnp.arange(n_steps, dtype=jnp.int32),
        )
        return carry

    (pos_t, fit_t, arch_t, m_f, m_cr, mem_k, best_pos, best_fit) = run(
        pos_t, fit_t, arch_t,
        state.m_f.astype(jnp.float32),
        state.m_cr.astype(jnp.float32),
        state.mem_k,
        state.best_pos.astype(jnp.float32),
        state.best_fit.astype(jnp.float32),
    )
    return SHADEState(
        pos=pos_t.T[:n].astype(dt),
        fit=fit_t[0, :n].astype(state.fit.dtype),
        best_pos=best_pos.astype(state.best_pos.dtype),
        best_fit=best_fit.astype(state.best_fit.dtype),
        m_f=m_f.astype(state.m_f.dtype),
        m_cr=m_cr.astype(state.m_cr.dtype),
        mem_k=mem_k,
        archive=arch_t.T[:n].astype(state.archive.dtype),
        archive_n=jnp.asarray(n, jnp.int32),
        key=jax.random.fold_in(state.key, n_steps),
        iteration=state.iteration + n_steps,
    )


@partial(
    jax.jit,
    static_argnames=(
        "objective", "mesh", "n_steps", "axis", "half_width", "beta0",
        "gamma", "alpha0", "alpha_decay", "tile_i", "tile_j",
        "interpret",
    ),
)
def fused_firefly_run_shmap(
    state,
    objective: Callable,
    mesh: Mesh,
    n_steps: int,
    axis: str = AGENT_AXIS,
    half_width: float = 5.12,
    beta0: float | None = None,
    gamma: float | None = None,
    alpha0: float | None = None,
    alpha_decay: float | None = None,
    tile_i: int | None = None,
    tile_j: int | None = None,
    interpret: bool = False,
):
    """Multi-chip tiled firefly: the O(N^2) attraction shards over the
    row axis — each device runs the RECTANGULAR Pallas kernel (its rows
    against the per-generation ``all_gather``'d full swarm), so the
    quadratic FLOPs split n_dev ways while the semantics stay exactly
    the square kernel's.  Cross-device traffic is one [N, D] gather +
    one [N] fitness gather per generation plus the best exchange."""
    from ..ops.firefly import (
        ALPHA0 as _A0,
        ALPHA_DECAY as _AD,
        BETA0 as _B0,
        GAMMA as _G,
        FireflyState,
    )
    from ..ops.pallas.firefly_fused import (
        DEFAULT_TILE_I,
        DEFAULT_TILE_J,
        firefly_attraction_pallas,
    )

    beta0 = _B0 if beta0 is None else beta0
    gamma = _G if gamma is None else gamma
    alpha0 = _A0 if alpha0 is None else alpha0
    alpha_decay = _AD if alpha_decay is None else alpha_decay
    tile_i = DEFAULT_TILE_I if tile_i is None else tile_i
    tile_j = DEFAULT_TILE_J if tile_j is None else tile_j
    n, d = state.pos.shape
    dt = state.pos.dtype
    n_dev = mesh.shape[axis]
    n_pad = pad_to_devices(n, n_dev)
    shard_w = n_pad // n_dev

    # Row padding with +inf fitness: never brighter, zero weight.
    pos_p = jnp.zeros((n_pad, d), jnp.float32).at[:n].set(
        state.pos.astype(jnp.float32)
    )
    fit_p = jnp.full((n_pad,), jnp.inf, jnp.float32).at[:n].set(
        state.fit.astype(jnp.float32)
    )
    rows = P(axis)

    @partial(
        shard_map, mesh=mesh,
        in_specs=(rows, rows, P(), P(), P(), P()),
        out_specs=(rows, rows, P(), P()),
        check_vma=False,
    )
    def run(pos_l, fit_l, best_pos, best_fit, key, it0):
        dev = lax.axis_index(axis)

        def gen(carry, step_i):
            pos_l, fit_l, best_pos, best_fit = carry
            kr = jax.random.fold_in(
                jax.random.fold_in(key, step_i), dev
            )
            full_pos = lax.all_gather(pos_l, axis).reshape(-1, d)
            full_fit = lax.all_gather(fit_l, axis).reshape(-1)
            move = firefly_attraction_pallas(
                pos_l, fit_l, beta0, gamma, tile_i, tile_j, interpret,
                pos_j=full_pos, fit_j=full_fit,
            )
            alpha_t = alpha0 * jnp.power(
                jnp.asarray(alpha_decay, jnp.float32),
                (it0 + step_i).astype(jnp.float32),
            )
            noise = alpha_t * (
                jax.random.uniform(kr, pos_l.shape, jnp.float32) - 0.5
            ) * (2.0 * half_width)
            pos_l = jnp.clip(
                pos_l + move + noise, -half_width, half_width
            )
            fit_l = objective(pos_l).astype(jnp.float32)
            # keep pad rows dark so they never attract anyone
            gcol = dev * shard_w + jnp.arange(shard_w)
            fit_l = jnp.where(gcol < n, fit_l, jnp.inf)
            b = jnp.argmin(fit_l)
            best_fit, best_pos = _exchange_best(
                fit_l[b], pos_l[b], best_fit, best_pos, dev, axis
            )
            return (pos_l, fit_l, best_pos, best_fit), None

        carry, _ = jax.lax.scan(
            gen, (pos_l, fit_l, best_pos, best_fit),
            jnp.arange(n_steps, dtype=jnp.int32),
        )
        return carry

    pos_p, fit_p, best_pos, best_fit = run(
        pos_p, fit_p,
        state.best_pos.astype(jnp.float32),
        state.best_fit.astype(jnp.float32),
        state.key, state.iteration,
    )
    return FireflyState(
        pos=pos_p[:n].astype(dt),
        fit=fit_p[:n].astype(state.fit.dtype),
        best_pos=best_pos.astype(state.best_pos.dtype),
        best_fit=best_fit.astype(state.best_fit.dtype),
        key=jax.random.fold_in(state.key, n_steps),
        iteration=state.iteration + n_steps,
    )


@partial(
    jax.jit,
    static_argnames=(
        "objective_name", "mesh", "n_steps", "axis", "migrate_every",
        "migrate_k", "w", "c1", "c2", "half_width", "vmax_frac",
        "tile_n", "rng", "interpret", "steps_per_kernel",
    ),
)
def fused_island_run_shmap(
    state,
    objective_name: str,
    mesh: Mesh,
    n_steps: int,
    axis: str = AGENT_AXIS,
    migrate_every: int = 25,
    migrate_k: int = 4,
    w: float | None = None,
    c1: float | None = None,
    c2: float | None = None,
    half_width: float = 5.12,
    vmax_frac: float = 0.5,
    tile_n: int | None = None,
    rng: str = "tpu",
    interpret: bool = False,
    steps_per_kernel: int = 8,
):
    """Multi-chip fused island PSO: the ISLAND axis shards over the
    mesh (requires islands % devices == 0) — each device runs the
    single-chip fused island block (ops/pallas/islands_fused.py) on
    its islands, and ring migration stays GLOBALLY EXACT: the
    within-shard ``jnp.roll`` of emigrant packs composes with one
    ``ppermute`` of the boundary pack to the next device, the same
    ring the portable islands path uses."""
    from ..ops.pallas.common import ceil_to
    from ..ops.pallas.islands_fused import (
        _island_gbest_update,
        _islands_step_t,
        _migrate_t,
    )
    from ..ops.pallas.pso_fused import (
        _auto_tile,
        host_uniforms,
        run_blocks,
        seed_base,
    )
    from ..ops.pso import C1 as _C1, C2 as _C2, W as _W

    w = _W if w is None else w
    c1 = _C1 if c1 is None else c1
    c2 = _C2 if c2 is None else c2
    pso = state.pso
    n_i, n, d = pso.pos.shape
    n_dev = mesh.shape[axis]
    if n_i % n_dev:
        raise ValueError(
            f"islands ({n_i}) must divide over devices ({n_dev})"
        )
    i_local = n_i // n_dev
    if rng == "host":
        steps_per_kernel = 1
    if tile_n is None:
        tile_n = _auto_tile(ceil_to(max(d, 8), 8))
    tile_n = min(tile_n, ceil_to(n, 128))
    n_l = ceil_to(n, tile_n)
    tpi = n_l // tile_n
    reps = -(-n_l // n)

    def prep(x_ind):                          # [I, n, D] -> [D, I*n_l]
        x = x_ind.astype(jnp.float32)
        if n_l != n:
            x = jnp.tile(x, (1, reps, 1))[:, :n_l]
        return x.reshape(n_i * n_l, d).T

    pos_t = prep(pso.pos)
    vel_t = prep(pso.vel)
    bpos_t = prep(pso.pbest_pos)
    bfit = pso.pbest_fit.astype(jnp.float32)
    if n_l != n:
        bfit = jnp.tile(bfit, (1, reps))[:, :n_l]
    bfit_t = bfit.reshape(1, n_i * n_l)

    gpos_ti = pso.gbest_pos.astype(jnp.float32).T          # [D, I]
    gfit_i = pso.gbest_fit.astype(jnp.float32)             # [I]

    stacked_keys = pso.key.ndim == 2
    base_key = pso.key[0] if stacked_keys else pso.key
    seed0 = seed_base(base_key)
    host_key = jax.random.fold_in(base_key, 0x15AD)
    n_tiles_local = i_local * tpi
    blocks_per_migration = max(1, migrate_every // steps_per_kernel)
    perm = [(i, (i + 1) % n_dev) for i in range(n_dev)]
    col = P(None, axis)

    @partial(
        shard_map, mesh=mesh,
        in_specs=(col, col, col, col, col, P(axis)),
        out_specs=(col, col, col, col, col, P(axis)),
        check_vma=False,
    )
    def run(pos_t, vel_t, bpos_t, bfit_t, gpos_ti, gfit_i):
        dev = lax.axis_index(axis)

        def ring_shift(em_pos, em_fit):
            # within-shard roll puts local island j-1's pack at j; the
            # pack now sitting at local island 0 (the shard's LAST
            # island's emigrants) is what the NEXT device's island 0
            # must receive — swap it over the device ring.
            rolled_pos = jnp.roll(em_pos, 1, axis=1)
            rolled_fit = jnp.roll(em_fit, 1, axis=0)
            recv_pos = lax.ppermute(rolled_pos[:, 0:1], axis, perm)
            recv_fit = lax.ppermute(rolled_fit[0:1], axis, perm)
            return (
                rolled_pos.at[:, 0:1].set(recv_pos),
                rolled_fit.at[0:1].set(recv_fit),
            )

        def block(carry, call_i, k):
            pos_t, vel_t, bpos_t, bfit_t, gpos_ti, gfit_i = carry
            seed = seed0 + (call_i * n_dev + dev) * n_tiles_local
            r1 = r2 = None
            if rng == "host":
                r1, r2 = host_uniforms(
                    host_key, call_i, pos_t.shape, fold=dev
                )
            pos_t, vel_t, bpos_t, bfit_t = _islands_step_t(
                seed, gpos_ti, pos_t, vel_t, bpos_t, bfit_t, r1, r2,
                objective_name=objective_name, w=w, c1=c1, c2=c2,
                half_width=half_width, vmax_frac=vmax_frac,
                tile_n=tile_n, tiles_per_island=tpi, rng=rng,
                interpret=interpret, k_steps=k,
            )

            due = (call_i + 1) % blocks_per_migration == 0

            def do_migrate(args):
                return _migrate_t(
                    *args, migrate_k, i_local, n_l, n_real=n,
                    shift_fn=ring_shift,
                )

            def no_migrate(args):
                # collectives must run on every branch-free path: the
                # ppermute inside do_migrate is manifest only when due,
                # and lax.cond with collectives requires both branches
                # shard-uniform — `due` is trace-level uniform (same
                # call_i on every shard), so this is safe.
                return args

            pos_t, vel_t, bpos_t, bfit_t = jax.lax.cond(
                due, do_migrate, no_migrate,
                (pos_t, vel_t, bpos_t, bfit_t),
            )
            gpos_ti, gfit_i = _island_gbest_update(
                bfit_t, bpos_t, gpos_ti, gfit_i, i_local, n_l
            )
            return (pos_t, vel_t, bpos_t, bfit_t, gpos_ti, gfit_i)

        return run_blocks(
            block,
            (pos_t, vel_t, bpos_t, bfit_t, gpos_ti, gfit_i),
            n_steps, steps_per_kernel,
        )

    pos_t, vel_t, bpos_t, bfit_t, gpos_ti, gfit_i = run(
        pos_t, vel_t, bpos_t, bfit_t, gpos_ti, gfit_i
    )
    dt = pso.pos.dtype

    def back(x_t):                            # [D, I*n_l] -> [I, n, D]
        return x_t.T.reshape(n_i, n_l, d)[:, :n].astype(dt)

    new_keys = (
        jax.vmap(lambda kk: jax.random.fold_in(kk, n_steps))(pso.key)
        if stacked_keys
        else jax.random.fold_in(pso.key, n_steps)
    )
    return state.replace(
        pso=pso.replace(
            pos=back(pos_t),
            vel=back(vel_t),
            pbest_pos=back(bpos_t),
            pbest_fit=bfit_t.reshape(n_i, n_l)[:, :n].astype(
                pso.pbest_fit.dtype
            ),
            gbest_pos=gpos_ti.T.astype(pso.gbest_pos.dtype),
            gbest_fit=gfit_i.astype(pso.gbest_fit.dtype),
            key=new_keys,
            iteration=pso.iteration + n_steps,
        ),
        iteration=state.iteration + n_steps,
    )


def fused_aco_run_shmap(
    state,
    mesh: Mesh,
    n_steps: int,
    n_ants: int,
    axis: str = AGENT_AXIS,
    alpha: float = 1.0,
    beta: float = 2.0,
    rho: float = 0.1,
    q0: float = 0.0,
    elite: float = 0.0,
    tile_a: int = 1024,
    rng: str = "tpu",
    interpret: bool = False,
):
    """Multi-chip fused ACO: the ANT axis is sharded, pheromone is
    replicated state.

    Each device constructs ``n_ants / n_dev`` whole tours with the
    fused kernel (ops/pallas/aco_fused.py) under a device-folded RNG
    stream, computes its local deposit matrix, and ``psum``s it over
    ICI; the tau update ``(1-rho)·tau + D + D^T`` is then replicated
    deterministic math, so every device carries an identical pheromone
    matrix with no further synchronization.  Unlike the optimizer-
    family drivers there is NO semantic lag here: the deposit is a sum
    over ants, so the sharded colony is exactly a single colony of the
    union ant set (only the RNG stream assignment differs from the
    1-device run).  Best tour/length ride the shared pmin/psum
    exchange (city indices are exact in f32 up to 2^24).
    """
    from ..ops.aco import deposit as _deposit
    from ..ops.pallas.aco_fused import (
        fused_construct_tours,
        fused_deposit_matrix,
    )

    n_dev = mesh.shape[axis]
    if n_ants % n_dev != 0:
        # A silent ceil round-up would run MORE ants than asked and
        # break the docstring's "exactly a single colony of the union
        # ant set" contract (advisor r3) — same raise-on-bad-split
        # rule as the other shmap drivers.
        raise ValueError(
            f"n_ants ({n_ants}) must divide evenly over the "
            f"{n_dev}-device '{axis}' mesh axis"
        )
    ants_local = n_ants // n_dev
    f32 = jnp.float32

    @partial(
        shard_map,
        mesh=mesh,
        in_specs=(P(), P(), P(), P(), P()),
        out_specs=(P(), P(), P(), P()),
        check_vma=False,
    )
    def run(tau, dist, best_tour_f, best_len, key):
        dev = lax.axis_index(axis)

        def body(carry, _):
            tau, best_tour_f, best_len, key = carry
            key, kc = jax.random.split(key)
            kd = jax.random.fold_in(kc, dev)
            tours, lengths = fused_construct_tours(
                tau, dist, kd, ants_local, alpha, beta, q0,
                tile_a=tile_a, rng=rng, interpret=interpret,
            )
            d = fused_deposit_matrix(
                tours, lengths, tile_a=tile_a, interpret=interpret
            )
            d = lax.psum(d, axis)
            loc = jnp.argmin(lengths)
            best_len, best_tour_f = _exchange_best(
                lengths[loc], tours[loc].astype(f32),
                best_len, best_tour_f, dev, axis,
            )
            tau = (1.0 - rho) * tau + d + d.T
            if elite > 0.0:
                # Same elitist reinforcement as fused_aco_step: the
                # exchanged global-best tour (replicated) deposits
                # elite/best_len on every device identically, so tau
                # stays replicated with no extra collective.
                tau = _deposit(
                    tau, best_tour_f.astype(jnp.int32)[None, :],
                    best_len[None] / elite, rho=0.0,
                )
            return (tau, best_tour_f, best_len, key), None

        (tau, best_tour_f, best_len, key), _ = lax.scan(
            body, (tau, best_tour_f, best_len, key), None,
            length=n_steps,
        )
        return tau, best_tour_f, best_len, key

    tau, bt_f, bl, key = run(
        state.tau, state.dist, state.best_tour.astype(f32),
        state.best_len, state.key,
    )
    return state.replace(
        tau=tau,
        best_tour=bt_f.astype(jnp.int32),
        best_len=bl,
        key=key,
        iteration=state.iteration + n_steps,
    )
