"""Multi-device execution: GSPMD sharding + explicit shard_map collectives.

Two complementary paths, per the scaling-book recipe ("pick a mesh,
annotate shardings, let XLA insert collectives"):

1. **GSPMD (default)** — ``shard_swarm`` / ``shard_pso`` place the state
   pytree on a mesh with the agent/particle axis sharded; the *same* jitted
   kernels (``swarm_tick``, ``pso_run``) then run partitioned, and XLA
   lowers every global reduction (election max-id, allocation argmax, gbest
   argmin) to ICI collectives automatically.

2. **Explicit shard_map** — ``pso_step_shmap`` and ``elect_shmap`` spell
   the collectives out (``lax.pmin``/``lax.pmax``/``lax.psum``) for the
   protocol-level reductions.  This is the TPU-native replacement for the
   reference's never-implemented UDP/TCP transport (agent.py:188-195) and
   its wire protocol (agent.py:184-214): the "message" is a reduction over
   the mesh axis, and delivery is the ICI fabric.
"""

from __future__ import annotations

from functools import partial
from typing import Callable

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
from jax import shard_map

from ..ops import pso as _pso
from ..state import NO_LEADER, SwarmState
from .mesh import AGENT_AXIS

_BIG_I32 = jnp.iinfo(jnp.int32).max


def _exchange_best(loc_fit, loc_pos, best_fit, best_pos, dev, axis):
    """Cross-device global-best exchange used by every shmap driver:
    ``pmin`` the per-shard best value, break ties to the lowest device
    index, ``psum``-broadcast the winner's position, and merge into the
    carried incumbent.  Returns ``(best_fit, best_pos)``."""
    gmin = lax.pmin(loc_fit, axis)
    mine = loc_fit == gmin
    win = lax.pmin(jnp.where(mine, dev, _BIG_I32), axis)
    gcand = lax.psum(jnp.where(dev == win, loc_pos, 0.0), axis)
    better = gmin < best_fit
    return (
        jnp.where(better, gmin, best_fit),
        jnp.where(better, gcand, best_pos),
    )


def _tree_shard_dim0(tree, mesh: Mesh, axis: str, n: int):
    """Shard every leaf whose dim 0 == n over ``axis``; replicate the rest."""
    sharded = NamedSharding(mesh, P(axis))
    repl = NamedSharding(mesh, P())

    def place(leaf):
        if getattr(leaf, "ndim", 0) >= 1 and leaf.shape[0] == n:
            return jax.device_put(leaf, sharded)
        return jax.device_put(leaf, repl)

    return jax.tree_util.tree_map(place, tree)


def shard_swarm(state: SwarmState, mesh: Mesh, axis: str = AGENT_AXIS):
    """Place a SwarmState with the agent axis sharded over the mesh.

    After this, calling the ordinary jitted ``swarm_tick`` runs SPMD: XLA
    partitions the per-agent updates and inserts all-reduces for the
    election/heartbeat/allocation reductions.  Requires n_agents % devices
    == 0 (pad the swarm with dead agents otherwise — alive-masking makes
    padding free).
    """
    return _tree_shard_dim0(state, mesh, axis, state.n_agents)


def shard_pso(state: _pso.PSOState, mesh: Mesh, axis: str = AGENT_AXIS):
    """Place a PSOState with the particle axis sharded over the mesh."""
    return _tree_shard_dim0(state, mesh, axis, state.pos.shape[0])


def pad_to_devices(n: int, n_devices: int) -> int:
    """Smallest multiple of n_devices ≥ n."""
    return -(-n // n_devices) * n_devices


# ---------------------------------------------------------------------------
# Explicit-collective path (shard_map)
# ---------------------------------------------------------------------------


def pso_step_shmap(
    state: _pso.PSOState,
    objective: Callable,
    mesh: Mesh,
    axis: str = AGENT_AXIS,
    w: float = _pso.W,
    c1: float = _pso.C1,
    c2: float = _pso.C2,
    half_width: float = 5.12,
    vmax_frac: float = 0.5,
) -> _pso.PSOState:
    """One PSO step with the cross-device gbest reduction written as
    explicit collectives: local argmin → ``lax.pmin`` for the value →
    min-device-index tie-break → ``lax.psum`` to broadcast the winning
    position.  Semantically identical to the GSPMD path."""

    shard = P(axis)
    spec = _pso.PSOState(
        pos=shard, vel=shard, pbest_pos=shard, pbest_fit=shard,
        gbest_pos=P(), gbest_fit=P(), key=P(), iteration=P(),
    )

    @partial(
        shard_map, mesh=mesh, in_specs=(spec,), out_specs=spec,
        check_vma=False,
    )
    def step(s: _pso.PSOState) -> _pso.PSOState:
        # Per-device keys: fold in the device index so shards draw
        # independent randomness from one replicated key.
        dev = lax.axis_index(axis)
        key = jax.random.fold_in(s.key, dev)
        key, k1, k2 = jax.random.split(key, 3)
        shape = s.pos.shape
        r1 = jax.random.uniform(k1, shape, s.pos.dtype)
        r2 = jax.random.uniform(k2, shape, s.pos.dtype)

        vel = (
            w * s.vel
            + c1 * r1 * (s.pbest_pos - s.pos)
            + c2 * r2 * (s.gbest_pos[None, :] - s.pos)
        )
        vmax = half_width * vmax_frac
        vel = jnp.clip(vel, -vmax, vmax)
        pos = jnp.clip(s.pos + vel, -half_width, half_width)

        fit = objective(pos)
        improved = fit < s.pbest_fit
        pbest_fit = jnp.where(improved, fit, s.pbest_fit)
        pbest_pos = jnp.where(improved[:, None], pos, s.pbest_pos)

        # Local best …
        loc = jnp.argmin(pbest_fit)
        loc_fit = pbest_fit[loc]
        loc_pos = pbest_pos[loc]
        # … global best via ICI collectives.
        gbest_fit, gbest_pos = _exchange_best(
            loc_fit, loc_pos, s.gbest_fit, s.gbest_pos, dev, axis
        )

        # Keep the carried key replicated (every shard advances the same
        # base key; shards re-diversify via fold_in above).
        base_key, _ = jax.random.split(s.key)
        return _pso.PSOState(
            pos=pos, vel=vel, pbest_pos=pbest_pos, pbest_fit=pbest_fit,
            gbest_pos=gbest_pos, gbest_fit=gbest_fit, key=base_key,
            iteration=s.iteration + 1,
        )

    return step(state)


@partial(
    jax.jit,
    static_argnames=(
        "objective", "mesh", "n_steps", "axis", "w", "c1", "c2",
        "half_width", "vmax_frac",
    ),
)
def pso_run_shmap(
    state: _pso.PSOState,
    objective: Callable,
    mesh: Mesh,
    n_steps: int,
    axis: str = AGENT_AXIS,
    w: float = _pso.W,
    c1: float = _pso.C1,
    c2: float = _pso.C2,
    half_width: float = 5.12,
    vmax_frac: float = 0.5,
) -> _pso.PSOState:
    """``n_steps`` explicit-collective PSO steps under one ``lax.scan`` —
    one dispatch for the whole rollout (important on oversubscribed hosts:
    CPU-backend collective rendezvous is time-limited, so per-step Python
    dispatch of 8-way collectives is avoidable flake surface)."""

    def body(s, _):
        return (
            pso_step_shmap(
                s, objective, mesh, axis, w, c1, c2, half_width, vmax_frac
            ),
            None,
        )

    state, _ = jax.lax.scan(body, state, None, length=n_steps)
    return state


@partial(
    jax.jit,
    static_argnames=(
        "objective_name", "mesh", "n_steps", "axis", "w", "c1", "c2",
        "half_width", "vmax_frac", "steps_per_kernel", "tile_n", "rng",
        "interpret",
    ),
)
def fused_pso_run_shmap(
    state: _pso.PSOState,
    objective_name: str,
    mesh: Mesh,
    n_steps: int,
    axis: str = AGENT_AXIS,
    w: float = _pso.W,
    c1: float = _pso.C1,
    c2: float = _pso.C2,
    half_width: float = 5.12,
    vmax_frac: float = 0.5,
    steps_per_kernel: int = 8,
    tile_n: int | None = None,
    rng: str = "tpu",
    interpret: bool = False,
) -> _pso.PSOState:
    """Multi-chip fused-Pallas PSO: each device runs ``steps_per_kernel``
    in-VMEM iterations of the fused kernel (ops/pallas/pso_fused.py) on its
    particle shard, then the shards exchange the global best over ICI
    (``pmin`` value + ``psum`` position broadcast) — the per-block gbest
    staleness of the single-chip kernel and the cross-device reduction
    cadence coincide, so multi-chip costs no extra semantic delay.

    N is padded (cyclic particle duplication, optimum-preserving) to
    devices × lane-tile.  On CPU meshes pass ``rng="host",
    interpret=True`` (tests do).  All padding/seed/loop/reassembly
    invariants are shared with the single-chip driver via the helpers in
    ops/pallas/pso_fused.py; only the gbest merge differs (collectives
    here, local compare there).
    """
    from ..ops.pallas.common import ceil_to
    from ..ops.pallas.pso_fused import (
        _auto_tile,
        best_of_block,
        fused_pso_step_t,
        host_uniforms,
        prep_padded_t,
        rebuild_state,
        run_blocks,
        seed_base,
    )

    n, d = state.pos.shape
    n_dev = mesh.shape[axis]
    if rng == "host":
        steps_per_kernel = 1
    if tile_n is None:
        tile_n = _auto_tile(ceil_to(max(d, 8), 8))
    tile_n = min(tile_n, ceil_to(-(-n // n_dev), 128))
    n_pad = ceil_to(n, n_dev * tile_n)
    n_tiles_local = (n_pad // n_dev) // tile_n

    pos_t, vel_t, bpos_t, bfit_t = prep_padded_t(state, n_pad)
    seed0 = seed_base(state.key)
    host_key = jax.random.fold_in(state.key, 0x5EED)

    col = P(None, axis)   # transposed layout: particles on the last axis

    @partial(
        shard_map,
        mesh=mesh,
        in_specs=(col, col, col, col, P(), P()),
        out_specs=(col, col, col, col, P(), P()),
        check_vma=False,
    )
    def run(pos_t, vel_t, bpos_t, bfit_t, gpos, gfit):
        dev = lax.axis_index(axis)

        def block(carry, call_i, k):
            pos_t, vel_t, bpos_t, bfit_t, gpos, gfit = carry
            seed = seed0 + (call_i * n_dev + dev) * n_tiles_local
            r1 = r2 = None
            if rng == "host":
                r1, r2 = host_uniforms(
                    host_key, call_i, pos_t.shape, fold=dev
                )
            pos_t, vel_t, bpos_t, bfit_t = fused_pso_step_t(
                seed, gpos[:, None], pos_t, vel_t, bpos_t, bfit_t, r1, r2,
                objective_name=objective_name, w=w, c1=c1, c2=c2,
                half_width=half_width, vmax_frac=vmax_frac, tile_n=tile_n,
                rng=rng, interpret=interpret, k_steps=k, track_best=False,
            )
            # Per-shard best, then cross-device gbest exchange.
            loc_fit, loc_pos = best_of_block(bfit_t, bpos_t)
            gfit, gpos = _exchange_best(
                loc_fit, loc_pos, gfit, gpos, dev, axis
            )
            return (pos_t, vel_t, bpos_t, bfit_t, gpos, gfit)

        return run_blocks(
            block,
            (pos_t, vel_t, bpos_t, bfit_t, gpos, gfit),
            n_steps, steps_per_kernel,
        )

    carry = run(
        pos_t, vel_t, bpos_t, bfit_t,
        state.gbest_pos.astype(jnp.float32),
        state.gbest_fit.astype(jnp.float32),
    )
    return rebuild_state(state, *carry, n_steps)


@partial(
    jax.jit,
    static_argnames=(
        "objective_name", "mesh", "n_steps", "axis", "half_width",
        "f_min", "f_max", "alpha", "gamma", "r0", "sigma_local",
        "steps_per_kernel", "tile_n", "rng", "interpret",
    ),
)
def fused_bat_run_shmap(
    state,
    objective_name: str,
    mesh: Mesh,
    n_steps: int,
    axis: str = AGENT_AXIS,
    half_width: float = 5.12,
    f_min: float | None = None,
    f_max: float | None = None,
    alpha: float | None = None,
    gamma: float | None = None,
    r0: float | None = None,
    sigma_local: float | None = None,
    steps_per_kernel: int = 8,
    tile_n: int | None = None,
    rng: str = "tpu",
    interpret: bool = False,
):
    """Multi-chip fused-Pallas bat colony (ops/pallas/bat_fused.py):
    each device runs ``steps_per_kernel`` in-VMEM generations on its bat
    shard, then the shards exchange the two global quantities over ICI —
    the incumbent best (``pmin`` value + ``psum`` position broadcast,
    exactly like the PSO driver) and the mean loudness (``pmean`` of the
    per-shard means; shards are equal-sized so that IS the colony mean).
    The per-block staleness of the single-chip kernel and the
    cross-device cadence coincide, so multi-chip costs no extra
    semantic delay.  On CPU meshes pass ``rng="host", interpret=True``.
    """
    from ..ops.bat import ALPHA, F_MAX, F_MIN, GAMMA, R0, SIGMA_LOCAL
    from ..ops.pallas.bat_fused import (
        bat_host_uniforms,
        fused_bat_step_t,
        rebuild_bat_state,
    )
    from ..ops.pallas.common import ceil_to, cyclic_pad_rows
    from ..ops.pallas.pso_fused import (
        _auto_tile,
        best_of_block,
        run_blocks,
        seed_base,
    )

    f_min = F_MIN if f_min is None else f_min
    f_max = F_MAX if f_max is None else f_max
    alpha = ALPHA if alpha is None else alpha
    gamma = GAMMA if gamma is None else gamma
    r0 = R0 if r0 is None else r0
    sigma_local = SIGMA_LOCAL if sigma_local is None else sigma_local

    n, d = state.pos.shape
    n_dev = mesh.shape[axis]
    if rng == "host":
        steps_per_kernel = 1
    if tile_n is None:
        tile_n = _auto_tile(ceil_to(max(d, 8), 8))
    tile_n = min(tile_n, ceil_to(-(-n // n_dev), 128))
    n_pad = ceil_to(n, n_dev * tile_n)
    n_tiles_local = (n_pad // n_dev) // tile_n

    pos_t = cyclic_pad_rows(state.pos, n_pad).T
    vel_t = cyclic_pad_rows(state.vel, n_pad).T
    fit_t = cyclic_pad_rows(state.fit, n_pad)[None, :]
    loud_t = cyclic_pad_rows(state.loudness, n_pad)[None, :]
    pulse_t = cyclic_pad_rows(state.pulse, n_pad)[None, :]
    seed0 = seed_base(state.key)
    host_key = jax.random.fold_in(state.key, 0xBA7)

    col = P(None, axis)

    @partial(
        shard_map,
        mesh=mesh,
        in_specs=(col, col, col, col, col, P(), P()),
        out_specs=(col, col, col, col, col, P(), P()),
        check_vma=False,
    )
    def run(pos_t, vel_t, fit_t, loud_t, pulse_t, bpos, bfit):
        dev = lax.axis_index(axis)

        def block(carry, call_i, k):
            pos_t, vel_t, fit_t, loud_t, pulse_t, bpos, bfit, it = carry
            scalars = jnp.stack(
                [seed0 + (call_i * n_dev + dev) * n_tiles_local, it]
            )
            rb = rw = re = ra = None
            if rng == "host":
                rb, rw, re, ra = bat_host_uniforms(
                    host_key, call_i, fit_t.shape, pos_t.shape, fold=dev
                )
            # Colony mean loudness: pmean of per-shard means (equal
            # shard sizes).  Padding duplicates are legal bats, so the
            # padded mean deviates only by duplicate weighting.
            mean_a = lax.pmean(jnp.mean(loud_t), axis)
            pos_t, vel_t, fit_t, loud_t, pulse_t = fused_bat_step_t(
                scalars, bpos[:, None], mean_a,
                pos_t, vel_t, fit_t, loud_t, pulse_t, rb, rw, re, ra,
                objective_name=objective_name, half_width=half_width,
                f_min=f_min, f_max=f_max, alpha=alpha, gamma=gamma,
                r0=r0, sigma_local=sigma_local, tile_n=tile_n, rng=rng,
                interpret=interpret, k_steps=k,
            )
            loc_fit, loc_pos = best_of_block(fit_t, pos_t)
            bfit, bpos = _exchange_best(
                loc_fit, loc_pos, bfit, bpos, dev, axis
            )
            return (
                pos_t, vel_t, fit_t, loud_t, pulse_t, bpos, bfit, it + k
            )

        carry = run_blocks(
            block,
            (pos_t, vel_t, fit_t, loud_t, pulse_t, bpos, bfit,
             state.iteration),
            n_steps, steps_per_kernel,
        )
        return carry[:7]

    carry = run(
        pos_t, vel_t, fit_t, loud_t, pulse_t,
        state.best_pos.astype(jnp.float32),
        state.best_fit.astype(jnp.float32),
    )
    return rebuild_bat_state(state, *carry, n_steps)


@partial(
    jax.jit,
    static_argnames=(
        "objective", "mesh", "n_steps", "n", "axis", "half_width",
        "sigma", "lr", "momentum",
    ),
)
def es_run_shmap(
    state,
    objective,
    mesh: Mesh,
    n_steps: int,
    n: int = 256,
    axis: str = AGENT_AXIS,
    half_width: float = 5.12,
    sigma: float | None = None,
    lr: float | None = None,
    momentum: float | None = None,
):
    """Multi-chip OpenAI-ES — the canonical distributed-ES design
    (Salimans et al. 2017) on ICI: every device draws its own antithetic
    perturbation shard from a device-folded key and evaluates it
    locally; the only cross-device traffic per generation is the
    ``psum`` of the partial gradient estimate ``shaped^T @ eps`` plus
    the best-sample exchange — O(D) bytes, independent of population
    size.  Rank shaping needs the global fitness vector, so fitnesses
    are ``all_gather``ed ([n] scalars — also tiny).

    ``n`` is the GLOBAL population (must divide by mesh size, halves
    antithetic per device).  Results match the single-chip ``es_run``
    semantics (different RNG stream).
    """
    from ..ops.es import ESState, LR, MOMENTUM, SIGMA, centered_ranks

    sigma = SIGMA if sigma is None else sigma
    lr = LR if lr is None else lr
    momentum = MOMENTUM if momentum is None else momentum
    n_dev = mesh.shape[axis]
    if n % (2 * n_dev):
        raise ValueError(
            f"global population n ({n}) must be a multiple of "
            f"2 * devices ({2 * n_dev})"
        )
    n_loc = n // n_dev
    d = state.mean.shape[0]
    s = sigma * half_width

    @partial(
        shard_map,
        mesh=mesh,
        in_specs=(P(), P(), P(), P(), P()),
        out_specs=(P(), P(), P(), P(), P()),
        check_vma=False,
    )
    def run(mean, mom, best_pos, best_fit, key):
        dev = lax.axis_index(axis)

        def step(carry, _):
            mean, mom, best_pos, best_fit, key = carry
            key, kd = jax.random.split(key)
            kd = jax.random.fold_in(kd, dev)
            eps_half = jax.random.normal(
                kd, (n_loc // 2, d), mean.dtype
            )
            eps = jnp.concatenate([eps_half, -eps_half], axis=0)
            pop = jnp.clip(mean + s * eps, -half_width, half_width)
            fit = objective(pop)                        # [n_loc]

            # Global centered ranks need every fitness; the gathered
            # vector is n scalars — negligible next to the [n, D] work
            # that stayed device-local.
            all_fit = lax.all_gather(fit, axis)         # [n_dev, n_loc]
            shaped_all = centered_ranks(all_fit.reshape(-1))
            shaped = lax.dynamic_slice(
                shaped_all, (dev * n_loc,), (n_loc,)
            )
            grad = lax.psum((shaped @ eps) / (n * s), axis)
            mom = momentum * mom - lr * half_width * grad
            mean = jnp.clip(mean + mom, -half_width, half_width)

            b = jnp.argmin(fit)
            best_fit, best_pos = _exchange_best(
                fit[b], pop[b], best_fit, best_pos, dev, axis
            )
            mean_fit = objective(mean[None, :])[0]
            better_mean = mean_fit < best_fit
            best_fit = jnp.where(better_mean, mean_fit, best_fit)
            best_pos = jnp.where(better_mean, mean, best_pos)
            return (mean, mom, best_pos, best_fit, key), None

        carry, _ = jax.lax.scan(
            step, (mean, mom, best_pos, best_fit, key), None,
            length=n_steps,
        )
        return carry

    mean, mom, best_pos, best_fit, key = run(
        state.mean, state.mom, state.best_pos, state.best_fit, state.key
    )
    return ESState(
        mean=mean,
        mom=mom,
        best_pos=best_pos,
        best_fit=best_fit,
        key=key,
        iteration=state.iteration + n_steps,
    )


@partial(
    jax.jit,
    static_argnames=(
        "objective_name", "mesh", "n_steps", "axis", "half_width",
        "t_max", "steps_per_kernel", "tile_n", "rng", "interpret",
    ),
)
def fused_gwo_run_shmap(
    state,
    objective_name: str,
    mesh: Mesh,
    n_steps: int,
    axis: str = AGENT_AXIS,
    half_width: float = 5.12,
    t_max: int = 500,
    steps_per_kernel: int = 8,
    tile_n: int | None = None,
    rng: str = "tpu",
    interpret: bool = False,
):
    """Multi-chip fused-Pallas GWO: each device runs ``steps_per_kernel``
    in-VMEM generations on its wolf shard; between blocks the three
    leaders are re-elected globally — each shard contributes its local
    top-3 (vs the incumbents) via ``all_gather`` ([n_dev, 3] candidates,
    O(D) bytes) and every shard deterministically re-ranks the same
    pool.  Leader staleness equals the single-chip kernel's per-block
    delay, so multi-chip costs no extra semantic lag."""
    from ..ops.gwo import GWOState
    from ..ops.pallas.common import ceil_to, cyclic_pad_rows
    from ..ops.pallas.gwo_fused import fused_gwo_step_t
    from ..ops.pallas.pso_fused import (
        _auto_tile,
        host_uniforms,
        run_blocks,
        seed_base,
    )

    n, d = state.pos.shape
    n_dev = mesh.shape[axis]
    if rng == "host":
        steps_per_kernel = 1
    if tile_n is None:
        tile_n = _auto_tile(ceil_to(max(8 * d, 8), 8))
    tile_n = min(tile_n, ceil_to(-(-n // n_dev), 128))
    n_pad = ceil_to(n, n_dev * tile_n)
    n_tiles_local = (n_pad // n_dev) // tile_n

    pos_t = cyclic_pad_rows(state.pos, n_pad).T
    fit_t = cyclic_pad_rows(state.fit, n_pad)[None, :]
    seed0 = seed_base(state.key)
    host_key = jax.random.fold_in(state.key, 0x6E0)

    col = P(None, axis)

    @partial(
        shard_map,
        mesh=mesh,
        in_specs=(col, col, P(), P()),
        out_specs=(col, col, P(), P()),
        check_vma=False,
    )
    def run(pos_t, fit_t, leaders, leader_fit):
        dev = lax.axis_index(axis)

        def block(carry, call_i, k):
            pos_t, fit_t, leaders, leader_fit, it = carry
            scalars = jnp.stack(
                [seed0 + (call_i * n_dev + dev) * n_tiles_local, it]
            )
            ra = rc = None
            if rng == "host":
                ra, rc = host_uniforms(
                    host_key, call_i, (3 * d,) + pos_t.shape[1:],
                    fold=dev,
                )
            pos_t, fit_t = fused_gwo_step_t(
                scalars, leaders, pos_t, ra, rc,
                objective_name=objective_name, half_width=half_width,
                t_max=t_max, tile_n=tile_n, rng=rng,
                interpret=interpret, k_steps=k,
            )
            # Each shard contributes its PACK-local top-3 only; the
            # replicated incumbents join the pool exactly once in the
            # global re-rank (gathering incumbents from every shard
            # would flood the pool with n_dev duplicates and collapse
            # alpha/beta/delta into copies of one wolf).
            _, loc3 = jax.lax.top_k(-fit_t[0], 3)
            cand_fit = jnp.concatenate([
                leader_fit,
                lax.all_gather(fit_t[0, loc3], axis).reshape(-1),
            ])                                    # [3 + n_dev * 3]
            cand_pos = jnp.concatenate([
                leaders,
                lax.all_gather(pos_t.T[loc3], axis).reshape(-1, d),
            ], axis=0)
            _, top3 = jax.lax.top_k(-cand_fit, 3)
            return (
                pos_t, fit_t, cand_pos[top3], cand_fit[top3], it + k
            )

        carry = run_blocks(
            block,
            (pos_t, fit_t, leaders, leader_fit, state.iteration),
            n_steps, steps_per_kernel,
        )
        return carry[:4]

    pos_t, fit_t, leaders, leader_fit = run(
        pos_t, fit_t,
        state.leaders.astype(jnp.float32),
        state.leader_fit.astype(jnp.float32),
    )
    dt = state.pos.dtype
    return GWOState(
        pos=pos_t.T[:n].astype(dt),
        fit=fit_t[0, :n].astype(state.fit.dtype),
        leaders=leaders.astype(state.leaders.dtype),
        leader_fit=leader_fit.astype(state.leader_fit.dtype),
        key=jax.random.fold_in(state.key, n_steps),
        iteration=state.iteration + n_steps,
    )


@partial(
    jax.jit,
    static_argnames=(
        "objective_name", "mesh", "n_steps", "axis", "f", "cr",
        "half_width", "steps_per_kernel", "tile_n", "rng", "interpret",
    ),
)
def fused_de_run_shmap(
    state,
    objective_name: str,
    mesh: Mesh,
    n_steps: int,
    axis: str = AGENT_AXIS,
    f: float | None = None,
    cr: float | None = None,
    half_width: float = 5.12,
    steps_per_kernel: int = 8,
    tile_n: int | None = None,
    rng: str = "tpu",
    interpret: bool = False,
):
    """Multi-chip fused-Pallas DE: each device runs rotational-donor DE
    blocks (ops/pallas/de_fused.py) on its population shard; the global
    best is exchanged over ICI per block (``pmin`` + ``psum``
    broadcast).  Donor pools are SHARD-LOCAL between exchanges — the
    mesh behaves like an island model whose islands share their best
    every ``steps_per_kernel`` generations, the same semantic lag class
    as every other fused shmap driver here.  Each shard needs >= 4 lane
    tiles for distinct donor shifts (n >= devices * 512)."""
    from ..ops.de import DEState, CR as _CR, F as _F
    from ..ops.pallas.common import ceil_to, cyclic_pad_rows
    from ..ops.pallas.de_fused import (
        _auto_tile,
        _distinct_tile_shifts,
        best_of_block,
        fused_de_step_t,
        host_uniforms,
        run_blocks,
        seed_base,
        shrink_tile_for_donors,
    )

    f = _F if f is None else f
    cr = _CR if cr is None else cr
    n, d = state.pos.shape
    n_dev = mesh.shape[axis]
    if rng == "host":
        steps_per_kernel = 1
    steps_per_kernel = min(steps_per_kernel, 32)   # VMEM (see de_fused)
    if tile_n is None:
        tile_n = _auto_tile(ceil_to(max(d, 8), 8))
    tile_n = min(tile_n, ceil_to(-(-n // n_dev), 128))
    tile_n, n_pad, n_tiles_local = shrink_tile_for_donors(
        n, tile_n, per_shard=n_dev
    )

    pos_t = cyclic_pad_rows(state.pos, n_pad).T
    fit_t = cyclic_pad_rows(state.fit, n_pad)[None, :]
    seed0 = seed_base(state.key)
    host_key = jax.random.fold_in(state.key, 0xDE)
    shift_key = jax.random.fold_in(state.key, 0x5F1F7)

    col = P(None, axis)

    @partial(
        shard_map,
        mesh=mesh,
        in_specs=(col, col, P(), P()),
        out_specs=(col, col, P(), P()),
        check_vma=False,
    )
    def run(pos_t, fit_t, best_pos, best_fit):
        dev = lax.axis_index(axis)

        def block(carry, call_i, k):
            pos_t, fit_t, best_pos, best_fit = carry
            kk = jax.random.fold_in(
                jax.random.fold_in(shift_key, call_i), dev
            )
            sa, sb, sc = _distinct_tile_shifts(kk, n_tiles_local)
            lanes = jax.random.randint(
                jax.random.fold_in(kk, 1), (3,), 0, tile_n
            )
            scalars = jnp.concatenate([
                jnp.stack([
                    seed0 + (call_i * n_dev + dev) * n_tiles_local,
                    sa, sb, sc,
                ]),
                lanes,
            ]).astype(jnp.int32)
            r = None
            if rng == "host":
                (r, _) = host_uniforms(
                    host_key, call_i, pos_t.shape, fold=dev
                )
            pos_t, fit_t = fused_de_step_t(
                scalars, pos_t, fit_t, r,
                objective_name=objective_name, f=f, cr=cr,
                half_width=half_width, tile_n=tile_n, rng=rng,
                interpret=interpret, k_steps=k,
            )
            loc_fit, loc_pos = best_of_block(fit_t, pos_t)
            best_fit, best_pos = _exchange_best(
                loc_fit, loc_pos, best_fit, best_pos, dev, axis
            )
            return (pos_t, fit_t, best_pos, best_fit)

        return run_blocks(
            block, (pos_t, fit_t, best_pos, best_fit),
            n_steps, steps_per_kernel,
        )

    pos_t, fit_t, best_pos, best_fit = run(
        pos_t, fit_t,
        state.best_pos.astype(jnp.float32),
        state.best_fit.astype(jnp.float32),
    )
    dt = state.pos.dtype
    return DEState(
        pos=pos_t.T[:n].astype(dt),
        fit=fit_t[0, :n].astype(state.fit.dtype),
        best_pos=best_pos.astype(state.best_pos.dtype),
        best_fit=best_fit.astype(state.best_fit.dtype),
        key=jax.random.fold_in(state.key, n_steps),
        iteration=state.iteration + n_steps,
    )


@partial(
    jax.jit,
    static_argnames=(
        "objective_name", "mesh", "n_steps", "axis", "half_width",
        "t_max", "spiral_b", "steps_per_kernel", "tile_n", "rng",
        "interpret",
    ),
)
def fused_woa_run_shmap(
    state,
    objective_name: str,
    mesh: Mesh,
    n_steps: int,
    axis: str = AGENT_AXIS,
    half_width: float = 5.12,
    t_max: int = 500,
    spiral_b: float | None = None,
    steps_per_kernel: int = 8,
    tile_n: int | None = None,
    rng: str = "tpu",
    interpret: bool = False,
):
    """Multi-chip fused-Pallas WOA: each device runs rotational-peer
    blocks (ops/pallas/woa_fused.py) on its pod shard; the incumbent
    best is exchanged over ICI per block (``pmin`` + ``psum``
    broadcast) — per-block best staleness and the cross-device cadence
    coincide, like every fused shmap driver here.  Random peers are
    shard-local between exchanges."""
    from ..ops.pallas.common import ceil_to, cyclic_pad_rows
    from ..ops.pallas.woa_fused import (
        _auto_tile,
        best_of_block,
        fused_woa_step_t,
        host_uniforms,
        run_blocks,
        seed_base,
    )
    from ..ops.woa import SPIRAL_B, WOAState

    spiral_b = float(SPIRAL_B if spiral_b is None else spiral_b)
    n, d = state.pos.shape
    n_dev = mesh.shape[axis]
    if rng == "host":
        steps_per_kernel = 1
    steps_per_kernel = min(steps_per_kernel, 32)   # VMEM (see woa_fused)
    if tile_n is None:
        tile_n = _auto_tile(ceil_to(max(d, 8), 8))
    tile_n = min(tile_n, ceil_to(-(-n // n_dev), 128))
    n_pad = ceil_to(n, n_dev * tile_n)
    n_tiles_local = (n_pad // n_dev) // tile_n

    pos_t = cyclic_pad_rows(state.pos, n_pad).T
    fit_t = cyclic_pad_rows(state.fit, n_pad)[None, :]
    seed0 = seed_base(state.key)
    host_key = jax.random.fold_in(state.key, 0x30A)
    shift_key = jax.random.fold_in(state.key, 0x0A1)

    col = P(None, axis)

    @partial(
        shard_map,
        mesh=mesh,
        in_specs=(col, col, P(), P()),
        out_specs=(col, col, P(), P()),
        check_vma=False,
    )
    def run(pos_t, fit_t, best_pos, best_fit):
        dev = lax.axis_index(axis)

        def block(carry, call_i, k):
            pos_t, fit_t, best_pos, best_fit, it = carry
            kk = jax.random.fold_in(
                jax.random.fold_in(shift_key, call_i), dev
            )
            tshift = jax.random.randint(kk, (), 0, n_tiles_local)
            lshift = jax.random.randint(
                jax.random.fold_in(kk, 1), (), 0, tile_n
            )
            scalars = jnp.stack([
                seed0 + (call_i * n_dev + dev) * n_tiles_local,
                tshift, it, lshift,
            ]).astype(jnp.int32)
            r_a = r_c = r_p = r_l = None
            if rng == "host":
                r_a, r_c = host_uniforms(
                    host_key, call_i, pos_t.shape, fold=dev
                )
                r_p, r_l = host_uniforms(
                    host_key, call_i, fit_t.shape, fold=1000 + dev
                )
            pos_t, fit_t = fused_woa_step_t(
                scalars, best_pos[:, None], pos_t, r_a, r_c, r_p, r_l,
                objective_name=objective_name, half_width=half_width,
                t_max=t_max, spiral_b=spiral_b, tile_n=tile_n, rng=rng,
                interpret=interpret, k_steps=k,
            )
            loc_fit, loc_pos = best_of_block(fit_t, pos_t)
            best_fit, best_pos = _exchange_best(
                loc_fit, loc_pos, best_fit, best_pos, dev, axis
            )
            return (pos_t, fit_t, best_pos, best_fit, it + k)

        carry = run_blocks(
            block,
            (pos_t, fit_t, best_pos, best_fit, state.iteration),
            n_steps, steps_per_kernel,
        )
        return carry[:4]

    pos_t, fit_t, best_pos, best_fit = run(
        pos_t, fit_t,
        state.best_pos.astype(jnp.float32),
        state.best_fit.astype(jnp.float32),
    )
    dt = state.pos.dtype
    return WOAState(
        pos=pos_t.T[:n].astype(dt),
        fit=fit_t[0, :n].astype(state.fit.dtype),
        best_pos=best_pos.astype(state.best_pos.dtype),
        best_fit=best_fit.astype(state.best_fit.dtype),
        key=jax.random.fold_in(state.key, n_steps),
        iteration=state.iteration + n_steps,
    )


def elect_shmap(
    alive: jax.Array,
    agent_id: jax.Array,
    mesh: Mesh,
    axis: str = AGENT_AXIS,
) -> jax.Array:
    """Bully-election fixed point as an explicit cross-device reduction:
    leader = max alive id (agent.py:244-251 collapsed to one ``lax.pmax``).
    Returns the replicated winning id (NO_LEADER if none alive)."""

    @partial(
        shard_map, mesh=mesh, in_specs=(P(axis), P(axis)), out_specs=P(),
        check_vma=False,
    )
    def elect(alive_l, id_l):
        local = jnp.max(jnp.where(alive_l, id_l, NO_LEADER))
        return lax.pmax(local, axis)[None]

    return elect(alive, agent_id)[0]
