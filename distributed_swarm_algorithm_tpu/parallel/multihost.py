"""Multi-host (multi-slice) deployment helpers.

The reference's scale-out story was one OS process per agent over a UDP
transport that was never implemented (/root/reference/agent.py:188-195,
349-360).  This framework's distributed backend is XLA collectives; this
module is the thin layer that takes it from one host to a pod:

  - ``init_distributed()``: wraps ``jax.distributed.initialize`` with the
    standard TPU-pod environment autodetection (on Cloud TPU the
    coordinator/process ids come from the metadata server, so a bare call
    suffices; explicit args cover manual clusters).
  - ``hybrid_mesh()``: builds the canonical 2-level mesh for swarm
    workloads — an ``islands`` axis laid out across *hosts* (slow DCN
    hops carry only the periodic migration / gbest exchange) and an
    ``agents`` axis across the *devices within each host* (fast ICI
    carries the per-tick election/allocation/separation collectives).
    This is the sharding-first equivalent of hierarchical NCCL
    communicators: the axis layout, not a comms library, decides which
    traffic rides which interconnect.
  - ``is_coordinator()`` / ``coord_print()``: process-0 guards for logs
    and checkpoint writes.

Everything here is shape/layout logic over ``jax.devices()`` and is
exercised on the 8-virtual-device CPU mesh in tests; the actual DCN path
needs real multi-host hardware and is validated by the same code paths
(`shard_map` + named-axis collectives are topology-agnostic by design).
"""

from __future__ import annotations

import builtins
from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh

from .mesh import AGENT_AXIS, ISLAND_AXIS


def init_distributed(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> None:
    """Start the JAX distributed runtime for a multi-host deployment.

    On Cloud TPU pods, call with no arguments before any other JAX call;
    each host then sees only its local devices in ``jax.local_devices()``
    while ``jax.devices()`` spans the pod.  No-op if already initialized.
    """
    if jax.distributed.is_initialized():
        return
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
    )


def is_coordinator() -> bool:
    return jax.process_index() == 0


def coord_print(*args, **kwargs) -> None:
    """print() on the coordinator process only (multi-host log dedup)."""
    if is_coordinator():
        builtins.print(*args, **kwargs)


def describe_mesh(mesh: Mesh) -> dict:
    """JSON-safe mesh topology for run manifests (r11, the
    ``swarmscope`` run directory): axis names/sizes, device platform,
    and the process (host) count — the context a telemetry summary or
    compile record is meaningless without on a pod.  Pure metadata:
    no collective, no device sync."""
    devices = list(mesh.devices.flat)
    return {
        "axes": {
            name: int(size)
            for name, size in zip(mesh.axis_names, mesh.devices.shape)
        },
        "n_devices": len(devices),
        "platform": devices[0].platform if devices else "none",
        "n_processes": len({d.process_index for d in devices}),
    }


def coord_write_json(path: str, obj) -> bool:
    """Write ``obj`` as JSON at ``path`` on the COORDINATOR process
    only — the multi-host guard for every run-directory artifact
    (manifest, telemetry summary, compile records): without it each
    host of a pod would race the same file.  Returns True iff this
    process wrote.  Creates parent directories."""
    if not is_coordinator():
        return False
    import json
    import os

    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    with open(path, "w") as fh:
        json.dump(obj, fh, indent=1, sort_keys=True)
        fh.write("\n")
    return True


def hybrid_mesh(
    islands_per_host: int = 1,
    devices: Optional[Sequence] = None,
    axis_names: Sequence[str] = (ISLAND_AXIS, AGENT_AXIS),
) -> Mesh:
    """A 2-D ``(islands, agents)`` mesh aligned with the host topology.

    Device order groups each host's local devices contiguously, so the
    leading (``islands``) axis cuts *between* hosts: collectives over the
    trailing (``agents``) axis stay inside a host's ICI domain, and only
    island-level exchanges (``parallel/islands.py`` migration, global-best
    reduction) cross the DCN.

    ``islands_per_host`` further splits a host's devices into multiple
    islands (> 1 shrinks each island's ICI group; the agents axis size is
    ``local_count // islands_per_host``).
    """
    if devices is None:
        devices = jax.devices()
    # Do not trust jax.devices() global order to group hosts contiguously
    # (on some topologies it interleaves processes, which would silently
    # put the per-tick 'agents' collectives on the DCN): sort explicitly
    # by owning process, stably, so each host's devices form one row group.
    devices = sorted(devices, key=lambda d: (d.process_index, d.id))
    # Derive the host split from the devices actually given (a subset may
    # span fewer processes than the whole job — jax.process_count() would
    # then cut the islands axis inside a host).
    n_proc = max(len({d.process_index for d in devices}), 1)
    local = len(devices) // n_proc
    if local * n_proc != len(devices):
        raise ValueError(
            f"devices ({len(devices)}) not evenly split over "
            f"{n_proc} processes"
        )
    if islands_per_host < 1 or local % islands_per_host:
        raise ValueError(
            f"islands_per_host ({islands_per_host}) must divide the "
            f"per-host device count ({local})"
        )
    n_islands = n_proc * islands_per_host
    per_island = local // islands_per_host
    grid = np.asarray(devices).reshape(n_islands, per_island)
    return Mesh(grid, tuple(axis_names))
