"""Dimension-axis (tensor-parallel-style) sharding for very-high-D
objectives.

SURVEY.md §2a's optional TP row: the reference has no tensors at all
(its state is two Python floats, /root/reference/agent.py:47), so the
only meaning "tensor parallel" can take here is sharding the *search
dimension* D over the mesh — worthwhile once D is large enough that a
particle no longer fits a lane tile comfortably (Ackley-100D and up,
e.g. neuroevolution parameter vectors at D = 10^4..10^6).

Design (the scaling-book recipe, applied to the D axis):

* every per-dimension array shards its LAST axis over ``"dim"`` —
  ``pos/vel/pbest_pos [N, D]`` as ``P(None, "dim")``, ``gbest_pos [D]``
  / ``mean [D]`` as ``P("dim")``; per-particle scalars ([N] fitness)
  and the RNG key replicate;
* the PSO/ES update rules are **dimension-wise independent** — the
  velocity/position/momentum updates never mix dimensions, so they run
  entirely device-local with zero communication;
* the only cross-dimension coupling is the *objective*: separable
  benchmark objectives reduce over D, so each device computes partial
  sums over its D-shard and one ``lax.psum`` of ``[P, N]`` scalars
  (P = 1-2 partials) produces the global fitness — O(N) bytes per step
  over ICI, independent of D.  Fitness-derived bookkeeping (pbest
  masks, argmin, centered ranks) is replicated arithmetic on identical
  inputs, so no further collectives are needed.

The objective goes through ``PARTIAL_OBJECTIVES`` — a registry of
``(local, combine)`` pairs, where ``local(x_local, offset, d_global) ->
[P, N]`` partial sums and ``combine(psummed [P, N], d_global) -> [N]``
applies the non-separable tail (Ackley's exponentials, Zakharov's
powers).  Objectives with true cross-dimension chains (Rosenbrock's
x_{i+1} terms, Levy) would need halo exchange and are not registered —
callers get a clear KeyError, and the agent/particle-axis sharding in
parallel/sharding.py remains the right tool for them.
"""

from __future__ import annotations

from functools import partial
from typing import Callable, Dict, Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..ops.es import ESState, LR, MOMENTUM, SIGMA, centered_ranks
from ..utils.compat import shard_map
from ..utils.compile_watch import watched
from ..ops.pso import C1, C2, PSOState, W

DIM_AXIS = "dim"

_TWO_PI = 2.0 * jnp.pi


# ------------------------------------------------------------ objectives

def _sphere_local(x, offset, d):
    return jnp.sum(x * x, axis=1)[None, :]


def _sphere_combine(s, d):
    return s[0]


def _rastrigin_local(x, offset, d):
    return jnp.sum(
        x * x - 10.0 * jnp.cos(_TWO_PI * x), axis=1
    )[None, :]


def _rastrigin_combine(s, d):
    return 10.0 * d + s[0]


def _ackley_local(x, offset, d):
    return jnp.stack(
        [
            jnp.sum(x * x, axis=1),
            jnp.sum(jnp.cos(_TWO_PI * x), axis=1),
        ]
    )


def _ackley_combine(s, d):
    s1 = s[0] / d
    s2 = s[1] / d
    return (
        -20.0 * jnp.exp(-0.2 * jnp.sqrt(s1)) - jnp.exp(s2) + 20.0 + jnp.e
    )


def _zakharov_local(x, offset, d):
    i = offset + 1.0 + jnp.arange(x.shape[1], dtype=x.dtype)
    return jnp.stack(
        [
            jnp.sum(x * x, axis=1),
            jnp.sum(0.5 * i[None, :] * x, axis=1),
        ]
    )


def _zakharov_combine(s, d):
    return s[0] + s[1] ** 2 + s[1] ** 4


def _styblinski_local(x, offset, d):
    return jnp.sum(x**4 - 16.0 * x * x + 5.0 * x, axis=1)[None, :]


def _styblinski_combine(s, d):
    return 0.5 * s[0] + 39.16616570377142 * d


# name -> (local partial-sum fn, combine fn).  ``local`` sees only the
# device's D-shard (plus its global offset); ``combine`` sees the
# psum'ed partials.  Semantics match ops/objectives.py exactly.
PARTIAL_OBJECTIVES: Dict[str, Tuple[Callable, Callable]] = {
    "sphere": (_sphere_local, _sphere_combine),
    "rastrigin": (_rastrigin_local, _rastrigin_combine),
    "ackley": (_ackley_local, _ackley_combine),
    "zakharov": (_zakharov_local, _zakharov_combine),
    "styblinski_tang": (_styblinski_local, _styblinski_combine),
}


def dimshard_supported(objective_name: str) -> bool:
    return objective_name in PARTIAL_OBJECTIVES


# ------------------------------------------------------------- placement

def shard_pso_dim(
    state: PSOState, mesh: Mesh, axis: str = DIM_AXIS
) -> PSOState:
    """Place a PSOState with the dimension axis sharded over ``axis``."""
    nd2 = NamedSharding(mesh, P(None, axis))
    nd1 = NamedSharding(mesh, P(axis))
    repl = NamedSharding(mesh, P())
    return PSOState(
        pos=jax.device_put(state.pos, nd2),
        vel=jax.device_put(state.vel, nd2),
        pbest_pos=jax.device_put(state.pbest_pos, nd2),
        pbest_fit=jax.device_put(state.pbest_fit, repl),
        gbest_pos=jax.device_put(state.gbest_pos, nd1),
        gbest_fit=jax.device_put(state.gbest_fit, repl),
        key=jax.device_put(state.key, repl),
        iteration=jax.device_put(state.iteration, repl),
    )


def shard_es_dim(
    state: ESState, mesh: Mesh, axis: str = DIM_AXIS
) -> ESState:
    """Place an ESState with the dimension axis sharded over ``axis``."""
    nd1 = NamedSharding(mesh, P(axis))
    repl = NamedSharding(mesh, P())
    return ESState(
        mean=jax.device_put(state.mean, nd1),
        mom=jax.device_put(state.mom, nd1),
        best_pos=jax.device_put(state.best_pos, nd1),
        best_fit=jax.device_put(state.best_fit, repl),
        key=jax.device_put(state.key, repl),
        iteration=jax.device_put(state.iteration, repl),
    )


# ---------------------------------------------------------------- drivers

@watched("pso-dimshard")
@partial(
    jax.jit,
    static_argnames=(
        "objective_name", "mesh", "n_steps", "axis", "w", "c1", "c2",
        "half_width", "vmax_frac", "telemetry",
    ),
)
def pso_run_dimshard(
    state: PSOState,
    objective_name: str,
    mesh: Mesh,
    n_steps: int,
    axis: str = DIM_AXIS,
    w: float = W,
    c1: float = C1,
    c2: float = C2,
    half_width: float = 5.12,
    vmax_frac: float = 0.5,
    telemetry: bool = False,
):
    """``n_steps`` of gbest PSO with the DIMENSION axis sharded.

    Same update rule as ``ops.pso.pso_step`` (trajectories differ only
    in RNG stream: each device draws its own [N, D_loc] uniforms from a
    device-folded key).  Communication per step: one ``psum`` of
    ``[P, N]`` objective partials — O(N) bytes regardless of D.

    ``telemetry=True`` (r11, static gate): per-step flight-recorder
    records ride the scan and the return becomes ``(state, telem)``.
    Speed gauges need the cross-shard norm, so the recorder adds one
    ``psum`` of per-particle squared partials per step — collection
    only READS the carried values, so the trajectory stays
    bitwise-equal (tests/test_mesh_telemetry.py); disabled, the trace
    is the identical telemetry-free HLO (trace-time Python gate).
    ``shard_max_alive``/``shard_imbalance`` report the per-device
    D-shard residency via ``lax.pmax``/``lax.pmin``.
    """
    local, combine = PARTIAL_OBJECTIVES[objective_name]
    n, d = state.pos.shape
    n_dev = mesh.shape[axis]
    if d % n_dev:
        raise ValueError(
            f"dim D ({d}) must be a multiple of mesh axis "
            f"{axis!r} size ({n_dev})"
        )
    d_loc = d // n_dev
    vmax = half_width * vmax_frac

    carry_spec = (
        P(None, axis), P(None, axis), P(None, axis), P(),
        P(axis), P(), P(),
    )
    out_spec = (carry_spec, P()) if telemetry else carry_spec

    @partial(
        shard_map,
        mesh=mesh,
        in_specs=carry_spec,
        out_specs=out_spec,
        check_vma=False,
    )
    def run(pos, vel, bpos, bfit, gpos, gfit, key):
        dev = lax.axis_index(axis)

        def step(carry, it):
            # ``it`` is the step index (scan xs), threaded ONLY when
            # the recorder is on — the disabled carry/HLO is untouched.
            pos, vel, bpos, bfit, gpos, gfit, key = carry
            key, k1, k2 = jax.random.split(key, 3)
            r1 = jax.random.uniform(
                jax.random.fold_in(k1, dev), (n, d_loc), pos.dtype
            )
            r2 = jax.random.uniform(
                jax.random.fold_in(k2, dev), (n, d_loc), pos.dtype
            )
            vel = (
                w * vel
                + c1 * r1 * (bpos - pos)
                + c2 * r2 * (gpos[None, :] - pos)
            )
            vel = jnp.clip(vel, -vmax, vmax)
            pos = jnp.clip(pos + vel, -half_width, half_width)

            # The one collective: global fitness from local partials.
            fit = combine(lax.psum(local(pos, dev * d_loc, d), axis), d)

            # Replicated-arithmetic bookkeeping: every device holds the
            # same [N] fitness, so masks and argmins agree everywhere.
            improved = fit < bfit
            bfit = jnp.where(improved, fit, bfit)
            bpos = jnp.where(improved[:, None], pos, bpos)
            b = jnp.argmin(bfit)
            better = bfit[b] < gfit
            gfit = jnp.where(better, bfit[b], gfit)
            gpos = jnp.where(better, bpos[b], gpos)
            telem = None
            if telemetry:  # static TelemetryConfig-style gate
                telem = _dimshard_tick_telemetry(
                    it, pos, vel, fit, bfit, d_loc, axis
                )
            return (pos, vel, bpos, bfit, gpos, gfit, key), telem

        xs = (
            jnp.arange(1, n_steps + 1, dtype=jnp.int32)
            if telemetry else None
        )
        carry, telem = lax.scan(
            step, (pos, vel, bpos, bfit, gpos, gfit, key), xs,
            length=n_steps,
        )
        if telemetry:
            return carry, telem
        return carry

    out = run(
        state.pos, state.vel, state.pbest_pos, state.pbest_fit,
        state.gbest_pos, state.gbest_fit, state.key,
    )
    (pos, vel, bpos, bfit, gpos, gfit, key), telem = (
        out if telemetry else (out, None)
    )
    new = PSOState(
        pos=pos, vel=vel, pbest_pos=bpos, pbest_fit=bfit,
        gbest_pos=gpos, gbest_fit=gfit, key=key,
        iteration=state.iteration + n_steps,
    )
    if telemetry:
        return new, telem
    return new


def _dimshard_tick_telemetry(
    it, pos, vel, fit, bfit, d_loc, axis, population=None
):
    """Per-step record inside a dim-sharded body: the speed gauges
    reduce per-particle squared partials over the named axis (one
    extra ``psum`` per step); the residency pair reports the local
    D-shard width via ``pmax``/``pmin``.  ``leader_id`` carries the
    incumbent-best particle index (replicated arithmetic — identical
    on every shard)."""
    from ..utils.telemetry import optimizer_tick_telemetry

    n = pos.shape[0] if population is None else population
    speed = jnp.sqrt(
        lax.psum(jnp.sum(vel * vel, axis=1), axis)
    )                                                    # [n] global
    finite_local = jnp.all(jnp.isfinite(pos)) & jnp.all(
        jnp.isfinite(vel)
    )
    # Packed-reduction rule (utils/telemetry.py): the speed psum
    # above plus ONE pmax pack — nonfinite flag, shard width, and the
    # negated width (pmin via pmax) ride together.
    width = jnp.asarray(d_loc, jnp.float32)
    flags = lax.pmax(
        jnp.stack(
            [(~finite_local).astype(jnp.float32), width, -width]
        ),
        axis,
    )
    nonfinite = (flags[0] > 0.0) | ~jnp.all(jnp.isfinite(fit))
    hi = flags[1].astype(jnp.int32)
    lo = (-flags[2]).astype(jnp.int32)
    return optimizer_tick_telemetry(
        it,
        n,
        speed_max=jnp.max(speed),
        speed_mean=jnp.mean(speed),
        nonfinite=nonfinite,
        best_shard=jnp.argmin(bfit),
        shard_max=hi,
        shard_imbalance=hi - lo,
    )


@watched("es-dimshard")
@partial(
    jax.jit,
    static_argnames=(
        "objective_name", "mesh", "n_steps", "n", "axis", "half_width",
        "sigma", "lr", "momentum", "telemetry",
    ),
)
def es_run_dimshard(
    state: ESState,
    objective_name: str,
    mesh: Mesh,
    n_steps: int,
    n: int = 256,
    axis: str = DIM_AXIS,
    half_width: float = 5.12,
    sigma: float = SIGMA,
    lr: float = LR,
    momentum: float = MOMENTUM,
    telemetry: bool = False,
):
    """OpenAI-ES with the PARAMETER axis sharded — proper tensor
    parallelism for neuroevolution-scale D.

    Everything except the fitness reduction is dimension-local: the
    antithetic draws, the rank-weighted gradient ``shaped @ eps``, and
    the momentum update all act per-dimension, so the gradient needs NO
    collective at all.  Per generation the devices exchange exactly one
    ``psum`` of ``[P, n]`` objective partials (the population's shaped
    ranks are then replicated arithmetic).  Complements
    ``parallel.sharding.es_run_shmap``, which shards the *population*
    axis instead — compose them on a 2-D mesh for both scales at once.

    ``telemetry=True`` (r11, static gate): returns ``(state, telem)``
    with per-generation records — ``speed_*`` gauges the momentum
    norm (one extra ``psum`` of the local squared partial), the
    residency pair the per-device D-shard width.  Same contract as
    ``pso_run_dimshard``.
    """
    local, combine = PARTIAL_OBJECTIVES[objective_name]
    d = state.mean.shape[0]
    n_dev = mesh.shape[axis]
    if d % n_dev:
        raise ValueError(
            f"dim D ({d}) must be a multiple of mesh axis "
            f"{axis!r} size ({n_dev})"
        )
    if n % 2:
        raise ValueError(f"population n ({n}) must be even")
    d_loc = d // n_dev
    s = sigma * half_width

    carry_spec = (P(axis), P(axis), P(axis), P(), P())
    out_spec = (carry_spec, P()) if telemetry else carry_spec

    @partial(
        shard_map,
        mesh=mesh,
        in_specs=carry_spec,
        out_specs=out_spec,
        check_vma=False,
    )
    def run(mean, mom, best_pos, best_fit, key):
        dev = lax.axis_index(axis)

        def step(carry, it):
            mean, mom, best_pos, best_fit, key = carry
            key, kd = jax.random.split(key)
            eps_half = jax.random.normal(
                jax.random.fold_in(kd, dev), (n // 2, d_loc), mean.dtype
            )
            eps = jnp.concatenate([eps_half, -eps_half], axis=0)
            pop = jnp.clip(mean + s * eps, -half_width, half_width)

            fit = combine(lax.psum(local(pop, dev * d_loc, d), axis), d)
            shaped = centered_ranks(fit)          # replicated arithmetic

            grad = (shaped @ eps) / (n * s)       # [d_loc] — local!
            mom = momentum * mom - lr * half_width * grad
            mean = jnp.clip(mean + mom, -half_width, half_width)

            b = jnp.argmin(fit)                   # same index everywhere
            better = fit[b] < best_fit
            best_fit = jnp.where(better, fit[b], best_fit)
            best_pos = jnp.where(better, pop[b], best_pos)

            mean_fit = combine(
                lax.psum(local(mean[None, :], dev * d_loc, d), axis), d
            )[0]
            better_mean = mean_fit < best_fit
            best_fit = jnp.where(better_mean, mean_fit, best_fit)
            best_pos = jnp.where(better_mean, mean, best_pos)
            telem = None
            if telemetry:  # static TelemetryConfig-style gate
                telem = _dimshard_tick_telemetry(
                    it, mean[None, :], mom[None, :], fit, fit,
                    d_loc, axis, population=n,
                )
            return (mean, mom, best_pos, best_fit, key), telem

        xs = (
            jnp.arange(1, n_steps + 1, dtype=jnp.int32)
            if telemetry else None
        )
        carry, telem = lax.scan(
            step, (mean, mom, best_pos, best_fit, key), xs,
            length=n_steps,
        )
        if telemetry:
            return carry, telem
        return carry

    out = run(
        state.mean, state.mom, state.best_pos, state.best_fit, state.key
    )
    (mean, mom, best_pos, best_fit, key), telem = (
        out if telemetry else (out, None)
    )
    new = ESState(
        mean=mean, mom=mom, best_pos=best_pos, best_fit=best_fit,
        key=key, iteration=state.iteration + n_steps,
    )
    if telemetry:
        return new, telem
    return new
