"""Spatially-sharded protocol tick: domain decomposition over a mesh axis.

Everything r8-r11 built for the neighbor-physics tick — the shared
``HashgridPlan``, the skin-radius Verlet carry, the flight recorder —
runs on ONE device; ``parallel/`` shards *populations* (islands,
dimshard, the fused zoo) but never the *spatial* tick, so "one swarm,
pod scale" was capped by one chip's memory and FLOPs (ROADMAP item 1).
This module is the domain decomposition that removes the cap: agents
are sharded by SPATIAL TILE along a mesh axis, each shard runs the r9
portable hashgrid tick over its own agents plus a thin HALO of
boundary agents exchanged with its ring neighbors, and detection stays
exact under the same Verlet-skin contract the single-device carry
already pins.

Decomposition
-------------

The torus ``[-hw, hw)^2`` is cut into ``n_tiles`` column strips of
width ``tile_width = 2*hw / n_tiles`` along x.  ``spatial_shard_swarm``
assigns every agent a HOME strip from its position (the same clip
convention as ``torus_cell_tables`` binning), lays the swarm out as
``[n_tiles * capacity]`` slots — tile ``d`` owns slots
``[d*capacity, (d+1)*capacity)``, unused slots padded with dead agents
(alive-masking makes padding free, the ``shard_swarm`` contract) — and
commits the state with ``PartitionSpec(axis)`` so slot blocks land one
per device.  The protocol prefix (election, heartbeat, allocation)
keeps running as the EXISTING cross-shard collectives: the state is
GSPMD-sharded, and every coordination reduction is already a masked
max/sum (ops/coordination.py deliberately has no ``pos[argmax]``
gathers), so XLA lowers them to scalar all-reduces — no positional
all-gather anywhere in the tick.

Halo exchange
-------------

Only the separation force needs cross-shard *per-agent* data, and only
near a strip boundary.  The band depth is ``halo_width = 2 *
cell_eff`` (two plan cells): physically, ``ps + skin`` — the r9
coverage bound — would already make detection exact, but two full
cells guarantee that EVERY cell an in-strip receiver's 3x3 stencil
touches is COMPLETE in the local view (one cell of reach, plus up to
one cell of strip/cell misalignment).  Complete cells mean the
per-shard plan's occupancy runs and candidate rows are *identical* to
the single-device plan's for every in-strip receiver — not merely
equivalent-up-to-masked-zeros — which is what upgrades sharded parity
from "equal within reduction-order noise" to BITWISE (a compacted
candidate row with different zero placement regroups a tree-shaped
fp reduction by ~1 ulp; tests/test_spatial_shard.py pins the bitwise
form).  Each shard keeps two MEMBERSHIP
lists (``send_lo``/``send_hi``: up to ``halo_cap`` local slots inside
the boundary bands, selected at plan-build time), and each tick ships
their CURRENT ``(x, y, alive, id)`` — one packed ``[halo_cap, 4]`` f32
``lax.ppermute`` per direction, the r11 packed-collective discipline
(f32 exact for ids < 2^24) — one step around the tile ring.  The
boundary exchange therefore lowers to ``collective-permute`` only;
bytes/tick is fixed by the spec (:func:`halo_bytes_per_tick`), not by
N.

Per-shard Verlet plan
---------------------

Each shard builds its own :class:`~..ops.hashgrid_plan.HashgridPlan`
over ``local + halo`` agents, on the SAME full-torus grid geometry the
single-device portable tick resolves (``ops/physics.
resolve_plan_geometry``), with the within-cell sort tie-broken by
GLOBAL agent id (``build_hashgrid_plan(tiebreak=...)``) — so a cell's
candidate order (and the cap-truncation set) is identical to the
single-device plan's, which is what makes sharded-vs-single parity
exact (tests/test_spatial_shard.py).  The plan is carried through the
rollout scan and rebuilt under ``lax.cond`` by the r9 staleness
triggers (displacement > skin/2, alive-set change, age ceiling),
evaluated over local + halo and then — in the default mode —
OR-reduced across the mesh (``lax.pmax``).  The global OR is
load-bearing twice over:

- **exactness**: shard ``d``'s halo membership was selected from
  BUILD-TIME positions, so a fast mover on shard ``e`` can invalidate
  ``d``'s membership without any local signal — the displacement
  probe must be global exactly like the r9 single-device trigger is
  global over all agents;
- **deadlock-freedom**: the rebuild branch re-selects membership and
  re-exchanges it (``ppermute`` inside the cond), and collectives
  under non-uniform predicates hang — the pmax makes the predicate
  uniform by construction, so every shard enters the same branch.

Per-tile triggers (r22)
-----------------------

``cfg.spatial_per_tile_rebuild`` replaces the mesh-wide OR with a
TWO-LEVEL predicate so one fast mover rebuilds its own neighborhood
instead of every tile.  Both global-OR obligations are re-discharged
locally:

- **exactness**: halo membership is re-selected EVERY tick from
  current positions (bitwise-equal to the carried lists on quiet
  ticks), so the shipped band is never stale; each tile compares the
  fresh lists against last tick's and ships a one-shot BAND-EDGE
  TRIGGER on the payload's meta row (``[halo_cap + 1, 4]`` — the
  extra row carries the trigger scalar plus the free-slot advert the
  re-homing pass reads).  A tile's rebuild predicate is its own
  local+halo staleness OR'd with the two received neighbor triggers:
  halo-slot *displacement* and *death* are visible in the tile's own
  ext staleness probe (the refresh ships current positions/alive
  bits), and halo-slot *identity* changes are exactly the neighbor
  membership changes the meta row announces — same tick, because
  selection is per-tick.
- **deadlock-freedom**: the single per-tick exchange happens BEFORE
  the cond and serves both branches (the rebuild branch bins the
  already-exchanged ``local + halo`` view), so the rebuild branch
  holds NO collectives and the non-uniform predicate is safe by
  construction.

Drifter re-homing (r22)
-----------------------

``cfg.spatial_rehome`` runs a bounded ring migration over every
agent-axis state leaf at the top of each sharded tick
(:func:`spatial_rehome_step`, before the separation pass): live
agents whose position left their home strip ship one ring hop toward
it per tick — below-strip escapees down, above-strip up — as fixed
``[spatial_migration_cap, F]`` f32 payloads (ids exact below 2^24,
the r11 packed-collective rule).  Receivers place arrivals into dead
slots; capacity is guaranteed one tick ahead by the free-slot advert
on the halo meta row (each sender caps a direction at
``min(cap, advertised_free // 2)``, so both directions together
never exceed the advert).  Escapees past the cap stay put and retry,
counted in ``SpatialCarry.migration_overflow``; shipped agents count
in ``SpatialCarry.migrations``.  Vacated slots become dead padding
with fresh synthetic ids past ``n_slots`` (never colliding with a
real id); arrivals and departures flip the local alive sets, so the
staleness triggers fire the same tick on both sides.  Migration is
deliberately NOT gated on the rebuild predicates — it runs every
tick in both trigger modes, which is what keeps a per-tile-trigger
run and a global-OR run bitwise-comparable under identical rebuild
schedules.

Exactness contract
------------------

Between rebuilds the per-shard plan is a provable superset of the true
``personal_space`` pairs under the r9 skin bound, PROVIDED every live
agent sits inside its home strip (plus the band's slack over
``ps + skin``) at build time and every boundary band fits its
``halo_cap``.  The build counts both hazards — ``escapes`` (live
agents outside their home strip at build; CONSERVATIVE: drift smaller
than the band slack is still covered, so a small positive count is a
warning, not yet an error) and ``halo_overflow`` (band members
truncated past ``halo_cap`` — immediately lossy) ride the
:class:`SpatialCarry`.  Out-of-contract runs may diverge from the
single-device tick, but never silently: the counters go positive the
build it happens (tests/test_spatial_shard.py pins both regimes;
benchmarks/bench_multichip_tick.py reports them, and the r11
residency counters ``shard_max_alive``/``shard_imbalance`` now
measure real spatial load imbalance).  ``cfg.spatial_rehome`` (r22,
above) closes the escapes hazard operationally: drifted agents
migrate back onto the tile that owns their position, one ring hop
per tick, and the counter drains to zero.

Scope: 2-D, ``separation_mode='hashgrid'``, portable path only (the
fused kernel is a single-device program), no moments field
(``k_align = k_coh = 0`` — a sharded commensurate deposit needs its
own halo, future work).  Entry points: ``spatial_shard_swarm`` →
``models/swarm.swarm_rollout(mesh=..., spatial=...)``, which threads
``ops/physics.physics_step_spatial`` through the scan.
"""

from __future__ import annotations

import dataclasses
import math
from dataclasses import dataclass
from functools import partial
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from flax import struct
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from ..ops.hashgrid_plan import (
    HashgridPlan,
    build_hashgrid_plan,
    plan_staleness,
)
from ..ops.neighbors import separation_grid_plan
from ..state import AGENT_AXIS_FIELDS, SwarmState, recount_alive_below
from ..utils.compat import shard_map
from ..utils.config import SwarmConfig

SPATIAL_AXIS = "tiles"

#: f32-packed id ceiling (the r11 packed-collective rule): the halo
#: payload ships agent ids through an f32 lane, exact below 2^24.
_ID_CEILING = 1 << 24


@dataclass(frozen=True)
class SpatialSpec:
    """Static geometry of a spatial decomposition (hashable — rides as
    a jit-static argument next to ``SwarmConfig``).

    ``capacity``: local agent slots per tile (padding slots are dead).
    ``halo_cap``: boundary-band slots per SIDE — the fixed ppermute
    payload width; band members past it are truncated and counted
    (``SpatialCarry.halo_overflow``).  ``halo_width`` is the band
    depth, ``2 * cell_eff`` of the plan grid — full-cell coverage of
    the boundary stencil, the bitwise-parity bound (module doc);
    physical exactness alone needs only ``ps + skin``, which
    ``cell_eff`` already dominates."""

    n_tiles: int
    capacity: int
    halo_cap: int
    world_hw: float
    halo_width: float

    @property
    def tile_width(self) -> float:
        return 2.0 * self.world_hw / self.n_tiles

    @property
    def n_slots(self) -> int:
        return self.n_tiles * self.capacity

    @property
    def ext_size(self) -> int:
        """Per-shard extended array length: local + both halos."""
        return self.capacity + 2 * self.halo_cap

    def replace(self, **kw) -> "SpatialSpec":
        return dataclasses.replace(self, **kw)


@struct.dataclass
class SpatialCarry:
    """The sharded tick's scan carry, one entry per tile stacked flat
    along dim 0 (every leaf is committed ``P(axis)`` so ``shard_map``
    splits it back per shard):

    - ``send_lo``/``send_hi`` ``[n_tiles * halo_cap]`` i32 — the halo
      MEMBERSHIP: local slot indices (``capacity`` = empty) whose
      current ``(x, y, alive, id)`` is shipped to the lower/upper ring
      neighbor each tick; re-selected at every plan rebuild.
    - ``plan`` — the per-shard :class:`HashgridPlan` over local + halo
      agents (leaves ``[n_tiles * ext_size]``-class, per-shard scalars
      widened to ``[n_tiles]``).
    - ``escapes`` ``[n_tiles]`` i32 — live agents outside their home
      strip at the last build (nonzero = the exactness contract is
      void for cross-boundary pairs; module doc).  Updated every tick
      under ``cfg.spatial_per_tile_rebuild`` (membership selection is
      per-tick there); with ``cfg.spatial_rehome`` the re-homing
      migration drains it to zero.
    - ``halo_overflow`` ``[n_tiles]`` i32 — band members truncated
      past ``halo_cap`` at the last build (per-tick under the r22
      per-tile trigger, like ``escapes``).
    - ``migrations`` ``[n_tiles]`` i32 — cumulative agents this tile
      SHIPPED to a neighbor by the r22 re-homing pass (0 with
      ``spatial_rehome`` off).
    - ``migration_overflow`` ``[n_tiles]`` i32 — cumulative escapees
      the pass could not ship (past ``spatial_migration_cap`` or the
      receiver's advertised free slots); they stay put and retry, so
      a transiently positive count is back-pressure, a growing one
      is a sizing error (the ``halo_overflow`` discipline).
    - ``free_lo``/``free_hi`` ``[n_tiles]`` i32 — dead-slot counts
      the lower/upper ring neighbor advertised on the last halo meta
      row: next tick's migration budget toward that neighbor.
    """

    send_lo: jax.Array
    send_hi: jax.Array
    plan: HashgridPlan
    escapes: jax.Array
    halo_overflow: jax.Array
    migrations: jax.Array
    migration_overflow: jax.Array
    free_lo: jax.Array
    free_hi: jax.Array


def spatial_plan_geometry(cfg: SwarmConfig) -> Tuple[int, float]:
    """(g, cell) of the per-shard plan grid — THE single-device
    portable geometry (``ops/physics.resolve_plan_geometry`` with
    ``use_kernel=False``), so the sharded and single-device binnings
    cannot drift.  Raises for configs the sharded tick does not
    support (moments field on, non-hashgrid separation)."""
    from ..ops.physics import resolve_plan_geometry, tick_field_enabled

    if cfg.separation_mode != "hashgrid":
        raise ValueError(
            "the spatially-sharded tick is the hashgrid tick "
            f"(separation_mode='hashgrid'); got "
            f"{cfg.separation_mode!r}"
        )
    if tick_field_enabled(cfg):
        raise ValueError(
            "k_align/k_coh moments-field forces are not supported "
            "under the spatially-sharded tick yet (the commensurate "
            "deposit needs its own halo); set both gains to 0"
        )
    g_plan, cell_plan, _ = resolve_plan_geometry(
        False, cfg.world_hw, cfg.grid_cell, cfg.personal_space,
        cfg.grid_max_per_cell, float(cfg.hashgrid_skin),
        field_on=False, field_sep_cell=cfg.grid_cell,
        align_cell=cfg.align_cell,
    )
    return g_plan, cell_plan


def _round8(n: int) -> int:
    return -(-int(n) // 8) * 8


def spatial_shard_swarm(
    state: SwarmState,
    mesh: Mesh,
    cfg: SwarmConfig,
    axis: str = SPATIAL_AXIS,
    capacity: Optional[int] = None,
    halo_cap: Optional[int] = None,
    slack: float = 1.5,
) -> Tuple[SwarmState, SpatialSpec]:
    """Lay a swarm out by home strip and commit it over ``mesh``.

    Returns ``(tiled_state, spec)``: a ``[n_tiles * capacity]``-slot
    state (tile ``d`` owns slots ``[d*capacity, (d+1)*capacity)``;
    unused slots are dead padding agents with fresh ids past the real
    swarm) placed with ``P(axis)`` on every agent-axis leaf, and the
    static :class:`SpatialSpec` the rollout needs.  Eager, host-side
    — the layout permutation is numpy (same boundary as
    ``shard_swarm``), done once per deployment, not per tick.

    ``capacity`` defaults to the larger of the measured max tile
    occupancy and ``slack * N / n_tiles``, rounded up to a multiple
    of 8; a tile whose occupancy exceeds an explicit ``capacity``
    raises.  ``halo_cap`` defaults to 2x the band's share of a full
    tile (``capacity * halo_width / tile_width``), floor 64.
    """
    import numpy as np

    from .sharding import _tree_shard_dim0

    if state.dim != 2:
        raise ValueError(
            f"spatial sharding tiles a 2-D torus; got dim={state.dim}"
        )
    if cfg.world_hw <= 0:
        raise ValueError(
            "spatial sharding needs world_hw > 0 (the torus the "
            "strips tile); set it in SwarmConfig"
        )
    n_tiles = int(mesh.shape[axis])
    hw = float(cfg.world_hw)
    # Band depth = two plan cells (module doc): one cell of stencil
    # reach + one of strip/cell misalignment, so every stencil cell
    # of an in-strip receiver is COMPLETE locally — the bitwise-
    # parity bound.  cell_eff >= ps + skin, so the r9 physical bound
    # is dominated.
    g_plan, _ = spatial_plan_geometry(cfg)
    halo_width = 2.0 * (2.0 * hw / g_plan)
    tile_w = 2.0 * hw / n_tiles
    if n_tiles > 1 and 2.0 * halo_width > tile_w:
        raise ValueError(
            f"halo bands overlap: 2 * halo_width = {2 * halo_width} "
            f"(4 plan cells) exceeds the tile width {tile_w} "
            f"({n_tiles} tiles over [-{hw}, {hw})); use fewer tiles, "
            "a larger arena, or a smaller cell/skin"
        )

    n = state.n_agents
    # swarmlint: disable=serve-host-sync -- the shard layout is host-computed by design at launch/rotation boundaries, before the rollout is in flight: nothing downstream is enqueued yet, so the transfer cannot serialize the pump
    x = np.asarray(state.pos[:, 0])
    tile = np.clip(
        np.floor((x + hw) / tile_w).astype(np.int64), 0, n_tiles - 1
    )
    occ = np.bincount(tile, minlength=n_tiles)
    if capacity is None:
        capacity = _round8(max(int(occ.max()),
                               int(np.ceil(slack * n / n_tiles)), 2))
    elif int(occ.max()) > capacity:
        raise ValueError(
            f"tile occupancy {int(occ.max())} exceeds capacity "
            f"{capacity}; raise capacity (or rebalance the swarm)"
        )
    if halo_cap is None:
        halo_cap = _round8(
            max(64, int(np.ceil(2.0 * capacity * halo_width / tile_w)))
        )
    spec = SpatialSpec(
        n_tiles=n_tiles, capacity=int(capacity),
        halo_cap=int(halo_cap), world_hw=hw, halo_width=halo_width,
    )
    if spec.n_slots >= _ID_CEILING:
        raise ValueError(
            f"{spec.n_slots} slots overflows the f32-packed halo id "
            f"lane (< {_ID_CEILING}); shard a smaller swarm per tile"
        )

    # Slot assignment: within a tile, agents keep ascending original
    # order (stable), so a quiet layout is reproducible.
    order = np.lexsort((np.arange(n), tile))
    ranks = np.zeros(n, np.int64)
    ranks[order] = np.arange(n) - np.concatenate(
        ([0], np.cumsum(occ)[:-1])
    )[tile[order]]
    slots = tile * capacity + ranks

    from ..state import AGENT_AXIS_FIELDS, make_swarm

    base = make_swarm(
        spec.n_slots, dim=2, n_tasks=state.n_tasks,
        n_caps=state.caps.shape[1], seed=0,
        dtype=state.pos.dtype,
    )
    slots_j = jnp.asarray(slots, jnp.int32)
    pad_count = spec.n_slots - n
    pad_ids = jnp.arange(n, n + pad_count, dtype=jnp.int32)
    pad_slots = jnp.asarray(
        np.setdiff1d(np.arange(spec.n_slots), slots), jnp.int32
    )
    updates = {}
    for f in AGENT_AXIS_FIELDS:
        src = getattr(state, f)
        dst = getattr(base, f)
        updates[f] = dst.at[slots_j].set(src)
    # Padding slots: dead, uniquely-id'd past the real swarm (kill /
    # revive match by value), no targets, everything else neutral.
    updates["alive"] = (
        jnp.zeros((spec.n_slots,), bool).at[slots_j].set(state.alive)
    )
    updates["agent_id"] = updates["agent_id"].at[pad_slots].set(pad_ids)
    updates["has_target"] = (
        jnp.zeros((spec.n_slots,), bool)
        .at[slots_j].set(state.has_target)
    )
    tiled = base.replace(
        tick=state.tick, key=state.key,
        task_pos=state.task_pos, task_cap=state.task_cap,
        task_winner=state.task_winner, task_util=state.task_util,
        **updates,
    )
    tiled = recount_alive_below(tiled)
    return _tree_shard_dim0(tiled, mesh, axis, spec.n_slots), spec


def gather_by_id(arr: jax.Array, agent_id: jax.Array, n: int):
    """Unscramble a tiled per-agent column back to agent-id order and
    drop the padding tail: ``out[id] = arr[slot_of(id)]`` for ids
    ``< n`` — the comparison lens the parity tests (and record
    frames) use.  ``mode='drop'``: slots the r22 re-homing pass
    vacated carry synthetic dead ids past ``n_slots`` (out of range
    here BY DESIGN — clipping would corrupt the last row)."""
    out_shape = (agent_id.shape[0],) + arr.shape[1:]
    return jnp.zeros(out_shape, arr.dtype).at[agent_id].set(
        arr, mode="drop"
    )[:n]


# ---------------------------------------------------------------------------
# shard_map body helpers.  Everything below runs PER SHARD: pos/alive/
# aid are the local [capacity] block, plan leaves the local slice.


def _pack_band(pos, alive, aid, idx, c):
    """[halo_cap, 4] f32 payload ``(x, y, alive, id)`` gathered at the
    membership list ``idx`` (``c`` = empty slot; id -1)."""
    valid = idx < c
    j = jnp.minimum(idx, c - 1)
    return jnp.stack(
        [
            pos[j, 0],
            pos[j, 1],
            (alive[j] & valid).astype(jnp.float32),
            jnp.where(valid, aid[j], -1).astype(jnp.float32),
        ],
        axis=1,
    )


def _meta_row(trig, free):
    """[4] f32 meta row appended to each band payload (r22): lane 0 =
    the band-edge trigger the per-tile predicate ORs in, lane 1 = the
    free-(dead-)slot advert the re-homing pass budgets against next
    tick, lanes 2-3 spare.  Rides every payload in both trigger modes
    so the exchange shape is mode-invariant."""
    z = jnp.zeros((), jnp.float32)
    return jnp.stack([
        jnp.asarray(trig, jnp.float32), jnp.asarray(free, jnp.float32),
        z, z,
    ])


def _unpack_halo(pay):
    """Inverse of :func:`_pack_band` over a concatenated [2H, 4]."""
    return (
        pay[:, :2],
        pay[:, 2] > 0.0,
        pay[:, 3].astype(jnp.int32),
    )


def _ring_exchange(pay_lo, pay_hi, axis, n_tiles):
    """One ring step of the band payloads: ship ``pay_hi`` up and
    ``pay_lo`` down, receive the mirror — ``(from_below, from_above)``.
    The ONLY cross-shard data motion in the sharded tick; lowers to
    two ``collective-permute`` ops (asserted on the lowered text by
    tests/test_spatial_shard.py).  ``n_tiles == 1`` has no neighbors:
    the halo is dead (a single tile IS the single-device tick)."""
    if n_tiles == 1:
        dead = jnp.zeros_like(pay_lo).at[:, 3].set(-1.0)
        return dead, dead
    fwd = [(i, (i + 1) % n_tiles) for i in range(n_tiles)]
    bwd = [(i, (i - 1) % n_tiles) for i in range(n_tiles)]
    from_below = lax.ppermute(pay_hi, axis, perm=fwd)
    from_above = lax.ppermute(pay_lo, axis, perm=bwd)
    return from_below, from_above


def _strip_offset(pos, spec, axis):
    """Per-agent minimum-image x-offset from this shard's strip
    center (the band/escape coordinate)."""
    d = lax.axis_index(axis)
    hw = spec.world_hw
    center = -hw + (d.astype(pos.dtype) + 0.5) * spec.tile_width
    return jnp.mod(pos[:, 0] - center + hw, 2.0 * hw) - hw


def _select_bands(pos, alive, spec, axis):
    """Boundary-band membership from CURRENT positions: the two send
    lists plus the escape/overflow counters their selection measures.
    Purely local — called per rebuild in the global-OR mode and every
    tick under the r22 per-tile trigger."""
    c, h = spec.capacity, spec.halo_cap
    half_w = 0.5 * spec.tile_width
    u = _strip_offset(pos, spec, axis)
    lo_mask = alive & (u <= -(half_w - spec.halo_width))
    hi_mask = alive & (u >= (half_w - spec.halo_width))
    send_lo = jnp.nonzero(lo_mask, size=h, fill_value=c)[0].astype(
        jnp.int32
    )
    send_hi = jnp.nonzero(hi_mask, size=h, fill_value=c)[0].astype(
        jnp.int32
    )
    n_lo = jnp.sum(lo_mask)
    n_hi = jnp.sum(hi_mask)
    halo_overflow = (
        jnp.maximum(n_lo - h, 0) + jnp.maximum(n_hi - h, 0)
    ).astype(jnp.int32)
    escapes = jnp.sum(alive & (jnp.abs(u) > half_w)).astype(jnp.int32)
    return send_lo, send_hi, escapes, halo_overflow


def _exchange_bands(pos, alive, aid, send_lo, send_hi, meta_lo,
                    meta_hi, spec, axis):
    """Pack both band payloads with their meta rows, one ring
    exchange, unpack: ``(epos, ealive, eids, meta_below,
    meta_above)`` — the extended local + halo view plus the two
    received neighbor meta rows (:func:`_meta_row`)."""
    c, h = spec.capacity, spec.halo_cap
    pay_lo = jnp.concatenate(
        [_pack_band(pos, alive, aid, send_lo, c), meta_lo[None, :]]
    )
    pay_hi = jnp.concatenate(
        [_pack_band(pos, alive, aid, send_hi, c), meta_hi[None, :]]
    )
    from_below, from_above = _ring_exchange(
        pay_lo, pay_hi, axis, spec.n_tiles
    )
    hpos, halive, hid = _unpack_halo(
        jnp.concatenate([from_below[:h], from_above[:h]])
    )
    epos = jnp.concatenate([pos, hpos])
    ealive = jnp.concatenate([alive, halive])
    eids = jnp.concatenate([aid, hid])
    return epos, ealive, eids, from_below[h], from_above[h]


def _build_ext_plan(epos, ealive, eids, spec, cfg, g_plan, cell_plan,
                    rebuilds_prev, cells_prev):
    """Per-shard plan build over an already-exchanged local + halo
    view — NO collectives, so it is safe under the r22 per-tile
    (non-uniform) rebuild predicate."""
    plan = build_hashgrid_plan(
        epos, ealive, spec.world_hw, cell_plan,
        cfg.grid_max_per_cell, need_csr=True,
        g=g_plan, skin=float(cfg.hashgrid_skin),
        neighbor_cap=(
            cfg.hashgrid_neighbor_cap
            if cfg.hashgrid_skin > 0 else 0
        ),
        tiebreak=eids,
    )
    return plan.replace(
        rebuilds=rebuilds_prev + 1,
        cells_rebuilt=(
            cells_prev + jnp.asarray(g_plan * g_plan, jnp.int32)
        ),
    )


def _rebuild_local(pos, alive, aid, rebuilds_prev, cells_prev, spec,
                   cfg, g_plan, cell_plan, axis):
    """Membership re-selection + halo exchange + per-shard plan build
    (the global-OR mode's ``lax.cond`` rebuild branch, and the initial
    build).  MUST run under a mesh-uniform predicate: it ppermutes."""
    send_lo, send_hi, escapes, halo_overflow = _select_bands(
        pos, alive, spec, axis
    )
    meta = _meta_row(
        jnp.zeros((), jnp.float32), jnp.sum(~alive).astype(jnp.int32)
    )
    epos, ealive, eids, _, _ = _exchange_bands(
        pos, alive, aid, send_lo, send_hi, meta, meta, spec, axis
    )
    plan = _build_ext_plan(
        epos, ealive, eids, spec, cfg, g_plan, cell_plan,
        rebuilds_prev, cells_prev,
    )
    return plan, send_lo, send_hi, epos, ealive, escapes, halo_overflow


def _tick_local(pos, alive, aid, carry, spec, cfg, g_plan, cell_plan,
                axis):
    """One shard's separation tick: halo exchange, staleness triggers,
    rebuild under cond, the r9 portable sweep.  ``carry`` is the
    squeezed per-shard :class:`SpatialCarry`; returns ``(f_sep,
    carry')``.

    Two STATIC trigger modes (module doc):

    - global-OR (default): per-tick halo refresh at the CARRIED
      membership, r9 staleness pmax-OR'd across the mesh, rebuild
      branch re-selects membership and re-exchanges under the
      uniform predicate;
    - ``cfg.spatial_per_tile_rebuild`` (r22): membership re-selected
      every tick, ONE exchange (band payloads + meta rows) serves
      both cond branches, and the predicate is local staleness OR'd
      with the two received neighbor band-edge triggers — no
      collectives inside the cond, so the non-uniform predicate is
      deadlock-free.
    """
    c = spec.capacity
    plan = carry.plan
    free = jnp.sum(~alive).astype(jnp.int32)

    if cfg.spatial_per_tile_rebuild:
        # --- r22 two-level trigger -------------------------------
        # Fresh membership from current positions; identical to the
        # carried lists on quiet ticks, and the per-side inequality
        # IS the band-edge trigger: the neighbor's halo slots change
        # identity exactly when my band membership changes.
        send_lo, send_hi, escapes, halo_overflow = _select_bands(
            pos, alive, spec, axis
        )
        trig_lo = jnp.any(send_lo != carry.send_lo)
        trig_hi = jnp.any(send_hi != carry.send_hi)
        epos, ealive, eids, meta_below, meta_above = _exchange_bands(
            pos, alive, aid, send_lo, send_hi,
            _meta_row(trig_lo, free), _meta_row(trig_hi, free),
            spec, axis,
        )
        # Own staleness over local + halo covers halo DISPLACEMENT
        # and DEATH (current positions/alive bits vs the plan refs);
        # halo IDENTITY changes arrive as the neighbor triggers.
        d2max, alive_changed = plan_staleness(epos, ealive, plan)
        skin = plan.skin
        stale = alive_changed | (4.0 * d2max > skin * skin)
        if cfg.hashgrid_rebuild_every > 0:
            stale = stale | (
                plan.age + 1 >= cfg.hashgrid_rebuild_every
            )
        pred = (
            stale | (meta_below[0] > 0.5) | (meta_above[0] > 0.5)
        )

        # Distinct names from the global-OR branch pair below: this
        # rebuild is collective-FREE (the exchange already happened
        # unconditionally), which is what makes the non-uniform
        # predicate legal — and what lets swarmlint's cond-collective
        # name resolution see it that way.
        def rebuild_prebuilt(_):
            return _build_ext_plan(
                epos, ealive, eids, spec, cfg, g_plan, cell_plan,
                plan.rebuilds, plan.cells_rebuilt,
            )

        def keep_prebuilt(_):
            return plan.replace(age=plan.age + 1)

        new_plan = lax.cond(pred, rebuild_prebuilt, keep_prebuilt,
                            None)
        out = carry.replace(
            send_lo=send_lo, send_hi=send_hi, plan=new_plan,
            escapes=escapes, halo_overflow=halo_overflow,
            free_lo=meta_below[1].astype(jnp.int32),
            free_hi=meta_above[1].astype(jnp.int32),
        )
    else:
        # --- r12 global-OR (the bitwise-pinned baseline) ---------
        # 1. Per-tick halo refresh at FIXED membership: current
        #    positions and alive bits of the build-time band members,
        #    so consumers read CURRENT neighbor positions through
        #    plan.order (the r9 stale-plan contract) and a neighbor-
        #    side kill is visible the tick it happens.
        meta = _meta_row(jnp.zeros((), jnp.float32), free)
        epos0, ealive0, _, meta_below, meta_above = _exchange_bands(
            pos, alive, aid, carry.send_lo, carry.send_hi,
            meta, meta, spec, axis,
        )

        # 2. Staleness over local + halo, then the mesh-wide OR
        #    (module doc: required for exactness AND for deadlock-
        #    free collectives inside the cond).
        d2max, alive_changed = plan_staleness(epos0, ealive0, plan)
        skin = plan.skin
        stale = alive_changed | (4.0 * d2max > skin * skin)
        if cfg.hashgrid_rebuild_every > 0:
            stale = stale | (
                plan.age + 1 >= cfg.hashgrid_rebuild_every
            )
        stale_any = lax.pmax(stale.astype(jnp.int32), axis) > 0

        def rebuild(_):
            return _rebuild_local(
                pos, alive, aid, plan.rebuilds, plan.cells_rebuilt,
                spec, cfg, g_plan, cell_plan, axis,
            )

        def keep(_):
            return (
                plan.replace(age=plan.age + 1),
                carry.send_lo, carry.send_hi, epos0, ealive0,
                carry.escapes, carry.halo_overflow,
            )

        (new_plan, send_lo, send_hi, epos, ealive, escapes,
         halo_overflow) = lax.cond(stale_any, rebuild, keep, None)
        out = carry.replace(
            send_lo=send_lo, send_hi=send_hi, plan=new_plan,
            escapes=escapes, halo_overflow=halo_overflow,
            free_lo=meta_below[1].astype(jnp.int32),
            free_hi=meta_above[1].astype(jnp.int32),
        )

    # 3. The r9 portable sweep over local + halo; receivers are the
    #    local block only.
    eps = jnp.asarray(cfg.dist_eps, pos.dtype)
    f = separation_grid_plan(
        epos, ealive, cfg.k_sep, cfg.personal_space, eps, new_plan
    )[:c]
    return f, out


def _squeeze_scalar(x):
    """Per-shard block -> local value: carry scalars are widened to
    [n_tiles] outside, so their block is [1].  No genuine [1]-length
    vector exists in the carry (ext_size >= 4, g*g >= 9 — enforced by
    the spec/geometry guards), so shape alone is unambiguous."""
    if x is None:
        return None
    return x.reshape(()) if x.ndim == 1 and x.shape[0] == 1 else x


def _widen_scalar(x):
    if x is None:
        return None
    return x[None] if x.ndim == 0 else x


def spatial_plan_init(
    state: SwarmState,
    cfg: SwarmConfig,
    spec: SpatialSpec,
    mesh: Mesh,
    axis: str = SPATIAL_AXIS,
) -> SpatialCarry:
    """Seed the rollout carry: select each shard's boundary bands,
    exchange them, build every per-shard plan (the sharded twin of
    ``ops/physics.build_tick_plan``)."""
    g_plan, cell_plan = spatial_plan_geometry(cfg)

    @partial(
        shard_map, mesh=mesh,
        in_specs=(P(axis), P(axis), P(axis)),
        out_specs=P(axis),
        check_vma=False,
    )
    def init(pos, alive, aid):
        # Counters seeded one rebuild BELOW zero so the seed build
        # lands at rebuilds == 0 / cells_rebuilt == 0, matching the
        # single-device build_tick_plan convention.
        plan, send_lo, send_hi, _, _, escapes, overflow = (
            _rebuild_local(
                pos, alive, aid, jnp.asarray(-1, jnp.int32),
                jnp.asarray(-g_plan * g_plan, jnp.int32), spec,
                cfg, g_plan, cell_plan, axis,
            )
        )
        zero = jnp.zeros((), jnp.int32)
        # free_lo/free_hi seed at 0: the first re-homing tick ships
        # nothing; the advert warms up on tick 1's halo exchange.
        return jax.tree_util.tree_map(
            _widen_scalar,
            SpatialCarry(
                send_lo=send_lo, send_hi=send_hi, plan=plan,
                escapes=escapes, halo_overflow=overflow,
                migrations=zero, migration_overflow=zero,
                free_lo=zero, free_hi=zero,
            ),
        )

    return init(state.pos, state.alive, state.agent_id)


def spatial_separation_step(
    pos: jax.Array,
    alive: jax.Array,
    agent_id: jax.Array,
    carry: SpatialCarry,
    cfg: SwarmConfig,
    spec: SpatialSpec,
    mesh: Mesh,
    axis: str = SPATIAL_AXIS,
):
    """(f_sep [n_slots, 2], carry'): one sharded separation tick —
    the ``shard_map`` wrapper around :func:`_tick_local`."""
    g_plan, cell_plan = spatial_plan_geometry(cfg)

    @partial(
        shard_map, mesh=mesh,
        in_specs=(P(axis), P(axis), P(axis), P(axis)),
        out_specs=(P(axis), P(axis)),
        check_vma=False,
    )
    def step(pos_l, alive_l, aid_l, carry_l):
        carry_l = jax.tree_util.tree_map(_squeeze_scalar, carry_l)
        f, out_carry = _tick_local(
            pos_l, alive_l, aid_l, carry_l, spec, cfg, g_plan,
            cell_plan, axis,
        )
        return f, jax.tree_util.tree_map(_widen_scalar, out_carry)

    return step(pos, alive, agent_id, carry)


def _flatten_leaf(arr):
    """Per-agent leaf ``[c, ...]`` -> ``[c, lanes]`` f32 migration
    lanes.  Bools ride as 0/1; ints are f32-exact below 2^24 (the
    :data:`_ID_CEILING` discipline — ``spatial_rehome_step`` guards
    the id fields, tick counters stay well under it for any run the
    repo models)."""
    return arr.reshape(arr.shape[0], -1).astype(jnp.float32)


def _unflatten_leaf(flat, like):
    """Inverse of :func:`_flatten_leaf` against a template leaf."""
    vals = flat.reshape((flat.shape[0],) + like.shape[1:])
    if like.dtype == jnp.bool_:
        return vals > 0.5
    return vals.astype(like.dtype)


def _rehome_local(leaves, carry, spec, cfg, axis):
    """One shard's drifter re-homing pass (module doc): select the
    live agents whose position left this strip, ship up to the
    per-direction budget one ring hop toward home, vacate their
    slots, and place the mirror arrivals into dead slots.  ``leaves``
    is the dict of per-agent state columns (``AGENT_AXIS_FIELDS``
    order defines the flat lane layout); returns ``(leaves',
    carry')``.

    Budget per direction = ``min(spatial_migration_cap, advert //
    2)`` where ``advert`` is the dead-slot count the receiver put on
    LAST tick's halo meta row.  Both neighbors draw on the same pool,
    so each gets half — total arrivals can never exceed the true free
    count (deaths since the advert only grow it), hence ``lost`` is 0
    by protocol and counted loudly anyway.  Escapees past the budget
    stay put and retry next tick (``migration_overflow``)."""
    c = spec.capacity
    m = int(cfg.spatial_migration_cap)
    half_w = 0.5 * spec.tile_width
    alive = leaves["alive"]
    u = _strip_offset(leaves["pos"], spec, axis)
    esc_lo = alive & (u < -half_w)
    esc_hi = alive & (u > half_w)

    cap_dn = jnp.minimum(m, carry.free_lo // 2)
    cap_up = jnp.minimum(m, carry.free_hi // 2)
    idx_dn = jnp.nonzero(esc_lo, size=m, fill_value=c)[0].astype(
        jnp.int32
    )
    idx_up = jnp.nonzero(esc_hi, size=m, fill_value=c)[0].astype(
        jnp.int32
    )
    lane = jnp.arange(m, dtype=jnp.int32)
    ship_dn = (idx_dn < c) & (lane < cap_dn)
    ship_up = (idx_up < c) & (lane < cap_up)

    flat = jnp.concatenate(
        [_flatten_leaf(leaves[f]) for f in AGENT_AXIS_FIELDS], axis=1
    )

    def payload(idx, ship):
        rows = flat[jnp.where(ship, idx, 0)] * ship[:, None]
        return jnp.concatenate(
            [rows, ship[:, None].astype(jnp.float32)], axis=1
        )

    from_below, from_above = _ring_exchange(
        payload(idx_dn, ship_dn), payload(idx_up, ship_up),
        axis, spec.n_tiles,
    )

    # Vacate shipped slots: dead, UNIQUE synthetic id past n_slots
    # (never a real agent; gather_by_id drops it), target cleared;
    # the other lanes go stale behind the dead bit, the documented
    # corpse contract.
    d = lax.axis_index(axis)
    vac = jnp.concatenate(
        [jnp.where(ship_dn, idx_dn, c), jnp.where(ship_up, idx_up, c)]
    )
    out = dict(leaves)
    out["alive"] = alive.at[vac].set(False, mode="drop")
    out["agent_id"] = leaves["agent_id"].at[vac].set(
        (spec.n_slots + d * c + vac).astype(jnp.int32), mode="drop"
    )
    out["has_target"] = leaves["has_target"].at[vac].set(
        False, mode="drop"
    )

    # Place arrivals: k-th valid arrival row -> k-th dead slot
    # (vacated slots included — they ARE free now).
    pay = jnp.concatenate([from_below, from_above])
    valid = pay[:, -1] > 0.5
    rank = jnp.cumsum(valid.astype(jnp.int32)) - 1
    free_idx = jnp.nonzero(
        ~out["alive"], size=2 * m, fill_value=c
    )[0].astype(jnp.int32)
    slot = jnp.where(valid, free_idx[jnp.clip(rank, 0, 2 * m - 1)], c)
    ok = valid & (slot < c)
    lost = jnp.sum(valid & ~ok).astype(jnp.int32)
    slot = jnp.where(ok, slot, c)
    off = 0
    for f in AGENT_AXIS_FIELDS:
        lanes = math.prod(leaves[f].shape[1:])
        out[f] = out[f].at[slot].set(
            _unflatten_leaf(pay[:, off:off + lanes], leaves[f]),
            mode="drop",
        )
        off += lanes

    shipped = (jnp.sum(ship_dn) + jnp.sum(ship_up)).astype(jnp.int32)
    n_esc = (jnp.sum(esc_lo) + jnp.sum(esc_hi)).astype(jnp.int32)
    return out, carry.replace(
        migrations=carry.migrations + shipped,
        migration_overflow=(
            carry.migration_overflow + (n_esc - shipped) + lost
        ),
    )


def spatial_rehome_step(
    state: SwarmState,
    carry: SpatialCarry,
    cfg: SwarmConfig,
    spec: SpatialSpec,
    mesh: Mesh,
    axis: str = SPATIAL_AXIS,
) -> Tuple[SwarmState, SpatialCarry]:
    """One sharded re-homing tick (``cfg.spatial_rehome``): migrate
    escaped agents one ring hop toward their position-owning tile.
    Runs at the TOP of the sharded physics tick, before any consumer
    of tile residency, so the separation step's ``escapes`` counter
    measures the post-migration state (0 under sustained drift once
    the advert warms up).  Statically a no-op on a 1-tile mesh (a
    single strip owns every position).  NOT gated on the rebuild
    predicates — migration must not depend on the trigger mode, or
    the per-tile/global-OR parity contract would break."""
    if spec.n_tiles == 1:
        return state, carry
    if 2 * spec.n_slots >= _ID_CEILING:
        raise ValueError(
            "spatial_rehome needs synthetic vacated-slot ids "
            f"(< 2 * n_slots = {2 * spec.n_slots}) to stay f32-exact "
            f"on the migration payload (< {_ID_CEILING}); shard a "
            "smaller swarm per tile"
        )
    leaves = {f: getattr(state, f) for f in AGENT_AXIS_FIELDS}

    @partial(
        shard_map, mesh=mesh,
        in_specs=(P(axis), P(axis)),
        out_specs=(P(axis), P(axis)),
        check_vma=False,
    )
    def step(leaves_l, carry_l):
        carry_l = jax.tree_util.tree_map(_squeeze_scalar, carry_l)
        leaves_out, carry_out = _rehome_local(
            leaves_l, carry_l, spec, cfg, axis
        )
        return leaves_out, jax.tree_util.tree_map(
            _widen_scalar, carry_out
        )

    leaves2, carry2 = step(leaves, carry)
    return state.replace(**leaves2), carry2


def tile_live_counts(alive: jax.Array, spec: SpatialSpec) -> jax.Array:
    """[n_tiles] live-agent counts from the tiled alive mask — the
    spatial residency the r11 telemetry counters report (each tile's
    slot block is contiguous, so this is a local reduction per
    device under GSPMD)."""
    return jnp.sum(
        alive.reshape(spec.n_tiles, spec.capacity), axis=1
    ).astype(jnp.int32)


def halo_bytes_per_tick(spec: SpatialSpec,
                        rebuilds_per_tick: float = 0.0) -> float:
    """Modelled cross-shard traffic of the sharded tick, bytes/tick
    over the whole mesh: every tick each tile ships two
    ``[halo_cap + 1, 4]`` f32 payloads (the per-tick position/alive
    refresh plus the r22 meta row carrying the band-edge trigger and
    free-slot advert), and a rebuild tick ships the same pair again
    (the membership re-exchange).  Independent of N — the number the
    MULTICHIP bytes row gates (docs/PERFORMANCE.md r12 halo-volume
    model)."""
    if spec.n_tiles == 1:
        return 0.0
    per_exchange = spec.n_tiles * 2 * (spec.halo_cap + 1) * 4 * 4
    return per_exchange * (1.0 + float(rebuilds_per_tick))
