"""Island-model multi-swarm PSO with ring migration.

BASELINE.json config 5: "64 islands × 16k particles, periodic migration
all-to-all over ICI".  Each island is an independent PSO swarm (its own
gbest, its own RNG stream); every ``migrate_every`` iterations each island
ships its ``k`` best particles to the next island on a ring, replacing that
island's ``k`` worst.

TPU mapping: all island state is stacked on a leading island axis
``[I, n, ...]`` and sharded over the mesh's island axis.  The per-island
update is ``jax.vmap`` of the single-swarm kernel (ops/pso.py), and the
migration is ``jnp.roll`` along the island axis — under GSPMD, XLA lowers
that roll to an ICI collective-permute between devices, which *is* the
migration network.  No hand-written transport, per the design stance in
SURVEY.md §2a.
"""

from __future__ import annotations

from functools import partial
from typing import Callable

import jax
import jax.numpy as jnp
from flax import struct

from ..ops import pso as _pso
from ..utils.compile_watch import watched
from .mesh import ISLAND_AXIS  # noqa: F401  (canonical axis name)


@struct.dataclass
class IslandPSOState:
    """Stacked per-island PSO state: I islands × n particles × D dims."""

    pso: _pso.PSOState     # every leaf carries a leading island axis [I, ...]
    iteration: jax.Array   # i32 scalar (shared; islands step in lockstep)

    @property
    def n_islands(self) -> int:
        return self.pso.pos.shape[0]


def island_init(
    objective: Callable,
    n_islands: int,
    n_per_island: int,
    dim: int,
    half_width: float,
    seed: int = 0,
    dtype=jnp.float32,
) -> IslandPSOState:
    seeds = jnp.arange(n_islands) + seed * 1_000_003

    # vmap over per-island seeds so each island draws an independent stream.
    def init_with_seed(island_seed):
        key = jax.random.PRNGKey(0)
        key = jax.random.fold_in(key, island_seed)
        kp, kv, kc = jax.random.split(key, 3)
        pos = jax.random.uniform(
            kp, (n_per_island, dim), dtype, minval=-half_width,
            maxval=half_width,
        )
        vel = (
            jax.random.uniform(
                kv, (n_per_island, dim), dtype, minval=-half_width,
                maxval=half_width,
            )
            * 0.1
        )
        fit = objective(pos)
        best = jnp.argmin(fit)
        return _pso.PSOState(
            pos=pos, vel=vel, pbest_pos=pos, pbest_fit=fit,
            gbest_pos=pos[best], gbest_fit=fit[best], key=kc,
            iteration=jnp.asarray(0, jnp.int32),
        )

    pso = jax.vmap(init_with_seed)(seeds)
    return IslandPSOState(pso=pso, iteration=jnp.asarray(0, jnp.int32))


def migrate(state: IslandPSOState, k: int) -> IslandPSOState:
    """Ring migration: island i's k best pbest particles replace island
    (i+1)'s k worst.  ``jnp.roll`` on the island axis = ICI collective."""
    pso = state.pso
    fit = pso.pbest_fit                                   # [I, n]

    _, best_idx = jax.lax.top_k(-fit, k)                  # k smallest
    em_pos = jnp.take_along_axis(pso.pbest_pos, best_idx[..., None], axis=1)
    em_fit = jnp.take_along_axis(fit, best_idx, axis=1)

    in_pos = jnp.roll(em_pos, 1, axis=0)                  # ring: i -> i+1
    in_fit = jnp.roll(em_fit, 1, axis=0)

    _, worst_idx = jax.lax.top_k(fit, k)                  # k largest

    def scatter_rows(arr, idx, val):
        return jax.vmap(lambda a, i, v: a.at[i].set(v))(arr, idx, val)

    pos = scatter_rows(pso.pos, worst_idx, in_pos)
    pbest_pos = scatter_rows(pso.pbest_pos, worst_idx, in_pos)
    pbest_fit = scatter_rows(pso.pbest_fit, worst_idx, in_fit)
    vel = scatter_rows(
        pso.vel, worst_idx, jnp.zeros_like(in_pos)
    )

    # Refresh island gbests with the immigrants.
    best = jnp.argmin(pbest_fit, axis=1)                  # [I]
    cand_fit = jnp.take_along_axis(pbest_fit, best[:, None], axis=1)[:, 0]
    cand_pos = jnp.take_along_axis(
        pbest_pos, best[:, None, None], axis=1
    )[:, 0]
    better = cand_fit < pso.gbest_fit
    gbest_fit = jnp.where(better, cand_fit, pso.gbest_fit)
    gbest_pos = jnp.where(better[:, None], cand_pos, pso.gbest_pos)

    return state.replace(
        pso=pso.replace(
            pos=pos, vel=vel, pbest_pos=pbest_pos, pbest_fit=pbest_fit,
            gbest_fit=gbest_fit, gbest_pos=gbest_pos,
        )
    )


@watched("island-run")
@partial(
    jax.jit,
    static_argnames=(
        "objective", "n_steps", "migrate_every", "migrate_k", "w", "c1",
        "c2", "half_width", "vmax_frac", "telemetry",
    ),
)
def island_run(
    state: IslandPSOState,
    objective: Callable,
    n_steps: int,
    migrate_every: int = 25,
    migrate_k: int = 4,
    w: float = _pso.W,
    c1: float = _pso.C1,
    c2: float = _pso.C2,
    half_width: float = 5.12,
    vmax_frac: float = 0.5,
    telemetry: bool = False,
):
    """Run all islands in lockstep under one scan, migrating periodically.

    ``telemetry=True`` (r11, static — the same trace-time gate shape as
    the r10 rollout recorder, so the disabled trace is the identical
    telemetry-free HLO) stacks one ``utils/telemetry.TickTelemetry``
    per iteration as scan ys and returns ``(state, telem)``:
    ``leader_id`` is the island holding the global best, ``speed_*``
    the particle-velocity gauges, ``shard_max_alive`` the per-island
    population.  Under GSPMD with the island axis sharded the
    cross-island reductions lower to ICI collectives; collection only
    READS the carried state, so the trajectory is bitwise-equal either
    way (tests/test_mesh_telemetry.py).
    """

    step_one = partial(
        _pso.pso_step, objective=objective, w=w, c1=c1, c2=c2,
        half_width=half_width, vmax_frac=vmax_frac,
    )
    vstep = jax.vmap(lambda s: step_one(s))

    def body(st: IslandPSOState, _):
        st = st.replace(pso=vstep(st.pso), iteration=st.iteration + 1)
        st = jax.lax.cond(
            st.iteration % migrate_every == 0,
            lambda s: migrate(s, migrate_k),
            lambda s: s,
            st,
        )
        telem = None
        if telemetry:  # static TelemetryConfig-style gate
            from ..utils.telemetry import island_tick_telemetry

            telem = island_tick_telemetry(st.pso, st.iteration)
        return st, telem

    state, telem = jax.lax.scan(body, state, None, length=n_steps)
    if telemetry:
        return state, telem
    return state


def global_best(state: IslandPSOState):
    """(fit, pos) of the best particle across all islands — one reduction
    (lax.pmin over ICI when the island axis is sharded)."""
    i = jnp.argmin(state.pso.gbest_fit)
    return state.pso.gbest_fit[i], state.pso.gbest_pos[i]
