"""Device-mesh construction helpers.

The reference's only notion of scale-out is "run more OS processes"
(/root/reference/agent.py:349-360) over a transport that was never written
(agent.py:188-195).  Here the communication backend is XLA collectives over
a ``jax.sharding.Mesh``: the agent/particle axis shards across devices
(data parallel over ICI), an optional island axis gives the multi-swarm
island model, and election/allocation/gbest reductions ride ICI as
``pmax``/``pmin``/``psum``/``ppermute``.
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

AGENT_AXIS = "agents"
ISLAND_AXIS = "islands"

#: The serve plane's 2D mesh axes (r18): small tenants shard their
#: scenario batch over ``scenarios``; jumbo tenants domain-decompose
#: over ``tiles`` (the r12 spatial tick's axis).  One mesh, both
#: workload shapes — see serve/buckets.BucketSpec.mesh_axes_for.
SCENARIO_AXIS = "scenarios"
TILE_AXIS = "tiles"


def make_mesh(
    axis_names: Sequence[str] = (AGENT_AXIS,),
    shape: Optional[Tuple[int, ...]] = None,
    devices=None,
) -> Mesh:
    """Build a mesh over available devices.

    Default: every device on one axis.  ``shape`` splits devices over
    multiple axes, e.g. ``make_mesh(("islands", "agents"), (2, 4))``.
    """
    devices = np.asarray(devices if devices is not None else jax.devices())
    if shape is None:
        shape = (devices.size,) + (1,) * (len(axis_names) - 1)
    return Mesh(devices.reshape(shape), axis_names)


def make_serve_mesh(
    scenarios: Optional[int] = None,
    tiles: int = 1,
    devices=None,
) -> Mesh:
    """The serving slice as a ``(scenarios, tiles)`` 2D mesh (r18,
    ROADMAP item 1): scenario-axis rungs shard their batch over
    ``scenarios`` (each scenario wholly on one device — embarrassingly
    parallel, zero per-tick collectives), and jumbo rungs run the r12
    spatial tick over ``tiles`` (collective-permute halo ring).  With
    both axes > 1, a dispatch on one axis is REPLICATED over the
    other — the whole slice serves either workload shape at any
    moment, which is the point; re-homing a rung onto a sub-rectangle
    is ROADMAP follow-up work.

    Default: every device on the scenario axis (``tiles=1`` — the
    pure scenario-serving layout; a 1-tile spatial axis is the
    single-device tick).  ``scenarios * tiles`` must cover the device
    list exactly.
    """
    devices = np.asarray(
        devices if devices is not None else jax.devices()
    )
    if tiles <= 0:
        raise ValueError(f"tiles must be >= 1, got {tiles}")
    if scenarios is None:
        if devices.size % tiles:
            raise ValueError(
                f"{devices.size} devices do not split into "
                f"tiles={tiles} columns; pass scenarios= explicitly"
            )
        scenarios = devices.size // tiles
    if scenarios * tiles != devices.size:
        raise ValueError(
            f"mesh shape ({scenarios}, {tiles}) needs "
            f"{scenarios * tiles} devices, have {devices.size}"
        )
    return Mesh(
        devices.reshape(scenarios, tiles), (SCENARIO_AXIS, TILE_AXIS)
    )


def agent_sharding(mesh: Mesh, axis: str = AGENT_AXIS) -> NamedSharding:
    """Shard dim 0 (the agent/particle axis) over ``axis``."""
    return NamedSharding(mesh, P(axis))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())
