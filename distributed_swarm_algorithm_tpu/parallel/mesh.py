"""Device-mesh construction helpers.

The reference's only notion of scale-out is "run more OS processes"
(/root/reference/agent.py:349-360) over a transport that was never written
(agent.py:188-195).  Here the communication backend is XLA collectives over
a ``jax.sharding.Mesh``: the agent/particle axis shards across devices
(data parallel over ICI), an optional island axis gives the multi-swarm
island model, and election/allocation/gbest reductions ride ICI as
``pmax``/``pmin``/``psum``/``ppermute``.
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

AGENT_AXIS = "agents"
ISLAND_AXIS = "islands"


def make_mesh(
    axis_names: Sequence[str] = (AGENT_AXIS,),
    shape: Optional[Tuple[int, ...]] = None,
    devices=None,
) -> Mesh:
    """Build a mesh over available devices.

    Default: every device on one axis.  ``shape`` splits devices over
    multiple axes, e.g. ``make_mesh(("islands", "agents"), (2, 4))``.
    """
    devices = np.asarray(devices if devices is not None else jax.devices())
    if shape is None:
        shape = (devices.size,) + (1,) * (len(axis_names) - 1)
    return Mesh(devices.reshape(shape), axis_names)


def agent_sharding(mesh: Mesh, axis: str = AGENT_AXIS) -> NamedSharding:
    """Shard dim 0 (the agent/particle axis) over ``axis``."""
    return NamedSharding(mesh, P(axis))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())
