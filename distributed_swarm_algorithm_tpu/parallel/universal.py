"""Family-agnostic multi-device execution: every optimizer family scales.

The PSO-specific paths (parallel/sharding.py, parallel/islands.py) spell
out collectives for the perf flagship.  This module gives the SAME two
scaling strategies to *every* population family (DE, ABC, GWO, WOA,
cuckoo, firefly, …) without touching family internals, exploiting the
framework-wide state convention: each family's state is a
struct-of-arrays pytree whose population leaves have dim 0 == N
(``pos [N, D]``, ``fit [N]``, …) plus replicated leaves (incumbent best,
PRNG key, iteration counter).

1. **GSPMD population sharding** — ``shard_population`` places any such
   state with the population axis sharded over the mesh; the family's
   ordinary jitted step/run then executes SPMD, XLA inserting ICI
   collectives for the global reductions (best argmin; firefly's
   all-pairs matmul becomes a sharded matmul with an all-gather).

2. **Generic island model** — ``stack_islands`` builds I independent
   populations (one PRNG stream each), ``run_islands`` steps them in
   lockstep under ``vmap`` (shardable over an island mesh axis, where
   the ring migration's ``jnp.roll`` lowers to a collective-permute),
   and ``migrate_ring`` exchanges k elites ring-wise using only the
   shared ``pos``/``fit`` fields (families with extra per-individual
   state — e.g. ABC ``trials`` — get immigrant slots reset to zero).

Capability lineage: the island model generalizes the reference's only
scale story ("more processes", /root/reference/agent.py:349-360) into
per-device subswarms with a working exchange protocol; migration plays
the role its stubbed transport (agent.py:188-195) never could.
"""

from __future__ import annotations

from functools import partial
from typing import Callable, Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .mesh import ISLAND_AXIS
from .sharding import _tree_shard_dim0


def shard_population(state, mesh: Mesh, axis: str):
    """Place any family state with the population axis (dim 0 of every
    leaf sized like ``state.pos``) sharded over ``axis``; other leaves
    replicate.  Requires N % mesh.shape[axis] == 0."""
    n = state.pos.shape[0]
    if n % mesh.shape[axis]:
        raise ValueError(
            f"population {n} not divisible by mesh axis "
            f"'{axis}' size {mesh.shape[axis]}"
        )
    return _tree_shard_dim0(state, mesh, axis, n)


# ---------------------------------------------------------------------------
# Generic island model
# ---------------------------------------------------------------------------


def stack_islands(
    init_fn: Callable,
    n_islands: int,
    seed: int = 0,
):
    """Stack ``n_islands`` independent populations into one pytree with a
    leading island axis on every leaf.

    ``init_fn(seed) -> state`` builds one island from an integer seed;
    islands get the seeds ``seed*1_000_003 + i`` (matching the PSO
    island model, parallel/islands.py) so their PRNG streams are
    disjoint.  Stacking runs per-island inits eagerly and stacks leaves
    — init cost is per-island Python, but init is once.
    """
    if n_islands < 1:
        raise ValueError(f"n_islands must be >= 1, got {n_islands}")
    states = [init_fn(seed * 1_000_003 + i) for i in range(n_islands)]
    return jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *states)


def shard_islands(stacked, mesh: Mesh, axis: str = ISLAND_AXIS):
    """Place a stacked island state with the island axis sharded."""
    n_i = stacked.pos.shape[0]
    if n_i % mesh.shape[axis]:
        raise ValueError(
            f"{n_i} islands not divisible by mesh axis "
            f"'{axis}' size {mesh.shape[axis]}"
        )
    sharded = NamedSharding(mesh, P(axis))

    # Every leaf carries the island axis at dim 0 (stack_islands built it
    # that way), so shard dim 0 unconditionally — including scalars-per-
    # island like iteration [I] and keys [I, 2].
    return jax.tree_util.tree_map(
        lambda leaf: jax.device_put(leaf, sharded), stacked
    )


def _check_migrate_k(n: int, k: int) -> None:
    if not 0 < k <= n:
        raise ValueError(f"migrate_k must be in [1, {n}], got {k}")


def migrate_ring(stacked, k: int):
    """Ring elite migration over the island axis, family-agnostic.

    Island i's k best individuals (by ``fit``) replace island (i+1)%I's
    k worst, copying the consistent ``(pos, fit)`` pairs so every
    family's ``fit == objective(pos)`` invariant survives.  If the state
    has an integer per-individual ``trials`` field (ABC), immigrant
    slots reset to 0 (a fresh source).  The ``jnp.roll`` over the island
    axis lowers to a collective-permute when that axis is sharded.
    """
    _check_migrate_k(stacked.fit.shape[1], k)
    return _migrate_ring_jit(stacked, k)


@partial(jax.jit, static_argnames=("k",))
def _migrate_ring_jit(stacked, k: int):
    pos, fit = stacked.pos, stacked.fit
    n_i, n = fit.shape

    _, best_idx = lax.top_k(-fit, k)                       # [I, k]
    em_pos = jnp.take_along_axis(pos, best_idx[..., None], axis=1)
    em_fit = jnp.take_along_axis(fit, best_idx, axis=1)
    in_pos = jnp.roll(em_pos, 1, axis=0)                   # ring i -> i+1
    in_fit = jnp.roll(em_fit, 1, axis=0)

    _, worst_idx = lax.top_k(fit, k)                       # [I, k]
    rows = jnp.arange(n_i)[:, None]
    updates = {
        "pos": pos.at[rows, worst_idx].set(in_pos),
        "fit": fit.at[rows, worst_idx].set(in_fit),
    }
    if hasattr(stacked, "trials"):
        updates["trials"] = stacked.trials.at[rows, worst_idx].set(0)
    if hasattr(stacked, "leader_fit"):
        # GWO reads only its leader archive (not ``fit``) when moving the
        # pack, so immigrants must enter the archive or migration is
        # lossy: merge them with the incumbent leaders and re-rank.
        n_lead = stacked.leader_fit.shape[1]
        all_fit = jnp.concatenate([stacked.leader_fit, in_fit], axis=1)
        all_pos = jnp.concatenate([stacked.leaders, in_pos], axis=1)
        _, top = lax.top_k(-all_fit, n_lead)               # [I, n_lead]
        updates["leader_fit"] = jnp.take_along_axis(all_fit, top, axis=1)
        updates["leaders"] = jnp.take_along_axis(
            all_pos, top[..., None], axis=1
        )
    return stacked.replace(**updates)


def run_islands(
    run_fn: Callable,
    stacked,
    n_steps: int,
    migrate_every: int = 0,
    migrate_k: int = 4,
):
    """Run all islands in lockstep; optionally migrate periodically.

    ``run_fn(state, n_steps) -> state`` is the family's jitted run
    closed over its objective/hyperparameters (e.g.
    ``lambda s, n: de_run(s, rastrigin, n)``).  With
    ``migrate_every <= 0`` this is one vmapped call; otherwise blocks of
    ``migrate_every`` steps alternate with ``migrate_ring`` (remainder
    steps run unmigrated at the end, matching parallel/islands.py).
    Each (block + migration) pair is one jit-composed executable — the
    per-block cost is a single dispatch, not a dozen eager ops.  The
    executable is local to this call (compiled once, reused across all
    its blocks, garbage-collected after): keying a global jit cache on
    ``run_fn`` identity would silently recompile for every fresh lambda
    AND pin each one's closure and executable forever.
    """
    if migrate_every <= 0:
        return jax.vmap(lambda s: run_fn(s, n_steps))(stacked)
    _check_migrate_k(stacked.fit.shape[1], migrate_k)
    n_blocks, rem = divmod(n_steps, migrate_every)
    block = jax.jit(
        lambda s: _migrate_ring_jit(
            jax.vmap(lambda t: run_fn(t, migrate_every))(s), migrate_k
        )
    )
    for _ in range(n_blocks):
        stacked = block(stacked)
    if rem:
        stacked = jax.vmap(lambda s: run_fn(s, rem))(stacked)
    return stacked


def islands_global_best(stacked) -> Tuple[jax.Array, jax.Array]:
    """(fit, pos) of the best archived optimum across all islands.

    Uses the framework-wide ``best_fit``/``best_pos`` archive fields;
    GWO, which archives the alpha wolf in ``leader_fit[0]``/
    ``leaders[0]`` instead, is handled transparently.
    """
    if hasattr(stacked, "best_fit"):
        fits, poss = stacked.best_fit, stacked.best_pos
    elif hasattr(stacked, "leader_fit"):
        fits, poss = stacked.leader_fit[:, 0], stacked.leaders[:, 0]
    else:
        raise TypeError(
            f"{type(stacked).__name__} has neither best_fit nor "
            "leader_fit archive fields"
        )
    i = jnp.argmin(fits)
    return fits[i], poss[i]
