"""The training plane (r20): pure-JAX IPPO/MAPPO over
:class:`~..envs.core.SwarmMARLEnv` with heterogeneous capability
classes.  See train/ppo.py (the fused ``train-step`` program:
rollout + GAE + clipped-surrogate epochs under one jit, donated
carry; the ``policy-rollout`` eval/serve entry; vmap-over-seeds
ensembles) and train/caps.py (ABMax-style per-class act/speed/reward
scale tables threaded as traced :class:`~..envs.core.EnvParams`
data).  docs/TRAINING.md holds the API contract."""

from .caps import (
    DEFAULT_CLASS,
    EVADER_CLASS,
    PURSUER_CLASS,
    CapabilityClass,
    caps_kwargs,
    default_caps,
    pursuit_caps,
)
from .ppo import (
    ALGOS,
    POLICY_ROLLOUT_ENTRY,
    TRAIN_STEP_ENTRY,
    TrainConfig,
    TrainState,
    actor_mean,
    init_policy_params,
    init_train_ensemble,
    init_train_state,
    policy_rollout,
    train_run,
    train_step,
    train_step_ensemble,
)

__all__ = [
    "ALGOS",
    "DEFAULT_CLASS",
    "EVADER_CLASS",
    "POLICY_ROLLOUT_ENTRY",
    "PURSUER_CLASS",
    "TRAIN_STEP_ENTRY",
    "CapabilityClass",
    "TrainConfig",
    "TrainState",
    "actor_mean",
    "caps_kwargs",
    "default_caps",
    "init_policy_params",
    "init_train_ensemble",
    "init_train_state",
    "policy_rollout",
    "pursuit_caps",
    "train_run",
    "train_step",
    "train_step_ensemble",
]
