"""Pure-JAX IPPO/MAPPO over :class:`~..envs.core.SwarmMARLEnv` (r20).

The env (r14) is JaxMARL-compatible (arxiv 2311.10090), and JaxMARL's
baselines prove the payoff of keeping the WHOLE learning loop inside
one jitted program: env rollout, GAE, and the clipped-surrogate update
fuse into a single ``lax.scan``-composed graph, so the per-update cost
is one dispatch, not ``T`` of them.  This module is that loop for the
swarm, with zero new dependencies — the network is a plain
``jax.numpy`` params-as-pytree MLP and the optimizer a hand-rolled
Adam, so the training plane rides the exact toolchain the serving
plane already ships.

Shape of the system:

- **Shared-parameter actor-critic.**  One tanh MLP maps each agent's
  observation row to a Gaussian steering mean (state-independent
  learned ``log_std``); a second MLP is the critic.  ``algo="ippo"``
  gives each agent an independent critic of its OWN observation;
  ``algo="mappo"`` is the centralized-critic variant — the critic
  additionally sees the alive-masked MEAN observation of the whole
  swarm (a fixed-shape global summary, so the centralized input
  vmaps like everything else).  Heterogeneous behavior under shared
  parameters comes from the observation, not from per-class
  networks: the env's class one-hot block (``n_cap_classes > 1``,
  envs/core.py) is how one policy plays both sides of the
  asymmetric pursuit game (train/caps.py).
- **One compiled train step.**  :func:`train_step` — the
  ``watched("train-step")`` entry — runs ``rollout_steps`` vmapped
  env steps (the S-scenario axis of the r13/r14 lattice), computes
  GAE, then scans ``n_epochs`` full-batch clipped-PPO epochs, all in
  ONE jitted program whose :class:`TrainState` carry (params, Adam
  moments, env states, observations, PRNG key) is DONATED — the
  update loop hands each step's buffers straight back to XLA, the
  r13 double-buffer discipline applied to the optimizer (swarmlint
  rule 18 ``nondonated-carry`` exists because forgetting this
  doubles live memory).  Registered with the compile observatory and
  budgeted in jaxlint (zero collectives, f64-free, donation floor).
- **Scale hooks.**  The scenario axis is already inside the program
  (train on the whole zoo at once — reward dispatch is the traced
  ``lax.switch``); :func:`init_train_ensemble` /
  :func:`train_step_ensemble` vmap the SAME step over a leading
  seeds axis (independent policies per member) — the meta-loop shape
  ROADMAP item 5 will reuse.
- **Serving the learned policy.**  :func:`policy_rollout` — the
  ``watched("policy-rollout")`` entry — rolls a (deterministic or
  sampled) policy through the env with the SAME key discipline as
  ``envs/core.env_rollout``, so a zero network's deterministic
  rollout reproduces the zero-action protocol rollout exactly; the
  serve layer buckets it (``serve/batched.train_rollouts``) like any
  other tenant workload.
"""

from __future__ import annotations

import dataclasses
import math
from functools import partial
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from flax import struct

from ..envs.core import EnvParams, EnvState, SwarmMARLEnv
from ..utils.compile_watch import watched
from ..utils.config import TELEMETRY_ON

#: Compile-observatory registry names of the training plane's jitted
#: entries (declared in jaxlint-budgets.json like every other entry).
TRAIN_STEP_ENTRY = "train-step"
POLICY_ROLLOUT_ENTRY = "policy-rollout"

#: Supported algorithm variants (static — they trace different
#: critic-input graphs).
ALGOS = ("ippo", "mappo")

_LOG2PI = math.log(2.0 * math.pi)
#: log_std clamp: exp(-5) ~ 7e-3 (effectively deterministic) to
#: exp(2) ~ 7.4 (wildly exploratory) — outside this band the
#: Gaussian logp is numerically useless.
_LOG_STD_LO, _LOG_STD_HI = -5.0, 2.0


@dataclasses.dataclass(frozen=True)
class TrainConfig:
    """Static training hyperparameters — frozen + hashable, so the
    config rides as a jit-static argument exactly like ``SwarmConfig``
    (every per-run tunable that must stay dynamic lives in the traced
    :class:`TrainState` instead)."""

    rollout_steps: int = 32     # T env steps collected per update
    n_epochs: int = 4           # full-batch PPO epochs per update
    gamma: float = 0.99
    gae_lambda: float = 0.95
    clip_eps: float = 0.2
    lr: float = 3e-4
    vf_coef: float = 0.5
    ent_coef: float = 0.01
    max_grad_norm: float = 0.5
    hidden: Tuple[int, ...] = (64, 64)
    algo: str = "ippo"
    log_std_init: float = -0.7  # exp(-0.7) ~ 0.5 — half the act bound
    adam_b1: float = 0.9
    adam_b2: float = 0.999
    adam_eps: float = 1e-8

    def __post_init__(self):
        if self.algo not in ALGOS:
            raise ValueError(
                f"algo must be one of {ALGOS}, got {self.algo!r}"
            )
        if self.rollout_steps < 1:
            raise ValueError(
                f"rollout_steps must be >= 1, got {self.rollout_steps}"
            )
        if self.n_epochs < 1:
            raise ValueError(
                f"n_epochs must be >= 1, got {self.n_epochs}"
            )
        if not self.hidden:
            raise ValueError("hidden must name at least one layer")

    def critic_in(self, obs_dim: int) -> int:
        """The critic MLP's input width: own obs (IPPO) or own obs +
        the pooled global summary (MAPPO's centralized critic)."""
        return obs_dim if self.algo == "ippo" else 2 * obs_dim


@struct.dataclass
class TrainState:
    """The donated carry of one learner: network params, Adam moments
    + step count, and the live env frontier (states, observations,
    PRNG key).  Everything is traced data — ensembles vmap a leading
    seeds axis over the whole pytree."""

    params: Any                # {"actor": [...], "critic": [...], "log_std"}
    opt_m: Any                 # Adam first moments (params-shaped)
    opt_v: Any                 # Adam second moments (params-shaped)
    opt_t: jax.Array           # i32 — Adam step count
    env: EnvState              # [S]-leaved env frontier
    obs: jax.Array             # [S, capacity, obs_dim]
    key: jax.Array             # PRNG key


# ---------------------------------------------------------------------------
# Network: params-as-pytree MLP (no deps beyond jax.numpy)


def _linear_init(key, n_in: int, n_out: int, scale: float):
    w = jax.random.normal(key, (n_in, n_out), jnp.float32) * (
        scale / jnp.sqrt(jnp.asarray(n_in, jnp.float32))
    )
    return w, jnp.zeros((n_out,), jnp.float32)


def _mlp_init(key, sizes, out_scale: float):
    keys = jax.random.split(key, len(sizes) - 1)
    layers = []
    for i in range(len(sizes) - 1):
        scale = math.sqrt(2.0) if i < len(sizes) - 2 else out_scale
        layers.append(
            _linear_init(keys[i], sizes[i], sizes[i + 1], scale)
        )
    return layers


def _mlp(layers, x: jax.Array) -> jax.Array:
    for w, b in layers[:-1]:
        x = jnp.tanh(x @ w + b)
    w, b = layers[-1]
    return x @ w + b


def init_policy_params(
    key: jax.Array, obs_dim: int, act_dim: int, tcfg: TrainConfig
):
    """The network pytree: actor (small-scaled output head so the
    initial policy is near-zero steering — the protocol-respecting
    start), critic, and the state-independent ``log_std``."""
    akey, ckey = jax.random.split(key)
    hidden = tuple(tcfg.hidden)
    return {
        "actor": _mlp_init(
            akey, (obs_dim,) + hidden + (act_dim,), out_scale=0.01
        ),
        "critic": _mlp_init(
            ckey, (tcfg.critic_in(obs_dim),) + hidden + (1,),
            out_scale=1.0,
        ),
        "log_std": jnp.full(
            (act_dim,), tcfg.log_std_init, jnp.float32
        ),
    }


def actor_mean(net, obs: jax.Array) -> jax.Array:
    """The policy's deterministic action (the eval/serve head)."""
    return _mlp(net["actor"], obs)


def _log_std(net) -> jax.Array:
    return jnp.clip(net["log_std"], _LOG_STD_LO, _LOG_STD_HI)


def _gauss_logp(mean, log_std, act) -> jax.Array:
    z = (act - mean) * jnp.exp(-log_std)
    return -0.5 * jnp.sum(
        z * z + 2.0 * log_std + _LOG2PI, axis=-1
    )


def _gauss_entropy(log_std) -> jax.Array:
    return jnp.sum(log_std + 0.5 * (_LOG2PI + 1.0))


def _critic_obs(obs: jax.Array, alive: jax.Array, algo: str):
    """The critic's input: own obs (IPPO), or own obs concatenated
    with the alive-masked mean observation of the whole swarm (MAPPO
    — a fixed-shape centralized summary; dead/pad rows are all-zero
    by the env contract so the mask only fixes the denominator)."""
    if algo == "ippo":
        return obs
    w = alive.astype(jnp.float32)[..., None]           # [..., N, 1]
    pooled = (obs * w).sum(axis=-2, keepdims=True) / jnp.maximum(
        w.sum(axis=-2, keepdims=True), 1.0
    )
    return jnp.concatenate(
        [obs, jnp.broadcast_to(pooled, obs.shape)], axis=-1
    )


# ---------------------------------------------------------------------------
# Optimizer: hand-rolled Adam (pure jnp, donation-friendly pytrees)


def _clip_by_global_norm(grads, max_norm: float):
    gn = jnp.sqrt(
        sum(jnp.sum(g * g) for g in jax.tree_util.tree_leaves(grads))
    )
    scale = jnp.minimum(1.0, max_norm / jnp.maximum(gn, 1e-9))
    return jax.tree_util.tree_map(lambda g: g * scale, grads), gn


def _adam(net, grads, m, v, t, tcfg: TrainConfig):
    t = t + 1
    b1, b2 = tcfg.adam_b1, tcfg.adam_b2
    m = jax.tree_util.tree_map(
        lambda mm, g: b1 * mm + (1.0 - b1) * g, m, grads
    )
    v = jax.tree_util.tree_map(
        lambda vv, g: b2 * vv + (1.0 - b2) * g * g, v, grads
    )
    tf = t.astype(jnp.float32)
    c1 = 1.0 - b1 ** tf
    c2 = 1.0 - b2 ** tf
    net = jax.tree_util.tree_map(
        lambda p, mm, vv: p - tcfg.lr * (mm / c1) / (
            jnp.sqrt(vv / c2) + tcfg.adam_eps
        ),
        net, m, v,
    )
    return net, m, v, t


# ---------------------------------------------------------------------------
# GAE


def _gae(rewards, values, dones, last_value, gamma, lam):
    """(advantages, returns) by reverse scan; ``dones`` terminates the
    bootstrap (per-agent — a tagged evader's stream ends where the
    episode-boundary select restarts everyone's)."""

    def back(carry, inp):
        adv_next, v_next = carry
        r, v, nonterm = inp
        delta = r + gamma * v_next * nonterm - v
        adv = delta + gamma * lam * nonterm * adv_next
        return (adv, v), adv

    nonterm = 1.0 - dones
    (_, _), advs = jax.lax.scan(
        back,
        (jnp.zeros_like(last_value), last_value),
        (rewards, values, nonterm),
        reverse=True,
    )
    return advs, advs + values


# ---------------------------------------------------------------------------
# The train step: rollout + GAE + epochs, ONE compiled program


def init_train_state(
    key: jax.Array,
    params: EnvParams,
    env: SwarmMARLEnv,
    tcfg: TrainConfig,
) -> TrainState:
    """Fresh learner state over the ``[S]``-stacked scenarios: vmapped
    env reset (one PRNG stream per scenario — the key-broadcast rule)
    plus network/optimizer init."""
    # The scenario params ride INSIDE the donated carry (EnvState
    # holds them), so without this copy the first train_step would
    # hand the CALLER's EnvParams buffers to XLA — and every later
    # use of them (a second learner, an eval rollout) would hit
    # "buffer has been deleted or donated".  They are a few hundred
    # bytes; copy once here.
    params = jax.tree_util.tree_map(jnp.copy, params)
    n_scen = params.reward_id.shape[0]
    key, nkey, rkey = jax.random.split(key, 3)
    obs, states = jax.vmap(env.reset)(
        jax.random.split(rkey, n_scen), params
    )
    net = init_policy_params(nkey, env.obs_dim, env.action_dim, tcfg)
    zeros = jax.tree_util.tree_map(jnp.zeros_like, net)
    return TrainState(
        params=net,
        opt_m=zeros,
        opt_v=jax.tree_util.tree_map(jnp.zeros_like, net),
        opt_t=jnp.zeros((), jnp.int32),
        env=states,
        obs=obs,
        key=key,
    )


def _train_step_core(
    ts: TrainState, env: SwarmMARLEnv, tcfg: TrainConfig
):
    """(TrainState, metrics): one full PPO update — see module doc.
    Plain (un-jitted): the jitted/vmapped entries below own the
    transform composition."""
    net = ts.params

    def rollout_body(carry, _):
        st, obs, key = carry
        key, akey, skey = jax.random.split(key, 3)
        mean = actor_mean(net, obs)
        log_std = _log_std(net)
        noise = jax.random.normal(akey, mean.shape, jnp.float32)
        act = mean + jnp.exp(log_std) * noise
        logp = _gauss_logp(mean, log_std, act)
        alive = st.swarm.alive                         # [S, N]
        val = _mlp(
            net["critic"], _critic_obs(obs, alive, tcfg.algo)
        )[..., 0]
        skeys = jax.random.split(skey, obs.shape[0])
        obs2, st2, rew, dones, _ = jax.vmap(
            lambda k, s, a: env.step(k, s, a)
        )(skeys, st, act)
        ys = (
            obs, act, logp, val, rew,
            dones.astype(jnp.float32),
            alive.astype(jnp.float32),
        )
        return (st2, obs2, key), ys

    (st_f, obs_f, key_f), traj = jax.lax.scan(
        rollout_body, (ts.env, ts.obs, ts.key), None,
        length=tcfg.rollout_steps,
    )
    obs_t, act_t, logp_t, val_t, rew_t, done_t, mask = traj
    last_val = _mlp(
        net["critic"],
        _critic_obs(obs_f, st_f.swarm.alive, tcfg.algo),
    )[..., 0]
    adv_t, ret_t = _gae(
        rew_t, val_t, done_t, last_val, tcfg.gamma, tcfg.gae_lambda
    )

    # Masked, PER-SCENARIO advantage normalization: dead/pad slots
    # carry obs of zeros and rewards of zero — they must not dilute
    # the statistics — and the zoo's reward scales span orders of
    # magnitude (obstacle-field ~ -9/step vs coverage ~ +0.06/step),
    # so a GLOBAL normalization would let the large-scale scenario's
    # variance crush every other scenario's gradient signal.  Axes
    # (T, N) per scenario; with S = 1 this is the classic global
    # normalization.
    msum = jnp.maximum(mask.sum(), 1.0)
    s_sum = jnp.maximum(mask.sum(axis=(0, 2), keepdims=True), 1.0)
    amean = (adv_t * mask).sum(axis=(0, 2), keepdims=True) / s_sum
    avar = (
        ((adv_t - amean) ** 2) * mask
    ).sum(axis=(0, 2), keepdims=True) / s_sum
    adv_n = (adv_t - amean) / jnp.sqrt(avar + 1e-8)

    def loss_fn(p):
        mean = actor_mean(p, obs_t)
        log_std = _log_std(p)
        logp = _gauss_logp(mean, log_std, act_t)
        ratio = jnp.exp(logp - logp_t)
        clipped = jnp.clip(
            ratio, 1.0 - tcfg.clip_eps, 1.0 + tcfg.clip_eps
        )
        pg = -(
            jnp.minimum(ratio * adv_n, clipped * adv_n) * mask
        ).sum() / msum
        v = _mlp(
            p["critic"],
            _critic_obs(obs_t, mask > 0.0, tcfg.algo),
        )[..., 0]
        v_loss = 0.5 * (((v - ret_t) ** 2) * mask).sum() / msum
        ent = _gauss_entropy(_log_std(p))
        kl = ((logp_t - logp) * mask).sum() / msum
        total = pg + tcfg.vf_coef * v_loss - tcfg.ent_coef * ent
        return total, (pg, v_loss, ent, kl)

    def epoch_body(carry, _):
        p, m, v, t = carry
        (total, aux), grads = jax.value_and_grad(
            loss_fn, has_aux=True
        )(p)
        grads, gn = _clip_by_global_norm(grads, tcfg.max_grad_norm)
        p, m, v, t = _adam(p, grads, m, v, t, tcfg)
        return (p, m, v, t), (total,) + aux + (gn,)

    (net2, m2, v2, t2), stats = jax.lax.scan(
        epoch_body, (net, ts.opt_m, ts.opt_v, ts.opt_t), None,
        length=tcfg.n_epochs,
    )
    total, pg, v_loss, ent, kl, gn = stats
    metrics = {
        "reward_mean": (rew_t * mask).sum() / msum,
        "loss": total[-1],
        "pg_loss": pg[-1],
        "v_loss": v_loss[-1],
        "entropy": ent[-1],
        "approx_kl": kl[-1],
        "grad_norm": gn[-1],
    }
    ts2 = TrainState(
        params=net2, opt_m=m2, opt_v=v2, opt_t=t2,
        env=st_f, obs=obs_f, key=key_f,
    )
    return ts2, metrics


@watched(TRAIN_STEP_ENTRY)
@partial(
    jax.jit, static_argnames=("env", "tcfg"), donate_argnums=(0,)
)
def _train_step_impl(
    ts: TrainState, env: SwarmMARLEnv, tcfg: TrainConfig
):
    return _train_step_core(ts, env, tcfg)


def _ens_core(ts, env, tcfg):
    return jax.vmap(
        lambda t: _train_step_core(t, env, tcfg)
    )(ts)


#: The seeds-axis twin: the SAME core vmapped over a leading ensemble
#: axis of the whole TrainState, registered under the same observatory
#: entry (one more signature, declared in the entry's bucket budget).
_train_step_ens_impl = watched(TRAIN_STEP_ENTRY)(
    partial(
        jax.jit, static_argnums=(1, 2), donate_argnums=(0,)
    )(_ens_core)
)


def _dealias_donated(ts: TrainState) -> TrainState:
    """Copy any leaf that shares a device buffer with an earlier leaf
    — XLA refuses to donate one buffer twice, and duplicate buffers
    are REAL here: the eager constant cache hands every same-shaped
    ``jnp.zeros`` the same buffer (Adam moments and bias init), and
    the compiled step's own output aliasing can merge identical
    values.  Duplicates are a handful of small leaves, so the copies
    cost microseconds; tracers (the vmapped ensemble core) expose no
    buffer and pass through untouched."""
    seen: set = set()

    def fix(x):
        try:
            p = x.unsafe_buffer_pointer()
        except Exception:
            return x
        if p in seen:
            return jnp.copy(x)
        seen.add(p)
        return x

    return jax.tree_util.tree_map(fix, ts)


def train_step(
    ts: TrainState, env: SwarmMARLEnv, tcfg: TrainConfig
):
    """(TrainState, metrics): ONE compiled PPO update — env rollout,
    GAE, and ``n_epochs`` clipped-surrogate epochs fused into the
    single ``"train-step"`` program.  ``ts`` is DONATED — rebind it
    (``ts, m = train_step(ts, ...)``); its buffers belong to XLA
    after the call."""
    return _train_step_impl(_dealias_donated(ts), env, tcfg)


def init_train_ensemble(
    keys: jax.Array,
    params: EnvParams,
    env: SwarmMARLEnv,
    tcfg: TrainConfig,
) -> TrainState:
    """[E]-leaved learner ensemble: one independent policy + env
    frontier per seed (``keys [E, 2]``), all stepping in one program
    via :func:`train_step_ensemble` — the vmap-over-seeds scale hook
    the meta-loop (ROADMAP item 5) reuses."""
    keys = jnp.asarray(keys)
    if keys.ndim != 2:
        raise ValueError(
            "init_train_ensemble wants batched keys [E, 2] — one "
            f"PRNG stream per ensemble member; got shape {keys.shape}"
        )
    return jax.vmap(
        lambda k: init_train_state(k, params, env, tcfg)
    )(keys)


def train_step_ensemble(
    ts: TrainState, env: SwarmMARLEnv, tcfg: TrainConfig
):
    """The ensemble twin of :func:`train_step`: E independent
    learners advance one update in one compiled program (metrics gain
    a leading ``[E]`` axis).  ``ts`` is DONATED."""
    return _train_step_ens_impl(_dealias_donated(ts), env, tcfg)


def train_run(
    ts: TrainState,
    env: SwarmMARLEnv,
    tcfg: TrainConfig,
    n_updates: int,
    ensemble: bool = False,
):
    """(TrainState, metrics): ``n_updates`` donated train steps with
    the per-update metrics stacked host-side (``{name: [n_updates]}``
    numpy arrays; ``[n_updates, E]`` for ensembles) — the loop every
    example/bench drives.  One compiled program total: the carry
    donation means update k+1 reuses update k's buffers."""
    step = train_step_ensemble if ensemble else train_step
    rows = []
    for _ in range(n_updates):
        ts, m = step(ts, env, tcfg)
        rows.append(m)
    metrics = {
        k: np.stack([np.asarray(r[k]) for r in rows])
        for k in (rows[0] if rows else {})
    }
    return ts, metrics


# ---------------------------------------------------------------------------
# Serving the learned policy


@watched(POLICY_ROLLOUT_ENTRY)
@partial(
    jax.jit,
    static_argnames=(
        "env", "tcfg", "n_steps", "deterministic", "telemetry",
    ),
)
def _policy_rollout_impl(
    keys: jax.Array,
    params: EnvParams,
    net,
    env: SwarmMARLEnv,
    tcfg: TrainConfig,
    n_steps: int,
    deterministic: bool = True,
    telemetry: bool = False,
):
    """``n_steps`` vmapped env steps under the LEARNED policy — the
    compiled eval/serve rollout.  The network rides as traced data,
    so one compiled program serves every checkpoint of one
    architecture.  Key discipline mirrors
    ``envs/core._env_rollout_impl`` exactly (reset from ``split[:,
    0]``, per-step 3-way splits), so a zero network's deterministic
    rollout steps the IDENTICAL episode stream the zero-action
    protocol rollout does — the learned-vs-protocol comparison is
    apples to apples by construction."""
    telem_on = telemetry or env.cfg.telemetry.enabled
    if telem_on and not env.cfg.telemetry.enabled:
        env = env.replace(cfg=env.cfg.replace(telemetry=TELEMETRY_ON))

    split = jax.vmap(lambda k: jax.random.split(k, 2))(keys)
    obs, states = jax.vmap(env.reset)(split[:, 0], params)

    def body(carry, _):
        lkeys, obs, states = carry
        parts = jax.vmap(lambda k: jax.random.split(k, 3))(lkeys)
        lkeys, akeys, skeys = parts[:, 0], parts[:, 1], parts[:, 2]
        mean = actor_mean(net, obs)
        if deterministic:
            acts = mean
        else:
            noise = jax.vmap(
                lambda ak, m: jax.random.normal(
                    ak, m.shape, jnp.float32
                )
            )(akeys, mean)
            acts = mean + jnp.exp(_log_std(net)) * noise
        obs, states, rew, dones, info = jax.vmap(
            lambda k, s, a: env.step(k, s, a)
        )(skeys, states, acts)
        telem = info["telemetry"] if telem_on else None
        return (lkeys, obs, states), (rew, dones, telem)

    (_, obs, states), (rewards, dones, telem) = jax.lax.scan(
        body, (split[:, 1], obs, states), None, length=n_steps
    )
    out = (states, rewards, dones)
    if telem_on:
        if not n_steps:
            telem = None
        out = out + (telem,)
    return out


def policy_rollout(
    keys: jax.Array,
    env: SwarmMARLEnv,
    params: EnvParams,
    net,
    tcfg: TrainConfig,
    n_steps: int,
    deterministic: bool = True,
    telemetry: bool = False,
):
    """Public entry for the compiled learned-policy rollout (see
    :func:`_policy_rollout_impl`).  ``keys`` must carry a leading
    scenario axis matching ``params`` (``[S, 2]``)."""
    keys = jnp.asarray(keys)
    if keys.ndim != 2:
        raise ValueError(
            "policy_rollout wants batched keys [S, 2] — one PRNG "
            f"stream per scenario; got shape {keys.shape} (wrap a "
            "single key with key[None] and stack_env_params([params]))"
        )
    return _policy_rollout_impl(
        keys, params, net, env, tcfg, n_steps, deterministic,
        telemetry,
    )
