"""Heterogeneous capability classes for the MARL env (r20).

ABMax (arxiv 2508.16508) makes heterogeneity a first-class batched
axis: agent *types* are data riding the vectorized state, never a
fork of the step function.  This module is that discipline on the
swarm env: a capability CLASS is a row of three per-class scale
tables —

  - ``act_scale``   — multiplies the env's ``act_limit`` (how hard
    this class can steer),
  - ``speed_scale`` — multiplies the scenario's ``max_speed`` clamp
    (how fast this class can move),
  - ``reward_scale`` — weights this class's per-agent reward (whose
    objective dominates the shared-policy gradient),

and the per-agent ``cap_class`` column assigns one class per slot.
All four arrays enter :class:`~..envs.core.EnvParams` as TRACED data
(``envs/core.make_env_params``), so one compiled program serves every
class layout — the r13 params-as-data discipline extended to agent
types.

The load-bearing default: a table of all-default classes (class 0
everywhere, every scale 1.0) is arithmetically a multiply-by-one, so
the r14 "zero action == protocol rollout BITWISE" pin survives the
caps machinery being always-on (tests/test_train.py pins this).

The flagship asymmetric game (:func:`pursuit_caps`): evaders out-run
pursuers (``speed_scale`` > 1) but steer more coarsely (``act_scale``
< 1) — pursuit-evasion stops being a symmetric race and becomes a
genuine pursuit-curve problem the learned policy must solve per
class (the class one-hot block in the observation is what lets one
shared policy condition on its own class).
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Optional, Sequence

import numpy as np

from ..envs.core import SwarmMARLEnv


@dataclasses.dataclass(frozen=True)
class CapabilityClass:
    """One capability class: a named row of the three scale tables."""

    name: str
    act_scale: float = 1.0
    speed_scale: float = 1.0
    reward_scale: float = 1.0


#: The homogeneous class every default-built scenario uses.
DEFAULT_CLASS = CapabilityClass("default")

#: The canonical asymmetric pursuit pair (module doc): pursuers are
#: the protocol baseline; evaders trade steering precision for top
#: speed — faster in a straight line, wider turns.
PURSUER_CLASS = CapabilityClass("pursuer")
EVADER_CLASS = CapabilityClass(
    "evader", act_scale=0.8, speed_scale=1.2
)


def caps_kwargs(
    env: SwarmMARLEnv,
    classes: Sequence[CapabilityClass],
    assignment: Sequence[int],
) -> Dict[str, object]:
    """The ``make_env_params`` kwargs for one class layout: validated
    per-class tables + the per-agent assignment column.  ``classes``
    must match the env's static ``n_cap_classes`` (a shape);
    ``assignment`` is one class id per capacity slot."""
    classes = list(classes)
    if len(classes) != env.n_cap_classes:
        raise ValueError(
            f"{len(classes)} classes for an env with n_cap_classes="
            f"{env.n_cap_classes} — the class table is a shape; "
            "build the env with matching n_cap_classes"
        )
    assign = np.asarray(list(assignment), np.int32)
    if assign.shape != (env.capacity,):
        raise ValueError(
            f"assignment must name a class per capacity slot "
            f"([{env.capacity}]), got shape {assign.shape}"
        )
    return {
        "cap_class": assign,
        "cap_act": [c.act_scale for c in classes],
        "cap_speed": [c.speed_scale for c in classes],
        "cap_reward": [c.reward_scale for c in classes],
    }


def default_caps(env: SwarmMARLEnv) -> Dict[str, object]:
    """The all-default table — the bitwise-neutral layout the r14
    parity pin extends over (every agent class 0, every scale 1.0)."""
    return caps_kwargs(
        env,
        [DEFAULT_CLASS] * env.n_cap_classes,
        [0] * env.capacity,
    )


def pursuit_caps(
    env: SwarmMARLEnv,
    n_agents: Optional[int] = None,
    pursuer: CapabilityClass = PURSUER_CLASS,
    evader: CapabilityClass = EVADER_CLASS,
) -> Dict[str, object]:
    """The asymmetric pursuit layout, aligned with
    ``envs/scenarios.pursuit_evasion``'s team split (lower half of
    the id range pursues = class 0, upper half evades = class 1) so
    the class table and the tag-sweep team column describe the same
    populations.  Needs ``n_cap_classes == 2``."""
    if env.n_cap_classes != 2:
        raise ValueError(
            "pursuit_caps is the two-class layout — build the env "
            f"with n_cap_classes=2 (got {env.n_cap_classes})"
        )
    cap = env.capacity
    n = cap if n_agents is None else int(n_agents)
    assign = [0] * cap
    for i in range(n // 2, n):
        assign[i] = 1
    return caps_kwargs(env, [pursuer, evader], assign)
