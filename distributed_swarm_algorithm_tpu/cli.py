"""Command-line interface.

A superset of the reference CLI (/root/reference/agent.py:349-360, flags
``--id --count --caps``) plus subcommands for the deployments the
reference could not actually run:

  agent   one per-agent process (reference-compatible; UDP transport works)
  sim     N agents on an in-process bus, stepped in lockstep
  swarm   the vectorized TPU swarm (VectorSwarm)
  pso     particle-swarm optimization (gbest/lbest topologies, memetic
          jax.grad refinement, island model)
  de      differential evolution on a benchmark objective
  cmaes   CMA-ES on a benchmark objective
  boids   Reynolds flocking simulation (order-parameter report)
  aco     ant-colony TSP solver
  abc     artificial bee colony on a benchmark objective
  gwo     grey wolf optimizer on a benchmark objective
  firefly firefly algorithm on a benchmark objective
  cuckoo  cuckoo search on a benchmark objective
  woa     whale optimization on a benchmark objective
  bat     bat algorithm on a benchmark objective
  salp    salp swarm algorithm on a benchmark objective
  mfo     moth-flame optimization on a benchmark objective
  hho     Harris hawks optimization on a benchmark objective
  nsga2   NSGA-II multi-objective search on a ZDT problem
  ga      real-coded genetic algorithm on a benchmark objective
  pt      parallel tempering (replica exchange) on a benchmark objective
  es      OpenAI-style evolution strategy on a benchmark objective
  shade   success-history adaptive DE on a benchmark objective
  mapelites  MAP-Elites quality-diversity archive on a benchmark objective
  bench   the headline benchmark (same as bench.py)

``python -m distributed_swarm_algorithm_tpu --id 1 --count 3 --caps lift``
is accepted as-is (bare reference flags imply ``agent``).
"""

from __future__ import annotations

import argparse
import json
import sys
import time


def _add_agent_args(p: argparse.ArgumentParser) -> None:
    p.add_argument("--id", type=int, required=True, help="Agent ID")
    p.add_argument("--count", type=int, default=1, help="Total Agents")
    p.add_argument("--caps", type=str, nargs="+", default=[],
                   help="Agent Capabilities")
    p.add_argument("--bind", type=str, default=None,
                   help="bind addr host:port (enables the socket transport)")
    p.add_argument("--peers", type=str, nargs="*", default=[],
                   help="peer addrs host:port")
    p.add_argument("--transport", choices=("udp", "tcp"), default="udp",
                   help="socket transport when --bind is given (the two "
                        "backends the reference names at agent.py:191-193)")
    p.add_argument("--steps", type=int, default=0,
                   help="run N ticks then exit (0 = forever)")
    p.add_argument("--tick-rate", type=float, default=None,
                   help="override loop rate in Hz (timeouts are "
                        "tick-derived, so protocol semantics scale with "
                        "it — handy for fast integration tests)")
    p.add_argument("--task", action="append", default=[],
                   metavar="ID,X,Y[,CAP]",
                   help="seed a task (repeatable); statuses are reported "
                        "in the exit JSON — gives the multi-process "
                        "deployment an end-to-end allocation path")
    p.add_argument("--hold", action="store_true",
                   help="after binding the transport (and printing the "
                        "online beacon), wait for one line on stdin "
                        "before starting the tick loop — lets an "
                        "orchestrator start N agents simultaneously "
                        "regardless of per-process startup skew")


def _parse_addr(addr: str):
    host, _, port = addr.rpartition(":")
    if not host or not port.isdigit():
        raise SystemExit(
            f"error: expected host:port, got {addr!r} (e.g. 127.0.0.1:9001)"
        )
    return host, int(port)


def _cmd_agent(args) -> int:
    import logging

    from .models.agent import SwarmAgent, TcpTransport, UdpTransport

    if args.hold and not args.bind:
        raise SystemExit(
            "error: --hold requires --bind (the release contract is "
            "the 'online' beacon, which only a bound transport prints)"
        )

    # The reference logs agent lifecycle at INFO (agent.py:9-10); match it
    # so elections/claims are visible from the terminal.
    logging.basicConfig(
        level=logging.INFO,
        format="%(asctime)s - %(name)s - %(levelname)s - %(message)s",
    )
    config = None
    if args.tick_rate:
        from .utils.config import SwarmConfig

        config = SwarmConfig(tick_rate_hz=args.tick_rate)
    agent = SwarmAgent(
        args.id, args.count, capabilities=args.caps, config=config
    )
    for spec in args.task:
        parts = spec.split(",")
        if len(parts) not in (3, 4):
            raise SystemExit(
                f"error: expected ID,X,Y[,CAP], got {spec!r}"
            )
        try:
            tid, x, y = int(parts[0]), float(parts[1]), float(parts[2])
        except ValueError:
            raise SystemExit(
                f"error: expected numeric ID,X,Y in {spec!r}"
            )
        agent.tasks[tid] = {
            "status": "OPEN",
            "pos": (x, y),
            "required_cap": parts[3] if len(parts) == 4 else None,
        }
    if args.bind:
        peers = [_parse_addr(p) for p in args.peers]
        cls = TcpTransport if args.transport == "tcp" else UdpTransport
        transport = cls(_parse_addr(args.bind), peers)
        transport.attach(agent)
        # Readiness beacon for process orchestration (integration tests
        # wait for this line before staging peers/faults).
        agent.log.info(
            "online: %s transport bound to %s", args.transport, args.bind
        )
    try:
        if args.hold:
            sys.stdin.readline()
            # The heartbeat clock started at construction; re-arm it so
            # the election timeout counts from the synchronized start.
            agent.last_heartbeat_time = agent.time_fn()
        if args.steps:
            period = 1.0 / agent.config.tick_rate_hz
            for _ in range(args.steps):
                start = time.time()
                agent.step()
                # Sleep the leftover, like update_loop (agent.py:78-81), so
                # wall-clock timing stays at tick_rate_hz.
                time.sleep(max(0.0, period - (time.time() - start)))
            out = {
                "id": agent.agent_id,
                "state": agent.state.name,
                "leader_id": agent.leader_id,
                "position": [round(p, 3) for p in agent.position],
                "tick": agent.tick,
            }
            if agent.tasks:
                out["tasks"] = {
                    str(tid): t["status"]
                    for tid, t in sorted(agent.tasks.items())
                }
            print(json.dumps(out))
        else:
            agent.update_loop()
    except KeyboardInterrupt:
        print("Shutting down.")
    finally:
        if args.bind:
            transport.close()
    return 0


def _cmd_sim(args) -> int:
    from .models.agent import AgentState, run_local_swarm

    agents, _ = run_local_swarm(args.n, args.steps, seed=args.seed)
    leaders = [a.agent_id for a in agents if a.state == AgentState.LEADER]
    print(json.dumps({
        "agents": args.n,
        "ticks": args.steps,
        "leaders": leaders,
        "consensus": len({a.leader_id for a in agents}) == 1,
    }))
    return 0


def _cmd_swarm(args) -> int:
    # Preflight flag combinations before any backend construction (the
    # native backend may trigger an on-demand C++ build) and before any
    # simulation work.
    if (
        getattr(args, "load_state", None)
        or getattr(args, "save_state", None)
    ) and args.backend != "jax":
        raise SystemExit(
            "error: --load-state/--save-state need --backend jax"
        )
    render = getattr(args, "render", None)
    if render and args.backend != "jax":
        raise SystemExit(
            "error: --render needs trajectory recording "
            "(--backend jax)"
        )
    if render and args.dim != 2:
        raise SystemExit("error: --render is 2-D only")
    if render and args.steps < 1:
        raise SystemExit(
            f"error: --steps ({args.steps}) must be >= 1 with --render"
        )
    if args.backend == "jax":
        from .models.swarm import VectorSwarm
        from .utils.config import DEFAULT_CONFIG

        cfg = DEFAULT_CONFIG.replace(separation_mode=args.separation)
        if args.separation == "hashgrid":
            # Default arena: 4x the spawn spread, so targets well
            # outside the spawn box stay inside the torus.
            cfg = cfg.replace(
                world_hw=args.world_hw
                if args.world_hw > 0 else 4.0 * max(args.spread, 1.0)
            )
        sw = VectorSwarm(args.n, dim=args.dim, seed=args.seed,
                         spread=args.spread, config=cfg)
    else:
        from .models.cpu_swarm import CpuSwarm

        if args.dim != 2:
            raise SystemExit("error: CPU backends are 2-D (like the "
                             "reference world); use --backend jax")
        sw = CpuSwarm(args.n, seed=args.seed, spread=args.spread,
                      backend=args.backend)
    if getattr(args, "load_state", None):
        sw.load(args.load_state)
        got = tuple(sw.state.pos.shape)
        if got != (args.n, args.dim):
            raise SystemExit(
                f"error: checkpoint holds a {got[0]}-agent {got[1]}-D "
                f"swarm; rerun with --n {got[0]} --dim {got[1]}"
            )
    if args.target:
        sw.set_target([float(x) for x in args.target])
    import contextlib

    if args.trace:
        from .utils.profiling import trace as _trace

        tracer = _trace(args.trace)
    else:
        tracer = contextlib.nullcontext()
    start = time.perf_counter()
    with tracer:
        if render:
            traj = sw.step(args.steps, record=True)
        else:
            sw.step(args.steps)
        if args.backend == "jax":
            # JAX dispatch is async — sync INSIDE the traced block so the
            # profiler captures the device work, and before timing.
            import jax

            jax.block_until_ready(sw.state.pos)
    elapsed = time.perf_counter() - start
    if getattr(args, "save_state", None):
        sw.save(args.save_state)
    if render:
        import numpy as _np

        from .utils.render import trajectory_svg

        trajectory_svg(
            _np.asarray(traj), render,
            targets=[[float(x) for x in args.target]]
            if args.target else None,
            trails=args.n <= 64,
        )
    lid, exists = sw.leader()
    print(json.dumps({
        "agents": args.n,
        "ticks": args.steps,
        "backend": getattr(sw, "backend", "jax"),
        "leader": lid if exists else None,
        "ticks_per_sec": round(args.steps / elapsed, 1),
        "agent_steps_per_sec": round(args.steps * args.n / elapsed, 1),
    }))
    return 0


def _cmd_pso(args) -> int:
    if args.islands < 1:
        raise SystemExit(f"error: --islands ({args.islands}) must be >= 1")
    if args.islands > 1:
        # The island path has its own migration-based social structure;
        # reject flags it would otherwise silently drop.
        if args.topology != "gbest" or args.refine_every > 0:
            raise SystemExit(
                "error: --topology/--refine-every are not supported with "
                "--islands > 1 (each island is a gbest swarm; diversity "
                "comes from migration)"
            )
        if getattr(args, "history", None):
            raise SystemExit(
                "error: --history is not supported with --islands > 1 "
                "(the island path runs one fused program end to end)"
            )
        return _cmd_pso_islands(args)

    kwargs = dict(topology=args.topology, ring_radius=args.ring_radius)
    if args.refine_every > 0:
        from .models.memetic import MemeticPSO

        opt = MemeticPSO(
            args.objective, n=args.n, dim=args.dim, seed=args.seed,
            refine_every=args.refine_every, refine_steps=args.refine_steps,
            lr=args.lr, **kwargs,
        )
    else:
        from .models.pso import PSO

        opt = PSO(args.objective, n=args.n, dim=args.dim, seed=args.seed,
                  **kwargs)
    return _run_report(
        opt, args, "particles",
        extra={"topology": args.topology, "memetic": args.refine_every > 0},
    )


def _cmd_pso_islands(args) -> int:
    """Island-model PSO: fused Pallas path on TPU, portable vmap on CPU."""
    import jax

    from .ops.objectives import get_objective
    from .ops.pallas.pso_fused import pallas_supported
    from .parallel.islands import global_best, island_init, island_run
    from .utils.platform import on_tpu

    fn, hw = get_objective(args.objective)
    n_per, rem = divmod(args.n, args.islands)
    if n_per < 1:
        raise SystemExit(
            f"error: --n ({args.n}) must be >= --islands ({args.islands})"
        )
    if rem:
        print(
            f"note: --n {args.n} not divisible by --islands "
            f"{args.islands}; running {n_per * args.islands} particles",
            file=sys.stderr,
        )
    st = island_init(fn, n_islands=args.islands, n_per_island=n_per,
                     dim=args.dim, half_width=hw, seed=args.seed)
    use_fused = on_tpu() and pallas_supported(
        args.objective, st.pso.pos.dtype, st.pso.pos.shape[-1]
    )
    start = time.perf_counter()
    if use_fused:
        from .ops.pallas.islands_fused import fused_island_run

        st = fused_island_run(
            st, args.objective, args.steps,
            migrate_every=args.migrate_every, migrate_k=args.migrate_k,
            half_width=hw,
        )
    else:
        st = island_run(
            st, fn, args.steps, migrate_every=args.migrate_every,
            migrate_k=args.migrate_k, half_width=hw,
        )
    fit, _ = global_best(st)
    best = float(fit)   # device sync included in the timing
    elapsed = time.perf_counter() - start
    print(json.dumps({
        "objective": args.objective,
        "islands": args.islands,
        "particles_per_island": n_per,
        "dim": args.dim,
        "iters": args.steps,
        "path": "pallas-fused" if use_fused else "vmap",
        "best": best,
        "steps_per_sec": round(args.steps / elapsed, 1),
    }))
    return 0


def _write_history(opt, args, metric=None) -> bool:
    """Handle ``--history`` for an optimizer subcommand: validate the
    flags, record the convergence curve (which runs the optimizer), and
    write JSON-safe output (non-finite samples — e.g. an unevaluated
    initial best — become null).  Returns True if a curve was recorded,
    False if the caller should run the optimizer itself."""
    import math

    history_path = getattr(args, "history", None)
    if not history_path:
        return False
    from .utils.history import best_curve

    every = getattr(args, "history_every", 16)
    if every <= 0:
        raise SystemExit(f"error: --history-every ({every}) must be >= 1")
    if args.steps <= 0:
        raise SystemExit(
            f"error: --steps ({args.steps}) must be >= 1 with --history"
        )
    curve = best_curve(opt, args.steps, chunk=every, metric=metric)
    for p in curve:
        if not math.isfinite(p["best"]):
            p["best"] = None
    with open(history_path, "w") as fh:
        json.dump(curve, fh)
    return True


def _run_report(opt, args, count_key: str, count=None, extra=None) -> int:
    """Shared optimizer-subcommand tail: timed run + one JSON line.

    Every benchmark-objective optimizer subcommand reports the same
    schema — objective, population size (under a family-specific key),
    dim, iters, best, steps/sec — plus optional family extras (callable
    values are evaluated after the run, for final-state fields).

    ``--history FILE`` (available on every single-objective optimizer
    subcommand) writes the best-so-far convergence curve as JSON to
    FILE, sampled every ``--history-every`` steps (chunked runs, still
    jitted).  NSGA-II records curves via the library API
    (``utils.history.best_curve`` with a custom metric)."""
    start = time.perf_counter()
    if not _write_history(opt, args):
        opt.run(args.steps)
    # Models dispatch asynchronously (PSO.run no longer blocks, r4):
    # force the result before reading the clock, or steps_per_sec
    # would measure dispatch latency, not the run.
    float(opt.best)
    elapsed = time.perf_counter() - start
    out = {
        "objective": args.objective,
        count_key: args.n if count is None else count,
        "dim": args.dim,
        "iters": args.steps,
        **{k: v() if callable(v) else v for k, v in (extra or {}).items()},
        "best": opt.best,
        "steps_per_sec": round(args.steps / elapsed, 1),
    }
    print(json.dumps(out))
    return 0


def _cmd_de(args) -> int:
    from .models.de import DE

    opt = DE(args.objective, n=args.n, dim=args.dim, f=args.f, cr=args.cr,
             variant=args.variant, seed=args.seed)
    return _run_report(opt, args, "population",
                       extra={"variant": args.variant})


def _cmd_cmaes(args) -> int:
    from .models.cmaes import CMAES

    opt = CMAES(args.objective, dim=args.dim, n=args.n, seed=args.seed)
    return _run_report(opt, args, "popsize", count=opt.params.popsize,
                       extra={"sigma": lambda: float(opt.state.sigma)})


def _cmd_boids(args) -> int:
    from .models.boids import Boids

    flock = Boids(n=args.n, dim=args.dim, seed=args.seed,
                  half_width=args.half_width,
                  neighbor_mode=args.neighbor_mode)
    p0 = flock.polarization
    start = time.perf_counter()
    flock.run(args.steps)
    # async dispatch (r4): force the result before reading the clock
    float(flock.state.pos[0, 0])
    elapsed = time.perf_counter() - start
    out = {
        "boids": args.n,
        "dim": args.dim,
        "ticks": args.steps,
        "polarization_start": round(p0, 3),
        "polarization_end": round(flock.polarization, 3),
        "neighbor_mode": args.neighbor_mode,
        "ticks_per_sec": round(args.steps / elapsed, 1),
    }
    if args.n <= 32768:
        # The NN-distance metric is an O(N^2) diagnostic — skip it at the
        # flock sizes window mode exists for (it would OOM post-run).
        out["nearest_neighbor_dist"] = round(flock.nearest_neighbor_dist, 3)
    print(json.dumps(out))
    return 0


def _cmd_aco(args) -> int:
    import numpy as np

    from .models.aco import ACO

    rng = np.random.default_rng(args.seed)
    if args.cities_file:
        coords = np.loadtxt(args.cities_file, delimiter=",")
    else:
        coords = rng.uniform(0.0, 100.0, size=(args.cities, 2))
    colony = ACO(coords=coords, n_ants=args.ants, alpha=args.alpha,
                 beta=args.beta, rho=args.rho, q0=args.q0,
                 elite=args.elite, seed=args.seed)
    start = time.perf_counter()
    if not _write_history(colony, args, metric=lambda c: c.best_length):
        colony.run(args.steps)
    # async dispatch (r4): force the result before reading the clock
    float(colony.best_length)
    elapsed = time.perf_counter() - start
    print(json.dumps({
        "cities": int(coords.shape[0]),
        "ants": args.ants,
        "iters": args.steps,
        "best_length": round(colony.best_length, 4),
        "steps_per_sec": round(args.steps / elapsed, 1),
    }))
    return 0


def _cmd_abc(args) -> int:
    from .models.abc_bees import ABC

    opt = ABC(args.objective, n=args.n, dim=args.dim, limit=args.limit,
              seed=args.seed)
    return _run_report(opt, args, "sources")


def _cmd_gwo(args) -> int:
    from .models.gwo import GWO

    opt = GWO(args.objective, n=args.n, dim=args.dim,
              t_max=args.t_max if args.t_max else args.steps,
              seed=args.seed)
    return _run_report(opt, args, "wolves")


def _cmd_firefly(args) -> int:
    from .models.firefly import Firefly

    opt = Firefly(args.objective, n=args.n, dim=args.dim,
                  gamma=args.gamma, alpha0=args.alpha0, seed=args.seed)
    return _run_report(opt, args, "fireflies")


def _cmd_cuckoo(args) -> int:
    from .models.cuckoo import Cuckoo

    opt = Cuckoo(args.objective, n=args.n, dim=args.dim, pa=args.pa,
                 seed=args.seed)
    return _run_report(opt, args, "nests")


def _cmd_woa(args) -> int:
    from .models.woa import WOA

    opt = WOA(args.objective, n=args.n, dim=args.dim,
              t_max=args.t_max if args.t_max else args.steps,
              seed=args.seed)
    return _run_report(opt, args, "whales")


def _cmd_bat(args) -> int:
    from .models.bat import Bat

    opt = Bat(args.objective, n=args.n, dim=args.dim, seed=args.seed)
    return _run_report(opt, args, "bats")


def _make_scheduled_family_cmd(module: str, cls: str, noun: str):
    """Handler factory for families whose only extra knob is the
    schedule horizon t_max (defaulting to --steps)."""

    def cmd(args) -> int:
        import importlib

        model = getattr(
            importlib.import_module(f".models.{module}", __package__), cls
        )
        opt = model(args.objective, n=args.n, dim=args.dim,
                    t_max=args.t_max if args.t_max else args.steps,
                    seed=args.seed)
        return _run_report(opt, args, noun)

    return cmd


_SCHEDULED_FAMILIES = (
    # (subcommand, module, class, report noun, help text)
    ("salp", "salp", "Salp", "salps", "salp swarm algorithm"),
    ("mfo", "mfo", "MFO", "moths", "moth-flame optimization"),
    ("hho", "hho", "HarrisHawks", "hawks", "Harris hawks optimization"),
)


def _cmd_ga(args) -> int:
    from .models.ga import GA

    opt = GA(args.objective, n=args.n, dim=args.dim, seed=args.seed)
    return _run_report(opt, args, "individuals")


def _cmd_pt(args) -> int:
    from .models.tempering import ParallelTempering

    opt = ParallelTempering(
        args.objective, n=args.n, dim=args.dim,
        swap_every=args.swap_every, seed=args.seed,
    )
    return _run_report(opt, args, "chains")


def _cmd_es(args) -> int:
    from .models.es import ES

    opt = ES(args.objective, n=args.n, dim=args.dim, seed=args.seed)
    return _run_report(opt, args, "samples")


def _cmd_shade(args) -> int:
    from .models.shade import SHADE

    opt = SHADE(args.objective, n=args.n, dim=args.dim, seed=args.seed)
    return _run_report(opt, args, "individuals")


def _cmd_mapelites(args) -> int:
    from .models.map_elites import MAPElites

    opt = MAPElites(args.objective, dim=args.dim, bins=args.bins,
                    batch=args.n, seed=args.seed)
    return _run_report(
        opt, args, "batch",
        extra={"bins": args.bins,
               "coverage": lambda: round(opt.coverage, 4)},
    )


def _cmd_nsga2(args) -> int:
    import time as _time

    import json

    from .models.nsga2 import NSGA2

    opt = NSGA2(args.problem, n=args.n, dim=args.dim, seed=args.seed)
    t0 = _time.perf_counter()
    opt.run(args.steps)
    # async dispatch (r4): force the result before reading the clock
    float(opt.state.objs[0, 0])
    dt = _time.perf_counter() - t0
    front = opt.pareto_front()
    print(json.dumps({
        "problem": args.problem,
        "pop": args.n,
        "dim": args.dim,
        "iters": args.steps,
        "front_size": int(front.shape[0]),
        "hypervolume@(1.1,1.1)": round(opt.hypervolume([1.1, 1.1]), 4),
        "steps_per_sec": round(args.steps / dt, 1),
    }))
    return 0


def _cmd_scope_summary(args) -> int:
    """``swarmscope summary RUN``: one human-readable roll-up of a
    run directory (manifest, metric counts, failures, telemetry
    highlights, compile observatory state)."""
    from .utils import rundir

    run = rundir.load_run(args.run)
    man = run.manifest
    print(f"run {run.label}  ({run.path})")
    if man:
        print(
            f"  created {man.get('created', '?')}  backend "
            f"{man.get('backend', '?')}"
        )
        if man.get("mesh"):
            print(f"  mesh {man['mesh']}")
    print(f"  metrics: {len(run.metrics)}"
          + (f"  FAILURES: {len(run.failures)}" if run.failures else ""))
    for obj in run.failures:
        print(f"    failed: {obj.get('metric')}  "
              f"({obj.get('error', '?')})")
    for tag, summ in sorted(run.telemetry.items()):
        print(
            f"  telemetry [{tag}]: ticks {summ.get('ticks')}, "
            f"rebuilds/100t {summ.get('rebuilds_per_100_ticks')}, "
            f"truncation {summ.get('truncation_events')}, "
            f"first nonfinite {summ.get('first_nonfinite_step')}, "
            f"shard imbalance {summ.get('shard_imbalance_max')}"
        )
    if run.events:
        kinds: dict = {}
        for ev in run.events:
            kinds[ev.get("event", "?")] = kinds.get(
                ev.get("event", "?"), 0
            ) + 1
        print("  events: " + ", ".join(
            f"{k} x{v}" for k, v in sorted(kinds.items())
        ))
    for entry, agg in sorted(run.compile_entries.items()):
        print(
            f"  compiles [{entry}]: {agg['compiles']} "
            f"({agg['wall_s']:.1f}s wall)"
        )
    storms = [
        e for e in run.compile_events
        if e.get("event") == "retrace-storm"
    ]
    for ev in storms:
        print(
            f"  RETRACE STORM: {ev.get('entry')} compiled "
            f"{ev.get('compiles')} signatures"
        )
    return 0


def _cmd_scope_diff(args) -> int:
    """``swarmscope diff A B``: metric-by-metric comparison with the
    union gate's semantics — exit 1 naming the regressed fixed-name
    rows when any gated metric regresses, 0 otherwise."""
    from .utils import rundir

    a = rundir.load_run(args.a)
    b = rundir.load_run(args.b)
    out = rundir.diff_runs(a, b, threshold=args.threshold)
    for row in out["rows"]:
        if row["unit"] == "pct":
            detail = (f"{row['prev']:.2f}% -> {row['cur']:.2f}% "
                      f"(ceiling {rundir.PCT_CEILING:.0f}%)")
        elif row["prev"] > 0:
            detail = (f"{row['prev']:.3g} -> {row['cur']:.3g} "
                      f"({row['cur'] / row['prev']:.2f}x)")
        else:
            detail = f"{row['prev']:.3g} -> {row['cur']:.3g}"
        print(f"{row['status']:>10}  {row['metric']}  {detail}")
    for name in out["only_a"]:
        print(f"{'dropped':>10}  {name}")
    for name in out["only_b"]:
        print(f"{'new':>10}  {name}")
    if out["regressions"]:
        print(
            f"\n{len(out['regressions'])} gated regression(s) "
            f"({a.label} -> {b.label}):",
            file=sys.stderr,
        )
        for name in out["regressions"]:
            print(f"  REGRESSION  {name}", file=sys.stderr)
        return 1
    print(f"\nno gated regressions ({a.label} -> {b.label})")
    return 0


#: Alert-event names of the serve SLO observatory (serve/slo.py) —
#: the subset of events.jsonl the ``slo`` subcommand counts.
_SLO_EVENTS = ("deadline-miss", "queue-overflow", "eviction")


def _spark(values, width: int = 48) -> str:
    """Resample ``values`` to ``width`` buckets and render a block-
    character sparkline (max per bucket — spikes must stay visible)."""
    if not values:
        return ""
    blocks = " ▁▂▃▄▅▆▇█"
    lo, hi = min(values), max(values)
    n = min(width, len(values))
    if hi == lo:
        # A constant series has no shape to normalize; a steady
        # nonzero level must still read as load, not as no data.
        return ("▄" if lo else " ") * n
    span = hi - lo
    out = []
    for b in range(n):
        chunk = values[
            b * len(values) // n: (b + 1) * len(values) // n
        ] or [values[-1]]
        frac = (max(chunk) - lo) / span
        out.append(blocks[min(len(blocks) - 1, int(frac * (len(blocks) - 1) + 0.5))])
    return "".join(out)


def _cmd_scope_slo(args) -> int:
    """``swarmscope slo RUN``: the serving-latency view of a run
    directory (r16) — the SLO summaries from ``slo.json`` (latency
    percentiles, occupancy, the queue-depth trajectory), the
    fixed-name ``ms-*`` metric rows, and the deadline-miss /
    queue-overflow / eviction alert events from ``events.jsonl``."""
    from .utils import rundir

    run = rundir.load_run(args.run)
    printed = False
    for tag, s in sorted(run.slo.items()):
        printed = True
        print(f"slo [{tag}]  (deadline {s.get('deadline_ms', '?')} ms"
              f" + grace {s.get('miss_grace_ms', '?')} ms)")
        for series, label in (("ttfr_ms", "ttfr"),
                              ("queue_ms", "queue")):
            p = s.get(series) or {}
            print(
                f"  {label:>6}: p50 {p.get('p50', 0.0):8.1f} ms   "
                f"p95 {p.get('p95', 0.0):8.1f} ms   "
                f"p99 {p.get('p99', 0.0):8.1f} ms   "
                f"(n={p.get('n', 0)})"
            )
        print(
            f"  dispatches {s.get('dispatches', 0)}  "
            f"filler {100.0 * s.get('filler_fraction', 0.0):.1f}%  "
            f"misses {s.get('deadline_misses', 0)}  "
            f"overflows {s.get('queue_overflows', 0)}  "
            f"evictions {s.get('evictions', 0)}"
        )
        # Per-rung occupancy (r18): one line per bucket rung with the
        # mesh axis it rides — "scenarios x8" / "tiles x2" / "device"
        # — so an operator can see WHICH axis a rung's filler cost
        # lives on (the aggregate above averages jumbo's structural
        # zero filler with the scenario rungs' padding).
        for label, r in sorted((s.get("rungs") or {}).items()):
            print(
                f"    rung {label:<14} [{r.get('mesh', 'device')}]"
                f"  dispatches {r.get('dispatches', 0):>4}  "
                f"filler {100.0 * r.get('filler_fraction', 0.0):.1f}%"
            )
        if "device_peak_bytes" in s:
            peak = s["device_peak_bytes"]
            if peak is None:
                print(
                    "  device memory: skipped "
                    f"({s.get('device_memory_skip', '?')})"
                )
            else:
                print(
                    f"  device memory: peak {peak / 1e6:.1f} MB "
                    "(allocator watermark)"
                )
        traj = s.get("queue_depth") or []
        if traj:
            depths = [row[1] for row in traj]
            flight = [row[2] for row in traj]
            print(f"  queue depth  [{min(depths)}..{max(depths)}]  "
                  f"{_spark(depths)}")
            print(f"  in flight    [{min(flight)}..{max(flight)}]  "
                  f"{_spark(flight)}")
    ms_rows = [
        row for row in run.metrics.values()
        if str(row.get("unit", "")).startswith("ms-")
    ]
    if ms_rows:
        printed = True
        print("gated latency rows:")
        for row in sorted(ms_rows, key=lambda r: r["metric"]):
            print(f"  {row['value']:10.1f} {row['unit']:>7}  "
                  f"{row['metric']}")
    counts = {k: 0 for k in _SLO_EVENTS}
    for ev in run.events:
        if ev.get("event") in counts:
            counts[ev["event"]] += 1
    if any(counts.values()):
        printed = True
        print("alert events: " + ", ".join(
            f"{k} x{v}" for k, v in sorted(counts.items()) if v
        ))
        for ev in run.events:
            if ev.get("event") == "deadline-miss":
                print(
                    f"  MISS rid {ev.get('rid')} queued "
                    f"{ev.get('queue_ms', 0.0):.1f} ms "
                    f"(deadline {ev.get('deadline_ms', 0.0):.0f} ms"
                    f" + grace {ev.get('grace_ms', 0.0):.0f} ms)"
                )
    if not printed:
        print(f"run {run.label}: no SLO data (no slo.json, no ms-* "
              "rows, no serve alert events) — was this run recorded "
              "by a streaming bench (bench_soak.py)?")
    return 0


#: Alert-event names of the stream-health watchdog (serve/health.py)
#: — the subset of events.jsonl the ``health`` subcommand renders.
_HEALTH_EVENTS = ("stream-stall", "stream-recovered")


def _cmd_scope_health(args) -> int:
    """``swarmscope health RUN``: the stream-health view of a run
    directory (r24 swarmpulse) — the watchdog's last per-stream
    table from ``slo.json`` (state, heartbeat age, device-stamped
    segment cursor), the stall/recovery alert totals, and the
    ``stream-stall`` / ``stream-recovered`` incident log from
    ``events.jsonl``."""
    from .utils import rundir

    run = rundir.load_run(args.run)
    printed = False
    for tag, s in sorted(run.slo.items()):
        stalls = s.get("stream_stalls", 0)
        recoveries = s.get("stream_recoveries", 0)
        health = s.get("stream_health")
        if not (stalls or recoveries or health):
            continue
        printed = True
        print(f"stream health [{tag}]  stalls {stalls}  "
              f"recoveries {recoveries}")
        if not health:
            continue
        counts = health.get("counts") or {}
        print(
            f"  expected segment wall "
            f"{health.get('expected_wall_ms', 0.0):.1f} ms   "
            + "  ".join(
                f"{st} {counts.get(st, 0)}"
                for st in ("healthy", "slow", "stalled", "wedged")
            )
        )
        for row in health.get("rows") or []:
            rids = ",".join(str(r) for r in row.get("rids", []))
            print(
                f"    {row.get('state', '?'):>8}  rids [{rids}]  "
                f"age {row.get('age_ms', 0.0):8.1f} ms  "
                f"segs launched {row.get('seg_done', 0)} / "
                f"landed {row.get('segs_landed', 0)}"
            )
    events = [
        ev for ev in run.events
        if ev.get("event") in _HEALTH_EVENTS
    ]
    if events:
        printed = True
        print(f"incident log ({len(events)} events):")
        for ev in events:
            rids = ",".join(str(r) for r in ev.get("rids", []))
            if ev.get("event") == "stream-stall":
                print(
                    f"  STALL     t {ev.get('t_ms', 0.0):10.1f} ms  "
                    f"rids [{rids}]  {ev.get('state', '?')}  "
                    f"age {ev.get('age_ms', 0.0):.1f} ms "
                    f"(expected wall "
                    f"{ev.get('expected_wall_ms', 0.0):.1f} ms, "
                    f"seg {ev.get('seg')})"
                )
            else:
                print(
                    f"  RECOVERED t {ev.get('t_ms', 0.0):10.1f} ms  "
                    f"rids [{rids}]  "
                    f"age {ev.get('age_ms', 0.0):.1f} ms"
                )
    if not printed:
        print(f"run {run.label}: no stream-health data (no watchdog "
              "snapshot in slo.json, no stream-stall/stream-recovered "
              "events) — streams stayed healthy, or the run predates "
              "the r24 watchdog")
    return 0


def _cmd_scope_history(args) -> int:
    """``swarmscope history METRIC``: the fixed-name row's trajectory
    across every recorded round of BENCH_HISTORY.json.

    ``--export-round rNN`` instead restores that round's standalone
    ``BENCH_rNN.json`` snapshot from the history (the run_all
    ``--record`` snapshot format) — the backfill path for rounds
    whose on-disk snapshot went missing."""
    from pathlib import Path

    from .utils import rundir

    path = args.file
    if path is None:
        path = str(
            Path(__file__).resolve().parent.parent / "BENCH_HISTORY.json"
        )
    if args.export_round:
        label = args.export_round
        with open(path) as fh:
            rounds = json.load(fh).get("rounds", {})
        if label not in rounds:
            print(
                f"round {label!r} is not recorded in {path} "
                f"(have: {sorted(rounds)}) — a round never merged "
                "into the history cannot be restored from it",
                file=sys.stderr,
            )
            return 1
        out = Path(path).parent / f"BENCH_{label}.json"
        with open(out, "w") as fh:
            json.dump(
                {"round": label, "metrics": rounds[label]},
                fh, indent=1, sort_keys=True,
            )
            fh.write("\n")
        print(f"restored {out} ({len(rounds[label])} metrics)")
        return 0
    if not args.metric:
        print("error: METRIC required (or --export-round rNN)",
              file=sys.stderr)
        return 2
    rows = rundir.history_rows(args.metric, path)
    if not rows:
        print(f"no rounds record a metric matching {args.metric!r}",
              file=sys.stderr)
        return 1
    prev = None
    for label, value, unit in rows:
        delta = ""
        if prev not in (None, 0.0):
            delta = f"  ({(value - prev) / prev:+.1%})"
        print(f"{label:>6}  {value:>14.4g} {unit}{delta}")
        prev = value
    return 0


def _cmd_scope_trace(args) -> int:
    """``swarmscope trace RUN``: the per-request critical-path view
    of a run's swarmtrace spans (r17, utils/trace.py) — where each
    request's time went (queue / coalesce / launch / compute /
    collect fractions), the slowest-span ranking, and ``--export``
    merging the host spans (plus an optional profiler capture dir)
    into one Perfetto-loadable Chrome trace."""
    import glob
    import gzip
    import os

    from .utils import trace as tracelib

    if os.path.isfile(args.run):
        files = [args.run]
    else:
        files = sorted(
            glob.glob(os.path.join(args.run, "trace", "*.json"))
        )
    if not files:
        print(
            f"no swarmtrace files under {args.run!r} (expected "
            "<run>/trace/*.json — record with DSA_TRACE=1 and "
            "DSA_RUN_DIR set, or pass a trace JSON directly)",
            file=sys.stderr,
        )
        return 1
    spans = []
    per_file = []
    sources = []
    for path in files:
        # One parse per file: the table, the ranking, and the
        # --export merge all read the same loaded dict.
        with open(path) as fh:
            data = json.load(fh)
        file_spans = tracelib.chrome_trace_spans(data)
        spans.extend(file_spans)
        per_file.append((os.path.basename(path), file_spans))
        if args.export:
            sources.append((os.path.basename(path), data))
    n_files = len(files)
    print(f"swarmtrace: {len(spans)} spans from {n_files} file(s)")
    # Rids number from 0 IN EACH PROCESS, so the critical-path table
    # is per source file — merging rid 0 of two processes would sum
    # unrelated requests into one bogus row.
    any_table = False
    for fname, file_spans in per_file:
        table = tracelib.request_table(file_spans)
        if not table:
            continue
        any_table = True
        if n_files > 1:
            print(f"-- {fname}")
        buckets = [b for b, _ in tracelib.CRITICAL_BUCKETS]
        header = "  ".join(f"{b:>9}" for b in buckets)
        print(f"{'rid':>5}  {'total_ms':>9}  {header}  kinds")
        for rid in sorted(table):
            row = table[rid]
            total = row["total_ms"]
            cells = "  ".join(
                f"{(100.0 * row[b] / total if total else 0.0):8.1f}%"
                for b in buckets
            )
            print(
                f"{rid:>5}  {total:9.3f}  {cells}  "
                f"{len(row['kinds'])}"
            )
    if not any_table:
        print("  (no rid/rids-attributed spans — nothing to bucket)")
    top = tracelib.slowest_spans(spans, args.top)
    if top:
        print("slowest spans:")
        for s in top:
            rids = tracelib.span_rids(s)
            who = f" rids={rids}" if rids else ""
            print(f"  {1e3 * s.dur_s():10.3f} ms  {s.name}{who}")
    if args.profile and not args.export:
        print(
            "# --profile only affects the --export merge; pass "
            "--export PATH to merge the capture",
            file=sys.stderr,
        )
    if args.export:
        # Profiler captures export Chrome traces as *.trace.json(.gz)
        # (TensorBoard's plugins/profile layout); merge whatever the
        # capture dir holds alongside the host spans.
        if args.profile:
            found = sorted(
                glob.glob(
                    os.path.join(args.profile, "**", "*.trace.json*"),
                    recursive=True,
                )
            )
            if not found:
                print(
                    f"# no *.trace.json(.gz) under {args.profile!r} "
                    "— exporting host spans only",
                    file=sys.stderr,
                )
            for path in found:
                opener = gzip.open if path.endswith(".gz") else open
                try:
                    with opener(path, "rt") as fh:
                        sources.append(
                            (os.path.basename(path), json.load(fh))
                        )
                except (OSError, json.JSONDecodeError) as e:
                    print(f"# skipping {path}: {e}", file=sys.stderr)
        merged = tracelib.merge_chrome_traces(sources)
        os.makedirs(
            os.path.dirname(os.path.abspath(args.export)),
            exist_ok=True,
        )
        with open(args.export, "w") as fh:
            json.dump(merged, fh)
            fh.write("\n")
        print(
            f"exported {len(merged['traceEvents'])} events from "
            f"{len(sources)} source(s) -> {args.export}"
        )
    return 0


def _metric_total(metric) -> float:
    """Sum of one snapshot metric's samples (counters/gauges)."""
    if metric is None:
        return 0.0
    return float(sum(s["value"] for s in metric.get("samples", ())))


def _cmd_scope_live(args) -> int:
    """``swarmscope live RUN``: the live operational view of a
    serving process (r19) — renders the ``metrics_live/`` snapshot
    deposits a running ``StreamingService`` appends each pump
    interval: alert counters, admissions/releases, rung occupancy,
    queue-depth/in-flight sparklines over the deposit trajectory, and
    TTFR percentile sparklines from the binned latency histograms.
    ``--follow`` re-reads and re-renders until interrupted (the
    `tail -f` of the metrics plane); one-shot by default."""
    import glob
    import os
    import time as _time

    from .utils import metrics as metricslib

    def _files():
        if os.path.isfile(args.run):
            return [args.run]
        return sorted(
            glob.glob(
                os.path.join(
                    args.run, metricslib.METRICS_LIVE_DIR, "*.jsonl"
                )
            )
        )

    def _render() -> bool:
        files = _files()
        printed = False
        for path in files:
            snapshots = metricslib.read_snapshots(path)
            if not snapshots:
                continue
            printed = True
            latest = {
                m["name"]: m
                for m in snapshots[-1].get("metrics", ())
            }
            span_s = (
                snapshots[-1].get("t_ms", 0.0)
                - snapshots[0].get("t_ms", 0.0)
            ) / 1e3
            print(
                f"live [{os.path.basename(path)}]  "
                f"{len(snapshots)} snapshot(s) over {span_s:.1f}s"
            )
            admit = _metric_total(latest.get("serve_admissions_total"))
            rel = latest.get("serve_releases_total")
            reasons = ", ".join(
                f"{s['labels'].get('reason', '?')} "
                f"{s['value']:.0f}"
                for s in (rel or {}).get("samples", ())
            )
            print(
                f"  admitted {admit:.0f}  released by "
                f"{{{reasons or 'none'}}}"
            )
            alerts = {
                "deadline-miss": "serve_deadline_miss_total",
                "queue-overflow": "serve_queue_overflow_total",
                "eviction": "serve_evictions_total",
            }
            counts = {
                label: _metric_total(latest.get(name))
                for label, name in alerts.items()
            }
            print("  alerts: " + ", ".join(
                f"{k} x{v:.0f}" for k, v in sorted(counts.items())
            ))
            # Per-rung occupancy from the row counters: the live twin
            # of the slo summary's rung table.
            rows = latest.get("serve_dispatch_rows_total")
            real = latest.get("serve_dispatch_real_rows_total")
            launches = latest.get("serve_dispatch_launches_total")
            if rows is not None:
                real_by = {
                    s["labels"].get("rung", "-"): s["value"]
                    for s in (real or {}).get("samples", ())
                }
                n_by = {
                    s["labels"].get("rung", "-"): s["value"]
                    for s in (launches or {}).get("samples", ())
                }
                for s in rows.get("samples", ()):
                    rung = s["labels"].get("rung", "-")
                    total = s["value"]
                    filler = (
                        100.0 * (total - real_by.get(rung, 0.0)) / total
                        if total else 0.0
                    )
                    print(
                        f"    rung {rung:<14} dispatches "
                        f"{n_by.get(rung, 0.0):>5.0f}  filler "
                        f"{filler:.1f}%"
                    )
            # Trajectories over the deposit sequence: gauges read
            # directly, percentiles re-derived per snapshot from the
            # cumulative histogram (a running-percentile view).
            for name, label in (
                ("serve_queue_depth", "queue depth"),
                ("serve_in_flight", "in flight"),
            ):
                series = [
                    _metric_total(m) for m in
                    metricslib.snapshot_series(snapshots, name)
                ]
                if series:
                    print(
                        f"  {label:<12} [{min(series):.0f}.."
                        f"{max(series):.0f}]  {_spark(series)}"
                    )
            hist_series = metricslib.snapshot_series(
                snapshots, "slo_ttfr_ms"
            )
            if hist_series:
                # inf = the percentile blew past the histogram's last
                # declared edge: render pinned AT that edge with a
                # loud marker — never filtered (a dashboard must not
                # read green during the worst regime; the metrics
                # module's own "outside the envelope must gate, not
                # flatter" contract).
                top = max(
                    (m.get("buckets") or [0.0])[-1]
                    for m in hist_series
                )
                for q, qlabel in ((50.0, "ttfr p50"), (99.0, "ttfr p99")):
                    vals = [
                        metricslib.histogram_percentile(m, q)
                        for m in hist_series
                    ]
                    blown = vals[-1] == float("inf")
                    vals = [
                        top if v == float("inf") else v for v in vals
                    ]
                    now = (
                        f">{top:.0f} ms PAST-ENVELOPE" if blown
                        else f"{vals[-1]:8.1f} ms"
                    )
                    print(
                        f"  {qlabel:<12} now {now}  {_spark(vals)}"
                    )
        return printed

    if not args.follow:
        if not _render():
            print(
                f"no live metrics under {args.run!r} (expected "
                f"<run>/{metricslib.METRICS_LIVE_DIR}/*.jsonl — a "
                "StreamingService deposits them each pump interval "
                "when DSA_RUN_DIR is set and its metrics registry is "
                "enabled, e.g. DSA_METRICS=1)",
                file=sys.stderr,
            )
            return 1
        return 0
    try:
        while True:
            if not _render():
                print(
                    f"# waiting for {metricslib.METRICS_LIVE_DIR}/ "
                    f"deposits under {args.run!r} ..."
                )
            print(f"--- ({args.interval:.0f}s; ctrl-c to stop)")
            _time.sleep(args.interval)
    except KeyboardInterrupt:
        return 0


def _cmd_jaxlint(args) -> int:
    """``jaxlint``: the trace/HLO-level program auditor (r15) —
    lower every ``compile_watch.watched()`` registry entry (no
    backend execution) and check its collective/donation/dtype census
    against the declared budgets in ``jaxlint-budgets.json``.  See
    docs/STATIC_ANALYSIS.md."""
    import os

    # The mesh entries (spatial tick, shmap/dimshard drivers) need
    # the 8-virtual-device rig, and the audit must never dial a real
    # chip just to *lower*.  jax is already imported (the package
    # import pulls it in) but its BACKEND is not initialized until
    # the first devices() call, and XLA_FLAGS is read at client
    # creation — so pinning env + live config here still lands, the
    # conftest pattern.  If a backend is somehow already live with
    # fewer devices, the mesh entries skip and say so.
    os.environ["JAX_PLATFORMS"] = "cpu"
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8"
        ).strip()
    try:
        import jax

        jax.config.update("jax_platforms", "cpu")
    except Exception:
        pass
    from .analysis import jaxlint

    return jaxlint.main_cli(args)


def _cmd_bench(args) -> int:
    # bench.py lives at the repo root (a driver contract), outside the
    # package — resolve it relative to this file so the subcommand works
    # from any CWD.
    import runpy
    from pathlib import Path

    bench_path = Path(__file__).resolve().parent.parent / "bench.py"
    if not bench_path.exists():
        print(f"error: bench script not found at {bench_path}",
              file=sys.stderr)
        return 2
    runpy.run_path(str(bench_path), run_name="__main__")
    return 0


def build_parser() -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser(prog="distributed_swarm_algorithm_tpu")
    sub = parser.add_subparsers(dest="cmd")

    p_agent = sub.add_parser("agent", help="run one per-agent process")
    _add_agent_args(p_agent)
    p_agent.set_defaults(fn=_cmd_agent)

    p_sim = sub.add_parser("sim", help="N agents on an in-process bus")
    p_sim.add_argument("--n", type=int, default=5)
    p_sim.add_argument("--steps", type=int, default=100)
    p_sim.add_argument("--seed", type=int, default=0)
    p_sim.set_defaults(fn=_cmd_sim)

    p_swarm = sub.add_parser("swarm", help="vectorized TPU swarm")
    p_swarm.add_argument("--n", type=int, default=1024)
    p_swarm.add_argument("--dim", type=int, default=2)
    p_swarm.add_argument("--steps", type=int, default=1000)
    p_swarm.add_argument("--seed", type=int, default=0)
    p_swarm.add_argument("--spread", type=float, default=10.0)
    p_swarm.add_argument("--target", nargs="+", default=None)
    p_swarm.add_argument(
        "--backend", default="jax",
        choices=["jax", "auto", "native", "numpy"],
        help="jax = vectorized XLA path; native = C++ CPU kernels; "
             "numpy = pure-NumPy oracle; auto = native if available",
    )
    p_swarm.add_argument(
        "--trace", default=None, metavar="DIR",
        help="capture a jax.profiler device trace into DIR "
             "(open with TensorBoard/XProf)")
    p_swarm.add_argument(
        "--separation", default="dense",
        choices=["dense", "pallas", "grid", "window", "hashgrid", "off"],
        help="neighbor-separation kernel (jax backend): dense all-pairs, "
             "tiled Pallas (exact, large N on TPU), spatial-hash grid "
             "(CPU), Morton-window (approximate, very large N on TPU), "
             "hashgrid (torus-world hash — exact up to the cell cap, "
             "fused Pallas kernel on TPU; needs --world-hw), or off",
    )
    p_swarm.add_argument(
        "--world-hw", type=float, default=0.0, metavar="HW",
        help="torus half-width for --separation hashgrid: the world "
             "becomes [-HW, HW)^2 (default: 4x --spread)",
    )
    p_swarm.add_argument(
        "--save-state", default=None, metavar="PATH",
        help="checkpoint the final swarm state (orbax dir or .npz)")
    p_swarm.add_argument(
        "--load-state", default=None, metavar="PATH",
        help="resume from a state saved with --save-state")
    p_swarm.add_argument(
        "--render", default=None, metavar="FILE.svg",
        help="record the rollout and write an animated SVG "
             "(jax backend, 2-D)")
    p_swarm.set_defaults(fn=_cmd_swarm)

    p_pso = sub.add_parser("pso", help="particle swarm optimization")
    p_pso.add_argument("--objective", default="rastrigin")
    p_pso.add_argument("--n", type=int, default=8192,
                       help="total particles (split across --islands)")
    p_pso.add_argument("--dim", type=int, default=30)
    p_pso.add_argument("--steps", type=int, default=500)
    p_pso.add_argument("--seed", type=int, default=0)
    p_pso.add_argument("--islands", type=int, default=1,
                       help="island-model: number of independent swarms "
                            "with periodic ring migration")
    p_pso.add_argument("--migrate-every", type=int, default=25)
    p_pso.add_argument("--migrate-k", type=int, default=4)
    p_pso.add_argument("--topology", default="gbest",
                       choices=["gbest", "ring", "vonneumann"],
                       help="social topology (lbest ring / torus grid)")
    p_pso.add_argument("--ring-radius", type=int, default=1)
    p_pso.add_argument("--refine-every", type=int, default=0,
                       help="memetic mode: jax.grad refinement every K "
                            "iterations (0 = off)")
    p_pso.add_argument("--refine-steps", type=int, default=5)
    p_pso.add_argument("--lr", type=float, default=0.01,
                       help="memetic gradient-descent learning rate")
    p_pso.set_defaults(fn=_cmd_pso)

    p_de = sub.add_parser("de", help="differential evolution")
    p_de.add_argument("--objective", default="rastrigin")
    p_de.add_argument("--n", type=int, default=256)
    p_de.add_argument("--dim", type=int, default=30)
    p_de.add_argument("--steps", type=int, default=500)
    p_de.add_argument("--seed", type=int, default=0)
    p_de.add_argument("--f", type=float, default=0.5,
                      help="differential weight F")
    p_de.add_argument("--cr", type=float, default=0.9,
                      help="crossover rate CR")
    p_de.add_argument("--variant", default="rand1bin",
                      choices=["rand1bin", "best1bin"])
    p_de.set_defaults(fn=_cmd_de)

    p_cma = sub.add_parser("cmaes", help="CMA-ES evolution strategy")
    p_cma.add_argument("--objective", default="rosenbrock")
    p_cma.add_argument("--n", type=int, default=None,
                       help="popsize lambda (default 4 + 3 ln D)")
    p_cma.add_argument("--dim", type=int, default=30)
    p_cma.add_argument("--steps", type=int, default=500)
    p_cma.add_argument("--seed", type=int, default=0)
    p_cma.set_defaults(fn=_cmd_cmaes)

    p_boids = sub.add_parser("boids", help="Reynolds flocking simulation")
    p_boids.add_argument("--n", type=int, default=512)
    p_boids.add_argument("--dim", type=int, default=2)
    p_boids.add_argument("--steps", type=int, default=500)
    p_boids.add_argument("--seed", type=int, default=0)
    p_boids.add_argument("--half-width", type=float, default=50.0)
    p_boids.add_argument("--neighbor-mode", default="dense",
                         choices=["dense", "window", "gridmean"],
                         help="dense = exact all-pairs; window = "
                              "Morton sliding window (million-boid "
                              "scale, 2-D only); gridmean = "
                              "particle-in-cell align/cohesion + "
                              "exact hash separation (dense-grade "
                              "flocking quality, 2-D only)")
    p_boids.set_defaults(fn=_cmd_boids)

    p_aco = sub.add_parser("aco", help="ant-colony TSP solver")
    p_aco.add_argument("--cities", type=int, default=32,
                       help="random-uniform instance size")
    p_aco.add_argument("--cities-file", default=None,
                       help="CSV of x,y coordinates (overrides --cities)")
    p_aco.add_argument("--ants", type=int, default=64)
    p_aco.add_argument("--steps", type=int, default=200)
    p_aco.add_argument("--alpha", type=float, default=1.0)
    p_aco.add_argument("--beta", type=float, default=2.0)
    p_aco.add_argument("--rho", type=float, default=0.1)
    p_aco.add_argument("--q0", type=float, default=0.0,
                       help="ACS exploitation probability")
    p_aco.add_argument("--elite", type=float, default=0.0,
                       help="elitist deposit weight on best-so-far tour")
    p_aco.add_argument("--seed", type=int, default=0)
    p_aco.set_defaults(fn=_cmd_aco)

    p_abc = sub.add_parser("abc", help="artificial bee colony")
    p_abc.add_argument("--objective", default="rastrigin")
    p_abc.add_argument("--n", type=int, default=128,
                       help="food sources (= employed bees = onlookers)")
    p_abc.add_argument("--dim", type=int, default=30)
    p_abc.add_argument("--steps", type=int, default=500)
    p_abc.add_argument("--limit", type=int, default=None,
                       help="scout abandonment limit (default n*dim)")
    p_abc.add_argument("--seed", type=int, default=0)
    p_abc.set_defaults(fn=_cmd_abc)

    p_gwo = sub.add_parser("gwo", help="grey wolf optimizer")
    p_gwo.add_argument("--objective", default="rastrigin")
    p_gwo.add_argument("--n", type=int, default=128)
    p_gwo.add_argument("--dim", type=int, default=30)
    p_gwo.add_argument("--steps", type=int, default=500)
    p_gwo.add_argument("--t-max", type=int, default=0,
                       help="exploration schedule length (default --steps)")
    p_gwo.add_argument("--seed", type=int, default=0)
    p_gwo.set_defaults(fn=_cmd_gwo)

    p_ff = sub.add_parser("firefly", help="firefly algorithm")
    p_ff.add_argument("--objective", default="rastrigin")
    p_ff.add_argument("--n", type=int, default=128)
    p_ff.add_argument("--dim", type=int, default=30)
    p_ff.add_argument("--steps", type=int, default=500)
    p_ff.add_argument("--gamma", type=float, default=1.0,
                      help="light absorption coefficient")
    p_ff.add_argument("--alpha0", type=float, default=0.25,
                      help="initial random-walk scale")
    p_ff.add_argument("--seed", type=int, default=0)
    p_ff.set_defaults(fn=_cmd_firefly)

    p_cs = sub.add_parser("cuckoo", help="cuckoo search")
    p_cs.add_argument("--objective", default="rastrigin")
    p_cs.add_argument("--n", type=int, default=128, help="nests")
    p_cs.add_argument("--dim", type=int, default=30)
    p_cs.add_argument("--steps", type=int, default=500)
    p_cs.add_argument("--pa", type=float, default=0.25,
                      help="nest abandonment probability")
    p_cs.add_argument("--seed", type=int, default=0)
    p_cs.set_defaults(fn=_cmd_cuckoo)

    p_woa = sub.add_parser("woa", help="whale optimization")
    p_woa.add_argument("--objective", default="rastrigin")
    p_woa.add_argument("--n", type=int, default=128)
    p_woa.add_argument("--dim", type=int, default=30)
    p_woa.add_argument("--steps", type=int, default=500)
    p_woa.add_argument("--t-max", type=int, default=0,
                       help="exploration schedule length (default --steps)")
    p_woa.add_argument("--seed", type=int, default=0)
    p_woa.set_defaults(fn=_cmd_woa)

    p_bat = sub.add_parser("bat", help="bat algorithm")
    p_bat.add_argument("--objective", default="rastrigin")
    p_bat.add_argument("--n", type=int, default=128)
    p_bat.add_argument("--dim", type=int, default=30)
    p_bat.add_argument("--steps", type=int, default=500)
    p_bat.add_argument("--seed", type=int, default=0)
    p_bat.set_defaults(fn=_cmd_bat)

    for name, module, cls, noun, helptext in _SCHEDULED_FAMILIES:
        p_fam = sub.add_parser(name, help=helptext)
        p_fam.add_argument("--objective", default="rastrigin")
        p_fam.add_argument("--n", type=int, default=128)
        p_fam.add_argument("--dim", type=int, default=30)
        p_fam.add_argument("--steps", type=int, default=500)
        p_fam.add_argument("--t-max", type=int, default=0,
                           help="schedule horizon (default --steps)")
        p_fam.add_argument("--seed", type=int, default=0)
        p_fam.set_defaults(fn=_make_scheduled_family_cmd(module, cls, noun))

    p_ga = sub.add_parser("ga", help="real-coded genetic algorithm")
    p_ga.add_argument("--objective", default="rastrigin")
    p_ga.add_argument("--n", type=int, default=128)
    p_ga.add_argument("--dim", type=int, default=30)
    p_ga.add_argument("--steps", type=int, default=500)
    p_ga.add_argument("--seed", type=int, default=0)
    p_ga.set_defaults(fn=_cmd_ga)

    p_pt = sub.add_parser("pt", help="parallel tempering")
    p_pt.add_argument("--objective", default="rastrigin")
    p_pt.add_argument("--n", type=int, default=32)
    p_pt.add_argument("--dim", type=int, default=30)
    p_pt.add_argument("--steps", type=int, default=2000)
    p_pt.add_argument("--swap-every", type=int, default=5)
    p_pt.add_argument("--seed", type=int, default=0)
    p_pt.set_defaults(fn=_cmd_pt)

    p_es = sub.add_parser("es", help="OpenAI-style evolution strategy")
    p_es.add_argument("--objective", default="rastrigin")
    p_es.add_argument("--n", type=int, default=256)
    p_es.add_argument("--dim", type=int, default=30)
    p_es.add_argument("--steps", type=int, default=500)
    p_es.add_argument("--seed", type=int, default=0)
    p_es.set_defaults(fn=_cmd_es)

    p_shade = sub.add_parser("shade", help="success-history adaptive DE")
    p_shade.add_argument("--objective", default="rastrigin")
    p_shade.add_argument("--n", type=int, default=256)
    p_shade.add_argument("--dim", type=int, default=30)
    p_shade.add_argument("--steps", type=int, default=500)
    p_shade.add_argument("--seed", type=int, default=0)
    p_shade.set_defaults(fn=_cmd_shade)

    p_me = sub.add_parser("mapelites", help="MAP-Elites quality-diversity")
    p_me.add_argument("--objective", default="rastrigin")
    p_me.add_argument("--n", type=int, default=256,
                      help="mutation batch per generation")
    p_me.add_argument("--dim", type=int, default=6)
    p_me.add_argument("--bins", type=int, default=16)
    p_me.add_argument("--steps", type=int, default=300)
    p_me.add_argument("--seed", type=int, default=0)
    p_me.set_defaults(fn=_cmd_mapelites)

    p_nsga2 = sub.add_parser("nsga2", help="NSGA-II multi-objective")
    p_nsga2.add_argument("--problem", default="zdt1",
                         choices=["zdt1", "zdt2", "zdt3"])
    p_nsga2.add_argument("--n", type=int, default=128)
    p_nsga2.add_argument("--dim", type=int, default=12)
    p_nsga2.add_argument("--steps", type=int, default=200)
    p_nsga2.add_argument("--seed", type=int, default=0)
    p_nsga2.set_defaults(fn=_cmd_nsga2)

    p_bench = sub.add_parser("bench", help="headline benchmark")
    p_bench.set_defaults(fn=_cmd_bench)

    p_jl = sub.add_parser(
        "jaxlint",
        help="trace/HLO-level program auditor: lower every watched "
             "registry entry (no backend execution) and gate its "
             "collective/donation/dtype census against "
             "jaxlint-budgets.json (r15; see docs/STATIC_ANALYSIS.md)",
    )
    p_jl.add_argument(
        "entries", nargs="*",
        help="registry entries to audit (default: all; stale-budget "
             "detection only runs on the full audit)",
    )
    p_jl.add_argument("--json", action="store_true", dest="as_json",
                      help="machine-readable summary on stdout")
    p_jl.add_argument("--census", action="store_true",
                      help="print the per-entry census table")
    p_jl.add_argument(
        "--budgets", default=None,
        help="budgets file (default <repo>/jaxlint-budgets.json)",
    )
    p_jl.add_argument(
        "--write-budgets", action="store_true",
        help="pin the measured censuses as declared budgets (keeps "
             "existing justifications; new entries get TODOs to edit)",
    )
    p_jl.add_argument("--list-entries", action="store_true",
                      help="list registered lint entries")
    p_jl.add_argument(
        "--no-memory", action="store_true", dest="no_memory",
        help="skip the bytes census (r17: compiled.memory_analysis "
             "per entry — needs a backend compile; lowering-only "
             "audit with this flag)",
    )
    p_jl.set_defaults(fn=_cmd_jaxlint)

    p_scope = sub.add_parser(
        "swarmscope",
        help="inspect benchmark run directories (r11; see "
             "docs/OBSERVABILITY.md)",
    )
    scope_sub = p_scope.add_subparsers(dest="scope_cmd")
    p_ss = scope_sub.add_parser(
        "summary", help="summarize one run directory"
    )
    p_ss.add_argument("run", help="run directory (runs/<label>)")
    p_ss.set_defaults(fn=_cmd_scope_summary)
    p_sd = scope_sub.add_parser(
        "diff",
        help="diff two run directories metric-by-metric; exit 1 "
             "naming the regressed rows when a gated metric regresses",
    )
    p_sd.add_argument("a", help="baseline run directory")
    p_sd.add_argument("b", help="candidate run directory")
    p_sd.add_argument("--threshold", type=float, default=0.2)
    p_sd.set_defaults(fn=_cmd_scope_diff)
    p_slo = scope_sub.add_parser(
        "slo",
        help="render a run's serving-latency view (r16): SLO "
             "percentile summaries + queue-depth trajectory from "
             "slo.json, gated ms-* rows, and deadline-miss/"
             "queue-overflow/eviction alert events",
    )
    p_slo.add_argument("run", help="run directory (runs/<label>)")
    p_slo.set_defaults(fn=_cmd_scope_slo)
    p_hl = scope_sub.add_parser(
        "health",
        help="render a run's stream-health view (r24): the "
             "watchdog's per-stream table (state, heartbeat age, "
             "device-stamped segment cursor) from slo.json plus the "
             "stream-stall/stream-recovered incident log",
    )
    p_hl.add_argument("run", help="run directory (runs/<label>)")
    p_hl.set_defaults(fn=_cmd_scope_health)
    p_sh = scope_sub.add_parser(
        "history",
        help="print a fixed-name row's BENCH_HISTORY trajectory, or "
             "restore a round's BENCH_rNN.json snapshot "
             "(--export-round)",
    )
    p_sh.add_argument("metric", nargs="?", default=None,
                      help="metric name (exact or substring)")
    p_sh.add_argument("--file", default=None,
                      help="history JSON (default: repo BENCH_HISTORY)")
    p_sh.add_argument(
        "--export-round", metavar="rNN", default=None,
        help="write BENCH_rNN.json next to the history file from "
             "round rNN's recorded metrics (the run_all --record "
             "snapshot format) instead of printing a trajectory",
    )
    p_sh.set_defaults(fn=_cmd_scope_history)
    p_st = scope_sub.add_parser(
        "trace",
        help="render a run's swarmtrace spans (r17): per-request "
             "critical-path table (queue/coalesce/launch/compute/"
             "collect fractions), slowest-span ranking, and --export "
             "to one merged Perfetto-loadable Chrome trace",
    )
    p_st.add_argument(
        "run",
        help="run directory (reads <run>/trace/*.json) or one "
             "Chrome-trace JSON file",
    )
    p_st.add_argument("--top", type=int, default=10,
                      help="slowest-span ranking depth")
    p_st.add_argument(
        "--export", metavar="OUT.json", default=None,
        help="write one merged Chrome trace (host spans + --profile "
             "capture) loadable in Perfetto / chrome://tracing",
    )
    p_st.add_argument(
        "--profile", metavar="DIR", default=None,
        help="profiler capture dir (utils/profiling.trace output) "
             "whose *.trace.json(.gz) exports merge into --export",
    )
    p_st.set_defaults(fn=_cmd_scope_trace)
    p_lv = scope_sub.add_parser(
        "live",
        help="render (or --follow) a running service's live metrics "
             "deposits (r19): alert counters, rung occupancy, "
             "queue-depth and TTFR-percentile sparklines from "
             "<run>/metrics_live/*.jsonl",
    )
    p_lv.add_argument(
        "run",
        help="run directory (reads <run>/metrics_live/*.jsonl) or "
             "one deposit file",
    )
    p_lv.add_argument(
        "--follow", action="store_true",
        help="re-render every --interval seconds until interrupted",
    )
    p_lv.add_argument("--interval", type=float, default=2.0,
                      help="--follow refresh period (seconds)")
    p_lv.set_defaults(fn=_cmd_scope_live)

    # Convergence-history flags for every single-objective optimizer
    # subcommand (utils/history.py; see _run_report).
    for name in (
        "pso", "de", "cmaes", "abc", "gwo", "firefly", "cuckoo", "woa",
        "bat", "salp", "mfo", "hho", "ga", "pt", "aco", "es",
        "mapelites", "shade",
    ):
        sp = sub.choices[name]
        sp.add_argument("--history", metavar="FILE", default=None,
                        help="write best-so-far curve as JSON to FILE")
        sp.add_argument("--history-every", type=int, default=16,
                        help="curve sampling stride in steps")

    return parser


def main(argv=None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    # Bare reference flags (`--id 1 --count 3`) imply the agent command.
    if argv and argv[0].startswith("--"):
        argv = ["agent"] + argv
    parser = build_parser()
    args = parser.parse_args(argv)
    if not getattr(args, "fn", None):
        parser.print_help()
        return 2
    try:
        return args.fn(args)
    except (KeyError, ValueError, RuntimeError, FileNotFoundError) as e:
        print(f"error: {e}", file=sys.stderr)
        return 2


if __name__ == "__main__":
    raise SystemExit(main())
