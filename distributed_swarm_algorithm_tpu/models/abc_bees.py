"""User-facing artificial-bee-colony optimizer model."""

from __future__ import annotations

from typing import Callable, Optional, Union

import jax

from ..ops import abc as _k
from ..ops.objectives import get_objective
from ._checkpoint import CheckpointMixin


class ABC(CheckpointMixin):
    """Artificial bee colony (employed / onlooker / scout phases).

    >>> opt = ABC("rastrigin", n=256, dim=10, seed=0)
    >>> opt.run(300)
    >>> opt.best  # doctest: +SKIP
    """

    def __init__(
        self,
        objective: Union[str, Callable],
        n: int,
        dim: int,
        half_width: Optional[float] = None,
        limit: Optional[int] = None,
        seed: int = 0,
        dtype=None,
    ):
        if isinstance(objective, str):
            fn, default_hw = get_objective(objective)
        else:
            fn, default_hw = objective, 5.12
        self.objective = fn
        self.half_width = float(
            half_width if half_width is not None else default_hw
        )
        # Karaboga's rule of thumb: limit = sources * dim
        self.limit = int(limit if limit is not None else n * dim)
        kwargs = {} if dtype is None else {"dtype": dtype}
        self.state = _k.abc_init(
            fn, n, dim, self.half_width, seed=seed, **kwargs
        )

    def step(self) -> _k.ABCState:
        self.state = _k.abc_step(
            self.state, self.objective, self.half_width, self.limit
        )
        return self.state

    def run(self, n_steps: int) -> _k.ABCState:
        self.state = _k.abc_run(
            self.state, self.objective, n_steps, self.half_width,
            self.limit,
        )
        jax.block_until_ready(self.state.best_fit)
        return self.state

    @property
    def best(self) -> float:
        return float(self.state.best_fit)
