"""User-facing artificial-bee-colony optimizer model."""

from __future__ import annotations

from typing import Callable, Optional, Union

import jax

from ..ops import abc as _k
from ..ops.objectives import get_objective
from ..ops.pallas import abc_fused as _af
from ..utils.platform import on_tpu as _on_tpu
from ._checkpoint import CheckpointMixin


class ABC(CheckpointMixin):
    """Artificial bee colony (employed / onlooker / scout phases).

    Two compute paths with the same ABCState contract: portable jit'd
    JAX (exact multinomial onlooker recruitment — its categorical
    sample + segment-min scatter + gather-back is the worst TPU
    profile in the zoo: 0.2M source-steps/s at 262k, device fault at
    1M) and the fused Pallas kernel (ops/pallas/abc_fused.py:
    Bernoulli recruitment + rotational partners, scatter/gather-free)
    — auto-selected on TPU for named objectives in float32 with
    n >= 512, or forced with ``use_pallas=True``.

    >>> opt = ABC("rastrigin", n=256, dim=10, seed=0)
    >>> opt.run(300)
    >>> opt.best  # doctest: +SKIP
    """

    def __init__(
        self,
        objective: Union[str, Callable],
        n: int,
        dim: int,
        half_width: Optional[float] = None,
        limit: Optional[int] = None,
        seed: int = 0,
        dtype=None,
        use_pallas: Optional[bool] = None,
    ):
        if isinstance(objective, str):
            fn, default_hw = get_objective(objective)
            self.objective_name: Optional[str] = objective
        else:
            fn, default_hw = objective, 5.12
            self.objective_name = None
        self.objective = fn
        self.half_width = float(
            half_width if half_width is not None else default_hw
        )
        # Karaboga's rule of thumb: limit = sources * dim
        self.limit = int(limit if limit is not None else n * dim)
        kwargs = {} if dtype is None else {"dtype": dtype}
        self.state = _k.abc_init(
            fn, n, dim, self.half_width, seed=seed, **kwargs
        )

        supported = (
            n >= 512            # rotational partners need >= 4 lane tiles
            and self.objective_name is not None
            and _af.abc_pallas_supported(
                self.objective_name or "", self.state.pos.dtype,
                self.state.pos.shape[-1],
            )
        )
        if use_pallas is None:
            self.use_pallas = supported and _on_tpu()
        elif use_pallas and not supported:
            raise ValueError(
                "use_pallas=True needs a named objective from "
                "ops.objectives, float32 state, and n >= 512"
            )
        else:
            self.use_pallas = bool(use_pallas)

    def step(self) -> _k.ABCState:
        self.state = _k.abc_step(
            self.state, self.objective, self.half_width, self.limit
        )
        return self.state

    def run(self, n_steps: int) -> _k.ABCState:
        if self.use_pallas:
            on_tpu = _on_tpu()
            self.state = _af.fused_abc_run(
                self.state, self.objective_name, n_steps,
                self.half_width, self.limit,
                rng="tpu" if on_tpu else "host",
                interpret=not on_tpu,
            )
        else:
            self.state = _k.abc_run(
                self.state, self.objective, n_steps, self.half_width,
                self.limit,
            )
        # Async dispatch (r4): see PSO.run's rationale.  Reading any
        # state field synchronizes.
        return self.state

    @property
    def best(self) -> float:
        return float(self.state.best_fit)
