"""User-facing real-coded genetic-algorithm model."""

from __future__ import annotations

from typing import Callable, Optional, Union

import jax

from ..ops import ga as _k
from ..ops.objectives import get_objective
from ._checkpoint import CheckpointMixin


class GA(CheckpointMixin):
    """Real-coded generational GA: tournament selection, SBX crossover,
    polynomial mutation, k-elitism — the classic baseline the rest of
    the zoo is measured against.

    >>> opt = GA("sphere", n=64, dim=6, seed=0)
    >>> opt.run(300)
    >>> opt.best  # doctest: +SKIP
    """

    def __init__(
        self,
        objective: Union[str, Callable],
        n: int,
        dim: int,
        half_width: Optional[float] = None,
        eta_c: float = _k.ETA_C,
        eta_m: float = _k.ETA_M,
        p_cross: float = _k.P_CROSS,
        p_mut: float | None = None,
        n_elite: int = _k.N_ELITE,
        seed: int = 0,
        dtype=None,
    ):
        if isinstance(objective, str):
            fn, default_hw = get_objective(objective)
        else:
            fn, default_hw = objective, 5.12
        self.objective = fn
        self.half_width = float(
            half_width if half_width is not None else default_hw
        )
        if not 0 <= n_elite < n:
            raise ValueError(f"n_elite ({n_elite}) must be in [0, n)")
        self.eta_c, self.eta_m = float(eta_c), float(eta_m)
        self.p_cross = float(p_cross)
        self.p_mut = None if p_mut is None else float(p_mut)
        self.n_elite = int(n_elite)
        kwargs = {} if dtype is None else {"dtype": dtype}
        self.state = _k.ga_init(
            fn, n, dim, self.half_width, seed=seed, **kwargs
        )

    def step(self) -> _k.GAState:
        self.state = _k.ga_step(
            self.state, self.objective, self.half_width, self.eta_c,
            self.eta_m, self.p_cross, self.p_mut, self.n_elite,
        )
        return self.state

    def run(self, n_steps: int) -> _k.GAState:
        self.state = _k.ga_run(
            self.state, self.objective, n_steps, self.half_width,
            self.eta_c, self.eta_m, self.p_cross, self.p_mut, self.n_elite,
        )
        jax.block_until_ready(self.state.best_fit)
        return self.state

    @property
    def best(self) -> float:
        return float(self.state.best_fit)
