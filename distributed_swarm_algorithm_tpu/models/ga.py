"""User-facing real-coded genetic-algorithm model."""

from __future__ import annotations

from typing import Callable, Optional, Union

import jax

from ..ops import ga as _k
from ..ops.objectives import get_objective
from ..ops.pallas import ga_fused as _gf
from ..utils.platform import on_tpu as _on_tpu
from ._checkpoint import CheckpointMixin


class GA(CheckpointMixin):
    """Real-coded generational GA: tournament selection, SBX crossover,
    polynomial mutation, k-elitism — the classic baseline the rest of
    the zoo is measured against.

    Two compute paths with the same GAState contract: portable jit'd
    JAX (iid tournament row gathers — gather-bound on TPU at large N,
    measured 16.1M steps/s at 1M) and the fused Pallas kernel
    (ops/pallas/ga_fused.py: rotational tournaments, in-kernel SBX +
    mutation, per-tile elitism) — auto-selected on TPU for named
    objectives in float32 with n >= 512, or forced with
    ``use_pallas=True``.

    >>> opt = GA("sphere", n=64, dim=6, seed=0)
    >>> opt.run(300)
    >>> opt.best  # doctest: +SKIP
    """

    def __init__(
        self,
        objective: Union[str, Callable],
        n: int,
        dim: int,
        half_width: Optional[float] = None,
        eta_c: float = _k.ETA_C,
        eta_m: float = _k.ETA_M,
        p_cross: float = _k.P_CROSS,
        p_mut: float | None = None,
        n_elite: int = _k.N_ELITE,
        seed: int = 0,
        dtype=None,
        use_pallas: Optional[bool] = None,
    ):
        if isinstance(objective, str):
            fn, default_hw = get_objective(objective)
            self.objective_name: Optional[str] = objective
        else:
            fn, default_hw = objective, 5.12
            self.objective_name = None
        self.objective = fn
        self.half_width = float(
            half_width if half_width is not None else default_hw
        )
        if not 0 <= n_elite < n:
            raise ValueError(f"n_elite ({n_elite}) must be in [0, n)")
        self.eta_c, self.eta_m = float(eta_c), float(eta_m)
        self.p_cross = float(p_cross)
        self.p_mut = None if p_mut is None else float(p_mut)
        self.n_elite = int(n_elite)
        kwargs = {} if dtype is None else {"dtype": dtype}
        self.state = _k.ga_init(
            fn, n, dim, self.half_width, seed=seed, **kwargs
        )

        supported = (
            n >= 512            # rotational donors need >= 4 lane tiles
            and self.objective_name is not None
            # the fused kernel's elitism is fixed per-tile-1; honor a
            # non-default n_elite (incl. 0 = "no elitism") by staying
            # on the portable path, like DE's variant gate
            and n_elite == _k.N_ELITE
            and _gf.ga_pallas_supported(
                self.objective_name or "", self.state.pos.dtype,
                self.state.pos.shape[-1],
            )
        )
        if use_pallas is None:
            self.use_pallas = supported and _on_tpu()
        elif use_pallas and not supported:
            raise ValueError(
                "use_pallas=True needs a named objective from "
                "ops.objectives, float32 state, n >= 512, and the "
                "default n_elite (the fused kernel's elitism is "
                "per-tile-1, not configurable)"
            )
        else:
            self.use_pallas = bool(use_pallas)

    def step(self) -> _k.GAState:
        self.state = _k.ga_step(
            self.state, self.objective, self.half_width, self.eta_c,
            self.eta_m, self.p_cross, self.p_mut, self.n_elite,
        )
        return self.state

    def run(self, n_steps: int) -> _k.GAState:
        if self.use_pallas:
            on_tpu = _on_tpu()
            self.state = _gf.fused_ga_run(
                self.state, self.objective_name, n_steps,
                self.half_width, self.eta_c, self.eta_m, self.p_cross,
                self.p_mut,
                rng="tpu" if on_tpu else "host",
                interpret=not on_tpu,
            )
        else:
            self.state = _k.ga_run(
                self.state, self.objective, n_steps, self.half_width,
                self.eta_c, self.eta_m, self.p_cross, self.p_mut,
                self.n_elite,
            )
        # Async dispatch (r4): see PSO.run's rationale.  Reading any
        # state field synchronizes.
        return self.state

    @property
    def best(self) -> float:
        return float(self.state.best_fit)
