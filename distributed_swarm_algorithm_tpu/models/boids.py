"""User-facing Boids flocking model.

Thin stateful wrapper over ``ops/boids.py``, same shape as the other
model classes (PSO/DE/CMAES/VectorSwarm).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from ..ops import boids as _k
from ._checkpoint import CheckpointMixin


class Boids(CheckpointMixin):
    """Reynolds flocking simulation on a toroidal world.

    >>> flock = Boids(n=256, seed=0)
    >>> flock.run(500)
    >>> float(flock.polarization)   # -> ~1.0 once aligned  # doctest: +SKIP
    """

    def __init__(
        self,
        n: int,
        dim: int = 2,
        params: Optional[_k.BoidsParams] = None,
        obstacles: Optional[jax.Array] = None,
        seed: int = 0,
        neighbor_mode: str = "dense",
        **overrides,
    ):
        base = params if params is not None else _k.BoidsParams()
        if overrides:
            base = base._replace(**overrides)
        self.params = base
        if neighbor_mode not in ("dense", "window", "gridmean"):
            raise ValueError(
                f"unknown neighbor_mode {neighbor_mode!r}; "
                "expected 'dense', 'window', or 'gridmean'"
            )
        if neighbor_mode != "dense" and dim != 2:
            raise ValueError(
                f"neighbor_mode={neighbor_mode!r} is 2-D only (a silent "
                "dense fallback would OOM at large-flock sizes); got "
                f"dim={dim}"
            )
        self.neighbor_mode = neighbor_mode
        self.obstacles = (
            jnp.asarray(obstacles, jnp.float32)
            if obstacles is not None
            else None
        )
        self.state = _k.boids_init(n, dim, self.params, seed=seed)

    def step(self) -> _k.BoidsState:
        step_fn = {
            "dense": _k.boids_step,
            "window": _k.boids_step_window,
            "gridmean": _k.boids_step_gridmean,
        }[self.neighbor_mode]
        self.state = step_fn(self.state, self.params, self.obstacles)
        return self.state

    # Longest single gridmean scan per XLA program on TPU.  Long
    # scans have INTERMITTENTLY crashed the TPU worker process —
    # observed r3 at 1M and r4 at 4096x2000 on the portable path,
    # and once on the FUSED path (r4b: 1M, K=32 lane-tiled, during a
    # ~157 s 200-step scan in a heavy process); never reproducible in
    # a fresh process (benchmarks/repro_gridmean_crash.py has the
    # characterization — the trigger is scan length x accumulated
    # worker state).  Chunking the host-side loop bounds any single
    # program far below every observed failure, at ~one extra
    # dispatch per chunk (~100 us) — semantics identical (pinned by
    # test).
    _GRIDMEAN_CHUNK = 500

    def _gridmean_chunking_on_tpu(self) -> bool:
        from ..utils.platform import on_tpu

        return self.neighbor_mode == "gridmean" and on_tpu()

    def run(self, n_steps: int, record: bool = False):
        """Advance ``n_steps`` ticks; with ``record=True`` returns the
        ``[n_steps, N, D]`` position trajectory."""
        chunk = (
            self._GRIDMEAN_CHUNK
            if n_steps > self._GRIDMEAN_CHUNK
            and self._gridmean_chunking_on_tpu()
            else n_steps
        )
        if n_steps <= 0:
            # Preserve the single-call contract (a 0-length scan
            # returns an empty [0, N, D] trajectory).
            self.state, traj = _k.boids_run(
                self.state, self.params, n_steps, self.obstacles,
                record, neighbor_mode=self.neighbor_mode,
            )
            # Async dispatch (r4): see PSO.run's rationale.
            return traj if record else self.state
        frames = []
        done = 0
        while done < n_steps:
            step = min(chunk, n_steps - done)
            self.state, traj = _k.boids_run(
                self.state, self.params, step, self.obstacles, record,
                neighbor_mode=self.neighbor_mode,
            )
            if record:
                frames.append(traj)
            done += step
        # Async dispatch (r4): see PSO.run's rationale.  Reading any
        # state field synchronizes.
        if record:
            return (
                frames[0] if len(frames) == 1
                else jax.numpy.concatenate(frames, axis=0)
            )
        return self.state

    @property
    def polarization(self) -> float:
        return float(_k.polarization(self.state))

    @property
    def nearest_neighbor_dist(self) -> float:
        return float(
            _k.nearest_neighbor_dist(self.state, self.params.half_width)
        )
