"""Shared checkpoint/restore behavior for stateful model classes.

Every model in ``models/`` keeps its whole device state in a single
pytree attribute ``self.state``; this mixin gives them all the same
save/load contract over ``utils/checkpoint.py`` (orbax dir or .npz).
"""

from __future__ import annotations


class CheckpointMixin:
    """save()/load() over the model's ``state`` pytree."""

    def save(self, path: str) -> None:
        """Checkpoint the model state (orbax dir or .npz file)."""
        from ..utils import checkpoint as _ckpt

        _ckpt.save(path, self.state)

    def load(self, path: str) -> None:
        """Restore state saved by :meth:`save` (shapes must match)."""
        from ..utils import checkpoint as _ckpt

        self.state = _ckpt.restore(path, self.state)
