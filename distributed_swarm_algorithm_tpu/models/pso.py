"""User-facing PSO optimizer model."""

from __future__ import annotations

from typing import Callable, Optional, Union

import jax

from ..ops import pso as _k
from ..ops.objectives import get_objective


class PSO:
    """Global-best particle swarm optimizer.

    >>> opt = PSO("rastrigin", n=4096, dim=30, seed=0)
    >>> opt.run(500)
    >>> float(opt.state.gbest_fit)  # doctest: +SKIP
    """

    def __init__(
        self,
        objective: Union[str, Callable],
        n: int,
        dim: int,
        half_width: Optional[float] = None,
        w: float = _k.W,
        c1: float = _k.C1,
        c2: float = _k.C2,
        vmax_frac: float = 0.5,
        seed: int = 0,
        dtype=None,
    ):
        if isinstance(objective, str):
            fn, default_hw = get_objective(objective)
        else:
            fn, default_hw = objective, 5.12
        self.objective = fn
        self.half_width = float(
            half_width if half_width is not None else default_hw
        )
        self.w, self.c1, self.c2 = float(w), float(c1), float(c2)
        self.vmax_frac = float(vmax_frac)
        kwargs = {} if dtype is None else {"dtype": dtype}
        self.state = _k.pso_init(
            fn, n, dim, self.half_width, seed=seed, **kwargs
        )

    def step(self) -> _k.PSOState:
        self.state = _k.pso_step(
            self.state, self.objective, self.w, self.c1, self.c2,
            self.half_width, self.vmax_frac,
        )
        return self.state

    def run(self, n_steps: int) -> _k.PSOState:
        self.state = _k.pso_run(
            self.state, self.objective, n_steps, self.w, self.c1, self.c2,
            self.half_width, self.vmax_frac,
        )
        jax.block_until_ready(self.state.gbest_fit)
        return self.state

    @property
    def best(self) -> float:
        return float(self.state.gbest_fit)
