"""User-facing PSO optimizer model."""

from __future__ import annotations

from typing import Callable, Optional, Union

import jax

from ..ops import pso as _k
from ..ops import topology as _topo
from ..ops.objectives import get_objective
from ..ops.pallas import pso_fused as _pf
from ..utils.platform import on_tpu as _on_tpu
from ._checkpoint import CheckpointMixin


class PSO(CheckpointMixin):
    """Global-best particle swarm optimizer.

    Two compute paths with the same PSOState contract:
      - portable jit'd JAX (any backend),
      - the fused Pallas TPU kernel (ops/pallas/pso_fused.py) — picked
        automatically on TPU for named objectives in float32, or forced
        with ``use_pallas=True`` (on CPU that runs the same kernel body in
        interpret mode with host RNG — slow, for testing).

    >>> opt = PSO("rastrigin", n=4096, dim=30, seed=0)
    >>> opt.run(500)
    >>> float(opt.state.gbest_fit)  # doctest: +SKIP
    """

    def __init__(
        self,
        objective: Union[str, Callable],
        n: int,
        dim: int,
        half_width: Optional[float] = None,
        w: float = _k.W,
        c1: float = _k.C1,
        c2: float = _k.C2,
        vmax_frac: float = 0.5,
        seed: int = 0,
        dtype=None,
        use_pallas: Optional[bool] = None,
        steps_per_kernel: int = 8,
        topology: str = "gbest",
        ring_radius: int = 1,
        grid_cols: int = 0,
    ):
        if isinstance(objective, str):
            fn, default_hw = get_objective(objective)
            self.objective_name: Optional[str] = objective
        else:
            fn, default_hw = objective, 5.12
            self.objective_name = None
        self.objective = fn
        self.half_width = float(
            half_width if half_width is not None else default_hw
        )
        self.w, self.c1, self.c2 = float(w), float(c1), float(c2)
        self.vmax_frac = float(vmax_frac)
        self.steps_per_kernel = int(steps_per_kernel)
        if topology not in _topo.TOPOLOGIES:
            raise ValueError(
                f"unknown topology {topology!r}; "
                f"available: {_topo.TOPOLOGIES}"
            )
        self.topology = topology
        self.ring_radius = int(ring_radius)
        self.grid_cols = int(grid_cols)
        kwargs = {} if dtype is None else {"dtype": dtype}
        self.state = _k.pso_init(
            fn, n, dim, self.half_width, seed=seed, **kwargs
        )

        # The fused Pallas kernel implements the gbest attractor only.
        supported = (
            topology == "gbest"
            and self.objective_name is not None
            and _pf.pallas_supported(
                self.objective_name or "", self.state.pos.dtype,
                self.state.pos.shape[-1],
            )
        )
        if use_pallas is None:
            self.use_pallas = supported and _on_tpu()
        elif use_pallas and not supported:
            raise ValueError(
                "use_pallas=True needs a named objective from "
                "ops.objectives, float32 state, and topology='gbest'"
            )
        else:
            self.use_pallas = bool(use_pallas)

    def step(self) -> _k.PSOState:
        self.state = _k.pso_step(
            self.state, self.objective, self.w, self.c1, self.c2,
            self.half_width, self.vmax_frac,
            self.topology, self.ring_radius, self.grid_cols,
        )
        return self.state

    def run(self, n_steps: int) -> _k.PSOState:
        """Advance ``n_steps`` iterations and return the new state.

        Dispatch contract (r4): ``run`` returns with device work
        possibly still IN FLIGHT — it does not block.  Reading any
        state field (``opt.best``, ``state.gbest_fit``, ...)
        synchronizes, which is where device-side failures surface;
        callers timing ``run()`` alone measure dispatch latency only.
        """
        if self.use_pallas:
            on_tpu = _on_tpu()
            self.state = _pf.fused_pso_run(
                self.state, self.objective_name, n_steps,
                self.w, self.c1, self.c2, self.half_width, self.vmax_frac,
                rng="tpu" if on_tpu else "host",
                interpret=not on_tpu,
                steps_per_kernel=self.steps_per_kernel,
            )
        else:
            self.state = _k.pso_run(
                self.state, self.objective, n_steps, self.w, self.c1,
                self.c2, self.half_width, self.vmax_frac,
                self.topology, self.ring_radius, self.grid_cols,
            )
        # Dispatch is ASYNC (r4): the block_until_ready that used to
        # sit here costs ~80 ms per call through the axon TPU tunnel
        # while being documented-unreliable on it (it can return
        # before remote execution finishes) — measured 1.08B -> 0.68B
        # agent-steps/s on the 20k-step 10k-particle bench.  JAX
        # semantics make this safe: reading any state field (e.g.
        # ``opt.best``) synchronizes.
        return self.state

    @property
    def best(self) -> float:
        return float(self.state.gbest_fit)
