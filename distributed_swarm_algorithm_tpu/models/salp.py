"""User-facing salp-swarm model."""

from __future__ import annotations

from typing import Callable, Optional, Union

import jax

from ..ops import salp as _k
from ..ops.objectives import get_objective
from ..ops.pallas import salp_fused as _sf
from ..utils.platform import on_tpu as _on_tpu
from ._checkpoint import CheckpointMixin


class Salp(CheckpointMixin):
    """Salp swarm algorithm (chain-topology search, Mirjalili 2017).

    The leader explores around the food source under a decaying
    envelope; followers average down the chain, rippling information
    with a built-in delay.

    Two compute paths with the same SalpState contract: portable
    jit'd JAX (exact per-step chain + food refresh — 218M
    salp-steps/s at 1M on v5e) and the fused Pallas kernel
    (ops/pallas/salp_fused.py: in-VMEM chain, block-cadence
    cross-tile links/food, per-step best recording) — auto-selected
    on TPU for named objectives in float32 with n >= 128, or forced
    with ``use_pallas=True``.

    >>> opt = Salp("sphere", n=64, dim=6, seed=0)
    >>> opt.run(300)
    >>> opt.best  # doctest: +SKIP
    """

    def __init__(
        self,
        objective: Union[str, Callable],
        n: int,
        dim: int,
        half_width: Optional[float] = None,
        t_max: int = _k.T_MAX,
        seed: int = 0,
        dtype=None,
        use_pallas: Optional[bool] = None,
    ):
        if isinstance(objective, str):
            fn, default_hw = get_objective(objective)
            self.objective_name: Optional[str] = objective
        else:
            fn, default_hw = objective, 5.12
            self.objective_name = None
        self.objective = fn
        self.half_width = float(
            half_width if half_width is not None else default_hw
        )
        if t_max <= 0:
            raise ValueError(f"t_max ({t_max}) must be positive")
        self.t_max = int(t_max)
        kwargs = {} if dtype is None else {"dtype": dtype}
        self.state = _k.salp_init(
            fn, n, dim, self.half_width, seed=seed, **kwargs
        )

        supported = (
            n >= 128            # one full lane tile
            and self.objective_name is not None
            and _sf.salp_pallas_supported(
                self.objective_name or "", self.state.pos.dtype,
                self.state.pos.shape[-1],
            )
        )
        if use_pallas is None:
            self.use_pallas = supported and _on_tpu()
        elif use_pallas and not supported:
            raise ValueError(
                "use_pallas=True needs a named objective from "
                "ops.objectives, float32 state, and n >= 128"
            )
        else:
            self.use_pallas = bool(use_pallas)

    def step(self) -> _k.SalpState:
        self.state = _k.salp_step(
            self.state, self.objective, self.half_width, self.t_max
        )
        return self.state

    def run(self, n_steps: int) -> _k.SalpState:
        if self.use_pallas:
            on_tpu = _on_tpu()
            self.state = _sf.fused_salp_run(
                self.state, self.objective_name, n_steps,
                self.half_width, self.t_max,
                rng="tpu" if on_tpu else "host",
                interpret=not on_tpu,
            )
        else:
            self.state = _k.salp_run(
                self.state, self.objective, n_steps, self.half_width,
                self.t_max,
            )
        # Async dispatch (r4): see PSO.run's rationale.  Reading any
        # state field synchronizes.
        return self.state

    @property
    def best(self) -> float:
        return float(self.state.best_fit)
