"""User-facing grey-wolf optimizer model."""

from __future__ import annotations

from typing import Callable, Optional, Union

import jax

from ..ops import gwo as _k
from ..ops.objectives import get_objective
from ._checkpoint import CheckpointMixin


class GWO(CheckpointMixin):
    """Grey wolf optimizer (alpha/beta/delta-led pack).

    ``t_max`` sets the exploration schedule length (a: 2 → 0); the pack
    exploits fully once ``t_max`` iterations have elapsed.

    >>> opt = GWO("rastrigin", n=256, dim=10, t_max=300, seed=0)
    >>> opt.run(300)
    >>> opt.best  # doctest: +SKIP
    """

    def __init__(
        self,
        objective: Union[str, Callable],
        n: int,
        dim: int,
        half_width: Optional[float] = None,
        t_max: int = 500,
        seed: int = 0,
        dtype=None,
    ):
        if isinstance(objective, str):
            fn, default_hw = get_objective(objective)
        else:
            fn, default_hw = objective, 5.12
        self.objective = fn
        self.half_width = float(
            half_width if half_width is not None else default_hw
        )
        if t_max < 1:
            raise ValueError(f"t_max must be >= 1, got {t_max}")
        self.t_max = int(t_max)
        kwargs = {} if dtype is None else {"dtype": dtype}
        self.state = _k.gwo_init(
            fn, n, dim, self.half_width, seed=seed, **kwargs
        )

    def step(self) -> _k.GWOState:
        self.state = _k.gwo_step(
            self.state, self.objective, self.half_width, self.t_max
        )
        return self.state

    def run(self, n_steps: int) -> _k.GWOState:
        self.state = _k.gwo_run(
            self.state, self.objective, n_steps, self.half_width,
            self.t_max,
        )
        jax.block_until_ready(self.state.leader_fit)
        return self.state

    @property
    def best(self) -> float:
        return float(self.state.leader_fit[0])
