"""User-facing grey-wolf optimizer model."""

from __future__ import annotations

from typing import Callable, Optional, Union

import jax

from ..ops import gwo as _k
from ..ops.objectives import get_objective
from ..ops.pallas import gwo_fused as _gf
from ..utils.platform import on_tpu as _on_tpu
from ._checkpoint import CheckpointMixin


class GWO(CheckpointMixin):
    """Grey wolf optimizer (alpha/beta/delta-led pack).

    ``t_max`` sets the exploration schedule length (a: 2 → 0); the pack
    exploits fully once ``t_max`` iterations have elapsed.

    ``run`` uses the fused Pallas TPU kernel
    (ops/pallas/gwo_fused.py) when on TPU with a named objective —
    force with ``use_pallas=True`` (CPU runs the same body in interpret
    mode) or disable with ``use_pallas=False``; ``step`` always uses
    the portable path.

    >>> opt = GWO("rastrigin", n=256, dim=10, t_max=300, seed=0)
    >>> opt.run(300)
    >>> opt.best  # doctest: +SKIP
    """

    def __init__(
        self,
        objective: Union[str, Callable],
        n: int,
        dim: int,
        half_width: Optional[float] = None,
        t_max: int = 500,
        seed: int = 0,
        dtype=None,
        use_pallas: Optional[bool] = None,
        steps_per_kernel: int = 8,
    ):
        if isinstance(objective, str):
            fn, default_hw = get_objective(objective)
            self.objective_name: Optional[str] = objective
        else:
            fn, default_hw = objective, 5.12
            self.objective_name = None
        self.objective = fn
        self.half_width = float(
            half_width if half_width is not None else default_hw
        )
        if t_max < 1:
            raise ValueError(f"t_max must be >= 1, got {t_max}")
        self.t_max = int(t_max)
        self.steps_per_kernel = int(steps_per_kernel)
        kwargs = {} if dtype is None else {"dtype": dtype}
        self.state = _k.gwo_init(
            fn, n, dim, self.half_width, seed=seed, **kwargs
        )
        supported = self.objective_name is not None and (
            _gf.gwo_pallas_supported(
                self.objective_name, self.state.pos.dtype,
                self.state.pos.shape[-1],
            )
        )
        if use_pallas is None:
            self.use_pallas = supported and _on_tpu()
        elif use_pallas and not supported:
            raise ValueError(
                "use_pallas=True needs a named objective from "
                f"{sorted(_gf.OBJECTIVES_T)} and float32 state"
            )
        else:
            self.use_pallas = bool(use_pallas)

    def step(self) -> _k.GWOState:
        self.state = _k.gwo_step(
            self.state, self.objective, self.half_width, self.t_max
        )
        return self.state

    def run(self, n_steps: int) -> _k.GWOState:
        if self.use_pallas:
            self.state = _gf.fused_gwo_run(
                self.state, self.objective_name, n_steps,
                half_width=self.half_width, t_max=self.t_max,
                rng="tpu" if _on_tpu() else "host",
                interpret=not _on_tpu(),
                steps_per_kernel=self.steps_per_kernel,
            )
        else:
            self.state = _k.gwo_run(
                self.state, self.objective, n_steps, self.half_width,
                self.t_max,
            )
        # Async dispatch (r4): see PSO.run's rationale.  Reading any
        # state field synchronizes.
        return self.state

    @property
    def best(self) -> float:
        return float(self.state.leader_fit[0])
