"""Per-agent, event-driven CPU path — reference-compatible API.

The vectorized model (models/swarm.py) is the TPU path; this module keeps
the reference's one-object-per-agent, message-driven semantics alive for
behavioral tests, interop, and real deployments of few-agent swarms — the
role SURVEY.md §7 assigns to the CPU backend.  The public surface matches
/root/reference/agent.py: ``SwarmAgent(agent_id, total_agents,
capabilities)``, ``set_target``, ``update_sensors``, ``update_loop``,
``on_message_received``, the ``tasks`` dict, ``position``/``velocity``.

What the reference never had, this does:
  * a **real transport** — the reference's ``_send_msg`` body is ``pass``
    (agent.py:188-195, "SIMULATION STUB"); here ``LoopbackBus`` wires
    agents in-process (with optional drop/delay fault injection) and
    ``UdpTransport`` moves actual datagrams between processes, the
    UDP backend the reference's comments promise.
  * u32 sender/winner ids on the wire — the reference's u8 header fields
    crash the swarm at 256 agents (agent.py:186; SURVEY.md §5a bug 2).
    Header is ``!BII`` (type u8, sender u32, tick u32) = 9 bytes.
  * an injectable clock (``time_fn``) so tests control time instead of
    back-dating attributes, and config instead of hard-coded constants.
  * epsilon-clamped norms — co-located agents don't crash (§5a bug 1).

Every constant comes from utils/config.SwarmConfig; defaults reproduce the
reference's observable behavior exactly.
"""

from __future__ import annotations

import argparse
import enum
import logging
import math
import random
import socket
import struct
import threading
import time
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from ..utils.config import DEFAULT_CONFIG, SwarmConfig

logger = logging.getLogger(__name__)

# Wire opcodes — same values as the reference (agent.py:12-17).
class MsgType(enum.IntEnum):
    HEARTBEAT = 0x01
    ELECTION_ACCLAIM = 0x02
    COORDINATOR = 0x03
    TASK_CLAIM = 0x04
    TASK_CONFLICT = 0x05


class AgentState(enum.Enum):
    FOLLOWER = 1
    ELECTION_WAIT = 2
    LEADER = 3


# Header: type u8, sender u32, tick u32 (network order).  The reference's
# 6-byte !BBI header capped swarms at 255 agents; this one is 9 bytes with
# no practical ceiling.
HEADER_FMT = "!BII"
HEADER_LEN = struct.calcsize(HEADER_FMT)
PAYLOAD_HEARTBEAT = "!ff"      # leader position (agent.py:286)
PAYLOAD_ACCLAIM = "!I"         # acclaimer id (agent.py:240, widened)
PAYLOAD_CLAIM = "!If"          # task id, utility (agent.py:302)
PAYLOAD_CONFLICT = "!II"       # task id, winner id (agent.py:322, widened)


# ---------------------------------------------------------------------------
# Transports
# ---------------------------------------------------------------------------


class Transport:
    """Broadcast fabric interface: agents call ``send``; the transport
    delivers packets to every *other* registered agent's ingress."""

    def send(self, sender_id: int, packet: bytes) -> None:
        raise NotImplementedError

    def close(self) -> None:
        pass


class NullTransport(Transport):
    """Byte-faithful to the reference stub: packets vanish."""

    def send(self, sender_id: int, packet: bytes) -> None:
        pass


class LoopbackBus(Transport):
    """In-process broadcast bus with fault injection.

    Delivers synchronously to every other attached agent.  ``drop_rate``
    drops packets at random; ``partition`` (a set of frozensets of agent
    ids) delivers only within a group — enough to reproduce every failure
    scenario the reference's protocol is meant to survive.
    """

    def __init__(self, drop_rate: float = 0.0, seed: int = 0):
        self.agents: Dict[int, "SwarmAgent"] = {}
        self.drop_rate = drop_rate
        self.partitions: Optional[List[frozenset]] = None
        self._rng = random.Random(seed)

    def attach(self, agent: "SwarmAgent") -> None:
        self.agents[agent.agent_id] = agent
        agent.transport = self

    def partition_groups(self, *groups: Sequence[int]) -> None:
        self.partitions = [frozenset(g) for g in groups]

    def heal(self) -> None:
        self.partitions = None

    def _reachable(self, a: int, b: int) -> bool:
        if self.partitions is None:
            return True
        return any(a in g and b in g for g in self.partitions)

    def send(self, sender_id: int, packet: bytes) -> None:
        for aid, agent in list(self.agents.items()):
            if aid == sender_id or not self._reachable(sender_id, aid):
                continue
            if self.drop_rate and self._rng.random() < self.drop_rate:
                continue
            agent.on_message_received(packet)


class UdpTransport(Transport):
    """Datagram transport between OS processes — the backend the reference
    names but never implements (agent.py:191-193 "this goes to UDP/TCP
    socket").  Each agent binds one port and unicasts to a static peer
    list; a daemon thread feeds received packets to the agent ingress."""

    def __init__(
        self,
        bind: Tuple[str, int],
        peers: Sequence[Tuple[str, int]],
    ):
        self.peers = list(peers)
        self.sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        self.sock.bind(bind)
        self.sock.settimeout(0.2)
        self._agent: Optional["SwarmAgent"] = None
        self._running = False
        self._thread: Optional[threading.Thread] = None

    def attach(self, agent: "SwarmAgent") -> None:
        self._agent = agent
        agent.transport = self
        self._running = True
        self._thread = threading.Thread(target=self._rx_loop, daemon=True)
        self._thread.start()

    def _rx_loop(self) -> None:
        while self._running:
            try:
                data, _ = self.sock.recvfrom(65536)
            except socket.timeout:
                continue
            except OSError:
                break
            if self._agent is not None:
                self._agent.on_message_received(data)

    def send(self, sender_id: int, packet: bytes) -> None:
        for peer in self.peers:
            try:
                self.sock.sendto(packet, peer)
            except OSError:
                pass

    def close(self) -> None:
        self._running = False
        try:
            self.sock.close()
        except OSError:
            pass
        if self._thread is not None:
            self._thread.join(timeout=1.0)


class TcpTransport(Transport):
    """Stream transport between OS processes — the *other* backend the
    reference's stub comment names (agent.py:191-193, "this goes to
    UDP/TCP socket").  TCP adds per-link ordering and reliability on top
    of what UdpTransport gives; since TCP is a byte stream, packets are
    framed with a u16 length prefix.

    Topology matches UdpTransport: every agent listens on ``bind`` and
    unicasts each broadcast to its static ``peers`` list.  Outbound
    links dial lazily on first send and re-dial after failure (at most
    once per ``redial_seconds`` per peer, so a dead peer does not stall
    the 10 Hz loop); inbound connections each get a daemon reader
    thread feeding the agent ingress.
    """

    FRAME_FMT = "!H"
    FRAME_LEN = struct.calcsize(FRAME_FMT)

    def __init__(
        self,
        bind: Tuple[str, int],
        peers: Sequence[Tuple[str, int]],
        redial_seconds: float = 1.0,
        connect_timeout: float = 0.25,
    ):
        self.peers = list(peers)
        self.redial_seconds = redial_seconds
        self.connect_timeout = connect_timeout
        self._listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._listener.bind(bind)
        self._listener.listen(16)
        self._listener.settimeout(0.2)
        self._agent: Optional["SwarmAgent"] = None
        self._running = False
        self._accept_thread: Optional[threading.Thread] = None
        self._readers: List[threading.Thread] = []
        self._inbound: List[socket.socket] = []
        self._out: Dict[Tuple[str, int], Optional[socket.socket]] = {}
        self._next_dial: Dict[Tuple[str, int], float] = {}
        self._out_lock = threading.Lock()
        # Per-peer WRITE locks: sendall can split across syscalls under
        # backpressure, and both the tick thread and the inbound reader
        # threads send — interleaved partial frames would permanently
        # desynchronize the length-prefixed stream.
        self._wlocks: Dict[Tuple[str, int], threading.Lock] = {
            peer: threading.Lock() for peer in self.peers
        }

    def attach(self, agent: "SwarmAgent") -> None:
        self._agent = agent
        agent.transport = self
        self._running = True
        self._accept_thread = threading.Thread(
            target=self._accept_loop, daemon=True
        )
        self._accept_thread.start()

    # --- inbound ---------------------------------------------------------
    def _accept_loop(self) -> None:
        while self._running:
            try:
                conn, _ = self._listener.accept()
            except socket.timeout:
                continue
            except OSError:
                break
            conn.settimeout(0.2)
            self._inbound.append(conn)
            t = threading.Thread(
                target=self._read_loop, args=(conn,), daemon=True
            )
            t.start()
            self._readers.append(t)

    def _read_loop(self, conn: socket.socket) -> None:
        buf = b""
        while self._running:
            try:
                chunk = conn.recv(65536)
            except socket.timeout:
                continue
            except OSError:
                break
            if not chunk:      # peer closed
                break
            buf += chunk
            while len(buf) >= self.FRAME_LEN:
                (length,) = struct.unpack(
                    self.FRAME_FMT, buf[: self.FRAME_LEN]
                )
                if len(buf) < self.FRAME_LEN + length:
                    break
                packet = buf[self.FRAME_LEN: self.FRAME_LEN + length]
                buf = buf[self.FRAME_LEN + length:]
                if self._agent is not None:
                    self._agent.on_message_received(packet)
        try:
            conn.close()
        except OSError:
            pass

    # --- outbound --------------------------------------------------------
    def _dial(self, peer: Tuple[str, int]) -> Optional[socket.socket]:
        now = time.monotonic()
        if now < self._next_dial.get(peer, 0.0):
            return None
        self._next_dial[peer] = now + self.redial_seconds
        try:
            s = socket.create_connection(peer, timeout=self.connect_timeout)
            s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            return s
        except OSError:
            return None

    def send(self, sender_id: int, packet: bytes) -> None:
        if len(packet) > 0xFFFF:
            # The u16 length prefix cannot frame it; treat like any
            # other link failure (drop + log) instead of letting
            # struct.error escape and kill the caller's tick/reader
            # thread.  No protocol packet comes near 64 KiB.
            logger.warning(
                "TcpTransport: dropping oversized packet (%d bytes)",
                len(packet),
            )
            return
        frame = struct.pack(self.FRAME_FMT, len(packet)) + packet
        # Dial dead peers OUTSIDE the lock: a blocking connect to an
        # unreachable host (up to connect_timeout) must not stall other
        # sender threads, or k dead peers would delay every tick by
        # k * connect_timeout and push heartbeats toward the election
        # timeout exactly when the swarm is already degraded.
        with self._out_lock:
            links = [(peer, self._out.get(peer)) for peer in self.peers]
        dialed = {}
        for peer, s in links:
            if s is None:
                dialed[peer] = self._dial(peer)
        if dialed:
            with self._out_lock:
                for peer, s in dialed.items():
                    if self._out.get(peer) is None:
                        self._out[peer] = s
                    elif s is not None:
                        # another sender won the race; drop ours
                        try:
                            s.close()
                        except OSError:
                            pass
                links = [
                    (peer, self._out.get(peer)) for peer in self.peers
                ]
        for peer, s in links:
            if s is None:
                continue
            try:
                with self._wlocks[peer]:
                    s.sendall(frame)
            except OSError:
                try:
                    s.close()
                except OSError:
                    pass
                with self._out_lock:
                    if self._out.get(peer) is s:
                        self._out[peer] = None

    def close(self) -> None:
        self._running = False
        try:
            self._listener.close()
        except OSError:
            pass
        with self._out_lock:
            for s in self._out.values():
                if s is not None:
                    try:
                        s.close()
                    except OSError:
                        pass
            self._out.clear()
        for c in self._inbound:
            try:
                c.close()
            except OSError:
                pass
        if self._accept_thread is not None:
            self._accept_thread.join(timeout=1.0)
        for t in self._readers:
            t.join(timeout=1.0)


# ---------------------------------------------------------------------------
# The agent
# ---------------------------------------------------------------------------


class SwarmAgent:
    """Event-driven swarm agent: election, heartbeat, allocation, APF.

    Observable behavior matches the reference's SwarmAgent; see module
    docstring for the deliberate divergences (all bug fixes).
    """

    def __init__(
        self,
        agent_id: int,
        total_agents: int = 1,
        capabilities: Optional[Sequence[str]] = None,
        config: Optional[SwarmConfig] = None,
        transport: Optional[Transport] = None,
        time_fn: Callable[[], float] = time.time,
        rng: Optional[random.Random] = None,
    ):
        self.agent_id = agent_id
        self.total_agents = total_agents
        self.config = config or DEFAULT_CONFIG
        self.transport = transport or NullTransport()
        self.time_fn = time_fn
        self.rng = rng or random.Random()
        self.log = logging.getLogger(f"A{agent_id}")

        # Coordination state (reference agent.py:31-39).
        # Serializes step() against transport-thread ingress (UdpTransport
        # delivers on a daemon thread).  LoopbackBus delivers synchronously
        # inside step() on one thread, so the lock is reentrant-by-absence
        # there (no cross-agent lock is ever held while sending).
        self._lock = threading.RLock()

        self.state = AgentState.FOLLOWER
        self.leader_id: Optional[int] = None
        self.leader_pos: Optional[Tuple[float, float]] = None
        self.last_heartbeat_time = self.time_fn()
        self.tick = 0
        self.election_wait_start = 0.0
        self.election_delay = 0.0

        # Tasks (reference agent.py:41-44).
        # {task_id: {'status': 'OPEN'|'TENTATIVE'|'ASSIGNED'|'LOCKED',
        #            'pos': (x, y), 'required_cap': str}}
        self.tasks: Dict[int, dict] = {}
        self.task_claims: Dict[int, dict] = {}

        # Physics & sensors (reference agent.py:47-52).
        self.position = [0.0, 0.0]
        self.velocity = [0.0, 0.0]
        self.sensors = {"obstacles": [], "neighbors": []}
        self.target: Optional[Tuple[float, float]] = None
        self.capabilities = list(capabilities) if capabilities else []

    # --- world injection (agent.py:56-65) --------------------------------
    def set_target(self, x: float, y: float) -> None:
        self.target = (x, y)

    def update_sensors(self, obstacles, neighbors) -> None:
        """obstacles: [(x, y, radius)]; neighbors: [(id, x, y)]."""
        self.sensors["obstacles"] = obstacles
        self.sensors["neighbors"] = neighbors

    # --- main loop (agent.py:67-92) --------------------------------------
    def update_loop(self) -> None:
        period = 1.0 / self.config.tick_rate_hz
        while True:
            start = self.time_fn()
            self.step(period)
            leftover = period - (self.time_fn() - start)
            if leftover > 0:
                time.sleep(leftover)

    def step(self, dt: Optional[float] = None) -> None:
        """One tick: logic then physics — callable directly (testable,
        unlike the reference's blocking-only loop)."""
        with self._lock:
            self.tick += 1
            self._process_logic()
            self._update_physics(dt if dt is not None else self.config.dt)

    def _process_logic(self) -> None:
        self._check_election_timeout()
        if self.state == AgentState.LEADER:
            self._maybe_heartbeat()
        self._process_tasks()

    # --- wire codec -------------------------------------------------------
    def _send(self, msg_type: MsgType, payload: bytes = b"") -> None:
        header = struct.pack(HEADER_FMT, msg_type, self.agent_id, self.tick)
        self.transport.send(self.agent_id, header + payload)

    def on_message_received(self, data: bytes) -> None:
        """Ingress dispatch (agent.py:197-214): short packets drop."""
        if len(data) < HEADER_LEN:
            return
        msg_type, sender, _tick = struct.unpack(
            HEADER_FMT, data[:HEADER_LEN]
        )
        payload = data[HEADER_LEN:]
        with self._lock:
            self._dispatch(msg_type, sender, payload)

    def _dispatch(self, msg_type: int, sender: int, payload: bytes) -> None:
        if msg_type == MsgType.HEARTBEAT:
            self._handle_heartbeat(sender, payload)
        elif msg_type == MsgType.ELECTION_ACCLAIM:
            self._handle_election_acclaim(sender)
        elif msg_type == MsgType.COORDINATOR:
            self._handle_coordinator(sender)
        elif msg_type == MsgType.TASK_CLAIM:
            self._handle_task_claim(sender, payload)
        elif msg_type == MsgType.TASK_CONFLICT:
            self._handle_task_conflict(sender, payload)

    # --- election: quiet bully (agent.py:216-289) ------------------------
    def _check_election_timeout(self) -> None:
        if self.state == AgentState.LEADER:
            return
        now = self.time_fn()
        if (
            self.state == AgentState.FOLLOWER
            and now - self.last_heartbeat_time > self.config.timeout_seconds
        ):
            self.log.warning("leader timeout; entering ELECTION_WAIT")
            self.state = AgentState.ELECTION_WAIT
            self.election_wait_start = now
            jitter_max = (
                self.config.election_jitter_ticks / self.config.tick_rate_hz
            )
            self.election_delay = self.rng.uniform(0.0, jitter_max)
            self.leader_id = None
            self.leader_pos = None
        if self.state == AgentState.ELECTION_WAIT:
            if now - self.election_wait_start > self.election_delay:
                self.log.info("election wait over; acclaiming leadership")
                self.state = AgentState.LEADER
                self.leader_id = self.agent_id
                self._send(
                    MsgType.ELECTION_ACCLAIM,
                    struct.pack(PAYLOAD_ACCLAIM, self.agent_id),
                )
                self._send(MsgType.COORDINATOR)

    def _handle_heartbeat(self, sender: int, payload: bytes) -> None:
        if self.state == AgentState.LEADER and sender < self.agent_id:
            # Suppress the lower-id leader.  Unlike the reference, the
            # reply is NOT tick-gated (SURVEY.md §5a bug 3), so the bully
            # actually lands.
            self._send_heartbeat_now()
            return
        if self.state == AgentState.LEADER and sender > self.agent_id:
            self.log.info("yielding to higher leader %d", sender)
            self.state = AgentState.FOLLOWER
        self.leader_id = sender
        self.last_heartbeat_time = self.time_fn()
        if len(payload) == struct.calcsize(PAYLOAD_HEARTBEAT):
            self.leader_pos = struct.unpack(PAYLOAD_HEARTBEAT, payload)
        if self.state == AgentState.ELECTION_WAIT:
            self.state = AgentState.FOLLOWER

    def _handle_election_acclaim(self, sender: int) -> None:
        if sender > self.agent_id:
            self.state = AgentState.FOLLOWER
            self.leader_id = sender
            self.last_heartbeat_time = self.time_fn()
        elif sender < self.agent_id and self.state in (
            AgentState.LEADER,
            AgentState.ELECTION_WAIT,
        ):
            if self.state == AgentState.ELECTION_WAIT:
                self.state = AgentState.LEADER
                self.leader_id = self.agent_id
            self._send_heartbeat_now()

    def _handle_coordinator(self, sender: int) -> None:
        # Reference quirk (agent.py:277-281): unconditional adoption — even
        # a higher-id leader would yield.  Fixed: ignore lower-id
        # coordinators while leading; the bully rule stays consistent.
        if self.state == AgentState.LEADER and sender < self.agent_id:
            self._send_heartbeat_now()
            return
        self.leader_id = sender
        self.state = AgentState.FOLLOWER
        self.last_heartbeat_time = self.time_fn()

    def _maybe_heartbeat(self) -> None:
        if self.tick % self.config.heartbeat_period_ticks == 0:
            self._send_heartbeat_now()

    def _send_heartbeat_now(self) -> None:
        self._send(
            MsgType.HEARTBEAT,
            struct.pack(PAYLOAD_HEARTBEAT, *self.position[:2]),
        )

    # --- task allocation (agent.py:291-347) ------------------------------
    def _process_tasks(self) -> None:
        for task_id, task in self.tasks.items():
            if task["status"] == "OPEN":
                utility = self._calculate_utility(task)
                if utility > self.config.utility_threshold:
                    task["status"] = "TENTATIVE"
                    task["claim_tick"] = self.tick
                    payload = struct.pack(PAYLOAD_CLAIM, task_id, utility)
                    self._send(MsgType.TASK_CLAIM, payload)
                    if self.state == AgentState.LEADER:
                        # Transports skip the sender, so a leader never
                        # hears its own claim (in the reference the stub
                        # made this moot) — arbitrate it locally like
                        # everyone else's.
                        self._handle_task_claim(self.agent_id, payload)
            elif task["status"] == "TENTATIVE":
                # Fix for SURVEY.md §5a bug 4: a claim whose verdict never
                # arrives (lost packet, dead leader) re-opens after one
                # election-timeout's worth of ticks instead of wedging.
                age = self.tick - task.get("claim_tick", self.tick)
                if age > self.config.election_timeout_ticks:
                    task["status"] = "OPEN"

    def _handle_task_claim(self, sender: int, payload: bytes) -> None:
        task_id, utility = struct.unpack(PAYLOAD_CLAIM, payload)
        if self.state != AgentState.LEADER:
            return
        current = self.task_claims.get(task_id)
        is_new_better = current is None or (
            utility > current["utility"] + self.config.claim_hysteresis
        )
        if is_new_better:
            self.task_claims[task_id] = {
                "winner": sender, "utility": utility,
            }
            verdict = struct.pack(PAYLOAD_CONFLICT, task_id, sender)
        else:
            # Re-affirm the incumbent — including to the incumbent itself:
            # if its original verdict was lost, its claim re-opens and it
            # re-claims (see _process_tasks), and this re-broadcast is what
            # finally lands the ASSIGNED status.
            verdict = struct.pack(
                PAYLOAD_CONFLICT, task_id, current["winner"]
            )
        self._send(MsgType.TASK_CONFLICT, verdict)
        # Apply the verdict to the leader's own task table as well — the
        # broadcast skips the sender (see _process_tasks).
        self._handle_task_conflict(self.agent_id, verdict)

    def _handle_task_conflict(self, sender: int, payload: bytes) -> None:
        task_id, winner = struct.unpack(PAYLOAD_CONFLICT, payload)
        if task_id not in self.tasks:
            return
        if winner == self.agent_id:
            self.log.info("won task %d", task_id)
            self.tasks[task_id]["status"] = "ASSIGNED"
        else:
            self.tasks[task_id]["status"] = "LOCKED"

    def _calculate_utility(self, task: dict) -> float:
        # U = scale / (1 + dist) * cap_match  (agent.py:338-347)
        dx = self.position[0] - task["pos"][0]
        dy = self.position[1] - task["pos"][1]
        dist = math.hypot(dx, dy)
        has_cap = 1.0
        req = task.get("required_cap")
        if req is not None and req not in self.capabilities:
            has_cap = 0.0
        return (self.config.utility_scale / (1.0 + dist)) * has_cap

    # --- physics: APF (agent.py:94-181) ----------------------------------
    def _update_physics(self, dt: float) -> None:
        cfg = self.config
        if self.state == AgentState.FOLLOWER and self.leader_pos:
            if cfg.formation_rank_mode == "id":
                rank = self.agent_id  # reference semantics (agent.py:99)
            else:
                # "ordinal" — a lone agent only knows its own id and the
                # leader's, so this is the contiguous-ids approximation of
                # the vectorized ordinal rank: skip the leader's slot and
                # never sit on the leader (SURVEY.md §5a bug 7).
                skip = (
                    1
                    if self.leader_id is not None
                    and self.leader_id < self.agent_id
                    else 0
                )
                rank = self.agent_id + 1 - skip
            sp = cfg.formation_spacing
            x_off = -sp * rank
            if cfg.formation_shape == "line":
                y_off = 0.0
            else:
                y_off = sp * rank if rank % 2 == 0 else -sp * rank
            self.target = (
                self.leader_pos[0] + x_off,
                self.leader_pos[1] + y_off,
            )

        if not self.target:
            return

        eps = cfg.dist_eps
        fx = fy = 0.0

        # attraction
        tx = self.target[0] - self.position[0]
        ty = self.target[1] - self.position[1]
        if math.hypot(tx, ty) > cfg.arrival_tolerance:
            fx += cfg.k_att * tx
            fy += cfg.k_att * ty

        # obstacle repulsion
        for ox, oy, r in self.sensors["obstacles"]:
            dx = self.position[0] - ox
            dy = self.position[1] - oy
            center = max(math.hypot(dx, dy), eps)
            surf = max(center - r, eps)
            if surf < cfg.rho0:
                mag = cfg.k_rep * (1.0 / surf - 1.0 / cfg.rho0) / (surf**2)
                fx += (dx / center) * mag
                fy += (dy / center) * mag

        # neighbor separation
        for _nid, nx, ny in self.sensors["neighbors"]:
            dx = self.position[0] - nx
            dy = self.position[1] - ny
            dist = max(math.hypot(dx, dy), eps)
            if dist < cfg.personal_space:
                mag = cfg.k_sep / (dist**2)
                fx += (dx / dist) * mag
                fy += (dy / dist) * mag

        # clamp + integrate
        speed = math.hypot(fx, fy)
        if speed > cfg.max_speed:
            scale = cfg.max_speed / speed
            fx, fy = fx * scale, fy * scale
        self.velocity = [fx, fy]
        self.position[0] += fx * dt
        self.position[1] += fy * dt


def run_local_swarm(
    n_agents: int,
    n_ticks: int,
    config: Optional[SwarmConfig] = None,
    drop_rate: float = 0.0,
    seed: int = 0,
) -> Tuple[List[SwarmAgent], LoopbackBus]:
    """Convenience: n agents on a LoopbackBus, stepped in lockstep — the
    multi-agent deployment the reference CLI promises but (with a stub
    transport) can never deliver."""
    cfg = config or DEFAULT_CONFIG
    bus = LoopbackBus(drop_rate=drop_rate, seed=seed)
    clock = [0.0]
    agents = []
    for i in range(n_agents):
        a = SwarmAgent(
            i, n_agents, config=cfg, time_fn=lambda: clock[0],
            rng=random.Random(seed * 7919 + i),
        )
        bus.attach(a)
        agents.append(a)
    dt = 1.0 / cfg.tick_rate_hz
    for _ in range(n_ticks):
        clock[0] += dt
        for a in agents:
            a.step(dt)
    return agents, bus
