"""User-facing differential-evolution optimizer model.

Same shape as :class:`~distributed_swarm_algorithm_tpu.models.pso.PSO`:
a thin stateful wrapper over the pure kernels in ``ops/de.py``.
"""

from __future__ import annotations

from typing import Callable, Optional, Union

import jax
import jax.numpy as jnp

from ..ops import de as _k
from ..ops.objectives import get_objective
from ..ops.pallas import de_fused as _df
from ..utils.platform import on_tpu as _on_tpu
from ._checkpoint import CheckpointMixin


class DE(CheckpointMixin):
    """Differential evolution (rand/1/bin by default).

    Two compute paths with the same DEState contract:
      - portable jit'd JAX (any backend; exact rand/1/bin donors via
        row gathers — gather-bound on TPU at large N),
      - the fused Pallas TPU kernel (ops/pallas/de_fused.py) with
        rotational donor selection — picked automatically on TPU for
        named objectives in float32 with the default rand1bin variant
        and a population of >= 512, or forced with ``use_pallas=True``
        (interpret mode on CPU, for testing).

    >>> opt = DE("rastrigin", n=256, dim=10, seed=0)
    >>> opt.run(300)
    >>> float(opt.state.best_fit)  # doctest: +SKIP
    """

    def __init__(
        self,
        objective: Union[str, Callable],
        n: int,
        dim: int,
        half_width: Optional[float] = None,
        f: float = _k.F,
        cr: float = _k.CR,
        variant: str = "rand1bin",
        seed: int = 0,
        dtype=None,
        use_pallas: Optional[bool] = None,
        steps_per_kernel: int = 8,
    ):
        if isinstance(objective, str):
            fn, default_hw = get_objective(objective)
            self.objective_name: Optional[str] = objective
        else:
            fn, default_hw = objective, 5.12
            self.objective_name = None
        self.objective = fn
        self.half_width = float(
            half_width if half_width is not None else default_hw
        )
        self.f, self.cr = float(f), float(cr)
        self.variant = variant
        self.steps_per_kernel = int(steps_per_kernel)
        kwargs = {} if dtype is None else {"dtype": dtype}
        self.state = _k.de_init(
            fn, n, dim, self.half_width, seed=seed, **kwargs
        )

        supported = (
            variant == "rand1bin"
            and n >= 512          # rotational donors need >= 4 lane tiles
            and self.objective_name is not None
            and _df.de_pallas_supported(
                self.objective_name or "", self.state.pos.dtype,
                self.state.pos.shape[-1],
            )
        )
        if use_pallas is None:
            self.use_pallas = supported and _on_tpu()
        elif use_pallas and not supported:
            raise ValueError(
                "use_pallas=True needs a named objective from "
                "ops.objectives, float32 state, variant='rand1bin', "
                "and n >= 512"
            )
        else:
            self.use_pallas = bool(use_pallas)

    def step(self) -> _k.DEState:
        self.state = _k.de_step(
            self.state, self.objective, self.f, self.cr, self.half_width,
            self.variant,
        )
        return self.state

    def run(self, n_steps: int) -> _k.DEState:
        if self.use_pallas:
            on_tpu = _on_tpu()
            self.state = _df.fused_de_run(
                self.state, self.objective_name, n_steps,
                self.f, self.cr, self.half_width,
                rng="tpu" if on_tpu else "host",
                interpret=not on_tpu,
                steps_per_kernel=self.steps_per_kernel,
            )
        else:
            self.state = _k.de_run(
                self.state, self.objective, n_steps, self.f, self.cr,
                self.half_width, self.variant,
            )
        # Async dispatch (r4): see PSO.run's rationale.  Reading any
        # state field synchronizes.
        return self.state

    @property
    def best(self) -> float:
        return float(self.state.best_fit)
