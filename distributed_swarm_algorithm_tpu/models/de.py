"""User-facing differential-evolution optimizer model.

Same shape as :class:`~distributed_swarm_algorithm_tpu.models.pso.PSO`:
a thin stateful wrapper over the pure kernels in ``ops/de.py``.
"""

from __future__ import annotations

from typing import Callable, Optional, Union

import jax
import jax.numpy as jnp

from ..ops import de as _k
from ..ops.objectives import get_objective
from ._checkpoint import CheckpointMixin


class DE(CheckpointMixin):
    """Differential evolution (rand/1/bin by default).

    >>> opt = DE("rastrigin", n=256, dim=10, seed=0)
    >>> opt.run(300)
    >>> float(opt.state.best_fit)  # doctest: +SKIP
    """

    def __init__(
        self,
        objective: Union[str, Callable],
        n: int,
        dim: int,
        half_width: Optional[float] = None,
        f: float = _k.F,
        cr: float = _k.CR,
        variant: str = "rand1bin",
        seed: int = 0,
        dtype=None,
    ):
        if isinstance(objective, str):
            fn, default_hw = get_objective(objective)
        else:
            fn, default_hw = objective, 5.12
        self.objective = fn
        self.half_width = float(
            half_width if half_width is not None else default_hw
        )
        self.f, self.cr = float(f), float(cr)
        self.variant = variant
        kwargs = {} if dtype is None else {"dtype": dtype}
        self.state = _k.de_init(
            fn, n, dim, self.half_width, seed=seed, **kwargs
        )

    def step(self) -> _k.DEState:
        self.state = _k.de_step(
            self.state, self.objective, self.f, self.cr, self.half_width,
            self.variant,
        )
        return self.state

    def run(self, n_steps: int) -> _k.DEState:
        self.state = _k.de_run(
            self.state, self.objective, n_steps, self.f, self.cr,
            self.half_width, self.variant,
        )
        jax.block_until_ready(self.state.best_fit)
        return self.state

    @property
    def best(self) -> float:
        return float(self.state.best_fit)
