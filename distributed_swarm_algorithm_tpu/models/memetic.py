"""User-facing memetic (gradient-hybrid) PSO model."""

from __future__ import annotations

import jax

from ..ops import memetic as _m
from ..ops import pso as _k
from ..utils.platform import on_tpu as _on_tpu
from .pso import PSO


class MemeticPSO(PSO):
    """PSO + periodic ``jax.grad`` local refinement of personal bests.

    Same constructor as :class:`PSO` plus the refinement schedule.
    Two compute paths: the portable XLA path (any callable objective),
    and — for named objectives in float32 with the gbest topology —
    the fused composition (``ops.memetic.fused_memetic_run``): fused
    Pallas PSO blocks with the gradient refinement applied in the
    same transposed layout — 693M agent-steps/s at 1M vs ~222M
    portable (3.1x; benchmarks/bench_memetic_1m.py).  Auto-selected
    on TPU; ``use_pallas=True`` forces the gate check.

    >>> opt = MemeticPSO("rosenbrock", n=512, dim=10, refine_every=5)
    >>> opt.run(100)
    >>> opt.best  # doctest: +SKIP
    """

    def __init__(
        self,
        objective,
        n: int,
        dim: int,
        refine_every: int = 10,
        refine_steps: int = 5,
        lr: float = 0.01,
        **kwargs,
    ):
        # PSO's own gate covers named-objective/f32/gbest; the fused
        # memetic path additionally needs a TPU (the refinement runs
        # through autodiff of the transposed registry, which the
        # interpret-mode host path doesn't exercise), so default the
        # auto-switch to PSO's and re-check at run().
        super().__init__(objective, n, dim, **kwargs)
        if refine_every < 1:
            raise ValueError(
                f"refine_every must be >= 1, got {refine_every} "
                "(use PSO for no refinement)"
            )
        self.refine_every = int(refine_every)
        self.refine_steps = int(refine_steps)
        self.lr = float(lr)

    def step(self) -> _k.PSOState:
        """One PSO step + refinement on the same schedule as :meth:`run`
        (a refinement pass fires when the post-step iteration counter hits
        a ``refine_every`` multiple).  Always portable (per-step use)."""
        state = super().step()
        if int(state.iteration) % self.refine_every == 0:
            self.state = _m.refine_pbest(
                state, self.objective, self.refine_steps, self.lr,
                self.half_width,
            )
        return self.state

    def run(self, n_steps: int) -> _k.PSOState:
        if self.use_pallas and _on_tpu():
            self.state = _m.fused_memetic_run(
                self.state, self.objective_name, self.objective,
                n_steps, self.refine_every, self.refine_steps, self.lr,
                self.w, self.c1, self.c2, self.half_width,
                self.vmax_frac,
                steps_per_kernel=self.steps_per_kernel,
            )
        else:
            self.state = _m.memetic_run(
                self.state, self.objective, n_steps,
                self.refine_every, self.refine_steps, self.lr,
                self.w, self.c1, self.c2, self.half_width,
                self.vmax_frac, self.topology, self.ring_radius,
                self.grid_cols,
            )
        # Async dispatch (r4): see PSO.run's rationale.  Reading any
        # state field synchronizes.
        return self.state
