"""User-facing memetic (gradient-hybrid) PSO model."""

from __future__ import annotations

import jax

from ..ops import memetic as _m
from ..ops import pso as _k
from .pso import PSO


class MemeticPSO(PSO):
    """PSO + periodic ``jax.grad`` local refinement of personal bests.

    Same constructor as :class:`PSO` plus the refinement schedule; the
    fused Pallas path is disabled (refinement needs autodiff, which runs
    on the portable XLA path).

    >>> opt = MemeticPSO("rosenbrock", n=512, dim=10, refine_every=5)
    >>> opt.run(100)
    >>> opt.best  # doctest: +SKIP
    """

    def __init__(
        self,
        objective,
        n: int,
        dim: int,
        refine_every: int = 10,
        refine_steps: int = 5,
        lr: float = 0.01,
        **kwargs,
    ):
        kwargs.setdefault("use_pallas", False)
        if kwargs["use_pallas"]:
            raise ValueError("MemeticPSO runs on the portable XLA path")
        super().__init__(objective, n, dim, **kwargs)
        if refine_every < 1:
            raise ValueError(
                f"refine_every must be >= 1, got {refine_every} "
                "(use PSO for no refinement)"
            )
        self.refine_every = int(refine_every)
        self.refine_steps = int(refine_steps)
        self.lr = float(lr)

    def step(self) -> _k.PSOState:
        """One PSO step + refinement on the same schedule as :meth:`run`
        (a refinement pass fires when the post-step iteration counter hits
        a ``refine_every`` multiple)."""
        state = super().step()
        if int(state.iteration) % self.refine_every == 0:
            self.state = _m.refine_pbest(
                state, self.objective, self.refine_steps, self.lr,
                self.half_width,
            )
        return self.state

    def run(self, n_steps: int) -> _k.PSOState:
        self.state = _m.memetic_run(
            self.state, self.objective, n_steps,
            self.refine_every, self.refine_steps, self.lr,
            self.w, self.c1, self.c2, self.half_width, self.vmax_frac,
            self.topology, self.ring_radius, self.grid_cols,
        )
        jax.block_until_ready(self.state.gbest_fit)
        return self.state
