"""User-facing OpenAI-ES model."""

from __future__ import annotations

from typing import Callable, Optional, Union

import jax

from ..ops import es as _k
from ..ops.objectives import get_objective
from ._checkpoint import CheckpointMixin


class ES(CheckpointMixin):
    """OpenAI-style evolution strategy (Salimans et al. 2017):
    antithetic Gaussian sampling, centered-rank shaping, momentum SGD
    on the search mean.  ``n`` is the per-generation population (even).

    >>> opt = ES("sphere", n=256, dim=6, seed=0)
    >>> opt.run(300)
    >>> opt.best  # doctest: +SKIP
    """

    def __init__(
        self,
        objective: Union[str, Callable],
        n: int,
        dim: int,
        half_width: Optional[float] = None,
        sigma: float = _k.SIGMA,
        lr: float = _k.LR,
        momentum: float = _k.MOMENTUM,
        seed: int = 0,
        dtype=None,
    ):
        if isinstance(objective, str):
            fn, default_hw = get_objective(objective)
        else:
            fn, default_hw = objective, 5.12
        self.objective = fn
        self.half_width = float(
            half_width if half_width is not None else default_hw
        )
        if n < 2 or n % 2:
            raise ValueError(f"n ({n}) must be even and >= 2 (antithetic)")
        self.n = int(n)
        self.sigma, self.lr = float(sigma), float(lr)
        self.momentum = float(momentum)
        kwargs = {} if dtype is None else {"dtype": dtype}
        self.state = _k.es_init(
            fn, dim, self.half_width, seed=seed, **kwargs
        )

    def step(self) -> _k.ESState:
        self.state = _k.es_step(
            self.state, self.objective, self.n, self.half_width,
            self.sigma, self.lr, self.momentum,
        )
        return self.state

    def run(self, n_steps: int) -> _k.ESState:
        self.state = _k.es_run(
            self.state, self.objective, n_steps, self.n, self.half_width,
            self.sigma, self.lr, self.momentum,
        )
        # Async dispatch (r4): see PSO.run's rationale.  Reading any
        # state field synchronizes.
        return self.state

    @property
    def best(self) -> float:
        return float(self.state.best_fit)
