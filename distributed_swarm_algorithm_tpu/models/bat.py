"""User-facing bat-algorithm model."""

from __future__ import annotations

from typing import Callable, Optional, Union

import jax

from ..ops import bat as _k
from ..ops.objectives import get_objective
from ._checkpoint import CheckpointMixin


class Bat(CheckpointMixin):
    """Bat algorithm (echolocation search, Yang 2010).

    Per-bat loudness/pulse adaptation schedules each individual's own
    exploration→exploitation transition.

    >>> opt = Bat("sphere", n=64, dim=6, seed=0)
    >>> opt.run(300)
    >>> opt.best  # doctest: +SKIP
    """

    def __init__(
        self,
        objective: Union[str, Callable],
        n: int,
        dim: int,
        half_width: Optional[float] = None,
        f_min: float = _k.F_MIN,
        f_max: float = _k.F_MAX,
        alpha: float = _k.ALPHA,
        gamma: float = _k.GAMMA,
        r0: float = _k.R0,
        sigma_local: float = _k.SIGMA_LOCAL,
        seed: int = 0,
        dtype=None,
    ):
        if isinstance(objective, str):
            fn, default_hw = get_objective(objective)
        else:
            fn, default_hw = objective, 5.12
        self.objective = fn
        self.half_width = float(
            half_width if half_width is not None else default_hw
        )
        if f_max < f_min:
            raise ValueError(f"f_max ({f_max}) must be >= f_min ({f_min})")
        self.f_min, self.f_max = float(f_min), float(f_max)
        self.alpha, self.gamma = float(alpha), float(gamma)
        self.r0, self.sigma_local = float(r0), float(sigma_local)
        kwargs = {} if dtype is None else {"dtype": dtype}
        self.state = _k.bat_init(
            fn, n, dim, self.half_width, seed=seed, **kwargs
        )

    def step(self) -> _k.BatState:
        self.state = _k.bat_step(
            self.state, self.objective, self.half_width, self.f_min,
            self.f_max, self.alpha, self.gamma, self.r0, self.sigma_local,
        )
        return self.state

    def run(self, n_steps: int) -> _k.BatState:
        self.state = _k.bat_run(
            self.state, self.objective, n_steps, self.half_width,
            self.f_min, self.f_max, self.alpha, self.gamma, self.r0,
            self.sigma_local,
        )
        jax.block_until_ready(self.state.best_fit)
        return self.state

    @property
    def best(self) -> float:
        return float(self.state.best_fit)
