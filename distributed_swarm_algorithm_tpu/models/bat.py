"""User-facing bat-algorithm model."""

from __future__ import annotations

from typing import Callable, Optional, Union

import jax

from ..ops import bat as _k
from ..ops.objectives import get_objective
from ..ops.pallas import bat_fused as _bf
from ..utils.platform import on_tpu as _on_tpu
from ._checkpoint import CheckpointMixin


class Bat(CheckpointMixin):
    """Bat algorithm (echolocation search, Yang 2010).

    Per-bat loudness/pulse adaptation schedules each individual's own
    exploration→exploitation transition.

    ``run`` uses the fused Pallas TPU kernel
    (ops/pallas/bat_fused.py) when on TPU with a named objective —
    force with ``use_pallas=True`` (CPU runs the same kernel body in
    interpret mode) or disable with ``use_pallas=False``.  ``step``
    always uses the portable path.

    >>> opt = Bat("sphere", n=64, dim=6, seed=0)
    >>> opt.run(300)
    >>> opt.best  # doctest: +SKIP
    """

    def __init__(
        self,
        objective: Union[str, Callable],
        n: int,
        dim: int,
        half_width: Optional[float] = None,
        f_min: float = _k.F_MIN,
        f_max: float = _k.F_MAX,
        alpha: float = _k.ALPHA,
        gamma: float = _k.GAMMA,
        r0: float = _k.R0,
        sigma_local: float = _k.SIGMA_LOCAL,
        seed: int = 0,
        dtype=None,
        use_pallas: Optional[bool] = None,
        steps_per_kernel: int = 8,
    ):
        if isinstance(objective, str):
            fn, default_hw = get_objective(objective)
            self.objective_name: Optional[str] = objective
        else:
            fn, default_hw = objective, 5.12
            self.objective_name = None
        self.objective = fn
        self.half_width = float(
            half_width if half_width is not None else default_hw
        )
        if f_max < f_min:
            raise ValueError(f"f_max ({f_max}) must be >= f_min ({f_min})")
        self.f_min, self.f_max = float(f_min), float(f_max)
        self.alpha, self.gamma = float(alpha), float(gamma)
        self.r0, self.sigma_local = float(r0), float(sigma_local)
        self.steps_per_kernel = int(steps_per_kernel)
        kwargs = {} if dtype is None else {"dtype": dtype}
        self.state = _k.bat_init(
            fn, n, dim, self.half_width, seed=seed, **kwargs
        )
        supported = self.objective_name is not None and (
            _bf.bat_pallas_supported(
                self.objective_name, self.state.pos.dtype,
                self.state.pos.shape[-1],
            )
        )
        if use_pallas is None:
            self.use_pallas = supported and _on_tpu()
        elif use_pallas and not supported:
            raise ValueError(
                "use_pallas=True needs a named objective from "
                f"{sorted(_bf.OBJECTIVES_T)} and float32 state"
            )
        else:
            self.use_pallas = bool(use_pallas)

    def step(self) -> _k.BatState:
        self.state = _k.bat_step(
            self.state, self.objective, self.half_width, self.f_min,
            self.f_max, self.alpha, self.gamma, self.r0, self.sigma_local,
        )
        return self.state

    def run(self, n_steps: int) -> _k.BatState:
        if self.use_pallas:
            self.state = _bf.fused_bat_run(
                self.state, self.objective_name, n_steps,
                half_width=self.half_width, f_min=self.f_min,
                f_max=self.f_max, alpha=self.alpha, gamma=self.gamma,
                r0=self.r0, sigma_local=self.sigma_local,
                rng="tpu" if _on_tpu() else "host",
                interpret=not _on_tpu(),
                steps_per_kernel=self.steps_per_kernel,
            )
        else:
            self.state = _k.bat_run(
                self.state, self.objective, n_steps, self.half_width,
                self.f_min, self.f_max, self.alpha, self.gamma, self.r0,
                self.sigma_local,
            )
        # Async dispatch (r4): see PSO.run's rationale.  Reading any
        # state field synchronizes.
        return self.state

    @property
    def best(self) -> float:
        return float(self.state.best_fit)
