"""User-facing SHADE model."""

from __future__ import annotations

from typing import Callable, Optional, Union

import jax

from ..ops import shade as _k
from ..ops.objectives import get_objective
from ..ops.pallas import shade_fused as _sf
from ..utils.platform import on_tpu as _on_tpu
from ._checkpoint import CheckpointMixin


class SHADE(CheckpointMixin):
    """Success-history adaptive DE (Tanabe & Fukunaga 2013): F/CR are
    sampled around a circular memory of recently-successful settings,
    mutation is current-to-pbest/1 with an external archive of defeated
    parents — the self-tuning member of the DE lineage.

    Two compute paths with the same SHADEState contract:
      - portable jit'd JAX (exact paper semantics; donor-gather-bound
        on TPU at large N),
      - the fused SHADE-R Pallas kernel (ops/pallas/shade_fused.py,
        rotational donors; memory adaptation stays exact per
        generation) — picked automatically on TPU for named objectives
        in float32 with default p_best and n >= 512, or forced with
        ``use_pallas=True`` (interpret mode on CPU, for testing).

    >>> opt = SHADE("rastrigin", n=256, dim=10, seed=0)
    >>> opt.run(300)
    >>> opt.best  # doctest: +SKIP
    """

    def __init__(
        self,
        objective: Union[str, Callable],
        n: int,
        dim: int,
        half_width: Optional[float] = None,
        p_best: float = _k.P_BEST,
        seed: int = 0,
        dtype=None,
        use_pallas: Optional[bool] = None,
    ):
        if isinstance(objective, str):
            fn, default_hw = get_objective(objective)
            self.objective_name: Optional[str] = objective
        else:
            fn, default_hw = objective, 5.12
            self.objective_name = None
        self.objective = fn
        self.half_width = float(
            half_width if half_width is not None else default_hw
        )
        if not 0.0 < p_best <= 1.0:
            raise ValueError(f"p_best ({p_best}) must be in (0, 1]")
        self.p_best = float(p_best)
        kwargs = {} if dtype is None else {"dtype": dtype}
        self.state = _k.shade_init(
            fn, n, dim, self.half_width, seed=seed, **kwargs
        )

        supported = (
            p_best == _k.P_BEST     # SHADE-R uses its own elite pool
            and n >= 512            # rotational donors need >= 4 tiles
            and self.objective_name is not None
            and _sf.shade_pallas_supported(
                self.objective_name or "", self.state.pos.dtype,
                self.state.pos.shape[-1],
            )
        )
        if use_pallas is None:
            self.use_pallas = supported and _on_tpu()
        elif use_pallas and not supported:
            raise ValueError(
                "use_pallas=True needs a named objective from "
                "ops.objectives, float32 state, default p_best, and "
                "n >= 512"
            )
        else:
            self.use_pallas = bool(use_pallas)

    def step(self) -> _k.SHADEState:
        self.state = _k.shade_step(
            self.state, self.objective, self.half_width, self.p_best
        )
        return self.state

    def run(self, n_steps: int) -> _k.SHADEState:
        if self.use_pallas:
            on_tpu = _on_tpu()
            self.state = _sf.fused_shade_run(
                self.state, self.objective_name, n_steps,
                self.half_width,
                rng="tpu" if on_tpu else "host",
                interpret=not on_tpu,
            )
        else:
            self.state = _k.shade_run(
                self.state, self.objective, n_steps, self.half_width,
                self.p_best,
            )
        # Async dispatch (r4): see PSO.run's rationale.  Reading any
        # state field synchronizes.
        return self.state

    @property
    def best(self) -> float:
        return float(self.state.best_fit)
