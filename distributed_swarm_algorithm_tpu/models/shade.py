"""User-facing SHADE model."""

from __future__ import annotations

from typing import Callable, Optional, Union

import jax

from ..ops import shade as _k
from ..ops.objectives import get_objective
from ._checkpoint import CheckpointMixin


class SHADE(CheckpointMixin):
    """Success-history adaptive DE (Tanabe & Fukunaga 2013): F/CR are
    sampled around a circular memory of recently-successful settings,
    mutation is current-to-pbest/1 with an external archive of defeated
    parents — the self-tuning member of the DE lineage.

    >>> opt = SHADE("rastrigin", n=256, dim=10, seed=0)
    >>> opt.run(300)
    >>> opt.best  # doctest: +SKIP
    """

    def __init__(
        self,
        objective: Union[str, Callable],
        n: int,
        dim: int,
        half_width: Optional[float] = None,
        p_best: float = _k.P_BEST,
        seed: int = 0,
        dtype=None,
    ):
        if isinstance(objective, str):
            fn, default_hw = get_objective(objective)
        else:
            fn, default_hw = objective, 5.12
        self.objective = fn
        self.half_width = float(
            half_width if half_width is not None else default_hw
        )
        if not 0.0 < p_best <= 1.0:
            raise ValueError(f"p_best ({p_best}) must be in (0, 1]")
        self.p_best = float(p_best)
        kwargs = {} if dtype is None else {"dtype": dtype}
        self.state = _k.shade_init(
            fn, n, dim, self.half_width, seed=seed, **kwargs
        )

    def step(self) -> _k.SHADEState:
        self.state = _k.shade_step(
            self.state, self.objective, self.half_width, self.p_best
        )
        return self.state

    def run(self, n_steps: int) -> _k.SHADEState:
        self.state = _k.shade_run(
            self.state, self.objective, n_steps, self.half_width,
            self.p_best,
        )
        jax.block_until_ready(self.state.best_fit)
        return self.state

    @property
    def best(self) -> float:
        return float(self.state.best_fit)
