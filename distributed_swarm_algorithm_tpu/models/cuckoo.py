"""User-facing cuckoo-search model."""

from __future__ import annotations

from typing import Callable, Optional, Union

import jax

from ..ops import cuckoo as _k
from ..ops.objectives import get_objective
from ._checkpoint import CheckpointMixin


class Cuckoo(CheckpointMixin):
    """Cuckoo search (Lévy flights + nest abandonment, Yang & Deb 2009).

    >>> opt = Cuckoo("rastrigin", n=64, dim=8, seed=0)
    >>> opt.run(400)
    >>> opt.best  # doctest: +SKIP
    """

    def __init__(
        self,
        objective: Union[str, Callable],
        n: int,
        dim: int,
        half_width: Optional[float] = None,
        pa: float = _k.PA,
        step_scale: float = _k.STEP_SCALE,
        levy_beta: float = _k.LEVY_BETA,
        seed: int = 0,
        dtype=None,
    ):
        if isinstance(objective, str):
            fn, default_hw = get_objective(objective)
        else:
            fn, default_hw = objective, 5.12
        self.objective = fn
        self.half_width = float(
            half_width if half_width is not None else default_hw
        )
        if not 0.0 <= pa <= 1.0:
            raise ValueError(f"pa must be in [0, 1], got {pa}")
        self.pa = float(pa)
        self.step_scale = float(step_scale)
        self.levy_beta = float(levy_beta)
        kwargs = {} if dtype is None else {"dtype": dtype}
        self.state = _k.cuckoo_init(
            fn, n, dim, self.half_width, seed=seed, **kwargs
        )

    def step(self) -> _k.CuckooState:
        self.state = _k.cuckoo_step(
            self.state, self.objective, self.half_width, self.pa,
            self.step_scale, self.levy_beta,
        )
        return self.state

    def run(self, n_steps: int) -> _k.CuckooState:
        self.state = _k.cuckoo_run(
            self.state, self.objective, n_steps, self.half_width,
            self.pa, self.step_scale, self.levy_beta,
        )
        jax.block_until_ready(self.state.best_fit)
        return self.state

    @property
    def best(self) -> float:
        return float(self.state.best_fit)
