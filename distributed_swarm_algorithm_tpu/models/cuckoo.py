"""User-facing cuckoo-search model."""

from __future__ import annotations

from typing import Callable, Optional, Union

import jax

from ..ops import cuckoo as _k
from ..ops.objectives import get_objective
from ..ops.pallas import cuckoo_fused as _cf
from ..utils.platform import on_tpu as _on_tpu
from ._checkpoint import CheckpointMixin


class Cuckoo(CheckpointMixin):
    """Cuckoo search (Lévy flights + nest abandonment, Yang & Deb 2009).

    Two compute paths with the same CuckooState contract: portable
    jit'd JAX (exact random egg targets + permuted peers — scatter/
    gather-bound on TPU at large N) and the fused Pallas kernel
    (ops/pallas/cuckoo_fused.py: rotational egg drop + peers, in-kernel
    Box-Muller Levy flights) — auto-selected on TPU for named
    objectives in float32 with n >= 512, or forced with
    ``use_pallas=True``.

    >>> opt = Cuckoo("rastrigin", n=64, dim=8, seed=0)
    >>> opt.run(400)
    >>> opt.best  # doctest: +SKIP
    """

    def __init__(
        self,
        objective: Union[str, Callable],
        n: int,
        dim: int,
        half_width: Optional[float] = None,
        pa: float = _k.PA,
        step_scale: float = _k.STEP_SCALE,
        levy_beta: float = _k.LEVY_BETA,
        seed: int = 0,
        dtype=None,
        use_pallas: Optional[bool] = None,
    ):
        if isinstance(objective, str):
            fn, default_hw = get_objective(objective)
            self.objective_name: Optional[str] = objective
        else:
            fn, default_hw = objective, 5.12
            self.objective_name = None
        self.objective = fn
        self.half_width = float(
            half_width if half_width is not None else default_hw
        )
        if not 0.0 <= pa <= 1.0:
            raise ValueError(f"pa must be in [0, 1], got {pa}")
        self.pa = float(pa)
        self.step_scale = float(step_scale)
        self.levy_beta = float(levy_beta)
        kwargs = {} if dtype is None else {"dtype": dtype}
        self.state = _k.cuckoo_init(
            fn, n, dim, self.half_width, seed=seed, **kwargs
        )

        supported = (
            n >= 512            # rotational peers need >= 4 lane tiles
            and self.objective_name is not None
            and _cf.cuckoo_pallas_supported(
                self.objective_name or "", self.state.pos.dtype,
                self.state.pos.shape[-1],
            )
        )
        if use_pallas is None:
            self.use_pallas = supported and _on_tpu()
        elif use_pallas and not supported:
            raise ValueError(
                "use_pallas=True needs a named objective from "
                "ops.objectives, float32 state, and n >= 512"
            )
        else:
            self.use_pallas = bool(use_pallas)

    def step(self) -> _k.CuckooState:
        self.state = _k.cuckoo_step(
            self.state, self.objective, self.half_width, self.pa,
            self.step_scale, self.levy_beta,
        )
        return self.state

    def run(self, n_steps: int) -> _k.CuckooState:
        if self.use_pallas:
            on_tpu = _on_tpu()
            self.state = _cf.fused_cuckoo_run(
                self.state, self.objective_name, n_steps,
                self.half_width, self.pa, self.step_scale,
                self.levy_beta,
                rng="tpu" if on_tpu else "host",
                interpret=not on_tpu,
            )
        else:
            self.state = _k.cuckoo_run(
                self.state, self.objective, n_steps, self.half_width,
                self.pa, self.step_scale, self.levy_beta,
            )
        # Async dispatch (r4): see PSO.run's rationale.  Reading any
        # state field synchronizes.
        return self.state

    @property
    def best(self) -> float:
        return float(self.state.best_fit)
