"""Vectorized CPU backend: NumPy oracle + optional native C++ kernels.

Role (SURVEY.md §7 design stance): the CPU path is the default debugging /
small-swarm backend and the baseline that the TPU path's speedups are
measured against (BASELINE.md).  This module re-implements the vectorized
swarm tick — coordination, allocation, physics, identical semantics to the
JAX kernels in ops/ — in plain NumPy, and transparently dispatches the two
compute hot spots (APF physics, utility/arbitration) to the C++ tier in
``native/`` when a compiler is available.

The NumPy implementations double as the *oracle* for testing the C++
kernels (tests/test_native.py) and for cross-checking the JAX path
(tests/test_cpu_swarm.py): three independent implementations, one
semantics.

World is 2-D like the reference's (agent.py:47).
"""

from __future__ import annotations

from typing import Optional, Tuple

import numpy as np

from ..utils.config import DEFAULT_CONFIG, SwarmConfig
from .. import native as _native

# FSM codes — keep in sync with state.py (reference agent.py:19-22).
FOLLOWER = 1
ELECTION_WAIT = 2
LEADER = 3
NO_LEADER = -1
NO_WINNER = -1
NO_CAP = -1


class CpuSwarm:
    """Whole-swarm lockstep simulator on NumPy arrays.

    Mirrors models/swarm.py:VectorSwarm field-for-field (see state.py for
    the reference-attribute mapping).  ``backend="native"`` uses the C++
    kernels for physics and allocation; ``backend="numpy"`` forces the
    pure-NumPy oracle; ``backend="auto"`` (default) picks native when the
    shared library builds/loads.
    """

    def __init__(
        self,
        n_agents: int,
        n_caps: int = 1,
        config: Optional[SwarmConfig] = None,
        seed: int = 0,
        spread: float = 0.0,
        backend: str = "auto",
    ):
        self.config = config or DEFAULT_CONFIG
        if self.config.allocation_mode not in ("greedy", "auction"):
            raise ValueError(
                f"unknown allocation_mode "
                f"{self.config.allocation_mode!r}"
            )
        self.n = n_agents
        rng = np.random.default_rng(seed)
        self.rng = rng

        if backend == "auto":
            backend = "native" if _native.available() else "numpy"
        elif backend == "native":
            if not _native.available():
                raise RuntimeError(
                    "native backend requested but unavailable"
                )
        elif backend != "numpy":
            raise ValueError(f"unknown backend {backend!r}")
        self.backend = backend

        self.tick = 0
        self.agent_id = np.arange(n_agents, dtype=np.int32)
        self.alive = np.ones(n_agents, bool)
        self.pos = (
            rng.uniform(-spread, spread, (n_agents, 2))
            if spread > 0.0
            else np.zeros((n_agents, 2))
        )
        self.vel = np.zeros((n_agents, 2))
        self.target = np.zeros((n_agents, 2))
        self.has_target = np.zeros(n_agents, bool)
        self.caps = np.zeros((n_agents, max(n_caps, 1)), bool)

        self.fsm = np.full(n_agents, FOLLOWER, np.int32)
        self.leader_id = np.full(n_agents, NO_LEADER, np.int32)
        self.leader_pos = np.zeros((n_agents, 2))
        self.has_leader_pos = np.zeros(n_agents, bool)
        self.last_hb_tick = np.zeros(n_agents, np.int32)
        self.wait_until = np.zeros(n_agents, np.int32)

        self.task_pos = np.zeros((0, 2))
        self.task_cap = np.zeros(0, np.int32)
        self.task_winner = np.zeros(0, np.int32)
        self.task_util = np.zeros(0)
        self.task_claimed = np.zeros((n_agents, 0), bool)

        self.obstacles: Optional[np.ndarray] = None
        # Flight-recorder twin (r10): one TickTelemetry per tick when
        # config.telemetry.enabled — the oracle's record uses the SAME
        # pytree type as the JAX scan's stacked ys, so one summary
        # reducer serves both (utils/telemetry.stack_telemetry).
        self.telemetry: list = []

    # --- world injection --------------------------------------------------
    def set_target(self, target, agents=None) -> None:
        t = np.broadcast_to(np.asarray(target, float), (self.n, 2))
        if agents is None:
            self.target[:] = t
            self.has_target[:] = True
        else:
            self.target[agents] = t[agents]
            self.has_target[agents] = True

    def set_obstacles(self, obstacles) -> None:
        self.obstacles = (
            None if obstacles is None else np.asarray(obstacles, float)
        )

    def add_tasks(self, task_pos, task_cap=None) -> None:
        self.task_pos = np.asarray(task_pos, float)
        t = self.task_pos.shape[0]
        self.task_cap = (
            np.full(t, NO_CAP, np.int32)
            if task_cap is None
            else np.asarray(task_cap, np.int32)
        )
        self.task_winner = np.full(t, NO_WINNER, np.int32)
        self.task_util = np.zeros(t)
        self.task_claimed = np.zeros((self.n, t), bool)

    def kill(self, ids) -> None:
        self.alive[np.asarray(ids)] = False

    def revive(self, ids) -> None:
        ids = np.asarray(ids)
        self.alive[ids] = True
        self.fsm[ids] = FOLLOWER
        self.leader_id[ids] = NO_LEADER
        self.last_hb_tick[ids] = self.tick

    # --- stepping ---------------------------------------------------------
    def step(self, n_steps: int = 1) -> None:
        auction = self.config.allocation_mode == "auction"
        for _ in range(n_steps):
            self.tick += 1
            if auction:
                had_leader = bool(
                    (self.alive & (self.fsm == LEADER)).any()
                )
                self._coordination_step()
                has_leader = bool(
                    (self.alive & (self.fsm == LEADER)).any()
                )
                self._auction_allocation_step(
                    leader_emerged=not had_leader and has_leader
                )
            else:
                self._coordination_step()
                self._allocation_step()
            self._physics_step()

    def leader(self) -> Tuple[int, bool]:
        mask = self.alive & (self.fsm == LEADER)
        if not mask.any():
            return NO_LEADER, False
        return int(self.agent_id[mask].max()), True

    # --- flight recorder (NumPy twin of utils/telemetry.py) ---------------
    def _collect_telemetry(self, force: Optional[np.ndarray]) -> None:
        """Append this tick's TickTelemetry (config.telemetry gate is
        checked by the caller).  ``force`` is the pre-clamp APF force
        — None on the native backend, whose C++ kernel integrates
        in-place (force gauges then read 0, documented delta)."""
        from ..utils.telemetry import tick_telemetry

        mask = self.alive & (self.fsm == LEADER)
        lid = int(self.agent_id[mask].max()) if mask.any() else NO_LEADER
        electing = int((self.alive & (self.fsm == ELECTION_WAIT)).sum())
        self.telemetry.append(
            tick_telemetry(
                self.pos.astype(np.float32),
                self.vel.astype(np.float32),
                self.alive, self.tick,
                force=(
                    None if force is None else force.astype(np.float32)
                ),
                leader_id=lid, electing=electing,
            )
        )

    def stacked_telemetry(self):
        """The rollout-shaped record: per-tick entries stacked into
        one ``[T]``-leaved TickTelemetry (raises on an empty log,
        mirroring utils/telemetry.stack_telemetry)."""
        from ..utils.telemetry import stack_telemetry

        return stack_telemetry(self.telemetry)

    # --- coordination (NumPy port of ops/coordination.py) ----------------
    def _coordination_step(self) -> None:
        cfg = self.config
        tick = self.tick

        silent = (tick - self.last_hb_tick) > cfg.election_timeout_ticks
        to_wait = self.alive & (self.fsm == FOLLOWER) & silent
        jitter = self.rng.integers(
            0, cfg.election_jitter_ticks + 1, self.n
        ).astype(np.int32)
        self.wait_until = np.where(
            to_wait, tick + jitter, self.wait_until
        )
        self.fsm = np.where(to_wait, ELECTION_WAIT, self.fsm)
        self.leader_id = np.where(to_wait, NO_LEADER, self.leader_id)
        self.has_leader_pos &= ~to_wait

        acclaim = (
            self.alive
            & (self.fsm == ELECTION_WAIT)
            & (tick > self.wait_until)
        )
        any_acclaim = acclaim.any()
        if any_acclaim:
            min_acclaim = self.agent_id[acclaim].min()
            bully = (
                self.alive
                & (self.fsm == ELECTION_WAIT)
                & (self.agent_id > min_acclaim)
            )
            contender = acclaim | bully | (self.alive & (self.fsm == LEADER))
            winner = self.agent_id[contender].max()
            is_winner = contender & (self.agent_id == winner)
            resolve = self.alive
            self.fsm = np.where(
                resolve, np.where(is_winner, LEADER, FOLLOWER), self.fsm
            )
            self.leader_id = np.where(resolve, winner, self.leader_id)
            self.last_hb_tick = np.where(
                resolve & ~is_winner, tick, self.last_hb_tick
            )

        leaders = self.alive & (self.fsm == LEADER)
        emit = leaders & (tick % cfg.heartbeat_period_ticks == 0)
        if emit.any():
            emit_ids = np.where(emit, self.agent_id, NO_LEADER)
            hb_id = emit_ids.max()
            hb_pos = self.pos[emit_ids.argmax()]
            recv = self.alive & (self.agent_id != hb_id)
            suppress = recv & (self.fsm == LEADER) & (self.agent_id > hb_id)
            adopt = recv & ~suppress
            self.fsm = np.where(adopt, FOLLOWER, self.fsm)
            self.leader_id = np.where(adopt, hb_id, self.leader_id)
            self.last_hb_tick = np.where(adopt, tick, self.last_hb_tick)
            self.leader_pos = np.where(
                adopt[:, None], hb_pos[None, :], self.leader_pos
            )
            self.has_leader_pos |= adopt

        mine = self.alive & (self.fsm == LEADER)
        self.leader_id = np.where(mine, self.agent_id, self.leader_id)

    # --- allocation (NumPy / native port of ops/allocation.py) -----------
    def _evict_dead_winners(self):
        """Dead-winner eviction (mirrors ops/allocation.py
        ``dead_winner_tasks``): a task awarded to a dead agent reopens
        and everyone's view of it resets, so the swarm re-bids —
        deliberate elastic recovery the reference lacks (SURVEY.md §5a
        bug 6).  Shared by both allocation modes; returns the [T] evict
        mask."""
        awarded = self.task_winner != NO_WINNER
        winner_alive = (
            (self.agent_id[:, None] == self.task_winner[None, :])
            & self.alive[:, None]
        ).any(axis=0)
        evict = awarded & ~winner_alive
        self.task_winner = np.where(
            evict, NO_WINNER, self.task_winner
        ).astype(np.int32)
        self.task_util = np.where(evict, 0.0, self.task_util)
        self.task_claimed &= ~evict[None, :]
        return evict

    def _utility_matrix(self, dtype=np.float64):
        """[N, T] utility (ops/allocation.py:utility_matrix).  The
        auction path passes float32 so the whole chain matches the JAX
        kernel's arithmetic bit for bit; the greedy path keeps the
        historical float64."""
        cfg = self.config
        pos = self.pos.astype(dtype)
        tpos = self.task_pos.astype(dtype)
        delta = pos[:, None, :] - tpos[None, :, :]
        dist = np.linalg.norm(delta, axis=-1)
        no_cap = self.task_cap < 0
        cap_ok = self.caps[:, np.maximum(self.task_cap, 0)]
        match = np.where(no_cap[None, :], True, cap_ok)
        return np.where(
            match, dtype(cfg.utility_scale) / (dtype(1.0) + dist),
            dtype(0.0),
        )

    def _allocation_step(self) -> None:
        cfg = self.config
        t = self.task_pos.shape[0]
        if t == 0:
            return

        self._evict_dead_winners()

        if self.backend == "native":
            u = _native.utility_matrix(
                self.pos, self.task_pos, self.caps, self.task_cap,
                cfg.utility_scale,
            )
        else:
            u = self._utility_matrix()

        leader_exists = (self.alive & (self.fsm == LEADER)).any()
        open_for_me = ~self.task_claimed
        if not cfg.allocation_lock_on_award:
            not_mine = self.task_winner[None, :] != self.agent_id[:, None]
            open_for_me = open_for_me | not_mine
        claims = (
            self.alive[:, None]
            & open_for_me
            & (u > cfg.utility_threshold)
            & leader_exists
        )
        claims_util = np.where(claims, u, 0.0)

        if self.backend == "native":
            _native.arbitrate(
                claims_util, self.task_winner, self.task_util,
                cfg.claim_hysteresis,
            )
        else:
            has_claim = (claims_util > 0.0).any(axis=0)
            best_row = claims_util.argmax(axis=0)
            best_util = claims_util.max(axis=0)
            best_id = self.agent_id[best_row]
            vacant = self.task_winner == NO_WINNER
            beats = best_util > self.task_util + cfg.claim_hysteresis
            award = has_claim & (vacant | beats)
            self.task_winner = np.where(
                award, best_id, self.task_winner
            ).astype(np.int32)
            self.task_util = np.where(award, best_util, self.task_util)

        awarded = self.task_winner != NO_WINNER
        self.task_claimed |= claims | awarded[None, :]

    def _auction_allocation_step(self, leader_emerged: bool) -> None:
        """NumPy mirror of ops/allocation.py:auction_allocation_step —
        immediate dead-winner eviction; eps-optimal re-solve (Bertsekas
        auction, ops/auction.py:auction_assign_np) on the auction_every
        cadence, on eviction, and on the leaderless->led pulse."""
        cfg = self.config
        t = self.task_pos.shape[0]
        if t == 0:
            return

        evict = self._evict_dead_winners()

        leader_exists = bool((self.alive & (self.fsm == LEADER)).any())
        resolve = leader_exists and (
            self.tick % cfg.auction_every == 0
            or bool(evict.any())
            or leader_emerged
        )
        if not resolve:
            return

        u = self._utility_matrix(dtype=np.float32)
        feasible = self.alive[:, None] & (
            u > np.float32(cfg.utility_threshold)
        )

        # phases=1 = the FLAT schedule, matching the JAX tick's r8
        # switch (ops/allocation.py) — the oracle parity contract is
        # bit-identical outcomes, so the schedules must agree.
        if self.backend == "native":
            res = _native.auction_assign(
                u, feasible, eps=cfg.auction_eps, phases=1
            )
        else:
            from ..ops.auction import auction_assign_np

            res = auction_assign_np(
                u, feasible, eps=cfg.auction_eps, phases=1
            )
        got = res.task_agent >= 0
        row = np.maximum(res.task_agent, 0)
        self.task_winner = np.where(
            got, self.agent_id[row], NO_WINNER
        ).astype(np.int32)
        self.task_util = np.where(got, u[row, np.arange(t)], 0.0)
        self.task_claimed = np.broadcast_to(
            got[None, :], self.task_claimed.shape
        ).copy()

    # --- physics (NumPy / native port of ops/physics.py) ------------------
    def _formation_targets(self):
        cfg = self.config
        if cfg.formation_rank_mode == "id":
            rank = self.agent_id.astype(float)
        else:
            alive_i = self.alive.astype(np.int64)
            alive_below = np.cumsum(alive_i) - alive_i
            lid = self.leader_id
            lid_valid = (lid >= 0) & (lid < self.n)
            leader_alive = self.alive[np.clip(lid, 0, self.n - 1)]
            leader_below = (
                lid_valid & leader_alive & (lid < self.agent_id)
            ).astype(np.int64)
            rank = (alive_below - leader_below + 1).astype(float)

        sp = cfg.formation_spacing
        x_off = -sp * rank
        if cfg.formation_shape == "line":
            y_off = np.zeros_like(x_off)
        else:
            side = np.where(rank.astype(np.int64) % 2 == 0, 1.0, -1.0)
            y_off = sp * rank * side

        is_follower = (
            (self.fsm == FOLLOWER) & self.has_leader_pos & self.alive
        )
        new_target = self.leader_pos + np.stack([x_off, y_off], axis=1)
        # Ephemeral (mirrors ops/physics.py:physics_step): the derived
        # target steers this tick only; self.target keeps the nav goal.
        return (
            np.where(is_follower[:, None], new_target, self.target),
            self.has_target | is_follower,
        )

    def _physics_step(self) -> None:
        cfg = self.config
        target, has_target = self._formation_targets()
        # separation_mode: "dense" and "grid" both mean exact all-pairs
        # here (grid is a TPU-scale optimization, ops/neighbors.py; CPU
        # swarms are small enough for O(N^2)); "off" disables the force —
        # mirrored by zeroing k_sep on the native path.
        sep_off = cfg.separation_mode == "off"
        if self.backend == "native":
            _native.physics_step(
                self.pos, self.vel, target, has_target,
                self.alive, self.obstacles,
                cfg.replace(k_sep=0.0) if sep_off else cfg,
            )
            if cfg.telemetry.enabled:
                self._collect_telemetry(None)
            return

        eps = cfg.dist_eps
        pos = self.pos
        delta = target - pos
        dist = np.linalg.norm(delta, axis=-1)
        pulling = has_target & (dist > cfg.arrival_tolerance)
        force = np.where(pulling[:, None], cfg.k_att * delta, 0.0)

        if self.obstacles is not None and len(self.obstacles):
            centers = self.obstacles[:, :2]
            radii = self.obstacles[:, 2]
            away = pos[:, None, :] - centers[None, :, :]
            center_dist = np.linalg.norm(away, axis=-1)
            surf = np.maximum(
                np.maximum(center_dist, eps) - radii[None, :], eps
            )
            mag = cfg.k_rep * (1.0 / surf - 1.0 / cfg.rho0) / (surf * surf)
            mag = np.where(surf < cfg.rho0, mag, 0.0)
            unit = away / np.maximum(center_dist, eps)[..., None]
            force = force + (mag[..., None] * unit).sum(axis=1)

        if not sep_off:
            diff = pos[:, None, :] - pos[None, :, :]
            d = np.linalg.norm(diff, axis=-1)
            d_c = np.maximum(d, eps)
            near = (
                self.alive[:, None]
                & self.alive[None, :]
                & ~np.eye(self.n, dtype=bool)
                & (d < cfg.personal_space)
            )
            mag = cfg.k_sep / (d_c * d_c)
            unit = diff / d_c[..., None]
            force = force + np.where(
                near[..., None], mag[..., None] * unit, 0.0
            ).sum(axis=1)

        speed = np.linalg.norm(force, axis=-1, keepdims=True)
        scale = np.where(
            speed > cfg.max_speed,
            cfg.max_speed / np.maximum(speed, eps),
            1.0,
        )
        vel = force * scale
        moving = has_target & self.alive
        vel = np.where(moving[:, None], vel, 0.0)
        self.pos = np.where(
            moving[:, None], pos + vel * cfg.dt, pos
        )
        self.vel = vel
        if cfg.telemetry.enabled:
            self._collect_telemetry(force)
