"""The vectorized swarm model — full reference capability parity.

``swarm_tick`` is the whole-swarm equivalent of one pass through the
reference's 10 Hz loop body (/root/reference/agent.py:67-92): coordination
(election + heartbeat + failure detection), task allocation, then physics.
It is a pure ``SwarmState -> SwarmState`` function; ``VectorSwarm`` wraps
it with jit, ``lax.scan`` batched rollouts, and an optional wall-clock
realtime mode matching the reference's pacing (agent.py:78-81).
"""

from __future__ import annotations

import time
from functools import partial
from typing import Optional, Tuple, Union

import jax
import jax.numpy as jnp

from ..ops.allocation import (
    allocation_step,
    auction_allocation_step,
    task_status_view,
)
from ..ops.coordination import coordination_step, current_leader, kill, revive
from ..ops.neighbors import morton_keys as _morton_keys
from ..ops.physics import (
    build_tick_plan,
    build_tick_plan_spatial,
    physics_step,
    physics_step_plan,
    physics_step_spatial,
    physics_step_telem,
)
from ..state import (
    LEADER,
    SwarmState,
    make_swarm,
    permute_agents,  # noqa: F401  (public re-export)
    sort_agents_by_key,
    with_tasks,
)
from ..utils.compile_watch import watched
from ..utils.config import DEFAULT_CONFIG, TELEMETRY_ON, SwarmConfig
from ._checkpoint import CheckpointMixin

_NO_OBSTACLES = None


def _hashgrid_multidevice_cfg(
    state: SwarmState, cfg: SwarmConfig
) -> SwarmConfig:
    """Eager-boundary guard (r6, ADVICE r5): the fused hash-grid
    kernel is a single-device program, and inside jit the position
    array is a tracer with no sharding — so the driver entry points
    (the only places the state is still concrete) must make the
    multi-device call.  Under ``hashgrid_backend='auto'`` a swarm
    committed across devices is re-dispatched onto the portable path
    (cfg is static, so the portable graph is what gets traced);
    a forced ``'pallas'`` raises the clear error from
    ``tick_uses_hashgrid_kernel``.  Tracer or non-hashgrid states
    pass through untouched.  Flavor-agnostic (r23): the predicate
    gates whichever program ``cfg.hashgrid_kernel`` selects — the
    slot-plane kernel or the plan-native candidate sweep."""
    if cfg.separation_mode != "hashgrid":
        return cfg
    if state.pos.ndim != 2 or state.pos.shape[1] != 2:
        return cfg
    from ..ops.physics import (
        _committed_multidevice,
        tick_uses_hashgrid_kernel,
    )

    # Cheap sharding probe first: single-device (and tracer) states
    # skip the geometry/VMEM predicate entirely — this wrapper runs
    # on the eager 10 Hz driver hot loop.
    if not _committed_multidevice(state.pos):
        return cfg
    # Raises for forced 'pallas' on a committed multi-device swarm.
    with_state = tick_uses_hashgrid_kernel(
        cfg, 2, state.pos.dtype, arr=state.pos
    )
    if not with_state and tick_uses_hashgrid_kernel(
        cfg, 2, state.pos.dtype
    ):
        return cfg.replace(hashgrid_backend="portable")
    return cfg


def _protocol_steps(
    state: SwarmState,
    cfg: SwarmConfig,
    sort_in_tick: bool,
    params=None,
) -> SwarmState:
    """The pre-physics tick prefix shared by the plain and
    plan-carrying ticks: tick stamp, cadenced Morton re-sort (window
    mode), coordination, allocation.

    ``params`` (r13, serve/batched.py): optional per-scenario override
    pytree — the allocation steps read ``utility_threshold`` /
    ``auction_eps`` from it as TRACED scalars (coordination timing
    stays static config).  ``None`` = the pre-r13 graph."""
    state = state.replace(tick=state.tick + 1)
    if (
        sort_in_tick
        and cfg.separation_mode == "window"
        and cfg.sort_every > 1
    ):
        # Keep the agent axis approximately Morton-sorted so the window
        # separation pass (ops/neighbors.py) runs roll-only.  The full
        # permutation is semantically transparent (permute_agents) and
        # amortizes over sort_every ticks; between re-sorts, drift costs
        # separation recall only.  tick % sort_every == 1 fires on the
        # first tick of a fresh swarm, then every sort_every.
        state = jax.lax.cond(
            state.tick % cfg.sort_every == 1,
            lambda s: sort_agents_by_key(
                s, _morton_keys(s.pos, cfg.grid_cell)
            ),
            lambda s: s,
            state,
        )
    if cfg.allocation_mode == "auction":
        had_leader = jnp.any(state.alive & (state.fsm == LEADER))
        state = coordination_step(state, cfg)      # agent.py:83-89
        has_leader = jnp.any(state.alive & (state.fsm == LEADER))
        state = auction_allocation_step(
            state, cfg, leader_emerged=~had_leader & has_leader,
            params=params,
        )
    else:
        state = coordination_step(state, cfg)      # agent.py:83-89
        state = allocation_step(state, cfg, params=params)  # agent.py:91-92
    return state


@watched("swarm-tick")
@partial(
    jax.jit, static_argnames=("cfg", "sort_in_tick", "telemetry")
)
def _swarm_tick_impl(
    state: SwarmState,
    obstacles: Optional[jax.Array],
    cfg: SwarmConfig,
    sort_in_tick: bool = True,
    telemetry: bool = False,
):
    """One synchronous swarm tick (= one 10 Hz loop body for every agent).

    ``sort_in_tick=False`` drops the cadenced Morton re-sort ``lax.cond``
    from the graph — callers that handle the cadence themselves
    (``swarm_rollout``'s chunked scan) MUST use it: a conditional
    carrying the full swarm state costs ~26 ms/tick at 1M on v5e even
    when the branch never fires (measured r3 — XLA TPU conditionals
    materialize their whole carried tuple).

    ``telemetry=True`` (r10, static) returns ``(state, telem)`` where
    ``telem`` is the tick's flight-recorder record (None unless
    ``cfg.telemetry.enabled`` — the rollout driver enables both
    together).
    """
    state = _protocol_steps(state, cfg, sort_in_tick)
    if telemetry:
        return physics_step_telem(state, obstacles, cfg)
    return physics_step(state, obstacles, cfg)     # agent.py:94-181


def _swarm_tick_plan(
    state: SwarmState,
    obstacles: Optional[jax.Array],
    cfg: SwarmConfig,
    plan,
):
    """The plan-carrying tick (r9): same protocol prefix, physics off
    the refreshed Verlet plan, plan (and, gated on
    ``cfg.telemetry.enabled``, the tick's telemetry record) handed
    back for the scan.  Plain (un-jitted) — it only runs inside the
    rollout scan."""
    state = _protocol_steps(state, cfg, sort_in_tick=False)
    state, plan, telem = physics_step_plan(state, obstacles, cfg, plan)
    return state, plan, telem


def swarm_tick(
    state: SwarmState,
    obstacles: Optional[jax.Array],
    cfg: SwarmConfig,
    sort_in_tick: bool = True,
    telemetry: bool = False,
):
    """One synchronous swarm tick — ``_swarm_tick_impl`` behind the
    eager multi-device hash-grid guard (see
    ``_hashgrid_multidevice_cfg``; a no-op under trace and for
    single-device swarms).  ``telemetry=True`` returns
    ``(state, telem)`` — see ``_swarm_tick_impl``."""
    return _swarm_tick_impl(
        state, obstacles, _hashgrid_multidevice_cfg(state, cfg),
        sort_in_tick, telemetry,
    )


def swarm_tick_dyn(
    state: SwarmState,
    obstacles: Optional[jax.Array],
    cfg: SwarmConfig,
    params=None,
    extra_force=None,
    return_derived: bool = False,
):
    """One protocol tick with DYNAMIC per-scenario parameters (r13) —
    the scenario-batching substrate.

    Identical tick order to the rollout scan body (protocol prefix
    with the re-sort cond dropped, then physics), but the gain /
    threshold scalars named by ``params`` (``serve/batched.
    ScenarioParams``: APF gains, max-speed clamp, auction eps/theta)
    are read from a TRACED pytree instead of the jit-static config —
    so ``jax.vmap`` over a leading scenario axis of ``(state,
    params)`` runs thousands of heterogeneous swarms in ONE compiled
    program with zero retraces (``serve/batched.batched_rollout``).
    With ``params=None`` every scalar comes from ``cfg`` and the
    graph is the pre-r13 tick — which is why a batched scenario is
    bitwise-equal to the same scenario run solo through
    :func:`swarm_rollout` with the params baked into the config
    (pinned by tests/test_serve.py).

    ``extra_force`` (r14, envs/): an optional ``[N, D]`` per-agent
    steering force injected between the APF sum and ``integrate`` —
    the RL action channel of the MARL env facade
    (``envs/core.SwarmMARLEnv``).  ``None`` keeps the pre-r14 graph;
    an all-zero array reproduces the pure-protocol trajectory BITWISE
    (the sign-of-zero-safe select lives in ``_physics_step_core``).

    Plain (un-jitted): callers own the jit/vmap/scan composition.
    Returns ``(state, telemetry-or-None)`` — telemetry gated on
    ``cfg.telemetry.enabled`` (the r10 static gate).

    ``return_derived`` (r18): additionally hand back the tick's
    ephemeral formation-derived ``(target, has_target)`` columns —
    the env facade reuses them for its observation pass instead of
    re-deriving per step (``ops/physics._physics_step_core``;
    the values are position-independent, so post-physics they are
    the columns a re-derivation would compute, bitwise).
    """
    state = _protocol_steps(state, cfg, sort_in_tick=False,
                            params=params)
    from ..ops.physics import _physics_step_core

    if return_derived:
        out, _, telem, derived = _physics_step_core(
            state, obstacles, cfg, None, None, params=params,
            extra_force=extra_force, return_derived=True,
        )
        return out, telem, derived
    out, _, telem = _physics_step_core(
        state, obstacles, cfg, None, None, params=params,
        extra_force=extra_force,
    )
    return out, telem


@watched("swarm-rollout")
@partial(
    jax.jit,
    static_argnames=(
        "cfg", "n_steps", "record", "return_plan", "telemetry",
    ),
)
def _swarm_rollout_impl(
    state: SwarmState,
    obstacles: Optional[jax.Array],
    cfg: SwarmConfig,
    n_steps: int,
    record: bool = False,
    return_plan: bool = False,
    telemetry: bool = False,
) -> Union[SwarmState, Tuple[SwarmState, jax.Array]]:
    """``n_steps`` ticks under one ``lax.scan`` — the as-fast-as-possible
    mode; XLA fuses each tick into a handful of kernels.

    ``record=True`` additionally returns the ``[n_steps, N, D]`` position
    trajectory IN AGENT-ID ORDER (the whole-history upgrade of the
    reference's per-tick pose log, agent.py:180-181).  Recording under
    the Morton re-sort is safe: each frame is unscrambled by scattering
    rows to their ``agent_id`` slots before stacking.

    Verlet amortization (r9): with ``separation_mode='hashgrid'`` and
    ``hashgrid_skin > 0`` the scan carry is ``(state, plan)`` — ONE
    skin-inflated ``HashgridPlan`` seeded by ``build_tick_plan`` and
    reused across ticks, rebuilt inside the tick only when
    ``refresh_plan``'s displacement/alive/ceiling triggers fire (or,
    with ``cfg.hashgrid_partial_refresh``, partially repaired by the
    r22 locality-aware ``refresh_plan_partial``).  The per-tick
    bin+sort (the r8 structural floor) becomes a per-rebuild cost;
    detection stays exact (ops/hashgrid_plan.py module doc).
    ``return_plan=True`` appends the final plan to the result — its
    ``rebuilds``/``cells_rebuilt``/``age`` counters are the observed
    full-rebuild rate and refreshed-row total the benches report
    (``None`` outside the plan-carry regime).

    Flight recorder (r10): with ``telemetry=True`` (or
    ``cfg.telemetry.enabled``) each tick's fixed-shape
    ``TickTelemetry`` rides the scan as stacked ``ys`` — on-device,
    zero host syncs, and provably non-perturbing (the carried state
    computation is untouched; tests/test_telemetry.py pins bitwise
    trajectory equality).  The stacked record is appended to the
    result AFTER the trajectory and BEFORE the plan:
    ``state`` -> ``(state, telem)``; with ``record``,
    ``(state, traj, telem)``; ``return_plan`` still appends last.
    ``n_steps == 0`` yields ``telem = None``.
    """
    telem_on = telemetry or cfg.telemetry.enabled
    if telem_on and not cfg.telemetry.enabled:
        cfg = cfg.replace(telemetry=TELEMETRY_ON)

    def compose(state, traj, telem, plan):
        out = (state, traj) if record else state
        if telem_on:
            if not n_steps:
                # n_steps == 0 yields None on EVERY path: the scan
                # paths would otherwise hand back a [0]-leaved record
                # while the chunked path has nothing to concatenate.
                telem = None
            out = out + (telem,) if record else (out, telem)
        return (out, plan) if return_plan else out

    plan_carried = (
        cfg.separation_mode == "hashgrid" and cfg.hashgrid_skin > 0
    )
    if plan_carried:
        plan = build_tick_plan(state, cfg)

        def pbody(carry, _):
            s, p = carry
            s, p, telem = _swarm_tick_plan(s, obstacles, cfg, p)
            return (s, p), ((s.pos if record else None), telem)

        (state, plan), (traj, telem) = jax.lax.scan(
            pbody, (state, plan), None, length=n_steps
        )
        return compose(state, traj, telem, plan)

    permuting = cfg.separation_mode == "window" and cfg.sort_every > 1

    def body(s, _):
        # The chunked path below owns the re-sort cadence, so the tick
        # runs cond-free (the conditional alone measured ~26 ms/tick
        # at 1M — see _swarm_tick_impl's docstring).
        telem = None
        if telem_on:
            s, telem = swarm_tick(
                s, obstacles, cfg, sort_in_tick=not permuting,
                telemetry=True,
            )
        else:
            s = swarm_tick(s, obstacles, cfg, sort_in_tick=not permuting)
        frame = None
        if record:
            # Unscramble to id order only when slots can actually move;
            # otherwise agent_id == arange and the scatter is waste.
            frame = (
                jnp.zeros_like(s.pos).at[s.agent_id].set(s.pos)
                if permuting
                else s.pos
            )
        return s, (frame, telem)

    if not permuting:
        state, (traj, telem) = jax.lax.scan(
            body, state, None, length=n_steps
        )
        return compose(state, traj, telem, None)

    # Window mode with a sort cadence: scan CHUNKS of sort_every ticks,
    # each chunk opening with one UNCONDITIONAL full-state variadic
    # sort (state.sort_agents_by_key — a comparison network, no
    # gathers).  Same staleness bound as the old in-tick cadence
    # (ordering is <= sort_every ticks stale), with zero conditionals
    # in the hot graph.  The entry sort also covers states produced
    # under a different config (or hand-built mid-cadence).
    chunk = cfg.sort_every

    def sorted_chunk(s, length):
        s = sort_agents_by_key(
            s, _morton_keys(s.pos, cfg.grid_cell)
        )
        return jax.lax.scan(body, s, None, length=length)

    n_chunks, rem = divmod(n_steps, chunk)
    frames = []
    telems = []
    if n_chunks:
        def chunk_body(s, _):
            s, ys = sorted_chunk(s, chunk)
            return s, ys

        state, (fr, tl) = jax.lax.scan(
            chunk_body, state, None, length=n_chunks
        )
        if record:
            frames.append(fr.reshape((n_chunks * chunk,) + fr.shape[2:]))
        if telem_on:
            # [n_chunks, chunk] leaves -> [n_chunks * chunk]
            telems.append(jax.tree_util.tree_map(
                lambda x: x.reshape((n_chunks * chunk,) + x.shape[2:]),
                tl,
            ))
    if rem:
        state, (fr, tl) = sorted_chunk(state, rem)
        if record:
            frames.append(fr)
        if telem_on:
            telems.append(tl)
    if record:
        traj = (
            jnp.concatenate(frames, axis=0)
            if frames
            else jnp.zeros((0,) + state.pos.shape, state.pos.dtype)
        )
    else:
        traj = None
    telem = None
    if telem_on and telems:
        from ..utils.telemetry import concat_telemetry

        telem = concat_telemetry(telems)
    return compose(state, traj, telem, None)


def _swarm_tick_spatial(
    state: SwarmState,
    obstacles: Optional[jax.Array],
    cfg: SwarmConfig,
    carry,
    spec,
    mesh,
):
    """The spatially-sharded tick (r12): same protocol prefix — the
    coordination/allocation reductions stay the existing cross-shard
    collectives GSPMD lowers them to — then physics off the per-tile
    halo'd Verlet plans (``ops/physics.physics_step_spatial``).
    Plain (un-jitted): it only runs inside the spatial rollout scan."""
    state = _protocol_steps(state, cfg, sort_in_tick=False)
    return physics_step_spatial(state, obstacles, cfg, carry, spec,
                                mesh)


@watched("swarm-rollout-spatial")
@partial(
    jax.jit,
    static_argnames=(
        "cfg", "n_steps", "mesh", "spatial", "record", "return_plan",
        "telemetry",
    ),
)
def _swarm_rollout_spatial_impl(
    state: SwarmState,
    obstacles: Optional[jax.Array],
    cfg: SwarmConfig,
    n_steps: int,
    mesh,
    spatial,
    record: bool = False,
    return_plan: bool = False,
    telemetry: bool = False,
    carry=None,
):
    """``n_steps`` spatially-sharded ticks under one ``lax.scan`` —
    the mesh-native rollout (r12, ROADMAP item 1).  ``state`` must be
    the tiled layout from ``parallel/spatial.spatial_shard_swarm``
    and ``spatial`` its :class:`~..parallel.spatial.SpatialSpec`; the
    scan carry is ``(state, SpatialCarry)`` — per-tile halo membership
    + per-tile Verlet plans, seeded by ``build_tick_plan_spatial`` and
    rebuilt inside the tick under the mesh-OR'd r9 triggers.

    Result composition mirrors ``_swarm_rollout_impl``: ``record``
    returns id-ordered ``[n_steps, n_slots, D]`` frames (padding slots
    ride as zero rows past the real swarm), ``telemetry`` appends the
    stacked recorder ys (residency counters filled from real per-tile
    live counts), ``return_plan`` appends the final
    ``SpatialCarry`` — its per-tile ``plan.rebuilds``/``escapes``/
    ``halo_overflow`` are the sharded-tick observability surface.

    ``carry`` (r18, the jumbo serve rung): an existing
    :class:`~..parallel.spatial.SpatialCarry` to resume from instead
    of seeding a fresh one — k carry-threaded segments are then the
    SAME tick sequence as one k*seg-tick rollout (no re-seed, no
    trigger reset), which is what makes the streaming service's
    segmented jumbo rollouts bitwise-equal to the one-shot spatial
    rollout (pinned in tests/test_serve_2d.py).  Pair it with
    ``return_plan=True`` to get the advanced carry back out."""
    telem_on = telemetry or cfg.telemetry.enabled
    if telem_on and not cfg.telemetry.enabled:
        cfg = cfg.replace(telemetry=TELEMETRY_ON)
    carry0 = (
        build_tick_plan_spatial(state, cfg, spatial, mesh)
        if carry is None else carry
    )

    def body(carry, _):
        s, c = carry
        s, c, telem = _swarm_tick_spatial(
            s, obstacles, cfg, c, spatial, mesh
        )
        frame = None
        if record:
            # Tiled slots are not id-ordered: unscramble like the
            # window mode does (ids are unique over the padded slots).
            frame = jnp.zeros_like(s.pos).at[s.agent_id].set(s.pos)
        return (s, c), (frame, telem)

    (state, carry), (traj, telem) = jax.lax.scan(
        body, (state, carry0), None, length=n_steps
    )
    out = (state, traj) if record else state
    if telem_on:
        if not n_steps:
            telem = None
        out = out + (telem,) if record else (out, telem)
    return (out, carry) if return_plan else out


def swarm_rollout(
    state: SwarmState,
    obstacles: Optional[jax.Array],
    cfg: SwarmConfig,
    n_steps: int,
    record: bool = False,
    return_plan: bool = False,
    telemetry: bool = False,
    mesh=None,
    spatial=None,
    carry=None,
) -> Union[SwarmState, Tuple[SwarmState, jax.Array]]:
    """``n_steps`` ticks under one ``lax.scan`` — ``_swarm_rollout_impl``
    behind the eager multi-device hash-grid guard (see
    ``_hashgrid_multidevice_cfg``; a no-op under trace and for
    single-device swarms).  ``return_plan``: also return the final
    carried Verlet plan (rebuild-rate observability; ``None`` unless
    ``separation_mode='hashgrid'`` with ``hashgrid_skin > 0``).
    ``telemetry``: enable the in-scan flight recorder for this rollout
    — the stacked per-tick ``TickTelemetry`` joins the result (see
    ``_swarm_rollout_impl``; ``utils/telemetry.summarize_telemetry``
    reduces it to a JSON-safe dict).

    ``mesh`` + ``spatial`` (r12): run the SPATIALLY-SHARDED tick —
    one swarm domain-decomposed across the mesh's tile axis with halo
    exchange at strip boundaries (``parallel/spatial.py``; ``state``
    must come from ``spatial_shard_swarm``, which also returns the
    ``spatial`` spec).  ``return_plan`` then appends the final
    ``SpatialCarry`` instead of a single plan; ``carry`` (r18) resumes
    from an existing ``SpatialCarry`` — the segmented-serving hook
    (see ``_swarm_rollout_spatial_impl``)."""
    if mesh is not None:
        if spatial is None:
            raise ValueError(
                "swarm_rollout(mesh=...) runs the spatially-sharded "
                "tick and needs its SpatialSpec: pass spatial= (both "
                "come from parallel.spatial.spatial_shard_swarm)"
            )
        return _swarm_rollout_spatial_impl(
            state, obstacles, cfg, n_steps, mesh, spatial,
            record, return_plan, telemetry, carry,
        )
    if carry is not None:
        raise ValueError(
            "swarm_rollout(carry=...) resumes a SpatialCarry and only "
            "makes sense with mesh=/spatial= (the spatially-sharded "
            "rollout); the single-device plan carry is internal"
        )
    if spatial is not None:
        # The inverse half-call must not silently run the
        # single-device path on a tiled state (return_plan would
        # then hand back a HashgridPlan where the caller expects a
        # SpatialCarry — an AttributeError far from the cause).
        raise ValueError(
            "swarm_rollout(spatial=...) needs the mesh too: pass "
            "mesh= (the one spatial_shard_swarm committed the state "
            "over)"
        )
    return _swarm_rollout_impl(
        state, obstacles, _hashgrid_multidevice_cfg(state, cfg),
        n_steps, record, return_plan, telemetry,
    )


class VectorSwarm(CheckpointMixin):
    """User-facing handle: owns a SwarmState + SwarmConfig.

    Replaces the reference's one-process-per-agent CLI deployment
    (agent.py:349-360) with one object for the entire swarm.  The per-agent
    API surface (set_target / update_sensors / tasks) maps to whole-swarm
    array setters.
    """

    def __init__(
        self,
        n_agents: int,
        dim: int = 2,
        n_tasks: int = 0,
        n_caps: int = 1,
        config: Optional[SwarmConfig] = None,
        seed: int = 0,
        spread: float = 0.0,
    ):
        self.config = config or DEFAULT_CONFIG
        self.state = make_swarm(
            n_agents, dim=dim, n_tasks=n_tasks, n_caps=n_caps, seed=seed,
            spread=spread, dtype=jnp.dtype(self.config.dtype),
        )
        self.obstacles: Optional[jax.Array] = _NO_OBSTACLES

    # --- world injection (reference: set_target / update_sensors) --------
    def set_target(self, target, agents=None) -> None:
        """Set a nav target for all agents (or a subset) — agent.py:56-57.

        ``agents`` are agent IDS, matched by value (like kill/revive) —
        array slots are internal once the Morton re-sort is active
        (separation_mode="window", sort_every > 1).  With the default
        ordering ids and slots coincide, so this is backward-compatible.
        """
        t = jnp.broadcast_to(
            jnp.asarray(target, self.state.pos.dtype), self.state.pos.shape
        )
        if agents is None:
            self.state = self.state.replace(
                target=t, has_target=jnp.ones_like(self.state.has_target)
            )
        else:
            ids = jnp.asarray(agents, jnp.int32).reshape(-1)
            sel = jnp.any(
                self.state.agent_id[:, None] == ids[None, :], axis=1
            )
            self.state = self.state.replace(
                target=jnp.where(sel[:, None], t, self.state.target),
                has_target=self.state.has_target | sel,
            )

    def set_obstacles(self, obstacles) -> None:
        """obstacles: [O, D+1] rows of (center..., radius) — agent.py:59-64."""
        self.obstacles = (
            None
            if obstacles is None
            else jnp.asarray(obstacles, self.state.pos.dtype)
        )

    def add_tasks(self, task_pos, task_cap=None) -> None:
        self.state = with_tasks(self.state, task_pos, task_cap)

    def set_capabilities(self, caps) -> None:
        """caps: [N, C] bool one-hot (replaces string lists, agent.py:52)."""
        self.state = self.state.replace(caps=jnp.asarray(caps, bool))

    # --- stepping --------------------------------------------------------
    def step(self, n: int = 1, record: bool = False):
        """Advance ``n`` ticks.  Returns the new state — or, with
        ``record=True`` (any n, including 1), the ``[n, N, D]`` position
        trajectory in agent-id order instead (state is on ``.state``)."""
        if record:
            self.state, traj = swarm_rollout(
                self.state, self.obstacles, self.config, n, record=True
            )
            return traj
        if n == 1:
            self.state = swarm_tick(self.state, self.obstacles, self.config)
        else:
            self.state = swarm_rollout(
                self.state, self.obstacles, self.config, n
            )
        return self.state

    def run_realtime(self, n_steps: int) -> SwarmState:
        """Wall-clock-paced loop at ``tick_rate_hz`` (agent.py:67-81)."""
        period = 1.0 / self.config.tick_rate_hz
        for _ in range(n_steps):
            start = time.time()
            self.state = swarm_tick(self.state, self.obstacles, self.config)
            jax.block_until_ready(self.state.pos)
            leftover = period - (time.time() - start)
            if leftover > 0:
                time.sleep(leftover)
        return self.state

    # checkpoint/resume (absent in the reference, SURVEY.md §5) comes
    # from CheckpointMixin.

    # --- introspection / fault injection ---------------------------------
    def leader(self):
        lid, exists = current_leader(self.state)
        return (int(lid), bool(exists))

    def task_statuses(self):
        return task_status_view(self.state)

    def kill(self, ids) -> None:
        self.state = kill(self.state, ids)

    def revive(self, ids) -> None:
        self.state = revive(self.state, ids)
