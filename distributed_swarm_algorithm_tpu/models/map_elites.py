"""User-facing MAP-Elites model."""

from __future__ import annotations

from typing import Callable, Optional, Union

import jax
import numpy as np

from ..ops import map_elites as _k
from ..ops.objectives import get_objective
from ._checkpoint import CheckpointMixin


class MAPElites(CheckpointMixin):
    """MAP-Elites quality-diversity search (Mouret & Clune 2015).

    ``descriptor`` maps solutions [K, D] -> behaviors [K, B] expected in
    [lo, hi]; the archive is a ``bins**B`` grid keeping the best
    solution per behavior cell.  The default descriptor is the first
    two solution coordinates normalized to [0, 1].

    >>> opt = MAPElites("rastrigin", dim=6, bins=16, seed=0)
    >>> opt.run(200)
    >>> opt.coverage, opt.best  # doctest: +SKIP
    """

    def __init__(
        self,
        objective: Union[str, Callable],
        dim: int,
        bins: int = 16,
        descriptor: Optional[Callable] = None,
        behavior_dims: int = 2,
        half_width: Optional[float] = None,
        lo: float = 0.0,
        hi: float = 1.0,
        batch: int = 256,
        sigma_mut: float = _k.SIGMA_MUT,
        n_init: int = 256,
        seed: int = 0,
        dtype=None,
    ):
        if isinstance(objective, str):
            fn, default_hw = get_objective(objective)
        else:
            fn, default_hw = objective, 5.12
        self.objective = fn
        self.half_width = float(
            half_width if half_width is not None else default_hw
        )
        if bins < 1:
            raise ValueError(f"bins ({bins}) must be >= 1")
        if descriptor is None:
            if dim < behavior_dims:
                raise ValueError(
                    f"default descriptor needs dim >= {behavior_dims}"
                )
            hw = self.half_width
            nb = behavior_dims

            def descriptor(x):
                return (x[:, :nb] + hw) / (2.0 * hw)

        self.descriptor = descriptor
        self.bins = int(bins)
        self.behavior_dims = int(behavior_dims)
        self.lo, self.hi = float(lo), float(hi)
        self.batch = int(batch)
        self.sigma_mut = float(sigma_mut)
        kwargs = {} if dtype is None else {"dtype": dtype}
        self.state = _k.me_init(
            fn, self.descriptor, dim, self.bins, self.behavior_dims,
            self.half_width, self.lo, self.hi, n_init=n_init, seed=seed,
            **kwargs,
        )

    def step(self) -> _k.MapElitesState:
        self.state = _k.me_step(
            self.state, self.objective, self.descriptor, self.bins,
            self.half_width, self.lo, self.hi, self.batch,
            self.sigma_mut,
        )
        return self.state

    def run(self, n_steps: int) -> _k.MapElitesState:
        self.state = _k.me_run(
            self.state, self.objective, self.descriptor, n_steps,
            self.bins, self.half_width, self.lo, self.hi, self.batch,
            self.sigma_mut,
        )
        # Async dispatch (r4): see PSO.run's rationale.  Reading any
        # state field synchronizes.
        return self.state

    @property
    def best(self) -> float:
        return float(jax.numpy.min(self.state.archive_fit))

    @property
    def coverage(self) -> float:
        return float(_k.coverage(self.state))

    def qd_score(self, offset: float = 0.0) -> float:
        return float(_k.qd_score(self.state, offset))

    def elites(self) -> tuple:
        """(positions [K, D], fitnesses [K]) of the filled cells."""
        fit = np.asarray(self.state.archive_fit)
        mask = np.isfinite(fit)
        return np.asarray(self.state.archive_pos)[mask], fit[mask]
