"""User-facing CMA-ES optimizer model.

Same shape as :class:`~distributed_swarm_algorithm_tpu.models.pso.PSO`:
a thin stateful wrapper over the pure kernels in ``ops/cmaes.py``.
"""

from __future__ import annotations

from typing import Callable, Optional, Union

import jax
import jax.numpy as jnp

from ..ops import cmaes as _k
from ..ops.objectives import get_objective
from ._checkpoint import CheckpointMixin


class CMAES(CheckpointMixin):
    """Covariance-matrix-adaptation evolution strategy.

    Unlike PSO/DE, ``n`` here is the per-generation sample count
    (lambda); Hansen's ``4 + 3 ln D`` default applies when omitted.
    ``half_width`` (resolved from the objective registry for named
    objectives) box-projects samples before evaluation.

    >>> opt = CMAES("rosenbrock", dim=10, seed=0)
    >>> opt.run(400)
    >>> float(opt.state.best_fit)  # doctest: +SKIP
    """

    def __init__(
        self,
        objective: Union[str, Callable],
        dim: int,
        n: Optional[int] = None,
        half_width: Optional[float] = None,
        sigma: Optional[float] = None,
        mean: Optional[jax.Array] = None,
        seed: int = 0,
    ):
        if isinstance(objective, str):
            fn, default_hw = get_objective(objective)
        else:
            fn, default_hw = objective, None
        self.objective = fn
        self.half_width = (
            float(half_width)
            if half_width is not None
            else (float(default_hw) if default_hw is not None else None)
        )
        self.params = _k.cmaes_params(dim, popsize=n)
        if sigma is None:
            # Hansen's rule of thumb: ~0.3x the search-domain width.
            sigma = (
                0.3 * 2.0 * self.half_width
                if self.half_width is not None
                else 0.3
            )
        if mean is None and self.half_width is not None:
            key = jax.random.PRNGKey(seed ^ 0xC3A)
            mean = jax.random.uniform(
                key, (dim,), jnp.float32,
                minval=-0.5 * self.half_width,
                maxval=0.5 * self.half_width,
            )
        self.state = _k.cmaes_init(dim, sigma=float(sigma), mean=mean,
                                   seed=seed)

    def step(self) -> _k.CMAESState:
        self.state = _k.cmaes_step(
            self.state, self.objective, self.params, self.half_width
        )
        return self.state

    def run(self, n_steps: int) -> _k.CMAESState:
        self.state = _k.cmaes_run(
            self.state, self.objective, self.params, n_steps,
            self.half_width,
        )
        # Async dispatch (r4): see PSO.run's rationale.  Reading any
        # state field synchronizes.
        return self.state

    @property
    def best(self) -> float:
        return float(self.state.best_fit)
