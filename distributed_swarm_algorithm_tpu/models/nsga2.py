"""User-facing NSGA-II multi-objective model."""

from __future__ import annotations

from typing import Callable, Union

import jax
import numpy as np

from ..ops import nsga2 as _k
from ._checkpoint import CheckpointMixin


class NSGA2(CheckpointMixin):
    """NSGA-II (Deb et al. 2002): elitist multi-objective search.

    ``objective`` maps [K, D] -> [K, M] batched (minimization), or pass
    a named ZDT problem ("zdt1" | "zdt2" | "zdt3", domain [0,1]).

    ``inequalities``/``equalities`` (batched [K, D] -> [K] functions;
    feasible when g <= 0 / h == 0) switch ranking to Deb's constrained
    domination: feasible beats infeasible, lower total violation beats
    higher, Pareto dominance decides among the feasible.

    >>> opt = NSGA2("zdt1", n=128, dim=12, seed=0)
    >>> opt.run(150)
    >>> front = opt.pareto_front()  # doctest: +SKIP
    """

    def __init__(
        self,
        objective: Union[str, Callable],
        n: int,
        dim: int,
        lb: float = 0.0,
        ub: float = 1.0,
        eta_c: float = _k.ETA_C,
        eta_m: float = _k.ETA_M,
        p_cross: float = _k.P_CROSS,
        p_mut: float | None = None,
        inequalities=(),
        equalities=(),
        seed: int = 0,
        dtype=None,
    ):
        if isinstance(objective, str):
            try:
                fn = _k.MOO_PROBLEMS[objective]
            except KeyError:
                raise ValueError(
                    f"unknown multi-objective problem {objective!r}; "
                    f"have {sorted(_k.MOO_PROBLEMS)}"
                ) from None
            self.problem_name: str | None = objective
        else:
            fn = objective
            self.problem_name = None
        if ub <= lb:
            raise ValueError(f"ub ({ub}) must be > lb ({lb})")
        self.objective = fn
        self.lb, self.ub = float(lb), float(ub)
        self.eta_c, self.eta_m = float(eta_c), float(eta_m)
        self.p_cross = float(p_cross)
        self.p_mut = None if p_mut is None else float(p_mut)
        if inequalities or equalities:
            from ..ops.constraints import violation as _violation

            ineqs, eqs = tuple(inequalities), tuple(equalities)

            def violation_fn(x):
                return _violation(x, ineqs, eqs)

            self.violation_fn = violation_fn
        else:
            self.violation_fn = None
        kwargs = {} if dtype is None else {"dtype": dtype}
        self.state = _k.nsga2_init(
            fn, n, dim, self.lb, self.ub, seed=seed,
            violation_fn=self.violation_fn, **kwargs
        )

    def load(self, path: str) -> None:
        """Restore a checkpoint; pre-``viol`` checkpoints (saved before
        constrained-domination support, 6 leaves) are migrated by
        positional mapping with a zero-filled violation vector."""
        from ..utils import checkpoint as _ckpt

        import jax.numpy as jnp
        import numpy as np

        if _ckpt.npz_layout(path) != ("v1", 6):
            # Anything but the legacy pre-viol layout (orbax dirs,
            # schema-v2 files, positional files of the current size):
            # the generic restore handles it — and its named errors
            # must propagate, not be swallowed into the migration.
            self.state = _ckpt.restore(path, self.state)
            return
        # Legacy pre-viol layout: 6 positional leaves.
        data = np.load(path if path.endswith(".npz") else path + ".npz")
        legacy = [jnp.asarray(data[f"leaf_{i}"]) for i in range(6)]
        pos, objs, rank, crowd, key, iteration = legacy
        self.state = self.state.replace(
            pos=pos, objs=objs, rank=rank, crowd=crowd, key=key,
            iteration=iteration,
            viol=jnp.zeros(objs.shape[:1], objs.dtype),
        )

    def step(self) -> _k.NSGA2State:
        self.state = _k.nsga2_step(
            self.state, self.objective, self.lb, self.ub, self.eta_c,
            self.eta_m, self.p_cross, self.p_mut, self.violation_fn,
        )
        return self.state

    def run(self, n_steps: int) -> _k.NSGA2State:
        self.state = _k.nsga2_run(
            self.state, self.objective, n_steps, self.lb, self.ub,
            self.eta_c, self.eta_m, self.p_cross, self.p_mut,
            self.violation_fn,
        )
        # Async dispatch (r4): see PSO.run's rationale.  Reading any
        # state field synchronizes.
        return self.state

    def igd(self, reference=None, k: int = 256) -> float:
        """Inverted generational distance (lower = better convergence +
        coverage).  ``reference`` is an explicit [R, M] reference front;
        omitted, the analytic front of the named problem is used
        (available for zdt1/zdt2)."""
        import jax.numpy as jnp

        if reference is None:
            try:
                reference = _k.MOO_FRONTS[self.problem_name](k)
            except KeyError:
                raise ValueError(
                    "no analytic front for this problem; pass an "
                    "explicit reference ([R, M] array)"
                ) from None
        return float(
            _k.igd(self.state.objs, jnp.asarray(reference),
                   self.state.viol)
        )

    def pareto_front(self) -> np.ndarray:
        """[K, M] objective vectors of the current rank-0 individuals."""
        mask = np.asarray(self.state.rank) == 0
        return np.asarray(self.state.objs)[mask]

    def hypervolume(self, ref) -> float:
        """2-D hypervolume of the current population w.r.t. ``ref``
        (constraint-aware: infeasible individuals contribute no area)."""
        import jax.numpy as jnp

        m = self.state.objs.shape[1]
        if m != 2:
            raise ValueError(
                f"hypervolume() supports 2 objectives, problem has {m}"
            )
        return float(
            _k.hypervolume_2d(
                self.state.objs, jnp.asarray(ref), self.state.viol
            )
        )
