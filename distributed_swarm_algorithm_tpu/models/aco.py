"""User-facing ACO (ant colony) TSP solver."""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..ops import aco as _k
from ._checkpoint import CheckpointMixin


class ACO(CheckpointMixin):
    """Ant-colony TSP solver over a coordinate set or distance matrix.

    The whole colony steps as one jitted kernel (ops/aco.py): per
    construction step every ant samples its next city simultaneously via
    masked Gumbel-argmax over pheromone × heuristic scores.

    ``use_pallas=True`` (auto on TPU) swaps construction for the fused
    whole-tour VMEM kernel (ops/pallas/aco_fused.py): logits resident in
    VMEM for all C-1 steps, row-select as MXU matmuls, on-chip Gumbel —
    measured 16x the portable iteration at C=256/A=1024 on v5e.

    >>> import numpy as np
    >>> pts = np.random.default_rng(0).uniform(size=(24, 2))
    >>> colony = ACO(coords=pts, n_ants=64, seed=0)
    >>> colony.run(50)
    >>> colony.best_length  # doctest: +SKIP
    """

    def __init__(
        self,
        coords=None,
        dist=None,
        n_ants: int = 64,
        alpha: float = 1.0,
        beta: float = 2.0,
        rho: float = 0.1,
        q0: float = 0.0,
        elite: float = 0.0,
        seed: int = 0,
        tau0: Optional[float] = None,
        use_pallas: Optional[bool] = None,
    ):
        if (coords is None) == (dist is None):
            raise ValueError("pass exactly one of coords= or dist=")
        if dist is None:
            dist = _k.coords_to_dist(jnp.asarray(coords, jnp.float32))
        else:
            dist = jnp.asarray(dist, jnp.float32)
            if dist.ndim != 2 or dist.shape[0] != dist.shape[1]:
                raise ValueError(f"dist must be square, got {dist.shape}")
        self.n_ants = int(n_ants)
        self.alpha, self.beta = float(alpha), float(beta)
        self.rho, self.q0, self.elite = float(rho), float(q0), float(elite)
        if use_pallas is None:
            from ..utils.platform import on_tpu

            use_pallas = on_tpu()
        self.use_pallas = bool(use_pallas)
        self.state = _k.aco_init(dist, seed=seed, tau0=tau0)

    def _fused_kwargs(self):
        # Off-TPU the fused path runs interpret-mode with host RNG
        # (pltpu's PRNG has no interpret rule) — the family pattern
        # every fused model follows (cf. models/pso.py).
        from ..utils.platform import on_tpu

        tpu = on_tpu()
        return {"rng": "tpu" if tpu else "host", "interpret": not tpu}

    def step(self) -> _k.ACOState:
        if self.use_pallas:
            from ..ops.pallas.aco_fused import fused_aco_step

            self.state = fused_aco_step(
                self.state, self.n_ants, self.alpha, self.beta,
                self.rho, self.q0, self.elite, **self._fused_kwargs(),
            )
        else:
            self.state = _k.aco_step(
                self.state, self.n_ants, self.alpha, self.beta, self.rho,
                self.q0, self.elite,
            )
        return self.state

    def run(self, n_steps: int) -> _k.ACOState:
        if self.use_pallas:
            from ..ops.pallas.aco_fused import fused_aco_run

            self.state = fused_aco_run(
                self.state, n_steps, self.n_ants, self.alpha, self.beta,
                self.rho, self.q0, self.elite, **self._fused_kwargs(),
            )
        else:
            self.state = _k.aco_run(
                self.state, n_steps, self.n_ants, self.alpha, self.beta,
                self.rho, self.q0, self.elite,
            )
        # Async dispatch (r4): see PSO.run's rationale.  Reading any
        # state field synchronizes.
        return self.state

    @property
    def best_length(self) -> float:
        return float(self.state.best_len)

    @property
    def best_tour(self) -> np.ndarray:
        return np.asarray(self.state.best_tour)
