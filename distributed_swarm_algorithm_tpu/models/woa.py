"""User-facing whale-optimization model."""

from __future__ import annotations

from typing import Callable, Optional, Union

import jax

from ..ops import woa as _k
from ..ops.objectives import get_objective
from ..ops.pallas import woa_fused as _wf
from ..utils.platform import on_tpu as _on_tpu
from ._checkpoint import CheckpointMixin


class WOA(CheckpointMixin):
    """Whale optimization algorithm (Mirjalili & Lewis 2016).

    ``t_max`` sets the exploration schedule length (a: 2 → 0); the pod
    exploits fully once ``t_max`` iterations have elapsed.

    Two compute paths with the same WOAState contract: portable jit'd
    JAX (exact iid random-peer draws — row-gather-bound on TPU at large
    N) and the fused Pallas kernel (ops/pallas/woa_fused.py, rotational
    random peer + per-block best snapshot) — auto-selected on TPU for
    named objectives in float32, or forced with ``use_pallas=True``.

    >>> opt = WOA("sphere", n=64, dim=6, t_max=200, seed=0)
    >>> opt.run(200)
    >>> opt.best  # doctest: +SKIP
    """

    def __init__(
        self,
        objective: Union[str, Callable],
        n: int,
        dim: int,
        half_width: Optional[float] = None,
        t_max: int = 500,
        spiral_b: float = _k.SPIRAL_B,
        seed: int = 0,
        dtype=None,
        use_pallas: Optional[bool] = None,
        steps_per_kernel: int = 8,
    ):
        if isinstance(objective, str):
            fn, default_hw = get_objective(objective)
            self.objective_name: Optional[str] = objective
        else:
            fn, default_hw = objective, 5.12
            self.objective_name = None
        self.objective = fn
        self.half_width = float(
            half_width if half_width is not None else default_hw
        )
        if t_max < 1:
            raise ValueError(f"t_max must be >= 1, got {t_max}")
        self.t_max = int(t_max)
        self.spiral_b = float(spiral_b)
        self.steps_per_kernel = int(steps_per_kernel)
        kwargs = {} if dtype is None else {"dtype": dtype}
        self.state = _k.woa_init(
            fn, n, dim, self.half_width, seed=seed, **kwargs
        )

        supported = (
            self.objective_name is not None
            and _wf.woa_pallas_supported(
                self.objective_name or "", self.state.pos.dtype,
                self.state.pos.shape[-1],
            )
        )
        if use_pallas is None:
            self.use_pallas = supported and _on_tpu()
        elif use_pallas and not supported:
            raise ValueError(
                "use_pallas=True needs a named objective from "
                "ops.objectives and float32 state"
            )
        else:
            self.use_pallas = bool(use_pallas)

    def step(self) -> _k.WOAState:
        self.state = _k.woa_step(
            self.state, self.objective, self.half_width, self.t_max,
            self.spiral_b,
        )
        return self.state

    def run(self, n_steps: int) -> _k.WOAState:
        if self.use_pallas:
            on_tpu = _on_tpu()
            self.state = _wf.fused_woa_run(
                self.state, self.objective_name, n_steps,
                self.half_width, self.t_max, self.spiral_b,
                rng="tpu" if on_tpu else "host",
                interpret=not on_tpu,
                steps_per_kernel=self.steps_per_kernel,
            )
        else:
            self.state = _k.woa_run(
                self.state, self.objective, n_steps, self.half_width,
                self.t_max, self.spiral_b,
            )
        # Async dispatch (r4): see PSO.run's rationale.  Reading any
        # state field synchronizes.
        return self.state

    @property
    def best(self) -> float:
        return float(self.state.best_fit)
