"""User-facing firefly-algorithm model."""

from __future__ import annotations

from typing import Callable, Optional, Union

import jax

from ..ops import firefly as _k
from ..ops.objectives import get_objective
from ..ops.pallas import firefly_fused as _ff
from ..utils.platform import on_tpu as _on_tpu
from ._checkpoint import CheckpointMixin


class Firefly(CheckpointMixin):
    """Firefly algorithm (all-pairs brightness attraction, Yang 2008).

    Synchronous generation-at-once variant (ops/firefly.py); the random
    walk scale ``alpha0`` decays by ``alpha_decay`` per iteration.

    Two compute paths with the same FireflyState contract and update
    rule: the portable XLA step materializes the [N, N] weight matrix
    (fast to ~16k, OOM beyond ~32k); the tiled Pallas path
    (ops/pallas/firefly_fused.py) streams interaction blocks through
    VMEM — modestly faster at 16k and the only option at 65k+.
    Auto-selected on TPU for n >= 8192; force with ``use_pallas``.

    >>> opt = Firefly("sphere", n=64, dim=4, seed=0)
    >>> opt.run(150)
    >>> opt.best  # doctest: +SKIP
    """

    def __init__(
        self,
        objective: Union[str, Callable],
        n: int,
        dim: int,
        half_width: Optional[float] = None,
        beta0: float = _k.BETA0,
        gamma: float = _k.GAMMA,
        alpha0: float = _k.ALPHA0,
        alpha_decay: float = _k.ALPHA_DECAY,
        seed: int = 0,
        dtype=None,
        use_pallas: Optional[bool] = None,
    ):
        if isinstance(objective, str):
            fn, default_hw = get_objective(objective)
        else:
            fn, default_hw = objective, 5.12
        self.objective = fn
        self.half_width = float(
            half_width if half_width is not None else default_hw
        )
        self.beta0 = float(beta0)
        self.gamma = float(gamma)
        self.alpha0 = float(alpha0)
        self.alpha_decay = float(alpha_decay)
        kwargs = {} if dtype is None else {"dtype": dtype}
        self.state = _k.firefly_init(
            fn, n, dim, self.half_width, seed=seed, **kwargs
        )
        # The tiled path works for any objective callable (the tail is
        # portable XLA); f32 only (the kernel accumulates in f32).
        import jax.numpy as jnp

        supported = self.state.pos.dtype == jnp.float32
        if use_pallas is None:
            self.use_pallas = supported and n >= 8192 and _on_tpu()
        elif use_pallas and not supported:
            raise ValueError("use_pallas=True needs float32 state")
        else:
            self.use_pallas = bool(use_pallas)

    def step(self) -> _k.FireflyState:
        self.state = _k.firefly_step(
            self.state, self.objective, self.half_width, self.beta0,
            self.gamma, self.alpha0, self.alpha_decay,
        )
        return self.state

    def run(self, n_steps: int) -> _k.FireflyState:
        if self.use_pallas:
            self.state = _ff.fused_firefly_run(
                self.state, self.objective, n_steps, self.half_width,
                self.beta0, self.gamma, self.alpha0, self.alpha_decay,
                interpret=not _on_tpu(),
            )
        else:
            self.state = _k.firefly_run(
                self.state, self.objective, n_steps, self.half_width,
                self.beta0, self.gamma, self.alpha0, self.alpha_decay,
            )
        # Async dispatch (r4): see PSO.run's rationale.  Reading any
        # state field synchronizes.
        return self.state

    @property
    def best(self) -> float:
        return float(self.state.best_fit)
