"""User-facing Harris-hawks model."""

from __future__ import annotations

from typing import Callable, Optional, Union

import jax

from ..ops import hho as _k
from ..ops.objectives import get_objective
from ..ops.pallas import hho_fused as _hf
from ..utils.platform import on_tpu as _on_tpu
from ._checkpoint import CheckpointMixin


class HarrisHawks(CheckpointMixin):
    """Harris hawks optimization (cooperative pursuit, Heidari 2019).

    The prey's decaying escape energy gates each hawk between
    exploration perches and four besiege strategies (soft/hard, with or
    without Lévy rapid dives).

    >>> opt = HarrisHawks("sphere", n=64, dim=6, seed=0)
    >>> opt.run(300)
    >>> opt.best  # doctest: +SKIP
    """

    def __init__(
        self,
        objective: Union[str, Callable],
        n: int,
        dim: int,
        half_width: Optional[float] = None,
        t_max: int = _k.T_MAX,
        levy_beta: float = _k.LEVY_BETA,
        seed: int = 0,
        dtype=None,
        use_pallas: Optional[bool] = None,
    ):
        if isinstance(objective, str):
            fn, default_hw = get_objective(objective)
            self.objective_name: Optional[str] = objective
        else:
            fn, default_hw = objective, 5.12
            self.objective_name = None
        self.objective = fn
        self.half_width = float(
            half_width if half_width is not None else default_hw
        )
        if t_max <= 0:
            raise ValueError(f"t_max ({t_max}) must be positive")
        self.t_max = int(t_max)
        self.levy_beta = float(levy_beta)
        kwargs = {} if dtype is None else {"dtype": dtype}
        self.state = _k.hho_init(
            fn, n, dim, self.half_width, seed=seed, **kwargs
        )

        supported = (
            n >= 512            # rotational peers need >= 4 lane tiles
            and self.objective_name is not None
            and _hf.hho_pallas_supported(
                self.objective_name or "", self.state.pos.dtype,
                self.state.pos.shape[-1],
            )
        )
        if use_pallas is None:
            self.use_pallas = supported and _on_tpu()
        elif use_pallas and not supported:
            raise ValueError(
                "use_pallas=True needs a named objective from "
                "ops.objectives, float32 state, and n >= 512"
            )
        else:
            self.use_pallas = bool(use_pallas)

    def step(self) -> _k.HHOState:
        self.state = _k.hho_step(
            self.state, self.objective, self.half_width, self.t_max,
            self.levy_beta,
        )
        return self.state

    def run(self, n_steps: int) -> _k.HHOState:
        if self.use_pallas:
            on_tpu = _on_tpu()
            self.state = _hf.fused_hho_run(
                self.state, self.objective_name, n_steps,
                self.half_width, self.t_max, self.levy_beta,
                rng="tpu" if on_tpu else "host",
                interpret=not on_tpu,
            )
        else:
            self.state = _k.hho_run(
                self.state, self.objective, n_steps, self.half_width,
                self.t_max, self.levy_beta,
            )
        # Async dispatch (r4): see PSO.run's rationale.  Reading any
        # state field synchronizes.
        return self.state

    @property
    def best(self) -> float:
        return float(self.state.best_fit)
