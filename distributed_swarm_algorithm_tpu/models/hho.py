"""User-facing Harris-hawks model."""

from __future__ import annotations

from typing import Callable, Optional, Union

import jax

from ..ops import hho as _k
from ..ops.objectives import get_objective
from ._checkpoint import CheckpointMixin


class HarrisHawks(CheckpointMixin):
    """Harris hawks optimization (cooperative pursuit, Heidari 2019).

    The prey's decaying escape energy gates each hawk between
    exploration perches and four besiege strategies (soft/hard, with or
    without Lévy rapid dives).

    >>> opt = HarrisHawks("sphere", n=64, dim=6, seed=0)
    >>> opt.run(300)
    >>> opt.best  # doctest: +SKIP
    """

    def __init__(
        self,
        objective: Union[str, Callable],
        n: int,
        dim: int,
        half_width: Optional[float] = None,
        t_max: int = _k.T_MAX,
        levy_beta: float = _k.LEVY_BETA,
        seed: int = 0,
        dtype=None,
    ):
        if isinstance(objective, str):
            fn, default_hw = get_objective(objective)
        else:
            fn, default_hw = objective, 5.12
        self.objective = fn
        self.half_width = float(
            half_width if half_width is not None else default_hw
        )
        if t_max <= 0:
            raise ValueError(f"t_max ({t_max}) must be positive")
        self.t_max = int(t_max)
        self.levy_beta = float(levy_beta)
        kwargs = {} if dtype is None else {"dtype": dtype}
        self.state = _k.hho_init(
            fn, n, dim, self.half_width, seed=seed, **kwargs
        )

    def step(self) -> _k.HHOState:
        self.state = _k.hho_step(
            self.state, self.objective, self.half_width, self.t_max,
            self.levy_beta,
        )
        return self.state

    def run(self, n_steps: int) -> _k.HHOState:
        self.state = _k.hho_run(
            self.state, self.objective, n_steps, self.half_width,
            self.t_max, self.levy_beta,
        )
        jax.block_until_ready(self.state.best_fit)
        return self.state

    @property
    def best(self) -> float:
        return float(self.state.best_fit)
