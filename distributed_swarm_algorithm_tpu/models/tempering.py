"""User-facing parallel-tempering model."""

from __future__ import annotations

from typing import Callable, Optional, Union

import jax

from ..ops import tempering as _k
from ..ops.objectives import get_objective
from ..ops.pallas import tempering_fused as _tf
from ..utils.platform import on_tpu as _on_tpu
from ._checkpoint import CheckpointMixin


class ParallelTempering(CheckpointMixin):
    """Parallel tempering (replica exchange): ``n`` Metropolis chains on
    a geometric temperature ladder, exchanging replicas with the
    detailed-balance probability every ``swap_every`` steps.

    Two compute paths with the same PTState contract: portable jit'd
    JAX (global XOR-parity exchange — 40.9M chain-steps/s at 1M on
    v5e) and the fused Pallas kernel (ops/pallas/tempering_fused.py:
    on-chip Box-Muller proposals, adjacent-lane exchange) —
    auto-selected on TPU for named objectives in float32 with
    n >= 128, or forced with ``use_pallas=True``.

    >>> opt = ParallelTempering("rastrigin", n=32, dim=6, seed=0)
    >>> opt.run(2000)
    >>> opt.best  # doctest: +SKIP
    """

    def __init__(
        self,
        objective: Union[str, Callable],
        n: int,
        dim: int,
        half_width: Optional[float] = None,
        t_min: float = _k.T_MIN,
        t_max: float = _k.T_MAX,
        sigma0: float = _k.SIGMA0,
        swap_every: int = _k.SWAP_EVERY,
        seed: int = 0,
        dtype=None,
        use_pallas: Optional[bool] = None,
    ):
        if isinstance(objective, str):
            fn, default_hw = get_objective(objective)
            self.objective_name: Optional[str] = objective
        else:
            fn, default_hw = objective, 5.12
            self.objective_name = None
        self.objective = fn
        self.half_width = float(
            half_width if half_width is not None else default_hw
        )
        if not 0 < t_min < t_max:
            raise ValueError(
                f"need 0 < t_min ({t_min}) < t_max ({t_max})"
            )
        if swap_every <= 0:
            raise ValueError(f"swap_every ({swap_every}) must be positive")
        self.sigma0 = float(sigma0)
        self.swap_every = int(swap_every)
        kwargs = {} if dtype is None else {"dtype": dtype}
        self.state = _k.pt_init(
            fn, n, dim, self.half_width, t_min=float(t_min),
            t_max=float(t_max), seed=seed, **kwargs
        )

        supported = (
            n >= 128            # one full lane tile
            and self.objective_name is not None
            and _tf.pt_pallas_supported(
                self.objective_name or "", self.state.pos.dtype,
                self.state.pos.shape[-1],
            )
        )
        if use_pallas is None:
            self.use_pallas = supported and _on_tpu()
        elif use_pallas and not supported:
            raise ValueError(
                "use_pallas=True needs a named objective from "
                "ops.objectives, float32 state, and n >= 128"
            )
        else:
            self.use_pallas = bool(use_pallas)

    def step(self) -> _k.PTState:
        self.state = _k.pt_step(
            self.state, self.objective, self.half_width, self.sigma0,
            self.swap_every,
        )
        return self.state

    def run(self, n_steps: int) -> _k.PTState:
        if self.use_pallas:
            on_tpu = _on_tpu()
            self.state = _tf.fused_pt_run(
                self.state, self.objective_name, n_steps,
                self.half_width, self.sigma0, self.swap_every,
                rng="tpu" if on_tpu else "host",
                interpret=not on_tpu,
            )
        else:
            self.state = _k.pt_run(
                self.state, self.objective, n_steps, self.half_width,
                self.sigma0, self.swap_every,
            )
        # Async dispatch (r4): see PSO.run's rationale.  Reading any
        # state field synchronizes.
        return self.state

    @property
    def best(self) -> float:
        return float(self.state.best_fit)
