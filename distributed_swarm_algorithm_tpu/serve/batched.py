"""Scenario-batched swarm rollouts: many small swarms, one program.

The north star's "millions of users" is not one 1M-agent swarm (r12
sharded that) but THOUSANDS of small, heterogeneous swarms per chip —
the population-batched pattern of Fast Population-Based RL (arxiv
2206.08888) and ABMax (arxiv 2508.16508): stack the per-scenario
state along a leading axis, move every per-scenario tunable from
jit-static config into a TRACED params pytree, and ``vmap`` the tick
so one compiled ``lax.scan`` steps the whole tenant population.

Three pieces:

- :class:`ScenarioParams` — the dynamic per-scenario scalars (APF
  gains, max-speed clamp, auction eps/theta).  Everything else stays
  in the static :class:`~..utils.config.SwarmConfig`, shared by the
  batch (structure: separation mode, shapes, cadences).
- :class:`ScenarioRequest` + :func:`materialize_batch` — the host
  description of one tenant's swarm, and THE one constructor of its
  padded :class:`~..state.SwarmState`: one jitted, vmapped build per
  dispatch (per-request ``make_swarm`` + ``kill`` calls measured
  ~3 ms/scenario of pure host/dispatch overhead — at service rates
  that was 40% of the whole rollout).  The per-scenario agent count
  rides the ``alive`` mask (pad slots are dead agents; every
  protocol reduction already masks on liveness), and every scenario
  derives its own PRNG key from its seed — never broadcast one key
  across the batch (swarmlint's ``key-broadcast`` rule exists
  because correlated election jitter across tenants is silent and
  wrong).  ``materialize_scenario`` is the batch-of-1 view, so the
  solo parity reference runs the IDENTICAL state by construction.
- :func:`batched_rollout` — the compiled entry: ``vmap`` of
  ``models/swarm.swarm_tick_dyn`` under one ``lax.scan``, the
  scenario-stacked state DONATED (the service's double-buffered loop
  hands dispatch buffers straight back to XLA).  Registered with the
  compile observatory as ``"serve-batched-rollout"`` (the
  materializer as ``"serve-materialize"``) so the bucket lattice
  (serve/buckets.py) is an enforced budget, not a hope.

Bitwise contract (pinned in tests/test_serve.py): scenario ``i`` of a
batched rollout equals the same materialized state run solo through
``swarm_rollout`` with the params baked into the config — per-scenario
scalars enter the identical arithmetic whether constant-folded or
traced, and vmapped agent-axis reductions keep their row-wise order.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from functools import lru_cache, partial
from typing import Dict, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from flax import struct

from ..models.swarm import swarm_tick_dyn
from ..state import (
    FOLLOWER,
    NO_CAP,
    NO_LEADER,
    NO_WINNER,
    SwarmState,
)
from ..utils.compile_watch import watched
from ..utils.config import TELEMETRY_ON, SwarmConfig

#: Compile-observatory registry names of the serve plane's jitted
#: entries — the names the service declares its bucket budgets under.
SERVE_ENTRY = "serve-batched-rollout"
MATERIALIZE_ENTRY = "serve-materialize"
#: The scenario-axis sharded twin (r18): the same vmapped scan, its
#: scenario batch shard_map-committed P('scenarios') so S tenants run
#: S/n_devices per device.  A separate registry entry because it is a
#: separate contract: jaxlint budgets pin ZERO per-tick collectives
#: here (per-scenario state never crosses the axis).
SERVE_SHARDED_ENTRY = "serve-batched-rollout-sharded"

#: Separation modes the batched tick supports.  Dense is exact at the
#: service's small-swarm scale and vmaps to one fused pair sweep;
#: "off" serves pure-protocol tenants.  The spatial-hash modes bake
#: grid geometry from static config (and the Pallas kernels bake
#: their gains), so they stay solo/sharded-path features.
SUPPORTED_SEPARATION = ("dense", "off")


@struct.dataclass
class ScenarioParams:
    """Per-scenario DYNAMIC overrides — every leaf an f32 scalar
    (stacked: ``[S]`` per leaf).  These are traced data: one compiled
    program serves every value combination.  Fields mirror their
    ``SwarmConfig`` namesakes; ``utility_threshold`` / ``auction_eps``
    are the allocation layer's theta/eps pair."""

    k_att: jax.Array
    k_rep: jax.Array
    k_sep: jax.Array
    max_speed: jax.Array
    utility_threshold: jax.Array
    auction_eps: jax.Array


#: The SwarmConfig fields ScenarioParams can override — one tuple so
#: the builder, the baker, and the docs cannot drift.
PARAM_FIELDS = (
    "k_att", "k_rep", "k_sep", "max_speed", "utility_threshold",
    "auction_eps",
)


def scenario_params(cfg: SwarmConfig, **overrides) -> ScenarioParams:
    """Build one scenario's params: config defaults, selectively
    overridden.  Values are stored as f32 scalars (the dtype the tick
    computes in), so baking them back into a config is lossless."""
    bad = set(overrides) - set(PARAM_FIELDS)
    if bad:
        raise ValueError(
            f"unknown scenario param(s) {sorted(bad)}; "
            f"overridable fields: {PARAM_FIELDS}"
        )
    return ScenarioParams(**{
        f: jnp.asarray(
            overrides.get(f, getattr(cfg, f)), jnp.float32
        )
        for f in PARAM_FIELDS
    })


def bake_params(cfg: SwarmConfig, params: ScenarioParams) -> SwarmConfig:
    """The inverse direction: one scenario's params as a STATIC config
    — the solo reference path of the bitwise parity contract
    (``swarm_rollout`` with this config == the batched row).  The
    f32 -> Python float -> f32 round trip is exact, so both paths
    compute with the identical scalar."""
    return cfg.replace(**{
        f: float(np.float32(np.asarray(getattr(params, f))))
        for f in PARAM_FIELDS
    })


def stack_params(params) -> ScenarioParams:
    """Stack per-scenario params into the ``[S]``-leaved batch pytree."""
    params = list(params)
    if not params:
        raise ValueError("stack_params needs at least one scenario")
    return jax.tree_util.tree_map(
        lambda *xs: jnp.stack(xs), *params
    )


@dataclass(frozen=True)
class ScenarioRequest:
    """One tenant's scenario, host-side.

    ``n_agents`` is the REAL agent count; the service pads it up to a
    capacity bucket (the pad slots are dead).  ``arena_hw`` scales the
    spawn spread (the per-scenario "arena size"; must be > 0 — every
    tenant draws its spawn from its own seed's stream); ``target`` is
    an optional shared nav goal (``None`` = station-keeping: every
    agent holds its spawn pose — the r12 arena).  ``task_pos`` rows
    install a task table (all requests of one service must agree on
    the row COUNT — it is a shape); ``kill_ids`` injects initial
    faults (the recovery-scenario hook: killing the would-be leader
    forces an election the per-tenant flight recorder then shows).
    ``params`` maps ScenarioParams field names to per-tenant values.
    """

    n_agents: int
    seed: int = 0
    arena_hw: float = 8.0
    target: Optional[Tuple[float, float]] = None
    task_pos: Tuple[Tuple[float, float], ...] = ()
    kill_ids: Tuple[int, ...] = ()
    params: Dict[str, float] = field(default_factory=dict)


def validate_request(req: ScenarioRequest, capacity=None) -> None:
    """Every per-request invariant, in one place — the service checks
    them at SUBMIT time (a bad request must fail at its own submit,
    not poison its co-batched requests' flush) and the materializer
    re-checks them (direct callers).  ``capacity`` adds the bucket
    bound when known."""
    if req.n_agents <= 0:
        raise ValueError(
            f"scenario needs n_agents >= 1, got {req.n_agents}"
        )
    if capacity is not None and req.n_agents > capacity:
        raise ValueError(
            f"n_agents {req.n_agents} outside (0, capacity="
            f"{capacity}]"
        )
    if not req.arena_hw > 0:
        raise ValueError(
            f"arena_hw must be > 0, got {req.arena_hw} (the spawn "
            "spread — every scenario draws its arena from its own "
            "seed)"
        )
    bad = set(req.params) - set(PARAM_FIELDS)
    if bad:
        raise ValueError(
            f"unknown scenario param(s) {sorted(bad)}; overridable "
            f"fields: {PARAM_FIELDS}"
        )
    out = [k for k in req.kill_ids if not 0 <= k < req.n_agents]
    if out:
        # Silently dropping these would turn an off-by-one on "kill
        # the would-be leader" into a quiet no-fault tenant (and a
        # negative id would wrap to a different slot).
        raise ValueError(
            f"kill_ids {out} outside [0, n_agents={req.n_agents}) — "
            "fault injection must name real agents"
        )


@watched(MATERIALIZE_ENTRY)
@partial(jax.jit, static_argnames=("capacity", "n_tasks"))
def _materialize_batch_impl(
    seeds: jax.Array,        # [S] i32
    spreads: jax.Array,      # [S] f32 arena half-widths
    alive: jax.Array,        # [S, capacity] bool (pads/faults dead)
    use_point: jax.Array,    # [S] bool — point target vs station
    points: jax.Array,       # [S, 2] f32 shared nav goal (if use_point)
    task_pos: jax.Array,     # [S, n_tasks, 2] f32
    capacity: int,
    n_tasks: int,
) -> SwarmState:
    """One compiled, vmapped constructor for a whole dispatch batch —
    the shapes-and-seeds half of scenario materialization.  Mirrors
    ``make_swarm(capacity, seed, spread) -> with_tasks -> kill ->
    station/point targets`` semantically: spawn drawn from the
    scenario's own seed (split exactly like ``make_swarm``), dead
    slots via the alive mask with the ``alive_below`` cache recounted,
    targets = spawn pose (station-keeping) or the shared point."""

    def one(seed, spread, alive_row, use_pt, point, tpos):
        key = jax.random.PRNGKey(seed)
        key, sub = jax.random.split(key)
        pos = jax.random.uniform(
            sub, (capacity, 2), jnp.float32,
            minval=-spread, maxval=spread,
        )
        aint = alive_row.astype(jnp.int32)
        alive_below = jnp.cumsum(aint) - aint
        target = jnp.where(
            use_pt,
            jnp.broadcast_to(point, pos.shape),
            pos,
        )
        return SwarmState(
            tick=jnp.asarray(0, jnp.int32),
            key=key,
            agent_id=jnp.arange(capacity, dtype=jnp.int32),
            alive=alive_row,
            pos=pos,
            vel=jnp.zeros((capacity, 2), jnp.float32),
            caps=jnp.zeros((capacity, 1), bool),
            target=target,
            has_target=jnp.ones((capacity,), bool),
            fsm=jnp.full((capacity,), FOLLOWER, jnp.int32),
            leader_id=jnp.full((capacity,), NO_LEADER, jnp.int32),
            leader_pos=jnp.zeros((capacity, 2), jnp.float32),
            has_leader_pos=jnp.zeros((capacity,), bool),
            last_hb_tick=jnp.zeros((capacity,), jnp.int32),
            wait_until=jnp.zeros((capacity,), jnp.int32),
            alive_below=alive_below,
            leader_live=jnp.ones((capacity,), bool),
            task_pos=tpos,
            task_cap=jnp.full((n_tasks,), NO_CAP, jnp.int32),
            task_winner=jnp.full((n_tasks,), NO_WINNER, jnp.int32),
            task_util=jnp.zeros((n_tasks,), jnp.float32),
            task_claimed=jnp.zeros((capacity, n_tasks), bool),
        )

    return jax.vmap(one)(
        seeds, spreads, alive, use_point, points, task_pos
    )


def materialize_batch(
    reqs: Sequence[ScenarioRequest],
    capacity: int,
    cfg: SwarmConfig,
    pad_to: Optional[int] = None,
) -> Tuple[SwarmState, ScenarioParams]:
    """Materialize a dispatch batch: ``[S, ...]``-stacked states +
    ``[S]``-leaved params, S = ``pad_to`` or ``len(reqs)``.  Rows past
    ``len(reqs)`` are dead FILLER scenarios (every slot dead — they
    tick along at full shape and their rows are discarded): the
    padding half of the bucket contract.  All host work is cheap
    numpy assembly; the build itself is one jitted call per
    ``(S, capacity, n_tasks)`` shape."""
    if not reqs:
        raise ValueError("materialize_batch needs at least one request")
    n_real = len(reqs)
    size = pad_to if pad_to is not None else n_real
    if size < n_real:
        raise ValueError(f"pad_to {size} < {n_real} requests")
    if cfg.dtype != "float32":
        raise ValueError(
            "scenario batching materializes float32 swarms; got "
            f"cfg.dtype={cfg.dtype!r}"
        )
    n_tasks = len(reqs[0].task_pos)
    seeds = np.zeros((size,), np.int32)
    spreads = np.full((size,), 1.0, np.float32)
    alive = np.zeros((size, capacity), bool)
    use_point = np.zeros((size,), bool)
    points = np.zeros((size, 2), np.float32)
    task_pos = np.zeros((size, n_tasks, 2), np.float32)
    pvals = {
        f: np.full((size,), getattr(cfg, f), np.float32)
        for f in PARAM_FIELDS
    }
    for i, req in enumerate(reqs):
        validate_request(req, capacity=capacity)
        if len(req.task_pos) != n_tasks:
            raise ValueError(
                "all scenarios in one batch must install the same "
                f"task count (a shape): got {n_tasks} and "
                f"{len(req.task_pos)}"
            )
        seeds[i] = req.seed
        spreads[i] = req.arena_hw
        alive[i, : req.n_agents] = True
        alive[i, list(req.kill_ids)] = False
        if req.target is not None:
            use_point[i] = True
            points[i] = req.target
        if n_tasks:
            # swarmlint: disable=serve-host-sync -- req.task_pos is a host-side Python list from the request payload; asarray here is host-to-host, no device array is touched
            task_pos[i] = np.asarray(req.task_pos, np.float32)
        for f, v in req.params.items():
            pvals[f][i] = v
    states = _materialize_batch_impl(
        jnp.asarray(seeds), jnp.asarray(spreads), jnp.asarray(alive),
        jnp.asarray(use_point), jnp.asarray(points),
        jnp.asarray(task_pos), capacity=capacity, n_tasks=n_tasks,
    )
    params = ScenarioParams(
        **{f: jnp.asarray(v) for f, v in pvals.items()}
    )
    return states, params


def materialize_scenario(
    req: ScenarioRequest, capacity: int, cfg: SwarmConfig
) -> Tuple[SwarmState, ScenarioParams]:
    """One scenario's padded state + params — the batch-of-1 view of
    :func:`materialize_batch`, so the solo parity reference and the
    batched service run the IDENTICAL constructor."""
    states, params = materialize_batch([req], capacity, cfg)
    return (
        tenant_state(states, 0),
        jax.tree_util.tree_map(lambda x: x[0], params),
    )


def stack_scenarios(states) -> SwarmState:
    """Stack per-scenario states into the ``[S, ...]``-leaved batch
    (scalar leaves — tick, key — become ``[S]`` / ``[S, 2]``)."""
    states = list(states)
    if not states:
        raise ValueError("stack_scenarios needs at least one scenario")
    return jax.tree_util.tree_map(
        lambda *xs: jnp.stack(xs), *states
    )


def tenant_state(states: SwarmState, i: int) -> SwarmState:
    """Scenario ``i``'s state out of the batch (still capacity-padded
    — trim with ``[:n_agents]`` views if needed)."""
    return jax.tree_util.tree_map(lambda x: x[i], states)


def validate_serve_config(cfg: SwarmConfig) -> SwarmConfig:
    """The batched tick's static-config envelope, checked eagerly at
    service construction so misconfiguration fails at the API
    boundary, not mid-trace."""
    if cfg.separation_mode not in SUPPORTED_SEPARATION:
        raise ValueError(
            f"scenario batching supports separation_mode in "
            f"{SUPPORTED_SEPARATION}, got {cfg.separation_mode!r} — "
            "the spatial-hash/window modes derive grid geometry from "
            "static config (and the Pallas kernels bake their "
            "gains), so they cannot take per-scenario dynamic params"
        )
    return cfg


@watched(SERVE_ENTRY)
@partial(
    jax.jit,
    static_argnames=("cfg", "n_steps", "record", "telemetry"),
    donate_argnums=(0,),
)
def _batched_rollout_impl(
    states: SwarmState,
    params: Optional[ScenarioParams],
    cfg: SwarmConfig,
    n_steps: int,
    record: bool = False,
    telemetry: bool = False,
):
    """``n_steps`` vmapped ticks under one ``lax.scan`` — the compiled
    multi-tenant program.  ``states``/``params`` carry a leading
    scenario axis; ``states`` is DONATED (the service's submit/collect
    loop hands each dispatch's buffers straight back to XLA — with
    async dispatch the host materializes bucket k+1 while bucket k
    executes, the double-buffering half of the r13 design).

    Result composition mirrors ``_swarm_rollout_impl``: ``record``
    prepends the ``[n_steps, S, capacity, D]`` position trajectory,
    ``telemetry`` appends the stacked per-tenant recorder ys
    (``[n_steps, S]`` per leaf — ``utils/telemetry.tenant_summaries``
    reduces them per scenario).  The telemetry gate is the r10 static
    contract: disabled, the lowering is byte-identical to the
    flag-free entry (pinned in tests/test_serve.py)."""
    telem_on = telemetry or cfg.telemetry.enabled
    if telem_on and not cfg.telemetry.enabled:
        cfg = cfg.replace(telemetry=TELEMETRY_ON)

    if params is None:
        vtick = jax.vmap(
            lambda s: swarm_tick_dyn(s, None, cfg, None)
        )

        def step(ss):
            return vtick(ss)
    else:
        vtick = jax.vmap(
            lambda s, p: swarm_tick_dyn(s, None, cfg, p)
        )

        def step(ss):
            return vtick(ss, params)

    def body(ss, _):
        ss, telem = step(ss)
        frame = ss.pos if record else None
        return ss, (frame, telem)

    states, (traj, telem) = jax.lax.scan(
        body, states, None, length=n_steps
    )
    out = (states, traj) if record else states
    if telem_on:
        if not n_steps:
            telem = None
        out = out + (telem,) if record else (out, telem)
    return out


def batched_rollout(
    states: SwarmState,
    params: Optional[ScenarioParams],
    cfg: SwarmConfig,
    n_steps: int,
    record: bool = False,
    telemetry: bool = False,
):
    """Public entry for the scenario-batched rollout (see
    :func:`_batched_rollout_impl`).  ``states`` must carry a leading
    scenario axis (:func:`materialize_batch` or
    :func:`stack_scenarios`) and is DONATED — do not reuse its
    buffers after the call."""
    validate_serve_config(cfg)
    return _batched_rollout_impl(
        states, params, cfg, n_steps, record, telemetry
    )


# ---------------------------------------------------------------------------
# Scenario-axis sharded rollout (r18): the 2D-mesh serve plane's
# small-tenant half.  The batched tick is embarrassingly parallel over
# scenarios — vmap rows never read each other — so sharding the
# leading axis over a mesh costs ZERO per-tick collectives.  The body
# is shard_map (not bare GSPMD) deliberately: jaxlint's census reads
# the LOWERED program, and only explicit shard_map partitioning makes
# "zero all-gathers on the scenario axis" a checkable contract instead
# of a hope about the SPMD partitioner (analysis/jaxlint.py module
# doc).  Bitwise contract: a vmap row's arithmetic is independent of
# its batch neighbors, so the S/n-per-device blocks compute exactly
# the rows the single-device batch computes — scenario i of the
# sharded rollout equals scenario i of the unsharded one BITWISE
# (pinned in tests/test_serve_2d.py).


def scenario_sharding(mesh, axis: str = None):
    """The serve plane's scenario-batch placement: dim 0 of every
    ``[S, ...]`` leaf split over ``axis`` of ``mesh`` (the one
    dim-0-over-an-axis helper, serve-axis default)."""
    from ..parallel.mesh import SCENARIO_AXIS, agent_sharding

    return agent_sharding(mesh, axis or SCENARIO_AXIS)


def shard_scenarios(tree, mesh, axis: str = None):
    """Commit a materialized ``[S, ...]``-leaved batch (states AND/OR
    params) over the mesh's scenario axis — done BEFORE the first
    launch so the donated carry keeps the sharding across every
    segment rotation (donation preserves placement)."""
    sh = scenario_sharding(mesh, axis)
    return jax.tree_util.tree_map(
        lambda x: jax.device_put(x, sh), tree
    )


@watched(SERVE_SHARDED_ENTRY)
@partial(
    jax.jit,
    static_argnames=("cfg", "n_steps", "mesh", "axis", "record",
                     "telemetry"),
    donate_argnums=(0,),
)
def _batched_rollout_sharded_impl(
    states: SwarmState,
    params: ScenarioParams,
    cfg: SwarmConfig,
    n_steps: int,
    mesh,
    axis: str,
    record: bool = False,
    telemetry: bool = False,
):
    """``n_steps`` vmapped ticks under one ``lax.scan``, the scenario
    axis shard_map-split over ``mesh[axis]`` — each device scans its
    own ``S/n`` block, no cross-device data motion anywhere (the
    whole point; budget-pinned by jaxlint).  Same donation and result
    composition as :func:`_batched_rollout_impl`."""
    from jax.sharding import PartitionSpec as P

    from ..utils.compat import shard_map

    telem_on = telemetry or cfg.telemetry.enabled
    if telem_on and not cfg.telemetry.enabled:
        cfg = cfg.replace(telemetry=TELEMETRY_ON)

    sp = P(axis)
    ys = P(None, axis)        # stacked [T, S]-class leaves
    out_specs: tuple = (sp, ys) if record else sp
    if telem_on:
        out_specs = (
            out_specs + (ys,) if record else (out_specs, ys)
        )

    @partial(
        shard_map, mesh=mesh, in_specs=(sp, sp),
        out_specs=out_specs, check_vma=False,
    )
    # swarmlint: disable=halo-width -- the sharded axis is the SCENARIO batch axis: every device holds whole scenarios, so each per-scenario plan (built under vmap) sees its complete swarm — there is no spatial shard boundary to halo across (zero-collective budget pinned by jaxlint)
    def block(ss, pp):
        vtick = jax.vmap(
            lambda s, p: swarm_tick_dyn(s, None, cfg, p)
        )

        def body(ss, _):
            ss, telem = vtick(ss, pp)
            frame = ss.pos if record else None
            return ss, (frame, telem)

        ss, (traj, telem) = jax.lax.scan(
            body, ss, None, length=n_steps
        )
        out = (ss, traj) if record else ss
        if telem_on:
            out = out + (telem,) if record else (out, telem)
        return out

    out = block(states, params)
    if telem_on and not n_steps:
        # Mirror the unsharded entry: a zero-length rollout yields
        # telem = None, never a [0]-leaved record.
        out = out[:-1] + (None,) if record else (out[0], None)
    return out


def batched_rollout_sharded(
    states: SwarmState,
    params: ScenarioParams,
    cfg: SwarmConfig,
    n_steps: int,
    mesh,
    axis: str = None,
    record: bool = False,
    telemetry: bool = False,
):
    """Public entry for the scenario-axis sharded rollout (see
    :func:`_batched_rollout_sharded_impl`).  ``states``/``params``
    must carry a leading scenario axis divisible by the mesh's
    scenario-axis size (shard_map splits it into equal blocks; the
    bucket lattice guarantees this by sizing sharded rungs as
    multiples of the axis), committed via :func:`shard_scenarios`;
    ``states`` is DONATED.  ``params`` is required — the sharded path
    exists for the heterogeneous serving workload, and a None-params
    twin would double the compiled-shape lattice for no caller."""
    from ..parallel.mesh import SCENARIO_AXIS

    axis = axis or SCENARIO_AXIS
    validate_serve_config(cfg)
    if params is None:
        raise ValueError(
            "batched_rollout_sharded needs params (the serve "
            "materializer always builds them); the params=None graph "
            "is the single-device batched_rollout's"
        )
    n_shards = int(mesh.shape[axis])
    s = states.pos.shape[0]
    if s % n_shards:
        raise ValueError(
            f"scenario batch {s} does not split over the "
            f"{n_shards}-way {axis!r} mesh axis; pad the dispatch to "
            "a rung sized a multiple of the axis (the service's "
            "sharded rungs are validated to be)"
        )
    return _batched_rollout_sharded_impl(
        states, params, cfg, n_steps, mesh, axis, record, telemetry
    )


def pulse_stamp_sharded(mesh, spec):
    """The swarmpulse heartbeat stamp for mesh-committed carries
    (r24): :func:`~.pulse.pulse_stamp`'s copy shard_map'd over the
    serve mesh, so the completion callback fires ONCE PER DEVICE with
    a linearized shard index — per-shard stamps are reduced host-side
    by ``pulse.pulse_drain`` (no collective, no cross-device gather on
    the serving path; the r19 review's deferred cross-device design).

    ``spec`` places the stamped leaf: ``P(SCENARIO_AXIS)`` for a
    sharded stream's ``[S]`` tick, ``P()`` for a jumbo stream's
    replicated scalar tick (``spatial_shard_swarm`` replicates
    non-slot leaves).  One compiled stamp per ``(mesh, spec)`` pair
    ever — the builder is cached, so the per-segment stamp costs a
    dispatch, never a retrace."""
    return _pulse_stamp_sharded_cached(mesh, spec)


@lru_cache(maxsize=None)
def _pulse_stamp_sharded_cached(mesh, spec):
    from jax.sharding import PartitionSpec as P

    from ..utils.compat import shard_map
    from .pulse import _pulse_landed_cb

    axes = tuple(mesh.axis_names)
    sizes = tuple(int(mesh.shape[a]) for a in axes)

    def _block(leaf, token, seg):
        # Linearized shard id over every mesh axis, row-major — the
        # host-side reduction only needs distinctness + a stable
        # count (mesh.size stamps per segment).
        idx = jax.lax.axis_index(axes[0])
        for name, size in zip(axes[1:], sizes[1:]):
            idx = idx * size + jax.lax.axis_index(name)
        jax.debug.callback(_pulse_landed_cb, token, seg, idx, leaf)
        return jnp.copy(leaf)

    fn = partial(
        shard_map, mesh=mesh,
        in_specs=(spec, P(), P()), out_specs=spec,
        check_vma=False,
    )(_block)
    return jax.jit(fn)


# ---------------------------------------------------------------------------
# Env serving (r14): the MARL rollout through the bucket lattice.

#: Static families the env-rollout entry has served in this process
#: (env, n_steps, random_policy, effective telemetry) — the jit cache
#: they key is process-global, so the declared budget must be too.
_ENV_ROLLOUT_FAMILIES: set = set()


@dataclass
class EnvRolloutResult:
    """One scenario of a bucketed env dispatch: the final
    :class:`~..envs.core.EnvState` row, the ``[n_steps, capacity]``
    per-agent reward/done stacks, and the tenant's flight-recorder
    summary (``None`` with telemetry off)."""

    index: int
    state: object
    rewards: object
    dones: object
    summary: Optional[dict] = None


def _bucketed_rollouts(
    env,
    scenarios,
    seeds,
    n_steps: int,
    spec,
    telemetry: bool,
    entry: str,
    families: set,
    family: tuple,
    dispatch,
):
    """THE bucket-serving loop both env and learned-policy serving
    share: seed validation, the rungs x observed-static-families
    compile budget (the r13 service's task-family discipline: each
    distinct static tuple legitimately mints its own compile per
    rung, and declaring rungs alone would turn the second family's
    compile into a spurious bucket-overflow event), dead-filler
    padding, per-scenario PRNG keying, telemetry unpack, and result
    assembly.  ``dispatch(keys, params)`` is the one compiled call
    per bucket; ``family`` the caller's static tuple for ``entry``'s
    process-global ``families`` ledger."""
    from ..envs.core import stack_env_params
    from ..envs.scenarios import filler_params
    from ..utils import compile_watch
    from ..utils.telemetry import TelemetrySummary, tenant_telemetry
    from .buckets import BucketSpec

    scenarios = list(scenarios)
    seeds = list(seeds)
    if len(seeds) != len(scenarios):
        raise ValueError(
            f"{len(scenarios)} scenarios but {len(seeds)} seeds — "
            "every scenario needs its own PRNG stream"
        )
    spec = spec or BucketSpec()
    watch = compile_watch.WATCH
    families.add(family)
    budget = max(
        len(spec.batches) * len(families),
        watch.bucket_budget(entry) or 0,
    )
    watch.declare_buckets(entry, budget)

    telem_on = telemetry or env.cfg.telemetry.enabled
    filler = filler_params(env) if scenarios else None
    results: list = [None] * len(scenarios)
    queue = list(range(len(scenarios)))
    for size in spec.split_batch(len(queue)):
        take = queue[:size]
        queue = queue[size:]
        rows = [scenarios[i] for i in take]
        row_seeds = [seeds[i] for i in take]
        n_pad = size - len(rows)
        rows += [filler] * n_pad
        row_seeds += [0] * n_pad
        params = stack_env_params(rows)
        keys = jnp.stack(
            [jax.random.PRNGKey(s) for s in row_seeds]
        )
        out = dispatch(keys, params)
        telem = None
        if telem_on:
            states, rewards, dones, telem = out
        else:
            states, rewards, dones = out
        for j, i in enumerate(take):
            summary = None
            if telem is not None:
                summary = TelemetrySummary.from_ticks(
                    tenant_telemetry(telem, j)
                ).to_dict()
            results[i] = EnvRolloutResult(
                index=i,
                state=jax.tree_util.tree_map(lambda x: x[j], states),
                rewards=rewards[:, j],
                dones=dones[:, j],
                summary=summary,
            )
    return results


def env_rollouts(
    env,
    scenarios,
    seeds,
    n_steps: int,
    spec=None,
    random_policy: bool = False,
    telemetry: bool = False,
):
    """Bucketed MARL serving: run a heterogeneous list of env
    scenarios through the batch-rung lattice — each dispatch is ONE
    compiled call of the ``"env-rollout"`` entry, padded with dead
    filler scenarios exactly like the tenant service
    (serve/buckets.py); a scenario is just params + a reward id, so
    the serve plane needs nothing new to carry RL workloads.

    ``env`` is a :class:`~..envs.core.SwarmMARLEnv` (its capacity is
    the agent-axis shape — already quantized by construction, so only
    the batch axis buckets here); ``scenarios`` a sequence of
    single-scenario :class:`~..envs.core.EnvParams`; ``seeds`` one
    PRNG seed per scenario (each scenario gets its own stream — the
    key-broadcast rule).  The batch-rung budget is declared to the
    compile observatory under the env entry.  Returns one
    :class:`EnvRolloutResult` per scenario, input order."""
    from ..envs.core import ENV_ROLLOUT_ENTRY, _env_rollout_impl

    return _bucketed_rollouts(
        env, scenarios, seeds, n_steps, spec, telemetry,
        entry=ENV_ROLLOUT_ENTRY,
        families=_ENV_ROLLOUT_FAMILIES,
        family=(env, int(n_steps), bool(random_policy),
                bool(telemetry or env.cfg.telemetry.enabled)),
        dispatch=lambda keys, params: _env_rollout_impl(
            keys, params, env, n_steps, random_policy, telemetry,
        ),
    )


#: Static families the policy-rollout entry has served in this process
#: (env, tcfg, n_steps, deterministic, effective telemetry) — the same
#: process-global budget discipline as the env entry above.
_POLICY_ROLLOUT_FAMILIES: set = set()


def train_rollouts(
    env,
    scenarios,
    seeds,
    n_steps: int,
    net,
    tcfg,
    spec=None,
    deterministic: bool = True,
    telemetry: bool = False,
):
    """Bucketed LEARNED-POLICY serving (r20): the twin of
    :func:`env_rollouts` for trained policies — a heterogeneous list
    of env scenarios runs through the batch-rung lattice with the
    network riding each dispatch as TRACED data, so every checkpoint
    of one architecture serves through the same compiled
    ``"policy-rollout"`` entry (train/ppo.py).  Padding, seeding, and
    result unpacking are the shared :func:`_bucketed_rollouts` loop;
    the learned policy is just one more tenant workload on the serve
    plane.

    ``net`` is the policy pytree (``train.ppo.init_policy_params``
    shape — its architecture must match ``env.obs_dim``); ``tcfg``
    the :class:`~..train.ppo.TrainConfig` it was trained under
    (static — it shapes the graph).  Returns one
    :class:`EnvRolloutResult` per scenario, input order."""
    from ..train.ppo import POLICY_ROLLOUT_ENTRY, _policy_rollout_impl

    return _bucketed_rollouts(
        env, scenarios, seeds, n_steps, spec, telemetry,
        entry=POLICY_ROLLOUT_ENTRY,
        families=_POLICY_ROLLOUT_FAMILIES,
        family=(env, tcfg, int(n_steps), bool(deterministic),
                bool(telemetry or env.cfg.telemetry.enabled)),
        dispatch=lambda keys, params: _policy_rollout_impl(
            keys, params, net, env, tcfg, n_steps, deterministic,
            telemetry,
        ),
    )
