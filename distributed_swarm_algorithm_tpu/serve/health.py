"""The stream-health watchdog (r24 swarmpulse, layer 3).

The pulse registry (serve/pulse.py) gives every in-flight stream a
monotonically advancing device-progress timestamp; this module turns
it into a LIVENESS signal: each pump, the monitor ages every stream's
heartbeat against the segment wall the service has actually been
paying (learned live from the r16 ``serve_segment_wall_ms``
histogram) and classifies it on a four-state ladder:

    healthy   age <= slow_mult  * expected wall   (keeping pace)
    slow      age <= stall_mult * expected wall   (straggling)
    stalled   age <= wedge_mult * expected wall   (not progressing)
    wedged    age >  wedge_mult * expected wall   (presumed dead)

Entering the alarm zone (``stalled``/``wedged``) emits ONE
``stream-stall`` event; leaving it (progress resumed, or the stream
finished) emits ``stream-recovered`` — both through
:class:`~.slo.SloTracker` so events.jsonl and the metric counters
update in the same method (the r19 count-for-count parity
discipline).  The ``stalled -> wedged`` escalation is visible in the
health table but is NOT a second alarm: one incident, one event pair.

Design constraints, in order:

- **No thread, no device work.**  ``check`` runs inside the pump,
  cadence-gated by ``interval_s``; it reads host floats the pulse
  drain already wrote.  A wedged DEVICE cannot block detection,
  because detection never touches the device.
- **Fake-clock testable.**  The monitor sees streams as plain
  objects with ``rids / done / seg_done / segs_landed /
  last_launch_t / last_progress_t / health_state`` attributes; tests
  drive it with ``SimpleNamespace`` rows and a hand-cranked clock
  (tests/test_health.py), no service required.
- **Learned walls, bounded floors.**  The expected wall is a
  percentile of the live segment-wall histogram so thresholds track
  the workload; before any history (or past the histogram envelope)
  it falls back to ``default_wall_ms``, and never drops below
  ``floor_ms`` — sub-millisecond CPU segments must not make an idle
  pump look wedged.
"""

from __future__ import annotations

import math
import time
from typing import Callable, List, Optional

HEALTHY = "healthy"
SLOW = "slow"
STALLED = "stalled"
WEDGED = "wedged"

#: The ladder, mild to dead — the fixed label set of the
#: ``serve_stream_health`` gauge (bounded cardinality by design).
HEALTH_STATES = (HEALTHY, SLOW, STALLED, WEDGED)

#: States that raise the stall alarm.
ALARM_STATES = (STALLED, WEDGED)

#: Watchdog defaults: one detection interval of 250 ms keeps the
#: drill's "classified within one interval" bound meaningful at
#: serving cadence while costing one float compare per pump.
DEFAULT_INTERVAL_S = 0.25
DEFAULT_WALL_MS = 1000.0


class HealthMonitor:
    """Classify in-flight streams from heartbeat age (see module
    doc).  ``wall_hist`` (the service's ``serve_segment_wall_ms``
    histogram) and ``slo`` (the tracker the events/counters ride) are
    wired by :class:`~.service.StreamingService`; a bare monitor with
    neither still classifies — it just has nowhere to report."""

    def __init__(
        self,
        clock: Optional[Callable[[], float]] = None,
        interval_s: float = DEFAULT_INTERVAL_S,
        slow_mult: float = 1.5,
        stall_mult: float = 4.0,
        wedge_mult: float = 16.0,
        floor_ms: float = 50.0,
        default_wall_ms: float = DEFAULT_WALL_MS,
        wall_quantile: float = 95.0,
        wall_hist=None,
        slo=None,
    ):
        if not 0 < slow_mult < stall_mult < wedge_mult:
            raise ValueError(
                "health thresholds must be ordered 0 < slow_mult < "
                f"stall_mult < wedge_mult, got ({slow_mult}, "
                f"{stall_mult}, {wedge_mult})"
            )
        self.clock = clock
        self.interval_s = float(interval_s)
        self.slow_mult = float(slow_mult)
        self.stall_mult = float(stall_mult)
        self.wedge_mult = float(wedge_mult)
        self.floor_ms = float(floor_ms)
        self.default_wall_ms = float(default_wall_ms)
        self.wall_quantile = float(wall_quantile)
        self.wall_hist = wall_hist
        self.slo = slo
        self._last_check: Optional[float] = None
        #: Last completed check's snapshot (None before the first) —
        #: what ``SloTracker.summary()`` re-renders between checks.
        self.last_snapshot: Optional[dict] = None

    def _now(self) -> float:
        return (self.clock or time.monotonic)()

    # -- thresholds --------------------------------------------------------
    def expected_wall_ms(self) -> float:
        """The segment wall the workload has been paying: a high
        percentile of the live histogram, floored, with a structured
        fallback before history exists or past the bucket envelope
        (``inf`` must not disable the watchdog)."""
        wall = None
        if self.wall_hist is not None:
            got = self.wall_hist.percentile(self.wall_quantile)
            if got and math.isfinite(got):
                wall = float(got)
        if wall is None:
            wall = self.default_wall_ms
        return max(self.floor_ms, wall)

    def classify(self, age_ms: float, wall_ms: float) -> str:
        if age_ms <= self.slow_mult * wall_ms:
            return HEALTHY
        if age_ms <= self.stall_mult * wall_ms:
            return SLOW
        if age_ms <= self.wedge_mult * wall_ms:
            return STALLED
        return WEDGED

    # -- the watchdog tick -------------------------------------------------
    def check(self, streams, force: bool = False) -> Optional[dict]:
        """One watchdog pass over ``streams`` (cadence-gated; returns
        None when skipped).  Emits stall/recovered transitions through
        the tracker, pushes the per-stream table + state counts to it,
        and returns the snapshot ``{"expected_wall_ms", "rows",
        "counts"}``."""
        now = self._now()
        if (
            not force
            and self._last_check is not None
            and now - self._last_check < self.interval_s
        ):
            return None
        self._last_check = now
        wall = self.expected_wall_ms()
        rows: List[dict] = []
        counts = {st: 0 for st in HEALTH_STATES}
        for s in streams:
            if s.done:
                # A finished (or abandoned) stream leaves the table;
                # completion IS recovery for an alarmed one — the
                # incident closes with an event, not silence.
                self.discharge(s)
                continue
            base = (
                s.last_progress_t
                if s.last_progress_t is not None
                else s.last_launch_t
            )
            if base is None:
                # Admitted but never launched this pump cycle — no
                # heartbeat to age yet.
                continue
            age_ms = max(0.0, 1e3 * (now - base))
            state = self.classify(age_ms, wall)
            prev = s.health_state
            if state != prev:
                in_alarm = state in ALARM_STATES
                was_alarm = prev in ALARM_STATES
                if in_alarm and not was_alarm:
                    self._emit_stall(s, state, age_ms, wall, now)
                elif was_alarm and not in_alarm:
                    self._emit_recovered(s, age_ms, now)
                s.health_state = state
            counts[state] += 1
            rows.append(
                {
                    "rids": list(s.rids),
                    "state": state,
                    "age_ms": round(age_ms, 3),
                    "seg_done": int(s.seg_done),
                    "segs_landed": int(s.segs_landed),
                }
            )
        snapshot = {
            "expected_wall_ms": round(wall, 3),
            "rows": rows,
            "counts": counts,
        }
        self.last_snapshot = snapshot
        if self.slo is not None:
            self.slo.set_stream_health(snapshot)
        return snapshot

    def discharge(self, s) -> None:
        """A stream is leaving observation (done, or its last tenant
        collected): close any open incident NOW, without waiting for
        the next cadence tick — a collect can race the cadence gate,
        and an alarm must never dangle past the stream it names."""
        if s.health_state in ALARM_STATES:
            self._emit_recovered(s, 0.0, self._now())
        s.health_state = HEALTHY

    def _emit_stall(self, s, state, age_ms, wall_ms, now) -> None:
        if self.slo is not None:
            self.slo.on_stream_stall(
                s.rids, state=state, age_ms=age_ms,
                expected_wall_ms=wall_ms, seg=s.seg_done, t=now,
            )

    def _emit_recovered(self, s, age_ms, now) -> None:
        if self.slo is not None:
            self.slo.on_stream_recovered(
                s.rids, age_ms=age_ms, t=now
            )
