"""Shape bucketing for the multi-tenant rollout service.

One compiled program exists per distinct ``(scenario_batch,
agent_capacity)`` shape of the batched tick — and a serving workload
left unquantized produces a fresh shape per request mix, which is a
retrace storm by construction (the runtime failure mode the compile
observatory's storm detector and swarmlint's ``retrace`` rule both
exist to catch; Fast Population-Based RL, arxiv 2206.08888, names
compilation cost as THE pitfall of population-batched stepping).

:class:`BucketSpec` quantizes both axes into a small fixed lattice:

- **agent capacity**: each request is padded up to the smallest
  capacity rung that fits it (the pad agents ride as dead slots in
  the existing ``alive`` mask — the protocol already masks every
  reduction on liveness, so padding is semantically free);
- **scenario batch**: each flush of same-capacity requests is split
  into dispatch batches drawn only from the ``batches`` rungs
  (largest-first; a final partial dispatch pads with dead filler
  scenarios up to the smallest rung that covers it).

The service therefore holds at most ``len(capacities) *
len(batches)`` compiled entries — a budget it declares to the
compile observatory (``utils/compile_watch.declare_buckets``), which
turns any excess compile into a structured ``bucket-overflow`` event
instead of a silent 2x latency bill.

**Mesh axes per rung (r18, the 2D-mesh serve plane).**  Every rung
additionally declares WHICH mesh axis its dispatches ride
(:meth:`BucketSpec.mesh_axes_for`):

- the ``capacities`` rungs are **scenario-axis** rungs
  (``('scenarios',)``): the vmapped batched tick, its scenario batch
  shard_map-committed ``P('scenarios')`` — embarrassingly parallel,
  per-scenario state never crosses the axis (jaxlint budget: zero
  per-tick collectives);
- the ``jumbo_capacities`` rungs are **tiles-axis** rungs
  (``('tiles',)``): ONE tenant per dispatch (the batch axis is
  meaningless for a swarm that spans the mesh), routed through the
  r12 spatially-sharded tick (``parallel/spatial.py`` — ring
  collective-permute halo exchange, all-gather-zero contract).

Jumbo rungs sit strictly ABOVE the largest scenario capacity — they
are where the scenario lattice's rejection bound used to be, so a
tenant too big to vmap is now served instead of refused.  The
admission queue keys on the axes tuple, so a jumbo group can never
co-batch (or head-of-line-block) a scenario group.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import List, Tuple

from ..parallel.mesh import SCENARIO_AXIS, TILE_AXIS

#: Default lattice: three capacity rungs x three batch rungs = nine
#: compiled shapes at most — "a handful of cache entries".
DEFAULT_CAPACITIES = (64, 256, 1024)
DEFAULT_BATCHES = (1, 8, 64)

#: The per-rung mesh-axes declarations (module doc).
SCENARIO_AXES: Tuple[str, ...] = (SCENARIO_AXIS,)
TILE_AXES: Tuple[str, ...] = (TILE_AXIS,)


@dataclass(frozen=True)
class BucketSpec:
    """The service's compiled-shape lattice (immutable; the compile
    budget is ``max_shapes``).  ``jumbo_capacities`` (r18) are the
    tiles-axis rungs — strictly above the largest scenario capacity,
    one tenant per dispatch, served by the r12 spatial tick."""

    capacities: Tuple[int, ...] = DEFAULT_CAPACITIES
    batches: Tuple[int, ...] = DEFAULT_BATCHES
    jumbo_capacities: Tuple[int, ...] = ()

    def __post_init__(self):
        for name, rungs in (
            ("capacities", self.capacities), ("batches", self.batches)
        ):
            if not rungs:
                raise ValueError(f"BucketSpec.{name} must be non-empty")
            if any(r <= 0 for r in rungs):
                raise ValueError(
                    f"BucketSpec.{name} must be positive, got {rungs}"
                )
            if tuple(sorted(set(rungs))) != tuple(rungs):
                raise ValueError(
                    f"BucketSpec.{name} must be strictly ascending "
                    f"(the quantizers binary-search them), got {rungs}"
                )
        j = self.jumbo_capacities
        if j:
            if tuple(sorted(set(j))) != tuple(j):
                raise ValueError(
                    "BucketSpec.jumbo_capacities must be strictly "
                    f"ascending, got {j}"
                )
            if j[0] <= self.capacities[-1]:
                raise ValueError(
                    f"jumbo rungs must sit ABOVE the largest scenario "
                    f"capacity {self.capacities[-1]} (they replace its "
                    f"rejection bound), got {j} — a tenant that fits a "
                    "scenario rung must ride the scenario axis"
                )

    @property
    def max_shapes(self) -> int:
        """The compile-cache budget: distinct (batch, capacity) shapes
        the service can ever dispatch.  Jumbo rungs are batch-of-1 by
        construction, so each adds exactly one shape."""
        return (
            len(self.capacities) * len(self.batches)
            + len(self.jumbo_capacities)
        )

    def is_jumbo(self, capacity: int) -> bool:
        return capacity in self.jumbo_capacities

    def mesh_axes_for(self, capacity: int) -> Tuple[str, ...]:
        """The declared mesh axes of ``capacity``'s rung — the thing
        the admission queue keys on and ``swarmscope slo`` renders
        next to each rung's occupancy (module doc)."""
        return TILE_AXES if self.is_jumbo(capacity) else SCENARIO_AXES

    def batches_for(self, capacity: int) -> Tuple[int, ...]:
        """The batch rungs available at ``capacity``: the declared
        lattice for scenario rungs, exactly ``(1,)`` for jumbo rungs
        (one mesh-spanning tenant per dispatch)."""
        return (1,) if self.is_jumbo(capacity) else self.batches

    def capacity_for(self, n_agents: int) -> int:
        """Smallest capacity rung holding ``n_agents`` — the agent-axis
        quantizer (scenario rungs first, then jumbo).  Raises for
        requests past the largest rung (the REJECTION half of the
        padding/eviction contract: an unservable shape must fail
        loudly at submit time, not compile a bespoke program)."""
        if n_agents <= 0:
            raise ValueError(
                f"scenario needs n_agents >= 1, got {n_agents}"
            )
        for cap in self.capacities + self.jumbo_capacities:
            if n_agents <= cap:
                return cap
        largest = (self.jumbo_capacities or self.capacities)[-1]
        raise ValueError(
            f"scenario with {n_agents} agents exceeds the largest "
            f"capacity bucket {largest}; widen BucketSpec."
            "capacities/jumbo_capacities (each rung is one compiled "
            "shape)"
        )

    def split_batch(self, k: int, capacity: int = None) -> List[int]:
        """Dispatch batch sizes covering ``k`` pending scenarios, every
        size a ``batches`` rung (sum >= k; the excess of the final
        dispatch is padded with dead filler scenarios).  ``capacity``
        (r18) selects the rung family: a jumbo capacity's only rung is
        1, so ``k`` jumbo tenants split into ``k`` one-tenant
        dispatches — zero filler, ever.

        Deterministic greedy with a BOUNDED-PAD tail: take the
        largest rung while it fits whole; for each remainder ``r``,
        round UP to the smallest rung ``>= r`` when that wastes at
        most half the dispatch (``rung <= 2*r`` — pad rows still
        compute, so unbounded rounding would trade cheap dispatch
        overhead for expensive dead compute), else take the largest
        rung ``<= r`` and continue; when no rung fits below ``r`` the
        smallest rung above is forced.  Rounding the near-full tail
        up is what keeps a 71-request flush at ``[64, 8]`` instead of
        seven single-scenario dispatches — per-dispatch host overhead
        is the cost the serve layer exists to amortize.
        """
        if k <= 0:
            return []
        rungs = (
            self.batches_for(capacity)
            if capacity is not None else self.batches
        )
        out: List[int] = []
        largest = rungs[-1]
        while k >= largest:
            out.append(largest)
            k -= largest
        while k > 0:
            up = [b for b in rungs if k <= b <= 2 * k]
            if up:
                out.append(up[0])
                break
            fit = [b for b in rungs if b <= k]
            rung = fit[-1] if fit else rungs[0]
            out.append(rung)
            k -= rung
        return out
