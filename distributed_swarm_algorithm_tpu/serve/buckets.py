"""Shape bucketing for the multi-tenant rollout service.

One compiled program exists per distinct ``(scenario_batch,
agent_capacity)`` shape of the batched tick — and a serving workload
left unquantized produces a fresh shape per request mix, which is a
retrace storm by construction (the runtime failure mode the compile
observatory's storm detector and swarmlint's ``retrace`` rule both
exist to catch; Fast Population-Based RL, arxiv 2206.08888, names
compilation cost as THE pitfall of population-batched stepping).

:class:`BucketSpec` quantizes both axes into a small fixed lattice:

- **agent capacity**: each request is padded up to the smallest
  capacity rung that fits it (the pad agents ride as dead slots in
  the existing ``alive`` mask — the protocol already masks every
  reduction on liveness, so padding is semantically free);
- **scenario batch**: each flush of same-capacity requests is split
  into dispatch batches drawn only from the ``batches`` rungs
  (largest-first; a final partial dispatch pads with dead filler
  scenarios up to the smallest rung that covers it).

The service therefore holds at most ``len(capacities) *
len(batches)`` compiled entries — a budget it declares to the
compile observatory (``utils/compile_watch.declare_buckets``), which
turns any excess compile into a structured ``bucket-overflow`` event
instead of a silent 2x latency bill.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import List, Tuple

#: Default lattice: three capacity rungs x three batch rungs = nine
#: compiled shapes at most — "a handful of cache entries".
DEFAULT_CAPACITIES = (64, 256, 1024)
DEFAULT_BATCHES = (1, 8, 64)


@dataclass(frozen=True)
class BucketSpec:
    """The service's compiled-shape lattice (immutable; the compile
    budget is ``max_shapes``)."""

    capacities: Tuple[int, ...] = DEFAULT_CAPACITIES
    batches: Tuple[int, ...] = DEFAULT_BATCHES

    def __post_init__(self):
        for name, rungs in (
            ("capacities", self.capacities), ("batches", self.batches)
        ):
            if not rungs:
                raise ValueError(f"BucketSpec.{name} must be non-empty")
            if any(r <= 0 for r in rungs):
                raise ValueError(
                    f"BucketSpec.{name} must be positive, got {rungs}"
                )
            if tuple(sorted(set(rungs))) != tuple(rungs):
                raise ValueError(
                    f"BucketSpec.{name} must be strictly ascending "
                    f"(the quantizers binary-search them), got {rungs}"
                )

    @property
    def max_shapes(self) -> int:
        """The compile-cache budget: distinct (batch, capacity) shapes
        the service can ever dispatch."""
        return len(self.capacities) * len(self.batches)

    def capacity_for(self, n_agents: int) -> int:
        """Smallest capacity rung holding ``n_agents`` — the agent-axis
        quantizer.  Raises for requests past the largest rung (the
        REJECTION half of the padding/eviction contract: an unservable
        shape must fail loudly at submit time, not compile a bespoke
        program)."""
        if n_agents <= 0:
            raise ValueError(
                f"scenario needs n_agents >= 1, got {n_agents}"
            )
        for cap in self.capacities:
            if n_agents <= cap:
                return cap
        raise ValueError(
            f"scenario with {n_agents} agents exceeds the largest "
            f"capacity bucket {self.capacities[-1]}; widen "
            "BucketSpec.capacities (each rung is one compiled shape)"
        )

    def split_batch(self, k: int) -> List[int]:
        """Dispatch batch sizes covering ``k`` pending scenarios, every
        size a ``batches`` rung (sum >= k; the excess of the final
        dispatch is padded with dead filler scenarios).

        Deterministic greedy with a BOUNDED-PAD tail: take the
        largest rung while it fits whole; for each remainder ``r``,
        round UP to the smallest rung ``>= r`` when that wastes at
        most half the dispatch (``rung <= 2*r`` — pad rows still
        compute, so unbounded rounding would trade cheap dispatch
        overhead for expensive dead compute), else take the largest
        rung ``<= r`` and continue; when no rung fits below ``r`` the
        smallest rung above is forced.  Rounding the near-full tail
        up is what keeps a 71-request flush at ``[64, 8]`` instead of
        seven single-scenario dispatches — per-dispatch host overhead
        is the cost the serve layer exists to amortize.
        """
        if k <= 0:
            return []
        out: List[int] = []
        largest = self.batches[-1]
        while k >= largest:
            out.append(largest)
            k -= largest
        while k > 0:
            up = [b for b in self.batches if k <= b <= 2 * k]
            if up:
                out.append(up[0])
                break
            fit = [b for b in self.batches if b <= k]
            rung = fit[-1] if fit else self.batches[0]
            out.append(rung)
            k -= rung
        return out
