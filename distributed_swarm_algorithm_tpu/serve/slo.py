"""The SLO observatory for the streaming serve loop (r16).

The r13 service could tell you WHAT it computed (per-tenant flight
recorder, bitwise parity) but nothing about what a tenant
*experienced*: how long a request sat in the queue, when its first
results became observable, whether the admission deadline held.  This
module is the host-side half of the observability story the on-device
recorder (utils/telemetry.py) cannot carry — request latency lives in
wall-clock time between host events, not in scan ys.

**Timestamp taxonomy** (all ``time.monotonic`` seconds, host-side,
one :class:`TenantClock` per request):

    submit        request entered the admission queue
    admit         request was assembled into a dispatch group
                  (coalescing decided — rung full or deadline hit)
    launch        the group's first rollout segment was dispatched
    first_result  the host first OBSERVED device output for the
                  tenant's dispatch (the segment-1 probe landed —
                  a real observation, not a dispatch-time guess)
    collect       the result was returned to the caller

Derived latencies (milliseconds):

    time-in-queue = launch - submit       (admission latency)
    ttfr          = first_result - submit (time-to-first-result,
                                           the headline SLO)

Reduction is nearest-rank p50/p95/p99
(``utils/telemetry.latency_percentiles`` — a gated p99 is a latency
some request actually paid).  Gauges (queue depth, in-flight
dispatches) are sampled per pump into a bounded trajectory, and
per-dispatch batch occupancy records the filler fraction (pad rows
still compute — wasted flops the bucket contract trades for bounded
compiles).

**Alert events** ride the same JSONL surface as the flight recorder's
threshold crossings (``utils/telemetry.write_events_jsonl`` →
``events.jsonl``, the file swarmscope reads):

    deadline-miss     a tenant launched later than deadline + grace —
                      the host loop stopped keeping up
    queue-overflow    a submit was rejected at the declared queue bound
    eviction          a tenant left mid-stream (partial results)
    stream-stall      a stream's device heartbeat aged into the
                      watchdog's alarm zone (r24 swarmpulse,
                      serve/health.py)
    stream-recovered  a stalled/wedged stream progressed again (or
                      finished) — the incident closed

The tracker is pure host bookkeeping: no jax import, no device
arrays, so the serve hot loop's ``serve-host-sync`` lint contract is
trivially honest here.

**Live metrics (r19).**  Every alert event ALSO increments a typed
counter on the injected :class:`~..utils.metrics.MetricsRegistry`
(default: the process-global ``METRICS``) — count-for-count with the
events list, because both surfaces update inside the same method
(pinned in tests/test_metrics.py).  The latency stamps feed the
``slo_ttfr_ms``/``slo_queue_ms`` bounded-bucket histograms on
collect (the nearest-rank reduction the percentiles here use,
applied to the binned live record), dispatch occupancy feeds the
per-rung launch/row counters, ``sample`` sets the queue-depth and
in-flight gauges, and ``summary`` records the device-memory
watermark gauge — the surface ``swarmscope live`` and the
``/metrics`` endpoint render while the service runs.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional

from ..utils import metrics as metricslib
from ..utils.telemetry import latency_percentiles

#: Default admission deadline: how long a partially-filled rung may
#: coalesce before it is dispatched padded (seconds).
DEFAULT_DEADLINE_S = 0.05

#: Gauge-trajectory bound: past this many samples the stored
#: trajectory is decimated 2x and the sampling stride doubles, so a
#: long soak keeps a full-span (coarser) trajectory in O(1) memory.
MAX_GAUGE_SAMPLES = 4096


@dataclass
class TenantClock:
    """One request's monotonic stamps (None = not reached)."""

    rid: int
    submit: float
    admit: Optional[float] = None
    launch: Optional[float] = None
    first_result: Optional[float] = None
    collect: Optional[float] = None

    def queue_ms(self) -> Optional[float]:
        if self.launch is None:
            return None
        return 1e3 * (self.launch - self.submit)

    def ttfr_ms(self) -> Optional[float]:
        if self.first_result is None:
            return None
        return 1e3 * (self.first_result - self.submit)


class SloTracker:
    """Per-tenant latency stamps + gauges + alert events.

    ``clock`` is injectable (tests drive deterministic timelines);
    everything else is plain lists/dicts — ``summary()`` is the
    JSON-safe roll-up the run directory stores (``slo.json``) and
    ``swarmscope slo`` renders."""

    def __init__(
        self,
        deadline_s: float = DEFAULT_DEADLINE_S,
        miss_grace_s: Optional[float] = None,
        clock: Callable[[], float] = time.monotonic,
        max_gauge_samples: int = MAX_GAUGE_SAMPLES,
        memory_probe: Optional[Callable[[], tuple]] = None,
        metrics: Optional[metricslib.MetricsRegistry] = None,
    ):
        if deadline_s <= 0:
            raise ValueError(
                f"deadline_s must be > 0, got {deadline_s} (the "
                "coalescing wait bound)"
            )
        self.deadline_s = float(deadline_s)
        #: A launch later than deadline + grace is a MISS: the
        #: deadline itself is the design point (a coalescing group
        #: legitimately launches AT its deadline), so the miss bar
        #: sits one grace above it.  Default grace = the deadline.
        self.miss_grace_s = float(
            miss_grace_s if miss_grace_s is not None else deadline_s
        )
        self.clock = clock
        self.t0 = clock()
        #: Device-memory watermark probe (r17, the runtime half of
        #: the memory observatory): a callable returning
        #: ``(peak_bytes | None, skip_reason)``.  Injected by the
        #: serve layer (``utils.trace.device_memory_watermark``) —
        #: the tracker itself stays jax-free, so backends without
        #: allocator stats surface a STRUCTURED skip in the summary,
        #: never a silent zero a gate would then trust.
        self.memory_probe = memory_probe
        #: IN-FLIGHT (and cancelled-while-queued) requests only:
        #: ``on_collect`` compacts a finished clock into the float
        #: sample lists below and drops the object, so a long-running
        #: service holds one clock per outstanding request, not per
        #: request ever served.  (The latency SAMPLE lists still grow
        #: one float per request — a tracker covers one observation
        #: window; a service that runs for weeks rotates trackers the
        #: way bench_soak.py does after its warm pass.)
        self.clocks: Dict[int, TenantClock] = {}
        self._ttfr_ms: List[float] = []
        self._queue_ms: List[float] = []
        #: Alert events, JSONL-ready (monotonic ms offsets from t0).
        self.events: List[dict] = []
        #: [(t_ms, queue_depth, in_flight_dispatches), ...] — the
        #: queue-depth trajectory, stride-decimated past the bound.
        self.gauges: List[tuple] = []
        self._gauge_stride = 1
        self._gauge_skip = 0
        self._max_gauge_samples = int(max_gauge_samples)
        #: Dispatch-occupancy running totals (O(1), not per-dispatch
        #: rows): filler fraction only ever needs the sums.
        self.n_dispatches = 0
        self._dispatch_rows = 0
        self._dispatch_real = 0
        #: Per-RUNG occupancy (r18): rung label -> [dispatches, rows,
        #: real rows, mesh label].  O(#rungs) — the bucket lattice is
        #: small by design — so a long-lived service pays nothing per
        #: dispatch beyond three adds.  The mesh label ("scenarios x8",
        #: "tiles x2", "device") is what ``swarmscope slo`` renders
        #: next to each rung's occupancy line, so an operator can see
        #: which axis a rung rides.
        self._rungs: Dict[str, list] = {}
        self.deadline_misses = 0
        self.queue_overflows = 0
        self.evictions = 0
        self.stream_stalls = 0
        self.stream_recoveries = 0
        #: Latest watchdog snapshot (r24 swarmpulse): the per-stream
        #: health table + state counts serve/health.py pushes here
        #: each check — what ``summary()``, the Prometheus gauge, and
        #: ``swarmscope health`` all render.
        self.stream_health: Optional[dict] = None
        #: Observation-window label ``rotate()`` stamps on successor
        #: trackers (None for the first window).
        self.window: Optional[str] = None
        #: Live metrics plane (r19): the alert counters increment in
        #: the SAME methods that append to ``events`` (alert parity —
        #: the two surfaces can never drift), the latency histograms
        #: bin the same derived milliseconds the percentile lists
        #: hold, and the gauges mirror ``sample``.  Registration is
        #: idempotent across trackers sharing one registry.
        self.metrics = metricslib.METRICS if metrics is None else metrics
        reg = self.metrics
        self._m_ttfr = reg.histogram(
            "slo_ttfr_ms",
            "Time-to-first-result per request (submit -> first "
            "observed device output), ms",
        )
        self._m_queue = reg.histogram(
            "slo_queue_ms",
            "Time-in-queue per request (submit -> launch), ms",
        )
        self._m_miss = reg.counter(
            "serve_deadline_miss_total",
            "Requests launched later than deadline + grace",
        )
        self._m_overflow = reg.counter(
            "serve_queue_overflow_total",
            "Submits rejected at the declared queue bound",
        )
        self._m_evict = reg.counter(
            "serve_evictions_total",
            "Tenants evicted mid-stream (partial results)",
        )
        self._m_depth = reg.gauge(
            "serve_queue_depth", "Admission-queue depth (requests)"
        )
        self._m_flight = reg.gauge(
            "serve_in_flight", "In-flight dispatches (segments left)"
        )
        self._m_launches = reg.counter(
            "serve_dispatch_launches_total",
            "Coalesced dispatch launches", labels=("rung",),
        )
        self._m_rows = reg.counter(
            "serve_dispatch_rows_total",
            "Dispatched batch rows incl. filler padding",
            labels=("rung",),
        )
        self._m_real = reg.counter(
            "serve_dispatch_real_rows_total",
            "Dispatched batch rows holding real tenants",
            labels=("rung",),
        )
        self._m_peak = reg.gauge(
            "device_peak_bytes",
            "Device allocator peak-bytes watermark (max over "
            "addressable devices)",
        )
        self._m_stall = reg.counter(
            "serve_stream_stalls_total",
            "Streams whose device heartbeat aged into the watchdog's "
            "alarm zone (stalled/wedged)",
        )
        self._m_recover = reg.counter(
            "serve_stream_recovered_total",
            "Alarmed streams that progressed again or finished",
        )
        self._m_health = reg.gauge(
            "serve_stream_health",
            "In-flight streams per watchdog health state",
            labels=("state",),
        )

    # -- stamps ------------------------------------------------------------
    def _ms(self, t: float) -> float:
        return 1e3 * (t - self.t0)

    def on_submit(self, rid: int) -> None:
        self.clocks[rid] = TenantClock(rid=rid, submit=self.clock())

    def on_admit(self, rid: int) -> None:
        c = self.clocks.get(rid)
        if c is not None and c.admit is None:
            c.admit = self.clock()

    def on_launch(self, rids) -> None:
        """Stamp a dispatch group's launch; fires one deadline-miss
        event per tenant whose queue time overran deadline + grace."""
        now = self.clock()
        bar_ms = 1e3 * (self.deadline_s + self.miss_grace_s)
        for rid in rids:
            c = self.clocks.get(rid)
            if c is None or c.launch is not None:
                continue
            c.launch = now
            q_ms = c.queue_ms()
            if q_ms is not None and q_ms > bar_ms:
                self.deadline_misses += 1
                self._m_miss.inc()
                self.events.append(
                    {
                        "event": "deadline-miss",
                        "t_ms": round(self._ms(now), 3),
                        "rid": rid,
                        "queue_ms": round(q_ms, 3),
                        "deadline_ms": round(1e3 * self.deadline_s, 3),
                        "grace_ms": round(1e3 * self.miss_grace_s, 3),
                    }
                )

    def on_first_result(self, rids, t: Optional[float] = None) -> None:
        """Idempotent: only the FIRST observation stamps.  ``t``
        backdates the stamp to a moment another observer already
        recorded — the r19 device callback hands the device-finish
        time here, so TTFR measures the device, not the pump cadence
        (ROADMAP item 2b)."""
        now = self.clock() if t is None else float(t)
        for rid in rids:
            c = self.clocks.get(rid)
            if c is not None and c.first_result is None:
                c.first_result = now

    def on_collect(self, rid: int) -> None:
        c = self.clocks.get(rid)
        if c is not None and c.collect is None:
            c.collect = now = self.clock()
            # A result collected before any probe observation (e.g.
            # a single-segment dispatch drained straight through)
            # still has a first observable moment: collection itself.
            if c.first_result is None:
                c.first_result = now
            # Compact: the derived latencies are all the reduction
            # ever reads — keep two floats, drop the clock.
            t = c.ttfr_ms()
            if t is not None:
                self._ttfr_ms.append(t)
                self._m_ttfr.observe(t)
            q = c.queue_ms()
            if q is not None:
                self._queue_ms.append(q)
                self._m_queue.observe(q)
            del self.clocks[rid]

    # -- alert events ------------------------------------------------------
    def on_queue_overflow(self, depth: int, bound: int) -> None:
        self.queue_overflows += 1
        self._m_overflow.inc()
        self.events.append(
            {
                "event": "queue-overflow",
                "t_ms": round(self._ms(self.clock()), 3),
                "depth": int(depth),
                "bound": int(bound),
            }
        )

    def on_eviction(self, rid: int, ticks: int) -> None:
        self.evictions += 1
        self._m_evict.inc()
        self.events.append(
            {
                "event": "eviction",
                "t_ms": round(self._ms(self.clock()), 3),
                "rid": rid,
                "ticks": int(ticks),
            }
        )

    def on_stream_stall(
        self, rids, state: str, age_ms: float,
        expected_wall_ms: float, seg: Optional[int] = None,
        t: Optional[float] = None,
    ) -> None:
        """One stream entered the watchdog's alarm zone (r24): the
        counter and the event update HERE, in the same method — the
        count-for-count parity contract every alert keeps."""
        self.stream_stalls += 1
        self._m_stall.inc()
        now = self.clock() if t is None else float(t)
        self.events.append(
            {
                "event": "stream-stall",
                "t_ms": round(self._ms(now), 3),
                "rids": list(rids),
                "state": state,
                "age_ms": round(float(age_ms), 3),
                "expected_wall_ms": round(float(expected_wall_ms), 3),
                "seg": None if seg is None else int(seg),
            }
        )

    def on_stream_recovered(
        self, rids, age_ms: float, t: Optional[float] = None
    ) -> None:
        self.stream_recoveries += 1
        self._m_recover.inc()
        now = self.clock() if t is None else float(t)
        self.events.append(
            {
                "event": "stream-recovered",
                "t_ms": round(self._ms(now), 3),
                "rids": list(rids),
                "age_ms": round(float(age_ms), 3),
            }
        )

    def set_stream_health(self, snapshot: dict) -> None:
        """Install the watchdog's latest per-stream table and mirror
        the state counts onto the ``serve_stream_health`` gauge (the
        label set is the fixed four-state ladder — bounded
        cardinality by construction)."""
        self.stream_health = snapshot
        for state, n in snapshot.get("counts", {}).items():
            self._m_health.set(int(n), state=state)

    # -- gauges ------------------------------------------------------------
    def sample(self, queue_depth: int, in_flight: int) -> None:
        """One pump's gauge sample; decimates 2x (and doubles the
        stride) at the bound so a long soak keeps a full-span
        trajectory instead of a truncated prefix."""
        # The live gauges update EVERY pump (two dict writes), ahead
        # of the stride decimation: a scrape between strides must see
        # the current depth, not the last stored sample.
        self._m_depth.set(queue_depth)
        self._m_flight.set(in_flight)
        self._gauge_skip += 1
        if self._gauge_skip < self._gauge_stride:
            return
        self._gauge_skip = 0
        self.gauges.append(
            (
                round(self._ms(self.clock()), 3),
                int(queue_depth),
                int(in_flight),
            )
        )
        if len(self.gauges) > self._max_gauge_samples:
            self.gauges = self.gauges[::2]
            self._gauge_stride *= 2

    def on_dispatch(
        self, size: int, n_real: int,
        rung: Optional[str] = None, mesh: Optional[str] = None,
    ) -> None:
        """One launched dispatch: ``size`` padded rows, ``n_real``
        real tenants.  ``rung`` (r18) attributes the occupancy to a
        bucket rung (e.g. ``"cap=64 b=8"``) and ``mesh`` names the
        axis it rides (``"scenarios x8"`` / ``"tiles x2"`` /
        ``"device"``) — the per-rung view the aggregate filler
        fraction hides (a zero-filler jumbo rung and a padded
        scenario rung average into a number describing neither)."""
        self.n_dispatches += 1
        self._dispatch_rows += int(size)
        self._dispatch_real += int(n_real)
        rung_label = rung if rung is not None else "-"
        self._m_launches.inc(rung=rung_label)
        self._m_rows.inc(int(size), rung=rung_label)
        self._m_real.inc(int(n_real), rung=rung_label)
        if rung is not None:
            row = self._rungs.setdefault(
                rung, [0, 0, 0, mesh or "device"]
            )
            row[0] += 1
            row[1] += int(size)
            row[2] += int(n_real)

    # -- window rotation ---------------------------------------------------
    def rotate(self, window: Optional[str] = None) -> "SloTracker":
        """Close this observation window and return its successor —
        the helper the r16 notes promised ("a weeks-long service
        rotates trackers per observation window").  The successor:

        - shares the clock, deadline/grace, gauge bound, memory
          probe, and the METRICS REGISTRY (registration is
          idempotent, and the Prometheus counters stay monotone
          across windows — a scrape never sees totals reset);
        - CARRIES the alert-counter totals (misses, overflows,
          evictions, stalls, recoveries) so the tracker attributes
          match their metric twins count-for-count across the
          rotation;
        - takes OWNERSHIP of the in-flight clocks — an open request's
          latency lands in the window that observes its collect — and
          of the latest health snapshot (the streams are still live);
        - starts EMPTY everywhere else: latency samples, events,
          gauge trajectory, dispatch/rung occupancy.  This tracker
          keeps its closed-window record for archival (``summary()``
          still works) but receives no new observations.

        The per-window state is therefore bounded by the window, not
        by service lifetime (tested in tests/test_health.py)."""
        nxt = SloTracker(
            deadline_s=self.deadline_s,
            miss_grace_s=self.miss_grace_s,
            clock=self.clock,
            max_gauge_samples=self._max_gauge_samples,
            memory_probe=self.memory_probe,
            metrics=self.metrics,
        )
        nxt.window = window
        nxt.deadline_misses = self.deadline_misses
        nxt.queue_overflows = self.queue_overflows
        nxt.evictions = self.evictions
        nxt.stream_stalls = self.stream_stalls
        nxt.stream_recoveries = self.stream_recoveries
        nxt.clocks = self.clocks
        self.clocks = {}
        nxt.stream_health = self.stream_health
        return nxt

    # -- reduction ---------------------------------------------------------
    def ttfr_ms(self) -> List[float]:
        """Collected samples plus any in-flight request that already
        has an observed first result."""
        return self._ttfr_ms + [
            c.ttfr_ms() for c in self.clocks.values()
            if c.ttfr_ms() is not None
        ]

    def queue_ms(self) -> List[float]:
        return self._queue_ms + [
            c.queue_ms() for c in self.clocks.values()
            if c.queue_ms() is not None
        ]

    def filler_fraction(self) -> float:
        """Wasted-flops fraction over all dispatches: pad rows /
        total rows (0.0 with no dispatches)."""
        total = self._dispatch_rows
        return (total - self._dispatch_real) / total if total else 0.0

    def summary(self) -> dict:
        """JSON-safe roll-up — the ``slo.json`` run-dir artifact and
        the ``swarmscope slo`` rendering surface."""
        out = {
            "deadline_ms": round(1e3 * self.deadline_s, 3),
            "miss_grace_ms": round(1e3 * self.miss_grace_s, 3),
            "ttfr_ms": latency_percentiles(self.ttfr_ms()),
            "queue_ms": latency_percentiles(self.queue_ms()),
            "deadline_misses": self.deadline_misses,
            "queue_overflows": self.queue_overflows,
            "evictions": self.evictions,
            "stream_stalls": self.stream_stalls,
            "stream_recoveries": self.stream_recoveries,
            "dispatches": self.n_dispatches,
            "filler_fraction": round(self.filler_fraction(), 4),
            "rungs": {
                label: {
                    "dispatches": row[0],
                    "filler_fraction": round(
                        (row[1] - row[2]) / row[1] if row[1] else 0.0,
                        4,
                    ),
                    "mesh": row[3],
                }
                for label, row in sorted(self._rungs.items())
            },
            "gauge_stride": self._gauge_stride,
            "queue_depth": [list(g) for g in self.gauges],
        }
        if self.window is not None:
            out["window"] = self.window
        if self.stream_health is not None:
            out["stream_health"] = self.stream_health
        if self.memory_probe is not None:
            peak, reason = self.memory_probe()
            out["device_peak_bytes"] = (
                int(peak) if peak is not None else None
            )
            if peak is None:
                out["device_memory_skip"] = reason
            else:
                self._m_peak.set(int(peak))
        return out
