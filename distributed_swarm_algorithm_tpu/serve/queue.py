"""Admission queue with deadline coalescing (r16).

The r13 service dispatched on an explicit ``flush()``: whoever called
it decided the batching, and a caller submitting one request at a
time degraded to batch-of-1 dispatches.  The streaming loop inverts
that: requests ACCUMULATE here, grouped by their compiled-shape key
``(capacity, n_tasks)``, and a group is released for dispatch when

- it can fill the LARGEST batch rung (a full dispatch wastes no pad
  rows — release immediately; waiting longer only adds latency), or
- its oldest request's admission deadline expires (release the whole
  group, split over the rungs by ``BucketSpec.split_batch`` — the
  bounded-pad tail applies, so a deadline flush pays at most half a
  dispatch of filler).

This is the continuous-batching admission policy of LLM serving
mapped onto the bucket lattice: the deadline bounds time-in-queue,
the rung-full fast path bounds wasted flops, and both bounds are
DECLARED (the SLO observatory gates the deadline; the occupancy
gauge shows the filler).  The queue holds host-side request records
only — nothing here touches a device array, so admission can never
serialize the dispatch pipeline (the ``serve-host-sync`` contract).
"""

from __future__ import annotations

import time
from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

from ..utils import metrics as metricslib
from ..utils.trace import QUEUE_SPAN, TRACER, SpanTracer
from .batched import ScenarioRequest
from .buckets import BucketSpec


class QueueOverflowError(RuntimeError):
    """Submit rejected at the declared queue bound — the service's
    loud backpressure signal (silently buffering unbounded requests
    would trade an honest rejection for a latency cliff)."""


@dataclass
class QueuedRequest:
    """One request awaiting admission."""

    rid: int
    req: ScenarioRequest
    capacity: int
    n_tasks: int
    submit_t: float
    deadline_t: float

    @property
    def key(self) -> tuple:
        """The compiled-shape group key.  The rung's declared mesh
        axes (r18) are derived from the capacity by the spec, so the
        (capacity, n_tasks) pair remains the full key — a jumbo
        capacity IS a different capacity, hence a different group, and
        jumbo groups can never co-batch or head-of-line-block a
        scenario group."""
        return (self.capacity, self.n_tasks)


class AdmissionQueue:
    """FIFO groups keyed by compiled shape, released by rung-full or
    deadline — see the module doc for the policy."""

    def __init__(
        self,
        spec: BucketSpec,
        deadline_s: float,
        clock=time.monotonic,
        tracer: Optional[SpanTracer] = None,
        metrics: Optional[metricslib.MetricsRegistry] = None,
    ):
        if deadline_s <= 0:
            raise ValueError(
                f"deadline_s must be > 0, got {deadline_s}"
            )
        self.spec = spec
        self.deadline_s = float(deadline_s)
        self.clock = clock
        #: Span registry (r17): each released request emits one
        #: RETROSPECTIVE ``queue.wait`` span from its already-stamped
        #: submit time — nothing to leak across pump cycles, and the
        #: emission shares the queue's clock (= the SLO tracker's) so
        #: span edges and latency stamps agree.
        self.tracer = TRACER if tracer is None else tracer
        #: Live metrics (r19): admissions by capacity rung (the label
        #: set is bounded by the spec's lattice) and releases by the
        #: POLICY that freed them — "rung-full" (the zero-pad fast
        #: path), "deadline" (oldest entry expired), "force" (drain),
        #: "targeted" (a blocking collect released one group).  The
        #: reason split is what the aggregate release count hides: a
        #: deadline-dominated stream is paying filler for its ladder
        #: (ROADMAP item 2a), a rung-full-dominated one is healthy.
        self.metrics = metricslib.METRICS if metrics is None else metrics
        self._m_admit = self.metrics.counter(
            "serve_admissions_total",
            "Requests admitted to the queue", labels=("cap",),
        )
        self._m_release = self.metrics.counter(
            "serve_releases_total",
            "Requests released to dispatch, by release policy",
            labels=("reason",),
        )
        #: (capacity, n_tasks) -> FIFO of QueuedRequest.
        self._groups: Dict[tuple, List[QueuedRequest]] = {}

    @property
    def depth(self) -> int:
        return sum(len(g) for g in self._groups.values())

    def push(self, rid: int, req: ScenarioRequest, capacity: int,
             n_tasks: int) -> QueuedRequest:
        now = self.clock()
        entry = QueuedRequest(
            rid=rid, req=req, capacity=capacity, n_tasks=n_tasks,
            submit_t=now, deadline_t=now + self.deadline_s,
        )
        self._groups.setdefault(entry.key, []).append(entry)
        self._m_admit.inc(cap=capacity)
        return entry

    def remove(self, rid: int) -> bool:
        """Cancel a queued request (queued-tenant eviction); False if
        ``rid`` is not queued."""
        for key, group in self._groups.items():
            for i, entry in enumerate(group):
                if entry.rid == rid:
                    del group[i]
                    if not group:
                        del self._groups[key]
                    return True
        return False

    def __contains__(self, rid: int) -> bool:
        return any(
            e.rid == rid for g in self._groups.values() for e in g
        )

    def _emit_release(self, key, entries, now) -> None:
        """One retrospective queue-wait span per released request.
        Guarded on ``enabled`` so the disabled path pays exactly one
        attribute check per release, not a per-entry loop."""
        if not self.tracer.enabled:
            return
        for e in entries:
            self.tracer.emit(
                QUEUE_SPAN, e.submit_t, now,
                rid=e.rid, capacity=key[0], n_tasks=key[1],
            )

    # -- release policy ----------------------------------------------------
    def pop_ready(
        self, now=None, force: bool = False
    ) -> List[Tuple[tuple, List[QueuedRequest], int]]:
        """Dispatch groups due at ``now``: ``[(key, entries, size)]``
        with ``size`` the batch rung each dispatch pads to.

        Rung-full groups release a largest-rung dispatch per fill;
        deadline-expired (or ``force``-flushed) groups release
        entirely via ``split_batch`` (bounded-pad tail).  FIFO within
        a group is preserved — admission order is dispatch order, so
        latency accounting is honest per tenant.  Rung families are
        PER CAPACITY (r18): a jumbo group's only rung is 1, so a
        jumbo tenant releases the pump cycle it arrives — its
        mesh-spanning dispatch never waits on coalescing, and the
        scenario groups keep coalescing independently (no cross-rung
        head-of-line blocking, pinned in tests/test_serve_2d.py)."""
        now = self.clock() if now is None else now
        out: List[Tuple[tuple, List[QueuedRequest], int]] = []
        for key in sorted(self._groups):
            group = self._groups[key]
            capacity = key[0]
            largest = self.spec.batches_for(capacity)[-1]
            while len(group) >= largest:
                out.append((key, group[:largest], largest))
                del group[:largest]
                self._m_release.inc(largest, reason="rung-full")
            if group and (force or now >= group[0].deadline_t):
                reason = "force" if force else "deadline"
                for size in self.spec.split_batch(
                    len(group), capacity
                ):
                    take = group[: min(size, len(group))]
                    del group[: len(take)]
                    out.append((key, take, size))
                    self._m_release.inc(len(take), reason=reason)
        self._groups = {k: g for k, g in self._groups.items() if g}
        for key, entries, _ in out:
            self._emit_release(key, entries, now)
        return out

    def pop_group(self, key) -> List[Tuple[tuple, List[QueuedRequest], int]]:
        """Release ONE shape group now, split over the rungs — the
        targeted drain a blocking collect on a queued rid uses, so
        unrelated groups keep coalescing toward their own rung or
        deadline instead of being force-flushed at partial fill."""
        group = self._groups.pop(key, None)
        if not group:
            return []
        out: List[Tuple[tuple, List[QueuedRequest], int]] = []
        for size in self.spec.split_batch(len(group), key[0]):
            take = group[: min(size, len(group))]
            del group[: len(take)]
            out.append((key, take, size))
            self._m_release.inc(len(take), reason="targeted")
        now = self.clock()
        for k, entries, _ in out:
            self._emit_release(k, entries, now)
        return out

    def flush_all(self):
        """Release everything now (the drain path)."""
        return self.pop_ready(force=True)
