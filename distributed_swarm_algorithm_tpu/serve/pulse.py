"""swarmpulse — per-segment device-progress heartbeats (r24).

The r19 probe stamped ONE moment per stream: segment 1's completion,
single-device streams only, feeding TTFR.  This module generalizes it
into the serve plane's liveness sensor: EVERY segment rotation of
EVERY stream class routes one tiny data-dependent leaf through a
``jax.debug.callback`` stamp, so each in-flight stream carries a
monotonically advancing ``last_device_progress`` timestamp and the
pump can harvest completed segments from the registry instead of
host-polling ``is_ready`` (ROADMAP item 5's "remaining r19 edge").

**The stamp programs.**  Two, both tiny copies whose callback operand
is the segment's output leaf — the data dependency is what pins the
callback AFTER the segment's computation; the runtime cannot run it
earlier:

- :func:`pulse_stamp` — single-device streams: one jitted copy, one
  callback, one stamp per segment (the r19 ``_probe_stamp`` shape
  plus a segment index).
- ``serve.batched.pulse_stamp_sharded`` — mesh-committed carries
  (scenario-sharded and jumbo/spatial): the same copy shard_map'd
  over the serve mesh, so the callback fires ONCE PER DEVICE with a
  linearized shard index.  Per-shard stamps are reduced host-side in
  :func:`pulse_drain` — a segment is complete when all ``n_shards``
  stamps landed, its completion time the max over shards (the
  straggler defines the segment, exactly like the device itself).
  This is the cross-device design the r19 review deferred: no
  collective, no cross-device gather on the serving path — each
  device reports only its own block, and the reduction is host
  arithmetic over a dict.

**The token registry.**  Module-level and lock-guarded because the
callbacks run on the runtime's threads, not the pump's.  One token
per stream, allocated at first launch (:func:`pulse_open`), wrapped
to the i32 domain the traced scalar rides in; the dicts are bounded
by what is in flight (:func:`pulse_close` on collect/abandon — the
r13 result-store discipline).  The callback touches ONLY these dicts
and only under ``_PROBE_LOCK``; the pump consumes stamps
single-threadedly via :func:`pulse_drain`.

Callbacks OFF is the r10 gate discipline: the service never imports a
stamp into its launch path — the probe reverts to the LITERAL
pre-r19 ``jnp.copy(states.tick)`` expression and harvest reverts to
``is_ready`` polling, so the disabled service's compiled set is
byte-identical to the r16 service (pinned in tests/test_metrics.py).
"""

from __future__ import annotations

import itertools
import threading
from typing import Callable, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp

_PROBE_TOKENS = itertools.count()
_PROBE_LOCK = threading.Lock()
#: token -> {segment index -> {shard index -> request-clock stamp}}.
#: Consumed segments are deleted by ``pulse_drain`` as soon as every
#: shard stamped, so an entry holds at most the in-flight segment
#: per shard, not the stream's history.
_PROBE_LANDED: Dict[int, Dict[int, Dict[int, float]]] = {}
#: token -> the stream's SLO clock (registered at open, read by the
#: callback; popped on close so neither dict outlives its stream).
_PROBE_CLOCKS: Dict[int, Callable[[], float]] = {}
#: token -> stamps expected per segment: 1 for single-device streams,
#: ``mesh.size`` for shard_map'd stamps (one per device).
_PROBE_SHARDS: Dict[int, int] = {}


def _pulse_landed_cb(token, seg, shard, _leaf) -> None:
    """The device-side heartbeat: one dict write under the lock.
    ``_leaf`` is the segment's output leaf — unused, but its presence
    as an operand is the data dependency that pins the callback AFTER
    the segment's computation."""
    tok, sg, sh = int(token), int(seg), int(shard)
    with _PROBE_LOCK:
        clock = _PROBE_CLOCKS.get(tok)
        if clock is not None:
            _PROBE_LANDED.setdefault(tok, {}).setdefault(
                sg, {}
            )[sh] = float(clock())


@jax.jit
def pulse_stamp(leaf, token, seg):
    """Single-device segment stamp: the same independent copy the
    host-poll probe makes, plus the observation effect.  ``token``
    and ``seg`` are traced i32 scalars (fresh Python ints would be
    fresh constants — a retrace per dispatch)."""
    jax.debug.callback(
        _pulse_landed_cb, token, seg, jnp.int32(0), leaf
    )
    return jnp.copy(leaf)


def pulse_open(clock: Callable[[], float], n_shards: int = 1) -> int:
    """Allocate a stream's heartbeat token and register its clock and
    expected per-segment stamp count.  Wrapped to the i32 domain the
    traced scalar rides in: only IN-FLIGHT tokens must be unique, and
    2^31 concurrent streams is not a regime."""
    token = next(_PROBE_TOKENS) % (2 ** 31)
    with _PROBE_LOCK:
        _PROBE_CLOCKS[token] = clock
        _PROBE_SHARDS[token] = max(1, int(n_shards))
    return token


def pulse_drain(
    token: Optional[int], next_seg: int
) -> Tuple[Optional[float], List[Tuple[int, float]]]:
    """Consume landed stamps: ``(latest stamp time or None,
    [(seg, completion time), ...])`` for the consecutive run of fully
    stamped segments starting at ``next_seg``.  ``latest`` advances on
    PARTIAL segments too (a straggling shard's peers still prove
    progress — the heartbeat the watchdog ages).  Completed segments
    are deleted from the registry; per-device program order makes
    completion consecutive, so a consecutive cursor loses nothing."""
    if token is None:
        return None, []
    out: List[Tuple[int, float]] = []
    latest: Optional[float] = None
    with _PROBE_LOCK:
        expected = _PROBE_SHARDS.get(token, 1)
        segs = _PROBE_LANDED.get(token)
        if segs:
            latest = max(
                t for sh in segs.values() for t in sh.values()
            )
            k = next_seg
            while True:
                shards = segs.get(k)
                if shards is None or len(shards) < expected:
                    break
                out.append((k, max(shards.values())))
                del segs[k]
                k += 1
            if not segs:
                del _PROBE_LANDED[token]
    return latest, out


def pulse_close(token: Optional[int]) -> None:
    """Drop a stream's token from every registry (collected or
    abandoned before its drain): the dicts are bounded by what is in
    flight, the r13 result-store discipline."""
    if token is None:
        return
    with _PROBE_LOCK:
        _PROBE_CLOCKS.pop(token, None)
        _PROBE_LANDED.pop(token, None)
        _PROBE_SHARDS.pop(token, None)
