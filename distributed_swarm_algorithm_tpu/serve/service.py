"""The multi-tenant rollout service: submit scenarios, collect results.

Two host loops over serve/batched.py + serve/buckets.py:

**One-shot** (r13) — the caller decides the batching:

    svc = RolloutService(cfg, n_steps=50)
    rid = svc.submit(ScenarioRequest(n_agents=100, seed=7))
    ...
    svc.flush()                      # dispatch everything pending
    result = svc.collect(rid)        # block on THAT dispatch only

**Streaming** (r16) — continuous batching with an SLO observatory:

    svc = StreamingService(cfg, n_steps=50, segment_steps=10,
                           deadline_s=0.05)
    rid = svc.submit(req)            # enters the admission queue
    while serving:
        svc.pump()                   # admit due rungs, rotate
                                     # segments, harvest results
    result = svc.collect(rid)        # full — or partial after evict()
    print(svc.slo.summary())         # p50/p95/p99 TTFR, queue depth

``StreamingService`` replaces the explicit flush with an admission
queue (serve/queue.py): requests coalesce into bucket rungs and
dispatch when a rung fills or their deadline expires, rollouts run in
fixed SEGMENTS so results stream and tenants can leave (``evict``)
or arrive mid-stream, and every request's latency is stamped into the
SLO tracker (serve/slo.py) — the heavy-traffic surface
benchmarks/bench_soak.py gates.

``flush`` groups pending requests by capacity bucket, splits each
group into batch-rung dispatches (serve/buckets.py), materializes the
padded states, and launches the compiled batched rollout WITHOUT
blocking: jax's async dispatch queues the device work, so the host is
already materializing dispatch k+1 while dispatch k executes, and the
donated state buffers go straight back to XLA — the double-buffered
submit/collect loop of the r13 design.  ``collect`` is keyed by
request id and blocks only on the dispatch that holds it, so results
may be consumed in ANY order relative to submission (out-of-order
completion is the normal case for a mixed-bucket stream).

Collected results are evicted from the service (the result store is
bounded by what is in flight, not by service lifetime); collecting an
unknown or already-collected id raises ``KeyError``.

Compile budget: the service declares ``spec.max_shapes`` to the
compile observatory under the ``"serve-batched-rollout"`` entry —
with the observatory enabled (``DSA_COMPILE_WATCH=1``), any compile
past the bucket lattice fires a structured ``bucket-overflow`` event
(utils/compile_watch.py), and benchmarks/bench_multitenant.py gates
the count as a fixed-name "compiles" row.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Callable, Dict, List, Optional, Set, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..state import SwarmState
from ..utils import compile_watch
from ..utils import metrics as metricslib
from ..utils.config import DEFAULT_CONFIG, SwarmConfig
from ..utils.trace import (
    COALESCE_SPAN,
    COLLECT_SPAN,
    EVICT_SPAN,
    FLUSH_SPAN,
    HARVEST_EVENT,
    LAUNCH_SPAN,
    OVERFLOW_EVENT,
    SEGMENT_SPAN,
    TRACER,
    SpanTracer,
    device_memory_watermark,
)
from ..utils.telemetry import (
    TelemetrySummary,
    concat_telemetry,
    tenant_telemetry,
)
from .batched import (
    MATERIALIZE_ENTRY,
    SERVE_ENTRY,
    SERVE_SHARDED_ENTRY,
    ScenarioRequest,
    batched_rollout,
    batched_rollout_sharded,
    materialize_batch,
    materialize_scenario,
    pulse_stamp_sharded,
    shard_scenarios,
    tenant_state,
    validate_request,
    validate_serve_config,
)
from .buckets import BucketSpec
from .health import HealthMonitor
from .pulse import pulse_close, pulse_drain, pulse_open, pulse_stamp
from .queue import AdmissionQueue, QueueOverflowError
from .slo import DEFAULT_DEADLINE_S, SloTracker

#: Compile-observatory entry the jumbo rung's dispatches land under —
#: the r12 spatial rollout IS the jumbo program (its collective
#: contract is already budgeted; the service only declares the bucket
#: count for its segment schedule).
JUMBO_ENTRY = "swarm-rollout-spatial"


# ---------------------------------------------------------------------------
# swarmpulse (r24): per-segment device heartbeats for EVERY stream
# class, generalizing the r19 segment-1 probe.  The machinery —
# token registry, the completion callback, the single-device and
# shard_map'd stamp programs — lives in serve/pulse.py (and
# batched.pulse_stamp_sharded for the mesh classes); the service only
# orchestrates: open a token at first launch, route every segment's
# tick leaf through a stamp, drain completed segments at harvest
# (callback-driven — no `is_ready` host polls while callbacks are
# on), and close the token when the stream leaves.  Callbacks OFF
# reverts the probe to the LITERAL pre-r19 `jnp.copy(states.tick)`
# and harvest to `is_ready` polling — the disabled service's compiled
# set stays byte-identical (pinned in tests/test_metrics.py).


def unshard_spatial_state(state: SwarmState, n: int) -> SwarmState:
    """A host-numpy tiled state (``spatial_shard_swarm`` slot layout)
    back in AGENT-ID order, trimmed to the first ``n`` ids — the lens
    a jumbo tenant's result is returned through, so its state compares
    field-for-field against the solo single-device rollout of the same
    materialized scenario (the r12 parity discipline).  Per-agent
    columns travel with their row; the ``alive_below`` ordinal cache
    is layout-local and is recounted for the restored order."""
    from ..state import AGENT_AXIS_FIELDS

    aid = np.asarray(state.agent_id)
    slot_of = np.empty(aid.shape[0], np.int64)
    slot_of[aid] = np.arange(aid.shape[0])
    take = slot_of[:n]
    updates = {
        f: np.asarray(getattr(state, f))[take]
        for f in AGENT_AXIS_FIELDS
    }
    aint = updates["alive"].astype(np.int32)
    # dtype pinned: numpy's cumsum silently widens sub-platform ints
    # to int64, and an i64 leaf in a returned SwarmState is a bespoke
    # retrace for any jitted consumer (the dtype contract is [N] i32).
    updates["alive_below"] = np.cumsum(aint, dtype=np.int32) - aint
    return state.replace(**updates)


@dataclass
class TenantResult:
    """One collected scenario.

    ``state`` is the final capacity-padded :class:`SwarmState` with
    HOST numpy leaves (the bitwise-parity surface — identical to the
    solo rollout of the same materialized scenario; one device->host
    transfer per dispatch, free views per tenant); ``summary`` the
    tenant's flight-recorder reduction (None with telemetry off);
    ``traj`` the ``[n_steps, n_agents, D]`` recorded trajectory
    trimmed to the REAL agent count (None with record off);
    ``ticks`` the rollout length this result covers — the full
    ``n_steps`` normally, or the elapsed prefix for a tenant evicted
    mid-stream (r16; None on the one-shot r13 path, whose length is
    always the service's)."""

    request_id: int
    n_agents: int
    capacity: int
    state: SwarmState
    summary: Optional[dict] = None
    traj: Optional[np.ndarray] = None
    ticks: Optional[int] = None


class _Dispatch:
    """One launched bucket batch: the async handles plus the rid ->
    batch-row map.  Buffers are dropped once every tenant is
    collected (result-store eviction)."""

    def __init__(self, rids, states, traj, telem):
        self.rids: List[int] = rids          # row i <-> rids[i]
        self.states = states                 # [S, ...] final states
        self.traj = traj                     # [T, S, C, D] or None
        self.telem = telem                   # [T, S]-leaved or None
        self._host = None

    def block(self):
        jax.block_until_ready(self.states.pos)

    def host_states(self) -> SwarmState:
        """The final states as host numpy — one device->host transfer
        per dispatch, then per-tenant extraction is a free view (a
        per-tenant device slice measured ~3 ms/tenant of dispatch
        overhead at collect time)."""
        if self._host is None:
            self.block()
            self._host = jax.tree_util.tree_map(
                np.asarray, self.states
            )
        return self._host

    def host_telem(self):
        """The stacked recorder ys as host numpy (same one-transfer
        discipline as :meth:`host_states`)."""
        if self.telem is not None and not isinstance(
            self.telem.tick, np.ndarray
        ):
            self.telem = jax.tree_util.tree_map(
                np.asarray, self.telem
            )
        return self.telem

    def host_traj(self):
        """The recorded trajectory as host numpy — the largest buffer
        in the dispatch, so per-tenant device slices would be the
        worst offenders of the one-transfer rule."""
        if self.traj is not None and not isinstance(
            self.traj, np.ndarray
        ):
            self.traj = np.asarray(self.traj)
        return self.traj


class RolloutService:
    """Scenario-batched swarm serving — thousands of concurrent small
    swarms per chip through a handful of compiled shapes.

    Static per-service: the shared :class:`SwarmConfig` (structure),
    the rollout length, and the telemetry/record composition — each
    is a jit-static of the batched entry, so keeping them per-service
    keeps the compile budget at ``spec.max_shapes``.  Per-REQUEST:
    agent count (alive-mask padding), arena, seed, faults, tasks, and
    every :class:`~.batched.ScenarioParams` scalar.
    """

    def __init__(
        self,
        cfg: Optional[SwarmConfig] = None,
        spec: Optional[BucketSpec] = None,
        n_steps: int = 50,
        telemetry: bool = True,
        record: bool = False,
        tracer: Optional[SpanTracer] = None,
    ):
        self.cfg = validate_serve_config(cfg or DEFAULT_CONFIG)
        self.spec = spec or BucketSpec()
        if self.spec.jumbo_capacities:
            # Without this, capacity_for would hand a jumbo rung to
            # the one-shot flush path, which co-batches by the
            # SCENARIO rungs and dispatches a mesh-scale tenant
            # through the single-device vmapped program — a bespoke
            # minutes-long compile (or OOM) where the r13 contract
            # promises a loud submit-time rejection.
            raise ValueError(
                "RolloutService has no tiles-axis dispatch plane; "
                f"jumbo rungs {self.spec.jumbo_capacities} need the "
                "StreamingService (mesh= + jumbo_cfg=)"
            )
        if n_steps <= 0:
            raise ValueError(f"n_steps must be >= 1, got {n_steps}")
        self.n_steps = int(n_steps)
        #: Span registry (r17): every dispatch phase emits into it
        #: when tracing is enabled; disabled, each emission site is
        #: one attribute check (the pinned no-op contract,
        #: utils/trace.py).  Injectable for tests and benches; the
        #: default is the process-global tracer DSA_TRACE enables.
        self.tracer = TRACER if tracer is None else tracer
        # The EFFECTIVE flag: the batched entry returns the telemetry
        # ys whenever the flag OR the config gate is on, so the
        # unpacking below must agree with that disjunction — a config
        # with telemetry pre-enabled plus telemetry=False would
        # otherwise make the service mistake (states, telem) for
        # states.
        self.telemetry = bool(telemetry) or self.cfg.telemetry.enabled
        self.record = bool(record)
        self._next_rid = 0
        #: (capacity, n_tasks) -> [(rid, request)] awaiting flush,
        #: FIFO.  The task count is part of the bucket key because it
        #: is a SHAPE (the task table rides the batch) — mixing task
        #: counts in one dispatch would be a retrace, not a batch.
        self._pending: Dict[tuple, List] = {}
        #: rid -> _Dispatch holding its row.
        self._dispatches: Dict[int, _Dispatch] = {}
        #: rid -> (request, capacity) for pending bookkeeping.
        self._requests: Dict[int, tuple] = {}
        #: distinct task counts seen — each one multiplies the
        #: compiled-shape lattice (shape axis #3).
        self._task_counts: set = set()
        self.stats = {
            "submitted": 0, "dispatches": 0, "padded_scenarios": 0,
            "collected": 0,
        }
        self._declare_budgets(n_task_families=1)

    def _declare_budgets(self, n_task_families: int) -> None:
        # Declare the compile budgets whether or not the observatory
        # is enabled — declaration is free and makes a later enable()
        # retroactively meaningful for new compiles.  The budget is
        # the bucket lattice times the observed task-count families;
        # the materializer adds the batch-of-1 scalar view.  The
        # registry (and the jit caches it mirrors) is PROCESS-GLOBAL:
        # with several services alive, the declared budget is the MAX
        # over services (a smaller second service must not turn the
        # first's legitimate compiles into overflow events), and
        # compile_entries() counts every service's compiles — the
        # per-service gate in bench_multitenant runs one service per
        # process, the honest granularity the jit cache offers.
        watch = compile_watch.WATCH
        budget = self.spec.max_shapes * max(n_task_families, 1)
        for entry, b in (
            (SERVE_ENTRY, budget), (MATERIALIZE_ENTRY, budget + 1)
        ):
            prev = watch.bucket_budget(entry)
            watch.declare_buckets(entry, max(b, prev or 0))

    # -- submit ------------------------------------------------------------
    def submit(self, req: ScenarioRequest) -> int:
        """Queue one scenario; returns its request id.  EVERY request
        invariant is checked here — oversized shapes (no capacity
        rung fits; the eviction half of the bucket contract) and the
        materializer's field contracts — so a bad request fails at
        its own submit instead of poisoning the co-batched requests'
        flush."""
        capacity = self.spec.capacity_for(req.n_agents)
        validate_request(req)
        rid = self._next_rid
        self._next_rid += 1
        n_tasks = len(req.task_pos)
        if n_tasks not in self._task_counts:
            self._task_counts.add(n_tasks)
            self._declare_budgets(len(self._task_counts))
        self._pending.setdefault((capacity, n_tasks), []).append(
            (rid, req)
        )
        self._requests[rid] = (req, capacity)
        self.stats["submitted"] += 1
        return rid

    # -- dispatch ----------------------------------------------------------
    def flush(self) -> int:
        """Dispatch every pending request as bucketed batches; returns
        the number of dispatches launched.  Non-blocking: the device
        works while the host materializes the next batch."""
        launched = 0
        with self.tracer.span(FLUSH_SPAN):
            for key in sorted(self._pending):
                capacity, _ = key
                group = self._pending[key]
                for size in self.spec.split_batch(len(group)):
                    entries = group[:size]
                    # Launch BEFORE dequeuing: a failed launch must
                    # not silently drop its co-batched requests.
                    self._launch(capacity, size, entries)
                    del group[:size]
                    launched += 1
        self._pending = {k: g for k, g in self._pending.items() if g}
        self.stats["dispatches"] += launched
        return launched

    def _launch(self, capacity: int, size: int, entries) -> None:
        rids = [rid for rid, _ in entries]
        reqs = [req for _, req in entries]
        n_pad = size - len(reqs)
        self.stats["padded_scenarios"] += n_pad
        # One jitted build for the whole dispatch (rows past the real
        # requests are dead filler scenarios), one compiled rollout;
        # neither call blocks, so the host is already materializing
        # the NEXT dispatch while this one executes (async dispatch =
        # the double buffer), and the donated state buffers go
        # straight back to XLA.
        with self.tracer.span(
            COALESCE_SPAN, rids=rids, capacity=capacity, size=size
        ):
            states, params = materialize_batch(
                reqs, capacity, self.cfg, pad_to=size
            )
        with self.tracer.span(
            LAUNCH_SPAN, rids=rids, capacity=capacity, size=size
        ):
            out = batched_rollout(
                states, params, self.cfg, self.n_steps,
                record=self.record, telemetry=self.telemetry,
            )
        traj = telem = None
        if self.record and self.telemetry:
            states, traj, telem = out
        elif self.record:
            states, traj = out
        elif self.telemetry:
            states, telem = out
        else:
            states = out
        d = _Dispatch(rids, states, traj, telem)
        for rid in rids:
            self._dispatches[rid] = d

    # -- collect -----------------------------------------------------------
    def collect(self, rid: int) -> TenantResult:
        """Block on (only) the dispatch holding ``rid`` and return its
        tenant's result, evicting it from the service.  Pending but
        unflushed requests are flushed first.  Raises ``KeyError``
        for unknown or already-collected ids."""
        if rid not in self._dispatches:
            if rid in self._requests and any(
                rid == r for g in self._pending.values() for r, _ in g
            ):
                self.flush()
        if rid not in self._dispatches:
            raise KeyError(
                f"request id {rid} is not in flight (never submitted, "
                "or already collected — results are evicted on "
                "collect)"
            )
        d = self._dispatches.pop(rid)
        i = d.rids.index(rid)
        req, capacity = self._requests.pop(rid)
        with self.tracer.span(COLLECT_SPAN, rid=rid):
            summary = None
            if d.telem is not None:
                summary = TelemetrySummary.from_ticks(
                    tenant_telemetry(d.host_telem(), i)
                ).to_dict()
            traj = None
            if d.traj is not None:
                traj = d.host_traj()[:, i, : req.n_agents]
            result = TenantResult(
                request_id=rid,
                n_agents=req.n_agents,
                capacity=capacity,
                state=tenant_state(d.host_states(), i),
                summary=summary,
                traj=traj,
            )
        self.stats["collected"] += 1
        return result

    def collect_all(self) -> Dict[int, TenantResult]:
        """Flush, then collect every outstanding request (in-flight
        and pending), keyed by request id."""
        self.flush()
        rids = sorted(self._dispatches)
        return {rid: self.collect(rid) for rid in rids}

    # -- introspection -----------------------------------------------------
    @property
    def n_pending(self) -> int:
        return sum(len(g) for g in self._pending.values())

    @property
    def n_in_flight(self) -> int:
        return len(self._dispatches)

    def compile_entries(self) -> int:
        """Distinct compiled signatures the observatory has seen for
        the batched entry (0 unless the observatory is enabled) —
        the number bench_multitenant gates against
        ``spec.max_shapes``."""
        return compile_watch.WATCH.compile_count(SERVE_ENTRY)


# ---------------------------------------------------------------------------
# Streaming service (r16): continuous batching + the SLO observatory.


def _swarm_rollout_spatial(tiled, cfg, n_steps, mesh, spatial,
                           telemetry, carry):
    """One jumbo segment: the r12 spatial rollout with the
    :class:`~..parallel.spatial.SpatialCarry` threaded through
    (``carry=None`` seeds segment 1 exactly like the one-shot rollout;
    ``return_plan=True`` hands the advanced carry back) — so k
    segments are the identical tick sequence as one k*seg scan."""
    from ..models.swarm import swarm_rollout

    return swarm_rollout(
        tiled, None, cfg, n_steps, telemetry=telemetry,
        return_plan=True, mesh=mesh, spatial=spatial, carry=carry,
    )


class _Stream:
    """One in-flight streaming dispatch: the donated rollout carry
    advanced segment by segment, plus everything harvested from it.

    The carry rotation IS the double buffer: each segment's output
    becomes the next segment's DONATED input, so XLA reuses the state
    buffers across the whole rollout; anything the host needs later
    (eviction views, the first-result probe, telemetry/trajectory ys)
    is materialized as an independent buffer BEFORE the donating
    launch, and read only after a successor launch is enqueued — the
    device pipeline never waits on the host."""

    def __init__(self, rids, reqs, capacity, size, params, states,
                 seg_plan, sharded=False, jumbo=False, spatial=None,
                 sp_carry=None):
        self.rids: List[int] = rids              # row i <-> rids[i]
        self.reqs = reqs                         # aligned with rids
        self.capacity = capacity
        self.size = size
        self.params = params
        self.carry = states                      # device; donated next
        self.seg_plan: Tuple[int, ...] = seg_plan
        self.seg_done = 0
        #: r18 (the 2D-mesh serve plane): ``sharded`` marks a
        #: scenario-axis dispatch (carry committed P('scenarios'),
        #: advanced by the sharded entry); ``jumbo`` marks a
        #: tiles-axis dispatch — ONE tenant in the r12 slot layout,
        #: ``spatial`` its SpatialSpec and ``sp_carry`` the
        #: SpatialCarry threaded segment to segment (what makes the
        #: segmented rollout bitwise-equal to the one-shot).
        self.sharded = sharded
        self.jumbo = jumbo
        self.spatial = spatial
        self.sp_carry = sp_carry
        self.telem_segs: List = []               # [seg_len, S] leaves
        self.traj_segs: List = []                # [seg_len, S, C, D]
        self.probe = None                        # independent tick copy
        self.probe_token: Optional[int] = None   # swarmpulse token
        #: True iff this stream ever opened a pulse token — keeps it
        #: off the host-poll path even after the token closes
        #: (abandon), so callbacks-on never mixes observation modes.
        self.pulsed = False
        self.first_stamped = False
        #: Clock time of this stream's latest segment launch — the
        #: rotation-interval histogram's left edge (r19).
        self.last_launch_t: Optional[float] = None
        # -- swarmpulse (r24): what the pulse drain writes ----------
        #: Segments fully device-stamped, consecutive from 0 — the
        #: callback-harvest cursor (``segs_landed == len(seg_plan)``
        #: means the result buffers are observable without a poll).
        self.segs_landed = 0
        #: Latest device stamp (monotone; partial shard stamps count
        #: — a straggler's peers still prove progress).  None until
        #: the first stamp; the watchdog falls back to
        #: ``last_launch_t`` as the heartbeat base.
        self.last_progress_t: Optional[float] = None
        #: Final segment's device completion stamp (harvest-lag's
        #: left edge); None until it lands.
        self.result_t: Optional[float] = None
        #: The final segment's stamped output leaf.  Collect blocks
        #: on it before the terminal pulse drain: the stamp program
        #: is enqueued with the launch, but its host callback runs
        #: asynchronously — without the barrier a fast collect could
        #: close the token before the last heartbeat lands.
        self.final_stamp = None
        #: The watchdog's current classification (serve/health.py
        #: owns transitions; the stream just stores the label).
        self.health_state = "healthy"
        self.evict_flags: Set[int] = set()
        #: rid -> (ticks_elapsed, device state view, n_telem_segs)
        self.evicted: Dict[int, tuple] = {}
        self.collected: Set[int] = set()
        self._host = None
        #: True once EVERY tenant of this stream has been evicted —
        #: the remaining segments would compute results no one can
        #: observe, so the rotation stops (load-bearing for the jumbo
        #: rung, where "every tenant" is one tenant and the dead work
        #: would be mesh-wide spatial segments).
        self.abandoned = False

    @property
    def done(self) -> bool:
        return self.abandoned or self.seg_done >= len(self.seg_plan)

    def ticks_elapsed(self) -> int:
        return sum(self.seg_plan[: self.seg_done])

    def host_states(self) -> SwarmState:
        """Final states as host numpy — the one-transfer-per-dispatch
        discipline of the r13 `_Dispatch`; only legal once the stream
        is done (the carry is never donated again)."""
        if self._host is None:
            jax.block_until_ready(self.carry.pos)
            self._host = jax.tree_util.tree_map(np.asarray, self.carry)
        return self._host

    def _host_telem_seg(self, k: int):
        """Segment ``k``'s recorder ys as host numpy, converted ONCE
        per dispatch and cached in place — per-tenant slices are
        then free views (the r13 ``_Dispatch.host_telem``
        one-transfer-per-dispatch discipline; re-transferring the
        full [T, S] batch per tenant multiplies collect-path
        transfer time by the batch size)."""
        t = self.telem_segs[k]
        if not isinstance(t.tick, np.ndarray):
            t = jax.tree_util.tree_map(np.asarray, t)
            self.telem_segs[k] = t
        return t

    def _host_traj_seg(self, k: int):
        """Segment ``k``'s trajectory as host numpy (same caching —
        the trajectory is the largest buffer in the dispatch, the
        worst offender of the one-transfer rule)."""
        t = self.traj_segs[k]
        if not isinstance(t, np.ndarray):
            t = np.asarray(t)
            self.traj_segs[k] = t
        return t

    def tenant_telem(self, i: int, n_segs=None):
        """Tenant ``i``'s [T]-leaved recorder slice across the
        harvested segments (``n_segs`` bounds the prefix for evicted
        tenants)."""
        n = len(self.telem_segs) if n_segs is None else n_segs
        parts = [
            jax.tree_util.tree_map(
                lambda x, i=i: x[:, i], self._host_telem_seg(k)
            )
            for k in range(n)
        ]
        return concat_telemetry(parts) if parts else None

    def jumbo_telem(self, n_segs=None):
        """The jumbo stream's [T]-leaved recorder record across the
        harvested segments — no tenant axis to slice (the spatial
        rollout records one mesh-wide stream per tick)."""
        n = len(self.telem_segs) if n_segs is None else n_segs
        parts = [self._host_telem_seg(k) for k in range(n)]
        return concat_telemetry(parts) if parts else None

    def tenant_traj(self, i: int, n_agents: int, n_segs=None):
        n = len(self.traj_segs) if n_segs is None else n_segs
        if not n:
            return None
        return np.concatenate(
            [self._host_traj_seg(k)[:, i, :n_agents] for k in range(n)],
            axis=0,
        )


class StreamingService:
    """Continuous-batching streaming rollout service with a
    first-class SLO observatory (r16) — the serve loop as an actual
    service instead of a submit/flush/collect API.

    Three mechanisms on top of :class:`RolloutService`'s bucket
    lattice (shapes, params, parity semantics all unchanged):

    - **Admission queue + deadline coalescing** (serve/queue.py):
      ``submit`` enqueues; ``pump`` dispatches a shape group when it
      fills the largest batch rung or when its oldest request's
      ``deadline_s`` expires (padded via the bounded-pad tail).  An
      optional ``max_queue`` bound makes backpressure loud
      (:class:`~.queue.QueueOverflowError` + a queue-overflow event)
      instead of a silent latency cliff.
    - **Segmented rollouts + donated double-buffer rotation**: the
      rollout runs as ``segment_steps``-tick segments; each segment's
      output carry is DONATED into the next launch, and everything
      the host reads (eviction views, the first-result probe) is
      sliced into independent buffers before the donating call — so
      collection never forces a ``block_until_ready`` on the next
      dispatch's critical path (the ``serve-host-sync`` lint
      contract).  Segment composition is bitwise: k segments of the
      vmapped tick are the same arithmetic as one k·seg-tick scan, so
      the r13 solo-parity contract survives the rewrite (pinned in
      tests/test_serve_stream.py).
    - **Mid-stream eviction/join**: ``evict(rid)`` returns a tenant's
      PARTIAL results at the next segment boundary (bitwise-prefix-
      equal to its solo rollout) via the existing batch-of-1
      materializer views; a tenant submitted mid-stream joins the
      next coalesced dispatch of its shape — no retrace, the shape is
      already in the lattice.

    Every request is stamped into the :class:`~.slo.SloTracker`
    (``svc.slo``): time-in-queue and time-to-first-result
    percentiles, queue-depth/in-flight gauges, per-dispatch
    occupancy, and the deadline-miss / queue-overflow / eviction
    alert events — the surface ``benchmarks/bench_soak.py`` gates and
    ``swarmscope slo`` renders.

    **2D-mesh serving (r18, ROADMAP item 1).**  With ``mesh=`` (a
    ``(scenarios, tiles)`` mesh from ``parallel.mesh.make_serve_mesh``)
    the one service runs both workload shapes on the whole slice:

    - scenario rungs whose batch size divides the scenario axis
      dispatch through ``serve-batched-rollout-sharded`` — the same
      vmapped scan shard_map-committed ``P('scenarios')``, donated
      sharded carries, ZERO per-tick collectives (jaxlint-budgeted);
      per-tenant results stay BITWISE equal to the single-device
      batched path (tests/test_serve_2d.py);
    - ``spec.jumbo_capacities`` rungs (with ``jumbo_cfg=``, a
      hashgrid config) route one large tenant per dispatch through
      the r12 spatial tick on the tiles axis — segmented via a
      threaded ``SpatialCarry`` so streaming composes bitwise with
      the one-shot spatial rollout, collective-permute-only contract
      unchanged.

    Both rung kinds ride the same admission queue (keyed per
    capacity, so a jumbo tenant never head-of-line-blocks a scenario
    rung), the same segment rotation, eviction, SLO stamps, and
    collect surface.

    The compile budget grows only by the distinct segment lengths
    (``n_steps = k·seg + rem`` → at most 2 scan lengths per bucket
    shape), declared to the observatory like every serve budget.
    """

    def __init__(
        self,
        cfg: Optional[SwarmConfig] = None,
        spec: Optional[BucketSpec] = None,
        n_steps: int = 50,
        segment_steps: Optional[int] = None,
        deadline_s: float = DEFAULT_DEADLINE_S,
        max_queue: Optional[int] = None,
        telemetry: bool = True,
        record: bool = False,
        slo: Optional[SloTracker] = None,
        tracer: Optional[SpanTracer] = None,
        mesh=None,
        jumbo_cfg: Optional[SwarmConfig] = None,
        metrics: Optional[metricslib.MetricsRegistry] = None,
        first_result_callback: bool = True,
        health: Optional[HealthMonitor] = None,
        launch_hook: Optional[Callable[[List[int], int], bool]] = None,
    ):
        self.cfg = validate_serve_config(cfg or DEFAULT_CONFIG)
        self.spec = spec or BucketSpec()
        # --- the 2D-mesh serve plane (r18, ROADMAP item 1) ----------
        # ``mesh``: a (scenarios, tiles) Mesh (parallel/mesh.
        # make_serve_mesh).  Scenario rungs whose batch size divides
        # the scenario axis dispatch through the shard_map'd sharded
        # entry (donated sharded carries); smaller rungs stay
        # single-device (sharding a sub-axis batch wastes devices and
        # loses to the vmapped program — measured, bench_mesh2d.py).
        # Jumbo rungs (spec.jumbo_capacities) route ONE tenant per
        # dispatch through the r12 spatial tick on the tiles axis and
        # need ``jumbo_cfg`` (a hashgrid config — the spatial tick's
        # envelope; per-request ScenarioParams cannot ride it, so
        # jumbo requests carry no param overrides).
        self.mesh = mesh
        self.jumbo_cfg = jumbo_cfg
        self.n_scenario_shards = 1
        self.n_tiles = 1
        if mesh is not None:
            from ..parallel.mesh import SCENARIO_AXIS, TILE_AXIS

            shape = dict(mesh.shape)
            if SCENARIO_AXIS not in shape:
                raise ValueError(
                    f"serve mesh must carry a {SCENARIO_AXIS!r} axis "
                    "(parallel.mesh.make_serve_mesh); got axes "
                    f"{tuple(shape)}"
                )
            self.n_scenario_shards = int(shape[SCENARIO_AXIS])
            self.n_tiles = int(shape.get(TILE_AXIS, 1))
        if self.spec.jumbo_capacities:
            if mesh is None or jumbo_cfg is None:
                raise ValueError(
                    "BucketSpec declares jumbo rungs "
                    f"{self.spec.jumbo_capacities} — the tiles-axis "
                    "path needs mesh= (make_serve_mesh with tiles >= "
                    "1) and jumbo_cfg= (the spatial tick's hashgrid "
                    "config)"
                )
            if record:
                raise ValueError(
                    "record=True is not supported with jumbo rungs — "
                    "the spatial rollout's frames are slot-ordered "
                    "mesh-wide buffers, not per-tenant trajectories"
                )
            # Fail at the API boundary, not mid-trace: the spatial
            # tick's envelope (hashgrid mode, no moments field) and
            # geometry guards all live here.
            from ..parallel.spatial import spatial_plan_geometry

            spatial_plan_geometry(jumbo_cfg)
        if n_steps <= 0:
            raise ValueError(f"n_steps must be >= 1, got {n_steps}")
        seg = n_steps if segment_steps is None else int(segment_steps)
        if not 0 < seg <= n_steps:
            raise ValueError(
                f"segment_steps must be in [1, n_steps={n_steps}], "
                f"got {seg}"
            )
        full, rem = divmod(n_steps, seg)
        self.n_steps = int(n_steps)
        self.segment_steps = seg
        #: The segment schedule, e.g. n_steps=25, seg=10 -> (10, 10,
        #: 5).  At most TWO distinct scan lengths — the compile-budget
        #: multiplier.
        self._seg_plan: Tuple[int, ...] = (seg,) * full + (
            (rem,) if rem else ()
        )
        # Same effective-flag disjunction as RolloutService.
        self.telemetry = bool(telemetry) or self.cfg.telemetry.enabled
        self.record = bool(record)
        self.max_queue = max_queue
        # Live metrics plane (r19): ONE registry feeds the tracker's
        # latency histograms / alert counters, the queue's admission
        # counters, and the service's own rotation instruments below
        # — split registries would scrape as traffic with no latency
        # and no alerts, so a conflicting injection fails loudly.
        if (
            metrics is not None and slo is not None
            and slo.metrics is not metrics
        ):
            raise ValueError(
                "StreamingService(slo=, metrics=) received a tracker "
                "bound to a DIFFERENT registry — the alert-parity "
                "contract needs one instrument plane; construct the "
                "tracker with SloTracker(metrics=...) or drop the "
                "metrics= argument"
            )
        if metrics is not None:
            self.metrics = metrics
        elif slo is not None:
            self.metrics = slo.metrics
        else:
            self.metrics = metricslib.METRICS
        self.slo = slo or SloTracker(
            deadline_s=deadline_s, metrics=self.metrics
        )
        self._m_rotations = self.metrics.counter(
            "serve_segment_rotations_total",
            "Segment launches past each stream's first",
        )
        self._m_segment_wall = self.metrics.histogram(
            "serve_segment_wall_ms",
            "Wall-clock between successive segment launches of one "
            "stream (the pipelined segment's wall time under a busy "
            "pump; pump cadence bounds it from below on an idle one)",
        )
        #: swarmpulse master switch (r24; name kept from the r19
        #: first-result callback it grew out of).  ON: every segment
        #: of every stream class — single-device, scenario-sharded,
        #: jumbo — routes a tick leaf through a device heartbeat
        #: stamp (serve/pulse.py), TTFR and harvest are
        #: callback-driven, and the watchdog ages real device
        #: progress.  OFF: the literal pre-r19 probe expression and
        #: `is_ready` host polling — the compiled set is pinned
        #: byte-identical to the r16 service.
        self.first_result_callback = bool(first_result_callback)
        #: Fault-injection hook (the wedge drill's injection point):
        #: called as ``launch_hook(rids, seg_index)`` before each
        #: segment launch; returning False skips THIS stream's launch
        #: this pump — the stream stays in-flight with an aging
        #: heartbeat, which is exactly what a wedged device looks
        #: like from the host.  None (the default) costs nothing.
        self.launch_hook = launch_hook
        #: Observation-lag samples (ms), one per request whose first
        #: result carried BOTH stamps: host-poll observation minus
        #: device-callback stamp — what the poll-only design was
        #: adding to observed TTFR (the bench_metrics_overhead row).
        #: Bounded like the SLO gauge trajectory: past the bound the
        #: stored samples decimate 2x and the keep-stride doubles, so
        #: a weeks-long service holds a full-span (coarser) sample in
        #: O(1) memory instead of one float per request ever served.
        self.ttfr_lag_ms: List[float] = []
        self._lag_stride = 1
        self._lag_skip = 0
        self._max_lag_samples = 4096
        #: harvest-lag twin (r24): host observation of a stream's
        #: FINAL segment minus its device completion stamp — what
        #: `is_ready` polling was adding to result latency; one
        #: sample per tenant, same decimation bound.
        self.harvest_lag_ms: List[float] = []
        self._hlag_stride = 1
        self._hlag_skip = 0
        #: Same injectable registry as RolloutService; the admission
        #: queue shares it (and the SLO clock), so its retrospective
        #: queue-wait spans land on the same timeline as the dispatch
        #: spans below.
        self.tracer = TRACER if tracer is None else tracer
        # The runtime half of the memory observatory (r17): the SLO
        # summary samples the device allocator's peak-bytes watermark
        # where the backend keeps one (structured skip on CPU).  The
        # tracker itself stays jax-free, so the probe is injected.
        if self.slo.memory_probe is None:
            self.slo.memory_probe = device_memory_watermark
        # The stall watchdog (r24, swarmpulse layer 3): runs INSIDE
        # the pump, cadence-gated — no new thread on the hot path.
        # An injected monitor keeps its thresholds; the service only
        # fills the wiring it left open (clock, the live segment-wall
        # histogram, the tracker the events ride).
        self.health = health or HealthMonitor()
        if self.health.clock is None:
            self.health.clock = self.slo.clock
        if self.health.wall_hist is None:
            self.health.wall_hist = self._m_segment_wall
        if self.health.slo is None:
            self.health.slo = self.slo
        self.queue = AdmissionQueue(
            self.spec, deadline_s, clock=self.slo.clock,
            tracer=self.tracer, metrics=self.metrics,
        )
        self._next_rid = 0
        self._streams: Dict[int, _Stream] = {}   # uncollected rids
        self._live: List[_Stream] = []
        self._requests: Dict[int, tuple] = {}
        self._task_counts: set = set()
        self.stats = {
            "submitted": 0, "dispatches": 0, "padded_scenarios": 0,
            "collected": 0, "evicted": 0,
        }
        self._declare_budgets(n_task_families=1)

    def _declare_budgets(self, n_task_families: int) -> None:
        # The r13 declaration times the distinct segment lengths:
        # each (bucket shape, scan length) pair is one legitimate
        # compile.  The materializer sees only the bucket shapes.
        # r18: scenario shapes are declared under BOTH batched entries
        # (a rung dispatches sharded when its size divides the
        # scenario axis, single-device otherwise — the max over both
        # is the honest ceiling); jumbo rungs land under the spatial
        # entry, times 2 for the seed-vs-resume carry structures of
        # the segment rotation.
        watch = compile_watch.WATCH
        fams = max(n_task_families, 1)
        seg_lens = len(set(self._seg_plan))
        scen_shapes = (
            len(self.spec.capacities) * len(self.spec.batches) * fams
        )
        budget = scen_shapes * seg_lens
        declarations = [
            (SERVE_ENTRY, budget),
            (MATERIALIZE_ENTRY, self.spec.max_shapes * fams + 1),
        ]
        if self.mesh is not None:
            declarations.append((SERVE_SHARDED_ENTRY, budget))
        if self.spec.jumbo_capacities:
            declarations.append((
                JUMBO_ENTRY,
                len(self.spec.jumbo_capacities) * fams * seg_lens * 2,
            ))
        for entry, b in declarations:
            prev = watch.bucket_budget(entry)
            watch.declare_buckets(entry, max(b, prev or 0))

    # -- submit ------------------------------------------------------------
    def submit(self, req: ScenarioRequest) -> int:
        """Enqueue one scenario; returns its request id.  Validation
        is the r13 contract (fail at YOUR OWN submit); additionally
        the declared queue bound rejects loudly — a queue-overflow
        event plus :class:`~.queue.QueueOverflowError` — instead of
        buffering unbounded latency."""
        capacity = self.spec.capacity_for(req.n_agents)
        validate_request(req)
        if (
            self.max_queue is not None
            and self.queue.depth >= self.max_queue
        ):
            self.slo.on_queue_overflow(self.queue.depth, self.max_queue)
            self.tracer.instant(
                OVERFLOW_EVENT, depth=self.queue.depth,
                bound=self.max_queue,
            )
            raise QueueOverflowError(
                f"admission queue at its declared bound "
                f"({self.queue.depth}/{self.max_queue}); pump() or "
                "widen max_queue"
            )
        if self.spec.is_jumbo(capacity):
            # Jumbo invariants fail at THEIR OWN submit (the r13
            # discipline): the spatial tick bakes its gains static,
            # and the tiled layout lives on the jumbo config's torus.
            if req.params:
                raise ValueError(
                    f"jumbo request (capacity {capacity}, tiles "
                    "axis) cannot carry per-request params "
                    f"{sorted(req.params)} — the r12 spatial tick "
                    "compiles its gains from the static jumbo_cfg; "
                    "bake them there (one config per jumbo service)"
                )
            if req.arena_hw > float(self.jumbo_cfg.world_hw):
                raise ValueError(
                    f"jumbo arena_hw {req.arena_hw} exceeds the "
                    f"jumbo_cfg torus world_hw "
                    f"{self.jumbo_cfg.world_hw} — spawns must land "
                    "inside the tiled domain"
                )
        rid = self._next_rid
        self._next_rid += 1
        n_tasks = len(req.task_pos)
        if n_tasks not in self._task_counts:
            self._task_counts.add(n_tasks)
            self._declare_budgets(len(self._task_counts))
        self.slo.on_submit(rid)
        self.queue.push(rid, req, capacity, n_tasks)
        self._requests[rid] = (req, capacity)
        self.stats["submitted"] += 1
        return rid

    # -- the host loop -----------------------------------------------------
    def pump(self, force: bool = False) -> dict:
        """One step of the serving loop: admit due rungs (rung-full
        or deadline-expired; ``force`` admits everything — the drain
        path), rotate every in-flight dispatch one segment, harvest
        ready first-result probes, and sample the gauges.  Returns
        ``{"launched": ..., "advanced": ...}``.  Never blocks on
        device work except the probe stamp, which only reads a
        segment whose successor is already enqueued."""
        launched = self._admit(force=force)
        advanced = self._advance()
        self._harvest()
        # The stall watchdog (r24): ages each in-flight stream's
        # heartbeat against the learned segment wall — cadence-gated
        # host floats only, no device work, no thread.
        self.health.check(self._live)
        self.slo.sample(self.queue.depth, self.n_in_flight)
        # The live surface: one snapshot line per deposit interval
        # when a run dir is configured (swarmscope live follows it);
        # a clock read + compare otherwise.
        self.metrics.maybe_deposit()
        return {"launched": launched, "advanced": advanced}

    def _admit(self, force: bool = False) -> int:
        n = 0
        for (capacity, _), entries, size in self.queue.pop_ready(
            force=force
        ):
            self._launch_group(capacity, size, entries)
            n += 1
        return n

    def _launch_group(self, capacity, size, entries) -> None:
        rids = [e.rid for e in entries]
        reqs = [e.req for e in entries]
        for rid in rids:
            self.slo.on_admit(rid)
        self.stats["padded_scenarios"] += size - len(reqs)
        if self.spec.is_jumbo(capacity):
            s = self._coalesce_jumbo(capacity, rids, reqs)
            mesh_label = f"tiles x{self.n_tiles}"
        else:
            sharded = (
                self.mesh is not None
                and size % self.n_scenario_shards == 0
            )
            with self.tracer.span(
                COALESCE_SPAN, rids=rids, capacity=capacity, size=size
            ):
                states, params = materialize_batch(
                    reqs, capacity, self.cfg, pad_to=size
                )
                if sharded:
                    # Committed BEFORE the first launch: donation
                    # preserves placement, so every later segment's
                    # carry stays P('scenarios') for free.
                    states = shard_scenarios(states, self.mesh)
                    params = shard_scenarios(params, self.mesh)
            s = _Stream(rids, reqs, capacity, size, params, states,
                        self._seg_plan, sharded=sharded)
            mesh_label = (
                f"scenarios x{self.n_scenario_shards}" if sharded
                else "device"
            )
        for rid in rids:
            self._streams[rid] = s
        self._live.append(s)
        self.slo.on_dispatch(
            size, len(reqs),
            rung=f"cap={capacity} b={size}", mesh=mesh_label,
        )
        self.stats["dispatches"] += 1

    def _coalesce_jumbo(self, capacity, rids, reqs) -> _Stream:
        """One jumbo tenant -> the r12 tiled layout: the IDENTICAL
        batch-of-1 materializer every parity reference runs (r13
        discipline), laid out by home strip over the tiles axis.  The
        host-side layout permutation runs once per dispatch — the
        deployment boundary ``spatial_shard_swarm`` documents."""
        from ..parallel.mesh import TILE_AXIS
        from ..parallel.spatial import spatial_shard_swarm

        assert len(reqs) == 1, "jumbo rungs are batch-of-1"
        with self.tracer.span(
            COALESCE_SPAN, rids=rids, capacity=capacity, size=1
        ):
            state, _ = materialize_scenario(
                reqs[0], capacity, self.jumbo_cfg
            )
            tiled, spec = spatial_shard_swarm(
                state, self.mesh, self.jumbo_cfg, axis=TILE_AXIS
            )
        return _Stream(
            rids, reqs, capacity, 1, None, tiled, self._seg_plan,
            jumbo=True, spatial=spec,
        )

    def _advance(self) -> int:
        """Rotate: one segment launch per in-flight dispatch.  At
        each boundary, flagged evictions are sliced out of the carry
        as independent batch-of-1 views BEFORE the donating launch
        (async device slices — no host sync on this path)."""
        n = 0
        for s in self._live:
            if s.done:
                continue
            for rid in sorted(s.evict_flags):
                if rid in s.evicted:
                    continue
                with self.tracer.span(
                    EVICT_SPAN, rid=rid, ticks=s.ticks_elapsed()
                ):
                    if s.jumbo:
                        # The whole tiled state IS the tenant; the
                        # spatial rollout never donates its input, so
                        # the reference stays valid across later
                        # segment launches.
                        view = s.carry
                    else:
                        i = s.rids.index(rid)
                        view = jax.tree_util.tree_map(
                            lambda x, i=i: x[i], s.carry
                        )
                    s.evicted[rid] = (
                        s.ticks_elapsed(), view, s.seg_done
                    )
                self.slo.on_eviction(rid, s.ticks_elapsed())
                self.stats["evicted"] += 1
            s.evict_flags.clear()
            if all(
                rid in s.evicted or rid in s.collected
                for rid in s.rids
            ):
                # Every tenant left: the remaining segments would
                # compute a result no one can observe.  Stop the
                # rotation (a jumbo stream would otherwise keep
                # burning the whole tiles axis on discarded work).
                s.abandoned = True
                # One last drain (stamps that already landed still
                # advance the cursor eviction cuts read), then the
                # registry entry goes — it must not outlive its
                # stream.
                self._drain_pulse(s)
                pulse_close(s.probe_token)
                s.probe_token = None
                continue
            if (
                self.launch_hook is not None
                and not self.launch_hook(list(s.rids), s.seg_done)
            ):
                # Fault injection (the wedge drill): the hook vetoed
                # this stream's launch this pump.  The stream stays
                # in-flight, its heartbeat ages, the watchdog sees a
                # wedge — without any device actually wedging.
                continue
            first = s.seg_done == 0
            if first:
                # Launch stamps BEFORE the jit dispatch: time-in-queue
                # measures the admission policy; a cold shape's
                # trace+compile belongs to TTFR (the tenant pays it),
                # not to the queue.
                self.slo.on_launch(s.rids)
            else:
                self._m_rotations.inc()
            now = self.slo.clock()
            if s.last_launch_t is not None:
                self._m_segment_wall.observe(
                    1e3 * (now - s.last_launch_t)
                )
            s.last_launch_t = now
            seg_len = s.seg_plan[s.seg_done]
            # Segment 1's dispatch is the LAUNCH span (TTFR's compute
            # edge); later rotations are SEGMENT spans — together the
            # critical-path table's compute proxy (the host-side
            # launches bracket the async device work they enqueue).
            with self.tracer.span(
                LAUNCH_SPAN if first else SEGMENT_SPAN,
                rids=s.rids, seg=s.seg_done, seg_len=seg_len,
            ):
                if s.jumbo:
                    out = _swarm_rollout_spatial(
                        s.carry, self.jumbo_cfg, seg_len, self.mesh,
                        s.spatial, self.telemetry, s.sp_carry,
                    )
                elif s.sharded:
                    out = batched_rollout_sharded(
                        s.carry, s.params, self.cfg, seg_len,
                        self.mesh, record=self.record,
                        telemetry=self.telemetry,
                    )
                else:
                    out = batched_rollout(
                        s.carry, s.params, self.cfg, seg_len,
                        record=self.record, telemetry=self.telemetry,
                    )
            traj = telem = None
            if s.jumbo:
                out, s.sp_carry = out
                if self.telemetry:
                    states, telem = out
                else:
                    states = out
            elif self.record and self.telemetry:
                states, traj, telem = out
            elif self.record:
                states, traj = out
            elif self.telemetry:
                states, telem = out
            else:
                states = out
            s.carry = states
            if traj is not None:
                s.traj_segs.append(traj)
            if telem is not None:
                s.telem_segs.append(telem)
            seg_idx = s.seg_done
            s.seg_done += 1
            if self.first_result_callback:
                # swarmpulse (r24): EVERY launched segment of EVERY
                # stream class routes its tick leaf through a device
                # heartbeat stamp — an INDEPENDENT copy outside the
                # donated rotation whose callback fires on segment
                # completion (the leaf operand is the data
                # dependency).  Segment 0's stamped copy doubles as
                # the first-result probe; later stamps are observe-
                # only (the enqueued effect outlives the dropped
                # reference).
                if first:
                    s.probe_token = pulse_open(
                        self.slo.clock,
                        n_shards=(
                            self.mesh.size
                            if (s.sharded or s.jumbo) else 1
                        ),
                    )
                    s.pulsed = True
                stamped = self._pulse_stamp_launch(s, states, seg_idx)
                if first:
                    s.probe = stamped
                if seg_idx == len(s.seg_plan) - 1:
                    # Collect blocks on the final stamp before the
                    # terminal drain — the heartbeat must land
                    # before the token closes.
                    s.final_stamp = stamped
                # Drain EVERY live pulse at the launch boundary, not
                # just at pass end: a heartbeat that lands while the
                # pump is busy launching some other stream's segment
                # is observed at the next boundary, so harvest lag is
                # bounded by one launch — not by the whole pass over
                # ``_live``.
                for t in self._live:
                    if t.probe_token is not None:
                        self._drain_pulse(t)
            elif first:
                # Callbacks off: the LITERAL pre-r19 probe — an
                # independent copy of one tiny leaf of segment 1's
                # output, host-polled at harvest.  Byte-identical
                # lowering to the r16 service (pinned).
                s.probe = jnp.copy(states.tick)
            n += 1
        return n

    def _pulse_stamp_launch(self, s: _Stream, states, seg: int):
        """Enqueue the heartbeat stamp for the segment just launched:
        the single-device jitted stamp, or the shard_map'd per-device
        stamp for mesh-committed carries (``P(SCENARIO_AXIS)`` for a
        sharded stream's [S] tick, replicated ``P()`` for the jumbo
        tiled scalar — ``spatial_shard_swarm`` replicates non-slot
        leaves, so the designated leaf is fully addressable)."""
        tok = jnp.asarray(s.probe_token, jnp.int32)
        sg = jnp.asarray(seg, jnp.int32)
        if s.sharded or s.jumbo:
            from jax.sharding import PartitionSpec as P

            from ..parallel.mesh import SCENARIO_AXIS

            spec = P(SCENARIO_AXIS) if s.sharded else P()
            return pulse_stamp_sharded(self.mesh, spec)(
                states.tick, tok, sg
            )
        return pulse_stamp(states.tick, tok, sg)

    def _drain_pulse(self, s: _Stream) -> None:
        """Consume the stream's landed heartbeats: advance the
        progress timestamp (partial shard stamps count), stamp TTFR
        from segment 0's device completion, and mark the final
        segment's landing (the callback-driven harvest — no
        ``is_ready`` poll anywhere on this path)."""
        if s.probe_token is None:
            return
        latest, completed = pulse_drain(s.probe_token, s.segs_landed)
        if latest is not None and (
            s.last_progress_t is None or latest > s.last_progress_t
        ):
            s.last_progress_t = latest
        if not completed:
            return
        now = self.slo.clock()
        for seg, t in completed:
            s.segs_landed = seg + 1
            if seg == 0 and not s.first_stamped:
                # The device stamped segment-1 completion: TTFR
                # measures the device, and poll-vs-callback lag is
                # what the host-poll design was charging (r19).
                self.slo.on_first_result(s.rids, t=t)
                self._record_lag(
                    max(0.0, 1e3 * (now - t)), len(s.rids)
                )
                self.tracer.instant(HARVEST_EVENT, rids=s.rids)
                s.first_stamped = True
            if seg == len(s.seg_plan) - 1 and s.result_t is None:
                s.result_t = t
                self._record_harvest_lag(
                    max(0.0, 1e3 * (now - t)), len(s.rids)
                )

    def _harvest(self) -> None:
        """Drain completed segments.  With callbacks on (swarmpulse,
        r24) the registry IS the harvest: the device already stamped
        every landed segment, so the pump reads host floats — no
        ``is_ready`` poll on the hot path.  With callbacks off the
        r16 poll survives verbatim: the segment-1 probe is polled via
        ``is_ready`` and only read once finished (a tenant collected
        before any poll observed it is backfilled by
        ``SloTracker.on_collect``).  Probe leaves without
        ``is_ready`` (host arrays) are observable as soon as every
        segment is launched."""
        for s in self._live:
            if s.probe_token is not None:
                self._drain_pulse(s)
            if s.pulsed:
                continue
            if s.probe is None or s.first_stamped:
                continue
            is_ready = getattr(s.probe, "is_ready", None)
            observable = s.done if is_ready is None else is_ready()
            if observable:
                # swarmlint: disable=serve-host-sync -- the probe is already finished (is_ready above) or a host array; the read cannot stall the pump
                np.asarray(s.probe)
                self.slo.on_first_result(s.rids, t=self.slo.clock())
                self.tracer.instant(HARVEST_EVENT, rids=s.rids)
                s.first_stamped = True

    def _record_lag(self, lag_ms: float, n: int) -> None:
        """Keep the observation-lag sample list bounded (the
        SloTracker gauge-decimation discipline): drop samples by the
        current stride, halve the store and double the stride at the
        bound."""
        for _ in range(n):
            self._lag_skip += 1
            if self._lag_skip < self._lag_stride:
                continue
            self._lag_skip = 0
            self.ttfr_lag_ms.append(lag_ms)
        if len(self.ttfr_lag_ms) > self._max_lag_samples:
            self.ttfr_lag_ms = self.ttfr_lag_ms[::2]
            self._lag_stride *= 2

    def _record_harvest_lag(self, lag_ms: float, n: int) -> None:
        """The r24 twin for final-segment (harvest) observation lag —
        same stride-decimated bound, separate store (TTFR lag and
        harvest lag gate as separate bench rows)."""
        for _ in range(n):
            self._hlag_skip += 1
            if self._hlag_skip < self._hlag_stride:
                continue
            self._hlag_skip = 0
            self.harvest_lag_ms.append(lag_ms)
        if len(self.harvest_lag_ms) > self._max_lag_samples:
            self.harvest_lag_ms = self.harvest_lag_ms[::2]
            self._hlag_stride *= 2

    # -- eviction / join ---------------------------------------------------
    def evict(self, rid: int) -> bool:
        """Remove a tenant mid-stream.  Queued: the request is
        cancelled outright (collect then raises ``KeyError``).
        In-flight: partial results are cut at the NEXT segment
        boundary and ``collect`` returns them (``ticks`` = the
        elapsed prefix, bitwise-prefix-equal to the solo rollout).
        Returns False for unknown/done/already-evicted tenants (the
        rollout finished first — collect returns the full result)."""
        if rid in self.queue:
            self.queue.remove(rid)
            self._requests.pop(rid, None)
            # The clock can never reach on_collect (collect raises
            # for cancelled rids), so compact it here — the tracker
            # holds one clock per OUTSTANDING request.
            self.slo.clocks.pop(rid, None)
            self.slo.on_eviction(rid, 0)
            self.stats["evicted"] += 1
            return True
        s = self._streams.get(rid)
        if (
            s is None or s.done or rid in s.evicted
            or rid in s.evict_flags
        ):
            return False
        s.evict_flags.add(rid)
        return True

    # -- collect -----------------------------------------------------------
    def ready_rids(self) -> List[int]:
        """Request ids whose result can be collected without further
        pumping (rollout complete, or eviction cut harvested)."""
        return sorted(
            rid for rid, s in self._streams.items()
            if s.done or rid in s.evicted
        )

    def result_ready(self, rid: int) -> bool:
        """True when ``collect(rid)`` returns without waiting on
        device work: the rollout (or eviction cut) is fully launched
        AND its result buffers are observable.  ``ready_rids`` means
        "nothing left to pump" — collecting such a tenant still
        blocks on the device for whatever segments are in flight; a
        serving loop that must keep admitting (bench_soak's) gates
        its collects on this instead, so the one legal blocking
        transfer happens only when it no longer waits."""
        s = self._streams.get(rid)
        if s is None:
            return False
        if s.probe_token is not None:
            # Callback-driven readiness (r24): the registry already
            # knows which segments the device finished — consult it
            # instead of touching a device handle.  (After abandon
            # the token is closed and the `is_ready` fallback below
            # answers for the eviction cuts.)
            self._drain_pulse(s)
        if rid in s.evicted:
            if s.probe_token is not None:
                return s.segs_landed >= s.evicted[rid][2]
            leaf = s.evicted[rid][1].pos
        elif s.done:
            if s._host is not None:
                return True
            if s.probe_token is not None:
                return s.segs_landed >= len(s.seg_plan)
            leaf = s.carry.pos
        else:
            return False
        is_ready = getattr(leaf, "is_ready", None)
        return True if is_ready is None else bool(is_ready())

    def active_rids(self) -> List[int]:
        """Request ids admitted and still rolling (not done, not
        evicted) — the evictable set a churn driver samples from."""
        return sorted(
            rid for rid, s in self._streams.items()
            if not s.done and rid not in s.evicted
            and rid not in s.evict_flags
        )

    def collect(self, rid: int) -> TenantResult:
        """Drive the loop until ``rid``'s result is ready and return
        it, evicting it from the service (the r13 result-store
        contract: second collect raises ``KeyError``)."""
        if rid not in self._requests:
            raise KeyError(
                f"request id {rid} is not in the service (never "
                "submitted, cancelled while queued, or already "
                "collected — results are evicted on collect)"
            )
        if rid in self.queue:
            # Targeted release: dispatch only THIS rid's shape group
            # — a blocking collect must not force-flush unrelated
            # groups still coalescing toward their rung or deadline.
            req, capacity = self._requests[rid]
            for key, entries, size in self.queue.pop_group(
                (capacity, len(req.task_pos))
            ):
                self._launch_group(key[0], size, entries)
        s = self._streams.get(rid)
        while s is not None and not (s.done or rid in s.evicted):
            self.pump()
        if s is None:                        # pragma: no cover
            raise KeyError(f"request id {rid} lost its dispatch")
        return self._result_for(s, rid)

    def drain(self) -> Dict[int, TenantResult]:
        """Admit everything immediately, run the loop to completion,
        and collect every outstanding tenant (keyed by rid)."""
        self.pump(force=True)
        while any(not s.done for s in self._live):
            self.pump()
        out = {rid: self.collect(rid) for rid in self.ready_rids()}
        if self.metrics.enabled:
            # One closing snapshot so the final collects' latency
            # observations reach the live surface (the cadence gate
            # only runs inside pump).
            self.metrics.deposit()
        return out

    def _result_for(self, s: _Stream, rid: int) -> TenantResult:
        req, capacity = self._requests.pop(rid)
        i = s.rids.index(rid)
        if s.probe_token is not None:
            # Collected before the last pump drained (a
            # single-segment plan collected straight through): any
            # stamp that already landed — TTFR, harvest lag — is
            # preferred over the on_collect backfill.  The final
            # segment's stamp program may still be executing (its
            # callback runs on the runtime thread); barrier on its
            # output once so the harvest-lag sample lands before the
            # token closes — the segment itself is already done, so
            # the wait is callback dispatch, not compute.
            if s.final_stamp is not None and s.done:
                jax.block_until_ready(s.final_stamp)
                s.final_stamp = None
            self._drain_pulse(s)
        with self.tracer.span(COLLECT_SPAN, rid=rid):
            if s.jumbo:
                if rid in s.evicted:
                    ticks, view, n_segs = s.evicted.pop(rid)
                    state = jax.tree_util.tree_map(np.asarray, view)
                else:
                    ticks, n_segs = self.n_steps, None
                    state = s.host_states()
                # Back to agent-id order at the bucket capacity: the
                # r12 parity lens — the result compares directly
                # against the solo single-device rollout of the same
                # materialized scenario.
                state = unshard_spatial_state(state, capacity)
                summary = None
                if self.telemetry and s.telem_segs and n_segs != 0:
                    summary = TelemetrySummary.from_ticks(
                        s.jumbo_telem(n_segs)
                    ).to_dict()
                traj = None
            elif rid in s.evicted:
                ticks, view, n_segs = s.evicted.pop(rid)
                state = jax.tree_util.tree_map(np.asarray, view)
                summary = None
                if self.telemetry and n_segs:
                    summary = TelemetrySummary.from_ticks(
                        s.tenant_telem(i, n_segs)
                    ).to_dict()
                traj = (
                    s.tenant_traj(i, req.n_agents, n_segs)
                    if self.record else None
                )
            else:
                ticks = self.n_steps
                state = tenant_state(s.host_states(), i)
                summary = None
                if self.telemetry and s.telem_segs:
                    summary = TelemetrySummary.from_ticks(
                        s.tenant_telem(i)
                    ).to_dict()
                traj = (
                    s.tenant_traj(i, req.n_agents)
                    if self.record else None
                )
        s.collected.add(rid)
        del self._streams[rid]
        if not any(r in self._streams for r in s.rids):
            # Every tenant of this stream is out: drop the buffers
            # (result-store eviction, the r13 discipline) and any
            # unharvested pulse token (collect backfilled TTFR; the
            # registry must not outlive the stream).
            pulse_close(s.probe_token)
            s.probe_token = None
            # Leaving observation closes any open stall incident —
            # the watchdog's cadence gate must not let an alarm
            # dangle past the stream it names.
            self.health.discharge(s)
            try:
                self._live.remove(s)
            except ValueError:
                pass
        self.slo.on_collect(rid)
        self.stats["collected"] += 1
        return TenantResult(
            request_id=rid,
            n_agents=req.n_agents,
            capacity=capacity,
            state=state,
            summary=summary,
            traj=traj,
            ticks=ticks,
        )

    # -- observation windows -----------------------------------------------
    def rotate_slo(self, window: Optional[str] = None) -> SloTracker:
        """Rotate the SLO observation window in place (r24 satellite;
        see :meth:`~.slo.SloTracker.rotate`): the service, watchdog,
        and queue continue on the successor tracker (the queue shares
        only the clock, which the successor keeps), and the CLOSED
        tracker is returned for archival — ``summary()`` on it is the
        window's frozen slo.json artifact."""
        closed = self.slo
        self.slo = closed.rotate(window)
        if self.health.slo is closed:
            self.health.slo = self.slo
        return closed

    # -- introspection -----------------------------------------------------
    @property
    def n_pending(self) -> int:
        return self.queue.depth

    @property
    def n_in_flight(self) -> int:
        """Dispatches with segments still to launch."""
        return sum(1 for s in self._live if not s.done)

    def compile_entries(self) -> int:
        return compile_watch.WATCH.compile_count(SERVE_ENTRY)

    def compile_entries_sharded(self) -> int:
        """Observatory cache entries of the scenario-axis sharded
        entry (r18) — gated against the same bucket lattice by
        bench_mesh2d.py."""
        return compile_watch.WATCH.compile_count(SERVE_SHARDED_ENTRY)
