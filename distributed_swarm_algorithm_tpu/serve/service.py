"""The multi-tenant rollout service: submit scenarios, collect results.

The async host loop over serve/batched.py + serve/buckets.py:

    svc = RolloutService(cfg, n_steps=50)
    rid = svc.submit(ScenarioRequest(n_agents=100, seed=7))
    ...
    svc.flush()                      # dispatch everything pending
    result = svc.collect(rid)        # block on THAT dispatch only

``flush`` groups pending requests by capacity bucket, splits each
group into batch-rung dispatches (serve/buckets.py), materializes the
padded states, and launches the compiled batched rollout WITHOUT
blocking: jax's async dispatch queues the device work, so the host is
already materializing dispatch k+1 while dispatch k executes, and the
donated state buffers go straight back to XLA — the double-buffered
submit/collect loop of the r13 design.  ``collect`` is keyed by
request id and blocks only on the dispatch that holds it, so results
may be consumed in ANY order relative to submission (out-of-order
completion is the normal case for a mixed-bucket stream).

Collected results are evicted from the service (the result store is
bounded by what is in flight, not by service lifetime); collecting an
unknown or already-collected id raises ``KeyError``.

Compile budget: the service declares ``spec.max_shapes`` to the
compile observatory under the ``"serve-batched-rollout"`` entry —
with the observatory enabled (``DSA_COMPILE_WATCH=1``), any compile
past the bucket lattice fires a structured ``bucket-overflow`` event
(utils/compile_watch.py), and benchmarks/bench_multitenant.py gates
the count as a fixed-name "compiles" row.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List, Optional

import jax
import numpy as np

from ..state import SwarmState
from ..utils import compile_watch
from ..utils.config import DEFAULT_CONFIG, SwarmConfig
from ..utils.telemetry import TelemetrySummary, tenant_telemetry
from .batched import (
    MATERIALIZE_ENTRY,
    SERVE_ENTRY,
    ScenarioRequest,
    batched_rollout,
    materialize_batch,
    tenant_state,
    validate_request,
    validate_serve_config,
)
from .buckets import BucketSpec


@dataclass
class TenantResult:
    """One collected scenario.

    ``state`` is the final capacity-padded :class:`SwarmState` with
    HOST numpy leaves (the bitwise-parity surface — identical to the
    solo rollout of the same materialized scenario; one device->host
    transfer per dispatch, free views per tenant); ``summary`` the
    tenant's flight-recorder reduction (None with telemetry off);
    ``traj`` the ``[n_steps, n_agents, D]`` recorded trajectory
    trimmed to the REAL agent count (None with record off)."""

    request_id: int
    n_agents: int
    capacity: int
    state: SwarmState
    summary: Optional[dict] = None
    traj: Optional[np.ndarray] = None


class _Dispatch:
    """One launched bucket batch: the async handles plus the rid ->
    batch-row map.  Buffers are dropped once every tenant is
    collected (result-store eviction)."""

    def __init__(self, rids, states, traj, telem):
        self.rids: List[int] = rids          # row i <-> rids[i]
        self.states = states                 # [S, ...] final states
        self.traj = traj                     # [T, S, C, D] or None
        self.telem = telem                   # [T, S]-leaved or None
        self._host = None

    def block(self):
        jax.block_until_ready(self.states.pos)

    def host_states(self) -> SwarmState:
        """The final states as host numpy — one device->host transfer
        per dispatch, then per-tenant extraction is a free view (a
        per-tenant device slice measured ~3 ms/tenant of dispatch
        overhead at collect time)."""
        if self._host is None:
            self.block()
            self._host = jax.tree_util.tree_map(
                np.asarray, self.states
            )
        return self._host

    def host_telem(self):
        """The stacked recorder ys as host numpy (same one-transfer
        discipline as :meth:`host_states`)."""
        if self.telem is not None and not isinstance(
            self.telem.tick, np.ndarray
        ):
            self.telem = jax.tree_util.tree_map(
                np.asarray, self.telem
            )
        return self.telem

    def host_traj(self):
        """The recorded trajectory as host numpy — the largest buffer
        in the dispatch, so per-tenant device slices would be the
        worst offenders of the one-transfer rule."""
        if self.traj is not None and not isinstance(
            self.traj, np.ndarray
        ):
            self.traj = np.asarray(self.traj)
        return self.traj


class RolloutService:
    """Scenario-batched swarm serving — thousands of concurrent small
    swarms per chip through a handful of compiled shapes.

    Static per-service: the shared :class:`SwarmConfig` (structure),
    the rollout length, and the telemetry/record composition — each
    is a jit-static of the batched entry, so keeping them per-service
    keeps the compile budget at ``spec.max_shapes``.  Per-REQUEST:
    agent count (alive-mask padding), arena, seed, faults, tasks, and
    every :class:`~.batched.ScenarioParams` scalar.
    """

    def __init__(
        self,
        cfg: Optional[SwarmConfig] = None,
        spec: Optional[BucketSpec] = None,
        n_steps: int = 50,
        telemetry: bool = True,
        record: bool = False,
    ):
        self.cfg = validate_serve_config(cfg or DEFAULT_CONFIG)
        self.spec = spec or BucketSpec()
        if n_steps <= 0:
            raise ValueError(f"n_steps must be >= 1, got {n_steps}")
        self.n_steps = int(n_steps)
        # The EFFECTIVE flag: the batched entry returns the telemetry
        # ys whenever the flag OR the config gate is on, so the
        # unpacking below must agree with that disjunction — a config
        # with telemetry pre-enabled plus telemetry=False would
        # otherwise make the service mistake (states, telem) for
        # states.
        self.telemetry = bool(telemetry) or self.cfg.telemetry.enabled
        self.record = bool(record)
        self._next_rid = 0
        #: (capacity, n_tasks) -> [(rid, request)] awaiting flush,
        #: FIFO.  The task count is part of the bucket key because it
        #: is a SHAPE (the task table rides the batch) — mixing task
        #: counts in one dispatch would be a retrace, not a batch.
        self._pending: Dict[tuple, List] = {}
        #: rid -> _Dispatch holding its row.
        self._dispatches: Dict[int, _Dispatch] = {}
        #: rid -> (request, capacity) for pending bookkeeping.
        self._requests: Dict[int, tuple] = {}
        #: distinct task counts seen — each one multiplies the
        #: compiled-shape lattice (shape axis #3).
        self._task_counts: set = set()
        self.stats = {
            "submitted": 0, "dispatches": 0, "padded_scenarios": 0,
            "collected": 0,
        }
        self._declare_budgets(n_task_families=1)

    def _declare_budgets(self, n_task_families: int) -> None:
        # Declare the compile budgets whether or not the observatory
        # is enabled — declaration is free and makes a later enable()
        # retroactively meaningful for new compiles.  The budget is
        # the bucket lattice times the observed task-count families;
        # the materializer adds the batch-of-1 scalar view.  The
        # registry (and the jit caches it mirrors) is PROCESS-GLOBAL:
        # with several services alive, the declared budget is the MAX
        # over services (a smaller second service must not turn the
        # first's legitimate compiles into overflow events), and
        # compile_entries() counts every service's compiles — the
        # per-service gate in bench_multitenant runs one service per
        # process, the honest granularity the jit cache offers.
        watch = compile_watch.WATCH
        budget = self.spec.max_shapes * max(n_task_families, 1)
        for entry, b in (
            (SERVE_ENTRY, budget), (MATERIALIZE_ENTRY, budget + 1)
        ):
            prev = watch.bucket_budget(entry)
            watch.declare_buckets(entry, max(b, prev or 0))

    # -- submit ------------------------------------------------------------
    def submit(self, req: ScenarioRequest) -> int:
        """Queue one scenario; returns its request id.  EVERY request
        invariant is checked here — oversized shapes (no capacity
        rung fits; the eviction half of the bucket contract) and the
        materializer's field contracts — so a bad request fails at
        its own submit instead of poisoning the co-batched requests'
        flush."""
        capacity = self.spec.capacity_for(req.n_agents)
        validate_request(req)
        rid = self._next_rid
        self._next_rid += 1
        n_tasks = len(req.task_pos)
        if n_tasks not in self._task_counts:
            self._task_counts.add(n_tasks)
            self._declare_budgets(len(self._task_counts))
        self._pending.setdefault((capacity, n_tasks), []).append(
            (rid, req)
        )
        self._requests[rid] = (req, capacity)
        self.stats["submitted"] += 1
        return rid

    # -- dispatch ----------------------------------------------------------
    def flush(self) -> int:
        """Dispatch every pending request as bucketed batches; returns
        the number of dispatches launched.  Non-blocking: the device
        works while the host materializes the next batch."""
        launched = 0
        for key in sorted(self._pending):
            capacity, _ = key
            group = self._pending[key]
            for size in self.spec.split_batch(len(group)):
                entries = group[:size]
                # Launch BEFORE dequeuing: a failed launch must not
                # silently drop its co-batched requests.
                self._launch(capacity, size, entries)
                del group[:size]
                launched += 1
        self._pending = {k: g for k, g in self._pending.items() if g}
        self.stats["dispatches"] += launched
        return launched

    def _launch(self, capacity: int, size: int, entries) -> None:
        rids = [rid for rid, _ in entries]
        reqs = [req for _, req in entries]
        n_pad = size - len(reqs)
        self.stats["padded_scenarios"] += n_pad
        # One jitted build for the whole dispatch (rows past the real
        # requests are dead filler scenarios), one compiled rollout;
        # neither call blocks, so the host is already materializing
        # the NEXT dispatch while this one executes (async dispatch =
        # the double buffer), and the donated state buffers go
        # straight back to XLA.
        states, params = materialize_batch(
            reqs, capacity, self.cfg, pad_to=size
        )
        out = batched_rollout(
            states, params, self.cfg, self.n_steps,
            record=self.record, telemetry=self.telemetry,
        )
        traj = telem = None
        if self.record and self.telemetry:
            states, traj, telem = out
        elif self.record:
            states, traj = out
        elif self.telemetry:
            states, telem = out
        else:
            states = out
        d = _Dispatch(rids, states, traj, telem)
        for rid in rids:
            self._dispatches[rid] = d

    # -- collect -----------------------------------------------------------
    def collect(self, rid: int) -> TenantResult:
        """Block on (only) the dispatch holding ``rid`` and return its
        tenant's result, evicting it from the service.  Pending but
        unflushed requests are flushed first.  Raises ``KeyError``
        for unknown or already-collected ids."""
        if rid not in self._dispatches:
            if rid in self._requests and any(
                rid == r for g in self._pending.values() for r, _ in g
            ):
                self.flush()
        if rid not in self._dispatches:
            raise KeyError(
                f"request id {rid} is not in flight (never submitted, "
                "or already collected — results are evicted on "
                "collect)"
            )
        d = self._dispatches.pop(rid)
        i = d.rids.index(rid)
        req, capacity = self._requests.pop(rid)
        summary = None
        if d.telem is not None:
            summary = TelemetrySummary.from_ticks(
                tenant_telemetry(d.host_telem(), i)
            ).to_dict()
        traj = None
        if d.traj is not None:
            traj = d.host_traj()[:, i, : req.n_agents]
        result = TenantResult(
            request_id=rid,
            n_agents=req.n_agents,
            capacity=capacity,
            state=tenant_state(d.host_states(), i),
            summary=summary,
            traj=traj,
        )
        self.stats["collected"] += 1
        return result

    def collect_all(self) -> Dict[int, TenantResult]:
        """Flush, then collect every outstanding request (in-flight
        and pending), keyed by request id."""
        self.flush()
        rids = sorted(self._dispatches)
        return {rid: self.collect(rid) for rid in rids}

    # -- introspection -----------------------------------------------------
    @property
    def n_pending(self) -> int:
        return sum(len(g) for g in self._pending.values())

    @property
    def n_in_flight(self) -> int:
        return len(self._dispatches)

    def compile_entries(self) -> int:
        """Distinct compiled signatures the observatory has seen for
        the batched entry (0 unless the observatory is enabled) —
        the number bench_multitenant gates against
        ``spec.max_shapes``."""
        return compile_watch.WATCH.compile_count(SERVE_ENTRY)
