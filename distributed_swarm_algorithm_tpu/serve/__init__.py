"""Multi-tenant rollout serving (r13) + the streaming serve loop
(r16): scenario-batched swarm rollouts with bucketed compiled shapes,
an async double-buffered submit/collect loop, and a continuous-
batching streaming service with an SLO observatory.  See
serve/batched.py (the vmapped tick + per-scenario params),
serve/buckets.py (the shape lattice), serve/service.py (the host
loops), serve/queue.py (deadline-coalescing admission), and
serve/slo.py (latency percentiles, gauges, alert events)."""

from .batched import (
    MATERIALIZE_ENTRY,
    PARAM_FIELDS,
    SERVE_ENTRY,
    EnvRolloutResult,
    ScenarioParams,
    ScenarioRequest,
    bake_params,
    batched_rollout,
    env_rollouts,
    materialize_batch,
    materialize_scenario,
    scenario_params,
    stack_params,
    stack_scenarios,
    tenant_state,
    validate_request,
    validate_serve_config,
)
from .buckets import BucketSpec
from .queue import AdmissionQueue, QueueOverflowError
from .service import RolloutService, StreamingService, TenantResult
from .slo import DEFAULT_DEADLINE_S, SloTracker

__all__ = [
    "DEFAULT_DEADLINE_S",
    "MATERIALIZE_ENTRY",
    "PARAM_FIELDS",
    "SERVE_ENTRY",
    "AdmissionQueue",
    "BucketSpec",
    "EnvRolloutResult",
    "QueueOverflowError",
    "RolloutService",
    "ScenarioParams",
    "ScenarioRequest",
    "SloTracker",
    "StreamingService",
    "TenantResult",
    "bake_params",
    "batched_rollout",
    "env_rollouts",
    "materialize_batch",
    "materialize_scenario",
    "scenario_params",
    "stack_params",
    "stack_scenarios",
    "tenant_state",
    "validate_request",
    "validate_serve_config",
]
