"""Multi-tenant rollout serving (r13) + the streaming serve loop
(r16) + 2D-mesh dispatch (r18): scenario-batched swarm rollouts with
bucketed compiled shapes, an async double-buffered submit/collect
loop, a continuous-batching streaming service with an SLO
observatory, and a ``(scenarios, tiles)`` mesh plane — scenario
rungs shard_map-committed ``P('scenarios')`` (zero per-tick
collectives), jumbo rungs through the r12 spatial tick on the tiles
axis, one ``StreamingService`` front door.  See serve/batched.py
(the vmapped tick + per-scenario params + the sharded twin),
serve/buckets.py (the shape lattice + per-rung mesh axes),
serve/service.py (the host loops), serve/queue.py
(deadline-coalescing admission), and serve/slo.py (latency
percentiles, gauges, per-rung occupancy, alert events)."""

from ..parallel.mesh import make_serve_mesh
from .batched import (
    MATERIALIZE_ENTRY,
    PARAM_FIELDS,
    SERVE_ENTRY,
    SERVE_SHARDED_ENTRY,
    EnvRolloutResult,
    ScenarioParams,
    ScenarioRequest,
    bake_params,
    batched_rollout,
    batched_rollout_sharded,
    env_rollouts,
    materialize_batch,
    materialize_scenario,
    scenario_params,
    shard_scenarios,
    stack_params,
    stack_scenarios,
    tenant_state,
    train_rollouts,
    validate_request,
    validate_serve_config,
)
from .buckets import SCENARIO_AXES, TILE_AXES, BucketSpec
from .queue import AdmissionQueue, QueueOverflowError
from .service import (
    JUMBO_ENTRY,
    RolloutService,
    StreamingService,
    TenantResult,
    unshard_spatial_state,
)
from .slo import DEFAULT_DEADLINE_S, SloTracker

__all__ = [
    "DEFAULT_DEADLINE_S",
    "JUMBO_ENTRY",
    "MATERIALIZE_ENTRY",
    "PARAM_FIELDS",
    "SCENARIO_AXES",
    "SERVE_ENTRY",
    "SERVE_SHARDED_ENTRY",
    "TILE_AXES",
    "AdmissionQueue",
    "BucketSpec",
    "EnvRolloutResult",
    "QueueOverflowError",
    "RolloutService",
    "ScenarioParams",
    "ScenarioRequest",
    "SloTracker",
    "StreamingService",
    "TenantResult",
    "bake_params",
    "batched_rollout",
    "batched_rollout_sharded",
    "env_rollouts",
    "make_serve_mesh",
    "materialize_batch",
    "materialize_scenario",
    "scenario_params",
    "shard_scenarios",
    "stack_params",
    "stack_scenarios",
    "tenant_state",
    "train_rollouts",
    "unshard_spatial_state",
    "validate_request",
    "validate_serve_config",
]
