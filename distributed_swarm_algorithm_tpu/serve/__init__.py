"""Multi-tenant rollout serving (r13): scenario-batched swarm
rollouts with bucketed compiled shapes and an async double-buffered
submit/collect loop.  See serve/batched.py (the vmapped tick +
per-scenario params), serve/buckets.py (the shape lattice), and
serve/service.py (the host loop)."""

from .batched import (
    MATERIALIZE_ENTRY,
    PARAM_FIELDS,
    SERVE_ENTRY,
    EnvRolloutResult,
    ScenarioParams,
    ScenarioRequest,
    bake_params,
    batched_rollout,
    env_rollouts,
    materialize_batch,
    materialize_scenario,
    scenario_params,
    stack_params,
    stack_scenarios,
    tenant_state,
    validate_request,
    validate_serve_config,
)
from .buckets import BucketSpec
from .service import RolloutService, TenantResult

__all__ = [
    "MATERIALIZE_ENTRY",
    "PARAM_FIELDS",
    "SERVE_ENTRY",
    "BucketSpec",
    "EnvRolloutResult",
    "RolloutService",
    "ScenarioParams",
    "ScenarioRequest",
    "TenantResult",
    "bake_params",
    "batched_rollout",
    "env_rollouts",
    "materialize_batch",
    "materialize_scenario",
    "scenario_params",
    "stack_params",
    "stack_scenarios",
    "tenant_state",
    "validate_request",
    "validate_serve_config",
]
