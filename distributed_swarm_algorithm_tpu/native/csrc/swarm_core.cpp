// Native CPU runtime kernels for the per-agent path.
//
// The reference is pure Python (SURVEY.md §2: zero native components) and
// its per-tick physics is the compute hot spot (~171k single-agent
// steps/sec in CPython, SURVEY.md §6 / reference agent.py:94-181).  The
// TPU path vectorizes this under XLA (ops/physics.py); this file is the
// equivalent *native* tier for the CPU per-agent runtime: the whole-swarm
// APF physics tick and the bid-matrix utility/arbitration kernels, batched
// over agents in C++ so the lockstep simulator (models/agent.py
// run_local_swarm and models/cpu_swarm.py) is not bottlenecked by the
// interpreter.
//
// Exposed as a plain C ABI, loaded from Python with ctypes
// (native/__init__.py) — no pybind11 dependency.  Semantics mirror
// ops/physics.py / ops/allocation.py exactly (same epsilon clamps, same
// force laws from reference agent.py:116-178, same hysteresis rule from
// reference agent.py:308-325); tests/test_native.py checks bit-level
// agreement with the NumPy oracle.
//
// World is 2-D, like the reference's (agent.py:47).

#include <cmath>
#include <cstdint>
#include <cstring>
#include <limits>
#include <vector>

extern "C" {

// One APF physics tick for the whole swarm (reference agent.py:94-181).
//
//   pos, vel:        [n][2] in/out (Euler-updated in place)
//   target:          [n][2]; has_target: [n] (0/1, agent.py:113-114)
//   alive:           [n] (0/1) — dead agents are frozen
//   obstacles:       [n_obs][3] rows of (x, y, radius)
//   neighbor mode:   all-pairs over alive agents (the vectorized-model
//                    semantics; any agent beyond personal_space contributes
//                    zero force, so this is exact)
//
// Config scalars are the reference constants (see utils/config.py for
// file:line provenance).  All norms clamp at eps — the reference's
// co-located-agent ZeroDivisionError (SURVEY.md §5a bug 1) cannot occur.
void dsa_physics_step(
    int64_t n,
    double* pos,
    double* vel,
    const double* target,
    const uint8_t* has_target,
    const uint8_t* alive,
    const double* obstacles,
    int64_t n_obs,
    double k_att,
    double arrival_tolerance,
    double k_rep,
    double rho0,
    double k_sep,
    double personal_space,
    double eps,
    double max_speed,
    double dt) {
  const double ps2 = personal_space * personal_space;
  for (int64_t i = 0; i < n; ++i) {
    if (!alive[i] || !has_target[i]) {
      vel[2 * i] = 0.0;
      vel[2 * i + 1] = 0.0;
      continue;
    }
    const double px = pos[2 * i];
    const double py = pos[2 * i + 1];
    double fx = 0.0, fy = 0.0;

    // Attraction (agent.py:116-125): full displacement, gated outside the
    // arrival tolerance.
    const double tx = target[2 * i] - px;
    const double ty = target[2 * i + 1] - py;
    if (std::sqrt(tx * tx + ty * ty) > arrival_tolerance) {
      fx += k_att * tx;
      fy += k_att * ty;
    }

    // Obstacle repulsion (agent.py:127-146): distance to the obstacle
    // *surface*, active inside rho0.
    for (int64_t o = 0; o < n_obs; ++o) {
      const double dx = px - obstacles[3 * o];
      const double dy = py - obstacles[3 * o + 1];
      double center = std::sqrt(dx * dx + dy * dy);
      if (center < eps) center = eps;
      double surf = center - obstacles[3 * o + 2];
      if (surf < eps) surf = eps;
      if (surf < rho0) {
        const double mag = k_rep * (1.0 / surf - 1.0 / rho0) / (surf * surf);
        fx += (dx / center) * mag;
        fy += (dy / center) * mag;
      }
    }

    // Neighbor separation (agent.py:148-160) over all alive others.
    for (int64_t j = 0; j < n; ++j) {
      if (j == i || !alive[j]) continue;
      const double dx = px - pos[2 * j];
      const double dy = py - pos[2 * j + 1];
      const double d2 = dx * dx + dy * dy;
      if (d2 >= ps2) continue;
      double dist = std::sqrt(d2);
      if (dist < eps) dist = eps;
      const double mag = k_sep / (dist * dist);
      fx += (dx / dist) * mag;
      fy += (dy / dist) * mag;
    }

    // Clamp + Euler (agent.py:165-178); force == velocity command.
    const double speed = std::sqrt(fx * fx + fy * fy);
    if (speed > max_speed) {
      const double s = max_speed / (speed < eps ? eps : speed);
      fx *= s;
      fy *= s;
    }
    vel[2 * i] = fx;
    vel[2 * i + 1] = fy;
  }
  // Second pass for positions so every separation force reads *pre-tick*
  // positions (synchronous semantics, matching the vectorized model).
  for (int64_t i = 0; i < n; ++i) {
    if (!alive[i] || !has_target[i]) continue;
    pos[2 * i] += vel[2 * i] * dt;
    pos[2 * i + 1] += vel[2 * i + 1] * dt;
  }
}

// Utility bid matrix U[n][t] = scale / (1 + dist) * cap_match
// (reference agent.py:338-347; ops/allocation.py:utility_matrix).
//   caps:     [n][n_caps] 0/1 one-hot agent capabilities
//   task_cap: [t] required capability index, -1 = none required
void dsa_utility_matrix(
    int64_t n,
    int64_t t,
    const double* pos,
    const double* task_pos,
    const uint8_t* caps,
    int64_t n_caps,
    const int32_t* task_cap,
    double scale,
    double* out /* [n][t] */) {
  for (int64_t i = 0; i < n; ++i) {
    const double px = pos[2 * i];
    const double py = pos[2 * i + 1];
    for (int64_t k = 0; k < t; ++k) {
      const double dx = px - task_pos[2 * k];
      const double dy = py - task_pos[2 * k + 1];
      const double dist = std::sqrt(dx * dx + dy * dy);
      const int32_t req = task_cap[k];
      const bool match = req < 0 || (req < n_caps && caps[i * n_caps + req]);
      out[i * t + k] = match ? scale / (1.0 + dist) : 0.0;
    }
  }
}

// Leader arbitration with hysteresis (reference agent.py:304-325;
// ops/allocation.py:arbitrate).  claims[n][t] holds each agent's live
// claim utility (0 = no claim).  winner/util[t] are the incumbent ledger,
// updated in place.  Highest utility wins; ties break to the lowest agent
// id; a challenger must beat the incumbent by `hysteresis`.
void dsa_arbitrate(
    int64_t n,
    int64_t t,
    const double* claims,
    int32_t* winner,
    double* util,
    double hysteresis) {
  for (int64_t k = 0; k < t; ++k) {
    double best = 0.0;
    int64_t best_i = -1;
    for (int64_t i = 0; i < n; ++i) {
      const double u = claims[i * t + k];
      if (u > best) {  // strict: ties keep the lower id
        best = u;
        best_i = i;
      }
    }
    if (best_i < 0) continue;  // no claim this tick
    const bool vacant = winner[k] < 0;
    if (vacant || best > util[k] + hysteresis) {
      winner[k] = static_cast<int32_t>(best_i);
      util[k] = best;
    }
  }
}

// eps-scaled Bertsekas forward auction (ops/auction.py), float32.
//
// Mirrors the JAX kernel / NumPy oracle EXACTLY — same squared problem
// (S = max(n, t), zero-value slots for infeasible/virtual pairs), same
// Jacobi rounds, same first-index argmax and lowest-id tie-breaks, same
// float32 arithmetic order — so all three tiers produce bit-identical
// assignments, prices, and round counts (tests/test_native.py).
//
//   util:     [n][t] float32 utilities
//   feasible: [n][t] 0/1
//   agent_task[n], task_agent[t]: outputs, -1 = unassigned
//   prices_out[t]: final prices; rounds_out: total Jacobi rounds
void dsa_auction_assign(
    int64_t n,
    int64_t t,
    const float* util,
    const uint8_t* feasible,
    double eps,
    int32_t phases,
    double theta,
    int64_t max_rounds,
    int32_t* agent_task_out,
    int32_t* task_agent_out,
    float* prices_out,
    int64_t* rounds_out) {
  const int64_t s = n > t ? n : t;
  // -inf masking identity, valid at any utility/price magnitude (a
  // finite sentinel breaks once prices approach it — ADVICE r1); the
  // JAX/NumPy tiers use the same identity + isfinite tests.
  const float kNeg = -std::numeric_limits<float>::infinity();
  std::vector<float> values(static_cast<size_t>(s) * s, 0.0f);
  for (int64_t i = 0; i < n; ++i)
    for (int64_t j = 0; j < t; ++j) {
      const float u = util[i * t + j];
      if (feasible[i * t + j] && u > 0.0f) values[i * s + j] = u;
    }

  std::vector<float> prices(s, 0.0f);
  std::vector<int32_t> agent_task(s), task_agent(s);
  std::vector<float> bid_v(s), best_bid(s);
  std::vector<int32_t> j1(s), winner(s);
  int64_t total_rounds = 0;

  for (int32_t k = phases - 1; k >= 0; --k) {
    const float cur_eps = static_cast<float>(eps * std::pow(theta, k));
    std::fill(agent_task.begin(), agent_task.end(), -1);
    std::fill(task_agent.begin(), task_agent.end(), -1);
    int64_t rounds = 0;
    while (rounds < max_rounds) {
      bool any_unassigned = false;
      for (int64_t i = 0; i < s; ++i)
        if (agent_task[i] < 0) { any_unassigned = true; break; }
      if (!any_unassigned) break;

      // Per-agent best / second-best net value (first-index argmax,
      // matching np/jnp.argmax).
      for (int64_t i = 0; i < s; ++i) {
        const float* vi = values.data() + i * s;
        float w1 = vi[0] - prices[0];  // first-index argmax, no floor
        int64_t best_j = 0;
        for (int64_t j = 1; j < s; ++j) {
          const float v = vi[j] - prices[j];
          if (v > w1) { w1 = v; best_j = j; }
        }
        float w2 = kNeg;  // the NumPy mirror masks j1 with _NEG
        for (int64_t j = 0; j < s; ++j) {
          if (j == best_j) continue;
          const float v = vi[j] - prices[j];
          if (v > w2) w2 = v;
        }
        if (!std::isfinite(w2)) w2 = w1;  // S == 1: zero margin
        j1[i] = static_cast<int32_t>(best_j);
        bid_v[i] = (agent_task[i] < 0)
                       ? (prices[best_j] + (w1 - w2)) + cur_eps
                       : kNeg;
      }

      // Per-task best bid and lowest-id winner.
      std::fill(best_bid.begin(), best_bid.end(), kNeg);
      for (int64_t i = 0; i < s; ++i)
        if (bid_v[i] > best_bid[j1[i]]) best_bid[j1[i]] = bid_v[i];
      std::fill(winner.begin(), winner.end(), -1);
      for (int64_t i = 0; i < s; ++i) {
        if (agent_task[i] >= 0) continue;        // not bidding
        const int32_t j = j1[i];
        if (bid_v[i] >= best_bid[j] && std::isfinite(best_bid[j]) &&
            winner[j] < 0)
          winner[j] = static_cast<int32_t>(i);   // ascending i = min id
      }

      // Evict previous owners of contested tasks, then seat winners.
      for (int64_t j = 0; j < s; ++j) {
        if (winner[j] < 0) continue;
        if (task_agent[j] >= 0) agent_task[task_agent[j]] = -1;
      }
      for (int64_t j = 0; j < s; ++j) {
        if (winner[j] < 0) continue;
        agent_task[winner[j]] = static_cast<int32_t>(j);
        task_agent[j] = winner[j];
        prices[j] = best_bid[j];
      }
      ++rounds;
    }
    total_rounds += rounds;
  }

  // Unpad: a real pair counts only if feasible with positive utility.
  for (int64_t j = 0; j < t; ++j) task_agent_out[j] = -1;
  for (int64_t i = 0; i < n; ++i) {
    const int32_t j = agent_task[i];
    const bool really = j >= 0 && j < t && feasible[i * t + j] &&
                        util[i * t + j] > 0.0f;
    agent_task_out[i] = really ? j : -1;
    if (really) task_agent_out[j] = static_cast<int32_t>(i);
  }
  for (int64_t j = 0; j < t; ++j) prices_out[j] = prices[j];
  *rounds_out = total_rounds;
}

// Version tag so the Python loader can verify the ABI.
int32_t dsa_abi_version() { return 2; }

}  // extern "C"
